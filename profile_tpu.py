"""Capture XLA profiler traces of the three benchmark models on the TPU.

Produces ``profiles/<model>/`` XPlane traces (TensorBoard 'Profile' tab) and
prints a JSON summary of measured step time vs the compiled step's XLA cost
analysis (FLOPs + bytes accessed), the evidence behind PROFILE.md's
conclusions on the XLA-conv thesis (≙ deeplearning4j-cuda's claim that the
helper kernels beat the builtin path — here the question is whether stock
XLA fusion suffices; see VERDICT round 2 item 6).

Run: ``python profile_tpu.py`` (real chip; ~2 min).
"""

import json
import os
import time

import numpy as np


def _trace(name, step, args_fn, steps=8):
    import jax

    out_dir = os.path.join("profiles", name)
    os.makedirs(out_dir, exist_ok=True)
    state, make_args = args_fn
    # warmup/compile outside the trace
    for _ in range(3):
        state = step(state, make_args())
    np.asarray(jax.device_get(state[-1]))
    jax.profiler.start_trace(out_dir)
    t0 = time.perf_counter()
    for _ in range(steps):
        state = step(state, make_args())
    np.asarray(jax.device_get(state[-1]))
    dt = (time.perf_counter() - t0) / steps
    jax.profiler.stop_trace()
    return dt


def main():
    import jax
    import jax.numpy as jnp

    from bench import _compile_step, _peak_flops
    from deeplearning4j_tpu.models.zoo import (
        graves_lstm_char_lm, lenet, resnet50,
    )

    dev = jax.devices()[0]
    peak = _peak_flops(dev)
    rs = np.random.RandomState(0)
    report = {"device": getattr(dev, "device_kind", "?"), "models": {}}

    # ---- LeNet fp32 b128
    net = lenet(updater="nesterovs", lr=0.01)
    x = jnp.asarray(rs.rand(128, 784).astype(np.float32))
    y = jnp.asarray(np.eye(10, dtype=np.float32)[rs.randint(0, 10, 128)])
    jstep = net._get_train_step()
    flops, compiled = _compile_step(jstep, net.params, net.updater_state,
                                    net.net_state, jnp.zeros(()), x, y,
                                    net._keys.next(), None, None, None)
    cost = compiled.cost_analysis()
    cost = cost[0] if isinstance(cost, (list, tuple)) else cost

    def step_lenet(state, _):
        p, u, n, loss, _c = compiled(state[0], state[1], state[2],
                                     jnp.zeros(()), x, y, net._keys.next(),
                                     None, None, None)
        return [p, u, n, loss]

    dt = _trace("lenet", step_lenet,
                ([net.params, net.updater_state, net.net_state, None],
                 lambda: None))
    report["models"]["lenet_b128_fp32"] = {
        "step_ms": round(dt * 1e3, 3), "flops": flops,
        "bytes_accessed": cost.get("bytes accessed", None),
        "mfu_vs_bf16_peak": round(flops / dt / peak, 4) if peak else None,
    }

    # ---- ResNet-50 bf16 b128
    net2 = resnet50(compute_dtype="bfloat16")
    x2 = {"input": jnp.asarray(rs.rand(128, 224, 224, 3).astype(np.float32))}
    y2 = {"fc": jnp.asarray(np.eye(1000, dtype=np.float32)[rs.randint(0, 1000, 128)])}
    jstep2 = net2._get_train_step()
    flops2, compiled2 = _compile_step(jstep2, net2.params, net2.updater_state,
                                      net2.net_state, jnp.zeros(()), x2, y2,
                                      net2._keys.next(), None, None, None)
    cost2 = compiled2.cost_analysis()
    cost2 = cost2[0] if isinstance(cost2, (list, tuple)) else cost2

    def step_resnet(state, _):
        p, u, n, loss, _c = compiled2(state[0], state[1], state[2],
                                      jnp.zeros(()), x2, y2,
                                      net2._keys.next(), None, None, None)
        return [p, u, n, loss]

    dt2 = _trace("resnet50", step_resnet,
                 ([net2.params, net2.updater_state, net2.net_state, None],
                  lambda: None))
    report["models"]["resnet50_b128_bf16"] = {
        "step_ms": round(dt2 * 1e3, 2), "flops": flops2,
        "bytes_accessed": cost2.get("bytes accessed", None),
        "mfu": round(flops2 / dt2 / peak, 4) if peak else None,
    }

    # ---- GravesLSTM fp32 b128 T50
    net3 = graves_lstm_char_lm(vocab_size=77, hidden=200, tbptt=50)
    ids = rs.randint(0, 77, (128, 50))
    x3 = jnp.asarray(np.eye(77, dtype=np.float32)[ids])
    y3 = jnp.asarray(np.eye(77, dtype=np.float32)[np.roll(ids, -1, 1)])
    jstep3 = net3._get_train_step()
    flops3, compiled3 = _compile_step(jstep3, net3.params, net3.updater_state,
                                      net3.net_state, jnp.zeros(()), x3, y3,
                                      net3._keys.next(), None, None, None)
    cost3 = compiled3.cost_analysis()
    cost3 = cost3[0] if isinstance(cost3, (list, tuple)) else cost3

    def step_lstm(state, _):
        p, u, n, loss, _c = compiled3(state[0], state[1], state[2],
                                      jnp.zeros(()), x3, y3,
                                      net3._keys.next(), None, None, None)
        return [p, u, n, loss]

    dt3 = _trace("graves_lstm", step_lstm,
                 ([net3.params, net3.updater_state, net3.net_state, None],
                  lambda: None))
    report["models"]["graves_lstm_b128_t50_fp32"] = {
        "step_ms": round(dt3 * 1e3, 2), "flops": flops3,
        "bytes_accessed": cost3.get("bytes accessed", None),
        "mfu_vs_bf16_peak": round(flops3 / dt3 / peak, 4) if peak else None,
    }

    print(json.dumps(report))
    with open("profiles/summary.json", "w") as f:
        json.dump(report, f, indent=2)


if __name__ == "__main__":
    main()
