"""Grad-sync bandwidth stand-in (BASELINE.md's one blank row; VERDICT r4
task 7, two rounds outstanding).

The reference's analog is the Spark parameter aggregate
(``ParameterAveragingTrainingMaster.java:628-645`` — processParams /
aggregate over the executor fleet).  Here the dp gradient sync is an XLA
all-reduce over the mesh's data axis, inserted automatically by sharding
propagation.  Single-chip hardware means the ICI number cannot be measured
directly, so this script produces the labeled stand-in the verdict asked
for:

1. **Measured (virtual mesh)**: time ONE psum of a ResNet-50-sized gradient
   tree over an 8-device host-platform CPU mesh, reported as wall-clock and
   effective algorithm bandwidth (ring all-reduce moves 2*(N-1)/N * bytes
   through each device).  This validates the collective's program shape and
   gives a real (if CPU-memory-bound) number.
2. **Analytic (v5e ICI)**: the same collective on a v5e ring using the
   public per-chip ICI figure (1,600 Gbps = 200 GB/s), the scaling-book
   recipe: t = 2*(N-1)/N * bytes / ICI_bw.

Run: env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
     XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python scripts/measure_grad_sync.py
Writes profiles/grad_sync.json and prints one JSON line.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESNET50_PARAMS = 25_557_032          # fc + conv + bn weights, our zoo config
DTYPE_BYTES = 4                       # grads sync in f32
V5E_ICI_BYTES_PER_S = 200e9           # 1,600 Gbps per chip (public spec)


def measure(n_devices: int = 8, iters: int = 20):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax import shard_map

    devices = jax.devices()[:n_devices]
    n = len(devices)
    mesh = Mesh(np.asarray(devices), ("data",))

    # ResNet-50-sized flat gradient, one replica per device (the dp state
    # right before the sync): [N, P] sharded over 'data'
    p = RESNET50_PARAMS
    rows = jnp.asarray(np.random.RandomState(0)
                       .rand(n, p).astype(np.float32))
    rows = jax.device_put(rows, NamedSharding(mesh, P("data")))

    @jax.jit
    def allreduce(rows):
        return shard_map(lambda r: lax.psum(r, "data"), mesh=mesh,
                         in_specs=P("data"), out_specs=P("data"))(rows)

    out = allreduce(rows)
    np.asarray(jax.device_get(out[0, :1]))  # warm + sync
    t0 = time.perf_counter()
    for _ in range(iters):
        out = allreduce(out)
    np.asarray(jax.device_get(out[0, :1]))
    dt = (time.perf_counter() - t0) / iters

    bytes_grad = p * DTYPE_BYTES
    ring_bytes_per_dev = 2 * (n - 1) / n * bytes_grad
    analytic_s = ring_bytes_per_dev / V5E_ICI_BYTES_PER_S
    return {
        "metric": "dp grad all-reduce (ResNet-50-sized tree)",
        "params": p,
        "grad_mb": round(bytes_grad / 1e6, 1),
        "n_devices": n,
        "platform": devices[0].platform,
        "measured_ms": round(dt * 1e3, 3),
        "measured_algbw_gbps": round(ring_bytes_per_dev / dt / 1e9, 2),
        "ring_bytes_per_device_mb": round(ring_bytes_per_dev / 1e6, 1),
        "analytic_v5e_ms": round(analytic_s * 1e3, 3),
        "analytic_ici_gbps": V5E_ICI_BYTES_PER_S / 1e9,
        "note": ("measured on the virtual host-platform mesh (CPU memory "
                 "bandwidth, shared address space — validates the collective "
                 "shape, NOT ICI); analytic row is the v5e ring estimate "
                 "t = 2(N-1)/N * bytes / ICI_bw"),
    }


def main():
    result = measure()
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "profiles", "grad_sync.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
