"""Grad-sync bandwidth CLI — a thin front-end over ``shardstats``.

The reference's analog is the Spark parameter aggregate
(``ParameterAveragingTrainingMaster.java:628-645`` — processParams /
aggregate over the executor fleet).  Here the dp gradient sync is an XLA
all-reduce over the mesh's data axis, and since the sharding-ledger PR
the ONE owner of "bytes moved per sync step" is
``observability.shardstats``: this script builds the ResNet-50-sized
collective, lets the HLO census count its bytes (instead of trusting the
hand-computed number), times it on the virtual mesh, and prices it with
the shared ``LINK_BANDWIDTH`` table + ``ring_wire_bytes`` recipe.

Rows produced:

1. **Measured (virtual mesh)**: wall-clock of ONE psum of a
   ResNet-50-sized gradient tree over an 8-device host-platform CPU mesh
   (validates the collective's program shape; CPU-memory-bound, NOT ICI).
2. **Censused**: the compiled program's all-reduce count/bytes from
   ``shardstats.program_analysis`` — the same census the training
   masters report through ``dl4j_step_collective_bytes``.
3. **Analytic (v5e ICI)**: the same collective priced on a v5e ring from
   ``LINK_BANDWIDTH`` (t = ring_wire_bytes / ICI_bw).

With ``--sharded`` a second arm censuses the ZeRO decomposition
(arXiv 2004.13336, ``parallel/zero.py``): the same gradient tree synced
as reduce-scatter(grads) + all-gather(params) instead of one all-reduce
— the HLO census counts both collectives and their payload bytes next
to the all-reduce arm, and the analytic row prices the ring wire bytes
of the pair (equal: 2(K-1)/K split as (K-1)/K + (K-1)/K).

Run: env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
     XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python scripts/measure_grad_sync.py [--sharded]
Writes profiles/grad_sync.json and prints one JSON line.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESNET50_PARAMS = 25_557_032          # fc + conv + bn weights, our zoo config
DTYPE_BYTES = 4                       # grads sync in f32


def measure(n_devices: int = 8, iters: int = 20):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.backend.compat import shard_map
    from deeplearning4j_tpu.observability import shardstats

    devices = jax.devices()[:n_devices]
    n = len(devices)
    mesh = Mesh(np.asarray(devices), ("data",))

    # ResNet-50-sized flat gradient, one replica per device (the dp state
    # right before the sync): [N, P] sharded over 'data'
    p = RESNET50_PARAMS
    rows = jnp.asarray(np.random.RandomState(0)
                       .rand(n, p).astype(np.float32))
    rows = jax.device_put(rows, NamedSharding(mesh, P("data")))

    @jax.jit
    def allreduce(rows):
        return shard_map(lambda r: lax.psum(r, "data"), mesh=mesh,
                         in_specs=P("data"), out_specs=P("data"))(rows)

    # census BEFORE the timed dispatches: the one owner of "bytes moved
    # per sync step" is the HLO count, not the hand math
    analysis = shardstats.program_analysis(allreduce, (rows,), {})
    census = analysis.get("collectives", {})
    ar = census.get("all-reduce", {"count": 0, "bytes": 0,
                                   "group_sizes": []})
    group = (ar["group_sizes"] or [n])[0]

    out = allreduce(rows)
    np.asarray(jax.device_get(out[0, :1]))  # warm + sync
    t0 = time.perf_counter()
    for _ in range(iters):
        out = allreduce(out)
    np.asarray(jax.device_get(out[0, :1]))
    dt = (time.perf_counter() - t0) / iters

    bytes_grad = p * DTYPE_BYTES
    # the census sees the partitioned program: each device's shard_map
    # block is one full [1, P] gradient row, so the psum payload equals
    # the FULL tree bytes (the same number the analytic row prices)
    ring_bytes_per_dev = shardstats.ring_wire_bytes(
        "all-reduce", bytes_grad, group)
    v5e_bw = shardstats.LINK_BANDWIDTH["TPU v5"]
    analytic_s = ring_bytes_per_dev / v5e_bw
    return {
        "metric": "dp grad all-reduce (ResNet-50-sized tree)",
        "params": p,
        "grad_mb": round(bytes_grad / 1e6, 1),
        "n_devices": n,
        "platform": devices[0].platform,
        "measured_ms": round(dt * 1e3, 3),
        "measured_algbw_gbps": round(ring_bytes_per_dev / dt / 1e9, 2),
        "ring_bytes_per_device_mb": round(ring_bytes_per_dev / 1e6, 1),
        "censused_allreduce_count": ar["count"],
        "censused_allreduce_bytes": ar["bytes"],
        "censused_group_size": group,
        "program_memory": analysis.get("memory"),
        "analytic_v5e_ms": round(analytic_s * 1e3, 3),
        "analytic_ici_gbps": v5e_bw / 1e9,
        "note": ("measured on the virtual host-platform mesh (CPU memory "
                 "bandwidth, shared address space — validates the collective "
                 "shape, NOT ICI); collective bytes are the HLO census "
                 "(shardstats.program_analysis) of the partitioned "
                 "program; analytic row prices ring_wire_bytes at the "
                 "LINK_BANDWIDTH['TPU v5'] spec figure"),
    }


def measure_sharded(n_devices: int = 8, iters: int = 20):
    """The ZeRO window's collective pattern over the same
    ResNet-50-sized tree: reduce-scatter the summed gradient, update the
    local 1/K shard (elementwise SGD stand-in), all-gather the params —
    censused with the same PR-14 API as the all-reduce arm."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.backend.compat import shard_map
    from deeplearning4j_tpu.observability import shardstats

    devices = jax.devices()[:n_devices]
    n = len(devices)
    mesh = Mesh(np.asarray(devices), ("data",))

    p = RESNET50_PARAMS - (RESNET50_PARAMS % n)   # shardable length
    rng = np.random.RandomState(0)
    grads = jax.device_put(
        jnp.asarray(rng.rand(n, p).astype(np.float32)),
        NamedSharding(mesh, P("data")))            # per-replica grads
    params = jax.device_put(
        jnp.asarray(rng.rand(p).astype(np.float32)),
        NamedSharding(mesh, P("data")))            # ZeRO-sharded params

    @jax.jit
    def zero_sync(params, grads):
        def local(p_blk, g_blk):
            # reduce-scatter: the sum of every replica's gradient,
            # delivered as this device's 1/K shard
            g_sh = lax.psum_scatter(g_blk[0], "data",
                                    scatter_dimension=0, tiled=True) / n
            new_p = p_blk - 0.1 * g_sh             # sharded update
            full = lax.all_gather(new_p, "data", axis=0, tiled=True)
            return new_p, full

        return shard_map(local, mesh=mesh,
                         in_specs=(P("data"), P("data")),
                         out_specs=(P("data"), P()),
                         check_vma=False)(params, grads)

    analysis = shardstats.program_analysis(zero_sync, (params, grads), {})
    census = analysis.get("collectives", {})
    rs = census.get("reduce-scatter", {"count": 0, "bytes": 0,
                                       "group_sizes": []})
    ag = census.get("all-gather", {"count": 0, "bytes": 0,
                                   "group_sizes": []})
    group = (rs["group_sizes"] or [n])[0]

    new_p, _full = zero_sync(params, grads)
    np.asarray(jax.device_get(new_p[:1]))          # warm + sync
    t0 = time.perf_counter()
    out_p = params
    for _ in range(iters):
        out_p, _full = zero_sync(out_p, grads)
    np.asarray(jax.device_get(out_p[:1]))
    dt = (time.perf_counter() - t0) / iters

    bytes_grad = p * DTYPE_BYTES
    ring_bytes = (shardstats.ring_wire_bytes("reduce-scatter", bytes_grad,
                                             group)
                  + shardstats.ring_wire_bytes("all-gather", bytes_grad,
                                               group))
    v5e_bw = shardstats.LINK_BANDWIDTH["TPU v5"]
    return {
        "metric": "ZeRO grad reduce-scatter + param all-gather "
                  "(ResNet-50-sized tree)",
        "params": p,
        "grad_mb": round(bytes_grad / 1e6, 1),
        "n_devices": n,
        "platform": devices[0].platform,
        "measured_ms": round(dt * 1e3, 3),
        "ring_bytes_per_device_mb": round(ring_bytes / 1e6, 1),
        "censused_reduce_scatter_count": rs["count"],
        "censused_reduce_scatter_bytes": rs["bytes"],
        "censused_all_gather_count": ag["count"],
        "censused_all_gather_bytes": ag["bytes"],
        "censused_group_size": group,
        "program_memory": analysis.get("memory"),
        "analytic_v5e_ms": round(ring_bytes / v5e_bw * 1e3, 3),
        "analytic_ici_gbps": v5e_bw / 1e9,
        "note": ("the ZeRO window's collective pattern "
                 "(parallel/zero.py): reduce-scatter + all-gather ring "
                 "wire bytes equal the all-reduce arm's 2(K-1)/K — the "
                 "win is the 1/K persistent updater state, not the "
                 "wire; collective bytes are the HLO census"),
    }


def main():
    result = measure()
    if "--sharded" in sys.argv[1:]:
        result = {"allreduce": result, "sharded": measure_sharded()}
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "profiles", "grad_sync.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
