"""Grad-sync bandwidth CLI — a thin front-end over ``shardstats``.

The reference's analog is the Spark parameter aggregate
(``ParameterAveragingTrainingMaster.java:628-645`` — processParams /
aggregate over the executor fleet).  Here the dp gradient sync is an XLA
all-reduce over the mesh's data axis, and since the sharding-ledger PR
the ONE owner of "bytes moved per sync step" is
``observability.shardstats``: this script builds the ResNet-50-sized
collective, lets the HLO census count its bytes (instead of trusting the
hand-computed number), times it on the virtual mesh, and prices it with
the shared ``LINK_BANDWIDTH`` table + ``ring_wire_bytes`` recipe.

Rows produced:

1. **Measured (virtual mesh)**: wall-clock of ONE psum of a
   ResNet-50-sized gradient tree over an 8-device host-platform CPU mesh
   (validates the collective's program shape; CPU-memory-bound, NOT ICI).
2. **Censused**: the compiled program's all-reduce count/bytes from
   ``shardstats.program_analysis`` — the same census the training
   masters report through ``dl4j_step_collective_bytes``.
3. **Analytic (v5e ICI)**: the same collective priced on a v5e ring from
   ``LINK_BANDWIDTH`` (t = ring_wire_bytes / ICI_bw).

Run: env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
     XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python scripts/measure_grad_sync.py
Writes profiles/grad_sync.json and prints one JSON line.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESNET50_PARAMS = 25_557_032          # fc + conv + bn weights, our zoo config
DTYPE_BYTES = 4                       # grads sync in f32


def measure(n_devices: int = 8, iters: int = 20):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.backend.compat import shard_map
    from deeplearning4j_tpu.observability import shardstats

    devices = jax.devices()[:n_devices]
    n = len(devices)
    mesh = Mesh(np.asarray(devices), ("data",))

    # ResNet-50-sized flat gradient, one replica per device (the dp state
    # right before the sync): [N, P] sharded over 'data'
    p = RESNET50_PARAMS
    rows = jnp.asarray(np.random.RandomState(0)
                       .rand(n, p).astype(np.float32))
    rows = jax.device_put(rows, NamedSharding(mesh, P("data")))

    @jax.jit
    def allreduce(rows):
        return shard_map(lambda r: lax.psum(r, "data"), mesh=mesh,
                         in_specs=P("data"), out_specs=P("data"))(rows)

    # census BEFORE the timed dispatches: the one owner of "bytes moved
    # per sync step" is the HLO count, not the hand math
    analysis = shardstats.program_analysis(allreduce, (rows,), {})
    census = analysis.get("collectives", {})
    ar = census.get("all-reduce", {"count": 0, "bytes": 0,
                                   "group_sizes": []})
    group = (ar["group_sizes"] or [n])[0]

    out = allreduce(rows)
    np.asarray(jax.device_get(out[0, :1]))  # warm + sync
    t0 = time.perf_counter()
    for _ in range(iters):
        out = allreduce(out)
    np.asarray(jax.device_get(out[0, :1]))
    dt = (time.perf_counter() - t0) / iters

    bytes_grad = p * DTYPE_BYTES
    # the census sees the partitioned program: each device's shard_map
    # block is one full [1, P] gradient row, so the psum payload equals
    # the FULL tree bytes (the same number the analytic row prices)
    ring_bytes_per_dev = shardstats.ring_wire_bytes(
        "all-reduce", bytes_grad, group)
    v5e_bw = shardstats.LINK_BANDWIDTH["TPU v5"]
    analytic_s = ring_bytes_per_dev / v5e_bw
    return {
        "metric": "dp grad all-reduce (ResNet-50-sized tree)",
        "params": p,
        "grad_mb": round(bytes_grad / 1e6, 1),
        "n_devices": n,
        "platform": devices[0].platform,
        "measured_ms": round(dt * 1e3, 3),
        "measured_algbw_gbps": round(ring_bytes_per_dev / dt / 1e9, 2),
        "ring_bytes_per_device_mb": round(ring_bytes_per_dev / 1e6, 1),
        "censused_allreduce_count": ar["count"],
        "censused_allreduce_bytes": ar["bytes"],
        "censused_group_size": group,
        "program_memory": analysis.get("memory"),
        "analytic_v5e_ms": round(analytic_s * 1e3, 3),
        "analytic_ici_gbps": v5e_bw / 1e9,
        "note": ("measured on the virtual host-platform mesh (CPU memory "
                 "bandwidth, shared address space — validates the collective "
                 "shape, NOT ICI); collective bytes are the HLO census "
                 "(shardstats.program_analysis) of the partitioned "
                 "program; analytic row prices ring_wire_bytes at the "
                 "LINK_BANDWIDTH['TPU v5'] spec figure"),
    }


def main():
    result = measure()
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "profiles", "grad_sync.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
