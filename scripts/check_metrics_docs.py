#!/usr/bin/env python
"""Lint: every registered metric family has help text and a docs row.

THIN SHIM — the scan now lives in the dl4jlint framework as the
``metrics-docs`` rule (``scripts/dl4jlint/rules/metrics_docs.py``) and
runs with the rest of the suite via ``python -m scripts.dl4jlint``.
This script keeps the original standalone entry point and its public
functions (``find_registrations`` / ``documented_families`` /
``run_lint``) so existing callers — ``tests/test_metrics_docs.py``
loads it by file path — keep working unchanged.

Run standalone with ``python scripts/check_metrics_docs.py``
(exit 0 = clean), same contract as before.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:   # file-path loads have no package context
    sys.path.insert(0, REPO)

from scripts.dl4jlint.core import (  # noqa: E402
    iter_source_files, load_contexts, run_rules,
)
from scripts.dl4jlint.rules import metrics_docs as _rule  # noqa: E402


def _contexts():
    return load_contexts(iter_source_files())


def find_registrations(ctxs=None) -> Dict[str, List[Tuple[str, int, bool]]]:
    """family name -> [(file, line, has_help)] across the codebase.
    ``ctxs`` lets callers that already parsed the corpus (``main``)
    reuse it instead of re-parsing 100+ files."""
    if ctxs is None:
        ctxs, _errors = _contexts()
    out: Dict[str, List[Tuple[str, int, bool]]] = {}
    for ctx in ctxs:
        for name, sites in _rule.registrations_in(ctx.tree, ctx.rel).items():
            out.setdefault(name, []).extend(sites)
    return out


def documented_families() -> Set[str]:
    """dl4j_* names appearing in table rows of docs/observability.md."""
    return _rule.documented_families()


def run_lint(loaded=None) -> List[str]:
    """Returns a list of violations (empty = clean).  ``loaded`` is an
    optional pre-parsed ``(ctxs, errors)`` pair (see ``main``)."""
    ctxs, errors = loaded if loaded is not None else _contexts()
    # run_rules (not finalize directly) so dl4jlint suppression comments
    # apply here exactly as in the full suite
    res = run_rules([_rule.MetricsDocsRule()], ctxs, list(errors))
    return list(res.errors) + [f.message for f in res.findings]


def main() -> int:
    loaded = _contexts()   # parse the corpus ONCE for both calls below
    problems = run_lint(loaded)
    for p in problems:
        print(f"check_metrics_docs: {p}", file=sys.stderr)
    if not problems:
        n = len(find_registrations(loaded[0]))
        print(f"check_metrics_docs: OK ({n} dl4j_* families documented)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
