#!/usr/bin/env python
"""Lint: every registered metric family has help text and a docs row.

Walks the ``deeplearning4j_tpu`` package (plus ``bench.py``) with ``ast``
looking for registry family registrations — ``.counter(...)``,
``.gauge(...)``, ``.histogram(...)`` calls whose first argument is a
string literal starting with ``dl4j_`` — and enforces two invariants:

1. the registration passes a NON-EMPTY help string (literal second
   positional argument or ``help=``) in at least one site — /metrics
   output without HELP lines is useless to an operator;
2. the family name appears in a table row (a line starting with ``|``)
   of ``docs/observability.md`` — the docs table is the metric
   catalogue, and a family that never made it there is invisible.

No imports of the package (and no jax) — the scan is pure source
analysis, so it runs in milliseconds and can't be defeated by lazy
registration.  Wired into the tier-1 suite via
``tests/test_metrics_docs.py``; run standalone with
``python scripts/check_metrics_docs.py`` (exit 0 = clean).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "deeplearning4j_tpu")
EXTRA_FILES = [os.path.join(REPO, "bench.py")]
DOCS = os.path.join(REPO, "docs", "observability.md")

_METHODS = {"counter", "gauge", "histogram"}


def _iter_py_files():
    for root, _dirs, files in os.walk(PACKAGE):
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(root, f)
    for f in EXTRA_FILES:
        if os.path.exists(f):
            yield f


def _literal_str(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def find_registrations() -> Dict[str, List[Tuple[str, int, bool]]]:
    """family name -> [(file, line, has_help)] across the codebase."""
    out: Dict[str, List[Tuple[str, int, bool]]] = {}
    for path in _iter_py_files():
        with open(path) as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:   # pragma: no cover - would fail tests too
            print(f"{path}: unparsable: {e}", file=sys.stderr)
            continue
        rel = os.path.relpath(path, REPO)
        # module-level string constants (the owning modules name their
        # families via _FAMILY = "dl4j_..." so they register in one place)
        consts: Dict[str, str] = {}
        for node in tree.body:
            if (isinstance(node, ast.Assign)
                    and (s := _literal_str(node.value)) is not None):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        consts[tgt.id] = s
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METHODS and node.args):
                continue
            arg0 = node.args[0]
            name = _literal_str(arg0)
            if name is None and isinstance(arg0, ast.Name):
                name = consts.get(arg0.id)
            if not name or not name.startswith("dl4j_"):
                continue
            help_text = None
            if len(node.args) > 1:
                help_text = _literal_str(node.args[1])
            for kw in node.keywords:
                if kw.arg == "help":
                    help_text = _literal_str(kw.value)
            # adjacent string literals concatenate into one Constant, so a
            # multi-line help renders as a single (truthy) literal here
            has_help = bool(help_text and help_text.strip())
            out.setdefault(name, []).append((rel, node.lineno, has_help))
    return out


def documented_families() -> Set[str]:
    """dl4j_* names appearing in table rows of docs/observability.md."""
    names: Set[str] = set()
    with open(DOCS) as f:
        for line in f:
            if not line.lstrip().startswith("|"):
                continue
            for tok in line.replace("`", " ").replace("|", " ").split():
                tok = tok.strip("*,.()/")
                if tok.startswith("dl4j_"):
                    names.add(tok)
    return names


def run_lint() -> List[str]:
    """Returns a list of violations (empty = clean)."""
    problems: List[str] = []
    regs = find_registrations()
    if not regs:
        return ["no dl4j_* metric registrations found — scanner broken?"]
    docs = documented_families()
    for name, sites in sorted(regs.items()):
        if not any(has_help for _f, _l, has_help in sites):
            where = ", ".join(f"{f}:{l}" for f, l, _ in sites[:3])
            problems.append(
                f"{name}: registered without non-empty help text ({where})")
        if name not in docs:
            problems.append(
                f"{name}: no row in docs/observability.md metric table")
    return problems


def main() -> int:
    problems = run_lint()
    for p in problems:
        print(f"check_metrics_docs: {p}", file=sys.stderr)
    if not problems:
        n = len(find_registrations())
        print(f"check_metrics_docs: OK ({n} dl4j_* families documented)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
