#!/usr/bin/env bash
# Test runner (≙ reference /runtests.sh:33 — the repo-root test entry).
#
#   scripts/runtests.sh            # CPU tier: full suite on the 8-device
#                                  # virtual mesh (no TPU needed)
#   scripts/runtests.sh tpu        # real-chip tier: pytest -m tpu
#   scripts/runtests.sh bench      # bench.py (one JSON line)
#   scripts/runtests.sh dryrun     # multichip sharding dryrun (8 virtual)
#   scripts/runtests.sh all        # everything above in order
set -euo pipefail
cd "$(dirname "$0")/.."

tier="${1:-cpu}"

run_cpu()    { python -m pytest tests/ -q; }
run_tpu()    { DL4J_TPU_TESTS=1 python -m pytest tests/ -m tpu -q; }
run_bench()  { python bench.py; }
run_dryrun() { python -c 'from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)'; }

case "$tier" in
  cpu)    run_cpu ;;
  tpu)    run_tpu ;;
  bench)  run_bench ;;
  dryrun) run_dryrun ;;
  all)    run_cpu; run_dryrun; run_tpu; run_bench ;;
  *) echo "usage: $0 [cpu|tpu|bench|dryrun|all]" >&2; exit 2 ;;
esac
