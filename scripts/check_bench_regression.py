#!/usr/bin/env python
"""CI gate: fail when a fresh ``bench_full.json`` regresses the committed
baseline.

Usage::

    python scripts/check_bench_regression.py                 # committed vs itself (sanity)
    python scripts/check_bench_regression.py --fresh /tmp/bench_full.json
    python scripts/check_bench_regression.py --rules rules.json --json
    python scripts/check_bench_regression.py --self-test     # rule-engine unit checks

Exit codes: 0 clean, 1 regression (or self-test failure), 2 usage/IO
error.  Rules come from ``observability/regression.py`` (DEFAULT_RULES,
or a JSON list via ``--rules``).  The regression module is loaded by FILE
PATH so this script never imports the package (and thus never imports
jax) — it runs in milliseconds, same pattern as ``check_metrics_docs.py``.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REGRESSION_PY = os.path.join(REPO, "deeplearning4j_tpu", "observability",
                             "regression.py")


def _load_regression():
    spec = importlib.util.spec_from_file_location("_bench_regression",
                                                  REGRESSION_PY)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _self_test(reg) -> int:
    """Unit checks for the rule engine: both directions, the tolerance
    boundary, missing-value handling, and rule (de)serialization — so the
    sentinel's parsing can't rot unnoticed."""
    checks = 0

    def expect(cond, what):
        nonlocal checks
        checks += 1
        if not cond:
            print(f"self-test FAILED: {what}", file=sys.stderr)
            sys.exit(1)

    def doc(**entries):
        return {"all": [{"metric": m, **(v if isinstance(v, dict)
                                         else {"value": v})}
                        for m, v in entries.items()]}

    R = reg.Rule
    base = doc(**{"Throughput (cfg)": 100.0, "Latency (cfg)": 10.0})

    # higher-is-better: a 50% drop past a 20% tolerance regresses
    rep = reg.compare(base, doc(**{"Throughput (cfg)": 50.0}),
                      [R("Throughput", tolerance=0.2)])
    expect(rep.exit_code == 1 and len(rep.regressions) == 1,
           "50% throughput drop not flagged")
    # within tolerance: ok
    rep = reg.compare(base, doc(**{"Throughput (cfg)": 85.0}),
                      [R("Throughput", tolerance=0.2)])
    expect(rep.exit_code == 0, "15% drop inside 20% tolerance flagged")
    # exactly at the limit: NOT a regression (strict inequality)
    rep = reg.compare(base, doc(**{"Throughput (cfg)": 80.0}),
                      [R("Throughput", tolerance=0.2)])
    expect(rep.exit_code == 0, "boundary value flagged")
    # improvement recognised
    rep = reg.compare(base, doc(**{"Throughput (cfg)": 150.0}),
                      [R("Throughput", tolerance=0.2)])
    expect(rep.verdicts[0].status == "improved", "improvement not labeled")
    # lower-is-better: latency doubling past tolerance regresses
    rep = reg.compare(base, doc(**{"Latency (cfg)": 20.0}),
                      [R("Latency", direction=reg.LOWER, tolerance=0.5)])
    expect(rep.exit_code == 1, "latency doubling not flagged")
    rep = reg.compare(base, doc(**{"Latency (cfg)": 12.0}),
                      [R("Latency", direction=reg.LOWER, tolerance=0.5)])
    expect(rep.exit_code == 0, "latency inside tolerance flagged")
    # zero baseline + zero tolerance: any increase regresses (the
    # steady-state-compiles contract)
    zb = doc(**{"Serving (cfg)": {"value": 1.0, "steady_state_compiles": 0}})
    zf = doc(**{"Serving (cfg)": {"value": 1.0, "steady_state_compiles": 2}})
    rep = reg.compare(zb, zf, [R("Serving", field="steady_state_compiles",
                                 direction=reg.LOWER, tolerance=0.0)])
    expect(rep.exit_code == 1, "compile appearing over a 0 baseline passed")
    # missing required value regresses; optional is only a warning
    rep = reg.compare(base, {"all": []}, [R("Throughput")])
    expect(rep.exit_code == 1, "missing required metric passed")
    rep = reg.compare(base, {"all": []}, [R("Throughput", required=False)])
    expect(rep.exit_code == 0
           and rep.verdicts[0].status == "missing", "optional missing failed")
    # missing baseline skips
    rep = reg.compare({"all": []}, base, [R("Throughput")])
    expect(rep.verdicts[0].status == "no_baseline", "no-baseline not skipped")
    # dotted-field extraction
    vb = doc(**{"Decode (cfg)": {"value": 1.0,
                                 "variants": {"fast": {"tps": 100.0}}}})
    vf = doc(**{"Decode (cfg)": {"value": 1.0,
                                 "variants": {"fast": {"tps": 10.0}}}})
    rep = reg.compare(vb, vf, [R("Decode", field="variants.fast.tps",
                                 tolerance=0.2)])
    expect(rep.exit_code == 1, "dotted-field regression not flagged")
    # doc-scoped rules resolve from the document root (the memory
    # sentinels): replication factor growing past a zero tolerance fails,
    # the ZeRO-style drop reads as an improvement
    mb = {"all": [], "observability": {"memory": {"sentinels": {
        "updater_replication_factor": 4.0}}}}
    mf_worse = {"all": [], "observability": {"memory": {"sentinels": {
        "updater_replication_factor": 8.0}}}}
    mf_zero = {"all": [], "observability": {"memory": {"sentinels": {
        "updater_replication_factor": 1.0}}}}
    doc_rule = R("Memory: updater replication", scope="doc",
                 field="observability.memory.sentinels."
                       "updater_replication_factor",
                 direction=reg.LOWER, tolerance=0.0, required=False)
    rep = reg.compare(mb, mf_worse, [doc_rule])
    expect(rep.exit_code == 1, "replication-factor growth passed")
    rep = reg.compare(mb, mf_zero, [doc_rule])
    expect(rep.verdicts[0].status == "improved",
           "ZeRO-style replication drop not labeled improved")
    # rule JSON round-trip + validation errors
    r = R("Throughput", field="p99_ms", direction=reg.LOWER, tolerance=0.3,
          required=False)
    r2 = R.from_dict(r.to_dict())
    expect(r2.to_dict() == r.to_dict(), "rule round-trip changed the rule")
    for bad in ({"field": "value"}, {"metric": "x", "direction": "sideways"},
                {"metric": "x", "bogus": 1}):
        try:
            R.from_dict(bad)
        except ValueError:
            checks += 1
        else:
            expect(False, f"bad rule accepted: {bad}")
    # DEFAULT_RULES parse and self-compare clean against the committed file
    committed = os.path.join(REPO, "bench_full.json")
    if os.path.exists(committed):
        rep = reg.check_files(committed, committed)
        expect(rep.exit_code == 0,
               "committed bench_full.json regresses against itself")
    print(f"self-test: {checks} checks ok")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--baseline",
                    default=os.path.join(REPO, "bench_full.json"),
                    help="baseline bench_full.json (default: committed)")
    ap.add_argument("--fresh", default=None,
                    help="fresh bench_full.json to check "
                         "(default: the baseline itself — a sanity pass)")
    ap.add_argument("--rules", default=None,
                    help="JSON list of rule dicts (default: DEFAULT_RULES)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    ap.add_argument("--self-test", action="store_true",
                    help="run the rule-engine unit checks and exit")
    args = ap.parse_args(argv)
    reg = _load_regression()
    if args.self_test:
        return _self_test(reg)
    fresh = args.fresh or args.baseline
    try:
        rules = reg.load_rules(args.rules) if args.rules else None
        report = reg.check_files(args.baseline, fresh, rules)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report.to_dict(), indent=1))
    else:
        print(report.format())
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
