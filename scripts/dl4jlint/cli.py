"""dl4jlint driver.

Usage::

    python -m scripts.dl4jlint                    # repo scan vs baseline
    python -m scripts.dl4jlint --update-baseline  # ratchet the debt DOWN
    python -m scripts.dl4jlint path/to/file.py --no-baseline
    python -m scripts.dl4jlint --rules lock-discipline,thread-hygiene
    python -m scripts.dl4jlint --list-rules
    python -m scripts.dl4jlint --json

Exit codes (same contract as the bench sentinel): 0 clean against the
baseline, 1 new findings (or a refused ratchet), 2 usage/IO error.
Stdlib-only, never imports jax; a full-repo run is sub-second.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from scripts.dl4jlint import baseline as baseline_mod
from scripts.dl4jlint.core import (
    REPO, RunResult, iter_source_files, load_contexts, run_rules,
)
from scripts.dl4jlint.rules import ALL_RULES, get_rules

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def run(paths=None, rule_names=()) -> RunResult:
    """Library entry: scan and return the RunResult (no baseline)."""
    files = iter_source_files(paths)
    ctxs, errors = load_contexts(files)
    return run_rules(get_rules(rule_names), ctxs, errors)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dl4jlint", description=__doc__.split("\n")[0])
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: the "
                         "deeplearning4j_tpu package + bench.py)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule names (default: all)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON path (default: the committed one)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding; exit 1 if any")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline at current counts "
                         "(refuses to grow it)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the full report as JSON")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.name:24s} {r.description}")
        return 0

    t0 = time.perf_counter()
    try:
        rule_names = ([n.strip() for n in args.rules.split(",") if n.strip()]
                      if args.rules else ())
        res = run(args.paths or None, rule_names)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        return 2
    for err in res.errors:
        print(f"error: {err}", file=sys.stderr)
    if res.errors:
        return 2

    if args.no_baseline:
        doc = None
        new, stale = list(res.findings), []
    else:
        try:
            doc = (baseline_mod.load(args.baseline)
                   if os.path.exists(args.baseline) else None)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if args.update_baseline:
            try:
                newdoc = baseline_mod.update(res.findings, doc)
            except baseline_mod.RatchetError as e:
                print(f"dl4jlint: {e}", file=sys.stderr)
                return 1
            baseline_mod.save(args.baseline, newdoc)
            print(f"dl4jlint: baseline "
                  f"{'created' if doc is None else 'ratcheted'} at "
                  f"{len(newdoc['entries'])} entr"
                  f"{'y' if len(newdoc['entries']) == 1 else 'ies'} "
                  f"({sum(e['count'] for e in newdoc['entries'])} accepted "
                  f"findings) -> {os.path.relpath(args.baseline, REPO)}")
            return 0
        new, stale = baseline_mod.compare(
            res.findings, doc if doc is not None else baseline_mod.empty())

    dt = time.perf_counter() - t0
    if args.as_json:
        print(json.dumps({
            "files": res.files, "seconds": round(dt, 3),
            "total_findings": len(res.findings),
            "suppressed": res.suppressed,
            "new": [f.to_dict() for f in new],
            "stale_baseline_keys": [list(k) for k in stale],
        }, indent=1))
    else:
        for f in new:
            print(f.format())
        if stale:
            print(f"dl4jlint: note: {len(stale)} baseline entr"
                  f"{'y has' if len(stale) == 1 else 'ies have'} fewer "
                  f"findings than budgeted — run --update-baseline to "
                  f"bank the progress")
        status = "FAIL" if new else "OK"
        print(f"dl4jlint: {status} — {res.files} files, "
              f"{len(res.findings)} findings "
              f"({len(res.findings) - len(new)} baselined, {len(new)} new, "
              f"{res.suppressed} suppressed) in {dt:.2f}s")
    return 1 if new else 0
