"""The ratcheting baseline: existing findings are debt, new ones are
failures, and the recorded count can only go down.

``baseline.json`` holds one entry per ``(rule, path, symbol)`` key with
the count of accepted findings under that key and an optional ``why``
justification (required by review for anything deliberately kept, e.g.
"cold path: end-of-fit summary").  Keys deliberately exclude line
numbers so unrelated edits don't churn the file.

Semantics:

- ``compare``: findings beyond a key's baselined count are NEW (CI
  fails); baselined keys whose current count shrank are STALE (a
  friendly nudge to run ``--update-baseline`` and bank the progress).
- ``update``: rewrites counts to the current state, carrying ``why``
  forward — but REFUSES (RatchetError) when any key grew or appeared,
  so the baseline can never absorb a regression; fix or suppress it
  instead.  Bootstrapping a missing baseline file is the one exception.
"""

from __future__ import annotations

import json
import os
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from scripts.dl4jlint.core import Finding

Key = Tuple[str, str, str]

VERSION = 1


class RatchetError(Exception):
    """--update-baseline refused: the baseline never grows."""


def empty() -> dict:
    return {"version": VERSION, "entries": []}


def load(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("version") != VERSION or "entries" not in doc:
        raise ValueError(f"{path}: not a dl4jlint baseline (version "
                         f"{VERSION} with an 'entries' list expected)")
    for e in doc["entries"]:
        missing = {"rule", "path", "symbol", "count"} - set(e)
        if missing:
            raise ValueError(f"{path}: baseline entry {e!r} missing "
                             f"{sorted(missing)}")
    return doc


def save(path: str, doc: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)


def _entry_map(doc: dict) -> "OrderedDict[Key, dict]":
    out: "OrderedDict[Key, dict]" = OrderedDict()
    for e in doc["entries"]:
        out[(e["rule"], e["path"], e["symbol"])] = e
    return out


def _current_counts(findings: Sequence[Finding]) -> Dict[Key, int]:
    counts: Dict[Key, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    return counts


def compare(findings: Sequence[Finding],
            doc: dict) -> Tuple[List[Finding], List[Key]]:
    """(new findings beyond the baseline, stale over-budgeted keys)."""
    allowed = {k: e["count"] for k, e in _entry_map(doc).items()}
    seen: Dict[Key, int] = {}
    new: List[Finding] = []
    for f in findings:
        seen[f.key] = seen.get(f.key, 0) + 1
        if seen[f.key] > allowed.get(f.key, 0):
            new.append(f)
    stale = [k for k, budget in allowed.items()
             if seen.get(k, 0) < budget]
    return new, stale


def update(findings: Sequence[Finding],
           doc: Optional[dict]) -> dict:
    """New baseline doc at current counts.  Raises RatchetError when any
    key grew (or appeared) relative to ``doc``; ``doc=None`` bootstraps
    a first baseline and accepts everything."""
    counts = _current_counts(findings)
    if doc is not None:
        old = {k: e["count"] for k, e in _entry_map(doc).items()}
        grown = sorted(k for k, n in counts.items() if n > old.get(k, 0))
        if grown:
            lines = [f"  {r} {p} :: {s} ({old.get((r, p, s), 0)} -> "
                     f"{counts[(r, p, s)]})" for r, p, s in grown]
            raise RatchetError(
                "refusing to grow the baseline — fix or suppress these "
                "first:\n" + "\n".join(lines))
        whys = {k: e.get("why") for k, e in _entry_map(doc).items()}
    else:
        whys = {}
    entries = []
    for key in sorted(counts):
        rule, path, symbol = key
        e = {"rule": rule, "path": path, "symbol": symbol,
             "count": counts[key]}
        if whys.get(key):
            e["why"] = whys[key]
        entries.append(e)
    return {"version": VERSION, "entries": entries}
