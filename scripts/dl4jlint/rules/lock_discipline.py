"""lock-discipline: a heuristic race detector for the repo's
``with self._lock`` convention.

For every class that creates ``threading.Lock``/``RLock``/``Condition``
attributes in ``__init__``, collect each instance attribute WRITTEN
under a ``with self.<lock>:`` block in any method.  Such an attribute is
declared lock-guarded; any read or write of it OUTSIDE a lock block in a
different place is then a suspected race and is reported.

Recognised conventions (no finding):

- ``__init__`` constructs freely (happens-before publication);
- methods whose name ends in ``_locked``, or whose docstring contains
  "lock held" / "Lock held", are by convention only called with the
  lock already taken — their whole body counts as guarded;
- deliberately lock-free monitoring reads (single-writer counters, dict
  snapshots relying on the GIL) get an inline
  ``# dl4jlint: disable=lock-discipline -- <invariant>`` stating WHY the
  unlocked access is sound, which is the documentation the next reader
  needs anyway.

This is a heuristic: it reasons per-class and per-module, does not track
aliasing, and treats any ``with self.<lock-attr>`` (including Conditions)
as the guard.  It found real bugs at introduction (see
docs/static-analysis.md), which is the bar it has to keep clearing.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Set, Tuple

from scripts.dl4jlint.core import FileContext, Finding, Rule, dotted_name

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
               "Lock", "RLock", "Condition"}
_LOCK_HELD_RE = re.compile(r"lock held", re.IGNORECASE)

# method calls that mutate a container in place — ``self._m.pop(k)`` is a
# write to ``self._m`` just like ``self._m[k] = v``
_MUTATORS = {"append", "appendleft", "extend", "insert", "add", "remove",
             "discard", "pop", "popleft", "popitem", "clear", "update",
             "setdefault", "move_to_end", "sort", "reverse"}


@dataclass(frozen=True)
class _Access:
    attr: str
    method: str
    line: int
    store: bool
    locked: bool


class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = ("attribute written under `with self._lock` in one "
                   "method but accessed without the lock elsewhere in "
                   "the class")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(ctx, node))
        return findings

    # ------------------------------------------------------------ per class
    def _check_class(self, ctx: FileContext,
                     cls: ast.ClassDef) -> List[Finding]:
        lock_attrs = self._lock_attrs(cls)
        if not lock_attrs:
            return []
        container_attrs = self._container_attrs(cls)
        accesses: List[_Access] = []
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                held = (item.name.endswith("_locked")
                        or bool(_LOCK_HELD_RE.search(
                            ast.get_docstring(item) or "")))
                self._walk(item, item.name, lock_attrs, container_attrs,
                           held, accesses)

        guarded: Dict[str, Tuple[str, int]] = {}
        for a in accesses:
            if a.store and a.locked and a.method != "__init__":
                guarded.setdefault(a.attr, (a.method, a.line))

        findings: List[Finding] = []
        reported: Set[Tuple[str, str]] = set()
        for a in accesses:
            if (a.attr in guarded and not a.locked
                    and a.method != "__init__"
                    and (a.attr, a.method) not in reported):
                reported.add((a.attr, a.method))
                gm, gl = guarded[a.attr]
                findings.append(self.finding(
                    ctx, a.line,
                    f"self.{a.attr} is written under a lock in "
                    f"{cls.name}.{gm} (line {gl}) but "
                    f"{'written' if a.store else 'read'} without it here — "
                    f"take the lock, or state the lock-free invariant in a "
                    f"suppression comment",
                    symbol=f"{cls.name}.{a.method}.{a.attr}"))
        return findings

    def _lock_attrs(self, cls: ast.ClassDef) -> Set[str]:
        attrs: Set[str] = set()
        for item in cls.body:
            if (isinstance(item, ast.FunctionDef)
                    and item.name == "__init__"):
                for node in ast.walk(item):
                    if (isinstance(node, ast.Assign)
                            and isinstance(node.value, ast.Call)
                            and dotted_name(node.value.func) in _LOCK_CTORS):
                        for tgt in node.targets:
                            if (isinstance(tgt, ast.Attribute)
                                    and isinstance(tgt.value, ast.Name)
                                    and tgt.value.id == "self"):
                                attrs.add(tgt.attr)
        return attrs

    def _container_attrs(self, cls: ast.ClassDef) -> Set[str]:
        """Attributes initialised as plain containers anywhere in the
        class — the only ones whose in-place mutations (``self._m[k] =``,
        ``self._m.pop(...)``) count as writes.  Mutator-named METHOD
        calls on arbitrary domain objects (``self.models.remove(name)``
        where models is a thread-safe registry) must not."""
        ctors = {"dict", "list", "set", "deque", "OrderedDict",
                 "defaultdict", "Counter", "collections.OrderedDict",
                 "collections.deque", "collections.defaultdict",
                 "collections.Counter"}
        attrs: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):   # self._m: Dict[...] = {}
                targets = [node.target]
            else:
                continue
            v = node.value
            is_container = (isinstance(v, (ast.Dict, ast.List, ast.Set,
                                           ast.DictComp, ast.ListComp,
                                           ast.SetComp))
                            or (isinstance(v, ast.Call)
                                and dotted_name(v.func) in ctors))
            if not is_container:
                continue
            for tgt in targets:
                attr = self._self_attr(tgt)
                if attr is not None:
                    attrs.add(attr)
        return attrs

    # ------------------------------------------------- lock-aware traversal
    def _walk(self, node: ast.AST, method: str, lock_attrs: Set[str],
              container_attrs: Set[str], locked: bool,
              out: List[_Access]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.With):
                takes = any(
                    isinstance(i.context_expr, ast.Attribute)
                    and isinstance(i.context_expr.value, ast.Name)
                    and i.context_expr.value.id == "self"
                    and i.context_expr.attr in lock_attrs
                    for i in child.items)
                for i in child.items:
                    self._walk(i.context_expr, method, lock_attrs,
                               container_attrs, locked, out)
                for stmt in child.body:
                    self._walk(stmt, method, lock_attrs, container_attrs,
                               locked or takes, out)
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                # nested callables (dispatch closures, HTTP handlers) run
                # on other threads with unknown lock state — skip them
                continue
            self._record(child, method, lock_attrs, container_attrs,
                         locked, out)
            self._walk(child, method, lock_attrs, container_attrs, locked,
                       out)

    @staticmethod
    def _self_attr(node: ast.AST) -> "str | None":
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None

    def _record(self, node: ast.AST, method: str, lock_attrs: Set[str],
                container_attrs: Set[str], locked: bool,
                out: List[_Access]) -> None:
        attr = self._self_attr(node)
        if attr is not None and attr not in lock_attrs:
            out.append(_Access(attr, method, node.lineno,
                               isinstance(node.ctx, (ast.Store, ast.Del)),
                               locked))
            return
        # container writes: ``self._m[k] = v`` / ``del self._m[k]`` /
        # ``self._m.pop(k)`` mutate self._m without an Attribute Store
        if (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, (ast.Store, ast.Del))):
            attr = self._self_attr(node.value)
            if attr in container_attrs and attr not in lock_attrs:
                out.append(_Access(attr, method, node.lineno, True, locked))
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS):
            attr = self._self_attr(node.func.value)
            if attr in container_attrs and attr not in lock_attrs:
                out.append(_Access(attr, method, node.lineno, True, locked))
