"""thread-hygiene: every ``threading.Thread`` needs a shutdown story.

Two findings:

- **error** — a thread created with neither ``daemon=`` nor any
  ``.join()`` of its binding anywhere in the module: it can outlive the
  work that spawned it and hang interpreter exit;
- **warning** — a daemon thread bound to an instance attribute
  (``self._thread = Thread(..., daemon=True)``) that is never joined in
  the module: daemonising hides the leak at exit, but the owning
  object's stop path should still join (bounded) so tests and restarts
  don't race a half-dead worker — the PR-8 review fixed two of these by
  hand, which is why the rule exists.

Join detection is symbolic: ``x.join(...)`` marks symbol ``x`` joined,
and a loop variable iterating a list of threads (``for t in threads:
t.join()``, or the listcomp equivalent) marks the list symbol joined.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from scripts.dl4jlint.core import FileContext, Finding, Rule, dotted_name, \
    WARNING

_THREAD_CTORS = {"threading.Thread", "Thread"}


class ThreadHygieneRule(Rule):
    name = "thread-hygiene"
    description = ("threading.Thread without daemon= or a join/stop "
                   "path; daemon self._thread never joined")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        joined = self._joined_symbols(ctx.nodes)
        findings: List[Finding] = []
        for node in ctx.nodes:
            if not isinstance(node, ast.Assign):
                continue
            for call in self._thread_calls(node.value):
                sym = self._target_symbol(node)
                daemon = self._daemon_value(call)
                is_joined = sym is not None and sym in joined
                if daemon is None or daemon is False:
                    if not is_joined:
                        findings.append(self.finding(
                            ctx, call.lineno,
                            "non-daemon thread is never joined in this "
                            "module: it can outlive its owner and hang "
                            "interpreter exit — pass daemon=True or join "
                            "it on the stop path"))
                elif (sym is not None and sym.startswith("self.")
                        and not is_joined):
                    findings.append(self.finding(
                        ctx, call.lineno,
                        f"daemon thread bound to {sym} is never joined: "
                        f"the owner's stop path should join (bounded) so "
                        f"shutdown doesn't race a live worker",
                        severity=WARNING))
        # bare Thread(...).start() with no binding at all
        for node in ctx.nodes:
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "start"):
                inner = node.func.value
                if (isinstance(inner, ast.Call)
                        and dotted_name(inner.func) in _THREAD_CTORS
                        and self._daemon_value(inner) is not True):
                    findings.append(self.finding(
                        ctx, node.lineno,
                        "unbound non-daemon Thread(...).start(): nothing "
                        "can ever join it"))
        return findings

    # -------------------------------------------------------------- helpers
    def _thread_calls(self, value: ast.AST) -> List[ast.Call]:
        return [n for n in ast.walk(value)
                if isinstance(n, ast.Call)
                and dotted_name(n.func) in _THREAD_CTORS]

    @staticmethod
    def _daemon_value(call: ast.Call) -> Optional[bool]:
        for kw in call.keywords:
            if kw.arg == "daemon":
                if isinstance(kw.value, ast.Constant):
                    return bool(kw.value.value)
                return True   # dynamic value: assume intentional
        return None

    @staticmethod
    def _target_symbol(assign: ast.Assign) -> Optional[str]:
        for tgt in assign.targets:
            if isinstance(tgt, ast.Name):
                return tgt.id
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                return f"self.{tgt.attr}"
        return None

    def _joined_symbols(self, nodes) -> Set[str]:
        joined: Set[str] = set()
        aliases: Dict[str, Set[str]] = {}
        for node in nodes:
            if isinstance(node, ast.For) and isinstance(node.target,
                                                        ast.Name):
                it = dotted_name(node.iter)
                if it is not None:
                    aliases.setdefault(node.target.id, set()).add(it)
            elif isinstance(node, ast.comprehension) and isinstance(
                    node.target, ast.Name):
                it = dotted_name(node.iter)
                if it is not None:
                    aliases.setdefault(node.target.id, set()).add(it)
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"):
                base = node.func.value
                sym = dotted_name(base)
                if sym is not None:
                    joined.add(sym)
        # ``for t in threads: t.join()`` joins every element of ``threads``
        # (an over-approximation when one loop variable iterates several
        # containers — acceptable for a should-have-a-stop-path heuristic)
        for alias, targets in aliases.items():
            if alias in joined:
                joined |= targets
        return joined
