"""host-sync-in-hot-path: device->host synchronisation inside code XLA
is supposed to keep on-device.

Two hot regions are audited (see ``jitscan``):

1. **jit-traced function bodies** — ``.item()``, ``float(x)`` /
   ``int(x)`` on a non-constant, ``np.asarray`` / ``np.array``, and
   ``.block_until_ready()`` inside a function jax traces either breaks
   tracing outright (ConcretizationTypeError at the first non-trivial
   input) or silently forces a transfer at trace time;
2. **hot loops** — loop bodies that invoke a jitted step callable.
   There, every ``.item()`` / ``np.asarray`` / ``block_until_ready``
   blocks the Python thread on the device ONCE PER STEP, serialising
   dispatch against execution — exactly the throughput leak the PR-1
   lazy-score work removed from the fit loops.  ``float()`` / ``int()``
   are only flagged in loops when applied directly to a jitted call's
   result (``float(self._step(...))``) — coercing unrelated Python
   scalars per step is ugly but free.

Cold-path conversions (end-of-fit summaries, checkpoint snapshots,
test utilities) are expected findings: baseline them with a ``why``
rather than suppressing, so the ratchet keeps the inventory visible.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from scripts.dl4jlint.core import FileContext, Finding, Rule, dotted_name
from scripts.dl4jlint import jitscan

_SYNC_ATTRS = {"item", "block_until_ready"}
_NP_FUNCS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
             "onp.asarray", "onp.array"}


class HostSyncRule(Rule):
    name = "host-sync-in-hot-path"
    description = ("device->host sync (.item()/float()/int()/np.asarray/"
                   "block_until_ready) inside a jit-traced function or a "
                   "loop driving a jitted step")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        scan = jitscan.scan(ctx)
        findings: List[Finding] = []
        seen: set = set()

        def emit(node: ast.AST, what: str, where: str) -> None:
            if node.lineno in seen:
                return
            seen.add(node.lineno)
            findings.append(self.finding(
                ctx, node.lineno,
                f"{what} forces a device sync {where}"))

        for fn in scan.traced:
            for node in ast.walk(fn):
                what = self._sync_call(node, in_loop=False, scan=scan)
                if what:
                    emit(node, what, "inside a jit-traced function")
        for loop in jitscan.hot_loops(ctx, scan):
            for node in ast.walk(loop):
                what = self._sync_call(node, in_loop=True, scan=scan)
                if what:
                    emit(node, what,
                         "every iteration of a loop driving a jitted step")
        return findings

    # ------------------------------------------------------------- matching
    def _sync_call(self, node: ast.AST, in_loop: bool,
                   scan: jitscan.JitScan) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _SYNC_ATTRS:
            return f".{func.attr}()"
        d = dotted_name(func)
        if d in _NP_FUNCS:
            return f"{d}()"
        if d in ("float", "int") and len(node.args) == 1:
            arg = node.args[0]
            if in_loop:
                # only float(jitted_step(...)) — a direct per-step coercion
                if (isinstance(arg, ast.Call)
                        and scan.symbol_of_call(arg) is not None):
                    return f"{d}() on a jitted step's result"
                return None
            if not isinstance(arg, ast.Constant):
                return f"{d}()"
        return None
