"""implicit-dtype-widening: float64 sneaking into device math.

The repo runs jax with x64 DISABLED (the TPU default): a ``float64``
request inside traced code is silently truncated to float32, so the
source claims a precision the computation never delivers — the exact
mismatch the precision ledger (observability/numerics.py) exists to
surface.  Worse, host-numpy reductions inside a traced function
(``np.sum(tracer)``) either break tracing outright or force the value
to host and promote it to float64, producing a reference that can never
agree bit-for-bit with the device result.

Two checks:

1. **Inside jit-traced functions** (the ``jitscan`` inventory): any
   float64 request — ``np.float64(x)``, ``.astype(np.float64)`` /
   ``.astype("float64")``, a ``dtype=float64`` keyword — and any
   host-numpy reduction (``np.sum`` / ``np.mean`` / ``np.dot`` / ...)
   whose result would be float64 on host.
2. **Corpus-wide**: ``dtype=float64`` passed to a ``jnp.`` / ``jax.``
   constructor — with x64 off jax warns once and hands back float32,
   so the annotation is dead weight at best and a portability trap at
   worst.

Deliberately NOT flagged: ``np.float64`` in plain host code — the
kernel-trust harness (observability/kerneldiff.py) builds float64
numpy references BY DESIGN, and host-side accumulators widening to
float64 is correct numerics, not a bug.  The hazard is float64 *near
the device boundary*, not float64 itself.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from scripts.dl4jlint.core import FileContext, Finding, Rule, dotted_name
from scripts.dl4jlint import jitscan

_F64_NAMES = {"np.float64", "numpy.float64", "onp.float64",
              "jnp.float64", "jax.numpy.float64", "float64"}
_NP_PREFIXES = ("np.", "numpy.", "onp.")
_JNP_PREFIXES = ("jnp.", "jax.numpy.")
# host-numpy ops that return float64 from float32 input (dtype-promoting
# reductions and contractions) — inside a traced fn these also force the
# tracer to host
_NP_REDUCTIONS = {"sum", "mean", "std", "var", "prod", "dot", "einsum",
                  "linalg.norm", "median", "average", "trapz"}


def _is_float64_expr(node: ast.AST) -> bool:
    """``np.float64`` / ``"float64"`` / ``'f8'`` as an expression."""
    if isinstance(node, ast.Constant) and node.value in ("float64", "f8",
                                                         "double"):
        return True
    return dotted_name(node) in _F64_NAMES


def _widening_call(node: ast.AST) -> Optional[str]:
    """What (if anything) this Call does that requests float64."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    # x.astype(np.float64) / x.astype("float64")
    if (isinstance(func, ast.Attribute) and func.attr == "astype"
            and node.args and _is_float64_expr(node.args[0])):
        return ".astype(float64)"
    # np.float64(x) — a conversion, not a bare dtype reference
    if dotted_name(func) in _F64_NAMES and node.args:
        return "float64(...) conversion"
    # any call carrying dtype=float64
    for kw in node.keywords:
        if kw.arg == "dtype" and _is_float64_expr(kw.value):
            return "dtype=float64 keyword"
    return None


def _np_reduction(node: ast.AST) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    d = dotted_name(node.func)
    if d is None:
        return None
    for prefix in _NP_PREFIXES:
        if d.startswith(prefix) and d[len(prefix):] in _NP_REDUCTIONS:
            return d
    return None


class DtypeWideningRule(Rule):
    name = "implicit-dtype-widening"
    description = ("float64 requests in jit-traced code (silently f32 "
                   "under x64-off) and host-numpy reductions inside "
                   "traced functions")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        seen: set = set()

        def emit(node: ast.AST, message: str) -> None:
            if node.lineno in seen:
                return
            seen.add(node.lineno)
            findings.append(self.finding(ctx, node.lineno, message))

        scan = jitscan.scan(ctx)
        traced_nodes: set = set()
        for fn in scan.traced:
            for node in ast.walk(fn):
                traced_nodes.add(id(node))
                what = _widening_call(node)
                if what:
                    emit(node, f"{what} inside a jit-traced function — "
                         "x64 is off, this computes in float32 while the "
                         "source claims float64")
                    continue
                red = _np_reduction(node)
                if red:
                    emit(node, f"host-numpy {red}() inside a jit-traced "
                         "function — breaks tracing (or silently promotes "
                         "to float64 on host)")
        # corpus-wide: dtype=float64 handed to a jnp/jax constructor
        for node in ctx.nodes:
            if id(node) in traced_nodes or not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d is None or not d.startswith(_JNP_PREFIXES):
                continue
            for kw in node.keywords:
                if kw.arg == "dtype" and _is_float64_expr(kw.value):
                    emit(node, f"{d}(dtype=float64) — jax with x64 off "
                         "returns float32; drop the annotation or build "
                         "the reference with host numpy")
        return findings
