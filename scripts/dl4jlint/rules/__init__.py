"""Rule registry.  Add a rule: write a module here, subclass ``Rule``,
append an instance to ``ALL_RULES`` (docs/static-analysis.md walks
through it)."""

from __future__ import annotations

from typing import List, Sequence

from scripts.dl4jlint.core import Rule
from scripts.dl4jlint.rules.dtype_widening import DtypeWideningRule
from scripts.dl4jlint.rules.host_sync import HostSyncRule
from scripts.dl4jlint.rules.lock_discipline import LockDisciplineRule
from scripts.dl4jlint.rules.metrics_docs import MetricsDocsRule
from scripts.dl4jlint.rules.recompile import RecompileHazardRule
from scripts.dl4jlint.rules.rng_reuse import RngReuseRule
from scripts.dl4jlint.rules.thread_hygiene import ThreadHygieneRule

ALL_RULES: List[Rule] = [
    HostSyncRule(),
    RecompileHazardRule(),
    LockDisciplineRule(),
    RngReuseRule(),
    DtypeWideningRule(),
    ThreadHygieneRule(),
    MetricsDocsRule(),
]


def get_rules(names: Sequence[str] = ()) -> List[Rule]:
    if not names:
        return list(ALL_RULES)
    by_name = {r.name: r for r in ALL_RULES}
    missing = [n for n in names if n not in by_name]
    if missing:
        raise KeyError(f"unknown rule(s): {', '.join(missing)} "
                       f"(known: {', '.join(sorted(by_name))})")
    return [by_name[n] for n in names]
