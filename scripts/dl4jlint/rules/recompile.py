"""recompile-hazard: patterns that defeat XLA's compile cache.

The repo's serving and bench contracts assume a CLOSED shape set and
zero steady-state compiles (PR-2's bucket policy; the
``steady_state_compiles`` bench rule).  Three statically detectable ways
code breaks that:

1. **fresh-jit-invoked-immediately** — ``jax.jit(f)(x)``: the jitted
   callable is born, compiled, and thrown away; every call pays a full
   trace+compile.
2. **jit-inside-a-loop** — a ``jax.jit(...)`` call in a For/While body
   builds a new callable (new cache) per iteration.  Legit one-off
   setups (one jit per pipeline stage, reused for the whole run) are
   expected findings: baseline them with a ``why``.
3. **shape-derived argument without static_argnums** — a call through a
   symbol bound to ``jax.jit(f)`` (no ``static_argnums``/
   ``static_argnames``) passing ``len(...)``, ``x.shape``/``x.shape[i]``
   or ``x.ndim``: a Python int that varies with the data retraces on
   every new value.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from scripts.dl4jlint.core import FileContext, Finding, Rule, dotted_name
from scripts.dl4jlint import jitscan


def _is_shape_derived(node: ast.AST) -> bool:
    """len(...), x.shape, x.shape[i], x.ndim — per-call Python ints."""
    if isinstance(node, ast.Call) and dotted_name(node.func) == "len":
        return True
    if isinstance(node, ast.Attribute) and node.attr in ("shape", "ndim"):
        return True
    if isinstance(node, ast.Subscript):
        return _is_shape_derived(node.value)
    return False


class RecompileHazardRule(Rule):
    name = "recompile-hazard"
    description = ("jax.jit created per call/iteration, or a jitted "
                   "callable fed per-call Python shapes without "
                   "static_argnums")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        scan = jitscan.scan(ctx)
        findings: List[Finding] = []
        seen: set = set()

        def emit(line: int, msg: str) -> None:
            if (line, msg[:20]) in seen:
                return
            seen.add((line, msg[:20]))
            findings.append(self.finding(ctx, line, msg))

        # one pass over the flat node list; placement via parent links
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            # 1) jax.jit(...)(...) — callable discarded after one call.
            # Direct form only: partial(jax.jit, kw)(fn) is the BINDING
            # idiom (construct once, reuse), not an immediate invocation.
            if jitscan.is_direct_jit_call(node.func):
                emit(node.lineno,
                     "jax.jit(...) invoked immediately: the compiled "
                     "callable is discarded, so every call re-traces and "
                     "re-compiles — bind it once and reuse it")
            # 2) jax.jit inside a loop body
            if jitscan.is_jit_call(node) and any(
                    isinstance(a, (ast.For, ast.While))
                    for a in ctx.ancestors(node)):
                emit(node.lineno,
                     "jax.jit(...) inside a loop: a fresh callable "
                     "(fresh compile cache) per iteration — hoist it "
                     "out of the loop or memoise per static config")
            # 3) shape-derived args into a jitted symbol w/o static_argnums
            sym = scan.symbol_of_call(node)
            if sym is None or scan.jitted_symbols.get(sym):
                continue   # unknown symbol, or jit declared static args
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if _is_shape_derived(arg):
                    emit(node.lineno,
                         f"jitted callable {sym} fed a per-call Python "
                         f"shape/length without static_argnums: every new "
                         f"value triggers a re-trace and XLA re-compile")
                    break
        return findings
