"""rng-key-reuse: the same PRNG key consumed by two ``jax.random`` calls.

JAX keys are use-once: feeding one key to two random ops yields
correlated (often identical) streams, and the PR-5 retry/RNG-rewind
semantics additionally assume every consumed key was minted by exactly
one ``split``/``fold_in`` step.  This rule does a statement-order scan
of each function body:

- passing a variable as the FIRST argument of any ``jax.random.*`` call
  (except ``PRNGKey``) marks it consumed;
- re-assigning the variable (``rng, sub = jax.random.split(rng)``)
  clears it;
- consuming an already-consumed variable is a finding.

Control flow is approximated: ``if``/``else`` branches are scanned with
independent copies of the state (a key consumed in only one branch is
not double-use), loop bodies are scanned twice so loop-carried reuse
(``for ...: jax.random.normal(key, ...)`` without a split inside the
loop) is caught on the simulated second iteration.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from scripts.dl4jlint.core import FileContext, Finding, Rule, dotted_name

_CREATORS = {"PRNGKey", "key"}   # jax.random.key is the new-style creator


def _terminates(stmts) -> bool:
    """True when a statement list always leaves the enclosing block
    (so its PRNG state never merges past the conditional)."""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


def _scoped_walk(root: ast.AST):
    """ast.walk that does NOT descend into nested function/lambda
    bodies: a jax.random call inside ``lambda s: normal(key, s)`` runs
    when the lambda is CALLED, not where it is defined, so it must not
    mark ``key`` consumed in the enclosing statement order."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.Lambda, ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                continue
            stack.append(child)


class RngReuseRule(Rule):
    name = "rng-key-reuse"
    description = ("the same PRNG key variable consumed by two "
                   "jax.random calls without an intervening split")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        prefixes = self._random_prefixes(ctx.tree)
        if not prefixes:
            return []
        findings: List[Finding] = []
        for node in ctx.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(ctx, node, prefixes, findings)
        return findings

    # ----------------------------------------------------------- module prep
    def _random_prefixes(self, tree: ast.Module) -> Set[str]:
        """Dotted prefixes that denote jax.random in this module."""
        prefixes: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax":
                        prefixes.add(f"{a.asname or 'jax'}.random")
                    elif a.name == "jax.random":
                        prefixes.add(a.asname or "jax.random")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax":
                    for a in node.names:
                        if a.name == "random":
                            prefixes.add(a.asname or "random")
        return prefixes

    # ------------------------------------------------------- function scan
    def _scan_function(self, ctx: FileContext, fn: ast.AST,
                       prefixes: Set[str],
                       findings: List[Finding]) -> None:
        reported: Set[int] = set()

        def consume_fn(call: ast.Call) -> Optional[str]:
            """The consumed key symbol for a jax.random call, else None."""
            d = dotted_name(call.func)
            if d is None or "." not in d:
                return None
            prefix, _, attr = d.rpartition(".")
            if prefix not in prefixes or attr in _CREATORS:
                return None
            if not call.args:
                return None
            return dotted_name(call.args[0])

        def uses_in(node: ast.AST) -> List[Tuple[str, int]]:
            out = []
            for sub in _scoped_walk(node):
                if isinstance(sub, ast.Call):
                    sym = consume_fn(sub)
                    if sym is not None:
                        out.append((sym, sub.lineno))
            return out

        def targets_in(node: ast.AST) -> List[str]:
            out = []
            for sub in _scoped_walk(node):
                if isinstance(sub, (ast.Name, ast.Attribute)) and isinstance(
                        getattr(sub, "ctx", None), ast.Store):
                    sym = dotted_name(sub)
                    if sym is not None:
                        out.append(sym)
            return out

        def run(stmts, state: Dict[str, int]) -> Dict[str, int]:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if isinstance(stmt, ast.If):
                    for sym, line in uses_in(stmt.test):
                        note(sym, line, state)
                    s_body = run(stmt.body, dict(state))
                    s_else = run(stmt.orelse, dict(state))
                    # a branch that terminates (return/raise/...) never
                    # reaches the code after the If — dispatch chains like
                    # ``if name == "uniform": return jax.random.uniform(key)``
                    # must not mark ``key`` consumed for later branches
                    merged = dict(state)
                    if not _terminates(stmt.body):
                        merged.update(s_body)
                    if stmt.orelse and not _terminates(stmt.orelse):
                        merged.update(s_else)
                    state = merged
                    continue
                if isinstance(stmt, (ast.For, ast.While)):
                    header = (stmt.iter if isinstance(stmt, ast.For)
                              else stmt.test)
                    for sym, line in uses_in(header):
                        note(sym, line, state)
                    if isinstance(stmt, ast.For):
                        for sym in targets_in(stmt.target):
                            state.pop(sym, None)
                    state = run(stmt.body, state)
                    state = run(stmt.body, state)   # simulated 2nd iteration
                    state = run(stmt.orelse, state)
                    continue
                if isinstance(stmt, ast.Try):
                    state = run(stmt.body, state)
                    for h in stmt.handlers:
                        state = run(h.body, dict(state))
                    state = run(stmt.orelse, state)
                    state = run(stmt.finalbody, state)
                    continue
                if isinstance(stmt, ast.With):
                    for item in stmt.items:
                        for sym, line in uses_in(item.context_expr):
                            note(sym, line, state)
                    state = run(stmt.body, state)
                    continue
                # plain statement: uses first, then (re)bindings clear
                for sym, line in uses_in(stmt):
                    note(sym, line, state)
                for sym in targets_in(stmt):
                    state.pop(sym, None)
            return state

        def note(sym: str, line: int, state: Dict[str, int]) -> None:
            prev = state.get(sym)
            if prev is not None and line not in reported:
                reported.add(line)
                findings.append(self.finding(
                    ctx, line,
                    f"PRNG key {sym!r} was already consumed by a "
                    f"jax.random call at line {prev} — split it "
                    f"(`{sym}, sub = jax.random.split({sym})`) before "
                    f"reusing, or the two draws are correlated"))
            state[sym] = line if prev is None else prev

        run(list(fn.body), {})
