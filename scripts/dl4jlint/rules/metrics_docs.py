"""metrics-docs: every registered dl4j_* metric family has help text and
a docs/observability.md table row.

The original standalone lint (``scripts/check_metrics_docs.py``, now a
shim over this rule) predates the dl4jlint framework; its scan logic
lives here unchanged in substance:

1. every ``.counter(...)`` / ``.gauge(...)`` / ``.histogram(...)``
   registration whose family name starts with ``dl4j_`` must pass a
   non-empty help string at least once across the codebase;
2. every family must appear in a table row of the metric catalogue in
   ``docs/observability.md``.

Runs project-level (``finalize``): help-text sites for one family may be
spread across files, so per-file checking can't decide anything.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from scripts.dl4jlint.core import (
    REPO, FileContext, Finding, Rule,
)

_METHODS = {"counter", "gauge", "histogram"}
DOCS = os.path.join(REPO, "docs", "observability.md")

# (rel path, line, has_help, normalized help text or None) per family
Registration = Tuple[str, int, bool, Optional[str]]


def _literal_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def registrations_in(tree: ast.Module,
                     rel: str) -> Dict[str, List[Registration]]:
    """family -> registration sites in one parsed module."""
    out: Dict[str, List[Registration]] = {}
    # module-level string constants (owning modules name their families
    # via _FAMILY = "dl4j_..." so they register in one place)
    consts: Dict[str, str] = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and (s := _literal_str(node.value)) is not None):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    consts[tgt.id] = s
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METHODS and node.args):
            continue
        arg0 = node.args[0]
        name = _literal_str(arg0)
        if name is None and isinstance(arg0, ast.Name):
            name = consts.get(arg0.id)
        if not name or not name.startswith("dl4j_"):
            continue
        def _resolve(n) -> Optional[str]:
            # literal, or a module-level string constant (families whose
            # help must be IDENTICAL across registration sites share a
            # _H_* constant — see the drift check in finalize)
            s = _literal_str(n)
            if s is None and isinstance(n, ast.Name):
                s = consts.get(n.id)
            return s

        help_text = None
        if len(node.args) > 1:
            help_text = _resolve(node.args[1])
        for kw in node.keywords:
            if kw.arg == "help":
                help_text = _resolve(kw.value)
        # adjacent string literals concatenate into one Constant, so a
        # multi-line help renders as a single (truthy) literal here
        has_help = bool(help_text and help_text.strip())
        # whitespace-normalized so a re-wrap is not "drift"
        norm = " ".join(help_text.split()) if has_help else None
        out.setdefault(name, []).append((rel, node.lineno, has_help, norm))
    return out


def documented_families(docs_path: str = DOCS) -> Set[str]:
    """dl4j_* names appearing in table rows of docs/observability.md."""
    names: Set[str] = set()
    with open(docs_path, encoding="utf-8") as f:
        for line in f:
            if not line.lstrip().startswith("|"):
                continue
            for tok in line.replace("`", " ").replace("|", " ").split():
                tok = tok.strip("*,.()/")
                if tok.startswith("dl4j_"):
                    names.add(tok)
    return names


class MetricsDocsRule(Rule):
    name = "metrics-docs"
    description = ("registered dl4j_* metric family lacks help text, a "
                   "docs/observability.md table row, or registers with "
                   "diverging help text across modules")

    def __init__(self, docs_path: str = DOCS):
        self.docs_path = docs_path

    def finalize(self, ctxs: Sequence[FileContext]) -> Iterable[Finding]:
        regs: Dict[str, List[Registration]] = {}
        for ctx in ctxs:
            for name, sites in registrations_in(ctx.tree, ctx.rel).items():
                regs.setdefault(name, []).extend(sites)
        findings: List[Finding] = []
        in_package = any(c.rel.startswith("deeplearning4j_tpu/")
                         for c in ctxs)
        if not regs:
            if in_package:
                c0 = ctxs[0]
                findings.append(self.finding(
                    c0, 1, "no dl4j_* metric registrations found in the "
                    "package — scanner broken?", symbol="<corpus>"))
            return findings
        docs = (documented_families(self.docs_path)
                if os.path.exists(self.docs_path) else set())
        for name, sites in sorted(regs.items()):
            path, line = sites[0][0], sites[0][1]
            if not any(h for _f, _l, h, _t in sites):
                where = ", ".join(f"{f}:{l}" for f, l, _h, _t in sites[:3])
                findings.append(Finding(
                    self.name, path, line, name,
                    f"{name}: registered without non-empty help text "
                    f"({where})"))
            if name not in docs:
                findings.append(Finding(
                    self.name, path, line, name,
                    f"{name}: no row in docs/observability.md metric "
                    f"table"))
            # diverging help across modules breaks the federated # HELP
            # line: the aggregator re-exports ONE help string per
            # family, so two owners must agree word-for-word
            helps: Dict[str, Tuple[str, int]] = {}
            for f, l, _h, text in sites:
                if text is not None and text not in helps:
                    helps[text] = (f, l)
            if (len(helps) > 1
                    and len({f for f, _l in helps.values()}) > 1):
                where = ", ".join(
                    f"{f}:{l}" for f, l in sorted(helps.values())[:3])
                findings.append(Finding(
                    self.name, path, line, name,
                    f"{name}: help text diverges across modules "
                    f"({where}) — the federated HELP line needs one "
                    f"agreed string"))
        return findings
