"""Shared jit-graph scan: which functions does XLA trace, and which
module symbols are bound to jitted callables?

Both the host-sync and recompile-hazard rules need the same inventory of
a module's jit surface, built once per file:

- ``traced``: function/lambda AST nodes whose BODY is traced by XLA —
  ``@jax.jit``-decorated (directly or via ``partial(jax.jit, ...)``),
  passed to a ``jax.jit(...)`` call (possibly through ``grad`` /
  ``value_and_grad`` / ``vmap`` / ``pmap`` wrappers), or a lambda inside
  one.
- ``jitted_symbols``: names a jitted callable is bound to — ``step =
  jax.jit(f)`` or ``self._step = instrument(jax.jit(f), ...)`` — mapped
  to whether the jit call passed ``static_argnums``/``static_argnames``.
  Calls through these symbols are the per-step hot invocations the
  recompile rule audits and the host-sync rule uses to mark hot loops.
- ``jit_calls``: every ``jax.jit(...)`` Call node (for placement checks:
  jit-inside-a-loop, jit-invoked-immediately).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from scripts.dl4jlint.core import dotted_name

_JIT_NAMES = {"jax.jit", "jit"}
_PARTIAL_NAMES = {"partial", "functools.partial"}
_TRANSFORMS = {"jax.grad", "jax.value_and_grad", "jax.vmap", "jax.pmap",
               "grad", "value_and_grad", "vmap", "pmap"}


def is_jit_call(node: ast.AST) -> bool:
    """``jax.jit(...)`` or ``partial(jax.jit, ...)``."""
    if not isinstance(node, ast.Call):
        return False
    d = dotted_name(node.func)
    if d in _JIT_NAMES:
        return True
    return (d in _PARTIAL_NAMES and node.args
            and dotted_name(node.args[0]) in _JIT_NAMES)


def is_direct_jit_call(node: ast.AST) -> bool:
    """``jax.jit(...)`` only — NOT ``partial(jax.jit, ...)``, which is a
    constructor whose result is normally bound and reused (the
    ``step = partial(jax.jit, donate_argnums=...)(fn)`` binding idiom
    must not read as invoke-immediately)."""
    return (isinstance(node, ast.Call)
            and dotted_name(node.func) in _JIT_NAMES)


def _jit_kwargs(node: ast.Call) -> List[ast.keyword]:
    return node.keywords


def has_static_args(node: ast.Call) -> bool:
    return any(kw.arg in ("static_argnums", "static_argnames")
               for kw in node.keywords)


def _unwrap_traced_arg(node: ast.AST) -> Optional[ast.AST]:
    """The function expression jax ultimately traces: unwraps transform
    calls like ``jax.jit(jax.value_and_grad(f))`` down to ``f``."""
    while (isinstance(node, ast.Call)
           and dotted_name(node.func) in _TRANSFORMS and node.args):
        node = node.args[0]
    if isinstance(node, (ast.Lambda, ast.Name)):
        return node
    return None


def _binding_symbol(target: ast.AST) -> Optional[str]:
    """``x`` or ``self.attr`` as a string symbol, else None."""
    if isinstance(target, ast.Name):
        return target.id
    if (isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"):
        return f"self.{target.attr}"
    return None


@dataclass
class JitScan:
    traced: List[ast.AST] = field(default_factory=list)
    jitted_symbols: Dict[str, bool] = field(default_factory=dict)  # -> static?
    jit_calls: List[ast.Call] = field(default_factory=list)

    def symbol_of_call(self, call: ast.Call) -> Optional[str]:
        """The jitted symbol a Call invokes, or None."""
        sym = _binding_symbol(call.func)
        if sym is not None and sym in self.jitted_symbols:
            return sym
        return None


def scan(ctx) -> JitScan:
    """The module's JitScan, computed once per file and cached on the
    FileContext (both the host-sync and recompile rules need it)."""
    hit = ctx.cache.get("jitscan")
    if hit is None:
        hit = ctx.cache["jitscan"] = _scan_nodes(ctx.nodes)
    return hit


def scan_module(tree: ast.Module) -> JitScan:
    return _scan_nodes(list(ast.walk(tree)))


def _scan_nodes(nodes: List[ast.AST]) -> JitScan:
    scan = JitScan()
    defs_by_name: Dict[str, List[ast.AST]] = {}
    traced_names: Set[str] = set()
    traced_nodes: List[ast.AST] = []

    for node in nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)
            for dec in node.decorator_list:
                if (dotted_name(dec) in _JIT_NAMES
                        or (isinstance(dec, ast.Call) and is_jit_call(dec))):
                    traced_nodes.append(node)
                    # the decorated NAME is a jitted callable too: a loop
                    # invoking it per iteration is a hot loop (the old
                    # per-tensor StatsListener sync storm hid behind this
                    # gap — decorator-jitted helpers driven from a Python
                    # loop never registered as jitted symbols)
                    static = (has_static_args(dec)
                              if isinstance(dec, ast.Call) else False)
                    scan.jitted_symbols.setdefault(node.name, static)
        if is_jit_call(node):
            scan.jit_calls.append(node)
            # partial(jax.jit, f): traced arg is args[1]; jax.jit(f): args[0]
            args = (node.args[1:] if dotted_name(node.func) in _PARTIAL_NAMES
                    else node.args)
            if args:
                fn = _unwrap_traced_arg(args[0])
                if isinstance(fn, ast.Lambda):
                    traced_nodes.append(fn)
                elif isinstance(fn, ast.Name):
                    traced_names.add(fn.id)

    for name in traced_names:
        traced_nodes.extend(defs_by_name.get(name, ()))
    scan.traced = traced_nodes

    # symbol bindings: assignments whose value subtree holds a jit call
    for node in nodes:
        if not isinstance(node, ast.Assign):
            continue
        jits = [n for n in ast.walk(node.value) if is_jit_call(n)]
        if not jits:
            continue
        static = any(has_static_args(j) for j in jits)
        for tgt in node.targets:
            sym = _binding_symbol(tgt)
            if sym is not None:
                scan.jitted_symbols[sym] = static
    return scan


def hot_loops(ctx, scan: JitScan) -> List[ast.AST]:
    """For/While loops whose body invokes a jitted symbol — the per-step
    regions where a host sync costs throughput every iteration.  Found
    by climbing parents from each jitted call (one pass, no re-walks)."""
    out: List[ast.AST] = []
    seen: Set[int] = set()
    for node in ctx.nodes:
        if not (isinstance(node, ast.Call) and scan.symbol_of_call(node)):
            continue
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.For, ast.While)) and id(anc) not in seen:
                seen.add(id(anc))
                out.append(anc)
    return out
