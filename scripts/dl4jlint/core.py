"""dl4jlint core: the rule API, finding model, suppressions, file walk.

Everything here is stdlib-only and never imports the package under
analysis (and therefore never imports jax) — the whole suite is pure
``ast`` source analysis, same discipline as ``check_metrics_docs.py``
and ``check_bench_regression.py`` before it, so a full-repo run stays
well under the 5-second budget and works on a machine with no
accelerator stack installed.

Vocabulary:

- A ``Rule`` inspects parsed sources and yields ``Finding``s.  Per-file
  analysis goes in ``check(ctx)``; rules that need the whole corpus (or
  non-Python inputs, like the metrics-docs table) implement
  ``finalize(ctxs)`` instead (or additionally).
- A ``Finding`` is keyed ``(rule, path, symbol)`` for baseline matching
  — deliberately NOT by line number, so unrelated edits shifting a file
  don't invalidate the committed baseline.
- Suppressions are source comments::

      x = y.item()   # dl4jlint: disable=host-sync-in-hot-path -- why
      # dl4jlint: disable-next-line=lock-discipline -- single writer
      # dl4jlint: disable-file=rng-key-reuse -- fixture corpus

  ``disable=all`` silences every rule for the scope.  The ``-- why``
  trailer is conventionally required by review, not enforced here.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PACKAGE_DIR = os.path.join(REPO, "deeplearning4j_tpu")
EXTRA_FILES = (os.path.join(REPO, "bench.py"),)

ERROR = "error"
WARNING = "warning"

_SUPPRESS_RE = re.compile(
    r"#\s*dl4jlint:\s*(disable|disable-next-line|disable-file)"
    r"\s*=\s*([A-Za-z0-9_,\- ]+)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    symbol: str        # enclosing ``Class.method`` / ``<module>`` / family
    message: str
    severity: str = ERROR

    @property
    def key(self) -> Tuple[str, str, str]:
        """Baseline identity — line numbers intentionally excluded."""
        return (self.rule, self.path, self.symbol)

    def format(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] "
                f"{self.symbol}: {self.message}")

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message,
                "severity": self.severity}


class Rule:
    """Base class for all dl4jlint rules.

    Subclasses set ``name`` (stable kebab-case id used in baselines and
    suppression comments), ``description`` (one line for --list-rules),
    and override ``check`` and/or ``finalize``."""

    name: str = ""
    description: str = ""
    severity: str = ERROR

    def check(self, ctx: "FileContext") -> Iterable[Finding]:
        return ()

    def finalize(self, ctxs: Sequence["FileContext"]) -> Iterable[Finding]:
        return ()

    # -------------------------------------------------------------- helpers
    def finding(self, ctx: "FileContext", line: int, message: str,
                symbol: Optional[str] = None,
                severity: Optional[str] = None) -> Finding:
        return Finding(self.name, ctx.rel, line,
                       symbol if symbol is not None else ctx.symbol_at(line),
                       message, severity or self.severity)


class FileContext:
    """One parsed source file plus the lookups every rule needs."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._suppress_file: Set[str] = set()
        self._suppress_line: Dict[int, Set[str]] = {}
        self._parse_suppressions()
        self._scopes = self._collect_scopes()
        self._nodes: Optional[List[ast.AST]] = None
        self._parent: Optional[Dict[ast.AST, ast.AST]] = None
        self.cache: Dict[str, object] = {}   # per-file rule scratch

    @property
    def nodes(self) -> List[ast.AST]:
        """Flat node list, computed lazily once — rules iterate this
        instead of re-walking subtrees (keeps the suite O(nodes)), and
        tree-only consumers (the metrics-docs shim) never pay for it."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    @property
    def parent(self) -> Dict[ast.AST, ast.AST]:
        if self._parent is None:
            self._parent = {}
            for node in self.nodes:
                for child in ast.iter_child_nodes(node):
                    self._parent[child] = node
        return self._parent

    def ancestors(self, node: ast.AST):
        parent = self.parent
        while node in parent:
            node = parent[node]
            yield node

    # --------------------------------------------------------- suppressions
    def _parse_suppressions(self) -> None:
        for i, text in enumerate(self.lines, start=1):
            for kind, names in _SUPPRESS_RE.findall(text):
                # the ``-- why`` trailer is prose (may contain commas):
                # strip it before splitting the rule list
                names = names.split("--")[0]
                rules = {n.strip() for n in names.split(",") if n.strip()}
                if kind == "disable-file":
                    self._suppress_file |= rules
                elif kind == "disable-next-line":
                    self._suppress_line.setdefault(i + 1, set()).update(rules)
                else:
                    self._suppress_line.setdefault(i, set()).update(rules)

    def is_suppressed(self, rule: str, line: int) -> bool:
        for scope in (self._suppress_file,
                      self._suppress_line.get(line, ())):
            if rule in scope or "all" in scope:
                return True
        return False

    # --------------------------------------------------------------- scopes
    def _collect_scopes(self) -> List[Tuple[int, int, str]]:
        """(start, end, qualified name) for every function/class, sorted
        outermost-first so the LAST containing interval is innermost."""
        out: List[Tuple[int, int, str]] = []

        def visit(node, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    qual = f"{prefix}.{child.name}" if prefix else child.name
                    out.append((child.lineno,
                                child.end_lineno or child.lineno, qual))
                    visit(child, qual)
                else:
                    visit(child, prefix)

        visit(self.tree, "")
        out.sort()
        return out

    def symbol_at(self, line: int) -> str:
        best = "<module>"
        for start, end, qual in self._scopes:
            if start <= line <= end:
                best = qual
        return best


# ------------------------------------------------------------------ running
def iter_source_files(paths: Optional[Sequence[str]] = None) -> List[str]:
    """Default scan scope: the whole ``deeplearning4j_tpu`` package plus
    ``bench.py`` (the same corpus the metrics-docs lint always walked).
    Explicit ``paths`` (files or directories) override it."""
    if paths:
        out: List[str] = []
        for p in paths:
            p = os.path.abspath(p)
            if os.path.isdir(p):
                for root, _dirs, files in os.walk(p):
                    out.extend(os.path.join(root, f) for f in sorted(files)
                               if f.endswith(".py"))
            else:
                out.append(p)
        return out
    out = []
    for root, _dirs, files in os.walk(PACKAGE_DIR):
        out.extend(os.path.join(root, f) for f in sorted(files)
                   if f.endswith(".py"))
    out.extend(f for f in EXTRA_FILES if os.path.exists(f))
    return sorted(out)


def load_contexts(files: Sequence[str]) -> Tuple[List[FileContext], List[str]]:
    """Parse every file once; unparsable files are reported, not fatal
    (they would fail the test suite on their own)."""
    ctxs: List[FileContext] = []
    errors: List[str] = []
    for path in files:
        rel = os.path.relpath(path, REPO).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
            ctxs.append(FileContext(path, rel, src))
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(f"{rel}: unparsable: {e}")
    return ctxs, errors


@dataclass
class RunResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    files: int = 0
    errors: List[str] = field(default_factory=list)


def run_rules(rules: Sequence[Rule], ctxs: Sequence[FileContext],
              errors: Optional[List[str]] = None) -> RunResult:
    res = RunResult(files=len(ctxs), errors=list(errors or ()))
    raw: List[Finding] = []
    for rule in rules:
        for ctx in ctxs:
            raw.extend(rule.check(ctx))
        raw.extend(rule.finalize(ctxs))
    by_path = {c.rel: c for c in ctxs}
    for f in raw:
        ctx = by_path.get(f.path)
        if ctx is not None and ctx.is_suppressed(f.rule, f.line):
            res.suppressed += 1
        else:
            res.findings.append(f)
    res.findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return res


# ------------------------------------------------------------ AST utilities
def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_call_to(node: ast.AST, *names: str) -> bool:
    """True when ``node`` is a Call whose function's dotted name is one of
    ``names`` (exact match on the dotted string)."""
    return (isinstance(node, ast.Call)
            and dotted_name(node.func) in names)
