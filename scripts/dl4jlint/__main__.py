import sys

from scripts.dl4jlint.cli import main

if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:   # e.g. `... | head`; not an analysis failure
        sys.exit(0)
