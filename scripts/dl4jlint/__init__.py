"""dl4jlint: the repo's JAX/TPU-aware static-analysis suite.

Stdlib-only AST analysis (never imports jax), a rule API with
per-line/per-file suppressions, and a ratcheting JSON baseline.  Run
``python -m scripts.dl4jlint`` from the repo root; the rule catalogue,
suppression syntax, and baseline runbook live in
docs/static-analysis.md.
"""

from scripts.dl4jlint.core import (   # noqa: F401 — public API
    ERROR, WARNING, FileContext, Finding, Rule,
    iter_source_files, load_contexts, run_rules,
)
