"""LSTM throughput-ceiling experiment (VERDICT r3 task 9 / r4 task 6).

PROFILE.md asserts the GravesLSTM bench's low MFU is "intrinsic to the
architecture" (T sequential [B,H]x[H,4H] matmuls cannot fill the MXU);
this script MEASURES that claim instead of asserting it (reference analog:
``LSTMHelpers.java:144-181`` — the cuDNN path has the same shape problem).

Three measurements on the forward scan (``recurrent._scan_lstm``, the
bench config 2x200 H, T=50, fp32), each timed at batch 128 / 512 / 1024:

1. ``scan``       — the real path: input projection as ONE [B*T, in]x[in,4H]
                    matmul + lax.scan of the recurrent cell.
2. ``no_recur``   — the same total FLOPs with the sequential chain removed:
                    xproj plus ONE [B*T, H]x[H,4H] matmul + the gate
                    nonlinearities applied blockwise.  This is the upper
                    bound ANY fused cell kernel (Pallas included) could
                    reach only by eliminating the dependency — which no
                    kernel can; it bounds the win from below-cell fusion.
3. ``matmul_only``— the scan with the cell's elementwise gates stripped
                    (recurrent matmul + add only): isolates how much of a
                    scan step is gate arithmetic (what a fused Pallas cell
                    kernel WOULD save) vs the matmul itself.

Interpretation: if scan/no_recur >> 1 while scan/matmul_only ~ 1, the
ceiling is the recurrence (wider batch is the only lever, until the
[B,H]x[H,4H] step matmul saturates the unit) and a hand-written cell
kernel cannot move it — the PROFILE.md claim, now with numbers.

Run on any platform; writes profiles/lstm_ceiling.json.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time(fn, warmup=2, iters=5):
    import jax

    out = None
    for _ in range(warmup):
        out = fn()
    np.asarray(jax.device_get(out)).ravel()[:1]
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    np.asarray(jax.device_get(out)).ravel()[:1]
    return (time.perf_counter() - t0) / iters


def run(T=50, H=200, n_in=200, batches=(128, 512, 1024)):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.layers.recurrent import (
        _lstm_init, _scan_lstm,
    )

    act = jnp.tanh
    gate = jax.nn.sigmoid
    params = _lstm_init(jax.random.PRNGKey(0), n_in, H, "xavier", None,
                        peephole=True, dtype=jnp.float32)

    rows = {}
    for B in batches:
        x = jnp.asarray(np.random.RandomState(0)
                        .rand(B, T, n_in).astype(np.float32))

        scan_fn = jax.jit(lambda p, x: _scan_lstm(
            p, act, gate, True, x, None)[0])

        def no_recur(p, x):
            B_, T_, _ = x.shape
            xproj = (x.reshape(B_ * T_, -1) @ p["W"] + p["b"])
            z = xproj + xproj[:, :H] @ p["RW"]
            zi, zf, zg, zo = (z[:, i * H:(i + 1) * H] for i in range(4))
            c = gate(zf) * act(zg) + gate(zi) * act(zg)
            return (gate(zo) * act(c)).reshape(B_, T_, H)

        no_recur_fn = jax.jit(no_recur)

        def matmul_only_cell(h_prev, c_prev, xp_t, p):
            z = xp_t + h_prev @ p["RW"]
            return z[:, :H] + c_prev, c_prev + z[:, H:2 * H]

        def matmul_only(p, x):
            B_, T_, _ = x.shape
            xproj = (x.reshape(B_ * T_, -1) @ p["W"] + p["b"]
                     ).reshape(B_, T_, 4 * H)

            def body(carry, xp_t):
                h, c = matmul_only_cell(carry[0], carry[1], xp_t, p)
                return (h, c), h

            z0 = jnp.zeros((B_, H), x.dtype)
            _, ys = jax.lax.scan(body, (z0, z0),
                                 jnp.swapaxes(xproj, 0, 1))
            return jnp.swapaxes(ys, 0, 1)

        matmul_only_fn = jax.jit(matmul_only)

        t_scan = _time(lambda: scan_fn(params, x))
        t_flat = _time(lambda: no_recur_fn(params, x))
        t_mm = _time(lambda: matmul_only_fn(params, x))
        rows[B] = {
            "scan_ms": round(t_scan * 1e3, 3),
            "no_recur_ms": round(t_flat * 1e3, 3),
            "matmul_only_ms": round(t_mm * 1e3, 3),
            "recurrence_cost_x": round(t_scan / t_flat, 2),
            "gate_overhead_x": round(t_scan / t_mm, 2),
            "chars_per_sec": round(B * T / t_scan, 0),
        }
        print(f"B={B}: {rows[B]}")
    return rows


def main():
    import jax

    rows = run()
    out = {
        "config": "T=50 H=200 n_in=200 fp32, forward scan",
        "platform": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
        "by_batch": rows,
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "profiles", "lstm_ceiling.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "by_batch"}))


if __name__ == "__main__":
    main()
