#!/usr/bin/env python
"""One command for every fast source-level CI gate.

Runs, in order:

1. ``dl4jlint`` — the full static-analysis suite (all six rules,
   including metrics-docs) against its committed ratcheting baseline;
2. ``check_metrics_docs`` — the standalone shim, proving the
   backwards-compatible entry point still answers (it shares the
   metrics-docs rule with dl4jlint, so this is a wiring check);
3. ``check_bench_regression --self-test`` — the bench sentinel's
   rule-engine unit checks plus a self-compare of the committed
   ``bench_full.json``;
4. ``fleet schema self-test`` — the fleet telemetry snapshot's
   serialize → merge → re-export round trip must be bit-stable
   (``observability.fleet.schema_roundtrip_selftest``);
5. ``kernel-trust registry`` — the committed ``kernel_trust.json`` and
   the kerneldiff sweep registry must list the same kernels in both
   directions, so no fused kernel can merge without sweep evidence and
   no stale trust entry can outlive its kernel
   (``kerneldiff --check-registry``);
6. ``fleet placement self-test`` — the router's placement policy
   simulated end to end with no jax and no package imports
   (``fleet/placement.py`` is loaded BY FILE PATH, same pattern as the
   bench sentinel): deterministic seeded ties, affinity beating the
   seeded-random control on hit rate, version-tag shadow invalidation,
   drain/stale/dead exclusion, canary-split fractions, session pins.

All six run in a few seconds with no device work — this is the
pre-test gate: run it before the pytest tiers and fail fast on lint
debt, a broken sentinel, a fleet wire-schema drift, or a placement
policy regression.

Usage::

    python scripts/ci_checks.py            # run everything
    python scripts/ci_checks.py --list     # show what would run

Exit codes: 0 all gates passed, 1 any gate failed, 2 usage/IO error —
the same contract as each individual gate.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from typing import List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHECKS: List[Tuple[str, List[str]]] = [
    ("dl4jlint", [sys.executable, "-m", "scripts.dl4jlint"]),
    ("metrics-docs shim",
     [sys.executable, os.path.join(REPO, "scripts",
                                   "check_metrics_docs.py")]),
    ("bench sentinel self-test",
     [sys.executable, os.path.join(REPO, "scripts",
                                   "check_bench_regression.py"),
      "--self-test"]),
    ("fleet schema self-test",
     [sys.executable, "-c",
      "import sys; "
      "from deeplearning4j_tpu.observability.fleet import "
      "schema_roundtrip_selftest; "
      "sys.exit(schema_roundtrip_selftest(verbose=True))"]),
    ("kernel-trust registry",
     [sys.executable, "-m",
      "deeplearning4j_tpu.observability.kerneldiff",
      "--check-registry", os.path.join(REPO, "kernel_trust.json")]),
    ("fleet placement self-test",
     [sys.executable, "-c",
      "import importlib.util, sys; "
      "spec = importlib.util.spec_from_file_location("
      "'fleet_placement', "
      f"{os.path.join(REPO, 'deeplearning4j_tpu', 'fleet', 'placement.py')!r}); "
      "m = importlib.util.module_from_spec(spec); "
      "spec.loader.exec_module(m); "
      "sys.exit(m.placement_selftest(verbose=True))"]),
]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--list", action="store_true",
                    help="print the gate commands and exit")
    args = ap.parse_args(argv)
    if args.list:
        for name, cmd in CHECKS:
            print(f"{name}: {' '.join(cmd)}")
        return 0

    failed: List[str] = []
    for name, cmd in CHECKS:
        t0 = time.perf_counter()
        proc = subprocess.run(cmd, cwd=REPO)
        dt = time.perf_counter() - t0
        status = "ok" if proc.returncode == 0 else f"FAILED ({proc.returncode})"
        print(f"ci_checks: {name}: {status} in {dt:.2f}s", file=sys.stderr)
        if proc.returncode == 2:
            print(f"ci_checks: {name} reported a usage/IO error — "
                  f"aborting", file=sys.stderr)
            return 2
        if proc.returncode != 0:
            failed.append(name)
    if failed:
        print(f"ci_checks: {len(failed)}/{len(CHECKS)} gates failed: "
              f"{', '.join(failed)}", file=sys.stderr)
        return 1
    print(f"ci_checks: all {len(CHECKS)} gates passed", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
