# Makes scripts/ importable so ``python -m scripts.dl4jlint`` works from
# the repo root; the standalone ``python scripts/<name>.py`` invocations
# are unaffected.
