#!/usr/bin/env bash
# One-shot real-chip evidence run (use when the device tunnel is healthy):
#   1. real-TPU test tier (compiled Pallas, donation, bf16, mesh step)
#   2. XPlane profile traces + summary (profiles/)
#   3. benchmark JSON (ResNet-50 imgs/sec + MFU, LeNet, GravesLSTM)
# Each stage is independently timeboxed so a wedged tunnel fails fast.
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== tunnel smoke (60s timebox)"
timeout 60 python -c "import jax, jax.numpy as jnp; print('tunnel OK:', float(jnp.ones((8,8)).sum()))" \
  || { echo "tunnel down — aborting"; exit 1; }

rc=0
echo "== TPU test tier"
timeout 1200 env DL4J_TPU_TESTS=1 python -m pytest tests/ -m tpu -q \
  || { echo "TPU test tier FAILED"; rc=1; }

echo "== profile traces"
timeout 1200 python profile_tpu.py || { echo "profiling FAILED"; rc=1; }

echo "== bench"
timeout 1800 python bench.py || { echo "bench FAILED"; rc=1; }

echo "== LSTM ceiling experiment (on-chip rerun; PROFILE.md round-5 row)"
timeout 900 python scripts/lstm_ceiling_experiment.py \
  || { echo "lstm ceiling FAILED"; rc=1; }

exit $rc
