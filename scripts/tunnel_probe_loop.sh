#!/bin/bash
# Standing TPU tunnel probe loop (VERDICT r4 task #2).
# Probes every 10 min; logs each attempt to profiles/tunnel_probe_r05.log.
# On success, touches /tmp/TPU_UP and exits so the builder can run the
# on-chip queue (pytest -m tpu, bench.py, profile_tpu.py).
LOG=/root/repo/profiles/tunnel_probe_r05.log
rm -f /tmp/TPU_UP
while true; do
  TS=$(date -u +%H:%M:%SZ)
  if timeout 110 python -c "import jax, jax.numpy as jnp; jax.device_get(jnp.ones((8,8)).sum()); print(jax.devices()[0].platform)" 2>/dev/null | grep -qi tpu; then
    echo "$TS UP" >> "$LOG"
    touch /tmp/TPU_UP
    exit 0
  else
    echo "$TS WEDGED" >> "$LOG"
  fi
  sleep 600
done
