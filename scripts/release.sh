#!/usr/bin/env bash
# Release packaging (≙ the reference's maven release scripting, minimized):
# build an sdist+wheel from setup.py/pyproject into dist/ after a green run.
set -euo pipefail
cd "$(dirname "$0")/.."

VERSION="${1:?usage: scripts/release.sh <version>}"

scripts/runtests.sh cpu
python - <<PY
import re, pathlib
p = pathlib.Path("deeplearning4j_tpu/__init__.py")
src = p.read_text()
if re.search(r'^__version__', src, flags=re.M):
    src = re.sub(r'^__version__ = .*$', f'__version__ = "${VERSION}"', src, flags=re.M)
else:
    src = f'__version__ = "${VERSION}"\n' + src
p.write_text(src)
print("version ->", "${VERSION}")
PY
python -m pip wheel --no-deps -w dist . 2>/dev/null || \
  python setup.py sdist 2>/dev/null || \
  echo "NOTE: no packaging backend in this image; version stamped only"
echo "release ${VERSION} prepared"
