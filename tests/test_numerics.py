"""Numerics observability (docs/observability.md "Numerics"): the
in-graph precision ledger, its sampling/exactness contract, interval
gating, the format-safety verdicts, the KV-page range stats, the
kernel-trust differential harness, and the spike drill.

Acceptance oracles (ISSUE 16):

- the device-side stat blocks match a numpy oracle exactly when the
  sample budget is off, and max-abs stays EXACT under sampling (a
  planted outlier the stride misses still trips the hard overflow
  flag);
- a ledger-on fit is BIT-IDENTICAL to a ledger-off fit with zero
  recompiles after the first step;
- interval-gated collection carries the stale snapshot through
  off-steps and refreshes exactly on the interval;
- `FaultInjector.poison_gradients(mode="spike")` flips a healthy
  layer's bf16 verdict and fires the `numerics_anomaly` flight event;
- the kernel-trust harness runs its CPU sweep and the exact kernels
  measure exactly zero error;
- the policy serializes with the model configuration.
"""

import json
import math

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import (
    NeuralNetConfiguration, TrainingNumerics,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.observability import get_flight_recorder, get_registry
from deeplearning4j_tpu.observability import kerneldiff, numerics
from deeplearning4j_tpu.resilience import FaultInjector, inject_faults

pytestmark = pytest.mark.numerics


def counter_value(name, **labels):
    fam = get_registry().get(name)
    if fam is None:
        return 0.0
    total = 0.0
    for label_pairs, child in fam.samples():
        d = dict(label_pairs)
        if all(d.get(k) == v for k, v in labels.items()):
            total += child.value
    return total


def flight_events(kind, **attrs):
    return [ev for ev in get_flight_recorder().events()
            if ev.kind == kind
            and all(ev.attrs.get(k) == v for k, v in attrs.items())]


def make_net(seed=1, num=True, updater="sgd", activation="tanh",
             **policy_kw):
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(updater, learning_rate=0.01))
    if num:
        b.training_numerics(**policy_kw)
    conf = (b.list()
            .layer(DenseLayer(n_in=6, n_out=10, activation=activation))
            .layer(OutputLayer(n_in=10, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def batch(seed=0, n=24, scale=1.0):
    rs = np.random.RandomState(seed)
    x = (scale * rs.rand(n, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, n)]
    return x, y


def entry_dict(block):
    """numerics._entry_host on a device [ENTRY] block."""
    return numerics._entry_host(np.asarray(jax.device_get(block)))


# --------------------------------------------------------- the numpy oracle

def oracle(arrs):
    """Host-side reference for one exact (sample=0) stat block."""
    flat = np.concatenate([np.asarray(a, np.float32).ravel() for a in arrs])
    a = np.abs(flat)
    n = float(a.size)
    max_abs = float(a.max()) if a.size else 0.0
    nz = a > 0
    under, over = {}, {}
    for name, lo, hi in numerics.FORMATS:
        if name == "int8":
            under[name] = float(np.sum(nz & (a < max_abs / 254.0)) / n)
            over[name] = 0.0
        else:
            under[name] = float(np.sum(nz & (a < lo)) / n)
            over[name] = float(np.sum(a > hi) / n)
    hist = np.zeros(numerics.HIST_BINS)
    e = np.floor(np.log2(np.where(nz, a, 1.0)))
    idx = np.clip(e - numerics.HIST_LO, 0, numerics.HIST_BINS - 1)
    for i, keep in zip(idx.astype(int), nz):
        if keep:
            hist[i] += 1
    return max_abs, under, over, hist


def test_entry_stats_matches_numpy_oracle():
    rs = np.random.RandomState(7)
    # exponents spanning subnormal-for-fp16 through past-fp16-max, plus
    # exact zeros (must not count as underflow or enter the histogram).
    # NO float32 subnormals: XLA CPU flushes them to zero in comparisons
    # (1e-40 > 0 is False under jit), so the ledger treats them as zeros
    # — host numpy does not, and the oracle would disagree.
    arrs = [
        (rs.randn(40, 3) * np.exp2(rs.randint(-30, 18, (40, 3)))
         ).astype(np.float32),
        np.zeros((11,), np.float32),
        np.array([1e-30, 7e4, 0.5], np.float32),
    ]
    block = jax.jit(
        lambda t: numerics._entry_stats(t, sample=0))(list(arrs))
    got = entry_dict(block)
    max_abs, under, over, hist = oracle(arrs)
    assert got["max_abs"] == pytest.approx(max_abs, rel=1e-6)
    for name in numerics.FORMAT_NAMES:
        assert got["underflow"][name] == pytest.approx(under[name],
                                                       abs=1e-6), name
        assert got["overflow"][name] == pytest.approx(over[name],
                                                      abs=1e-6), name
    assert np.allclose(got["exponent_histogram"], hist)
    # the histogram counts exactly the nonzero elements
    assert sum(got["exponent_histogram"]) == int(np.sum(
        np.abs(np.concatenate([a.ravel() for a in arrs])) > 0))


def test_sampled_stats_keep_max_abs_exact():
    """The design contract: fractions/histogram may sample, max-abs may
    not — a single planted outlier at an off-stride index must still
    trip the hard fp16 overflow flag."""
    a = np.full((10_000,), 0.5, np.float32)
    a[3] = 1e6          # stride for sample=1024 is 10, index 3 is unsampled
    block = jax.jit(
        lambda t: numerics._entry_stats(t, sample=1024))([a])
    got = entry_dict(block)
    assert got["max_abs"] == pytest.approx(1e6)
    assert got["overflow"]["float16"] == 0.0      # the sample missed it...
    assert numerics.overflow_hard(got, "float16")  # ...the exact pass didn't
    assert numerics.risk_score(got, "float16") == 1.0
    assert not numerics.verdicts(got)["float16"]
    # and the sampled fractions are computed over the strided subset
    assert sum(got["exponent_histogram"]) == 1000


def test_verdict_thresholds():
    healthy = {
        "max_abs": 1.0,
        "underflow": {n: 0.0 for n in numerics.FORMAT_NAMES},
        "overflow": {n: 0.0 for n in numerics.FORMAT_NAMES},
        "exponent_histogram": [0.0] * numerics.HIST_BINS,
    }
    healthy["exponent_histogram"][0 - numerics.HIST_LO] = 100.0
    assert all(numerics.verdicts(healthy).values())
    assert numerics.risk_score(healthy, "bfloat16") == 0.0
    # absorption: values 2^-20 next to a 2^0 max are below the bf16 (8
    # mantissa bits) cutoff but inside fp16's 11 bits? no — 20 > 11:
    # both absorb; fp8 (4 bits) certainly
    wide = dict(healthy)
    wide["exponent_histogram"] = [0.0] * numerics.HIST_BINS
    wide["exponent_histogram"][0 - numerics.HIST_LO] = 40.0
    wide["exponent_histogram"][-20 - numerics.HIST_LO] = 60.0
    assert numerics.absorption_fraction(wide, "bfloat16") == pytest.approx(0.6)
    assert not numerics.verdicts(wide)["bfloat16"]
    assert numerics.verdicts(wide, TrainingNumerics(absorb_threshold=0.7)
                             )["bfloat16"]


# ------------------------------------------------- in-step collection

def test_bit_identical_and_zero_recompiles():
    """Ledger on (collecting EVERY step) vs off: params bit-identical,
    zero compiles/recompiles after the first step."""
    x, y = batch()
    on = make_net(num=True, interval=1)
    off = make_net(num=False)
    on.fit(x, y)
    off.fit(x, y)
    c0 = counter_value("dl4j_compiles_total")
    r0 = counter_value("dl4j_recompiles_total")
    for _ in range(6):
        on.fit(x, y)
        off.fit(x, y)
    assert counter_value("dl4j_compiles_total") == c0
    assert counter_value("dl4j_recompiles_total") == r0
    for a, b in zip(jax.tree_util.tree_leaves(on.params),
                    jax.tree_util.tree_leaves(off.params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    h = numerics.harvest_model(on)
    assert h["iteration"] == on.iteration - 1
    assert set(h["gradients"]) == {"layer_0", "layer_1"}
    assert set(h["activations"]) == {"layer_0", "layer_1"}
    for e in h["gradients"].values():
        assert math.isfinite(e["max_abs"]) and e["max_abs"] > 0
        assert set(e["verdicts"]) == set(numerics.FORMAT_NAMES)


def test_interval_gating_stale_carry():
    """interval=3: harvests between collection steps return the stale
    snapshot (same iteration stamp), and the refresh lands exactly on
    the interval — with zero recompiles across the boundary."""
    x, y = batch()
    net = make_net(num=True, interval=3)
    net.fit(x, y)                      # iteration 0: collected
    c0 = counter_value("dl4j_compiles_total")
    assert numerics.harvest_model(net)["iteration"] == 0
    net.fit(x, y)                      # iteration 1: stale carry
    net.fit(x, y)                      # iteration 2: stale carry
    assert numerics.harvest_model(net)["iteration"] == 0
    net.fit(x, y)                      # iteration 3: collected
    assert numerics.harvest_model(net)["iteration"] == 3
    assert counter_value("dl4j_compiles_total") == c0


def test_moment_entries_under_adam():
    x, y = batch()
    net = make_net(num=True, updater="adam", interval=1)
    for _ in range(3):
        net.fit(x, y)
    h = numerics.harvest_model(net)
    for e in h["moments"].values():
        assert e["max_abs"] > 0        # m and v both measured post-update


# ------------------------------------------------------------- spike drill

def test_spike_drill_flips_bf16_verdict_and_flight_event():
    """The fire drill: healthy fit -> bf16-safe gradients; a spike-mode
    poison (features x1e4) widens the within-layer dynamic range past
    the absorption threshold, the bf16 verdict flips, and
    NumericsMonitor fires the numerics_anomaly flight event naming the
    layer.  Relu, not tanh: a tanh saturated by the spike has exactly
    zero derivative in f32, which *kills* the layer-0 gradients instead
    of widening them — the drill would silently pass the healthy check.
    With relu the W grads blow up ~x1e4 while the bias grads stay O(1)
    in the same stat block: a ~2^15 within-block spread, so the small
    half of the block falls below max_exp - 8 bf16 mantissa bits and
    the absorption fraction crosses the 0.15 drill threshold."""
    x, y = batch(scale=1.0)
    net = make_net(num=True, interval=1, absorb_threshold=0.15,
                   activation="relu")
    for _ in range(3):
        net.fit(x, y)
    before = numerics.harvest_model(net)
    safe_before = {(c, l) for c in ("gradients", "activations")
                   for l, e in before[c].items()
                   if e["verdicts"]["bfloat16"]}
    assert safe_before, "healthy run must have bf16-safe blocks"

    inj = FaultInjector().poison_gradients("0", at_step=net.iteration,
                                           mode="spike")
    with inject_faults(inj):
        net.fit(x, y)
    after = numerics.harvest_model(net)
    assert after["iteration"] == net.iteration - 1
    flipped = [(c, l) for (c, l) in safe_before
               if not after[c][l]["verdicts"]["bfloat16"]]
    assert flipped, "spike did not flip any bf16 verdict"
    # the spike is visible in the exact max-abs, not just the verdicts
    grew = max(after[c][l]["max_abs"] / max(before[c][l]["max_abs"], 1e-30)
               for (c, l) in safe_before)
    assert grew > 1e2

    monitor = numerics.NumericsMonitor(component="drill", min_iteration=0,
                                       warn=lambda *a, **k: None)
    violations = monitor.check(after)
    assert violations
    layer = violations[0]["layer"]
    evs = flight_events("numerics_anomaly", component="drill", layer=layer)
    assert evs, "no numerics_anomaly flight event recorded"


# ------------------------------------------------------------ KV-page stats

def test_kv_page_ledger_under_generation_engine():
    from deeplearning4j_tpu.generation import GenerationEngine
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    lm = transformer_char_lm(vocab_size=29, d_model=32, n_heads=4,
                             layers=2, max_cache=128)
    eng = GenerationEngine(lm, slots=2, page_size=4, max_context=32,
                           max_queue=16, deadline_s=30.0)
    eng.start()
    try:
        toks = eng.generate([1, 2, 3, 4, 5], 8)
        assert len(toks) > 0
        # full pool view: generate() released its pages on completion,
        # but the written values are still in the pool
        full = eng.kv_numerics(allocated_only=False)
        live = eng.kv_numerics()
    finally:
        eng.stop()
    assert full, "no pageable attention layers reported"
    for pools in full.values():
        assert set(pools) == {"pk", "pv"}
        for e in pools.values():
            assert e["pages"], "non-trash pages must be listed"
            # written pages carry real values; the per-page max-abs
            # spread is the int8 per-page-scale decision input
            assert max(e["page_max_abs"]) > 0
            assert all(0.0 <= u <= 1.0 for u in e["int8_underflow"])
            assert 0.0 <= e["int8_ready_fraction"] <= 1.0
    # allocated-only view is a subset (possibly empty: the request freed
    # its pages when it completed) with the same schema
    for layer, pools in live.items():
        for leaf, e in pools.items():
            assert set(e["pages"]) <= set(full[layer][leaf]["pages"])


# --------------------------------------------------------- kernel trust

def test_kerneldiff_cpu_smoke():
    report = kerneldiff.run_sweep(
        kernels=["dot_product_attention", "gather_pages",
                 "pallas_bn_inference"])
    assert report["summary"]["kernels"] == 3
    ks = report["kernels"]
    # gather is pure indexing: exactly zero error, bit-for-bit
    assert ks["gather_pages"]["max_rel_error"] == 0.0
    assert ks["gather_pages"]["classification"] == "within_tolerance"
    for k in ks.values():
        assert k["trusted"], k
        for cfg in k["configs"]:
            assert cfg["status"] == "pass", cfg
    # the report is regression-comparable against itself
    doc = {e["metric"]: e for e in report["all"]}
    assert any(m.startswith("Kernel max rel error") for m in doc)
    text = kerneldiff.format_report(report)
    assert "dot_product_attention" in text
    kerneldiff.publish_metrics(report)
    fam = get_registry().get("dl4j_kernel_max_rel_error")
    assert fam is not None


def test_committed_kernel_trust_snapshot_passes_rules():
    """The committed kernel_trust.json satisfies KERNEL_TRUST_RULES
    against itself — the regression sentinel's fixed point."""
    import os
    from deeplearning4j_tpu.observability import regression

    path = os.path.join(os.path.dirname(__file__), "..",
                        "kernel_trust.json")
    with open(path) as f:
        snap = json.load(f)
    report = regression.compare(snap, snap,
                                rules=regression.KERNEL_TRUST_RULES)
    assert report.regressions == []
    assert report.exit_code == 0
    assert snap["summary"]["failing_configs"] == 0
    assert snap["summary"]["untrusted"] == []
    # satellite 1: the 18 flash-attention failures are triaged as
    # harness/API drift, not kernel bugs
    assert snap["triage"]["flash_attention_tests"]["kernel_bug_count"] == 0


# ---------------------------------------------------------------- conf serde

def test_policy_serde_roundtrip():
    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater("adam", learning_rate=0.01)
            .training_numerics(sample=512, interval=4,
                               absorb_threshold=0.25)
            .list()
            .layer(DenseLayer(n_in=4, n_out=4, activation="relu"))
            .layer(OutputLayer(n_in=4, n_out=2, loss="mcxent",
                               activation="softmax"))
            .build())
    d = conf.to_dict()
    back = type(conf).from_dict(d)
    assert back.numerics == conf.numerics
    assert back.numerics.sample == 512
    assert back.numerics.interval == 4
    assert back.numerics.absorb_threshold == 0.25
    with pytest.raises(ValueError):
        TrainingNumerics(sample=-1)
    with pytest.raises(ValueError):
        TrainingNumerics(interval=0)
    with pytest.raises(ValueError):
        TrainingNumerics(absorb_threshold=0.0)
