"""Clean corpus for implicit-dtype-widening (parsed, never executed)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decorated_step(params, x):
    # in-graph math stays in f32; jnp reductions are fine
    h = (params * x).astype(jnp.float32)
    return jnp.mean(h) + jnp.sum(h ** 2)


def host_reference(x):
    # float64 in PLAIN host code is correct numerics, not a finding —
    # the kernel-trust harness builds f64 numpy references on purpose
    a = np.asarray(x, dtype=np.float64)
    return np.sum(a) / np.float64(a.size)


def device_side():
    return jnp.zeros((8,), dtype=jnp.float32)
