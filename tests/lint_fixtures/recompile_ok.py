"""Clean negatives for recompile-hazard."""
from functools import partial

import jax


def f(x):
    return x * 2


step = jax.jit(f)               # bound once at module level


def run(xs):
    return [step(x) for x in xs]


@partial(jax.jit, static_argnums=(1,))
def bucketed(x, size):
    return x[:size]


sized = jax.jit(f, static_argnames=("n",))


def varying_shape_declared(batch):
    # a per-call length is FINE when the jit declared it static
    return sized(batch, n=len(batch))
