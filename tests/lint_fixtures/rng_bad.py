"""True positives for rng-key-reuse (parsed, never executed)."""
import jax


def double_draw(key, shape):
    a = jax.random.normal(key, shape)
    b = jax.random.uniform(key, shape)      # same key: correlated draws
    return a + b


def loop_carried(key, steps):
    outs = []
    for _ in range(steps):
        outs.append(jax.random.normal(key, ()))   # never split in the loop
    return outs
