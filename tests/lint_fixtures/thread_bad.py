"""True positives for thread-hygiene (parsed, never executed)."""
import threading


def fire_and_forget(fn):
    t = threading.Thread(target=fn)      # no daemon=, never joined
    t.start()
    return t


class Server:
    def start(self, loop):
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()             # daemon bound to self, no join

    def stop(self):
        pass                             # stop path forgets the thread
