"""True positives for recompile-hazard (parsed, never executed)."""
import jax


def f(x):
    return x * 2


def immediate(x):
    return jax.jit(f)(x)        # fresh jit invoked immediately


def per_iteration(xs):
    out = []
    for x in xs:
        g = jax.jit(f)          # fresh callable per iteration
        out.append(g(x))
    return out


step = jax.jit(f)               # no static_argnums ...


def varying_shape(batch):
    return step(len(batch))     # ... fed a per-call Python length
