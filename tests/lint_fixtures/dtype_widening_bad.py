"""True positives for implicit-dtype-widening (parsed, never executed)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decorated_step(params, x):
    acc = jnp.zeros((4,), dtype=np.float64)   # f32 under x64-off
    h = (params * x).astype("float64")        # silent truncation to f32
    return acc + np.mean(h)                   # host reduction on a tracer


def wrapped(params, x):
    scale = np.float64(0.5)                   # conversion in traced code
    return (params * scale * x).sum()


step = jax.jit(wrapped)


def build_reference():
    # corpus-wide check: jnp constructor asking for a dtype jax
    # (x64 off) will never give it
    return jnp.arange(16, dtype="float64")
