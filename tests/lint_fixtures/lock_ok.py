"""Clean negatives for lock-discipline: consistent locking, the
``_locked`` suffix convention, "lock held" docstrings, and a documented
lock-free read behind a suppression."""
import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._active = {}
        self._count = 0

    def activate(self, name, version):
        with self._lock:
            self._active[name] = version
            self._count += 1

    def lookup(self, name):
        with self._lock:
            return self._active.get(name)

    def evict(self, name):
        with self._lock:
            self._evict_locked(name)

    def _evict_locked(self, name):
        self._active.pop(name, None)         # convention: caller holds lock

    def _recount(self):
        """Recompute the counter (lock held)."""
        self._count = len(self._active)

    def size(self):
        # dl4jlint: disable-next-line=lock-discipline -- monitoring read of a GIL-atomic int
        return self._count
