"""Second module of the drift fixture pair — same family, different
help text (see ``metrics_docs_drift_bad.py``)."""

from deeplearning4j_tpu.observability.metrics import get_registry


def register():
    get_registry().counter(
        "dl4j_fixture_drift_total",
        "Fixture requests, by outcome")
