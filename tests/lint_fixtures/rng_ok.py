"""Clean negatives for rng-key-reuse: the split discipline, loop
re-splitting, and the if/return dispatch shape that must NOT count as
double consumption (only one branch ever runs)."""
import jax


def split_draw(key, shape):
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, shape)
    b = jax.random.uniform(k2, shape)
    return a + b


def loop_resplit(key, steps):
    outs = []
    for _ in range(steps):
        key, sub = jax.random.split(key)
        outs.append(jax.random.normal(sub, ()))
    return outs


def dispatch(name, key, shape):
    if name == "normal":
        return jax.random.normal(key, shape)
    if name == "uniform":
        return jax.random.uniform(key, shape)
    return jax.random.bernoulli(key, 0.5, shape)
