"""True positive for metrics-docs: a dl4j_* family registered with no
help text (and no docs/observability.md row exists for it)."""


def register(registry):
    registry.counter("dl4j_fixture_only_total")
    registry.counter("dl4j_fixture_only_total", "")
