"""True positives for host-sync-in-hot-path (parsed, never executed)."""
import jax
import numpy as np


@jax.jit
def decorated_step(params, x):
    loss = (params * x).sum()
    return loss.item()          # sync inside a jit-traced function


def wrapped(params, x):
    return float(params @ x)    # traced via jax.jit(wrapped) below


step = jax.jit(wrapped)


def fit_loop(batches, params):
    total = 0.0
    for b in batches:
        out = step(params, b)                 # hot loop: jitted step
        total += np.asarray(out).sum()        # per-step device readback
        out.block_until_ready()               # per-step pipeline stall
    return total


def per_tensor_stats(tree):
    # the StatsListener sync storm: a loop driving a DECORATED jit
    # helper with a per-tensor host pull (fixed in ui/stats.py — the
    # decorated name must register as a jitted symbol for this to flag)
    out = {}
    for name, arr in tree.items():
        summary = decorated_step(arr, arr)
        out[name] = np.asarray(summary).tolist()   # per-tensor readback
    return out
