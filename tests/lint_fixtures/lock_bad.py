"""True positives for lock-discipline (parsed, never executed)."""
import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._active = {}
        self._count = 0

    def activate(self, name, version):
        with self._lock:
            self._active[name] = version     # guarded container write
            self._count += 1                 # guarded scalar write

    def lookup(self, name):
        return self._active.get(name)        # unlocked read of guarded map

    def evict(self, name):
        self._active.pop(name, None)         # unlocked container mutation

    def size(self):
        return self._count                   # unlocked read of guarded int
