"""Fixture: one dl4j_* family registered in two 'modules' with
DIVERGING help text (metrics-docs drift finding).

The rule keys drift on distinct source FILES, so this file pairs with
``metrics_docs_drift_bad2.py`` — both register
``dl4j_fixture_drift_total`` with different help strings.  The family
name is fixture-only so the repo-wide lint never sees it registered in
the package (both registrations live under tests/lint_fixtures, which
the corpus scan skips).
"""

from deeplearning4j_tpu.observability.metrics import get_registry


def register():
    get_registry().counter(
        "dl4j_fixture_drift_total",
        "Requests served by the fixture engine")
