"""Clean negative for metrics-docs: a documented family (it has a row
in docs/observability.md) registered with non-empty help text."""

_FAMILY = "dl4j_fit_step_seconds"


def register(registry):
    registry.histogram(_FAMILY, "Wall time of one optimisation step")
