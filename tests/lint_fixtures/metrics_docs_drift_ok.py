"""Fixture: the same family registered in two call sites with help
text that differs only in whitespace/wrapping — NOT drift (the rule
normalizes whitespace before comparing)."""

from deeplearning4j_tpu.observability.metrics import get_registry


def register_a():
    get_registry().counter(
        "dl4j_fixture_drift_total",
        "Requests served by the fixture engine")


def register_b():
    get_registry().counter(
        "dl4j_fixture_drift_total",
        "Requests served "
        "by the fixture engine")
