"""Clean negatives for thread-hygiene."""
import threading


def scatter_gather(fn, n):
    threads = [threading.Thread(target=fn, args=(i,)) for i in range(n)]
    [t.start() for t in threads]
    [t.join() for t in threads]          # joined via the list alias


class Server:
    def start(self, loop):
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._thread.join(timeout=5.0)   # bounded join on the stop path
