"""Clean negatives for host-sync-in-hot-path."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decorated_step(params, x):
    return (params * x).sum()   # stays on device


def wrapped(params, x):
    return jnp.dot(params, x)


step = jax.jit(wrapped)


def fit_loop(batches, params):
    outs = []
    for b in batches:
        outs.append(step(params, b))   # no per-step readback
    return np.asarray(outs[-1])        # one sync AFTER the loop is fine


def cold_summary(x):
    return float(np.asarray(x).mean())   # not jitted, not a hot loop


def batched_tensor_stats(tree):
    # the fixed StatsListener shape: stack every tensor's summary in ONE
    # jitted call, one host pull AFTER the loop
    flats = tuple(jnp.ravel(a) for a in tree.values())
    summaries = decorated_step(flats, flats)   # single device program
    return np.asarray(summaries)               # single transfer
