"""Clean negatives for host-sync-in-hot-path."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decorated_step(params, x):
    return (params * x).sum()   # stays on device


def wrapped(params, x):
    return jnp.dot(params, x)


step = jax.jit(wrapped)


def fit_loop(batches, params):
    outs = []
    for b in batches:
        outs.append(step(params, b))   # no per-step readback
    return np.asarray(outs[-1])        # one sync AFTER the loop is fine


def cold_summary(x):
    return float(np.asarray(x).mean())   # not jitted, not a hot loop
