"""Tensor / pipeline / expert parallelism.

Oracle, as everywhere (SURVEY.md §4): parallel training must equal local
sequential math — TP shards the same program (bitwise-close), PP is exact
GPipe grad accumulation, MoE is checked for routing mass conservation and
trainability."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.datasets import DataSet, ListDataSetIterator
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    BatchNormalization, DenseLayer, MoELayer, OutputLayer,
)
from deeplearning4j_tpu.parallel import (
    DistributedNetwork, PipelineParallelTrainingMaster,
    TensorParallelTrainingMaster, split_stages, tensor_parallel_spec,
)


def mlp(seed=3, updater="adam", lr=0.05, widths=(8, 16, 16, 4)):
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(updater, learning_rate=lr).list())
    for i in range(len(widths) - 2):
        b = b.layer(DenseLayer(n_in=widths[i], n_out=widths[i + 1],
                               activation="tanh"))
    b = b.layer(OutputLayer(n_in=widths[-2], n_out=widths[-1], loss="mcxent",
                            activation="softmax"))
    return MultiLayerNetwork(b.build()).init()


def data(n=32, n_in=8, n_out=4, seed=0):
    rs = np.random.RandomState(seed)
    return (rs.rand(n, n_in).astype(np.float32),
            np.eye(n_out, dtype=np.float32)[rs.randint(0, n_out, n)])


def test_tensor_parallel_spec_alternates():
    net = mlp()
    spec = tensor_parallel_spec(net.params, tp=2)
    from jax.sharding import PartitionSpec as P

    assert spec["layer_0"]["W"] == P(None, "model")
    assert spec["layer_1"]["W"] == P("model", None)
    assert spec["layer_2"]["W"] == P(None, "model")
    assert spec["layer_0"]["b"] == P()


def test_tensor_parallel_matches_serial():
    x, y = data()
    serial = mlp()
    serial.fit(ListDataSetIterator(DataSet(x, y), 16), epochs=2)

    tp_net = mlp()
    mesh = backend.default_mesh(data=4, model=2)
    master = TensorParallelTrainingMaster(mesh=mesh)
    DistributedNetwork(tp_net, master).fit(
        ListDataSetIterator(DataSet(x, y), 16), epochs=2)
    for ln in serial.params:
        for pn in serial.params[ln]:
            np.testing.assert_allclose(
                np.asarray(serial.params[ln][pn]),
                np.asarray(tp_net.params[ln][pn]), atol=2e-5,
                err_msg=f"{ln}/{pn}")


def test_split_stages_balanced_and_contiguous():
    net = mlp(widths=(8, 32, 32, 32, 4))
    stages = split_stages(net, 2)
    assert [i for s in stages for i in s] == list(range(len(net.layers)))
    assert len(stages) == 2 and all(stages)


def test_pipeline_matches_serial():
    x, y = data()
    serial = mlp(updater="sgd", lr=0.5)
    serial.fit(ListDataSetIterator(DataSet(x, y), 16), epochs=2)

    pp_net = mlp(updater="sgd", lr=0.5)
    master = PipelineParallelTrainingMaster(n_stages=3, n_microbatches=4,
                                            devices=jax.devices()[:3])
    DistributedNetwork(pp_net, master).fit(
        ListDataSetIterator(DataSet(x, y), 16), epochs=2)
    for ln in serial.params:
        for pn in serial.params[ln]:
            np.testing.assert_allclose(
                np.asarray(serial.params[ln][pn]),
                np.asarray(pp_net.params[ln][pn]), atol=2e-5,
                err_msg=f"{ln}/{pn}")
    assert abs(serial.score_value - pp_net.score_value) < 1e-4


def test_pipeline_rejects_stateful_layers():
    b = (NeuralNetConfiguration.builder().seed(1).updater("sgd").list()
         .layer(DenseLayer(n_in=4, n_out=8))
         .layer(BatchNormalization(n_out=8))
         .layer(OutputLayer(n_in=8, n_out=2)))
    net = MultiLayerNetwork(b.build()).init()
    master = PipelineParallelTrainingMaster(n_stages=2,
                                            devices=jax.devices()[:2])
    x, y = data(8, 4, 2)
    with pytest.raises(ValueError, match="stateless"):
        DistributedNetwork(net, master).fit(
            ListDataSetIterator(DataSet(x, y), 8))


def test_moe_layer_forward_and_training():
    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater("adam", learning_rate=0.02).list()
            .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
            .layer(MoELayer(n_in=16, n_out=16, num_experts=4,
                            capacity_factor=2.0))
            .layer(OutputLayer(n_in=16, n_out=4, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x, y = data(64, 8, 4)
    s0 = net.score(x, y)
    for _ in range(30):
        net.fit(x, y)
    assert net.score(x, y) < s0 * 0.8
    out = np.asarray(net.output(x))
    np.testing.assert_allclose(out.sum(-1), 1.0, atol=1e-5)


def test_moe_expert_sharding_under_tp():
    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater("sgd", learning_rate=0.1).list()
            .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
            .layer(MoELayer(n_in=16, n_out=16, num_experts=4,
                            capacity_factor=2.0))
            .layer(OutputLayer(n_in=16, n_out=4))
            .build())
    net = MultiLayerNetwork(conf).init()
    spec = tensor_parallel_spec(net.params, tp=2)
    from jax.sharding import PartitionSpec as P

    assert spec["layer_1"]["W_up"] == P("model", None, None)
    assert spec["layer_1"]["W_down"] == P("model", None, None)
    mesh = backend.default_mesh(data=4, model=2)
    master = TensorParallelTrainingMaster(mesh=mesh)
    x, y = data(32, 8, 4)
    DistributedNetwork(net, master).fit(ListDataSetIterator(DataSet(x, y), 16))
    assert np.isfinite(net.score_value)


def test_tp_and_pp_with_paramless_layers_and_stateful_updater():
    # regression: updater-state sharding/placement must track the TRAINABLE
    # tree, which omits param-less layers (ActivationLayer etc.)
    from deeplearning4j_tpu.nn.layers import ActivationLayer

    def build():
        return MultiLayerNetwork(
            (NeuralNetConfiguration.builder().seed(4)
             .updater("adam", learning_rate=0.02).list()
             .layer(DenseLayer(n_in=8, n_out=16))
             .layer(ActivationLayer(activation="relu"))
             .layer(OutputLayer(n_in=16, n_out=4)).build())).init()

    x, y = data(16, 8, 4)
    tp_net = build()
    DistributedNetwork(
        tp_net, TensorParallelTrainingMaster(
            mesh=backend.default_mesh(data=4, model=2))
    ).fit(ListDataSetIterator(DataSet(x, y), 16))
    assert np.isfinite(tp_net.score_value)

    pp_net = build()
    DistributedNetwork(
        pp_net, PipelineParallelTrainingMaster(
            n_stages=2, n_microbatches=2, devices=jax.devices()[:2])
    ).fit(ListDataSetIterator(DataSet(x, y), 16))
    assert np.isfinite(pp_net.score_value)


def test_moe_width_inference_from_input_type():
    from deeplearning4j_tpu.nn.inputs import InputType

    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater("sgd", learning_rate=0.1).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(MoELayer(num_experts=2, capacity_factor=2.0))
            .layer(OutputLayer(n_out=4))
            .set_input_type(InputType.feed_forward(8))
            .build())
    net = MultiLayerNetwork(conf).init()
    assert net.params["layer_1"]["W_router"].shape == (16, 2)
    x, y = data(8, 8, 4)
    net.fit(x, y)
    assert np.isfinite(net.score_value)


def test_split_stages_exact_stage_count():
    # regression: 4 layers / 4 stages must give 4 singleton stages
    net = mlp(widths=(8, 16, 16, 4))  # 3 layers
    assert split_stages(net, 3) == [[0], [1], [2]]
    net4 = mlp(widths=(8, 16, 16, 16, 4))  # 4 layers
    assert split_stages(net4, 4) == [[0], [1], [2], [3]]


def test_pipeline_score_includes_regularization():
    def build():
        return MultiLayerNetwork(
            (NeuralNetConfiguration.builder().seed(4)
             .updater("sgd", learning_rate=0.1).list()
             .layer(DenseLayer(n_in=8, n_out=16, l2=0.01))
             .layer(OutputLayer(n_in=16, n_out=4, l2=0.01)).build())).init()

    x, y = data(16, 8, 4)
    serial = build()
    serial.fit(x, y)
    pp_net = build()
    DistributedNetwork(pp_net, PipelineParallelTrainingMaster(
        n_stages=2, n_microbatches=2, devices=jax.devices()[:2])
    ).fit(ListDataSetIterator(DataSet(x, y), 16))
    assert abs(serial.score_value - pp_net.score_value) < 1e-5


def test_moe_partial_inference_builds():
    # regression: validate used to run before setup and reject inferred sizes
    from deeplearning4j_tpu.nn.inputs import InputType

    conf = (NeuralNetConfiguration.builder().seed(1).updater("sgd").list()
            .layer(MoELayer(num_experts=2, capacity_factor=2.0))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(6))
            .build())
    net = MultiLayerNetwork(conf).init()
    assert net.params["layer_0"]["W_router"].shape == (6, 2)


def test_moe_validation():
    with pytest.raises(ValueError, match="n_in == n_out"):
        (NeuralNetConfiguration.builder().list()
         .layer(MoELayer(n_in=8, n_out=4))
         .layer(OutputLayer(n_in=4, n_out=2)).build())


def test_tensor_parallel_spec_attention_and_blocks():
    """Transformer stacks get real TP layouts: attention groups follow the
    Megatron pattern (Wq/Wk/Wv column, Wo row) and nested ResidualBlock
    sublayers are sharded, not silently replicated."""
    from jax.sharding import PartitionSpec as P

    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    net = transformer_char_lm(vocab_size=8, d_model=8, n_heads=2, layers=2)
    spec = tensor_parallel_spec(net.params, tp=2)
    for blk in (1, 3):                      # both attention blocks
        attn = spec[f"layer_{blk}"]["sub1"]
        assert attn["Wq"] == P(None, "model")
        assert attn["Wk"] == P(None, "model")
        assert attn["Wv"] == P(None, "model")
        assert attn["Wo"] == P("model", None)
    for blk in (2, 4):                      # both FFN blocks: col THEN row
        ff = spec[f"layer_{blk}"]
        ws = [v["W"] for k, v in sorted(ff.items()) if "W" in v]
        assert ws == [P(None, "model"), P("model", None)], (blk, ws)


def test_tensor_parallel_transformer_matches_serial():
    """TP-trained transformer (blocks + attention sharded over model=2) ==
    single-device training — the Megatron layout must not change the math."""
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    rs = np.random.RandomState(0)
    ids = rs.randint(0, 11, (8, 8))
    x = ids.astype(np.float32)
    y = np.eye(11, dtype=np.float32)[np.roll(ids, -1, 1)]

    serial = transformer_char_lm(vocab_size=11, d_model=8, n_heads=2,
                                 layers=1, seed=9, updater="sgd", lr=0.1)
    serial.fit(ListDataSetIterator(DataSet(x, y), 8), epochs=2)

    tp_net = transformer_char_lm(vocab_size=11, d_model=8, n_heads=2,
                                 layers=1, seed=9, updater="sgd", lr=0.1)
    mesh = backend.default_mesh(data=4, model=2)
    DistributedNetwork(tp_net, TensorParallelTrainingMaster(mesh=mesh)).fit(
        ListDataSetIterator(DataSet(x, y), 8), epochs=2)
    np.testing.assert_allclose(tp_net.params_to_vector(),
                               serial.params_to_vector(), atol=2e-5)
