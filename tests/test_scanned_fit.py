"""Scanned K-step fit (dispatch amortization for small models): exact
equivalence with the per-batch path is the oracle — same batches, same RNG
stream, same updates, so parameters must match bitwise-close."""

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import lenet
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer


def _mlp(seed=3, dropout=0.0):
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater("adam", learning_rate=1e-2).list()
         .layer(DenseLayer(n_in=12, n_out=16, activation="tanh",
                           dropout=dropout))
         .layer(OutputLayer(n_in=16, n_out=4)))
    return MultiLayerNetwork(b.build()).init()


def _batches(n, batch=8, seed=0):
    rs = np.random.RandomState(seed)
    return [(rs.rand(batch, 12).astype(np.float32),
             np.eye(4, dtype=np.float32)[rs.randint(0, 4, batch)])
            for _ in range(n)]


@pytest.mark.parametrize("n_batches,k", [(8, 4), (7, 4), (3, 8)])
def test_scanned_matches_per_batch(n_batches, k):
    """Windows, short tails (7 % 4), and all-tail (3 < 8) all match the
    sequential path exactly."""
    data = _batches(n_batches)
    a = _mlp()
    for x, y in data:
        a.fit(x, y)
    b = _mlp()
    b.fit_scanned(data, scan_steps=k)
    assert b.iteration == a.iteration == n_batches
    for ln in a.params:
        for pn in a.params[ln]:
            np.testing.assert_allclose(
                np.asarray(a.params[ln][pn]), np.asarray(b.params[ln][pn]),
                rtol=1e-6, atol=1e-7, err_msg=f"{ln}/{pn}")


def test_scanned_dropout_same_rng_stream():
    """Dropout draws flow from the same KeyStream in the same order, so
    even stochastic training matches."""
    data = _batches(4, seed=1)
    a = _mlp(dropout=0.3)
    for x, y in data:
        a.fit(x, y)
    b = _mlp(dropout=0.3)
    b.fit_scanned(data, scan_steps=4)
    for ln in a.params:
        for pn in a.params[ln]:
            np.testing.assert_allclose(
                np.asarray(a.params[ln][pn]), np.asarray(b.params[ln][pn]),
                rtol=1e-6, atol=1e-7, err_msg=f"{ln}/{pn}")


def test_scanned_shape_change_splits_window():
    data = _batches(4, batch=8) + _batches(4, batch=16, seed=2)
    net = _mlp()
    net.fit_scanned(data, scan_steps=4)
    assert net.iteration == 8
    assert np.isfinite(net.score_value)


def test_scanned_lenet_smoke():
    rs = np.random.RandomState(0)
    data = [(rs.rand(16, 784).astype(np.float32),
             np.eye(10, dtype=np.float32)[rs.randint(0, 10, 16)])
            for _ in range(4)]
    net = lenet()
    net.fit_scanned(data, scan_steps=4)
    assert net.iteration == 4
    assert np.isfinite(net.score_value)


def test_scanned_rejects_unsupported():
    net = _mlp()
    with pytest.raises(ValueError, match="scan_steps"):
        net.fit_scanned(_batches(2), scan_steps=0)


# ------------------------------------------------------ ComputationGraph
def _cg(seed=11):
    from deeplearning4j_tpu.models.graph import ComputationGraph
    from deeplearning4j_tpu.models.vertices import MergeVertex

    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater("adam", learning_rate=1e-2).graph()
            .add_inputs("in")
            .add_layer("d0", DenseLayer(n_in=12, n_out=8,
                                        activation="tanh"), "in")
            .add_layer("d1", DenseLayer(n_in=12, n_out=8,
                                        activation="relu"), "in")
            .add_vertex("m", MergeVertex(), "d0", "d1")
            .add_layer("out", OutputLayer(n_in=16, n_out=4, loss="mcxent",
                                          activation="softmax"), "m")
            .set_outputs("out").build())
    return ComputationGraph(conf).init()


@pytest.mark.parametrize("n_batches,k", [(8, 4), (7, 4)])
def test_cg_scanned_matches_per_batch(n_batches, k):
    """Round 5: the K-step scan covers ComputationGraph too — same oracle
    (bitwise-close params vs the per-batch path over the same batches)."""
    data = _batches(n_batches, seed=4)
    a = _cg()
    for x, y in data:
        a.fit(x, y)
    b = _cg()
    b.fit_scanned(data, scan_steps=k)
    assert b.iteration == a.iteration == n_batches
    for ln in a.params:
        for pn in a.params[ln]:
            np.testing.assert_allclose(
                np.asarray(a.params[ln][pn]), np.asarray(b.params[ln][pn]),
                rtol=1e-6, atol=1e-7, err_msg=f"{ln}/{pn}")


def test_cg_scanned_multidataset_and_guards():
    from deeplearning4j_tpu.datasets.multidataset import MultiDataSet

    data = _batches(4, seed=5)
    mds = [MultiDataSet([x], [y]) for x, y in data]
    a = _cg(seed=12)
    for x, y in data:
        a.fit(x, y)
    b = _cg(seed=12)
    b.fit_scanned(mds, scan_steps=4)
    for ln in a.params:
        for pn in a.params[ln]:
            np.testing.assert_allclose(
                np.asarray(a.params[ln][pn]), np.asarray(b.params[ln][pn]),
                rtol=1e-6, atol=1e-7, err_msg=f"{ln}/{pn}")
    with pytest.raises(ValueError, match="scan_steps"):
        b.fit_scanned(mds, scan_steps=0)
