"""Helper (Pallas) parity tests — the CuDNNGradientChecks pattern.

Reference: ``deeplearning4j-cuda/src/test/.../CuDNNGradientChecks.java:66,
114-122`` — FIRST assert the accelerated helper is actually the one loaded
(so the fast path is really exercised), THEN numerically gradient-check
through it and compare against the plain path.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import helpers
from deeplearning4j_tpu.helpers import pallas_ops
from deeplearning4j_tpu.nn.layers.normalization import (
    BatchNormalization,
    LocalResponseNormalization,
)


@pytest.fixture(autouse=True)
def _helpers_on():
    helpers.enable_helpers(True)
    yield
    helpers.enable_helpers(True)


def test_helper_discovery_loads_pallas_impls():
    """≙ CuDNNGradientChecks: assertTrue(helper instanceof Cudnn...)."""
    h = helpers.get_helper("lrn")
    assert h is not None and type(h).__name__ == "PallasLRNHelper"
    h2 = helpers.get_helper("batch_norm")
    assert h2 is not None and type(h2).__name__ == "PallasBatchNormHelper"


def test_helper_disable_falls_back():
    helpers.enable_helpers(False)
    assert helpers.get_helper("lrn") is None


def reference_lrn(x, k, n, alpha, beta):
    """Plain-path LRN (the layer's reduce_window fallback), rank-4 NHWC."""
    half = n // 2
    ws = jax.lax.reduce_window(
        x * x, 0.0, jax.lax.add,
        window_dimensions=(1, 1, 1, n), window_strides=(1, 1, 1, 1),
        padding=((0, 0), (0, 0), (0, 0), (half, half)))
    return x / jnp.power(k + alpha * ws, beta)


def test_lrn_kernel_matches_reference_forward():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(2, 3, 5, 7).astype(np.float32))
    h = helpers.get_helper("lrn")
    got = h.apply(x, 2.0, 5, 1e-4, 0.75)
    want = reference_lrn(x, 2.0, 5, 1e-4, 0.75)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_lrn_kernel_gradient_matches_reference():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(2, 3, 4, 6).astype(np.float32))
    h = helpers.get_helper("lrn")

    def f_helper(x):
        return (h.apply(x, 2.0, 5, 1e-2, 0.75) ** 2).sum()

    def f_plain(x):
        return (reference_lrn(x, 2.0, 5, 1e-2, 0.75) ** 2).sum()

    g_helper = jax.grad(f_helper)(x)
    g_plain = jax.grad(f_plain)(x)
    np.testing.assert_allclose(np.asarray(g_helper), np.asarray(g_plain),
                               rtol=1e-4, atol=1e-5)


def test_lrn_numerical_gradient_check():
    """Central-difference check straight through the Pallas custom VJP
    (the reference's GradientCheckUtil contract)."""
    rs = np.random.RandomState(2)
    x = rs.randn(3, 9).astype(np.float64)
    k, n, alpha, beta = 2.0, 3, 0.1, 0.75

    def f(v):
        return float((pallas_ops.lrn(jnp.asarray(v), k, n, alpha, beta) ** 2).sum())

    g = np.asarray(jax.grad(
        lambda v: (pallas_ops.lrn(v, k, n, alpha, beta) ** 2).sum()
    )(jnp.asarray(x)))
    eps = 1e-5
    for idx in [(0, 0), (1, 4), (2, 8), (0, 5)]:
        xp = x.copy(); xp[idx] += eps
        xm = x.copy(); xm[idx] -= eps
        num = (f(xp) - f(xm)) / (2 * eps)
        assert abs(num - g[idx]) / max(abs(num), 1e-8) < 1e-3, \
            f"grad mismatch at {idx}: {num} vs {g[idx]}"


def test_bn_inference_fused_matches_plain():
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(4, 5, 5, 8).astype(np.float32))
    mean = jnp.asarray(rs.randn(8).astype(np.float32))
    var = jnp.asarray(rs.rand(8).astype(np.float32) + 0.5)
    gamma = jnp.asarray(rs.randn(8).astype(np.float32))
    beta = jnp.asarray(rs.randn(8).astype(np.float32))
    h = helpers.get_helper("batch_norm")
    got = h.apply_inference(x, mean, var, gamma, beta, 1e-5)
    want = gamma * (x - mean) * jax.lax.rsqrt(var + 1e-5) + beta
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_bn_layer_inference_uses_helper_and_matches_fallback():
    """Same layer, helper on vs off → identical outputs (the
    accelerated-vs-interpreted parity triangle leg)."""
    rs = np.random.RandomState(4)
    layer = BatchNormalization(n_out=6)
    key = jax.random.PRNGKey(0)
    params = layer.init(key)
    state = {"mean": jnp.asarray(rs.randn(6).astype(np.float32)),
             "var": jnp.asarray(rs.rand(6).astype(np.float32) + 0.5)}
    x = jnp.asarray(rs.randn(10, 6).astype(np.float32))
    helpers.enable_helpers(True)
    y_fast, _ = layer.apply(params, state, x, train=False)
    helpers.enable_helpers(False)
    y_plain, _ = layer.apply(params, state, x, train=False)
    np.testing.assert_allclose(np.asarray(y_fast), np.asarray(y_plain),
                               rtol=1e-5, atol=1e-6)


def test_bn_training_fused_matches_plain():
    """Fused training-mode kernel (≙ cudnnBatchNormalizationForwardTraining):
    forward moments + output parity vs the stock jnp path."""
    from deeplearning4j_tpu.helpers import pallas_ops

    rs = np.random.RandomState(5)
    x = jnp.asarray(rs.randn(12, 7).astype(np.float32))
    gamma = jnp.asarray(rs.randn(7).astype(np.float32))
    beta = jnp.asarray(rs.randn(7).astype(np.float32))
    y, mean, var = pallas_ops.bn_training(x, gamma, beta, 1e-5)
    m = x.mean(0)
    v = x.var(0)
    want = gamma * (x - m) * jax.lax.rsqrt(v + 1e-5) + beta
    np.testing.assert_allclose(np.asarray(mean), np.asarray(m), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(var), np.asarray(v), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want), rtol=1e-4, atol=1e-5)


def test_bn_training_fused_gradient_parity():
    """Fused backward VJP vs jax.grad of the stock formula, all of
    (dx, dgamma, dbeta)."""
    from deeplearning4j_tpu.helpers import pallas_ops

    rs = np.random.RandomState(6)
    x = jnp.asarray(rs.randn(10, 5).astype(np.float32))
    gamma = jnp.asarray(rs.randn(5).astype(np.float32))
    beta = jnp.asarray(rs.randn(5).astype(np.float32))
    w = jnp.asarray(rs.randn(10, 5).astype(np.float32))  # loss weights

    def fused(x, g, b):
        y, _, _ = pallas_ops.bn_training(x, g, b, 1e-5)
        return jnp.sum(y * w)

    def plain(x, g, b):
        m, v = x.mean(0), x.var(0)
        return jnp.sum((g * (x - m) * jax.lax.rsqrt(v + 1e-5) + b) * w)

    got = jax.grad(fused, argnums=(0, 1, 2))(x, gamma, beta)
    want = jax.grad(plain, argnums=(0, 1, 2))(x, gamma, beta)
    for a, b_, name in zip(got, want, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-3, atol=1e-5, err_msg=name)


def test_bn_layer_training_helper_vs_fallback_parity():
    """BN layer train-mode forward + grads: helper on == helper off."""
    rs = np.random.RandomState(7)
    layer = BatchNormalization(n_out=6, name="bn")
    params = layer.init(jax.random.PRNGKey(1))
    state = layer.init_state()
    x = jnp.asarray(rs.randn(16, 6).astype(np.float32))

    def loss(params, on):
        helpers.enable_helpers(on)
        try:
            y, ns = layer.apply(params, state, x, train=True)
            return jnp.sum(y ** 2), ns
        finally:
            helpers.enable_helpers(True)

    (l_fast, ns_fast), g_fast = jax.value_and_grad(loss, has_aux=True)(params, True)
    (l_plain, ns_plain), g_plain = jax.value_and_grad(loss, has_aux=True)(params, False)
    np.testing.assert_allclose(float(l_fast), float(l_plain), rtol=1e-4)
    for k in g_fast:
        np.testing.assert_allclose(np.asarray(g_fast[k]), np.asarray(g_plain[k]),
                                   rtol=1e-3, atol=1e-5, err_msg=k)
    for k in ns_fast:
        np.testing.assert_allclose(np.asarray(ns_fast[k]), np.asarray(ns_plain[k]),
                                   rtol=1e-4, atol=1e-6, err_msg=k)


def test_lrn_layer_helper_vs_fallback_parity():
    rs = np.random.RandomState(5)
    layer = LocalResponseNormalization()
    x = jnp.asarray(rs.randn(2, 4, 4, 5).astype(np.float32))
    helpers.enable_helpers(True)
    y_fast, _ = layer.apply({}, {}, x)
    helpers.enable_helpers(False)
    y_plain, _ = layer.apply({}, {}, x)
    np.testing.assert_allclose(np.asarray(y_fast), np.asarray(y_plain),
                               rtol=1e-5, atol=1e-6)


def test_lrn_under_jit_and_odd_shapes():
    """Padding wrappers must survive jit and non-aligned channel counts."""
    rs = np.random.RandomState(6)
    h = helpers.get_helper("lrn")
    for shape in [(1, 1, 1, 3), (2, 2, 2, 130), (5, 257)]:
        x = jnp.asarray(rs.randn(*shape).astype(np.float32))
        if x.ndim == 2:
            got = jax.jit(lambda v: pallas_ops.lrn(v, 2.0, 5, 1e-4, 0.75))(x)
            ws = jax.lax.reduce_window(
                x * x, 0.0, jax.lax.add, (1, 5), (1, 1),
                ((0, 0), (2, 2)))
            want = x / jnp.power(2.0 + 1e-4 * ws, 0.75)
        else:
            got = jax.jit(lambda v: h.apply(v, 2.0, 5, 1e-4, 0.75))(x)
            want = reference_lrn(x, 2.0, 5, 1e-4, 0.75)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
