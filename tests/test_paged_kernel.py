"""Fused-kernel tests (ISSUE 19): the paged decode-attention kernel
(`helpers/paged_attention.py`) and the fused dropout/residual/norm train
epilogue (`helpers/fused_epilogue.py`).

The decode kernel's contract: computing per-row causal attention straight
off the flattened page pool + int32 block tables must match the legacy
gather+softmax oracle (``gather_pages`` + ``paged_attention``) on every
impl (lax fallback, interpreted Pallas) and at every integration level —
raw function, layer-level streaming across a page boundary, and the full
continuous-batching engine under join/leave, prefix-cache-hit, and
hot-swap traffic.  The epilogue's contract: one fused VMEM pass equals
LayerNorm + inverted dropout in jnp, forward and backward, with a
bit-identical bernoulli mask for the same rng key.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deeplearning4j_tpu.helpers as helpers
from deeplearning4j_tpu.helpers.fused_epilogue import (
    FusedEpilogueHelper, dropout_residual_norm,
)
from deeplearning4j_tpu.helpers.paged_attention import (
    PagedAttentionHelper, paged_attention_mode, paged_decode_attention,
    set_paged_attention_mode,
)
from deeplearning4j_tpu.nn.layers.attention import (
    SelfAttentionLayer, gather_pages, paged_attention,
)

pytestmark = pytest.mark.kernels

VOCAB = 29


# --------------------------------------------------------------- scenarios
def _scenario(seed, *, pages, page_size, maxp, b, t, hq, hkv, d,
              dtype=jnp.float32, trash_row=True):
    """Engine-shaped inputs: page 0 is the trash page, unassigned
    block-table slots point at it, per-row positions are mixed, and
    (``trash_row``) row 0 is an all-padding fresh slot at position 0."""
    rng = np.random.default_rng(seed)
    pool_k = jnp.asarray(
        rng.standard_normal((pages * page_size, hkv, d)), dtype)
    pool_v = jnp.asarray(
        rng.standard_normal((pages * page_size, hkv, d)), dtype)
    q = jnp.asarray(rng.standard_normal((b, t, hq, d)), dtype)
    block = rng.integers(1, pages, size=(b, maxp))
    qlast = rng.integers(t - 1, maxp * page_size, size=(b,))
    if trash_row:
        qlast[0] = t - 1
        block[0] = 0
    for bi in range(b):
        live = int(qlast[bi]) // page_size + 1
        block[bi, live:] = 0
    qpos = (qlast - (t - 1))[:, None] + np.arange(t)[None]
    return (q, pool_k, pool_v, jnp.asarray(block, jnp.int32),
            jnp.asarray(qpos, jnp.int32))


def _oracle(q, pk, pv, block, qpos, page_size):
    gk = gather_pages(pk, block, page_size).astype(q.dtype)
    gv = gather_pages(pv, block, page_size).astype(q.dtype)
    return paged_attention(q, gk, gv, qpos)


CONFIGS = {
    "gqa": dict(pages=10, page_size=8, maxp=4, b=3, t=1, hq=4, hkv=2, d=32),
    "mha_chunk": dict(pages=12, page_size=8, maxp=4, b=2, t=2, hq=4,
                      hkv=4, d=64),
    "odd_head_dim": dict(pages=8, page_size=16, maxp=3, b=4, t=1, hq=8,
                         hkv=2, d=48),
}


# ----------------------------------------------------- raw kernel parity
@pytest.mark.parametrize("impl", ["lax", "pallas"])
@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_fused_matches_gather_oracle(impl, name):
    cfg = CONFIGS[name]
    q, pk, pv, block, qpos = _scenario(7, **cfg)
    ref = _oracle(q, pk, pv, block, qpos, cfg["page_size"])
    out = paged_decode_attention(q, pk, pv, block, qpos,
                                 page_size=cfg["page_size"], impl=impl,
                                 interpret=True)
    assert bool(jnp.isfinite(out).all())
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("impl", ["lax", "pallas"])
def test_all_padding_trash_row(impl):
    """A fresh slot (block table all trash-page-0, position 0) must stay
    finite and agree with the oracle — the engine pads every idle lane
    this way, so a NaN here poisons the whole running batch."""
    cfg = CONFIGS["gqa"]
    q, pk, pv, block, qpos = _scenario(11, **cfg, trash_row=True)
    assert int(block[0].max()) == 0 and int(qpos[0, 0]) == 0
    out = paged_decode_attention(q, pk, pv, block, qpos,
                                 page_size=cfg["page_size"], impl=impl,
                                 interpret=True)
    ref = _oracle(q, pk, pv, block, qpos, cfg["page_size"])
    assert bool(jnp.isfinite(out).all())
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_mode_toggle_and_helper_gating():
    assert paged_attention_mode() == "fused"       # the default
    helper = PagedAttentionHelper()
    q = jnp.zeros((1, 1, 4, 32))
    assert helper.supports(q, 4)
    try:
        set_paged_attention_mode("gather")
        assert paged_attention_mode() == "gather"
        assert not helper.supports(q, 4)
    finally:
        set_paged_attention_mode("fused")
    with pytest.raises(ValueError):
        set_paged_attention_mode("einsum")


def test_lax_fallback_zero_recompiles_across_fill_levels():
    """The fori_loop fallback bounds its page walk by a TRACED watermark
    (max position), so rows filling up over decode steps must not force
    retraces — the engine's zero-steady-state-compile contract depends
    on it."""
    cfg = CONFIGS["gqa"]
    ps = cfg["page_size"]
    fn = jax.jit(lambda *a: paged_decode_attention(
        *a, page_size=ps, impl="lax"))
    q, pk, pv, block, qpos = _scenario(13, **cfg)
    fn(q, pk, pv, block, qpos).block_until_ready()
    traces = 0
    for fill in (0, ps - 1, 2 * ps, 3 * ps + 1):
        qp = jnp.full_like(qpos, fill)
        with jax.log_compiles(False):
            before = fn._cache_size()
            fn(q, pk, pv, block, qp).block_until_ready()
            traces += fn._cache_size() - before
    assert traces == 0


# ------------------------------------------------- layer-level streaming
def test_row_crosses_page_boundary_mid_decode():
    """Token-by-token streaming through ``apply_with_carry``: the row's
    position walks across page boundaries (ps-1 -> ps allocates the next
    page's lane); every step's fused output must match the gather
    oracle's, including the boundary steps."""
    ps, maxp, num_pages = 4, 3, 7
    layer = SelfAttentionLayer(n_in=32, n_out=32, n_heads=4, causal=True,
                               n_kv_heads=2)
    params = layer.init(jax.random.PRNGKey(0))
    steps = 2 * ps + 2                             # crosses two boundaries
    xs = jax.random.normal(jax.random.PRNGKey(1), (steps, 1, 1, 32))
    block = jnp.asarray([[1, 4, 2]], jnp.int32)    # page ids, row 0

    def run():
        carry = dict(layer.init_paged_cache(num_pages, ps),
                     block=block, pos=jnp.zeros((1,), jnp.int32))
        outs = []
        for i in range(steps):
            y, _, nc = layer.apply_with_carry(params, {}, xs[i], carry)
            outs.append(y)
            carry = dict(nc, block=block)
        return outs

    fused = run()
    set_paged_attention_mode("gather")
    try:
        oracle = run()
    finally:
        set_paged_attention_mode("fused")
    for i, (a, b) in enumerate(zip(fused, oracle)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6,
            err_msg=f"step {i} (position {i}, page {i // ps})")


# ------------------------------------------------- engine-level oracles
def _small_lm(seed=12345):
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    return transformer_char_lm(vocab_size=VOCAB, d_model=32, n_heads=4,
                               layers=2, max_cache=128, seed=seed)


def _engine(lm, **kw):
    from deeplearning4j_tpu.generation import GenerationEngine

    return GenerationEngine(lm, slots=4, page_size=4, max_context=32,
                            max_queue=64, deadline_s=60.0, **kw).start()


def _in_mode(mode, fn):
    set_paged_attention_mode(mode)
    try:
        return fn()
    finally:
        set_paged_attention_mode("fused")


def test_engine_join_leave_parity_fused_vs_gather(rng):
    """The PR-13 scheduler oracle, run cross-mode: mixed join/leave
    traffic on the fused default must produce the same greedy tokens as
    the gather-oracle engine decoding the same requests sequentially."""
    import time

    lm = _small_lm()
    prompts = [rng.randint(0, VOCAB, rng.randint(1, 12)).tolist()
               for _ in range(8)]
    lens = [int(rng.randint(2, 10)) for _ in prompts]

    def gather_sequential():
        eng = _engine(lm)
        try:
            return [eng.generate(p, n).tolist()
                    for p, n in zip(prompts, lens)]
        finally:
            eng.stop()

    ref = _in_mode("gather", gather_sequential)

    eng = _engine(lm)            # fused default, concurrent + staggered
    try:
        handles = []
        for i, (p, n) in enumerate(zip(prompts, lens)):
            handles.append(eng.submit(p, n))
            if i % 3 == 0:
                time.sleep(0.002)
        mixed = [h.result(timeout=60) for h in handles]
    finally:
        eng.stop()
    assert mixed == ref


def test_engine_prefix_cache_hit_parity(rng):
    """A persistent prefix-cache hit restores cached KV pages the fused
    kernel then attends over — the suffix decoded off restored pages
    must match the gather oracle's."""
    lm = _small_lm()
    prefix = rng.randint(0, VOCAB, 12).tolist()
    tails = [rng.randint(0, VOCAB, 3).tolist() for _ in range(2)]

    def run():
        eng = _engine(lm, prefix_cache=True)
        try:
            out, shared = [], []
            for tail in tails:
                h = eng.submit(prefix + tail, 6)
                out.append(h.result(timeout=60))
                shared.append(h.shared_len)
            return out, shared
        finally:
            eng.stop()

    fused_out, fused_shared = run()
    gather_out, gather_shared = _in_mode("gather", run)
    assert fused_shared[1] > 0 and gather_shared[1] > 0   # hit path ran
    assert fused_out == gather_out


def test_engine_hot_swap_parity(rng):
    """The hot-swap drill cross-mode: greedy outputs before AND after a
    between-requests weight swap must agree between the fused default
    and the gather oracle."""
    prompt = rng.randint(0, VOCAB, 6).tolist()

    def run():
        eng = _engine(_small_lm())
        try:
            pre = eng.generate(prompt, 8).tolist()
            eng.deploy("default", _small_lm(seed=777))
            post = eng.generate(prompt, 8).tolist()
            return pre, post
        finally:
            eng.stop()

    fused = run()
    oracle = _in_mode("gather", run)
    assert fused == oracle
    assert fused[0] != fused[1]       # the swap actually changed weights


# ------------------------------------------------------- fused epilogue
def _np_ref(h, res, gamma, beta, eps, mask, keep):
    x = np.asarray(h, np.float64)
    if res is not None:
        x = x + np.asarray(res, np.float64)
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    y = ((x - mu) / np.sqrt(var + eps) * np.asarray(gamma, np.float64)
         + np.asarray(beta, np.float64))
    if mask is not None:
        y = np.where(np.asarray(mask), y / keep, 0.0)
    return y


@pytest.mark.parametrize("variant",
                         ["residual_dropout", "prologue", "norm_only"])
def test_epilogue_forward_parity(variant):
    rng = np.random.default_rng(21)
    m, c = 17, 40                                   # pad-heavy odd shape
    h = jnp.asarray(rng.standard_normal((m, c)), jnp.float32)
    gamma = jnp.asarray(rng.standard_normal(c), jnp.float32)
    beta = jnp.asarray(rng.standard_normal(c), jnp.float32)
    res = (jnp.asarray(rng.standard_normal((m, c)), jnp.float32)
           if variant == "residual_dropout" else None)
    mask, keep, rate = None, 1.0, 0.0
    if variant != "norm_only":
        keep, rate = 0.75, 0.25
        mask = jnp.asarray(rng.random((m, c)) < keep)
    out = dropout_residual_norm(h, res, gamma, beta, eps=1e-5, rate=rate,
                                mask=mask)
    ref = _np_ref(h, res, gamma, beta, 1e-5,
                  np.asarray(mask) if mask is not None else None, keep)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_epilogue_grads_match_reference():
    rng = np.random.default_rng(22)
    m, c = 12, 96
    h = jnp.asarray(rng.standard_normal((m, c)), jnp.float32)
    res = jnp.asarray(rng.standard_normal((m, c)), jnp.float32)
    gamma = jnp.asarray(rng.standard_normal(c), jnp.float32)
    beta = jnp.asarray(rng.standard_normal(c), jnp.float32)
    mask = jnp.asarray(rng.random((m, c)) < 0.8)

    def fused(h, res, gamma, beta):
        return jnp.sum(jnp.sin(dropout_residual_norm(
            h, res, gamma, beta, eps=1e-5, rate=0.2, mask=mask)))

    def ref(h, res, gamma, beta):
        x = h + res
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        y = (x - mu) * jax.lax.rsqrt(var + 1e-5) * gamma + beta
        y = jnp.where(mask, y / 0.8, 0.0)
        return jnp.sum(jnp.sin(y))

    gf = jax.grad(fused, argnums=(0, 1, 2, 3))(h, res, gamma, beta)
    gr = jax.grad(ref, argnums=(0, 1, 2, 3))(h, res, gamma, beta)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_epilogue_mask_bit_identical_to_maybe_dropout():
    """Same rng key => the fused prologue's keep/drop pattern is the
    SAME bernoulli draw ``Layer.maybe_dropout`` makes — the fused and
    unfused train paths see identical masks, not just same-rate ones."""
    from deeplearning4j_tpu.nn.layers.dense import DenseLayer

    rng_key = jax.random.PRNGKey(99)
    x = jax.random.normal(jax.random.PRNGKey(5), (9, 64), jnp.float32)
    gamma, beta = jnp.ones((64,)), jnp.zeros((64,))
    out = dropout_residual_norm(x, None, gamma, beta, eps=1e-5, rate=0.4,
                                rng=rng_key, train=True)
    layer = DenseLayer(n_in=64, n_out=64, dropout=0.4)
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    ln = (x - mu) * jax.lax.rsqrt(var + 1e-5)
    ref = layer.maybe_dropout(ln, train=True, rng=rng_key)
    assert bool(jnp.array_equal(out == 0.0, ref == 0.0))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_epilogue_supports_gating():
    h = FusedEpilogueHelper()                       # allow_interpret off
    x = jnp.zeros((8, 64), jnp.float32)
    assert not h.supports(x)                        # CPU: stock jnp path
    h = FusedEpilogueHelper(allow_interpret=True)
    assert h.supports(x)
    assert not h.supports(jnp.zeros((8, 64), jnp.float64))
    assert not h.supports(jnp.zeros((9000, 1000), jnp.float32))


def test_residual_block_fused_parity_and_remat_grads():
    """ResidualBlock routes its leading LayerNorm + the next sublayer's
    input dropout through the fused prologue when the helper qualifies;
    fused and stock paths must agree forward (train + eval) and through
    remat gradients."""
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.composite import ResidualBlock
    from deeplearning4j_tpu.nn.layers.dense import DenseLayer
    from deeplearning4j_tpu.nn.layers.normalization import LayerNorm

    blk = ResidualBlock(layers=(
        LayerNorm(), DenseLayer(n_out=64, activation="relu", dropout=0.3),
        DenseLayer(n_out=64)), remat=True).setup(InputType.feed_forward(64))
    params = blk.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 64), jnp.float32)
    rng_key = jax.random.PRNGKey(2)

    def run(train):
        y, _ = blk.apply(params, {}, x, train=train,
                         rng=rng_key if train else None)
        return y

    def grads():
        def loss(p):
            y, _ = blk.apply(p, {}, x, train=True, rng=rng_key)
            return jnp.sum(y * y)
        return jax.grad(loss)(params)

    ref_train, ref_eval, ref_g = run(True), run(False), grads()
    saved = helpers._registry.get("epilogue")
    helpers._registry["epilogue"] = FusedEpilogueHelper(
        allow_interpret=True)
    try:
        fused_train, fused_eval, fused_g = run(True), run(False), grads()
    finally:
        if saved is None:
            helpers._registry.pop("epilogue", None)
        else:
            helpers._registry["epilogue"] = saved
    np.testing.assert_allclose(np.asarray(fused_train),
                               np.asarray(ref_train), rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(fused_eval),
                               np.asarray(ref_eval), rtol=2e-5, atol=2e-6)
    for a, b in zip(jax.tree_util.tree_leaves(fused_g),
                    jax.tree_util.tree_leaves(ref_g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


# --------------------------------------------------------- harness gates
def test_trust_registry_gate_green_on_committed_doc():
    from deeplearning4j_tpu.observability.kerneldiff import check_registry

    path = os.path.join(os.path.dirname(__file__), "..",
                        "kernel_trust.json")
    assert check_registry(path) == 0


def test_trust_registry_gate_flags_mismatch(tmp_path):
    import json

    doc = {"kernels": {"flash_attention": {}, "ghost_kernel": {}}}
    p = tmp_path / "trust.json"
    p.write_text(json.dumps(doc))
    from deeplearning4j_tpu.observability.kerneldiff import check_registry

    assert check_registry(str(p)) == 1
