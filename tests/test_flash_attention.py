"""Flash-attention Pallas kernel: parity against the XLA einsum path.

≙ the reference's accelerated-vs-builtin parity discipline
(``CuDNNGradientChecks.java:66,114-122``): the fused kernel must match the
stock path forward AND backward.  Here the kernels run ``interpret=True``
(CPU tier); ``tests/test_tpu.py`` re-runs parity compiled on a real chip.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.helpers import flash_attention as fa
from deeplearning4j_tpu.nn.layers.attention import dot_product_attention


def _rand(shape, seed=0, scale=0.3):
    return jnp.asarray(
        np.random.RandomState(seed).randn(*shape).astype(np.float32) * scale)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t,d", [(256, 64), (128, 128), (384, 32)])
def test_forward_parity(causal, t, d):
    q, k, v = (_rand((2, t, 2, d), s) for s in (0, 1, 2))
    ref = dot_product_attention(q, k, v, causal=causal)
    out = fa.flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradient_parity(causal):
    q, k, v = (_rand((2, 256, 2, 64), s) for s in (0, 1, 2))

    def loss(attn, q, k, v):
        return jnp.sum(attn(q, k, v) ** 2)

    gr = jax.grad(lambda *a: loss(
        lambda q, k, v: dot_product_attention(q, k, v, causal=causal), *a),
        argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(lambda *a: loss(
        lambda q, k, v: fa.flash_attention(q, k, v, causal=causal), *a),
        argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gf):
        scale = float(jnp.max(jnp.abs(a))) + 1e-9
        np.testing.assert_allclose(np.asarray(b) / scale, np.asarray(a) / scale,
                                   atol=2e-5, err_msg=f"d{name}")


@pytest.mark.parametrize("bq,bk", [(64, 128), (128, 64)])
def test_causal_parity_mixed_block_ratios(bq, bk):
    """bk > bq is the flagship regime (T=2048 -> bq512/bk1024) and the one
    the causal diagonal-clamp index maps must get right: several q blocks
    clamp to one kv block (bk > bq) or the k-major q-index jumps by >1
    (bq > bk).  Exercise both with explicit small blocks."""
    q, k, v = (_rand((2, 256, 2, 32), s) for s in (0, 1, 2))
    ref = dot_product_attention(q, k, v, causal=True)
    out = fa.flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    gr = jax.grad(lambda q, k, v: jnp.sum(
        dot_product_attention(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(lambda q, k, v: jnp.sum(
        fa.flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gf):
        scale = float(jnp.max(jnp.abs(a))) + 1e-9
        np.testing.assert_allclose(np.asarray(b) / scale, np.asarray(a) / scale,
                                   atol=2e-5, err_msg=f"d{name}")


def test_block_picking_and_unsupported():
    assert fa.pick_blocks(2048) == (512, 1024)
    assert fa.pick_blocks(1024) == (512, 512)   # bk capped at T/2
    assert fa.pick_blocks(512) == (512, 256)
    assert fa.pick_blocks(128) == (128, 128)    # T/2 < 128 -> bk = T
    assert fa.pick_blocks(384) == (128, 128)
    assert fa.pick_blocks(320) is None
    assert not fa.supports(100, 64)
    q = _rand((1, 100, 2, 64))
    with pytest.raises(ValueError, match="flash_attention"):
        fa.flash_attention(q, q, q)


@pytest.fixture
def interpret_helper():
    """Register the attention helper with interpret mode allowed so the
    layer's auto-routing exercises the fused path on the CPU tier (on
    non-TPU backends the helper declines by default — see
    FlashAttentionHelper.allow_interpret)."""
    from deeplearning4j_tpu import helpers

    helpers.register_helper("attention", fa.FlashAttentionHelper(
        allow_interpret=True))
    yield
    helpers._registry.pop("attention", None)


def test_layer_flash_matches_einsum_path(interpret_helper):
    """SelfAttentionLayer with flash on vs off produces the same output and
    gradients end-to-end (fused path swapped under the same params)."""
    import dataclasses

    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer

    layer = SelfAttentionLayer(n_in=32, n_out=32, n_heads=2, causal=True)
    params = layer.init(jax.random.PRNGKey(0))
    x = _rand((2, 128, 32), 3)
    y_flash, _ = layer.apply(params, {}, x)
    y_ref, _ = dataclasses.replace(layer, flash=False).apply(params, {}, x)
    np.testing.assert_allclose(np.asarray(y_flash), np.asarray(y_ref),
                               atol=3e-5)

    def loss(layer, p):
        return jnp.sum(layer.apply(p, {}, x)[0] ** 2)

    gf = jax.grad(lambda p: loss(layer, p))(params)
    gr = jax.grad(lambda p: loss(dataclasses.replace(layer, flash=False), p))(params)
    for key in gf:
        np.testing.assert_allclose(np.asarray(gf[key]), np.asarray(gr[key]),
                                   atol=3e-5, err_msg=key)


def test_layer_falls_back_on_mask_and_odd_t(interpret_helper):
    """A padding mask or a non-tileable T must route to the einsum path,
    not crash the fused one."""
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer

    layer = SelfAttentionLayer(n_in=16, n_out=16, n_heads=2, causal=True)
    params = layer.init(jax.random.PRNGKey(0))
    x = _rand((2, 100, 16), 1)          # T=100: no block tiling
    y, _ = layer.apply(params, {}, x)
    assert y.shape == (2, 100, 16)
    x2 = _rand((2, 128, 16), 2)
    m = jnp.ones((2, 128))              # mask present → fallback
    y2, _ = layer.apply(params, {}, x2, mask=m)
    assert y2.shape == (2, 128, 16)


def test_helper_seam_routing(monkeypatch):
    """The layer goes through helpers.get_helper("attention"): the global
    disable switch reverts it to the einsum path, and the helper declines
    interpret-mode execution on non-TPU backends by default."""
    from deeplearning4j_tpu import helpers
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer

    calls = []

    class Spy(fa.FlashAttentionHelper):
        def attend(self, q, k, v, **kw):
            calls.append(q.shape)
            return super().attend(q, k, v, **kw)

    helpers.register_helper("attention", Spy(allow_interpret=True))
    try:
        layer = SelfAttentionLayer(n_in=16, n_out=16, n_heads=2, causal=True)
        params = layer.init(jax.random.PRNGKey(0))
        x = _rand((1, 128, 16), 4)
        layer.apply(params, {}, x)
        assert len(calls) == 1, "helper not routed through the seam"

        helpers.enable_helpers(False)
        try:
            layer.apply(params, {}, x)
            assert len(calls) == 1, "disable switch did not bypass the helper"
        finally:
            helpers.enable_helpers(True)

        # default helper declines on CPU (no interpret-mode hot paths)
        assert not fa.FlashAttentionHelper().supports(128, 64)
    finally:
        helpers._registry.pop("attention", None)


def test_bf16_inputs():
    q, k, v = (_rand((2, 256, 2, 64), s).astype(jnp.bfloat16)
               for s in (0, 1, 2))
    ref = dot_product_attention(q, k, v, causal=True)
    out = fa.flash_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=2e-2)


def test_transformer_streaming_kv_cache_matches_full_forward():
    """rnn_time_step on a transformer stack: attention layers carry a KV
    cache (reference streaming analog: ``rnnTimeStep``/stateMap,
    ``MultiLayerNetwork.java:2195``), so feeding tokens one at a time
    reproduces the full causal forward exactly."""
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    net = transformer_char_lm(vocab_size=12, d_model=16, n_heads=2, layers=2)
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 12, (3, 7))
    full = np.asarray(net.output(jnp.asarray(ids)))        # [B, T, V]
    net.rnn_clear_previous_state()
    for t in range(7):
        step = np.asarray(net.rnn_time_step(jnp.asarray(ids[:, t])))
        np.testing.assert_allclose(step, full[:, t], rtol=2e-4, atol=1e-5,
                                   err_msg=f"t={t}")
    # multi-token chunks through the same cache
    net.rnn_clear_previous_state()
    chunk = np.asarray(net.rnn_time_step(jnp.asarray(ids[:, :4])))
    np.testing.assert_allclose(chunk, full[:, :4], rtol=2e-4, atol=1e-5)
    rest = np.asarray(net.rnn_time_step(jnp.asarray(ids[:, 4:])))
    np.testing.assert_allclose(rest, full[:, 4:], rtol=2e-4, atol=1e-5)


def test_streaming_cache_overflow_raises():
    """Overflowing max_cache must be a hard error, not silent key
    relocation (dynamic_update_slice clamps out-of-range writes)."""
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    net = transformer_char_lm(vocab_size=8, d_model=8, n_heads=2, layers=1)
    # shrink every attention cache via the overflow guard: max_cache is a
    # layer field, so build a tiny-cache variant through the public check
    ids = np.zeros((2, 3), np.int64)
    net.rnn_clear_previous_state()
    net.rnn_time_step(jnp.asarray(ids))        # pos=3, default max_cache
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer

    carry = {"k": jnp.zeros((2, 4, 2, 4)), "v": jnp.zeros((2, 4, 2, 4)),
             "pos": jnp.asarray(3, jnp.int32)}
    assert SelfAttentionLayer.cache_overflow(carry, 2)
    assert not SelfAttentionLayer.cache_overflow(carry, 1)
    with pytest.raises(ValueError, match="max_cache"):
        from deeplearning4j_tpu.models.common import check_cache_capacity

        check_cache_capacity({"blk": {"sub1": carry}}, 2)


def test_streaming_overflow_via_facade_host_counter():
    """The facade tracks the stream position HOST-side (_stream_pos) so the
    per-chunk capacity check never syncs the device scalar; overflow must
    still raise at exactly the right chunk, and clearing state resets it."""
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    net = transformer_char_lm(vocab_size=8, d_model=8, n_heads=2, layers=1,
                              max_cache=4)
    ids = np.zeros((2, 3), np.int64)
    net.rnn_clear_previous_state()
    net.rnn_time_step(jnp.asarray(ids))            # pos 0 -> 3
    assert net._stream_pos == 3
    with pytest.raises(ValueError, match="max_cache"):
        net.rnn_time_step(jnp.asarray(ids))        # 3 + 3 > 4
    net.rnn_clear_previous_state()
    assert net._stream_pos == 0
    net.rnn_time_step(jnp.asarray(ids))            # fits again after reset
    assert net._stream_pos == 3


def test_streaming_requires_causal_unmasked():
    """The cache path refuses non-causal layers and padding masks instead
    of silently computing different activations than output()."""
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer

    layer = SelfAttentionLayer(n_in=8, n_out=8, n_heads=2, causal=False)
    params = layer.init(jax.random.PRNGKey(0))
    carry = layer.init_cache(batch=2)
    with pytest.raises(ValueError, match="causal"):
        layer.apply_with_carry(params, {}, _rand((2, 1, 8)), carry)
    causal = SelfAttentionLayer(n_in=8, n_out=8, n_heads=2, causal=True)
    cp = causal.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="mask"):
        causal.apply_with_carry(cp, {}, _rand((2, 1, 8)),
                                causal.init_cache(batch=2),
                                mask=jnp.ones((2, 1)))


def test_streaming_rank_contract_column_ids():
    """Embedding-first nets with column semantics (collapse_column=True):
    a [B, 1] id column is ONE timestep and rnn_time_step returns [B, V],
    matching the pre-KV-cache streaming contract."""
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (
        EmbeddingLayer, GravesLSTM, RnnOutputLayer,
    )

    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(5)
         .updater("sgd", learning_rate=0.1).list()
         .layer(EmbeddingLayer(n_in=9, n_out=6))      # collapse_column=True
         .layer(GravesLSTM(n_in=6, n_out=6))
         .layer(RnnOutputLayer(n_in=6, n_out=9)).build())).init()
    out = net.rnn_time_step(jnp.asarray(np.array([[1], [4]])))   # [B, 1]
    assert out.shape == (2, 9), out.shape
    out1 = net.rnn_time_step(jnp.asarray(np.array([2, 5])))      # [B]
    assert out1.shape == (2, 9), out1.shape


def test_residual_block_lstm_sublayer_streams_state():
    """A recurrent sublayer inside ResidualBlock must carry hidden state
    across streamed chunks (not reset every call): step-by-step equals the
    full forward."""
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (
        GravesLSTM, LayerNorm, ResidualBlock, RnnOutputLayer,
    )

    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(6)
         .updater("sgd", learning_rate=0.1).list()
         .layer(ResidualBlock(layers=(
             LayerNorm(n_in=5), GravesLSTM(n_in=5, n_out=5))))
         .layer(RnnOutputLayer(n_in=5, n_out=3)).build())).init()
    rs = np.random.RandomState(7)
    x = rs.randn(2, 6, 5).astype(np.float32)
    full = np.asarray(net.output(jnp.asarray(x)))
    net.rnn_clear_previous_state()
    for t in range(6):
        step = np.asarray(net.rnn_time_step(jnp.asarray(x[:, t])))
        np.testing.assert_allclose(step, full[:, t], rtol=2e-4, atol=1e-5,
                                   err_msg=f"t={t}")


def test_sample_sequence_both_families():
    """utils.sampling primes on a prompt and feeds samples back through
    rnn_time_step for BOTH model families (reference char-modelling
    example loop)."""
    from deeplearning4j_tpu.models.zoo import (
        graves_lstm_char_lm, transformer_char_lm,
    )
    from deeplearning4j_tpu.utils.sampling import sample_sequence

    rs = np.random.RandomState(0)
    prompt = rs.randint(0, 11, (2, 3))

    lstm = graves_lstm_char_lm(vocab_size=11, hidden=12, layers=1)
    out = sample_sequence(lstm, prompt, steps=5, temperature=0.8,
                          rng=jax.random.PRNGKey(1))
    assert out.shape == (2, 5) and out.min() >= 0 and out.max() < 11

    tfm = transformer_char_lm(vocab_size=11, d_model=8, n_heads=2, layers=1)
    greedy = sample_sequence(tfm, prompt, steps=5, temperature=0.0)
    assert greedy.shape == (2, 5)
    # greedy sampling is deterministic
    again = sample_sequence(tfm, prompt, steps=5, temperature=0.0)
    np.testing.assert_array_equal(greedy, again)


def test_rope_invariants_and_gradcheck():
    """RoPE: rotation preserves pair norms, position 0 is identity, scores
    depend on RELATIVE position; and the rope'd attention layer passes the
    central-difference gradient check (f64)."""
    from deeplearning4j_tpu.nn.layers.attention import rope

    x = _rand((1, 8, 2, 16), 0)
    r = rope(x, jnp.arange(8))
    # norm preserved per rotated pair block
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(r), axis=-1), rtol=1e-5)
    # position 0 untouched
    np.testing.assert_allclose(np.asarray(r[:, 0]), np.asarray(x[:, 0]),
                               rtol=1e-6)
    # relative property: <rope(q,p1), rope(k,p2)> == <rope(q,p1+s), rope(k,p2+s)>
    q, k = _rand((1, 1, 1, 16), 1), _rand((1, 1, 1, 16), 2)
    def score(qp, kp):
        return float(jnp.sum(rope(q, jnp.array([qp])) * rope(k, jnp.array([kp]))))
    np.testing.assert_allclose(score(3, 5), score(10, 12), rtol=1e-5)

    from deeplearning4j_tpu.gradientcheck import check_gradients
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import RnnOutputLayer
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer

    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(8)
         .updater("sgd", learning_rate=0.05).list()
         .layer(SelfAttentionLayer(n_in=6, n_out=6, n_heads=2, causal=True,
                                   rope=True))
         .layer(RnnOutputLayer(n_in=6, n_out=3)).build())).init(
             dtype=jnp.float64)
    rs = np.random.RandomState(9)
    x = rs.randn(2, 5, 6)
    y = np.eye(3)[rs.randint(0, 3, (2, 5))]
    assert check_gradients(net, x, y, max_params_per_array=24)


def test_gqa_shapes_and_streaming_equivalence():
    """Grouped-query attention: KV projections and the streaming cache
    shrink to n_kv_heads, outputs stay [B, T, F], and streaming decode
    still matches the full forward exactly.  n_kv_heads == n_heads
    degenerates to standard MHA."""
    import dataclasses

    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer

    layer = SelfAttentionLayer(n_in=16, n_out=16, n_heads=4, n_kv_heads=2,
                               causal=True, rope=True)
    params = layer.init(jax.random.PRNGKey(0))
    assert params["Wk"].shape == (16, 8)        # 2 kv heads x d_head 4
    assert params["Wv"].shape == (16, 8)
    assert params["Wq"].shape == (16, 16)
    cache = layer.init_cache(batch=2)
    assert cache["k"].shape == (2, layer.max_cache, 2, 4)

    x = _rand((2, 6, 16), 1)
    full, _ = layer.apply(params, {}, x)
    carry = layer.init_cache(batch=2)
    for t in range(6):
        y, _, carry = layer.apply_with_carry(params, {}, x[:, t:t + 1], carry)
        np.testing.assert_allclose(np.asarray(y[:, 0]), np.asarray(full[:, t]),
                                   rtol=2e-4, atol=1e-5, err_msg=f"t={t}")

    # invalid grouping refuses at init
    bad = SelfAttentionLayer(n_in=16, n_out=16, n_heads=4, n_kv_heads=3)
    with pytest.raises(ValueError, match="n_kv_heads"):
        bad.init(jax.random.PRNGKey(0))

    # degenerate case: explicit n_kv_heads == n_heads matches default MHA
    mha = SelfAttentionLayer(n_in=16, n_out=16, n_heads=4, causal=True)
    gqa4 = dataclasses.replace(mha, n_kv_heads=4)
    p = mha.init(jax.random.PRNGKey(1))
    y1, _ = mha.apply(p, {}, x)
    y2, _ = gqa4.apply(p, {}, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=1e-6)


def test_gqa_gradcheck():
    """Central-difference gradient check through a GQA layer (f64)."""
    from deeplearning4j_tpu.gradientcheck import check_gradients
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import RnnOutputLayer
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer

    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(10)
         .updater("sgd", learning_rate=0.05).list()
         .layer(SelfAttentionLayer(n_in=8, n_out=8, n_heads=4, n_kv_heads=2,
                                   causal=True, rope=True))
         .layer(RnnOutputLayer(n_in=8, n_out=3)).build())).init(
             dtype=jnp.float64)
    rs = np.random.RandomState(11)
    x = rs.randn(2, 4, 8)
    y = np.eye(3)[rs.randint(0, 3, (2, 4))]
    assert check_gradients(net, x, y, max_params_per_array=24)


def test_gqa_zero_kv_heads_rejected():
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer

    with pytest.raises(ValueError, match="positive divisor"):
        SelfAttentionLayer(n_in=8, n_out=8, n_heads=4,
                           n_kv_heads=0).init(jax.random.PRNGKey(0))


def test_grouped_dot_product_matches_expanded():
    """The grouped contraction equals attention over explicitly repeated
    KV heads (with causal + padding mask engaged)."""
    q = _rand((2, 8, 4, 16), 0)
    k = _rand((2, 8, 2, 16), 1)
    v = _rand((2, 8, 2, 16), 2)
    m = jnp.asarray(np.array([[1] * 8, [1] * 5 + [0] * 3], np.float32))
    grouped = dot_product_attention(q, k, v, causal=True, mask=m)
    expanded = dot_product_attention(
        q, jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2),
        causal=True, mask=m)
    np.testing.assert_allclose(np.asarray(grouped), np.asarray(expanded),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("window", [32, 100, 500])
def test_sliding_window_parity(window):
    """Windowed flash == windowed einsum attention, fwd and grads, for
    windows smaller than, straddling, and larger than the block sizes."""
    q, k, v = (_rand((2, 256, 2, 32), s) for s in (0, 1, 2))
    ref = dot_product_attention(q, k, v, causal=True, window=window)
    out = fa.flash_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    gr = jax.grad(lambda q, k, v: jnp.sum(
        dot_product_attention(q, k, v, causal=True, window=window) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(lambda q, k, v: jnp.sum(
        fa.flash_attention(q, k, v, causal=True, window=window) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gf):
        scale = float(jnp.max(jnp.abs(a))) + 1e-9
        np.testing.assert_allclose(np.asarray(b) / scale, np.asarray(a) / scale,
                                   atol=2e-5, err_msg=f"d{name}")


def test_sliding_window_layer_and_streaming():
    """Windowed attention layer: streaming decode matches the full forward
    (the band is position-based, so the cache path inherits it), and the
    config round-trips."""
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer

    layer = SelfAttentionLayer(n_in=12, n_out=12, n_heads=2, causal=True,
                               window=3, rope=True)
    params = layer.init(jax.random.PRNGKey(0))
    x = _rand((2, 8, 12), 1)
    full, _ = layer.apply(params, {}, x)
    carry = layer.init_cache(batch=2)
    for t in range(8):
        y, _, carry = layer.apply_with_carry(params, {}, x[:, t:t + 1], carry)
        np.testing.assert_allclose(np.asarray(y[:, 0]), np.asarray(full[:, t]),
                                   rtol=2e-4, atol=1e-5, err_msg=f"t={t}")
    back = SelfAttentionLayer.from_dict(layer.to_dict())
    assert back.window == 3

    with pytest.raises(ValueError, match="window"):
        SelfAttentionLayer(n_in=12, n_out=12, n_heads=2, causal=False,
                           window=3).init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="window"):
        fa.flash_attention(_rand((1, 128, 2, 32)), _rand((1, 128, 2, 32)),
                           _rand((1, 128, 2, 32)), causal=False, window=4)


def test_sliding_window_ring_matches_exact():
    """Ring attention with a window == exact windowed attention (the band
    uses global positions, so shard offsets must line up)."""
    import functools

    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from deeplearning4j_tpu.backend.compat import shard_map

    from deeplearning4j_tpu.backend import device as backend
    from deeplearning4j_tpu.parallel.sequence_parallel import ring_attention

    rng = np.random.default_rng(3)
    q, k, v = (jnp.asarray(rng.standard_normal((1, 16, 2, 4)), jnp.float32)
               for _ in range(3))
    devs = np.array(jax.devices()[:4]).reshape(1, 1, 4)
    mesh = Mesh(devs, (backend.AXIS_DATA, backend.AXIS_MODEL, backend.AXIS_SEQ))
    spec = P(None, backend.AXIS_SEQ)
    got = shard_map(
        functools.partial(ring_attention, axis_name=backend.AXIS_SEQ,
                          causal=True, window=5),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)
    want = dot_product_attention(q, k, v, causal=True, window=5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_gqa_window_flash_and_ring_paths(interpret_helper):
    """GQA combined with window through every path: grouped einsum, the
    flash helper's _expand_kv branch (interpret), and the grouped ring
    fold — all equal to attention over explicitly repeated KV heads."""
    import dataclasses
    import functools

    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from deeplearning4j_tpu.backend.compat import shard_map

    from deeplearning4j_tpu.backend import device as backend
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer
    from deeplearning4j_tpu.parallel.sequence_parallel import ring_attention

    # layer level: flash helper engaged (interpret) vs flash off — the
    # expand branch must agree with the grouped einsum branch
    layer = SelfAttentionLayer(n_in=16, n_out=16, n_heads=4, n_kv_heads=2,
                               causal=True, window=40)
    params = layer.init(jax.random.PRNGKey(0))
    x = _rand((2, 128, 16), 3)
    y_flash, _ = layer.apply(params, {}, x)
    y_plain, _ = dataclasses.replace(layer, flash=False).apply(params, {}, x)
    np.testing.assert_allclose(np.asarray(y_flash), np.asarray(y_plain),
                               atol=3e-5)

    # ring fold: grouped + windowed vs exact grouped attention
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((1, 16, 4, 8)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 16, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 16, 2, 8)), jnp.float32)
    devs = np.array(jax.devices()[:4]).reshape(1, 1, 4)
    mesh = Mesh(devs, (backend.AXIS_DATA, backend.AXIS_MODEL, backend.AXIS_SEQ))
    spec = P(None, backend.AXIS_SEQ)
    got = shard_map(
        functools.partial(ring_attention, axis_name=backend.AXIS_SEQ,
                          causal=True, window=6),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)
    want = dot_product_attention(q, k, v, causal=True, window=6)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_window_without_causal_raises_everywhere():
    """The window-without-causal contract is a loud error on every
    entry point, not a silent no-op on some."""
    from deeplearning4j_tpu.parallel.sequence_parallel import ring_attention

    q = _rand((1, 128, 2, 16))
    with pytest.raises(ValueError, match="window"):
        dot_product_attention(q, q, q, causal=False, window=8)
    with pytest.raises(ValueError, match="window"):
        fa.flash_attention(q, q, q, causal=False, window=8)


def test_rolling_window_cache_unbounded_decode():
    """Windowed layers stream in O(window) memory forever: the ring buffer
    holds `window` slots, wraps many times, and step-by-step decode still
    matches the full windowed forward — including a chunked prime that
    crosses the wrap boundary and a chunk longer than the window."""
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer

    W = 4
    layer = SelfAttentionLayer(n_in=8, n_out=8, n_heads=2, causal=True,
                               window=W, rope=True)
    params = layer.init(jax.random.PRNGKey(0))
    carry = layer.init_cache(batch=2)
    assert carry["k"].shape[1] == W          # O(window), not max_cache
    T = 6 * W
    x = _rand((2, T, 8), 1)
    full, _ = layer.apply(params, {}, x)
    for t in range(T):                        # wraps the buffer 6 times
        y, _, carry = layer.apply_with_carry(params, {}, x[:, t:t + 1], carry)
        np.testing.assert_allclose(np.asarray(y[:, 0]), np.asarray(full[:, t]),
                                   rtol=2e-4, atol=1e-5, err_msg=f"t={t}")

    # chunked feeding: prime with W+3 (crosses a wrap), then 2-token chunks
    carry = layer.init_cache(batch=2)
    outs = []
    y, _, carry = layer.apply_with_carry(params, {}, x[:, :W + 3], carry)
    outs.append(y)
    for t0 in range(W + 3, T, 2):
        y, _, carry = layer.apply_with_carry(params, {}, x[:, t0:t0 + 2], carry)
        outs.append(y)
    got = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full),
                               rtol=2e-4, atol=1e-5)

    # a single chunk longer than the window (only the tail stays cached)
    carry = layer.init_cache(batch=2)
    y, _, carry = layer.apply_with_carry(params, {}, x[:, :3 * W], carry)
    np.testing.assert_allclose(np.asarray(y), np.asarray(full[:, :3 * W]),
                               rtol=2e-4, atol=1e-5)
    y, _, carry = layer.apply_with_carry(params, {}, x[:, 3 * W:3 * W + 1],
                                         carry)
    np.testing.assert_allclose(np.asarray(y[:, 0]),
                               np.asarray(full[:, 3 * W]),
                               rtol=2e-4, atol=1e-5)


def test_sampling_topk_topp_filters():
    """top-k keeps exactly k candidates; nucleus keeps the smallest prefix
    covering top_p mass (always >= 1 token); filtered sampling only ever
    draws kept ids."""
    from deeplearning4j_tpu.utils.sampling import _filter_logits

    logits = jnp.asarray(np.log(np.array([[0.5, 0.3, 0.15, 0.05]],
                                         np.float32)))
    k2 = np.asarray(_filter_logits(logits, 2, None))
    assert (k2[0, :2] > -1e29).all() and (k2[0, 2:] < -1e29).all()
    p6 = np.asarray(_filter_logits(logits, None, 0.6))
    # 0.5 alone < 0.6 -> keep {0.5, 0.3}
    assert (p6[0, :2] > -1e29).all() and (p6[0, 2:] < -1e29).all()
    p01 = np.asarray(_filter_logits(logits, None, 0.01))
    assert (p01[0, :1] > -1e29).all() and (p01[0, 1:] < -1e29).all()

    from deeplearning4j_tpu.models.zoo import transformer_char_lm
    from deeplearning4j_tpu.utils.sampling import sample_sequence

    net = transformer_char_lm(vocab_size=9, d_model=8, n_heads=2, layers=1)
    out = sample_sequence(net, np.array([[1, 2]]), steps=6, temperature=1.0,
                          top_k=3, top_p=0.9, rng=jax.random.PRNGKey(2))
    assert out.shape == (1, 6) and out.min() >= 0 and out.max() < 9


def test_sampling_filter_edge_cases():
    from deeplearning4j_tpu.utils.sampling import _filter_logits

    logits = jnp.asarray(np.log(np.array([[0.5, 0.3, 0.15, 0.05]],
                                         np.float32)))
    # top_k beyond vocab clamps (keeps everything)
    allk = np.asarray(_filter_logits(logits, 100, None))
    assert (allk > -1e29).all()
    with pytest.raises(ValueError, match="top_k"):
        _filter_logits(logits, 0, None)
    with pytest.raises(ValueError, match="top_p"):
        _filter_logits(logits, None, 0.0)
    with pytest.raises(ValueError, match="top_p"):
        _filter_logits(logits, None, 1.5)
    # top_p = 1.0 keeps everything
    p1 = np.asarray(_filter_logits(logits, None, 1.0))
    assert (p1 > -1e29).all()
