"""Bench regression sentinel: the rule engine (direction, tolerance,
missing-value handling), the CLI exit codes — nonzero on a synthetically
regressed bench_full.json, zero on the committed one — and the --self-test
wired into tier-1 so rule parsing can't rot."""

import copy
import importlib.util
import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.profiling

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "check_bench_regression.py")
COMMITTED = os.path.join(REPO, "bench_full.json")


def _load_module():
    spec = importlib.util.spec_from_file_location(
        "_reg_under_test",
        os.path.join(REPO, "deeplearning4j_tpu", "observability",
                     "regression.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


reg = _load_module()


def run_script(*args):
    return subprocess.run([sys.executable, SCRIPT, *args],
                          capture_output=True, text=True, timeout=60)


# ----------------------------------------------------------- rule engine

def test_direction_and_tolerance():
    base = {"all": [{"metric": "Tput (x)", "value": 100.0},
                    {"metric": "Lat (x)", "value": 10.0}]}
    worse = {"all": [{"metric": "Tput (x)", "value": 70.0},
                     {"metric": "Lat (x)", "value": 13.0}]}
    rules = [reg.Rule("Tput", tolerance=0.2),
             reg.Rule("Lat", direction=reg.LOWER, tolerance=0.2)]
    rep = reg.compare(base, worse, rules)
    assert [v.status for v in rep.verdicts] == ["regressed", "regressed"]
    assert rep.exit_code == 1
    within = {"all": [{"metric": "Tput (x)", "value": 85.0},
                      {"metric": "Lat (x)", "value": 11.0}]}
    assert reg.compare(base, within, rules).exit_code == 0


def test_missing_and_no_baseline():
    base = {"all": [{"metric": "Tput (x)", "value": 100.0}]}
    rep = reg.compare(base, {"all": []}, [reg.Rule("Tput")])
    assert rep.verdicts[0].status == "regressed"   # required by default
    rep = reg.compare(base, {"all": []},
                      [reg.Rule("Tput", required=False)])
    assert rep.verdicts[0].status == "missing" and rep.exit_code == 0
    rep = reg.compare({"all": []}, base, [reg.Rule("Tput")])
    assert rep.verdicts[0].status == "no_baseline" and rep.exit_code == 0


def test_dotted_field_and_rule_roundtrip():
    base = {"all": [{"metric": "D (x)", "value": 1.0,
                     "variants": {"v": {"tps": 50.0}}}]}
    fresh = copy.deepcopy(base)
    fresh["all"][0]["variants"]["v"]["tps"] = 10.0
    rule = reg.Rule("D", field="variants.v.tps", tolerance=0.3)
    assert reg.compare(base, fresh, [rule]).exit_code == 1
    assert reg.Rule.from_dict(rule.to_dict()).to_dict() == rule.to_dict()
    with pytest.raises(ValueError):
        reg.Rule("x", direction="sideways")
    with pytest.raises(ValueError):
        reg.Rule.from_dict({"metric": "x", "bogus": 1})


def test_default_rules_cover_committed_bench():
    """Every required default rule finds its value in the committed
    bench_full.json — a renamed metric would silently disarm the gate."""
    with open(COMMITTED) as f:
        doc = json.load(f)
    for rule in reg.DEFAULT_RULES:
        if rule.required:
            assert reg.extract(doc, rule) is not None, rule.key


# ------------------------------------------------------------ CLI contract

def test_script_self_test_is_green():
    out = run_script("--self-test")
    assert out.returncode == 0, out.stderr
    assert "self-test" in out.stdout


def test_script_zero_on_committed_baseline():
    out = run_script()
    assert out.returncode == 0, out.stdout + out.stderr
    assert "PASS" in out.stdout


def test_script_nonzero_on_synthetic_regression(tmp_path):
    """Acceptance: degrade the decode headline 60% in a copy of the
    committed bench_full.json -> exit 1, naming the regressed rule."""
    with open(COMMITTED) as f:
        doc = json.load(f)
    for entry in doc["all"]:
        if entry["metric"].startswith("Decode tokens/sec"):
            entry["value"] = entry["value"] * 0.4
    fresh = tmp_path / "bench_full.json"
    fresh.write_text(json.dumps(doc))
    out = run_script("--fresh", str(fresh))
    assert out.returncode == 1
    assert "REGRESSED" in out.stdout
    assert "Decode tokens/sec" in out.stdout
    # --json variant carries the structured report
    out = run_script("--fresh", str(fresh), "--json")
    assert out.returncode == 1
    report = json.loads(out.stdout)
    assert report["regressed"] >= 1


def test_script_custom_rules_and_bad_input(tmp_path):
    rules = tmp_path / "rules.json"
    rules.write_text(json.dumps(
        [{"metric": "Serving rows/sec", "tolerance": 0.4}]))
    out = run_script("--rules", str(rules))
    assert out.returncode == 0
    assert "1 checked rule" in out.stdout.replace("rule(s)", "rule")
    out = run_script("--fresh", str(tmp_path / "nope.json"))
    assert out.returncode == 2
