"""Real-IDX parse path, exercised hermetically (VERDICT r3 missing #1).

The reference downloads and parses real MNIST IDX binaries
(``deeplearning4j-core/.../base/MnistFetcher.java:35``, readers
``datasets/mnist/MnistManager.java``).  This image has no egress, so the
REAL parse branch (``is_synthetic=False``) is driven by writing valid IDX
files (``write_idx``, the format inverse) from the synthetic corpus and
round-tripping them through the fetcher — both plain and gzipped, exactly
the two forms the reference's fetcher produces.
"""

import gzip

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.mnist import (
    MnistDataFetcher, MnistDataSetIterator, _read_idx, _synthetic_mnist,
    write_idx,
)


def _write_corpus(root, n_train=256, n_test=64, suffix=""):
    imgs, labels = _synthetic_mnist(n_train, seed=123)
    timgs, tlabels = _synthetic_mnist(n_test, seed=124)
    u8 = lambda a: np.round(a * 255.0).astype(np.uint8)
    write_idx(root / ("train-images-idx3-ubyte" + suffix), u8(imgs))
    write_idx(root / ("train-labels-idx1-ubyte" + suffix),
              labels.astype(np.uint8))
    write_idx(root / ("t10k-images-idx3-ubyte" + suffix), u8(timgs))
    write_idx(root / ("t10k-labels-idx1-ubyte" + suffix),
              tlabels.astype(np.uint8))
    return u8(imgs), labels


@pytest.mark.parametrize("suffix", ["", ".gz"])
def test_idx_write_read_round_trip(tmp_path, suffix):
    imgs, labels = _write_corpus(tmp_path, suffix=suffix)
    back = _read_idx(tmp_path / ("train-images-idx3-ubyte" + suffix))
    assert back.dtype == np.uint8 and back.shape == (256, 28, 28)
    np.testing.assert_array_equal(back, imgs)
    if suffix == ".gz":  # actually gzipped, not just renamed
        raw = (tmp_path / ("train-labels-idx1-ubyte" + suffix)).read_bytes()
        assert raw[:2] == b"\x1f\x8b"
        assert gzip.decompress(raw)[:4] == b"\x00\x00\x08\x01"


def test_fetcher_real_branch(tmp_path):
    imgs, labels = _write_corpus(tmp_path)
    fetcher = MnistDataFetcher(train=True, data_dir=str(tmp_path),
                               allow_synthetic=False)
    assert fetcher.is_synthetic is False
    assert fetcher.features.shape == (256, 784)
    np.testing.assert_allclose(
        fetcher.features, imgs.reshape(256, 784).astype(np.float32) / 255.0)
    np.testing.assert_array_equal(np.argmax(fetcher.labels, 1), labels)


def test_fetcher_env_var_and_iterator(tmp_path, monkeypatch):
    _write_corpus(tmp_path)
    monkeypatch.setenv("DL4J_TPU_MNIST_DIR", str(tmp_path))
    it = MnistDataSetIterator(batch_size=32, num_examples=64, train=True)
    assert it.is_synthetic is False  # what bench.py keys "data": "real" on
    ds = next(iter(it))
    assert ds.features.shape == (32, 784)


def test_missing_files_still_raise_without_synthetic(tmp_path):
    with pytest.raises(FileNotFoundError, match="DL4J_TPU_MNIST_DIR"):
        MnistDataFetcher(train=True, data_dir=str(tmp_path / "nope"),
                         allow_synthetic=False)


def test_accuracy_parity_real_vs_synthetic_branch(tmp_path, monkeypatch):
    """End-to-end through the REAL parse branch: same corpus, same model,
    same training — accuracy must match the synthetic-branch e2e result
    (the data is identical up to uint8 quantization, so this isolates the
    parse path as the only variable)."""
    from deeplearning4j_tpu.evaluation import Evaluation
    from deeplearning4j_tpu.models.zoo import lenet

    # 1024 x 3 epochs is the synthetic-branch e2e recipe for the 0.85 bar
    # (tests/test_mnist_e2e.py); same recipe here isolates the parse path
    _write_corpus(tmp_path, n_train=1024, n_test=128)
    monkeypatch.setenv("DL4J_TPU_MNIST_DIR", str(tmp_path))
    train_iter = MnistDataSetIterator(batch_size=64, num_examples=1024,
                                      train=True)
    test_iter = MnistDataSetIterator(batch_size=64, num_examples=128,
                                     train=False)
    assert train_iter.is_synthetic is False
    net = lenet(updater="adam", lr=1e-3)
    net.fit(train_iter, epochs=3)
    ev = Evaluation(10)
    for ds in test_iter:
        ev.eval(ds.labels, np.asarray(net.output(ds.features)))
    assert ev.accuracy() > 0.85, ev.stats()
