"""Real-format parse branches for CIFAR-10 and LFW, exercised hermetically
(VERDICT r4 task 8 — the ``write_*`` inverse-format trick from
tests/test_mnist_idx.py, applied to the two remaining image datasets).

Reference formats: CIFAR binary batches (1 label byte + 3072 CHW RGB bytes
per record, ``CifarDataSetIterator.java``/``CifarLoader``) and the LFW
archive layout (one directory per person, images resized to a fixed side,
person index as label, ``LFWDataFetcher.java``).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.cifar import (
    CifarDataFetcher, CifarDataSetIterator, _synthetic_cifar,
    write_cifar_batch,
)
from deeplearning4j_tpu.datasets.lfw import (
    LFWDataFetcher, LFWDataSetIterator, _synthetic_faces, read_pgm,
    write_pgm, SIDE,
)


# ------------------------------------------------------------------- CIFAR
def _write_cifar_corpus(root, n_train=128, n_test=32):
    imgs, labels = _synthetic_cifar(n_train, seed=7)
    u8 = np.round(imgs * 255.0).astype(np.uint8)
    # spread across two train batch files like the real archive's five
    write_cifar_batch(root / "data_batch_1.bin", u8[: n_train // 2],
                      labels[: n_train // 2])
    write_cifar_batch(root / "data_batch_2.bin", u8[n_train // 2:],
                      labels[n_train // 2:])
    timgs, tlabels = _synthetic_cifar(n_test, seed=8)
    write_cifar_batch(root / "test_batch.bin",
                      np.round(timgs * 255.0).astype(np.uint8), tlabels)
    return u8, labels


def test_cifar_batch_write_read_round_trip(tmp_path):
    u8, labels = _write_cifar_corpus(tmp_path)
    fetcher = CifarDataFetcher(train=True, data_dir=str(tmp_path),
                               allow_synthetic=False)
    assert fetcher.is_synthetic is False
    assert fetcher.features.shape == (128, 3072)
    np.testing.assert_allclose(fetcher.features,
                               u8.astype(np.float32) / 255.0)
    np.testing.assert_array_equal(np.argmax(fetcher.labels, 1), labels)


def test_cifar_record_layout_is_the_reference_format(tmp_path):
    # 1 label byte then 3072 image bytes, back to back — byte-level check
    img = np.arange(3072, dtype=np.uint8).reshape(1, 3072)
    write_cifar_batch(tmp_path / "data_batch_1.bin", img, np.array([3]))
    raw = (tmp_path / "data_batch_1.bin").read_bytes()
    assert len(raw) == 3073
    assert raw[0] == 3
    assert np.array_equal(np.frombuffer(raw, np.uint8)[1:], img[0])


def test_cifar_iterator_real_branch_and_subdir_layout(tmp_path, monkeypatch):
    sub = tmp_path / "cifar-10-batches-bin"
    sub.mkdir()
    _write_cifar_corpus(sub)
    monkeypatch.setenv("DL4J_TPU_CIFAR_DIR", str(tmp_path))
    it = CifarDataSetIterator(batch_size=32, train=True)
    assert it.is_synthetic is False
    ds = next(iter(it))
    assert ds.features.shape == (32, 3072)


def test_cifar_test_split_real_branch(tmp_path):
    _write_cifar_corpus(tmp_path)
    fetcher = CifarDataFetcher(train=False, data_dir=str(tmp_path),
                               allow_synthetic=False)
    assert fetcher.is_synthetic is False
    assert len(fetcher.features) == 32


# --------------------------------------------------------------------- LFW
def _write_lfw_corpus(root, people=4, per_person=6):
    """The reference archive layout: root/<person>/<person>_NNNN.pgm, at a
    non-native size so the resize path runs too."""
    rs = np.random.RandomState(11)
    raw = {}
    for p in range(people):
        d = root / f"person_{p:02d}"
        d.mkdir(parents=True)
        imgs, _ = _synthetic_faces(per_person, 1, seed=100 + p)
        for i, img in enumerate(imgs.reshape(per_person, SIDE, SIDE)):
            big = np.kron(np.round(img * 255).astype(np.uint8),
                          np.ones((2, 2), np.uint8))  # 80x80 -> resize
            write_pgm(d / f"person_{p:02d}_{i:04d}.pgm", big)
            raw[(p, i)] = big
    return raw


def test_pgm_write_read_round_trip(tmp_path):
    img = np.arange(np.uint8(200), dtype=np.uint8).reshape(10, 20)
    write_pgm(tmp_path / "x.pgm", img)
    back = read_pgm(tmp_path / "x.pgm")
    np.testing.assert_array_equal(back, img)
    # header robustness: comments + multi-whitespace, like real tools emit
    (tmp_path / "c.pgm").write_bytes(
        b"P5\n# made by a scanner\n20  10\n255\n" + img.tobytes())
    np.testing.assert_array_equal(read_pgm(tmp_path / "c.pgm"), img)


def test_pgm_rejects_ascii_and_16bit(tmp_path):
    (tmp_path / "a.pgm").write_bytes(b"P2\n2 2\n255\n0 1 2 3\n")
    with pytest.raises(ValueError, match="P5"):
        read_pgm(tmp_path / "a.pgm")
    (tmp_path / "w.pgm").write_bytes(b"P5\n2 2\n65535\n" + bytes(8))
    with pytest.raises(ValueError, match="16-bit"):
        read_pgm(tmp_path / "w.pgm")


def test_lfw_person_dir_real_branch(tmp_path):
    _write_lfw_corpus(tmp_path, people=4, per_person=6)
    fetcher = LFWDataFetcher(data_dir=str(tmp_path), allow_synthetic=False)
    assert fetcher.is_synthetic is False
    assert fetcher.num_classes == 4
    assert fetcher.features.shape == (24, SIDE * SIDE)
    # labels follow sorted directory order, per the reference fetcher
    np.testing.assert_array_equal(np.argmax(fetcher.labels, 1),
                                  np.repeat(np.arange(4), 6))
    # 2x-upscaled PGMs resized back to SIDE: nearest-neighbour on an even
    # factor reproduces the original pixels exactly
    orig, _ = _synthetic_faces(6, 1, seed=100)
    np.testing.assert_allclose(
        fetcher.features[0],
        np.round(orig[0] * 255).astype(np.uint8).astype(np.float32) / 255.0)


def test_lfw_iterator_env_var(tmp_path, monkeypatch):
    _write_lfw_corpus(tmp_path, people=3, per_person=4)
    monkeypatch.setenv("DL4J_TPU_LFW_DIR", str(tmp_path))
    it = LFWDataSetIterator(batch_size=4)
    assert it.is_synthetic is False
    assert it.num_classes == 3
    ds = next(iter(it))
    assert ds.features.shape == (4, SIDE * SIDE)


def test_lfw_npy_branch_still_works(tmp_path):
    feats, labels = _synthetic_faces(12, 3, seed=5)
    np.save(tmp_path / "faces.npy", feats)
    np.save(tmp_path / "labels.npy", labels)
    fetcher = LFWDataFetcher(data_dir=str(tmp_path), allow_synthetic=False)
    assert fetcher.is_synthetic is False
    assert fetcher.num_classes == int(labels.max()) + 1
    np.testing.assert_allclose(fetcher.features, feats)
