"""AdamW (decoupled weight decay) + warmup-cosine schedule.

No reference analog (``nn/conf/Updater.java`` predates both); these are the
standard transformer-training pieces, built into the same updater/schedule
machinery as the reference-era policies.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, UpdaterConfig
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize import updaters as upd


def test_warmup_cosine_schedule_shape():
    cfg = UpdaterConfig(name="adam", learning_rate=1.0,
                        lr_policy="warmup_cosine", lr_policy_warmup_steps=10,
                        lr_policy_steps=110, lr_policy_min_fraction=0.1)
    lrs = [float(upd.current_lr(cfg, i)) for i in range(0, 121, 5)]
    # ramps linearly to base at warmup end
    assert abs(float(upd.current_lr(cfg, 5)) - 0.5) < 1e-6
    assert abs(float(upd.current_lr(cfg, 10)) - 1.0) < 1e-6
    # monotone decay after warmup, down to the floor
    after = lrs[2:]
    assert all(a >= b - 1e-9 for a, b in zip(after, after[1:]))
    assert abs(float(upd.current_lr(cfg, 110)) - 0.1) < 1e-6
    assert abs(float(upd.current_lr(cfg, 500)) - 0.1) < 1e-6  # clamped floor
    # midpoint of the cosine ~ halfway between base and floor
    mid = float(upd.current_lr(cfg, 60))
    assert abs(mid - 0.55) < 1e-6


def test_adamw_decoupled_decay_math():
    """One adamw step == one adam step + lr*wd*param pulled directly from
    the parameter (not through the adaptive denominator)."""
    params = {"l": {"W": jnp.asarray(np.ones((3, 3), np.float32) * 2.0)}}
    grads = {"l": {"W": jnp.asarray(np.full((3, 3), 0.5, np.float32))}}
    adam = UpdaterConfig(name="adam", learning_rate=0.1)
    adamw = UpdaterConfig(name="adamw", learning_rate=0.1, weight_decay=0.01)
    s1 = upd.init_state(adam, params)
    s2 = upd.init_state(adamw, params)
    u1, _ = upd.update(adam, grads, s1, 0, params=params)
    u2, _ = upd.update(adamw, grads, s2, 0, params=params)
    diff = np.asarray(u2["l"]["W"] - u1["l"]["W"])
    np.testing.assert_allclose(diff, 0.1 * 0.01 * 2.0, rtol=1e-5)


def test_adamw_requires_params():
    cfg = UpdaterConfig(name="adamw", weight_decay=0.01)
    with pytest.raises(ValueError, match="adamw"):
        upd.update(cfg, {"l": {"W": jnp.ones((2, 2))}},
                   upd.init_state(cfg, {"l": {"W": jnp.ones((2, 2))}}), 0)


def test_adamw_warmup_cosine_trains_via_facade():
    """Builder plumbing end-to-end: .updater('adamw', ...) with the
    warmup_cosine policy trains, decays weights, and round-trips config."""
    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater("adamw", learning_rate=0.01, weight_decay=0.1)
            .lr_policy("warmup_cosine", warmup_steps=5, steps=50,
                       min_fraction=0.1)
            .list()
            .layer(DenseLayer(n_in=6, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2))
            .build())
    net = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(0)
    x = rs.rand(16, 6).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 16)]
    import dataclasses

    # same run without decay: the decayed weights must end up measurably
    # smaller, proving weight_decay survives the builder->fit plumbing
    conf_nodecay = dataclasses.replace(
        conf, updater=dataclasses.replace(conf.updater, weight_decay=0.0))
    net_nd = MultiLayerNetwork(conf_nodecay).init()
    for _ in range(20):
        net.fit(x, y)
        net_nd.fit(x, y)
    assert np.isfinite(net.score_value)
    w_decay = float(jnp.abs(net.params["layer_0"]["W"]).mean())
    w_plain = float(jnp.abs(net_nd.params["layer_0"]["W"]).mean())
    assert w_decay < w_plain, (w_decay, w_plain)
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

    back = MultiLayerConfiguration.from_json(conf.to_json())
    assert back.updater.name == "adamw"
    assert back.updater.weight_decay == 0.1
    assert back.updater.lr_policy == "warmup_cosine"
