"""Distributed embedding training (Spark Word2Vec analog).

Oracle, per the reference test strategy (SURVEY.md §4): distributed
training must be equivalent to single-machine math — here a 1-device mesh
must reproduce the serial engine bitwise, and the full 8-device mesh must
learn the same corpus structure."""

from collections import Counter

import jax
import numpy as np

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.nlp.distributed import (
    DistributedWord2Vec, parallel_vocab_count,
)
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.word2vec import Word2Vec

from tests.test_nlp import check_cluster_structure, synthetic_corpus


def builder(cls, sentences, **kw):
    b = (cls.Builder()
         .iterate(sentences)
         .layer_size(32)
         .window_size(3)
         .min_word_frequency(2)
         .learning_rate(0.2)
         .epochs(8)
         .seed(1)
         .batch_size(64))
    return b


def test_parallel_vocab_count_matches_serial():
    sentences = synthetic_corpus(100)
    tf = DefaultTokenizerFactory()
    serial = Counter()
    for s in sentences:
        serial.update(tf.create(s).tokens())
    assert parallel_vocab_count(sentences, tf, n_threads=4) == serial


def test_one_device_mesh_matches_serial_word2vec():
    # parity is epoch-count-invariant (both sides run the same schedule),
    # so the oracle keeps full strength at the cheaper epoch budget
    sentences = synthetic_corpus(60)
    serial = builder(Word2Vec, sentences).epochs(3).build().fit()
    mesh1 = backend.default_mesh(devices=jax.devices()[:1])
    dist = (builder(DistributedWord2Vec, sentences).epochs(3)
            .mesh(mesh1).build().fit())
    np.testing.assert_allclose(np.asarray(serial.syn0),
                               np.asarray(dist.syn0), atol=1e-5)


def test_eight_device_mesh_matches_serial_word2vec():
    # the count-weighted psum reconstruction makes sharded training compute
    # the same global-mean update as the unsharded kernel (float
    # reassociation aside) — the distributed==local oracle, on HS and NS
    sentences = synthetic_corpus(60)
    for hs, neg in ((True, 0), (False, 5)):
        # epoch count doesn't weaken the oracle: both sides run the same
        # schedule and are compared to each other, not to a threshold
        serial = (builder(Word2Vec, sentences).epochs(3)
                  .use_hierarchic_softmax(hs).negative_sample(neg)
                  .build().fit())
        dist = (builder(DistributedWord2Vec, sentences).epochs(3)
                .use_hierarchic_softmax(hs).negative_sample(neg)
                .mesh(backend.default_mesh()).build().fit())
        np.testing.assert_allclose(np.asarray(serial.syn0),
                                   np.asarray(dist.syn0), atol=1e-4)


def test_full_mesh_distributed_word2vec_learns_structure():
    sentences = synthetic_corpus()
    mesh = backend.default_mesh()
    model = builder(DistributedWord2Vec, sentences).mesh(mesh).build().fit()
    check_cluster_structure(model)
    near = model.words_nearest("rain", top_n=4)
    assert len(set(near) & {"snow", "storm", "cloud", "wind", "sun"}) >= 3


def test_distributed_glove_learns_structure():
    from deeplearning4j_tpu.nlp.distributed import DistributedGlove

    glove = (DistributedGlove.Builder()
             .iterate(synthetic_corpus(400))
             .layer_size(24)
             .window_size(4)
             .epochs(12)
             .learning_rate(0.1)
             .min_word_frequency(2)
             .seed(3)
             .mesh(backend.default_mesh())
             .build())
    glove.fit()
    weather = ["rain", "snow", "storm"]
    finance = ["bank", "money", "stock"]
    within = np.mean([glove.similarity(a, b)
                      for a in weather for b in weather if a != b])
    across = np.mean([glove.similarity(a, b)
                      for a in weather for b in finance])
    assert within > across + 0.1, f"within={within:.3f} across={across:.3f}"
    assert glove.batch_size % 8 == 0


def test_distributed_negative_sampling_learns_structure():
    sentences = synthetic_corpus()
    model = (builder(DistributedWord2Vec, sentences)
             .use_hierarchic_softmax(False)
             .negative_sample(5)
             .epochs(12)
             .mesh(backend.default_mesh())
             .build().fit())
    assert np.isfinite(model.cum_loss)
    check_cluster_structure(model)
