"""Observability/UI tests (≙ BaseUiServerTest / TestRenders / stats storage
suites)."""

import json
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.ui import (
    ChartHistogram,
    ChartLine,
    ComponentDiv,
    ComponentTable,
    ComponentText,
    FileStatsStorage,
    FlowIterationListener,
    HistogramIterationListener,
    InMemoryStatsStorage,
    RemoteStatsListener,
    StatsListener,
    StatsReport,
    StatsUpdateConfiguration,
    UIServer,
    component_from_dict,
)


def tiny_net():
    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater("sgd", learning_rate=0.5)
            .list()
            .layer(DenseLayer(n_in=2, n_out=4, activation="tanh"))
            .layer(OutputLayer(n_in=4, n_out=2, loss="mcxent", activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def xor():
    x = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
    y = np.array([[1, 0], [0, 1], [0, 1], [1, 0]], np.float32)
    return x, y


# --------------------------------------------------------------- listener

def test_stats_listener_collects_scores_and_histograms():
    net = tiny_net()
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(storage, session_id="s1"))
    x, y = xor()
    for _ in range(5):
        net.fit(x, y)
    assert storage.list_session_ids() == ["s1"]
    init = storage.get_init_report("s1")
    assert init.model_class == "MultiLayerNetwork"
    assert init.num_params == net.num_params()
    ups = storage.get_updates("s1")
    assert len(ups) == 5
    assert all(np.isfinite(u.score) for u in ups)
    hist = ups[-1].param_histograms
    assert any(k.endswith("/W") for k in hist)
    k = next(iter(hist))
    assert len(hist[k]["counts"]) == 20
    assert sum(hist[k]["counts"]) > 0


def test_stats_listener_frequency():
    net = tiny_net()
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(
        storage, session_id="s2",
        config=StatsUpdateConfiguration(reporting_frequency=3,
                                        collect_histograms_params=False)))
    x, y = xor()
    for _ in range(9):
        net.fit(x, y)
    assert len(storage.get_updates("s2")) == 3   # iterations 3, 6, 9


def test_flow_listener_records_structure():
    net = tiny_net()
    storage = InMemoryStatsStorage()
    net.set_listeners(FlowIterationListener(storage, session_id="f1",
                                            frequency=1))
    x, y = xor()
    net.fit(x, y)
    flow = storage.get_updates("f1")[-1].param_stats["flow"]
    assert len(flow["layers"]) == 2
    assert flow["layers"][0]["params"] > 0


# ---------------------------------------------------------------- storage

def test_file_storage_roundtrip(tmp_path):
    p = str(tmp_path / "stats.jsonl")
    storage = FileStatsStorage(p)
    net = tiny_net()
    net.set_listeners(HistogramIterationListener(storage))
    x, y = xor()
    for _ in range(3):
        net.fit(x, y)
    sid = storage.list_session_ids()[0]
    reloaded = FileStatsStorage(p)
    assert reloaded.list_session_ids() == storage.list_session_ids()
    assert len(reloaded.get_updates(sid)) == 3
    assert reloaded.get_init_report(sid) is not None


def test_storage_listener_fanout():
    storage = InMemoryStatsStorage()
    got = []
    storage.add_listener(lambda rep: got.append(rep.iteration))
    storage.put_update(StatsReport(session_id="x", iteration=7,
                                   timestamp=time.time()))
    assert got == [7]


# ----------------------------------------------------------------- server

def test_ui_server_endpoints():
    storage = InMemoryStatsStorage()
    server = UIServer(storage)
    port = server.start()
    try:
        net = tiny_net()
        net.set_listeners(StatsListener(storage, session_id="web"))
        x, y = xor()
        for _ in range(4):
            net.fit(x, y)

        def get(path):
            with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                        timeout=5) as r:
                return r.read().decode()

        assert "deeplearning4j_tpu training UI" in get("/train/")
        assert json.loads(get("/train/sessions")) == ["web"]
        ov = json.loads(get("/train/overview?sid=web"))
        assert len(ov["iterations"]) == 4
        assert len(ov["latest_histograms"]) > 0
    finally:
        server.stop()


def test_remote_listener_posts_to_server():
    storage = InMemoryStatsStorage()
    server = UIServer(storage)
    port = server.start()
    try:
        net = tiny_net()
        net.set_listeners(RemoteStatsListener(
            f"http://127.0.0.1:{port}", session_id="remote1"))
        x, y = xor()
        for _ in range(3):
            net.fit(x, y)
        deadline = time.time() + 5
        while time.time() < deadline and len(storage.get_updates("remote1")) < 3:
            time.sleep(0.05)
        assert len(storage.get_updates("remote1")) == 3
    finally:
        server.stop()


def test_remote_listener_survives_dead_server():
    net = tiny_net()
    net.set_listeners(RemoteStatsListener("http://127.0.0.1:1",  # closed port
                                          timeout=0.2))
    x, y = xor()
    net.fit(x, y)  # must not raise


# ------------------------------------------------------------- components

def test_chart_components_roundtrip():
    line = ChartLine("score").add_series("s", [0, 1, 2], [3.0, 2.0, 1.0])
    hist = ChartHistogram("w").add_bin(0, 1, 5).add_bin(1, 2, 3)
    table = ComponentTable(["a", "b"]).add_row(1, 2)
    div = ComponentDiv(line, hist, table, ComponentText("hello"))
    d = json.loads(div.to_json())
    back = component_from_dict(d)
    assert back.to_dict() == div.to_dict()
    assert d["components"][0]["series"][0]["y"] == [3.0, 2.0, 1.0]
