"""NLP stack tests.

Reference test models: ``Word2VecTests.java`` (wordsNearest sanity on a small
corpus), tokenizer/iterator suites (``BasicLineIteratorTest`` etc.),
``GloveTest``, ParagraphVectors label-inference tests, Huffman invariants.
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    BagOfWordsVectorizer,
    BasicLineIterator,
    CollectionSentenceIterator,
    CommonPreprocessor,
    DefaultTokenizerFactory,
    Glove,
    LabelledDocument,
    NGramTokenizerFactory,
    ParagraphVectors,
    Sequence,
    SequenceVectors,
    TfidfVectorizer,
    VectorsConfiguration,
    VocabCache,
    VocabConstructor,
    VocabWord,
    Word2Vec,
    WordVectorSerializer,
    build_huffman,
    codes_matrix,
)


# ------------------------------------------------------------- corpus fixture

def synthetic_corpus(n=300, seed=7):
    """Two topic clusters with strong co-occurrence structure: weather words
    co-occur, finance words co-occur, never across."""
    rs = np.random.RandomState(seed)
    weather = ["rain", "snow", "storm", "cloud", "wind", "sun"]
    finance = ["bank", "money", "stock", "market", "trade", "price"]
    sentences = []
    for _ in range(n):
        topic = weather if rs.rand() < 0.5 else finance
        words = rs.choice(topic, size=6, replace=True)
        sentences.append(" ".join(words))
    return sentences


# -------------------------------------------------------------- tokenization

def test_default_tokenizer_and_preprocessor():
    tf = DefaultTokenizerFactory()
    tf.set_token_pre_processor(CommonPreprocessor())
    toks = tf.create("Hello, World! 42 times.").tokens()
    assert toks == ["hello", "world", "times"]


def test_ngram_tokenizer():
    tf = NGramTokenizerFactory(1, 2)
    toks = tf.create("a b c").tokens()
    assert "a" in toks and "a b" in toks and "b c" in toks


def test_basic_line_iterator(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_text("line one\nline two\nline three\n")
    it = BasicLineIterator(str(p))
    assert list(it) == ["line one", "line two", "line three"]
    it.reset()
    assert it.next_sentence() == "line one"


# ------------------------------------------------------------------- vocab

def test_vocab_constructor_counts_and_min_freq():
    seqs = []
    for words in (["a", "b", "a"], ["a", "c"]):
        s = Sequence()
        for w in words:
            s.add_element(VocabWord(label=w))
        seqs.append(s)
    cache = VocabConstructor(min_element_frequency=2).build_vocab(seqs)
    assert cache.contains_word("a")
    assert not cache.contains_word("b")  # freq 1 < 2 pruned
    assert cache.word_frequency("a") == 3


def test_huffman_invariants():
    cache = VocabCache()
    freqs = {"the": 100, "of": 50, "cat": 10, "dog": 8, "zebu": 1}
    for w, f in freqs.items():
        cache.add_token(VocabWord(label=w, element_frequency=f))
    cache.finalize_vocab()
    build_huffman(cache)
    words = cache.vocab_words()
    # prefix-free: no code is a prefix of another
    codes = {tuple(w.codes) for w in words}
    assert len(codes) == len(words)
    for c1 in codes:
        for c2 in codes:
            if c1 != c2:
                assert c1 != c2[:len(c1)]
    # frequent words get codes no longer than rare words
    assert len(cache.word_for("the").codes) <= len(cache.word_for("zebu").codes)
    # dense matrices align
    cds, pts, lens = codes_matrix(cache)
    assert cds.shape == pts.shape
    w = cache.word_for("cat")
    assert list(cds[w.index][:lens[w.index]]) == w.codes


# ---------------------------------------------------------------- word2vec

def fit_w2v(sentences, hs=True, negative=0, algo="skipgram", seed=1):
    # NB small-vocab corpus + collision-mean kernels: fewer effective row
    # updates per batch, compensated by a higher lr + smaller batches
    w2v = (Word2Vec.Builder()
           .iterate(sentences)
           .layer_size(32)
           .window_size(3)
           .min_word_frequency(2)
           .use_hierarchic_softmax(hs)
           .negative_sample(negative)
           .elements_learning_algorithm(algo)
           .learning_rate(0.2)
           .epochs(12)
           .seed(seed)
           .batch_size(64)
           .build())
    return w2v.fit()


def check_cluster_structure(model):
    weather = ["rain", "snow", "storm", "cloud"]
    finance = ["bank", "money", "stock", "market"]
    within = np.mean([model.similarity(a, b)
                      for a in weather for b in weather if a != b])
    across = np.mean([model.similarity(a, b)
                      for a in weather for b in finance])
    assert within > across + 0.15, f"within={within:.3f} across={across:.3f}"


def test_word2vec_skipgram_hs_learns_structure():
    model = fit_w2v(synthetic_corpus(), hs=True, negative=0)
    check_cluster_structure(model)
    near = model.words_nearest("rain", top_n=4)
    assert len(set(near) & {"snow", "storm", "cloud", "wind", "sun"}) >= 3


def test_word2vec_skipgram_ns_learns_structure():
    model = fit_w2v(synthetic_corpus(), hs=False, negative=5)
    check_cluster_structure(model)


def test_word2vec_cbow_learns_structure():
    model = fit_w2v(synthetic_corpus(), hs=True, negative=0, algo="cbow")
    check_cluster_structure(model)


def test_word2vec_vocab_and_vectors():
    model = fit_w2v(synthetic_corpus())
    assert model.has_word("rain")
    assert not model.has_word("notaword")
    v = model.get_word_vector("rain")
    assert v.shape == (32,)
    assert abs(model.similarity("rain", "rain") - 1.0) < 1e-5


# -------------------------------------------------------------- serializer

def test_text_format_roundtrip(tmp_path):
    model = fit_w2v(synthetic_corpus())
    p = str(tmp_path / "vecs.txt")
    WordVectorSerializer.write_word_vectors(model, p)
    loaded = WordVectorSerializer.read_word_vectors(p)
    np.testing.assert_allclose(loaded.get_word_vector("rain"),
                               model.get_word_vector("rain"), atol=1e-5)
    assert loaded.words_nearest("rain", top_n=3) == model.words_nearest("rain", top_n=3)


def test_binary_format_roundtrip(tmp_path):
    model = fit_w2v(synthetic_corpus())
    p = str(tmp_path / "vecs.bin")
    WordVectorSerializer.write_binary(model, p)
    loaded = WordVectorSerializer.read_binary(p)
    np.testing.assert_allclose(loaded.get_word_vector("storm"),
                               model.get_word_vector("storm"), atol=1e-6)


def test_full_model_zip_roundtrip(tmp_path):
    model = fit_w2v(synthetic_corpus())
    p = str(tmp_path / "model.zip")
    WordVectorSerializer.write_full_model(model, p)
    loaded = WordVectorSerializer.read_full_model(p)
    np.testing.assert_allclose(np.asarray(loaded.lookup.syn0),
                               np.asarray(model.lookup.syn0), atol=1e-6)
    assert loaded.vocab.word_frequency("rain") == model.vocab.word_frequency("rain")
    # huffman codes survive
    assert loaded.vocab.word_for("rain").codes == model.vocab.word_for("rain").codes


# ----------------------------------------------------------------- glove

def test_glove_learns_structure():
    glove = (Glove.Builder()
             .iterate(synthetic_corpus(400))
             .layer_size(24)
             .window_size(4)
             .epochs(25)
             .learning_rate(0.1)
             .min_word_frequency(2)
             .seed(3)
             .build())
    glove.fit()
    weather = ["rain", "snow", "storm"]
    finance = ["bank", "money", "stock"]
    within = np.mean([glove.similarity(a, b)
                      for a in weather for b in weather if a != b])
    across = np.mean([glove.similarity(a, b)
                      for a in weather for b in finance])
    assert within > across + 0.1, f"within={within:.3f} across={across:.3f}"


# --------------------------------------------------------- paragraph vectors

def test_paragraph_vectors_labels_cluster():
    rs = np.random.RandomState(11)
    weather = ["rain", "snow", "storm", "cloud", "wind", "sun"]
    finance = ["bank", "money", "stock", "market", "trade", "price"]
    docs = []
    for i in range(60):
        topic, tag = (weather, "W") if i % 2 == 0 else (finance, "F")
        content = " ".join(rs.choice(topic, size=8))
        docs.append(LabelledDocument(content=content, labels=[f"{tag}_{i}"]))
    pv = (ParagraphVectors.Builder()
          .iterate(docs)
          .layer_size(24)
          .window_size(3)
          .min_word_frequency(1)
          .use_hierarchic_softmax(True)
          .learning_rate(0.2)
          .epochs(12)
          .seed(5)
          .batch_size(64)
          .build())
    pv.fit()
    # label vectors of same-topic docs are closer than cross-topic
    w_labels = [f"W_{i}" for i in range(0, 20, 2)]
    f_labels = [f"F_{i}" for i in range(1, 20, 2)]
    within = np.mean([pv.similarity(a, b) for a in w_labels for b in w_labels if a != b])
    across = np.mean([pv.similarity(a, b) for a in w_labels for b in f_labels])
    assert within > across, f"within={within:.3f} across={across:.3f}"
    # inference maps unseen text near the right cluster
    pred = pv.predict("rain snow storm wind cloud sun rain storm")
    assert pred.startswith("W_"), pred


# ------------------------------------------------------------------- bow

def test_bag_of_words_counts():
    bow = BagOfWordsVectorizer()
    mat = bow.fit_transform(["a b a", "b c"])
    ia, ib = bow.vocab.index_of("a"), bow.vocab.index_of("b")
    assert mat[0, ia] == 2 and mat[0, ib] == 1
    assert mat.shape == (2, 3)


def test_tfidf_downweights_common_terms():
    docs = ["a b", "a c", "a d"]
    tv = TfidfVectorizer()
    mat = tv.fit_transform(docs)
    ia = tv.vocab.index_of("a")
    ib = tv.vocab.index_of("b")
    # 'a' appears in every doc -> idf 0
    assert mat[0, ia] == pytest.approx(0.0)
    assert mat[0, ib] > 0
