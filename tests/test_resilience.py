"""Resilience subsystem: atomic async checkpointing, auto-resume,
preemption handling, retry/backoff, and the deterministic fault harness.

Acceptance oracles (ISSUE 5):
- a fit loop killed mid-run (injected crash or SIGTERM) resumes from the
  latest committed checkpoint and reaches the SAME final params as an
  uninterrupted run;
- a checkpoint directory with a torn snapshot is never selected by
  ``latest()``;
- crash-mid-save (writer killed between shard files) leaves the previous
  valid checkpoint discoverable and resume-equivalent.
"""

import json
import os
import signal
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import (
    AsyncDataSetIterator, ListDataSetIterator,
)
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.observability import (
    HealthEvaluator, HealthRule, MetricsRegistry, get_flight_recorder,
)
from deeplearning4j_tpu.resilience import (
    CheckpointManager, FaultInjector, InjectedFault, PreemptionHandler,
    RetryPolicy, TransientError, inject_faults, is_transient,
)

pytestmark = pytest.mark.faults


# ---------------------------------------------------------------- helpers
def _net(seed=21):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(seed)
         .updater("adam", learning_rate=0.05).list()
         .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
         .layer(OutputLayer(n_in=16, n_out=4)).build())
    ).init()


def _batches(n_batches=6, batch=8, seed=0):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n_batches):
        x = rs.rand(batch, 8).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, batch)]
        out.append((x, y))
    return out


def _params(net):
    return net.params_to_vector()


# ===================================================== CheckpointManager
class TestCheckpointManager:
    def test_commit_layout_and_latest(self, tmp_path):
        net = _net()
        net.fit(*_batches(1)[0])
        cm = CheckpointManager(str(tmp_path), async_save=False,
                               registry=MetricsRegistry())
        cm.save(net)
        path = cm.latest()
        assert path is not None and path.endswith("step-00000001")
        commit = json.load(open(os.path.join(path, "COMMIT")))
        assert commit["step"] == 1 and set(commit["files"]) >= {
            "shards-0.npz", "manifest-0.json", "checkpoint.json"}
        # a second save at a new step becomes the new latest
        net.fit(*_batches(1, seed=1)[0])
        cm.save(net)
        assert cm.latest_step() == 2

    def test_keep_n_retention_with_archival(self, tmp_path):
        net = _net()
        cm = CheckpointManager(str(tmp_path), keep=2, archive_every_steps=3,
                               async_save=False, registry=MetricsRegistry())
        for x, y in _batches(7):
            net.fit(x, y)
            cm.save(net)
        # newest 2 kept (6, 7) plus archival multiples of 3 (3, 6)
        assert cm.all_steps() == [3, 6, 7]

    def test_latest_skips_torn_and_corrupt(self, tmp_path):
        net = _net()
        cm = CheckpointManager(str(tmp_path), keep=5, async_save=False,
                               registry=MetricsRegistry())
        batches = _batches(3)
        for x, y in batches:
            net.fit(x, y)
            cm.save(net)
        assert cm.latest_step() == 3
        inj = FaultInjector(seed=5)
        inj.corrupt_checkpoint(cm._step_dir(3), mode="truncate")
        assert cm.latest_step() == 2           # size mismatch -> skipped
        inj.corrupt_checkpoint(cm._step_dir(2), mode="corrupt")
        assert cm.latest_step() == 1           # CRC mismatch -> skipped
        inj.corrupt_checkpoint(cm._step_dir(1), mode="drop_commit")
        assert cm.latest() is None             # no COMMIT -> torn -> skipped

    def test_wall_clock_trigger_and_priority(self, tmp_path):
        net = _net()
        net.fit(*_batches(1)[0])
        cm = CheckpointManager(str(tmp_path), save_every_seconds=3600,
                               async_save=False, registry=MetricsRegistry())
        assert cm.due(net.iteration) is None
        cm._last_mark_time -= 3601             # fast-forward the clock
        assert cm.due(net.iteration) == "time_interval"
        cm.request_priority_save()
        assert cm.due(net.iteration) == "priority"
        assert cm.maybe_save(net) == "priority"
        assert cm.latest_step() == 1
        assert cm.due(net.iteration) is None   # priority flag cleared

    def test_async_save_commits_off_thread(self, tmp_path):
        net = _net()
        net.fit(*_batches(1)[0])
        reg = MetricsRegistry()
        with CheckpointManager(str(tmp_path), registry=reg) as cm:
            job = cm.save(net)
            job.wait(timeout=30)
            assert cm.latest_step() == 1
            assert reg.get_value("dl4j_checkpoint_saves_total",
                                 trigger="explicit") == 1
            assert reg.get_value("dl4j_checkpoint_last_bytes") > 0

    def test_staleness_gauge_and_health_rule(self, tmp_path):
        reg = MetricsRegistry()
        cm = CheckpointManager(str(tmp_path), async_save=False, registry=reg)
        rule = HealthRule("ckpt_staleness", "max_checkpoint_staleness", 3600)
        assert HealthEvaluator([rule], registry=reg).evaluate().healthy
        # a manager that stopped (or never started) committing goes stale
        cm._start_mono -= 7200
        verdict = HealthEvaluator([rule], registry=reg).evaluate()
        assert not verdict.healthy
        assert verdict.failing[0]["name"] == "ckpt_staleness"
        # a committed save resets staleness
        net = _net()
        net.fit(*_batches(1)[0])
        cm.save(net)
        assert HealthEvaluator([rule], registry=reg).evaluate().healthy


# ============================================================ crash-mid-save
class TestCrashMidSave:
    def test_writer_killed_between_shard_files(self, tmp_path):
        """The satellite's oracle: the writer dies between staged files of
        the step-3 save; latest() must return step 2 and a resumed run
        must reach the uninterrupted run's exact params."""
        batches = _batches(6)
        ref = _net()
        for x, y in batches:
            ref.fit(x, y)

        # each commit stages 4 files (shards, manifest, meta, COMMIT); the
        # 9th file is the shard file of save #3 -> die before its manifest
        inj = FaultInjector(seed=7).crash_after_files(9)
        reg = MetricsRegistry()
        net = _net()
        cm = CheckpointManager(str(tmp_path), keep=10, save_every_steps=1,
                               fault_injector=inj, registry=reg)
        net.fit(batches, checkpoint_manager=cm)
        cm.wait_idle()
        assert inj.injected and inj.injected[0]["kind"] == "writer_crash"
        # the failed save is visible, not fatal: training completed
        assert net.iteration == 6
        assert reg.get_value("dl4j_checkpoint_failures_total",
                             stage="write") == 1
        # step 3 never committed; only its .tmp (or nothing) remains
        assert 3 not in cm.all_steps()

        # process "dies"; a fresh process resumes from the newest valid
        # commit and replays the stream to the same final params
        resumed = _net(seed=99)     # wrong seed on purpose; restore fixes it
        cm2 = CheckpointManager(str(tmp_path), keep=10)
        resumed.fit(batches, checkpoint_manager=cm2)
        assert resumed.iteration == 6
        np.testing.assert_allclose(_params(ref), _params(resumed), atol=1e-6)
        cm.close()
        cm2.close()


# ========================================================== injected crashes
class TestCrashResume:
    def test_fatal_crash_then_auto_resume_equivalence(self, tmp_path):
        batches = _batches(6)
        ref = _net()
        for x, y in batches:
            ref.fit(x, y)

        net = _net()
        cm = CheckpointManager(str(tmp_path), keep=10, save_every_steps=1,
                               async_save=False, registry=MetricsRegistry())
        with inject_faults(FaultInjector().fail_at_step(3, transient=False)):
            with pytest.raises(InjectedFault):
                net.fit(batches, checkpoint_manager=cm)
        assert net.iteration == 3 and cm.latest_step() == 3

        resumed = _net(seed=99)
        resumed.fit(batches, checkpoint_manager=cm)
        assert resumed.iteration == 6
        np.testing.assert_allclose(_params(ref), _params(resumed), atol=1e-6)

    def test_transient_crash_retried_in_place(self, tmp_path):
        """A transient step failure retries (same RNG key replayed) and the
        run still matches the uninterrupted one bit-for-bit."""
        batches = _batches(6)
        ref = _net()
        for x, y in batches:
            ref.fit(x, y)

        reg = MetricsRegistry()
        net = _net()
        rp = RetryPolicy(max_retries=2, base_delay_s=0.0, jitter=0.0,
                         seed=1, component="fit", registry=reg)
        with inject_faults(FaultInjector().fail_at_step(2, transient=True)):
            net.fit(batches, retry_policy=rp)
        assert net.iteration == 6
        assert rp.retries == 1
        assert reg.get_value("dl4j_step_retries_total", component="fit") == 1
        np.testing.assert_allclose(_params(ref), _params(net), atol=1e-6)


# ================================================================ preemption
class TestPreemption:
    def test_sigterm_smoke_checkpoint_and_resume(self, tmp_path):
        """Tier-1 smoke: a 6-step fit SIGTERMed at step 3 stops cleanly
        with a priority checkpoint, then resumes to completion with the
        uninterrupted run's params."""
        batches = _batches(6)
        ref = _net()
        for x, y in batches:
            ref.fit(x, y)

        class KillAt:
            def __init__(self, at):
                self.at = at

            def iteration_done(self, model, iteration):
                if iteration == self.at:
                    os.kill(os.getpid(), signal.SIGTERM)

        reg = MetricsRegistry()
        net = _net()
        net.add_listener(KillAt(3))
        cm = CheckpointManager(str(tmp_path), keep=10, async_save=False,
                               registry=reg)
        with PreemptionHandler(cm, registry=reg) as handler:
            net.fit(batches, checkpoint_manager=cm)
            assert handler.stop_requested
            assert handler.signal_received == signal.SIGTERM
        assert net.iteration == 3
        assert cm.latest_step() == 3
        commit = cm.read_commit(cm.latest())
        assert commit["trigger"] in ("priority", "preempt")
        assert reg.get_value("dl4j_preemptions_total", signal="SIGTERM") == 1

        resumed = _net(seed=99)
        resumed.fit(batches, checkpoint_manager=cm)
        assert resumed.iteration == 6
        np.testing.assert_allclose(_params(ref), _params(resumed), atol=1e-6)

    def test_second_fit_without_signal_runs_normally(self, tmp_path):
        """After uninstall the flag is gone: plain fits are unaffected."""
        net = _net()
        net.fit(_batches(2))
        assert net.iteration == 2


# ================================================================ retry unit
class TestRetryPolicy:
    def test_classification(self):
        assert is_transient(TransientError("x"))
        assert is_transient(ConnectionError("x"))
        assert is_transient(RuntimeError("RESOURCE_EXHAUSTED: hbm"))
        assert is_transient(RuntimeError("backend UNAVAILABLE"))
        assert not is_transient(ValueError("bad shape"))
        assert not is_transient(KeyboardInterrupt())
        assert not is_transient(RuntimeError("NaN loss"))

    def test_backoff_deterministic_and_bounded(self):
        a = RetryPolicy(base_delay_s=1.0, max_delay_s=4.0, jitter=0.25,
                        seed=42, sleep=lambda s: None)
        b = RetryPolicy(base_delay_s=1.0, max_delay_s=4.0, jitter=0.25,
                        seed=42, sleep=lambda s: None)
        da = [a.delay(i) for i in range(6)]
        db = [b.delay(i) for i in range(6)]
        assert da == db                      # seeded jitter is deterministic
        assert all(d <= 4.0 * 1.25 for d in da)
        assert da[1] > da[0] * 0.5           # roughly exponential growth

    def test_retries_then_succeeds(self):
        reg = MetricsRegistry()
        slept = []
        rp = RetryPolicy(max_retries=3, base_delay_s=0.01, seed=0,
                         component="unit", sleep=slept.append, registry=reg)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise TransientError("blip")
            return "ok"

        assert rp.run(flaky) == "ok"
        assert calls["n"] == 3 and len(slept) == 2
        assert reg.get_value("dl4j_step_retries_total",
                             component="unit") == 2

    def test_fatal_not_retried_and_budget_exhausts(self):
        reg = MetricsRegistry()
        rp = RetryPolicy(max_retries=2, base_delay_s=0.0, component="unit",
                         sleep=lambda s: None, registry=reg)
        with pytest.raises(ValueError):
            rp.run(lambda: (_ for _ in ()).throw(ValueError("bug")))
        assert reg.get_value("dl4j_step_retries_total",
                             component="unit") is None

        def always():
            raise TransientError("down")

        with pytest.raises(TransientError):
            rp.run(always)
        assert reg.get_value("dl4j_retry_exhausted_total",
                             component="unit") == 1
        assert reg.get_value("dl4j_step_retries_total",
                             component="unit") == 2


# ===================================================== distributed wiring
class TestMasters:
    def test_sync_master_crash_resume_equivalence(self, tmp_path):
        from deeplearning4j_tpu.backend import device as backend
        from deeplearning4j_tpu.parallel import (
            DistributedNetwork, SyncTrainingMaster,
        )

        mesh = backend.default_mesh()
        rs = np.random.RandomState(1)
        x = rs.rand(64, 8).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 64)]

        ref = _net()
        DistributedNetwork(ref, SyncTrainingMaster(mesh=mesh)).fit(
            ListDataSetIterator(DataSet(x, y), 16))

        net = _net()
        cm = CheckpointManager(str(tmp_path), keep=10, save_every_steps=1,
                               async_save=False, registry=MetricsRegistry())
        master = SyncTrainingMaster(mesh=mesh, checkpoint_manager=cm)
        with inject_faults(FaultInjector().fail_at_step(
                2, component="sync_master", transient=False)):
            with pytest.raises(InjectedFault):
                DistributedNetwork(net, master).fit(
                    ListDataSetIterator(DataSet(x, y), 16))
        assert cm.latest_step() == 2

        resumed = _net(seed=1234)
        master2 = SyncTrainingMaster(mesh=mesh, checkpoint_manager=cm)
        DistributedNetwork(resumed, master2).fit(
            ListDataSetIterator(DataSet(x, y), 16))
        assert resumed.iteration == 4
        np.testing.assert_allclose(_params(ref), _params(resumed), atol=1e-6)

    def test_parallel_wrapper_window_saves_and_resume(self, tmp_path):
        from deeplearning4j_tpu.parallel import ParallelWrapper

        rs = np.random.RandomState(3)
        # 16 minibatches of 8 over 8 replicas -> 2 windows (it: 0 -> 2)
        x = rs.rand(128, 8).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 128)]

        ref = _net()
        ParallelWrapper(ref, averaging_frequency=1).fit(
            ListDataSetIterator(DataSet(x, y), 8))

        net = _net()
        cm = CheckpointManager(str(tmp_path), keep=10, save_every_steps=1,
                               async_save=False, registry=MetricsRegistry())
        pw = ParallelWrapper(net, averaging_frequency=1,
                             checkpoint_manager=cm)
        with inject_faults(FaultInjector().fail_at_step(
                1, component="parallel_wrapper", transient=False)):
            with pytest.raises(InjectedFault):
                pw.fit(ListDataSetIterator(DataSet(x, y), 8))
        assert cm.latest_step() == 1

        resumed = _net(seed=77)
        pw2 = ParallelWrapper(resumed, averaging_frequency=1,
                              checkpoint_manager=cm)
        pw2.fit(ListDataSetIterator(DataSet(x, y), 8))
        assert resumed.iteration == ref.iteration
        np.testing.assert_allclose(_params(ref), _params(resumed), atol=1e-6)


    def test_computation_graph_crash_resume_equivalence(self, tmp_path):
        from deeplearning4j_tpu.models.graph import ComputationGraph

        def build():
            conf = (NeuralNetConfiguration.builder().seed(7)
                    .updater("adam", learning_rate=0.05).graph()
                    .add_inputs("in")
                    .add_layer("d", DenseLayer(n_in=8, n_out=16,
                                               activation="relu"), "in")
                    .add_layer("out", OutputLayer(n_in=16, n_out=4), "d")
                    .set_outputs("out").build())
            return ComputationGraph(conf).init()

        batches = _batches(5)
        ref = build()
        for x, y in batches:
            ref.fit(x, y)

        net = build()
        cm = CheckpointManager(str(tmp_path), save_every_steps=1,
                               async_save=False, registry=MetricsRegistry())
        with inject_faults(FaultInjector().fail_at_step(
                2, component="ComputationGraph", transient=False)):
            with pytest.raises(InjectedFault):
                net.fit(batches, checkpoint_manager=cm)
        assert cm.latest_step() == 2

        resumed = build()
        resumed.fit(batches, checkpoint_manager=cm)
        assert resumed.iteration == 5
        import jax

        flat = lambda n: np.concatenate(
            [np.asarray(l).ravel()
             for l in jax.tree_util.tree_leaves(n.params)])
        np.testing.assert_allclose(flat(ref), flat(resumed), atol=1e-6)

    def test_pipeline_master_crash_resume_equivalence(self, tmp_path):
        from deeplearning4j_tpu.parallel import PipelineParallelTrainingMaster

        def build():
            return MultiLayerNetwork(
                (NeuralNetConfiguration.builder().seed(21)
                 .updater("sgd", learning_rate=0.1).list()
                 .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
                 .layer(OutputLayer(n_in=16, n_out=4)).build())).init()

        rs = np.random.RandomState(1)
        x = rs.rand(64, 8).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 64)]
        it = lambda: ListDataSetIterator(DataSet(x, y), 16)

        ref = build()
        PipelineParallelTrainingMaster(
            n_stages=2, n_microbatches=4,
            mode="orchestrated").execute_training(ref, it())

        net = build()
        cm = CheckpointManager(str(tmp_path), save_every_steps=1,
                               async_save=False, registry=MetricsRegistry())
        master = PipelineParallelTrainingMaster(
            n_stages=2, n_microbatches=4, mode="orchestrated",
            checkpoint_manager=cm)
        with inject_faults(FaultInjector().fail_at_step(
                2, component="pipeline_master", transient=False)):
            with pytest.raises(InjectedFault):
                master.execute_training(net, it())
        assert cm.latest_step() == 2

        resumed = build()
        PipelineParallelTrainingMaster(
            n_stages=2, n_microbatches=4, mode="orchestrated",
            checkpoint_manager=cm).execute_training(resumed, it())
        assert resumed.iteration == ref.iteration
        np.testing.assert_allclose(_params(ref), _params(resumed), atol=1e-6)


# ==================================================== skip granularity
class TestSkipGranularity:
    """Resume skip is counted in ITERATIONS, not batches — batches that
    advance the iteration by more than 1 (num_iterations > 1, TBPTT
    windows) must skip whole batches worth of iterations on resume."""

    def test_num_iterations_gt_1_resume_equivalence(self, tmp_path):
        def build():
            return MultiLayerNetwork(
                (NeuralNetConfiguration.builder().seed(31)
                 .updater("adam", learning_rate=0.05).iterations(2).list()
                 .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
                 .layer(OutputLayer(n_in=16, n_out=4)).build())).init()

        batches = _batches(5)
        ref = build()
        for x, y in batches:
            ref.fit(x, y)
        assert ref.iteration == 10        # 2 iterations per batch

        net = build()
        cm = CheckpointManager(str(tmp_path), keep=20, save_every_steps=1,
                               async_save=False, registry=MetricsRegistry())
        # injected fault at iteration 4 = mid-run, on a batch boundary
        with inject_faults(FaultInjector().fail_at_step(4, transient=False)):
            with pytest.raises(InjectedFault):
                net.fit(batches, checkpoint_manager=cm)
        assert cm.latest_step() == 4

        resumed = build()
        resumed.fit(batches, checkpoint_manager=cm)
        assert resumed.iteration == 10
        np.testing.assert_allclose(_params(ref), _params(resumed), atol=1e-6)

    def test_tbptt_resume_equivalence(self, tmp_path):
        from deeplearning4j_tpu.nn.layers import GravesLSTM, RnnOutputLayer

        def build():
            return MultiLayerNetwork(
                (NeuralNetConfiguration.builder().seed(13)
                 .updater("sgd", learning_rate=0.1).list()
                 .layer(GravesLSTM(n_in=3, n_out=6))
                 .layer(RnnOutputLayer(n_in=6, n_out=3, loss="mcxent",
                                       activation="softmax"))
                 .backprop_type("truncated_bptt", fwd_length=4,
                                back_length=4).build())).init()

        rs = np.random.RandomState(2)
        batches = []
        for _ in range(4):
            x = rs.rand(2, 12, 3).astype(np.float32)
            y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, (2, 12))]
            batches.append((x, y))

        ref = build()
        for x, y in batches:
            ref.fit(x, y)
        assert ref.iteration == 12        # 12 timesteps / fwd 4 = 3 per batch

        net = build()
        cm = CheckpointManager(str(tmp_path), keep=20, save_every_steps=1,
                               async_save=False, registry=MetricsRegistry())
        with inject_faults(FaultInjector().fail_at_step(6, transient=False)):
            with pytest.raises(InjectedFault):
                net.fit(batches, checkpoint_manager=cm)
        assert cm.latest_step() == 6      # batch boundary after 2 batches

        resumed = build()
        resumed.fit(batches, checkpoint_manager=cm)
        assert resumed.iteration == 12
        np.testing.assert_allclose(_params(ref), _params(resumed), atol=1e-6)

    def test_tbptt_transient_window_retry(self):
        """A transient failure inside a TBPTT window retries that WINDOW
        (not the whole batch) and still matches the uninterrupted run."""
        from deeplearning4j_tpu.nn.layers import GravesLSTM, RnnOutputLayer

        def build():
            return MultiLayerNetwork(
                (NeuralNetConfiguration.builder().seed(17)
                 .updater("sgd", learning_rate=0.1).list()
                 .layer(GravesLSTM(n_in=3, n_out=6))
                 .layer(RnnOutputLayer(n_in=6, n_out=3, loss="mcxent",
                                       activation="softmax"))
                 .backprop_type("truncated_bptt", fwd_length=4,
                                back_length=4).build())).init()

        rs = np.random.RandomState(4)
        x = rs.rand(2, 12, 3).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, (2, 12))]

        ref = build()
        ref.fit(x, y)

        net = build()
        rp = RetryPolicy(max_retries=2, base_delay_s=0.0, jitter=0.0,
                         registry=MetricsRegistry())
        with inject_faults(FaultInjector().fail_at_step(1, transient=True)):
            net.fit(x, y, retry_policy=rp)    # fault in the 2nd window
        assert rp.retries == 1 and net.iteration == 3
        np.testing.assert_allclose(_params(ref), _params(net), atol=1e-6)


# ============================================== wiring-parity hardening
class TestWiringParity:
    def test_solver_path_preempts_and_saves(self, tmp_path):
        """The non-SGD solver branch honors the same boundary duties as
        the SGD branch: interval saves fire and SIGTERM (via trigger)
        stops the loop with a priority checkpoint."""
        net = MultiLayerNetwork(
            (NeuralNetConfiguration.builder().seed(11)
             .updater("sgd", learning_rate=0.1)
             .optimization_algo("line_gradient_descent").list()
             .layer(DenseLayer(n_in=8, n_out=8, activation="relu"))
             .layer(OutputLayer(n_in=8, n_out=4)).build())).init()
        cm = CheckpointManager(str(tmp_path), keep=10, save_every_steps=1,
                               async_save=False, registry=MetricsRegistry())

        class TriggerAt:
            def iteration_done(self, model, iteration):
                if iteration == 2:
                    handler.trigger()

        net.add_listener(TriggerAt())
        with PreemptionHandler(cm, registry=MetricsRegistry()) as handler:
            net.fit(_batches(4), checkpoint_manager=cm)
        assert net.iteration == 2          # stopped at the boundary
        assert cm.latest_step() == 2       # interval saves fired too
        assert cm.all_steps() == [1, 2]

    def test_graph_single_pair_path_saves_on_interval(self, tmp_path):
        """A user-driven loop of graph.fit(x, y, checkpoint_manager=...)
        gets the same boundary saves as the iterable path."""
        from deeplearning4j_tpu.models.graph import ComputationGraph

        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater("adam", learning_rate=0.05).graph()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_in=8, n_out=8,
                                           activation="relu"), "in")
                .add_layer("out", OutputLayer(n_in=8, n_out=4), "d")
                .set_outputs("out").build())
        net = ComputationGraph(conf).init()
        cm = CheckpointManager(str(tmp_path), keep=10, save_every_steps=2,
                               async_save=False, auto_resume=False,
                               registry=MetricsRegistry())
        for x, y in _batches(4):
            net.fit(x, y, checkpoint_manager=cm)
        assert net.iteration == 4
        assert cm.all_steps() == [2, 4]

    def test_staleness_gauge_labels_do_not_collide(self, tmp_path):
        """Two managers whose directories share a basename (every
        CheckpointModelSaver has a best/ and latest/) keep separate
        staleness gauge children."""
        reg = MetricsRegistry()
        a = CheckpointManager(str(tmp_path / "run1" / "best"),
                              async_save=False, registry=reg)
        b = CheckpointManager(str(tmp_path / "run2" / "best"),
                              async_save=False, registry=reg)
        assert a.label != b.label
        net = _net()
        net.fit(*_batches(1)[0])
        a.save(net)
        # a just committed (fresh), b never did: with colliding labels b's
        # callback would have replaced a's and both would read identical
        sa = reg.get_value("dl4j_checkpoint_staleness_seconds",
                           directory=a.label)
        sb = reg.get_value("dl4j_checkpoint_staleness_seconds",
                           directory=b.label)
        assert sa is not None and sb is not None and sa != sb


# ================================================= preempt-save hardening
class TestSaveIfStale:
    def test_failed_async_save_does_not_cover_preempt_save(self, tmp_path):
        """A queued async save that FAILS in the writer must not satisfy
        the preemption path's 'already covered' check — the last-chance
        save has to commit."""
        net = _net()
        net.fit(*_batches(1)[0])
        inj = FaultInjector(seed=3).crash_after_files(1)
        cm = CheckpointManager(str(tmp_path), fault_injector=inj,
                               registry=MetricsRegistry())
        cm.save(net)                       # async; writer dies mid-stage
        cm.wait_idle()
        assert cm.latest() is None         # nothing committed
        assert cm.save_if_stale(net, block=True)   # NOT covered -> saves
        assert cm.latest_step() == 1
        cm.close()


class TestPreemptionRearm:
    def test_reset_rearms_os_handlers(self, tmp_path):
        """reset() must re-hook the OS handlers the first signal restored
        (second-signal escalation), or a long-lived trainer loses
        preemption protection after one handled stop."""
        cm = CheckpointManager(str(tmp_path), async_save=False,
                               registry=MetricsRegistry())
        with PreemptionHandler(cm, registry=MetricsRegistry()) as ph:
            os.kill(os.getpid(), signal.SIGTERM)
            for _ in range(100):
                if ph.stop_requested:
                    break
                time.sleep(0.01)
            assert ph.stop_requested
            ph.reset()
            assert not ph.stop_requested
            # the second preemption is caught again, not fatal
            os.kill(os.getpid(), signal.SIGTERM)
            for _ in range(100):
                if ph.stop_requested:
                    break
                time.sleep(0.01)
            assert ph.stop_requested
            ph.reset()


# ======================================================== earlystopping
class TestEarlyStoppingSaver:
    def test_checkpoint_model_saver_bounded_and_atomic(self, tmp_path):
        from deeplearning4j_tpu.earlystopping import (
            CheckpointModelSaver, EarlyStoppingConfiguration,
            EarlyStoppingTrainer, MaxEpochsTerminationCondition,
            DataSetLossCalculator,
        )

        rs = np.random.RandomState(5)
        x = rs.rand(32, 8).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 32)]
        train = ListDataSetIterator(DataSet(x, y), 16)
        saver = CheckpointModelSaver(str(tmp_path), keep=2)
        cfg = (EarlyStoppingConfiguration.Builder()
               .model_saver(saver)
               .epoch_termination_conditions(MaxEpochsTerminationCondition(5))
               .score_calculator(DataSetLossCalculator(
                   ListDataSetIterator(DataSet(x, y), 16)))
               .save_last_model()
               .build())
        net = _net()
        result = EarlyStoppingTrainer(cfg, net, train).fit()
        best = result.best_model
        assert best is not None
        # retention bounded: at most `keep` checkpoints per track, however
        # many epochs ran (the unbounded-growth fix)
        assert len(saver._best.all_steps()) <= 2
        assert len(saver._latest.all_steps()) <= 2
        # every committed dir is atomic (COMMIT present + verifies)
        assert saver._best.latest() is not None
        # the restored best model scores like the live net it cloned
        xq = rs.rand(4, 8).astype(np.float32)
        out = np.asarray(best.output(xq))
        assert out.shape == (4, 4) and np.isfinite(out).all()

    def test_local_file_saver_writes_atomically(self, tmp_path):
        from deeplearning4j_tpu.earlystopping import LocalFileModelSaver

        saver = LocalFileModelSaver(str(tmp_path))
        net = _net()
        saver.save_best_model(net, 0.5)
        assert os.path.exists(saver.best_path)
        assert not os.path.exists(saver.best_path + ".tmp")
        loaded = saver.get_best_model()
        np.testing.assert_allclose(_params(net), _params(loaded), atol=0)


# ====================================================== iterator reset fix
class TestAsyncIteratorReset:
    def test_reset_hard_fails_on_stuck_producer(self):
        release = threading.Event()

        class Stuck(ListDataSetIterator):
            def __init__(self, data, batch):
                super().__init__(data, batch)
                self.calls = 0

            def next(self):
                self.calls += 1
                if self.calls > 1:
                    release.wait(30)   # producer wedges on the 2nd batch
                return super().next()

        rs = np.random.RandomState(0)
        data = DataSet(rs.rand(64, 4).astype(np.float32),
                       np.eye(2, dtype=np.float32)[rs.randint(0, 2, 64)])
        it = AsyncDataSetIterator(Stuck(data, 4), prefetch_size=1,
                                  reset_timeout_s=0.3)
        assert it.has_next()
        try:
            with pytest.raises(RuntimeError, match="second producer"):
                it.reset()
        finally:
            release.set()   # let the wedged thread die

    def test_reset_tolerates_slow_but_alive_producer(self):
        """A producer that is merely SLOW (heavy per-batch preprocessing)
        re-arms the drain deadline with every batch it delivers — only a
        producer making NO progress for a whole window hard-fails."""
        class Slow(ListDataSetIterator):
            def next(self):
                time.sleep(0.15)           # slower than half the timeout
                return super().next()

        rs = np.random.RandomState(0)
        data = DataSet(rs.rand(32, 4).astype(np.float32),
                       np.eye(2, dtype=np.float32)[rs.randint(0, 2, 32)])
        it = AsyncDataSetIterator(Slow(data, 8), prefetch_size=1,
                                  reset_timeout_s=0.4)
        assert it.has_next()
        it.reset()                         # drains 4 slow batches: no raise
        assert sum(1 for _ in it) == 4

    def test_reset_still_works_on_healthy_producer(self):
        rs = np.random.RandomState(0)
        data = DataSet(rs.rand(32, 4).astype(np.float32),
                       np.eye(2, dtype=np.float32)[rs.randint(0, 2, 32)])
        it = AsyncDataSetIterator(ListDataSetIterator(data, 8))
        n1 = sum(1 for _ in it)
        n2 = sum(1 for _ in it)    # __iter__ resets
        assert n1 == n2 == 4


# ===================================================== flight integration
class TestFlightEvents:
    def test_commit_and_retry_land_in_flight_recorder(self, tmp_path):
        rec = get_flight_recorder()
        rec.clear()
        net = _net()
        cm = CheckpointManager(str(tmp_path), async_save=False,
                               registry=MetricsRegistry())
        net.fit(*_batches(1)[0])
        cm.save(net)
        rp = RetryPolicy(max_retries=1, base_delay_s=0.0,
                         sleep=lambda s: None, registry=MetricsRegistry())
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise TransientError("blip")

        rp.run(flaky)
        kinds = [e.kind for e in rec.events()]
        assert "checkpoint" in kinds and "retry" in kinds
        ckpt = [e for e in rec.events() if e.kind == "checkpoint"
                and e.attrs.get("committed")]
        assert ckpt and ckpt[-1].attrs["step"] == 1
