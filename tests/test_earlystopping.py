"""Early stopping + full-batch solver tests.

Reference: deeplearning4j-core ``earlystopping`` test suites (e.g.
TestEarlyStopping.java patterns: max-epochs termination, score improvement
patience, invalid-score guard, best-model tracking) and the solver dispatch
(``Solver.java``, ``BackTrackLineSearch.java``, ``LBFGS.java``).
"""

import math

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    BestScoreEpochTerminationCondition,
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
    TerminationReason,
)
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize import solvers


def make_net(lr=0.5, algo="stochastic_gradient_descent", iters=1):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(7)
        .updater("sgd", learning_rate=lr)
        .optimization_algo(algo)
        .iterations(iters)
        .list()
        .layer(DenseLayer(n_in=2, n_out=8, activation="tanh", weight_init="xavier"))
        .layer(OutputLayer(n_in=8, n_out=2, loss="mcxent", activation="softmax"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def xor_iter(batch=4):
    x = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
    y = np.array([[1, 0], [0, 1], [0, 1], [1, 0]], np.float32)
    return ListDataSetIterator(DataSet(x, y), batch)


def test_max_epochs_termination():
    net = make_net()
    cfg = (EarlyStoppingConfiguration.Builder()
           .epoch_termination_conditions(MaxEpochsTerminationCondition(5))
           .score_calculator(DataSetLossCalculator(xor_iter()))
           .model_saver(InMemoryModelSaver())
           .build())
    result = EarlyStoppingTrainer(cfg, net, xor_iter()).fit()
    assert result.termination_reason == TerminationReason.EPOCH_TERMINATION_CONDITION
    assert result.total_epochs == 5
    assert result.best_model is not None
    assert len(result.score_vs_epoch) == 5
    # best model score must equal the recorded minimum
    assert math.isclose(result.best_model_score,
                        min(result.score_vs_epoch.values()), rel_tol=1e-9)


def test_score_improvement_patience_stops_on_plateau():
    net = make_net(lr=0.0)  # lr 0 -> score never improves
    cfg = (EarlyStoppingConfiguration.Builder()
           .epoch_termination_conditions(
               ScoreImprovementEpochTerminationCondition(3),
               MaxEpochsTerminationCondition(50))
           .score_calculator(DataSetLossCalculator(xor_iter()))
           .build())
    result = EarlyStoppingTrainer(cfg, net, xor_iter()).fit()
    assert result.termination_reason == TerminationReason.EPOCH_TERMINATION_CONDITION
    assert result.total_epochs <= 6  # plateau detected quickly


def test_max_score_iteration_termination():
    net = make_net()
    cfg = (EarlyStoppingConfiguration.Builder()
           .iteration_termination_conditions(MaxScoreIterationTerminationCondition(1e-9))
           .epoch_termination_conditions(MaxEpochsTerminationCondition(50))
           .score_calculator(DataSetLossCalculator(xor_iter()))
           .build())
    result = EarlyStoppingTrainer(cfg, net, xor_iter()).fit()
    assert result.termination_reason == TerminationReason.ITERATION_TERMINATION_CONDITION


def test_invalid_score_guard():
    c = InvalidScoreIterationTerminationCondition()
    assert c.terminate(float("nan"))
    assert c.terminate(float("inf"))
    assert not c.terminate(1.0)


def test_max_time_condition():
    c = MaxTimeIterationTerminationCondition(0.0)
    c.initialize()
    assert c.terminate(1.0)


def test_best_score_condition_and_local_saver(tmp_path):
    net = make_net(lr=1.0)
    saver = LocalFileModelSaver(str(tmp_path), MultiLayerNetwork)
    cfg = (EarlyStoppingConfiguration.Builder()
           .epoch_termination_conditions(
               BestScoreEpochTerminationCondition(0.3),
               MaxEpochsTerminationCondition(400))
           .score_calculator(DataSetLossCalculator(xor_iter()))
           .model_saver(saver)
           .save_last_model()
           .build())
    result = EarlyStoppingTrainer(cfg, net, xor_iter()).fit()
    assert result.best_model_score < 0.31
    best = saver.get_best_model()
    latest = saver.get_latest_model()
    assert best is not None and latest is not None
    # restored best model reproduces the recorded score
    sc = DataSetLossCalculator(xor_iter()).calculate_score(best)
    assert math.isclose(sc, result.best_model_score, rel_tol=1e-5)


# ---------------------------------------------------------------- solvers

def quadratic(center):
    center = np.asarray(center, np.float64)

    def vg(x):
        d = x - center
        return float(np.dot(d, d)), 2.0 * d

    return vg


def test_lbfgs_minimizes_quadratic():
    x, fx = solvers.lbfgs(quadratic([1.0, -2.0, 3.0]), np.zeros(3), 50)
    assert fx < 1e-8
    np.testing.assert_allclose(x, [1.0, -2.0, 3.0], atol=1e-4)


def test_cg_minimizes_quadratic():
    x, fx = solvers.conjugate_gradient(quadratic([0.5, 0.5]), np.zeros(2), 50)
    assert fx < 1e-8


def test_line_gd_minimizes_quadratic():
    x, fx = solvers.line_gradient_descent(quadratic([2.0]), np.zeros(1), 100)
    assert fx < 1e-6


def test_rosenbrock_lbfgs():
    def vg(x):
        a, b = 1.0, 100.0
        f = (a - x[0]) ** 2 + b * (x[1] - x[0] ** 2) ** 2
        g = np.array([
            -2 * (a - x[0]) - 4 * b * x[0] * (x[1] - x[0] ** 2),
            2 * b * (x[1] - x[0] ** 2),
        ])
        return float(f), g

    x, fx = solvers.lbfgs(vg, np.array([-1.2, 1.0]), 200)
    assert fx < 1e-6


@pytest.mark.parametrize("algo", ["lbfgs", "conjugate_gradient", "line_gradient_descent"])
def test_network_trains_with_solver(algo):
    net = make_net(algo=algo, iters=30)
    x = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
    y = np.array([[1, 0], [0, 1], [0, 1], [1, 0]], np.float32)
    s0 = net.score(x, y)
    net.fit(x, y)  # one call = `iters` solver iterations on the full batch
    net.fit(x, y)
    assert net.score(x, y) < s0


def test_lbfgs_solves_xor_fully():
    net = make_net(algo="lbfgs", iters=100)
    x = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
    y = np.array([[1, 0], [0, 1], [0, 1], [1, 0]], np.float32)
    for _ in range(3):
        net.fit(x, y)
    preds = np.asarray(net.output(x))
    assert (preds.argmax(-1) == y.argmax(-1)).all()


def test_early_stopping_with_computation_graph():
    """The trainer is facade-generic: a ComputationGraph trains, saves, and
    restores through the same early-stopping loop (reference
    EarlyStoppingGraphTrainer)."""
    from deeplearning4j_tpu.models.graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    b = (NeuralNetConfiguration.builder().seed(3)
         .updater("adam", learning_rate=0.1).graph()
         .add_inputs("in")
         .add_layer("h", DenseLayer(n_in=2, n_out=8, activation="tanh"), "in")
         .add_layer("out", OutputLayer(n_in=8, n_out=2), "h")
         .set_outputs("out"))
    net = ComputationGraph(b.build()).init()
    cfg = (EarlyStoppingConfiguration.Builder()
           .epoch_termination_conditions(MaxEpochsTerminationCondition(8))
           .score_calculator(DataSetLossCalculator(xor_iter()))
           .model_saver(InMemoryModelSaver())
           .build())
    result = EarlyStoppingTrainer(cfg, net, xor_iter()).fit()
    assert result.total_epochs == 8
    assert result.best_model is not None
    assert np.isfinite(result.best_model_score)
    scores = list(result.score_vs_epoch.values())
    assert scores[-1] < scores[0]  # xor is learnable by epoch 8


def test_computation_graph_trains_with_lbfgs():
    """CG's solver path (Solver.java dispatch on a DAG facade)."""
    from deeplearning4j_tpu.models.graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    b = (NeuralNetConfiguration.builder().seed(5)
         .optimization_algo("lbfgs").iterations(50).graph()
         .add_inputs("in")
         .add_layer("h", DenseLayer(n_in=2, n_out=8, activation="tanh"), "in")
         .add_layer("out", OutputLayer(n_in=8, n_out=2), "h")
         .set_outputs("out"))
    net = ComputationGraph(b.build()).init()
    x = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
    y = np.array([[1, 0], [0, 1], [0, 1], [1, 0]], np.float32)
    s0 = net.score(x, y)
    net.fit(x, y)
    net.fit(x, y)
    assert net.score(x, y) < s0
