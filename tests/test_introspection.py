"""Training-introspection layer (docs/observability.md "Training
introspection"): device-side per-layer gradient/update/activation
statistics inside the jitted train step, StatsListener harvest into
extended StatsReports, anomaly rules naming the offending layer, SSE /
run-comparison UI endpoints, and crash-safe FileStatsStorage.

Acceptance oracles (ISSUE 12):

- a guarded fit with introspection enabled is BIT-IDENTICAL to an
  introspection-off run with zero recompiles after the first step;
- an injected dying-ReLU layer (large negative bias) is named by layer
  in a dead_fraction health-rule violation + flight event;
- a 4-replica ParallelWrapper run exposes per-replica gradient-norm
  series, and the SSE stream + run-comparison endpoint replay them live
  and post-hoc from a FileStatsStorage reopened after a simulated crash.
"""

import http.client
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.models.graph import ComputationGraph
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import (
    NeuralNetConfiguration, TrainingIntrospection,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.observability import (
    AnomalyMonitor, HealthRule, get_flight_recorder, get_registry,
    introspection,
)
from deeplearning4j_tpu.parallel import (
    DistributedNetwork, ParallelWrapper, SyncTrainingMaster,
)
from deeplearning4j_tpu.ui import (
    FileStatsStorage, InMemoryStatsStorage, StatsListener, StatsReport,
    StatsUpdateConfiguration, UIServer,
)

pytestmark = pytest.mark.introspect


def counter_value(name, **labels):
    fam = get_registry().get(name)
    if fam is None:
        return 0.0
    total = 0.0
    for label_pairs, child in fam.samples():
        d = dict(label_pairs)
        if all(d.get(k) == v for k, v in labels.items()):
            total += child.value
    return total


def flight_events(kind, **attrs):
    out = []
    for ev in get_flight_recorder().events():
        if ev.kind != kind:
            continue
        if all(ev.attrs.get(k) == v for k, v in attrs.items()):
            out.append(ev)
    return out


def make_net(seed=1, intro=True, stab=False, activation="tanh",
             updater="adam"):
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(updater, learning_rate=0.01))
    if intro:
        b.training_introspection()
    if stab:
        b.training_stability()
    conf = (b.list()
            .layer(DenseLayer(n_in=6, n_out=10, activation=activation))
            .layer(OutputLayer(n_in=10, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def batch(seed=0, n=24):
    rs = np.random.RandomState(seed)
    x = rs.rand(n, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, n)]
    return x, y


# ----------------------------------------------------- device-side collection

def test_bit_identical_and_zero_recompiles_guarded():
    """Acceptance: guarded (stability) fit with introspection on is
    bit-identical to introspection-off, with zero recompiles after the
    first step."""
    x, y = batch()
    on = make_net(intro=True, stab=True)
    off = make_net(intro=False, stab=True)
    on.fit(x, y)   # first step compiles
    off.fit(x, y)
    compiles0 = counter_value("dl4j_compiles_total")
    recompiles0 = counter_value("dl4j_recompiles_total")
    for _ in range(6):
        on.fit(x, y)
        off.fit(x, y)
    assert counter_value("dl4j_compiles_total") == compiles0
    assert counter_value("dl4j_recompiles_total") == recompiles0
    for a, b in zip(jax.tree_util.tree_leaves(on.params),
                    jax.tree_util.tree_leaves(off.params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    h = introspection.harvest_model(on)
    assert h["iteration"] == on.iteration - 1
    assert all(np.isfinite(e["norm"]) and e["norm"] > 0
               for e in h["gradient_stats"].values())


def test_unguarded_collection_and_ratio():
    x, y = batch()
    net = make_net(intro=True, stab=False)
    for _ in range(4):
        net.fit(x, y)
    h = introspection.harvest_model(net)
    assert set(h["gradient_stats"]) == {"layer_0", "layer_1"}
    for e in h["update_stats"].values():
        assert e["norm"] > 0 and e["param_norm"] > 0
        assert abs(e["ratio"] - e["norm"] / e["param_norm"]) < 1e-9
    assert h["replicas"] is None
    for e in h["activation_stats"].values():
        assert np.isfinite(e["mean"]) and np.isfinite(e["std"])


def test_graph_facade_collection():
    from deeplearning4j_tpu.models.graph import GraphBuilder

    p = NeuralNetConfiguration.builder().seed(3).updater(
        "adam", learning_rate=0.01)
    p.training_introspection()
    gb = GraphBuilder(p)
    conf = (gb.add_inputs("in")
            .add_layer("dense", DenseLayer(n_in=6, n_out=8,
                                           activation="relu"), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                          activation="softmax"), "dense")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    x, y = batch()
    for _ in range(3):
        net.fit(x, y)
    h = introspection.harvest_model(net)
    assert set(h["gradient_stats"]) == {"dense", "out"}
    assert "dense" in h["activation_stats"]


def test_conf_serde_roundtrip_and_model_save(tmp_path):
    net = make_net(intro=True)
    d = net.conf.to_json()
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

    back = MultiLayerConfiguration.from_json(d)
    assert back.introspection == TrainingIntrospection()
    x, y = batch()
    net.fit(x, y)
    p = str(tmp_path / "model.zip")
    net.save(p)
    loaded = MultiLayerNetwork.load(p)
    assert introspection.STATE_KEY in loaded.updater_state
    # the checkpointed stats travel with the updater state
    assert np.array_equal(
        np.asarray(loaded.updater_state[introspection.STATE_KEY]["packed"]),
        np.asarray(net.updater_state[introspection.STATE_KEY]["packed"]))
    loaded.fit(x, y)   # and the restored net keeps training + collecting
    assert introspection.harvest_model(loaded)["iteration"] == 1


# --------------------------------------------------------------- dead units

def test_dying_relu_named_in_rule_and_flight_event():
    """Acceptance: a large negative bias on a ReLU layer is named by
    layer in a dead_fraction health-rule violation + flight event."""
    net = make_net(seed=7, intro=True, activation="relu")
    # inject the dying layer: bias so negative every pre-activation < 0
    net.params["layer_0"]["b"] = (
        net.params["layer_0"]["b"] - 100.0)
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(storage, session_id="dying"))
    x, y = batch(seed=7)
    for _ in range(3):
        net.fit(x, y)
    rep = storage.get_latest_update("dying")
    assert rep.activation_stats["layer_0"]["zero_fraction"] > 0.99
    # flight event names the layer
    evs = flight_events("introspection_anomaly", rule="max_dead_fraction",
                        layer="layer_0")
    assert evs, "no introspection_anomaly flight event for layer_0"
    # the health-rule kind reads the published gauge and names the layer
    verdict = HealthRule("dead", "max_dead_fraction", 0.5).evaluate(
        get_registry())
    assert not verdict["ok"]
    assert "layer_0" in verdict["detail"]


def test_anomaly_monitor_update_ratio_and_spread():
    mon = AnomalyMonitor(band_low=1e-3, band_high=1e-1,
                         max_gradient_norm_ratio=10.0, warn_interval_s=0.0)
    harvested = {
        "iteration": 5,
        "gradient_stats": {"a": {"norm": 100.0}, "b": {"norm": 1.0}},
        "update_stats": {"a": {"norm": 1.0, "param_norm": 1.0,
                               "ratio": 1.0},      # above band
                         "b": {"norm": 1e-6, "param_norm": 1.0,
                               "ratio": 1e-6}},    # below band
        "activation_stats": {},
    }
    rules = {(v["rule"], v["layer"]) for v in mon.check(harvested)}
    assert ("update_ratio_band", "a") in rules
    assert ("update_ratio_band", "b") in rules
    assert ("max_gradient_norm_ratio", "b") in rules  # names the min layer
    # a skipped (no-op) step is not evidence
    harvested["update_stats"]["a"]["ratio"] = 0.0
    assert ("update_ratio_band", "a") not in {
        (v["rule"], v["layer"]) for v in mon.check(harvested)}


def test_update_ratio_band_health_rule():
    from deeplearning4j_tpu.observability.metrics import MetricsRegistry

    reg = MetricsRegistry()   # isolated: the global one has live layers
    g = reg.gauge("dl4j_layer_update_ratio",
                  "Per-layer update:param norm ratio (test reuse)",
                  labels=("layer",))
    g.set(1e-3, layer="healthy_x")
    g.set(0.9, layer="bouncy_x")
    rule = HealthRule("band", "update_ratio_band", 0.1, limit_low=1e-5)
    verdict = rule.evaluate(reg)
    assert not verdict["ok"]
    assert "bouncy_x" in verdict["detail"]
    g.set(1e-3, layer="bouncy_x")
    assert rule.evaluate(reg)["ok"]
    # a frozen layer (ratio 0) fails the band too
    g.set(0.0, layer="frozen_x")
    verdict = rule.evaluate(reg)
    assert not verdict["ok"] and "frozen_x" in verdict["detail"]
    # gradient-norm spread rule names both extremes
    gn = reg.gauge("dl4j_layer_gradient_norm", "test", labels=("layer",))
    gn.set(100.0, layer="top_x")
    gn.set(1e-6, layer="bottom_x")
    verdict = HealthRule("spread", "max_gradient_norm_ratio",
                         1e3).evaluate(reg)
    assert not verdict["ok"]
    assert "top_x" in verdict["detail"] and "bottom_x" in verdict["detail"]


# --------------------------------------------------------------- parallel

def test_parallel_wrapper_per_replica_series():
    """Acceptance: a 4-replica ParallelWrapper run exposes per-replica
    gradient-norm series."""
    K = 4
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    net = make_net(seed=11, intro=True)
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(storage, session_id="pw"))
    rs = np.random.RandomState(1)
    feats = rs.rand(64, 6).astype(np.float32)
    labs = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 64)]
    recompiles0 = counter_value("dl4j_recompiles_total")
    pw = ParallelWrapper(net, workers=K, averaging_frequency=1, mesh=mesh)
    pw.fit(iter(ListDataSetIterator(DataSet(feats, labs), 8)))
    assert counter_value("dl4j_recompiles_total") == recompiles0
    ups = storage.get_updates("pw")
    assert len(ups) >= 2          # one report per averaging window
    for rep in ups:
        assert rep.replicas == K
        pr = rep.gradient_stats["layer_0"]["per_replica"]
        assert len(pr) == K and all(np.isfinite(v) for v in pr)
    # replicas see different shards -> different per-replica norms
    assert len({round(v, 9) for v in
                ups[0].gradient_stats["layer_0"]["per_replica"]}) > 1


def test_sync_master_collection():
    K = 4
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    net = make_net(seed=13, intro=True)
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(storage, session_id="sm"))
    rs = np.random.RandomState(2)
    feats = rs.rand(32, 6).astype(np.float32)
    labs = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 32)]
    m = SyncTrainingMaster(mesh=mesh)
    DistributedNetwork(net, m).fit(
        ListDataSetIterator(DataSet(feats, labs), 16))
    ups = storage.get_updates("sm")
    assert len(ups) == 2
    # the sync-master gradient is the all-reduced global mean: one
    # cluster-wide (replicated) value per layer, no per-replica axis
    assert ups[-1].replicas is None
    assert ups[-1].gradient_stats["layer_0"]["norm"] > 0


# ------------------------------------------------------------ report serde

def test_stats_report_serde_roundtrip_new_fields():
    rep = StatsReport(
        session_id="s", iteration=3, timestamp=1.5, score=0.25,
        learning_rate=0.01,   # explicit: default NaN breaks == on purpose
        gradient_stats={"l0": {"norm": 0.5, "per_replica": [0.4, 0.6]}},
        update_stats={"l0": {"norm": 0.01, "ratio": 2e-3,
                             "param_norm": 5.0}},
        activation_stats={"l0": {"mean": 0.1, "std": 0.2,
                                 "zero_fraction": 0.3}},
        replicas=2)
    back = StatsReport.from_json(rep.to_json())
    assert back == rep
    # forward compat: unknown fields from a newer writer are dropped
    d = json.loads(rep.to_json())
    d["field_from_the_future"] = {"x": 1}
    tolerant = StatsReport.from_json(json.dumps(d))
    assert tolerant == rep


# ------------------------------------------------------------ file storage

def _fill_storage(path, n=3):
    storage = FileStatsStorage(path)
    net = make_net(seed=5, intro=True)
    net.set_listeners(StatsListener(storage, session_id="filed"))
    x, y = batch(seed=5)
    for _ in range(n):
        net.fit(x, y)
    return storage


def test_file_storage_reload_equals_memory(tmp_path):
    p = str(tmp_path / "stats.jsonl")
    storage = _fill_storage(p)
    reloaded = FileStatsStorage(p)
    assert reloaded.list_session_ids() == storage.list_session_ids()
    mem, disk = storage.get_updates("filed"), reloaded.get_updates("filed")
    assert len(disk) == len(mem)
    for a, b in zip(mem, disk):
        # field-wise (== would trip on the NaN learning_rate default)
        assert (a.iteration, a.score, a.gradient_stats, a.update_stats,
                a.activation_stats, a.param_histograms) == \
               (b.iteration, b.score, b.gradient_stats, b.update_stats,
                b.activation_stats, b.param_histograms)
    assert reloaded.get_init_report("filed") is not None


def test_file_storage_torn_tail_recovered(tmp_path):
    """Satellite: a torn trailing JSONL line (killed writer) must not
    lose the history — skip/truncate with a warning, and the file keeps
    accepting appends afterwards."""
    p = str(tmp_path / "stats.jsonl")
    storage = _fill_storage(p)
    n_good = len(storage.get_updates("filed"))
    with open(p, "ab") as f:   # simulate a writer killed mid-record
        f.write(b'{"type": "update", "session_id": "filed", "iter')
    reloaded = FileStatsStorage(p)   # must NOT raise
    assert len(reloaded.get_updates("filed")) == n_good
    # the torn tail was truncated: a new append produces a valid file
    reloaded.put_update(StatsReport(session_id="filed", iteration=99,
                                    timestamp=time.time()))
    again = FileStatsStorage(p)
    ups = again.get_updates("filed")
    assert len(ups) == n_good + 1 and ups[-1].iteration == 99


def test_file_storage_missing_final_newline_kept(tmp_path):
    p = str(tmp_path / "stats.jsonl")
    FileStatsStorage(p).put_update(StatsReport(
        session_id="s", iteration=1, timestamp=0.0))
    with open(p, "r+b") as f:   # full record, cut newline
        f.seek(0, 2)
        f.truncate(f.tell() - 1)
    reloaded = FileStatsStorage(p)
    assert len(reloaded.get_updates("s")) == 1
    reloaded.put_update(StatsReport(session_id="s", iteration=2,
                                    timestamp=0.0))
    assert len(FileStatsStorage(p).get_updates("s")) == 2


def test_session_id_no_collision():
    ids = {StatsListener(InMemoryStatsStorage()).session_id
           for _ in range(50)}
    assert len(ids) == 50


# ------------------------------------------------------------- UI server

def _get_json(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return json.loads(r.read().decode())


def _sse_collect(port, path, want, timeout_s=15.0):
    """Read SSE events until ``want`` data lines arrived (or timeout)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout_s)
    conn.request("GET", path)
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type").startswith("text/event-stream")
    events = []
    deadline = time.time() + timeout_s
    while len(events) < want and time.time() < deadline:
        line = resp.fp.readline()
        if not line:
            break
        if line.startswith(b"data: "):
            events.append(json.loads(line[6:].decode()))
    conn.close()
    return events


def test_sse_and_compare_under_concurrent_writers(tmp_path):
    """Satellite + acceptance: SSE live stream and the run-comparison
    endpoint under concurrent writers, replayed post-hoc from a
    FileStatsStorage reopened after a simulated crash."""
    p = str(tmp_path / "stats.jsonl")
    storage = FileStatsStorage(p)
    server = UIServer(storage)
    port = server.start()
    try:
        n_each = 6

        def writer(sid, seed):
            net = make_net(seed=seed, intro=True)
            net.set_listeners(StatsListener(storage, session_id=sid))
            x, y = batch(seed=seed)
            for _ in range(n_each):
                net.fit(x, y)

        # live SSE client attaches BEFORE the writers start
        got = {}
        t_sse = threading.Thread(
            target=lambda: got.setdefault("events", _sse_collect(
                port, "/train/stream", want=2 * n_each)),
            daemon=True)
        t_sse.start()
        time.sleep(0.3)
        threads = [threading.Thread(target=writer, args=(sid, seed))
                   for sid, seed in (("run_a", 21), ("run_b", 22))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        t_sse.join(timeout=20)
        events = got.get("events") or []
        sids = {e["session_id"] for e in events}
        assert {"run_a", "run_b"} <= sids
        assert len(events) >= 2 * n_each

        # run comparison overlays both sessions by iteration
        cmp_ = _get_json(
            port, "/train/compare?sids=run_a,run_b&metric=score")
        assert set(cmp_["sessions"]) == {"run_a", "run_b"}
        for s in cmp_["sessions"].values():
            assert len(s["iterations"]) == n_each
        layer_cmp = _get_json(
            port,
            "/train/compare?sids=run_a,run_b&metric=gradient_norm:layer_0")
        assert all(len(s["values"]) == n_each
                   for s in layer_cmp["sessions"].values())
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get_json(port, "/train/compare?sids=a&metric=nope:x")
        assert exc.value.code == 400

        # per-layer drill-down renders a component tree
        detail = _get_json(port, "/train/layer?sid=run_a&layer=layer_0")
        assert detail["componentType"] == "ComponentDiv"
        titles = [c.get("title", "") for c in detail["components"]]
        assert any("gradient norm" in t for t in titles)
    finally:
        server.stop()

    # simulated crash: torn tail appended, storage reopened — post-hoc
    # replay must serve the full history through BOTH endpoints
    with open(p, "ab") as f:
        f.write(b'{"type": "update", "session_id": "run_a"')
    reopened = FileStatsStorage(p)
    server2 = UIServer(reopened)
    port2 = server2.start()
    try:
        cmp2 = _get_json(
            port2, "/train/compare?sids=run_a,run_b&metric=score")
        assert all(len(s["values"]) == n_each
                   for s in cmp2["sessions"].values())
        replay = _sse_collect(
            port2, "/train/stream?sid=run_a&replay=1", want=n_each,
            timeout_s=10)
        assert len(replay) == n_each
        assert [e["iteration"] for e in replay] == sorted(
            e["iteration"] for e in replay)
    finally:
        server2.stop()


def test_introspection_series_endpoint():
    storage = InMemoryStatsStorage()
    server = UIServer(storage)
    port = server.start()
    try:
        net = make_net(seed=31, intro=True)
        net.set_listeners(StatsListener(storage, session_id="ser"))
        x, y = batch(seed=31)
        for _ in range(4):
            net.fit(x, y)
        series = _get_json(port, "/train/introspection?sid=ser")
        assert "layer_0" in series["layers"]
        s = series["series"]["layer_0"]
        assert len(s["gradient_norm"]["values"]) == 4
        assert s["gradient_norm"]["iterations"] == [1, 2, 3, 4]
        assert len(s["update_ratio"]["values"]) == 4
        # no nulls anywhere: every emitted point is chartable
        for entry in s.values():
            assert all(v is not None and np.isfinite(v)
                       for v in entry["values"])
    finally:
        server.stop()
