"""Test harness: 8 virtual CPU devices so multi-chip sharding logic runs
without TPU hardware (the reference's Spark local[N] pattern — SURVEY.md §4:
'multi-node is simulated ... correctness of distribution is proven by
equivalence to local sequential math').

Note: jax may already be imported by the interpreter's sitecustomize (TPU
tunnel registration), so platform selection must go through
``jax.config.update`` (still effective pre-backend-init), not env vars.
"""

import os

# Read by the CPU client at first backend init (lazy), so setting it here
# works even if jax itself is already imported.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
# float64 available for gradient-check precision (tests opt in per-array)
jax.config.update("jax_enable_x64", True)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(12345)
