"""Test harness: 8 virtual CPU devices so multi-chip sharding logic runs
without TPU hardware (the reference's Spark local[N] pattern — SURVEY.md §4:
'multi-node is simulated ... correctness of distribution is proven by
equivalence to local sequential math').

Two tiers:

- default: everything runs on the virtual CPU mesh; tests marked ``tpu``
  are skipped.
- ``DL4J_TPU_TESTS=1 python -m pytest -m tpu``: the real-device tier — the
  platform is left alone (real TPU via the tunnel), only ``tpu``-marked
  tests are meant to run (compiled non-interpret Pallas kernels, donation,
  bf16, one real SyncTrainingMaster step).

Note: jax may already be imported by the interpreter's sitecustomize (TPU
tunnel registration), so platform selection must go through
``jax.config.update`` (still effective pre-backend-init), not env vars.
"""

import os

import pytest

TPU_MODE = os.environ.get("DL4J_TPU_TESTS") == "1"

if not TPU_MODE:
    # Read by the CPU client at first backend init (lazy), so setting it here
    # works even if jax itself is already imported.
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    # float64 available for gradient-check precision (tests opt in per-array)
    jax.config.update("jax_enable_x64", True)
else:
    import jax  # real platform; no x64 (TPUs have no native f64)

import numpy as np


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: must run on a real TPU chip "
        "(DL4J_TPU_TESTS=1 python -m pytest -m tpu)")
    config.addinivalue_line(
        "markers",
        "faults: fault-injection / chaos tests driving the resilience "
        "subsystem (python -m pytest -m faults)")
    config.addinivalue_line(
        "markers",
        "elastic: degraded-mode data parallelism and topology-portable "
        "resharded-resume tests (python -m pytest -m elastic)")
    config.addinivalue_line(
        "markers",
        "profiling: performance-attribution tests — step profiler "
        "captures, XLA cost analysis / MFU gauges, request tracing, bench "
        "regression sentinel (python -m pytest -m profiling)")
    config.addinivalue_line(
        "markers",
        "online: continuous-learning pipeline tests — stream consumption "
        "with quarantine, windowed incremental fit, SLO-gated promotion, "
        "canary, hot-swap watch + automatic rollback "
        "(python -m pytest -m online)")
    config.addinivalue_line(
        "markers",
        "lint: source-level static-analysis gates — the dl4jlint rule "
        "suite, its ratcheting baseline, and the metrics-docs/"
        "bench-sentinel shims (python -m pytest -m lint)")
    config.addinivalue_line(
        "markers",
        "stability: training-stability engine tests — device-side "
        "non-finite step guard, loss scaling, divergence sentinel with "
        "auto-rewind, per-replica poison masking "
        "(python -m pytest -m stability)")
    config.addinivalue_line(
        "markers",
        "introspect: training-introspection tests — device-side "
        "per-layer gradient/update/activation stats, anomaly rules, "
        "SSE/run-comparison UI endpoints, crash-safe stats storage "
        "(python -m pytest -m introspect)")
    config.addinivalue_line(
        "markers",
        "zero: ZeRO update-sharding tests — reduce-scatter/all-gather "
        "decomposition of the weight update, sharded updater state, "
        "replicated-vs-ZeRO oracles, projection-vs-actual ledger, "
        "checkpoint interop (python -m pytest -m zero)")
    config.addinivalue_line(
        "markers",
        "generation: continuous-batching generation-engine tests — "
        "paged KV cache with prefix sharing, iteration-level join/leave "
        "scheduling, zero-recompile decode, hot-swap under decode load, "
        "streaming HTTP surface (python -m pytest -m generation)")
    config.addinivalue_line(
        "markers",
        "numerics: precision-observability tests — the in-graph "
        "precision ledger (dynamic-range stats, format-safety verdicts, "
        "spike drill), KV-page range stats, and the kernel-trust "
        "differential harness (python -m pytest -m numerics)")
    config.addinivalue_line(
        "markers",
        "prefix_cache: persistent radix-tree prefix-cache tests — "
        "cross-request KV reuse, pinning, host-tier offload/restore "
        "round-trips, cache-aware admission, invalidation-on-swap, and "
        "the seeded cache-invariant fuzzer "
        "(python -m pytest -m prefix_cache)")
    config.addinivalue_line(
        "markers",
        "fleet: fleet telemetry plane tests — cross-process metrics "
        "federation (schema-versioned snapshots, epoch/seq delta merge, "
        "staleness), decode SLO attribution (TTFT/ITL/goodput, phase "
        "breakdown), and the router-facing cache stats surface "
        "(python -m pytest -m fleet)")
    config.addinivalue_line(
        "markers",
        "kernels: fused-kernel tests — the Pallas paged decode-attention "
        "kernel (lax + interpret impls vs the gather oracle, engine-level "
        "parity) and the fused dropout/residual/norm train epilogue "
        "(parity, grads, dropout-mask bit-identity) "
        "(python -m pytest -m kernels)")
    config.addinivalue_line(
        "markers",
        "fleet_router: serving-fleet control-plane tests — cache-aware "
        "placement (prefix affinity, seeded ties, canary split), "
        "health-gated membership, SIGKILL failover with queued-request "
        "retry and session re-pin, fleet-wide canary rollout with "
        "auto-rollback, replica supervisor lifecycle "
        "(python -m pytest -m fleet_router)")


def pytest_collection_modifyitems(config, items):
    if TPU_MODE:
        skip = pytest.mark.skip(
            reason="CPU-tier test skipped in real-TPU mode (run without "
                   "DL4J_TPU_TESTS for the full suite)")
        for item in items:
            if "tpu" not in item.keywords:
                item.add_marker(skip)
    else:
        skip = pytest.mark.skip(reason="requires a real TPU "
                                       "(DL4J_TPU_TESTS=1 -m tpu)")
        for item in items:
            if "tpu" in item.keywords:
                item.add_marker(skip)


@pytest.fixture
def rng():
    return np.random.RandomState(12345)
