"""Real-TPU test tier (``DL4J_TPU_TESTS=1 python -m pytest -m tpu``).

The decisive on-chip facts the CPU tier cannot prove (≙ the reference's
``CuDNNGradientChecks.java:66,114-122`` — helper-vs-builtin parity executed
on the accelerator):

- Pallas kernels compile and run NON-interpreted, matching the stock XLA
  math forward and backward.
- The jitted train step runs with buffer donation on HBM.
- bf16 mixed precision executes on the MXU with fp32 master params.
- A mesh-placed SyncTrainingMaster step executes on the chip.
- Streaming rnnTimeStep and ring attention produce device-correct results.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.tpu


def _lrn_reference(x, k, n, alpha, beta):
    """Stock XLA formula: y = x * (k + alpha * window_sum(x^2))^-beta."""
    half = n // 2
    C = x.shape[-1]
    sq = x * x
    acc = jnp.zeros_like(x)
    for w in range(-half, half + 1):
        lo, hi = max(0, -w), min(C, C - w)
        acc = acc.at[..., lo:hi].add(sq[..., lo + w : hi + w])
    return x * jnp.power(k + alpha * acc, -beta)


def test_on_tpu():
    assert jax.devices()[0].platform == "tpu"


def test_pallas_lrn_forward_compiled():
    from deeplearning4j_tpu.helpers import pallas_ops

    assert not pallas_ops._interpret(), "must compile for real on TPU"
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(32, 96).astype(np.float32) + 0.1)
    got = pallas_ops.lrn(x, 2.0, 5, 1e-4, 0.75)
    want = _lrn_reference(x, 2.0, 5, 1e-4, 0.75)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_pallas_lrn_backward_compiled():
    from deeplearning4j_tpu.helpers import pallas_ops

    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.rand(16, 64).astype(np.float32) + 0.1)

    g_pallas = jax.grad(lambda a: pallas_ops.lrn(a, 2.0, 5, 1e-4, 0.75).sum())(x)
    g_ref = jax.grad(lambda a: _lrn_reference(a, 2.0, 5, 1e-4, 0.75).sum())(x)
    np.testing.assert_allclose(np.asarray(g_pallas), np.asarray(g_ref),
                               rtol=2e-4, atol=2e-6)


def test_pallas_bn_inference_compiled():
    from deeplearning4j_tpu.helpers import pallas_ops

    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.rand(64, 48).astype(np.float32))
    mean = jnp.asarray(rs.rand(48).astype(np.float32))
    var = jnp.asarray(rs.rand(48).astype(np.float32) + 0.5)
    gamma = jnp.asarray(rs.rand(48).astype(np.float32))
    beta = jnp.asarray(rs.rand(48).astype(np.float32))
    got = pallas_ops.bn_inference(x, mean, var, gamma, beta, 1e-5)
    want = (x - mean) * jax.lax.rsqrt(var + 1e-5) * gamma + beta
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_pallas_bn_training_compiled():
    from deeplearning4j_tpu.helpers import pallas_ops

    rs = np.random.RandomState(12)
    x = jnp.asarray(rs.randn(32, 24).astype(np.float32))
    gamma = jnp.asarray(rs.randn(24).astype(np.float32))
    beta = jnp.asarray(rs.randn(24).astype(np.float32))
    y, mean, var = pallas_ops.bn_training(x, gamma, beta, 1e-5)
    m, v = x.mean(0), x.var(0)
    want = gamma * (x - m) * jax.lax.rsqrt(v + 1e-5) + beta
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-3, atol=1e-4)
    g = jax.grad(lambda a: pallas_ops.bn_training(a, gamma, beta, 1e-5)[0].sum())(x)
    g_ref = jax.grad(lambda a: (gamma * (a - a.mean(0))
                                * jax.lax.rsqrt(a.var(0) + 1e-5) + beta).sum())(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-2, atol=1e-4)


def test_lenet_train_step_loss_decreases():
    from deeplearning4j_tpu.models.zoo import lenet

    net = lenet(updater="nesterovs", lr=0.01)
    rs = np.random.RandomState(3)
    x = rs.rand(64, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, 64)]
    net.fit(x, y)
    first = net.score_value
    for _ in range(10):
        net.fit(x, y)
    assert net.score_value < first


def test_train_step_donates_buffers():
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(4)
         .updater("sgd", learning_rate=0.1).list()
         .layer(DenseLayer(n_in=8, n_out=16))
         .layer(OutputLayer(n_in=16, n_out=4)).build())).init()
    rs = np.random.RandomState(5)
    x = rs.rand(16, 8).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 16)]
    old_w = net.params["layer_0"]["W"]
    net.fit(x, y)  # jitted step has donate_argnums=(0,1,2)
    assert old_w.is_deleted(), "param buffers must be donated on TPU"


def test_bf16_mixed_precision_on_mxu():
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(6)
         .updater("adam", learning_rate=0.01).list()
         .compute_dtype("bfloat16")
         .layer(DenseLayer(n_in=32, n_out=64, activation="relu"))
         .layer(OutputLayer(n_in=64, n_out=4)).build())).init()
    rs = np.random.RandomState(7)
    x = rs.rand(32, 32).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 32)]
    for _ in range(5):
        net.fit(x, y)
    assert net.params["layer_0"]["W"].dtype == jnp.float32
    assert np.isfinite(net.score_value)
    out = np.asarray(net.output(x))
    assert out.dtype == np.float32


def test_sync_training_master_step_on_chip():
    from deeplearning4j_tpu.backend import device as backend
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
    from deeplearning4j_tpu.models.zoo import lenet
    from deeplearning4j_tpu.parallel import DistributedNetwork, SyncTrainingMaster

    net = lenet()
    mesh = backend.default_mesh(devices=jax.devices()[:1])
    rs = np.random.RandomState(8)
    x = rs.rand(32, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, 32)]
    DistributedNetwork(net, SyncTrainingMaster(mesh=mesh)).fit(
        ListDataSetIterator(DataSet(x, y), 32))
    assert np.isfinite(net.score_value)


def test_rnn_time_step_on_chip():
    from deeplearning4j_tpu.models.zoo import graves_lstm_char_lm

    net = graves_lstm_char_lm(vocab_size=11, hidden=16, layers=1)
    rs = np.random.RandomState(9)
    ids = rs.randint(0, 11, (2, 4))
    x = np.eye(11, dtype=np.float32)[ids]
    full = np.asarray(net.output(x))
    net.rnn_clear_previous_state()
    for t in range(4):
        step = np.asarray(net.rnn_time_step(x[:, t]))
        np.testing.assert_allclose(full[:, t], step, rtol=1e-4, atol=1e-5)


def test_ring_attention_local_matches_exact():
    from deeplearning4j_tpu.backend import device as backend
    from deeplearning4j_tpu.nn.layers.attention import dot_product_attention
    from deeplearning4j_tpu.parallel import ring_self_attention
    from jax.sharding import Mesh

    dev = np.array(jax.devices()[:1]).reshape(1, 1, 1)
    mesh = Mesh(dev, (backend.AXIS_DATA, backend.AXIS_MODEL, backend.AXIS_SEQ))
    rs = np.random.RandomState(10)
    q = jnp.asarray(rs.rand(2, 8, 2, 4).astype(np.float32))
    k = jnp.asarray(rs.rand(2, 8, 2, 4).astype(np.float32))
    v = jnp.asarray(rs.rand(2, 8, 2, 4).astype(np.float32))
    got = ring_self_attention(q, k, v, mesh, causal=True)
    want = dot_product_attention(q, k, v, causal=True)
    # TPU einsums accumulate at the MXU's default (bf16-input) precision, so
    # the two op orders agree only to ~1e-3 relative — that is chip-expected
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-3, atol=2e-3)


def test_resnet_cifar_step_bf16():
    from deeplearning4j_tpu.models.zoo import resnet50

    net = resnet50(height=32, width=32, stem_stride=1, n_classes=10,
                   blocks=(1, 1, 1, 1), compute_dtype="bfloat16")
    rs = np.random.RandomState(11)
    x = {"input": rs.rand(16, 32, 32, 3).astype(np.float32)}
    y = {"fc": np.eye(10, dtype=np.float32)[rs.randint(0, 10, 16)]}
    net.fit(x, y)
    assert np.isfinite(net.score_value)


def test_flash_attention_compiled_parity():
    """The flash kernel compiled on the chip (non-interpret) matches the
    XLA einsum path fwd+bwd to MXU default-precision tolerance, and beats
    it on step time at the flagship shape (the reason it exists)."""
    from deeplearning4j_tpu.helpers import flash_attention as fa
    from deeplearning4j_tpu.nn.layers.attention import dot_product_attention

    rs = np.random.RandomState(12)
    q, k, v = (jnp.asarray(rs.randn(2, 512, 4, 64).astype(np.float32) * 0.3)
               for _ in range(3))
    ref = jax.jit(lambda q, k, v: dot_product_attention(q, k, v, causal=True))(q, k, v)
    out = jax.jit(lambda q, k, v: fa.flash_attention(q, k, v, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=5e-3, atol=2e-3)

    def loss(attn, q, k, v):
        return jnp.sum(attn(q, k, v) ** 2)

    gr = jax.jit(jax.grad(lambda *a: loss(
        lambda q, k, v: dot_product_attention(q, k, v, causal=True), *a),
        argnums=(0, 1, 2)))(q, k, v)
    gf = jax.jit(jax.grad(lambda *a: loss(
        lambda q, k, v: fa.flash_attention(q, k, v, causal=True), *a),
        argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", gr, gf):
        # flash's delta=Σ(dO·O) vs autodiff's Σ(p·dp): same math, different
        # rounding — individual near-cancelled elements disagree at ~1e-2 of
        # the gradient scale on the MXU (both are equally far from the f64
        # truth; verified when the kernel landed)
        scale = float(jnp.max(jnp.abs(a))) + 1e-9
        np.testing.assert_allclose(np.asarray(b) / scale, np.asarray(a) / scale,
                                   atol=2e-2, err_msg=f"d{name}")


def test_flash_attention_beats_xla_at_scale():
    """bq512/bk1024 fwd+bwd at B8 T2048 D128 bf16 must be faster than the
    unfused einsum path (measured 3.4x on v5e; assert a conservative >1.2x
    so tunnel jitter doesn't flake the tier)."""
    import time

    from deeplearning4j_tpu.helpers import flash_attention as fa
    from deeplearning4j_tpu.nn.layers.attention import dot_product_attention

    rs = np.random.RandomState(13)
    q, k, v = (jnp.asarray(rs.randn(8, 2048, 8, 128).astype(np.float32) * 0.3,
                           dtype=jnp.bfloat16) for _ in range(3))

    def bench(attn):
        g = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(attn(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2)))
        out = g(q, k, v)
        np.asarray(jax.device_get(out[0][0, 0, 0, :1]))
        t0 = time.perf_counter()
        for _ in range(10):
            out = g(q, k, v)
        np.asarray(jax.device_get(out[0][0, 0, 0, :1]))
        return (time.perf_counter() - t0) / 10

    t_xla = bench(lambda q, k, v: dot_product_attention(q, k, v, causal=True))
    t_flash = bench(lambda q, k, v: fa.flash_attention(q, k, v, causal=True))
    assert t_flash < t_xla / 1.2, (
        f"flash {t_flash*1e3:.2f}ms not faster than XLA {t_xla*1e3:.2f}ms")


def test_ulysses_flash_composes_with_shard_map():
    """Compiled flash attention under shard_map (1-device 'seq' mesh): the
    multi-host Ulysses path routes its local attention through the Pallas
    kernel on TPU — this is the composition a pod run depends on."""
    from jax.sharding import Mesh

    from deeplearning4j_tpu.backend import device as backend
    from deeplearning4j_tpu.nn.layers.attention import dot_product_attention
    from deeplearning4j_tpu.parallel import ring_self_attention

    devs = np.array(jax.devices()[:1]).reshape(1, 1, 1)
    mesh = Mesh(devs, (backend.AXIS_DATA, backend.AXIS_MODEL, backend.AXIS_SEQ))
    rng = np.random.default_rng(14)
    q = jnp.asarray(rng.standard_normal((2, 512, 4, 64)), jnp.float32) * 0.3
    k = jnp.asarray(rng.standard_normal((2, 512, 4, 64)), jnp.float32) * 0.3
    v = jnp.asarray(rng.standard_normal((2, 512, 4, 64)), jnp.float32)
    got = ring_self_attention(q, k, v, mesh, causal=True, impl="ulysses")
    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-3, atol=2e-3)


def test_flash_attention_windowed_compiled_parity():
    """Sliding-window flash compiled on the chip matches the banded einsum
    path — the two-sided index clamps must be Mosaic-correct, not just
    interpreter-correct."""
    from deeplearning4j_tpu.helpers import flash_attention as fa
    from deeplearning4j_tpu.nn.layers.attention import dot_product_attention

    rs = np.random.RandomState(15)
    q, k, v = (jnp.asarray(rs.randn(2, 1024, 4, 64).astype(np.float32) * 0.3)
               for _ in range(3))
    for window in (128, 700):
        ref = jax.jit(lambda q, k, v, w=window: dot_product_attention(
            q, k, v, causal=True, window=w))(q, k, v)
        out = jax.jit(lambda q, k, v, w=window: fa.flash_attention(
            q, k, v, causal=True, window=w))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=5e-3, atol=2e-3,
                                   err_msg=f"window={window}")
        gr = jax.jit(jax.grad(lambda q, k, v, w=window: jnp.sum(
            dot_product_attention(q, k, v, causal=True, window=w) ** 2),
            argnums=(0, 1, 2)))(q, k, v)
        gf = jax.jit(jax.grad(lambda q, k, v, w=window: jnp.sum(
            fa.flash_attention(q, k, v, causal=True, window=w) ** 2),
            argnums=(0, 1, 2)))(q, k, v)
        for name, a, b in zip("qkv", gr, gf):
            scale = float(jnp.max(jnp.abs(a))) + 1e-9
            np.testing.assert_allclose(
                np.asarray(b) / scale, np.asarray(a) / scale, atol=2e-2,
                err_msg=f"d{name} window={window}")


def test_compiled_decode_scan_on_chip():
    """Round 5: the one-XLA-program decode (prefill + lax.scan + sampling)
    compiles and runs on the chip; greedy determinism across calls."""
    from deeplearning4j_tpu.models.decode import generate
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    net = transformer_char_lm(vocab_size=64, d_model=64, n_heads=4,
                              layers=2, max_cache=128,
                              compute_dtype="bfloat16")
    rs = np.random.RandomState(0)
    prompt = rs.randint(0, 64, (4, 8))
    a = generate(net, prompt, 32, temperature=0.0)
    b = generate(net, prompt, 32, temperature=0.0)
    assert a.shape == (4, 32)
    np.testing.assert_array_equal(a, b)


def test_scanned_fit_amortizes_dispatch_floor_on_chip():
    """Round-3 task 7's on-chip 'done' gate: with the K-step lax.scan
    window in place, the amortized step must beat the per-step path (the
    ~1 ms host/tunnel dispatch floor, PROFILE.md) — and by enough to call
    the floor amortized, not noise."""
    import time

    from deeplearning4j_tpu.models.zoo import lenet

    net = lenet(updater="nesterovs", lr=0.01)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(128, 28, 28, 1).astype(np.float32))
    y = jnp.asarray(np.eye(10, dtype=np.float32)[rs.randint(0, 10, 128)])

    step = net._get_train_step()
    s = [net.params, net.updater_state, net.net_state]
    loss = None
    for _ in range(5):
        s[0], s[1], s[2], loss, _ = step(s[0], s[1], s[2], jnp.zeros(()),
                                         x, y, net._keys.next(),
                                         None, None, None)
    np.asarray(jax.device_get(loss))
    t0 = time.perf_counter()
    for _ in range(50):
        s[0], s[1], s[2], loss, _ = step(s[0], s[1], s[2], jnp.zeros(()),
                                         x, y, net._keys.next(),
                                         None, None, None)
    np.asarray(jax.device_get(loss))
    per_step = (time.perf_counter() - t0) / 50

    K = 32
    scanned = net._make_scanned_step()
    xs = jnp.broadcast_to(x, (K,) + x.shape)
    ys = jnp.broadcast_to(y, (K,) + y.shape)
    ss = [s[0], s[1], s[2]]
    keys = lambda: jnp.stack([net._keys.next() for _ in range(K)])
    ss[0], ss[1], ss[2], l = scanned(ss[0], ss[1], ss[2], jnp.zeros(()),
                                     xs, ys, keys())
    np.asarray(jax.device_get(l))
    t0 = time.perf_counter()
    for _ in range(5):
        ss[0], ss[1], ss[2], l = scanned(ss[0], ss[1], ss[2], jnp.zeros(()),
                                         xs, ys, keys())
    np.asarray(jax.device_get(l))
    amortized = (time.perf_counter() - t0) / 5 / K

    assert amortized < per_step * 0.5, (
        f"scan should amortize the dispatch floor: per-step "
        f"{per_step*1e3:.3f} ms vs amortized {amortized*1e3:.3f} ms")
