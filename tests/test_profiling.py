"""Performance attribution layer: StepProfiler captures (step-N trigger,
straggler trigger in a real 4-replica ParallelWrapper run, watchdog
trigger), XLA cost analysis through the RecompileDetector seam, MFU /
roofline / step-flops gauges, recompile flight events with cost deltas,
memory attribution in flight dumps, and the capture disk budget."""

import json
import os

import numpy as np
import pytest

from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers.dense import DenseLayer, OutputLayer
from deeplearning4j_tpu.observability import (
    FlightRecorder, MetricsRegistry, SpanTracer, StepProfiler, StepWatchdog,
    get_registry, get_tracer, set_flight_recorder, set_registry, set_tracer,
    step_guard,
)
from deeplearning4j_tpu.observability import profiling
from deeplearning4j_tpu.observability import flightrecorder as fr_mod
from deeplearning4j_tpu.observability.flightrecorder import (
    dump_flight_report, get_flight_recorder, read_flight_report,
)
from deeplearning4j_tpu.observability.recompile import instrument

pytestmark = pytest.mark.profiling


@pytest.fixture(autouse=True)
def fresh_telemetry():
    """Isolate registry/tracer/flight recorder AND the installed profiler
    per test."""
    old_reg = get_registry()
    old_tr = get_tracer()
    reg = set_registry(MetricsRegistry())
    set_tracer(SpanTracer())
    set_flight_recorder(FlightRecorder())
    yield reg
    prof = profiling.active_profiler()
    if prof is not None:
        prof.uninstall()
    wd = fr_mod.get_watchdog()
    if wd is not None:
        wd.uninstall()
    set_registry(old_reg)
    set_tracer(old_tr)
    set_flight_recorder(FlightRecorder())


def make_net(seed=7, n_in=8):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(seed)
         .updater("sgd", learning_rate=0.1).list()
         .layer(DenseLayer(n_in=n_in, n_out=16))
         .layer(OutputLayer(n_in=16, n_out=4)).build())).init()


def make_batches(n, n_in=8, batch=16, seed=0):
    rs = np.random.RandomState(seed)
    return [(rs.rand(batch, n_in).astype(np.float32),
             np.eye(4, dtype=np.float32)[rs.randint(0, 4, batch)])
            for _ in range(n)]


def flight_events(kind):
    return [e.to_dict() for e in get_flight_recorder().events()
            if e.kind == kind]


# ----------------------------------------------------------- cost analysis

def test_jit_cost_analysis_abstract():
    """Cost analysis lowers at the abstract signature: flops/bytes come
    back positive and no concrete buffer is needed."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda a, b: (a @ b).sum())
    x = jnp.ones((64, 32))
    y = jnp.ones((32, 16))
    cost = profiling.jit_cost_analysis(f, (x, y), {})
    assert cost["flops"] > 0
    assert cost["bytes_accessed"] > 0


def test_peak_flops_table_and_cpu_estimate():
    peak, source = profiling.peak_flops_for()
    assert peak > 0
    assert source in ("table", "cpu-estimate")
    # every table entry is a plausible positive FLOP/s
    assert all(v > 1e12 for v in profiling.PEAK_FLOPS.values())


def test_cost_cached_per_signature(tmp_path):
    """The detector cost-analyzes once per NEW signature; repeat calls
    reuse the cache, and every dispatch counts into the flops counter."""
    import jax
    import jax.numpy as jnp

    calls = []
    orig = profiling.jit_cost_analysis

    def counting(fn, args, kwargs):
        calls.append(1)
        return orig(fn, args, kwargs)

    profiling.jit_cost_analysis, restore = counting, orig
    try:
        with StepProfiler(str(tmp_path)):
            f = instrument(jax.jit(lambda a: (a * 2.0).sum()), "unit.cached")
            x = jnp.ones((16, 4))
            for _ in range(3):
                f(x)
        assert len(calls) == 1          # one analysis for one signature
        flops1 = get_registry().get_value("dl4j_step_flops_total",
                                          fn="unit.cached")
        assert flops1 > 0
        per_call = f.detector.last_cost["flops"]
        assert flops1 == pytest.approx(3 * per_call)
    finally:
        profiling.jit_cost_analysis = restore


# -------------------------------------------- acceptance: fit-run capture

def test_fit_capture_step_and_mfu(tmp_path):
    """Acceptance: a fit run with StepProfiler(capture_step=3) produces a
    readable trace file and populates dl4j_model_flops_utilization with a
    finite value in (0, 1]."""
    prof = StepProfiler(str(tmp_path / "prof"), capture_step=3).install()
    net = make_net()
    net.fit(make_batches(5))

    mfu = get_registry().get_value("dl4j_model_flops_utilization",
                                   component="MultiLayerNetwork")
    assert mfu is not None and np.isfinite(mfu)
    assert 0.0 < mfu <= 1.0
    flops = get_registry().get_value("dl4j_step_flops_total",
                                     fn="MultiLayerNetwork.train_step")
    assert flops > 0
    bpf = get_registry().get_value("dl4j_step_bytes_per_flop",
                                   component="MultiLayerNetwork")
    assert bpf > 0

    # exactly one capture, named in the flight recorder
    caps = flight_events("profile_capture")
    assert len(caps) == 1
    assert caps[0]["reason"] == "step:3"
    assert caps[0]["step"] == "fit_step"
    cap_dir = caps[0]["path"]
    # readable Chrome-trace file with the step's host spans
    doc = json.load(open(os.path.join(cap_dir, "host_spans.trace.json")))
    names = [e["name"] for e in doc["traceEvents"] if e["ph"] == "X"]
    assert "fit_step" in names
    meta = json.load(open(os.path.join(cap_dir, "capture.json")))
    assert meta["flops"] > 0 and 0.0 < meta["mfu"] <= 1.0
    assert prof.capture_paths == [cap_dir]
    assert get_registry().get_value("dl4j_profile_captures_total",
                                    reason="step") == 1


def test_capture_disk_budget(tmp_path):
    """Oldest capture directories are deleted once the budget is
    exceeded; the newest capture always survives."""
    prof = StepProfiler(str(tmp_path), max_disk_bytes=1,
                        use_jax_profiler=False).install()
    for i in range(3):
        prof.request_capture(f"manual:{i}")
        with step_guard("fit_step", model="Unit", iteration=i):
            pass
    survivors = sorted(d for d in os.listdir(tmp_path)
                       if d.startswith("cap-"))
    assert survivors == ["cap-0003-manual-2"]
    assert len(prof.capture_paths) == 3   # all three were written


def test_watchdog_dump_arms_capture(tmp_path):
    """Capture-on-watchdog: a watchdog dump arms the profiler, and the
    next guarded step is captured with a watchdog reason."""
    prof = StepProfiler(str(tmp_path / "prof"),
                        use_jax_profiler=False).install()
    wd = StepWatchdog(deadline_s=60.0,
                      report_dir=str(tmp_path / "diag")).install()
    wd.dump("hang", step="fit_step")
    with step_guard("fit_step", model="Unit", iteration=9):
        pass
    caps = flight_events("profile_capture")
    assert len(caps) == 1
    assert caps[0]["reason"] == "watchdog:hang"
    wd.uninstall()


# ------------------------------- acceptance: straggler-triggered capture

def test_straggler_verdict_triggers_capture(tmp_path, monkeypatch):
    """Acceptance: a straggler verdict in a 4-replica ParallelWrapper run
    triggers an automatic capture named in the flight recorder."""
    import jax

    from deeplearning4j_tpu.backend import device as backend
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper

    K = 4
    real = ParallelWrapper._worker_step_times

    def slowed(self, losses, dispatch_s):
        times = real(self, losses, dispatch_s)
        times["2"] = times["2"] + 0.05   # worker 2 is 'slow'
        return times

    monkeypatch.setattr(ParallelWrapper, "_worker_step_times", slowed)
    prof = StepProfiler(str(tmp_path), use_jax_profiler=False,
                        cost_analysis=False).install()
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    net = make_net(n_in=6)
    rs = np.random.RandomState(1)
    batches = [DataSet(rs.rand(4, 6).astype(np.float32),
                       np.eye(4, dtype=np.float32)[rs.randint(0, 4, 4)])
               for _ in range(K * 8)]
    pw = ParallelWrapper(net, workers=K, averaging_frequency=1, mesh=mesh,
                         collect_worker_stats=True)
    pw.fit(iter(batches))

    assert "2" in pw.straggler_detector.stragglers()
    caps = flight_events("profile_capture")
    assert caps, "straggler verdict did not trigger a capture"
    assert caps[0]["reason"] == "straggler:parallel_wrapper:2"
    assert caps[0]["step"] == "parallel_window"
    assert flight_events("profile_requested")
    assert get_registry().get_value("dl4j_profile_captures_total",
                                    reason="straggler") >= 1


# --------------------------------------------- recompile cost flight event

def test_unexpected_recompile_dumps_signature_and_cost(tmp_path):
    """Satellite: an unexpected recompile leaves a flight event with the
    new abstract signature and its flops/bytes delta vs the evicted
    signature — not just a counter bump."""
    import jax
    import jax.numpy as jnp

    with StepProfiler(str(tmp_path)):
        f = instrument(jax.jit(lambda a: (a @ a.T).sum()), "unit.recomp")
        f(jnp.ones((8, 8), jnp.float32))
        f(jnp.ones((16, 8), jnp.float32))   # unexpected shape change
    evs = flight_events("recompile")
    assert len(evs) == 1
    ev = evs[0]
    assert ev["fn"] == "unit.recomp"
    assert "f32[16,8]" in ev["signature"]
    assert "f32[8,8]" in ev["evicted_signature"]
    assert ev["flops"] > ev["evicted_flops"] > 0
    assert ev["flops_delta"] == pytest.approx(
        ev["flops"] - ev["evicted_flops"])
    assert ev["bytes_delta"] > 0


def test_recompile_event_without_profiler_still_names_signature():
    """Cost analysis is profiler-gated, but the signature dump is not."""
    import jax
    import jax.numpy as jnp

    f = instrument(jax.jit(lambda a: a.sum()), "unit.nocost")
    f(jnp.ones((4,), jnp.float32))
    f(jnp.ones((6,), jnp.float32))
    evs = flight_events("recompile")
    assert len(evs) == 1
    assert "f32[6]" in evs[0]["signature"]
    assert "flops" not in evs[0]


# -------------------------------------------------- memory attribution

def test_model_memory_breakdown():
    net = make_net()
    net.fit(make_batches(1))   # materialize updater state
    br = profiling.model_memory_breakdown(net)
    assert br["params_bytes"] > 0
    assert br["total_bytes"] >= br["params_bytes"]
    assert br["top_leaves"][0]["bytes"] >= br["top_leaves"][-1]["bytes"]
    paths = {l["path"] for l in br["top_leaves"]}
    assert any("w" in p or "W" in p for p in paths)


def test_live_buffer_snapshot():
    import jax.numpy as jnp

    keep = jnp.ones((128, 128))   # noqa: F841 — held live on purpose
    snap = profiling.live_buffer_snapshot()
    assert snap["total_bytes"] >= keep.nbytes
    assert snap["count"] >= 1
    assert snap["top"][0]["bytes"] > 0


def test_flight_dump_contains_memory_attribution(tmp_path):
    """Watchdog/crash dumps show WHAT held memory: live buffers plus the
    tracked model's per-leaf breakdown."""
    prof = StepProfiler(str(tmp_path / "prof"),
                        use_jax_profiler=False).install()
    net = make_net()
    net.fit(make_batches(2))
    path = str(tmp_path / "report.jsonl")
    dump_flight_report(path, "unit-test")
    records = read_flight_report(path)
    mem = [r for r in records if r["record"] == "memory_attribution"]
    assert len(mem) == 1
    assert mem[0]["live_buffers"]["total_bytes"] > 0
    assert mem[0]["models"]["MultiLayerNetwork"]["params_bytes"] > 0


def test_step_peak_memory_gauge_or_graceful(tmp_path):
    """On PJRT backends the per-step peak gauge fills; on CPU (no memory
    stats) it simply never appears — either way the step must not fail."""
    with StepProfiler(str(tmp_path)):
        net = make_net()
        net.fit(make_batches(2))
    fam = get_registry().get("dl4j_step_peak_memory_bytes")
    from deeplearning4j_tpu.observability.memory import device_memory_stats

    if device_memory_stats():
        assert fam is not None and fam.samples()
    # registered lazily only when stats exist; absence is the CPU case


# ------------------------------------------------------ chrome trace export

def test_chrome_trace_export_roundtrip(tmp_path):
    tracer = get_tracer()
    with tracer.span("outer", trace_id="t1"):
        with tracer.span("inner"):
            pass
    path = str(tmp_path / "trace.json")
    n = tracer.export_chrome_trace(path)
    assert n == 2
    doc = json.load(open(path))
    evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in evs} == {"outer", "inner"}
    outer = next(e for e in evs if e["name"] == "outer")
    assert outer["args"]["trace_id"] == "t1"
    assert outer["dur"] >= 0
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert metas and metas[0]["name"] == "thread_name"
