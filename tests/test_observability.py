"""Unified telemetry core: registry semantics, Prometheus rendering, span
nesting + JSONL round-trip, recompile detection, device-memory gauges,
serving /metrics, and the fit-loop smoke contract (tier-1: a fit must
record nonzero step-time metrics)."""

import json
import math
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.layers.dense import DenseLayer, OutputLayer
from deeplearning4j_tpu.observability import (
    DeviceMemoryMonitor, MetricsRegistry, SpanTracer, fingerprint,
    get_registry, instrument, sample_once, set_registry,
)
from deeplearning4j_tpu.observability.phases import PhaseTimers
from deeplearning4j_tpu.observability.recompile import RecompileDetector


@pytest.fixture(autouse=True)
def fresh_registry():
    """Isolate each test's metrics; restore the shared registry after."""
    old = get_registry()
    reg = set_registry(MetricsRegistry())
    yield reg
    set_registry(old)


def make_net(seed=7):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(seed)
         .updater("sgd", learning_rate=0.1).list()
         .layer(DenseLayer(n_in=8, n_out=16))
         .layer(OutputLayer(n_in=16, n_out=4)).build())).init()


def make_data(n=32, rs=None):
    rs = rs or np.random.RandomState(0)
    x = rs.rand(n, 8).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, n)]
    return x, y


# ------------------------------------------------------------- registry

def test_counter_semantics(fresh_registry):
    c = fresh_registry.counter("t_total", "help here")
    c.inc()
    c.inc(2.5)
    assert fresh_registry.get_value("t_total") == 3.5
    with pytest.raises(ValueError):
        c.labels().inc(-1)


def test_labeled_children_are_independent(fresh_registry):
    fam = fresh_registry.counter("req_total", labels=("status",))
    fam.inc(status="ok")
    fam.inc(status="ok")
    fam.inc(status="error")
    assert fam.labels(status="ok").value == 2
    assert fam.labels(status="error").value == 1
    with pytest.raises(ValueError):
        fam.labels(wrong="x")


def test_gauge_set_function_and_lazy_value(fresh_registry):
    g = fresh_registry.gauge("queue_depth")
    items = [1, 2, 3]
    g.set_function(lambda: len(items))
    assert fresh_registry.get_value("queue_depth") == 3
    items.pop()
    assert fresh_registry.get_value("queue_depth") == 2
    # lazy device scalar: float() deferred to read
    import jax.numpy as jnp

    g2 = fresh_registry.gauge("lazy_score")
    g2.set(jnp.asarray(1.5))
    assert fresh_registry.get_value("lazy_score") == 1.5


def test_histogram_semantics(fresh_registry):
    h = fresh_registry.histogram("lat_seconds", buckets=(0.1, 1.0, 10.0)
                                 ).labels()
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    assert h.count == 5
    assert h.sum == pytest.approx(56.05)
    assert h.min == pytest.approx(0.05)
    assert h.max == pytest.approx(50.0)
    cum = dict(h.cumulative_buckets())
    assert cum[0.1] == 1 and cum[1.0] == 3 and cum[10.0] == 4
    assert cum[math.inf] == 5


def test_reregistration_is_idempotent_and_kind_checked(fresh_registry):
    a = fresh_registry.counter("same_name")
    b = fresh_registry.counter("same_name")
    assert a is b
    with pytest.raises(ValueError):
        fresh_registry.gauge("same_name")
    with pytest.raises(ValueError):
        fresh_registry.counter("same_name", labels=("x",))


def test_registry_thread_safety(fresh_registry):
    c = fresh_registry.counter("contended_total").labels()

    def work():
        for _ in range(1000):
            c.inc()

    threads = [threading.Thread(target=work) for _ in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert c.value == 8000


# ----------------------------------------------------------- prometheus

def test_prometheus_rendering(fresh_registry):
    fresh_registry.counter("c_total", "a counter",
                           labels=("k",)).inc(2, k='va"l')
    fresh_registry.gauge("g", "a gauge").set(1.5)
    h = fresh_registry.histogram("h_seconds", "a histogram",
                                 buckets=(0.5, 1.0))
    h.observe(0.25)
    h.observe(0.75)
    text = fresh_registry.to_prometheus()
    assert "# HELP c_total a counter" in text
    assert "# TYPE c_total counter" in text
    assert 'c_total{k="va\\"l"} 2' in text
    assert "g 1.5" in text
    assert 'h_seconds_bucket{le="0.5"} 1' in text
    assert 'h_seconds_bucket{le="1"} 2' in text
    assert 'h_seconds_bucket{le="+Inf"} 2' in text
    assert "h_seconds_count 2" in text
    assert "h_seconds_sum 1" in text


def test_json_snapshot_round_trips(fresh_registry):
    fresh_registry.counter("j_total").inc(3)
    h = fresh_registry.histogram("j_seconds").labels()
    h.observe(0.01)
    snap = json.loads(fresh_registry.to_json_str())
    assert snap["j_total"]["values"][0]["value"] == 3
    assert snap["j_seconds"]["values"][0]["count"] == 1


# -------------------------------------------------------------- tracing

def test_span_nesting_and_jsonl_round_trip(tmp_path):
    tr = SpanTracer(max_spans=64)
    with tr.span("outer", kind="test") as outer:
        with tr.span("inner") as inner:
            pass
        with tr.span("inner2"):
            pass
    path = str(tmp_path / "spans.jsonl")
    n = tr.export_jsonl(path)
    assert n == 3
    spans = {s.name: s for s in SpanTracer.read_jsonl(path)}
    assert spans["inner"].parent_id == spans["outer"].span_id
    assert spans["inner2"].parent_id == spans["outer"].span_id
    assert spans["outer"].parent_id is None
    assert spans["outer"].attrs == {"kind": "test"}
    # children finish before (and within) the parent: monotonic clocks
    assert spans["outer"].duration_ns >= spans["inner"].duration_ns
    assert spans["outer"].start_ns <= spans["inner"].start_ns
    assert spans["inner"].end_ns <= spans["outer"].end_ns


def test_tracer_bounded_buffer():
    tr = SpanTracer(max_spans=4)
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    assert len(tr.spans()) == 4
    assert tr.dropped == 6
    assert [s.name for s in tr.spans()] == ["s6", "s7", "s8", "s9"]


# ------------------------------------------------------------ recompile

def test_recompile_detector_fires_once_per_new_signature(fresh_registry):
    import jax
    import jax.numpy as jnp

    warns = []
    fn = instrument(jax.jit(lambda a: a.sum()), "toy.step",
                    warn=warns.append)
    a32 = jnp.zeros((32, 8))
    a20 = jnp.zeros((20, 8))
    fn(a32)
    fn(a32)
    assert fn.detector.compile_count == 1 and warns == []
    fn(a20)                       # new signature -> exactly one warning
    assert fn.detector.compile_count == 2 and len(warns) == 1
    assert "32,8" in warns[0] and "20,8" in warns[0]
    fn(a20)                       # seen signature -> silent
    fn(a32)                       # seen signature -> silent
    assert len(warns) == 1
    # dtype churn is a recompile too
    fn(jnp.zeros((32, 8), jnp.bfloat16))
    assert fn.detector.compile_count == 3 and len(warns) == 2
    # counters mirrored in the registry
    assert fresh_registry.get_value("dl4j_compiles_total", fn="toy.step") == 3
    assert fresh_registry.get_value("dl4j_recompiles_total",
                                    fn="toy.step") == 2


def test_fingerprint_distinguishes_structure():
    import jax.numpy as jnp

    a = jnp.zeros((4,))
    assert fingerprint((a,), {}) == fingerprint((a,), {})
    assert fingerprint((a,), {}) != fingerprint(({"k": a},), {})
    assert fingerprint((a,), {}) != fingerprint((a.astype(jnp.int32),), {})


def test_instrumented_jit_delegates_aot_workflow():
    import jax
    import jax.numpy as jnp

    fn = instrument(jax.jit(lambda a: a * 2), "toy.aot")
    lowered = fn.lower(jnp.zeros((3,)))   # attribute delegation
    compiled = lowered.compile()
    np.testing.assert_allclose(np.asarray(compiled(jnp.ones((3,)))), 2.0)


# --------------------------------------------------------- phase timers

def test_phase_timers_schema_and_registry(fresh_registry):
    pt = PhaseTimers("unit_test")
    for _ in range(3):
        with pt.phase("work"):
            pass
    pt.steps = 3
    d = pt.as_dict()
    assert d["steps"] == 3
    w = d["phases"]["work"]
    assert w["count"] == 3
    assert w["total_ms"] >= w["mean_ms"] >= 0.0
    assert w["max_ms"] >= w["min_ms"]
    fam = fresh_registry.get("dl4j_phase_seconds")
    child = fam.get(component="unit_test", phase="work")
    assert child.count == 3
    # disabled timers record nothing
    off = PhaseTimers("off_test", enabled=False)
    with off.phase("x"):
        pass
    assert off.as_dict()["phases"] == {}


# -------------------------------------------------------- device memory

def test_device_memory_sampling_graceful(fresh_registry):
    stats = sample_once(fresh_registry)   # CPU: typically no stats — no-op
    assert isinstance(stats, dict)
    fam = fresh_registry.get("dl4j_device_memory_bytes")
    if stats:
        assert fam is not None
        dev, per = next(iter(stats.items()))
        stat = next(k for k, v in per.items() if v is not None)
        assert fam.get(device=dev, stat=stat) is not None
    mon = DeviceMemoryMonitor(interval_s=0.05, registry=fresh_registry)
    mon.start()
    import time

    time.sleep(0.15)
    mon.stop()
    assert mon.samples >= 1


# ---------------------------------------------------- fit loop contract

def test_fit_records_step_metrics_smoke(fresh_registry):
    """Tier-1 smoke: a fit run must record nonzero step-time metrics,
    iteration counters, and compile counts (acceptance criteria)."""
    net = make_net()
    x, y = make_data(32)
    for _ in range(3):
        net.fit(x, y)
    reg = fresh_registry
    assert reg.get_value("dl4j_fit_iterations_total",
                         model="MultiLayerNetwork") == 3
    hist = reg.get("dl4j_fit_step_seconds").get(model="MultiLayerNetwork")
    assert hist.count == 3 and hist.sum > 0
    assert reg.get_value("dl4j_compiles_total",
                         fn="MultiLayerNetwork.train_step") == 1
    assert reg.get_value("dl4j_fit_batch_size",
                         model="MultiLayerNetwork") == 32
    sps = reg.get_value("dl4j_fit_samples_per_second",
                        model="MultiLayerNetwork")
    assert sps and sps > 0
    text = reg.to_prometheus()
    assert "dl4j_fit_step_seconds_bucket" in text
    assert "dl4j_fit_iterations_total" in text
    assert "dl4j_compiles_total" in text


def test_fit_shape_change_warns_exactly_once(fresh_registry):
    """Acceptance: a batch-shape change mid-run emits ONE warning carrying
    the old -> new signature."""
    from deeplearning4j_tpu.observability import recompile as rc

    warns = []
    orig = rc.logger.warning
    rc.logger.warning = lambda msg, *a: warns.append(msg % a if a else msg)
    try:
        net = make_net()
        x, y = make_data(32)
        net.fit(x, y)
        net.fit(x, y)
        net.fit(x[:20], y[:20])   # shape change -> one warning
        net.fit(x[:20], y[:20])   # same shape again -> silent
    finally:
        rc.logger.warning = orig
    mine = [w for w in warns if "MultiLayerNetwork.train_step" in w]
    assert len(mine) == 1
    assert "32,8" in mine[0] and "20,8" in mine[0]
    assert fresh_registry.get_value(
        "dl4j_recompiles_total", fn="MultiLayerNetwork.train_step") == 1


def test_performance_listener_auto_batch_size(fresh_registry):
    from deeplearning4j_tpu.optimize.listeners import PerformanceListener

    pl = PerformanceListener(frequency=100)
    net = make_net()
    x, y = make_data(16)
    for _ in range(3):
        net.fit(x, y)
    # no manual set_batch_size call anywhere: the fit loop wired it
    assert pl.last_samples_per_sec is None  # not attached yet -> untouched
    net.set_listeners(pl)
    for _ in range(3):
        net.fit(x, y)
    assert pl.last_samples_per_sec is not None
    assert pl.last_samples_per_sec > 0
    assert net.last_batch_size == 16


def test_scanned_fit_listener_gets_window_samples(fresh_registry):
    """Listeners fire once per scanned window, so the wired batch size is
    the WINDOW's sample count (else samples/sec under-reports by
    scan_steps) while the telemetry batch-size gauge keeps the per-step
    minibatch size."""
    net = make_net()
    x, y = make_data(16)
    net.fit_scanned([(x, y)] * 4, scan_steps=4)
    assert net.last_batch_size == 16 * 4
    assert fresh_registry.get_value("dl4j_fit_batch_size",
                                    model="MultiLayerNetwork") == 16
    assert fresh_registry.get_value("dl4j_fit_iterations_total",
                                    model="MultiLayerNetwork") == 4


def test_stats_timing_is_per_model_instance(fresh_registry):
    """Fit loops stamp last_step_seconds on the model instance, so two
    same-class models never read each other's timing."""
    a, b = make_net(1), make_net(2)
    x, y = make_data(16)
    a.fit(x, y)
    assert getattr(a, "last_step_seconds", None)
    assert not hasattr(b, "last_step_seconds")


def test_score_listener_tolerates_missing_score():
    from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener

    class Bare:
        pass

    logs = []
    lst = ScoreIterationListener(print_iterations=1, log=logs.append)
    lst.iteration_done(Bare(), 1)   # must not raise
    assert "nan" in logs[0]


def test_graph_fit_records_metrics(fresh_registry):
    from deeplearning4j_tpu.models.graph import ComputationGraph

    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater("sgd", learning_rate=0.1)
            .graph()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=8, n_out=16), "in")
            .add_layer("out", OutputLayer(n_in=16, n_out=4), "d")
            .set_outputs("out").build())
    net = ComputationGraph(conf).init()
    x, y = make_data(16)
    net.fit(x, y)
    assert fresh_registry.get_value("dl4j_fit_iterations_total",
                                    model="ComputationGraph") == 1
    hist = fresh_registry.get("dl4j_fit_step_seconds").get(
        model="ComputationGraph")
    assert hist.count == 1 and hist.sum > 0


def test_sync_master_phases_in_registry(fresh_registry):
    from deeplearning4j_tpu.backend import device as backend
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
    from deeplearning4j_tpu.parallel.training_master import (
        DistributedNetwork, SyncTrainingMaster,
    )

    net = make_net()
    x, y = make_data(64, np.random.RandomState(3))
    master = SyncTrainingMaster(mesh=backend.default_mesh(),
                                collect_stats=True)
    DistributedNetwork(net, master).fit(
        ListDataSetIterator(DataSet(x, y), 16))
    stats = master.training_stats()
    assert stats["steps"] == 4
    assert set(stats["phases"]) >= {"fetch", "place", "dispatch",
                                    "device_sync"}
    # the same timings landed in the shared registry
    fam = fresh_registry.get("dl4j_phase_seconds")
    assert fam is not None
    assert fam.get(component="sync_master", phase="dispatch").count >= 4
    assert fresh_registry.get_value(
        "dl4j_compiles_total", fn="SyncTrainingMaster.step") == 1


# -------------------------------------------------------------- serving

def test_inference_server_metrics_endpoint(fresh_registry):
    from deeplearning4j_tpu.streaming.serving import InferenceServer

    net = make_net()
    server = InferenceServer(net, max_batch=8, port=0)
    port = server.start()
    try:
        url = f"http://127.0.0.1:{port}"
        body = json.dumps(np.random.rand(3, 8).tolist()).encode()
        req = urllib.request.Request(
            f"{url}/predict", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
            assert r.status == 200
            ctype = r.headers.get("Content-Type", "")
            text = r.read().decode()
        assert ctype.startswith("text/plain")
        assert 'dl4j_serving_requests_total{status="ok"} 1' in text
        assert "dl4j_serving_request_seconds_bucket" in text
        assert "dl4j_serving_queue_depth" in text
        assert "dl4j_serving_batch_rows" in text
        # in-process path shares the same counters
        server.predict(np.random.rand(2, 8).astype(np.float32))
        assert fresh_registry.get_value("dl4j_serving_requests_total",
                                        status="ok") == 2
    finally:
        server.stop()


def test_stats_listener_reads_registry_timing(fresh_registry):
    from deeplearning4j_tpu.ui.stats import StatsListener, StatsUpdateConfiguration

    class MemStorage:
        def __init__(self):
            self.updates = []

        def put_init_report(self, rep):
            pass

        def put_update(self, rep):
            self.updates.append(rep)

    storage = MemStorage()
    net = make_net()
    net.set_listeners(StatsListener(
        storage, config=StatsUpdateConfiguration(
            collect_histograms_params=False, collect_memory=False,
            collect_mean_magnitudes=False)))
    x, y = make_data(16)
    for _ in range(3):
        net.fit(x, y)
    assert storage.updates
    rep = storage.updates[-1]
    # timing comes from the shared registry (set by the fit loop), so it is
    # nonzero from the FIRST report (the old clock needed two iterations)
    assert storage.updates[0].iteration_time_ms > 0
    assert rep.iteration_time_ms > 0
    assert rep.samples_per_second > 0
