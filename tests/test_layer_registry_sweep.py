"""Registry-wide invariants: EVERY registered layer type round-trips its
config through the subtype registry (the Jackson @JsonSubTypes contract,
reference custom-layer tests ``nn/layers/custom/``) and, when parameterised,
initialises + applies with matching shapes.

A sweep rather than per-layer tests: a newly registered layer gets this
coverage automatically or fails loudly here.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deeplearning4j_tpu.nn.layers  # noqa: F401 — populate the registry
from deeplearning4j_tpu.nn.layers import base

# minimal constructor kwargs per type (sizes chosen tiny); None = defaults ok
_KWARGS = {
    "ActivationLayer": dict(activation="relu"),
    "AutoEncoder": dict(n_in=6, n_out=4),
    "BatchNormalization": dict(n_out=5),
    "ConvolutionLayer": dict(n_in=2, n_out=3, kernel_size=(3, 3)),
    "DenseLayer": dict(n_in=4, n_out=3),
    "DropoutLayer": dict(dropout=0.5),
    "EmbeddingLayer": dict(n_in=7, n_out=4),
    "GlobalPoolingLayer": dict(),
    "GravesBidirectionalLSTM": dict(n_in=3, n_out=4),
    "GravesLSTM": dict(n_in=3, n_out=4),
    "LSTM": dict(n_in=3, n_out=4),
    "LayerNorm": dict(n_in=5),
    "LocalResponseNormalization": dict(),
    "MoELayer": dict(n_in=4, n_out=4, num_experts=2),
    "OutputLayer": dict(n_in=4, n_out=3),
    "RBM": dict(n_in=6, n_out=4),
    "ResidualBlock": None,  # composite: exercised in test_mixed/test_graph
    "RnnOutputLayer": dict(n_in=4, n_out=3),
    "SelfAttentionLayer": dict(n_in=4, n_out=4, n_heads=2),
    "SubsamplingLayer": dict(kernel_size=(2, 2), stride=(2, 2)),
}

# input shape per type for the apply smoke (batch of 2)
_INPUTS = {
    "ActivationLayer": (2, 5),
    "AutoEncoder": (2, 6),
    "BatchNormalization": (2, 5),
    "ConvolutionLayer": (2, 6, 6, 2),
    "DenseLayer": (2, 4),
    "DropoutLayer": (2, 5),
    "EmbeddingLayer": (2, 3),          # integer ids
    "GlobalPoolingLayer": (2, 4, 4, 3),
    "GravesBidirectionalLSTM": (2, 5, 3),
    "GravesLSTM": (2, 5, 3),
    "LSTM": (2, 5, 3),
    "LayerNorm": (2, 5),
    "LocalResponseNormalization": (2, 4, 4, 3),
    "MoELayer": (2, 4),
    "OutputLayer": (2, 4),
    "RBM": (2, 6),
    "RnnOutputLayer": (2, 5, 4),
    "SelfAttentionLayer": (2, 5, 4),
    "SubsamplingLayer": (2, 6, 6, 2),
}


def _make(name):
    kwargs = _KWARGS[name]
    if kwargs is None:
        pytest.skip("composite covered elsewhere")
    return base._LAYER_REGISTRY[name](name=f"t_{name}", **kwargs)


def test_registry_covers_sweep():
    """The sweep tables must track the registry exactly — a new layer type
    has to add itself here (and thereby gain the invariants below)."""
    assert set(_KWARGS) == set(base._LAYER_REGISTRY), (
        set(_KWARGS) ^ set(base._LAYER_REGISTRY))


@pytest.mark.parametrize("name", sorted(_KWARGS))
def test_config_round_trips(name):
    layer = _make(name)
    d = layer.to_dict()
    assert d["type"] == name
    back = base.layer_from_dict(d)
    assert back.to_dict() == d


@pytest.mark.parametrize("name", sorted(_KWARGS))
def test_init_and_apply_smoke(name):
    layer = _make(name)
    layer.validate()
    key = jax.random.key(0)
    params = layer.init(key) if layer.has_params() else {}
    state = layer.init_state() or {}
    shape = _INPUTS[name]
    rs = np.random.RandomState(0)
    if name == "EmbeddingLayer":
        x = jnp.asarray(rs.randint(0, 7, shape).astype(np.float32))
    else:
        x = jnp.asarray(rs.rand(*shape).astype(np.float32))
    if hasattr(layer, "apply_with_carry"):
        y, _, carry = layer.apply_with_carry(params, state, x, None,
                                             train=False, rng=None)
    else:
        y, _ = layer.apply(params, state, x, train=False, rng=None)
    y = np.asarray(y)
    assert np.isfinite(y).all(), name
    assert y.shape[0] == shape[0], name


@pytest.mark.parametrize("updater", ["sgd", "nesterovs", "adagrad",
                                     "rmsprop", "adadelta", "adam"])
def test_every_updater_trains_finite(updater):
    """Updater sweep: each rule initialises state, applies one step, and
    moves params without NaN (reference UpdaterCreator zoo)."""
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(1)
         .updater(updater, learning_rate=0.05).list()
         .layer(DenseLayer(n_in=4, n_out=8))
         .layer(OutputLayer(n_in=8, n_out=2)).build())).init()
    rs = np.random.RandomState(0)
    x = rs.rand(8, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 8)]
    before = net.params_to_vector()
    net.fit(x, y)
    net.fit(x, y)
    after = net.params_to_vector()
    assert np.isfinite(after).all(), updater
    assert not np.allclose(before, after), updater
    assert np.isfinite(net.score_value), updater
