"""Memory & collective-communication observability (`observability/
shardstats.py`): HLO collective census, sharding ledger, comm roofline.

Acceptance oracles from the PR issue:

- analytic oracle: for K-replica data parallel on the virtual CPU mesh,
  HLO-counted all-reduce bytes per step == parameter(+averaged updater)
  bytes within dtype/fusion tolerance, and the ledger's updater-state
  replication factor == K;
- pipeline master's per-stage ledger sums to the single-device total;
- on a 4-replica ParallelWrapper run: ≥1 all-reduce censused, zero
  extra recompiles in steady state, `GET /memory` serves the ledger;
- flight-recorder dumps carry a `sharding_ledger` record;
- the per-dispatch hook cost is bounded (the <2% bench-overhead budget).
"""

import json
import time
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.observability import shardstats
from deeplearning4j_tpu.observability.metrics import MetricsRegistry
from deeplearning4j_tpu.observability.shardstats import (
    ShardStatsCollector, attribute_mesh_axes, collective_census,
    format_ledger, link_bandwidth_for, program_analysis, record_ledger,
    ring_wire_bytes, sharding_ledger,
)


def param_bytes(tree, itemsize=4):
    return sum(int(np.asarray(l).size) * itemsize
               for l in jax.tree_util.tree_leaves(tree))


def dense_net(n_in=12, hidden=32, n_out=4, updater="adam", seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(updater, learning_rate=0.01).list()
            .layer(DenseLayer(n_in=n_in, n_out=hidden, activation="tanh"))
            .layer(OutputLayer(n_in=hidden, n_out=n_out, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def class_data(n, n_in=12, n_out=4, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.rand(n, n_in).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rs.randint(0, n_out, n)]
    return DataSet(x, y)


# ---------------------------------------------------------------- census unit
def test_census_counts_and_sizes_ops():
    hlo = """
  %all-reduce = f32[16,8]{1,0} all-reduce(f32[16,8]{1,0} %dot), channel_id=1, replica_groups=[1,4]<=[4], to_apply=%add
  %all-reduce.1 = f32[] all-reduce(f32[] %b), channel_id=2, replica_groups=[1,4]<=[4], to_apply=%add
  %ag = f32[32,8]{1,0} all-gather(f32[8,8]{1,0} %x), channel_id=3, replica_groups={{0,1,2,3}}, dimensions={0}
  %rs = f32[8,8]{1,0} reduce-scatter(f32[32,8]{1,0} %y), channel_id=4, replica_groups=[1,4]<=[4], to_apply=%add
  %cp = f32[4,4]{1,0} collective-permute(f32[4,4]{1,0} %z), channel_id=5, source_target_pairs={{0,1},{1,0}}
  ROOT %fused = f32[16,8]{1,0} fusion(f32[16,8]{1,0} %all-reduce, f32[8,8]{1,0} %rs), kind=kLoop
"""
    census = collective_census(hlo)
    assert census["all-reduce"]["count"] == 2
    assert census["all-reduce"]["bytes"] == 16 * 8 * 4 + 4
    assert census["all-reduce"]["group_sizes"] == [4]
    # all-gather payload is the GATHERED tensor (result > operand)
    assert census["all-gather"]["bytes"] == 32 * 8 * 4
    assert census["all-gather"]["group_sizes"] == [4]   # explicit groups
    # reduce-scatter payload is the PRE-scatter tensor (operand > result)
    assert census["reduce-scatter"]["bytes"] == 32 * 8 * 4
    assert census["collective-permute"]["bytes"] == 4 * 4 * 4
    # the fusion line referencing %all-reduce must NOT count
    assert sum(e["count"] for e in census.values()) == 5


def test_census_async_start_counts_once_without_double_bytes():
    hlo = """
  %ar-start = (f32[256]{0}, f32[256]{0}) all-reduce-start(f32[256]{0} %g), channel_id=1, replica_groups=[1,8]<=[8], to_apply=%add
  %ar-done = f32[256]{0} all-reduce-done((f32[256]{0}, f32[256]{0}) %ar-start)
"""
    census = collective_census(hlo)
    assert census["all-reduce"]["count"] == 1
    assert census["all-reduce"]["bytes"] == 256 * 4   # not 2x
    assert census["all-reduce"]["group_sizes"] == [8]


def test_census_tpu_tiled_layouts_and_variadic_tuples():
    """Post-layout TPU HLO carries tile annotations with parens inside
    the layout braces and fuses logical all-reduces into variadic ops
    with tuple results — both must still be counted."""
    hlo = """
  %fused-ar = (f32[1024]{0:T(1024)}, f32[512]{0:T(512)}) all-reduce(f32[1024]{0:T(1024)} %a, f32[512]{0:T(512)} %b), replica_groups=[1,4]<=[4], to_apply=%add
  %ar-start = (f32[256]{0:T(256)}, f32[256]{0:T(256)}) all-reduce-start(f32[256]{0:T(256)} %g), replica_groups=[1,8]<=[8], to_apply=%add
  %tiled = f32[8,128]{1,0:T(8,128)} all-gather(f32[2,128]{1,0:T(8,128)} %x), replica_groups=[1,4]<=[4], dimensions={0}
"""
    census = collective_census(hlo)
    assert census["all-reduce"]["count"] == 2
    # variadic: tuple result = sum of both payloads; -start: one payload
    assert census["all-reduce"]["bytes"] == (1024 + 512) * 4 + 256 * 4
    assert census["all-gather"]["bytes"] == 8 * 128 * 4
    assert sorted(census["all-reduce"]["group_sizes"]) == [4, 8]


def test_census_dtype_sizes_and_empty():
    hlo = "%ar = bf16[10]{0} all-reduce(bf16[10]{0} %g), replica_groups=[1,2]<=[2]"
    assert collective_census(hlo)["all-reduce"]["bytes"] == 20
    assert collective_census("ROOT %r = f32[8]{0} add(...)") == {}


def test_attribute_mesh_axes():
    census = {"all-reduce": {"count": 1, "bytes": 4, "group_sizes": [4]},
              "all-gather": {"count": 1, "bytes": 4, "group_sizes": [2]}}
    attr = attribute_mesh_axes(census, {"data": 4, "model": 2})
    assert attr == {"all-reduce": ["data"], "all-gather": ["model"]}
    # ambiguous sizes stay unattributed
    attr = attribute_mesh_axes(census, {"data": 4, "model": 4})
    assert attr["all-reduce"] == []


def test_ring_wire_bytes_recipe():
    assert ring_wire_bytes("all-reduce", 100.0, 4) == pytest.approx(150.0)
    assert ring_wire_bytes("all-gather", 100.0, 4) == pytest.approx(75.0)
    assert ring_wire_bytes("reduce-scatter", 100.0, 4) == pytest.approx(75.0)
    assert ring_wire_bytes("collective-permute", 100.0, 4) == 100.0
    assert ring_wire_bytes("all-reduce", 100.0, None) == 100.0  # lower bound


def test_link_bandwidth_sources():
    bw, src = link_bandwidth_for()
    assert src in ("table", "cpu-estimate")
    assert bw > 0

    class FakeTPU:
        device_kind = "TPU v5 lite"
        platform = "tpu"

    bw, src = link_bandwidth_for(FakeTPU())
    assert (bw, src) == (shardstats.LINK_BANDWIDTH["TPU v5"], "table")


# -------------------------------------------------------- program analysis
def test_program_analysis_counts_grad_allreduce_exactly():
    """The canonical DP shape: replicated params, sharded batch — the
    gradient all-reduce payload must equal the parameter bytes."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    repl, data = NamedSharding(mesh, P()), NamedSharding(mesh, P("data"))

    def loss(params, x):
        return jnp.mean((jnp.tanh(x @ params["w1"]) @ params["w2"]) ** 2)

    def step(params, x):
        g = jax.grad(loss)(params, x)
        return jax.tree_util.tree_map(lambda p, gg: p - 0.1 * gg, params, g)

    f = jax.jit(step, in_shardings=({"w1": repl, "w2": repl}, data))
    params = jax.device_put({"w1": jnp.zeros((8, 16), jnp.float32),
                             "w2": jnp.zeros((16, 4), jnp.float32)},
                            {"w1": repl, "w2": repl})
    x = jax.device_put(jnp.zeros((16, 8), jnp.float32), data)
    analysis = program_analysis(f, (params, x), {})
    pb = (8 * 16 + 16 * 4) * 4
    assert analysis["collectives"]["all-reduce"]["bytes"] == pb
    assert analysis["collectives"]["all-reduce"]["group_sizes"] == [4]
    assert analysis["memory"]["argument"] > 0
    assert analysis["flops"] > 0


def test_program_analysis_preserves_argument_shardings():
    """A jit WITHOUT in_shardings gets its layout from the arguments —
    absifying must carry the NamedSharding or the partitioner compiles a
    collective-free single-device program."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    data = NamedSharding(mesh, P("data"))
    f = jax.jit(lambda a: jnp.sum(a, 0))        # cross-device reduction
    a = jax.device_put(jnp.ones((4, 64)), data)
    analysis = program_analysis(f, (a,), {})
    assert analysis.get("collective_bytes", 0) > 0


def test_program_analysis_never_executes_or_consumes(monkeypatch):
    """Donation safety: analysis lowers abstractly, so a donated-argnums
    jit can be analyzed and then still dispatched with the same arrays."""
    f = jax.jit(lambda a: a + 1.0, donate_argnums=(0,))
    a = jnp.ones((32,))
    analysis = program_analysis(f, (a,), {})
    assert analysis["memory"]["argument"] == 32 * 8 or \
        analysis["memory"]["argument"] == 32 * 4   # x64 on/off
    out = f(a)   # the buffer is still live — analysis did not consume it
    assert float(out[0]) == 2.0


# ------------------------------------------------------------------- ledger
def test_ledger_replicated_vs_sharded_vs_stacked():
    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    repl, data = NamedSharding(mesh, P()), NamedSharding(mesh, P("data"))
    replicated = jax.device_put(jnp.zeros((8, 8), jnp.float32), repl)
    sharded = jax.device_put(jnp.zeros((8, 8), jnp.float32), data)
    led = sharding_ledger({"r": {"w": replicated}, "s": {"w": sharded}},
                          data_axis_size=4)
    r, s = led["trees"]["r"], led["trees"]["s"]
    assert r["replication_factor"] == 4.0
    assert r["per_device_bytes"] == 256
    assert s["replication_factor"] == 1.0
    assert s["per_device_bytes"] == 64
    # ZeRO projection: replicated tree would drop to logical/K per device
    assert r["zero_projected_per_device_bytes"] == 64
    assert r["zero_savings_per_device_bytes"] == 256 - 64
    # stacked replica view measured against the logical single tree
    stacked = jax.device_put(jnp.zeros((4, 8, 8), jnp.float32), data)
    led = sharding_ledger({"u": stacked},
                          logical_trees={"u": jnp.zeros((8, 8),
                                                        jnp.float32)},
                          data_axis_size=4)
    assert led["trees"]["u"]["replication_factor"] == 4.0
    # subtree rows ride along for dict trees
    led = sharding_ledger({"params": {"l0": {"w": replicated},
                                      "l1": {"w": sharded}}})
    subs = led["trees"]["params"]["subtrees"]
    assert subs["l0"]["replication_factor"] == 4.0
    assert subs["l1"]["replication_factor"] == 1.0


def test_ledger_handles_host_arrays_and_non_arrays():
    led = sharding_ledger({"params": {"w": np.zeros((4, 4), np.float32),
                                      "flag": True, "name": "x"}})
    row = led["trees"]["params"]
    assert row["logical_bytes"] == 64
    assert row["replication_factor"] == 1.0


def test_format_ledger_is_readable():
    led = sharding_ledger({"params": {"w": np.zeros((64, 64), np.float32)}},
                          data_axis_size=4)
    txt = format_ledger(led, "unit")
    assert "sharding ledger — unit" in txt
    assert "params" in txt and "TOTAL" in txt


def test_record_ledger_sets_gauges_and_flight_event():
    from deeplearning4j_tpu.observability.flightrecorder import (
        get_flight_recorder,
    )

    reg = MetricsRegistry()
    record_ledger("unit_test", {"params": {"w": np.zeros((8,), np.float32)}},
                  registry=reg)
    snap = reg.to_json()
    vals = {(v["labels"]["component"], v["labels"]["tree"]): v["value"]
            for v in snap["dl4j_sharded_bytes"]["values"]}
    assert vals[("unit_test", "params")] == 32.0
    assert shardstats.latest_ledgers()["unit_test"]["trees"]["params"]
    kinds = [e.kind for e in get_flight_recorder().events()]
    assert "sharding_ledger" in kinds


# ----------------------------------------------------- analytic oracle tests
def test_sync_master_allreduce_bytes_match_param_bytes():
    """K-replica sync DP: the per-step gradient all-reduce must move
    exactly the parameter bytes (within scalar/fusion tolerance)."""
    from deeplearning4j_tpu.backend import device as backend
    from deeplearning4j_tpu.parallel.training_master import (
        DistributedNetwork, SyncTrainingMaster,
    )

    net = dense_net(updater="sgd")
    mesh = backend.default_mesh(data=8)
    with ShardStatsCollector() as coll:
        master = SyncTrainingMaster(mesh=mesh)
        DistributedNetwork(net, master).fit(
            ListDataSetIterator(class_data(64), 16))
        prog = coll.programs()["SyncTrainingMaster.step"]
    census = prog["collectives"]
    assert census["all-reduce"]["count"] >= 1
    pb = param_bytes(net.params)
    # per-leaf grad all-reduces + the scalar loss mean; fusion may merge,
    # padding/scalars may add — bytes must stay within 10% + 1KB slack
    assert pb <= census["all-reduce"]["bytes"] <= pb * 1.1 + 1024
    # replicated params on the 8-way mesh: ledger factor == mesh size
    led = shardstats.latest_ledgers()["sync_master"]
    assert led["trees"]["params"]["replication_factor"] == 8.0


def test_parallel_wrapper_acceptance_4_replicas():
    """The PR acceptance criterion, end to end: 4-replica ParallelWrapper
    — updater replication factor 4, ≥1 all-reduce with bytes matching the
    analytic count, zero extra recompiles in steady state, and the ledger
    served over GET /memory."""
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
    from deeplearning4j_tpu.ui.server import UIServer

    net = dense_net(updater="adam")
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4, 1, 1),
                ("data", "model", "seq"))
    with ShardStatsCollector() as coll:
        pw = ParallelWrapper(net, workers=4, mesh=mesh,
                             averaging_frequency=1, average_updaters=True)
        pw.fit(ListDataSetIterator(class_data(96, seed=3), 8))
        prog = coll.programs()["ParallelWrapper.fit_window"]

        led = shardstats.latest_ledgers()["parallel_wrapper"]
        assert led["trees"]["updater_state"]["replication_factor"] == 4.0
        assert led["trees"]["params"]["replication_factor"] == 4.0
        assert led["data_axis_size"] == 4

        census = prog["collectives"]
        assert census["all-reduce"]["count"] >= 1
        # the averaging collective moves params + (averaged) Adam moments
        expected = param_bytes(net.params) + param_bytes(net.updater_state)
        assert expected <= census["all-reduce"]["bytes"] \
            <= expected * 1.1 + 1024
        assert census["all-reduce"]["group_sizes"] == [4]
        assert attribute_mesh_axes(
            census, {"data": 4, "model": 1, "seq": 1})["all-reduce"] \
            == ["data"]

        # zero extra recompiles in steady state: one signature for the
        # full windows (a ragged tail window would be a second PLANNED
        # shape, not a recompile-after-warn)
        det = pw._step_fn.detector
        assert det.recompile_count == 0
        assert det.compile_count == 1

        # GET /memory serves the ledger + the per-program census
        server = UIServer()
        port = server.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/memory", timeout=10) as r:
                doc = json.loads(r.read())
        finally:
            server.stop()
        pw_led = doc["ledgers"]["parallel_wrapper"]
        assert pw_led["trees"]["updater_state"]["replication_factor"] == 4.0
        assert doc["programs"]["ParallelWrapper.fit_window"][
            "collective_bytes"] > 0

    # comm roofline populated (CPU estimate, labeled by the gauge source)
    assert prog["comm_seconds_estimate"] > 0
    assert prog["comm_compute_ratio"] is not None


def test_pipeline_per_stage_ledger_sums_to_single_device_total():
    from deeplearning4j_tpu.parallel.pipeline import (
        PipelineParallelTrainingMaster,
    )

    net = dense_net(n_in=16, hidden=24, n_out=4, updater="sgd")
    single_total = param_bytes(net.params)
    master = PipelineParallelTrainingMaster(
        n_stages=2, n_microbatches=2, mode="orchestrated",
        devices=jax.devices()[:2])
    master.execute_training(net, ListDataSetIterator(
        class_data(16, n_in=16), 8))
    led = shardstats.latest_ledgers()["pipeline_master"]
    stage_rows = {k: v for k, v in led["trees"].items()
                  if k.startswith("params_stage")}
    assert len(stage_rows) == 2
    assert sum(r["logical_bytes"] for r in stage_rows.values()) \
        == single_total
    # each stage holds ONLY its share (true pipeline memory win)
    assert all(0 < r["per_device_bytes"] < single_total
               for r in stage_rows.values())


def test_facade_fit_records_ledger():
    shardstats.clear_ledgers()
    net = dense_net()
    ds = class_data(16)
    net.fit(ds.features, ds.labels, epochs=1)
    led = shardstats.latest_ledgers()["MultiLayerNetwork"]
    assert led["trees"]["params"]["logical_bytes"] \
        == param_bytes(net.params)


# ----------------------------------------------------------- flight recorder
def test_flight_dump_includes_sharding_ledger(tmp_path):
    from deeplearning4j_tpu.observability.flightrecorder import (
        dump_flight_report, read_flight_report,
    )

    record_ledger("dump_test",
                  {"params": {"w": np.zeros((16,), np.float32)}})
    path = dump_flight_report(str(tmp_path / "report.jsonl"), "unit")
    records = read_flight_report(path)
    ledgers = [r for r in records if r["record"] == "sharding_ledger"]
    assert len(ledgers) == 1
    assert "dump_test" in ledgers[0]["ledgers"]
    assert ledgers[0]["ledgers"]["dump_test"]["trees"]["params"][
        "logical_bytes"] == 64


# -------------------------------------------------------- generation warmup
@pytest.mark.generation
def test_generation_warmup_records_pools_ledger_and_census():
    from deeplearning4j_tpu.generation.programs import GenerationPrograms
    from deeplearning4j_tpu.models.zoo import transformer_char_lm
    from deeplearning4j_tpu.observability.recompile import RecompileDetector

    net = transformer_char_lm(vocab_size=29, d_model=32, n_heads=4,
                              layers=2, max_cache=64, seed=5)
    shardstats.clear_ledgers()
    with ShardStatsCollector() as coll:
        progs = GenerationPrograms(
            net, slots=2, pages_per_slot=4, page_size=4, num_pages=16,
            prefill_buckets=(8,),
            detector=RecompileDetector("generation.test",
                                       registry=MetricsRegistry()))
        progs.warm()
        collected = coll.programs()
    led = shardstats.latest_ledgers()["generation"]
    assert led["trees"]["kv_pools"]["logical_bytes"] > 0
    assert led["trees"]["params"]["logical_bytes"] > 0
    assert "generation.decode" in collected
    assert "generation.prefill_8" in collected
    # single-device decode: census empty but memory accounting present
    assert collected["generation.decode"]["memory"]["argument"] > 0


# -------------------------------------------------------- grad-sync CLI
def test_measure_grad_sync_uses_census(monkeypatch):
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "measure_grad_sync",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "measure_grad_sync.py"))
    mgs = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mgs)
    monkeypatch.setattr(mgs, "RESNET50_PARAMS", 4096)
    out = mgs.measure(n_devices=2, iters=2)
    assert out["censused_allreduce_count"] == 1
    assert out["censused_allreduce_bytes"] == 4096 * 4
    assert out["censused_group_size"] == 2
    assert out["analytic_v5e_ms"] >= 0
    assert out["measured_ms"] > 0


# ------------------------------------------------------------ hook overhead
def test_note_dispatch_hot_path_is_cheap():
    """The per-dispatch cost while a collector is installed is an
    identity check + a couple of cached counter increments — bound it
    hard so the <2% bench budget cannot rot silently."""
    coll = ShardStatsCollector(registry=MetricsRegistry())
    analysis = {"flops": 1e6, "memory": {"argument": 1},
                "collectives": {"all-reduce": {"count": 2, "bytes": 1024,
                                               "group_sizes": [4]}},
                "collective_bytes": 1024.0, "collective_count": 2}
    coll.note_dispatch("fn", analysis)   # slow path once
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        coll.note_dispatch("fn", analysis)
    per_call = (time.perf_counter() - t0) / n
    # generous CI bound: 50 µs/dispatch is still ~0.1% of a 50 ms step
    assert per_call < 50e-6


def test_no_analysis_when_no_collector_installed():
    """Without a collector the instrument seam must not lower/compile
    anything extra: cost_fn stays None and last_cost is None."""
    from deeplearning4j_tpu.observability.recompile import instrument

    assert shardstats.active_collector() is None
    f = instrument(jax.jit(lambda a: a * 2), "shardstats_off_test",
                   registry=MetricsRegistry())
    f(jnp.ones((4,)))
    assert f.detector.last_cost is None


# -------------------------------------------------------- regression rules
def test_default_rules_include_memory_sentinels():
    from deeplearning4j_tpu.observability import regression

    doc_rules = [r for r in regression.DEFAULT_RULES if r.scope == "doc"]
    fields = {r.field for r in doc_rules}
    assert ("observability.memory.sentinels.updater_replication_factor"
            in fields)
    assert ("observability.memory.sentinels.collective_bytes_per_step"
            in fields)
    # the ZeRO-flip rule: growth fails, shrink improves
    rule = next(r for r in doc_rules
                if r.field.endswith("updater_replication_factor"))
    base = {"all": [], "observability": {"memory": {"sentinels": {
        "updater_replication_factor": 4.0}}}}
    worse = {"all": [], "observability": {"memory": {"sentinels": {
        "updater_replication_factor": 5.0}}}}
    better = {"all": [], "observability": {"memory": {"sentinels": {
        "updater_replication_factor": 1.0}}}}
    assert regression.compare(base, worse, [rule]).exit_code == 1
    assert regression.compare(base, better,
                              [rule]).verdicts[0].status == "improved"
    # rules survive the JSON round-trip with their scope
    r2 = regression.Rule.from_dict(rule.to_dict())
    assert r2.scope == "doc" and r2.field == rule.field
