"""ComputationGraph runtime parity with MultiLayerNetwork: recurrent DAGs,
TBPTT fit, rnnTimeStep streaming, pretrain, MultiDataSet iterators.

Reference: ``ComputationGraph.java`` :599-747 (fit MultiDataSetIterator),
:1549 (doTruncatedBPTT), :1674 (rnnTimeStep), :478 (pretrain);
``RecordReaderMultiDataSetIterator.java``; ``AsyncMultiDataSetIterator.java``.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.multidataset import (
    AsyncMultiDataSetIterator,
    ListMultiDataSetIterator,
    MultiDataSet,
    RecordReaderMultiDataSetIterator,
)
from deeplearning4j_tpu.gradientcheck import check_gradients
from deeplearning4j_tpu.models.graph import ComputationGraph
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    DenseLayer, GravesLSTM, OutputLayer, RnnOutputLayer,
)

F64 = jnp.float64


def _lstm_graph(tbptt=None, seed=3, lr=0.05, hidden=4, vocab=3):
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater("sgd", learning_rate=lr).graph()
         .add_inputs("in")
         .add_layer("lstm", GravesLSTM(n_in=vocab, n_out=hidden,
                                       activation="tanh"), "in")
         .add_layer("out", RnnOutputLayer(n_in=hidden, n_out=vocab,
                                          loss="mcxent", activation="softmax"),
                    "lstm")
         .set_outputs("out"))
    if tbptt:
        b = b.backprop_type("truncated_bptt", fwd_length=tbptt,
                            back_length=tbptt)
    return ComputationGraph(b.build()).init()


def _seq_data(rs, b=2, t=6, vocab=3, dtype=np.float32):
    ids = rs.randint(0, vocab, (b, t))
    x = np.eye(vocab, dtype=dtype)[ids]
    y = np.eye(vocab, dtype=dtype)[np.roll(ids, -1, 1)]
    return x, y


def test_graph_lstm_gradients():
    """CG analog of test_graves_lstm_gradients (CuDNNGradientChecks style)."""
    rs = np.random.RandomState(46)
    net = _lstm_graph()
    net = ComputationGraph(net.conf).init(dtype=F64)
    x = rs.randn(2, 5, 3)
    y = np.eye(3)[rs.randint(0, 3, (2, 5))]
    assert check_gradients(net, x, y, max_params_per_array=32)


def test_graph_tbptt_equivalence():
    """One TBPTT pass with window == T must equal one standard fit step."""
    rs = np.random.RandomState(7)
    x, y = _seq_data(rs, b=2, t=6)
    std = _lstm_graph(tbptt=None, seed=11)
    tb = _lstm_graph(tbptt=6, seed=11)
    std.fit(x, y)
    tb.fit(x, y)
    assert np.allclose(std.params_to_vector(), tb.params_to_vector(),
                       atol=1e-6), "window==T TBPTT diverged from standard fit"


def test_graph_tbptt_trains_and_carries():
    """Window < T: multiple windows per batch, state carried, loss drops."""
    rs = np.random.RandomState(8)
    x, y = _seq_data(rs, b=4, t=12)
    # lr=0.05: at 0.1 plain SGD on this 4-unit LSTM oscillates around the
    # optimum (4.37 -> 4.27 -> 4.43 over the 31 fits) so the "loss drops"
    # assertion is a coin flip; the TBPTT math itself is pinned by the
    # window==T equivalence test above.
    net = _lstm_graph(tbptt=4, seed=5, lr=0.05)
    net.fit(x, y)
    first = net.score_value
    # 3 windows of 4 -> 3 optimizer steps for one batch
    assert net.iteration == 3
    for _ in range(30):
        net.fit(x, y)
    assert net.score_value < first


def test_graph_rnn_time_step_matches_full_forward():
    """Feeding T steps one at a time == one full-sequence forward
    (reference rnnTimeStep contract, ComputationGraph.java:1674)."""
    rs = np.random.RandomState(9)
    net = _lstm_graph(seed=13)
    x, _ = _seq_data(rs, b=2, t=5)
    full = np.asarray(net.output(x))
    net.rnn_clear_previous_state()
    stepped = [np.asarray(net.rnn_time_step(x[:, t])) for t in range(5)]
    for t in range(5):
        assert np.allclose(full[:, t], stepped[t], atol=1e-5), f"t={t}"
    # clearing state restarts the stream
    net.rnn_clear_previous_state()
    again = np.asarray(net.rnn_time_step(x[:, 0]))
    assert np.allclose(again, stepped[0], atol=1e-6)


def test_graph_tbptt_masking():
    """Masked TBPTT fit runs and produces finite loss (CG analog of the
    masking gradient tests)."""
    rs = np.random.RandomState(10)
    x, y = _seq_data(rs, b=2, t=8)
    mask = np.ones((2, 8), np.float32)
    mask[0, 5:] = 0.0
    net = _lstm_graph(tbptt=4, seed=17)
    net.fit(x, y, fmask=mask, lmask=mask)
    assert np.isfinite(net.score_value)


def test_graph_pretrain_autoencoder():
    from deeplearning4j_tpu.nn.layers import AutoEncoder

    rs = np.random.RandomState(11)
    b = (NeuralNetConfiguration.builder().seed(19)
         .updater("sgd", learning_rate=0.1).graph()
         .add_inputs("in")
         .add_layer("ae", AutoEncoder(n_in=8, n_out=4, activation="sigmoid",
                                      learning_rate=0.1), "in")
         .add_layer("out", OutputLayer(n_in=4, n_out=2), "ae")
         .set_outputs("out"))
    net = ComputationGraph(b.build()).init()
    x = rs.rand(32, 8).astype(np.float32)
    before = {k: np.asarray(v) for k, v in net.params["ae"].items()}
    net.pretrain([(x, None)], epochs=3)
    after = net.params["ae"]
    assert any(not np.allclose(before[k], np.asarray(after[k]))
               for k in before), "pretrain did not move AE params"


# --------------------------------------------------------- MultiDataSet path

def _two_input_graph(seed=23):
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater("adam", learning_rate=0.05).graph()
         .add_inputs("a", "b"))
    from deeplearning4j_tpu.models.vertices import MergeVertex

    b.add_layer("da", DenseLayer(n_in=3, n_out=8, activation="relu"), "a")
    b.add_layer("db", DenseLayer(n_in=2, n_out=8, activation="relu"), "b")
    b.add_vertex("m", MergeVertex(), "da", "db")
    b.add_layer("out", OutputLayer(n_in=16, n_out=2), "m")
    return ComputationGraph(b.set_outputs("out").build()).init()


def _multi_data(rs, n=64):
    xa = rs.rand(n, 3).astype(np.float32)
    xb = rs.rand(n, 2).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[((xa.sum(1) + xb.sum(1)) > 2.5).astype(int)]
    return MultiDataSet((xa, xb), (y,))


def test_graph_fit_multidataset_iterator():
    rs = np.random.RandomState(12)
    mds = _multi_data(rs)
    net = _two_input_graph()
    it = ListMultiDataSetIterator(mds, batch_size=16)
    for _ in range(30):
        net.fit(it)
    out = net.output({"a": mds.features[0], "b": mds.features[1]})
    acc = (np.asarray(out).argmax(-1) == mds.labels[0].argmax(-1)).mean()
    assert acc > 0.85, acc


def test_graph_fit_async_multidataset():
    rs = np.random.RandomState(13)
    mds = _multi_data(rs)
    net = _two_input_graph(seed=29)
    it = AsyncMultiDataSetIterator(ListMultiDataSetIterator(mds, 16),
                                  prefetch_size=2)
    for _ in range(5):
        net.fit(it)
    assert np.isfinite(net.score_value)
    assert net.iteration == 20  # 4 batches x 5 epochs


def test_multidataset_mismatch_raises():
    rs = np.random.RandomState(14)
    net = _two_input_graph(seed=31)
    bad = MultiDataSet((rs.rand(4, 3).astype(np.float32),),
                       (np.eye(2, dtype=np.float32)[[0, 1, 0, 1]],))
    with pytest.raises(ValueError, match="feature arrays"):
        net.fit(ListMultiDataSetIterator(bad, 4))


def test_record_reader_multidataset_iterator():
    from deeplearning4j_tpu.datasets.datavec import CollectionRecordReader

    rs = np.random.RandomState(15)
    rows = [list(rs.rand(5).astype(float)) + [float(rs.randint(0, 2))]
            for _ in range(20)]
    it = (RecordReaderMultiDataSetIterator.builder(batch_size=8)
          .add_reader("r", CollectionRecordReader(rows))
          .add_input("r", 0, 2)
          .add_input("r", 3, 4)
          .add_output_one_hot("r", 5, 2)
          .build())
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].features[0].shape == (8, 3)
    assert batches[0].features[1].shape == (8, 2)
    assert batches[0].labels[0].shape == (8, 2)
    assert batches[2].features[0].shape == (4, 3)  # short last batch kept
    # one-hot is exact
    assert set(np.unique(batches[0].labels[0])) <= {0.0, 1.0}
    # reset replays
    it.reset()
    again = list(it)
    assert len(again) == 3
    assert np.allclose(again[0].features[0], batches[0].features[0])


def test_async_iterator_surfaces_producer_errors():
    """A failing underlying iterator must raise on the consumer side, not
    silently truncate the epoch."""

    class Exploding(ListMultiDataSetIterator):
        def next(self):
            if self._pos >= 1:
                raise IOError("corrupt record")
            return super().next()

    rs = np.random.RandomState(17)
    it = AsyncMultiDataSetIterator(Exploding(_multi_data(rs, 32), 8))
    batches = []
    with pytest.raises(RuntimeError, match="async prefetch producer failed"):
        while it.has_next():
            batches.append(it.next())
    assert len(batches) == 1


def test_graph_feed_forward_activation_map():
    """feedForward() returns every vertex's activations by name (reference
    ComputationGraph.feedForward :1012-1036)."""
    rs = np.random.RandomState(18)
    net = _two_input_graph(seed=37)
    xa, xb = rs.rand(4, 3).astype(np.float32), rs.rand(4, 2).astype(np.float32)
    acts = net.feed_forward({"a": xa, "b": xb})
    assert set(acts) >= {"a", "b", "da", "db", "m", "out"}
    assert acts["da"].shape == (4, 8)
    assert acts["m"].shape == (4, 16)
    # output vertex carries post-activation (softmax) values
    np.testing.assert_allclose(np.asarray(acts["out"]).sum(-1), 1.0, rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(acts["out"]),
        np.asarray(net.output({"a": xa, "b": xb})), atol=1e-6)


def test_facade_evaluate_iterator():
    """net.evaluate(iterator) parity on both facades (reference
    MultiLayerNetwork.evaluate / ComputationGraph.doEvaluation)."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork

    rs = np.random.RandomState(19)
    x = rs.rand(64, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 2).astype(int)]
    mln = MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(41)
         .updater("adam", learning_rate=0.05).list()
         .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
         .layer(OutputLayer(n_in=16, n_out=2)).build())).init()
    it = ListDataSetIterator(DataSet(x, y), 16)
    for _ in range(40):
        mln.fit(it)
    ev = mln.evaluate(it)
    assert ev.accuracy() > 0.85

    # CG: multi-input via MultiDataSet iterator
    rs2 = np.random.RandomState(20)
    mds = _multi_data(rs2)
    cg = _two_input_graph(seed=43)
    mit = ListMultiDataSetIterator(mds, 16)
    for _ in range(30):
        cg.fit(mit)
    ev2 = cg.evaluate(mit)
    assert ev2.accuracy() > 0.85


def test_multidataset_merge_and_shuffle():
    rs = np.random.RandomState(16)
    a, b = _multi_data(rs, 8), _multi_data(rs, 8)
    m = MultiDataSet.merge([a, b])
    assert len(m) == 16
    s = m.shuffle(np.random.RandomState(0))
    assert len(s) == 16
    assert not np.allclose(s.features[0], m.features[0])


def test_graph_multi_output_per_head_label_masks():
    """Per-head lmask dict: masking one head's labels changes only that
    head's loss contribution (ComputationGraph.java multi-output fit)."""
    b = (NeuralNetConfiguration.builder().seed(47)
         .updater("sgd", learning_rate=0.0).graph()  # lr 0: score only
         .add_inputs("in"))
    b.add_layer("h", DenseLayer(n_in=4, n_out=8, activation="relu"), "in")
    b.add_layer("o1", OutputLayer(n_in=8, n_out=2), "h")
    b.add_layer("o2", OutputLayer(n_in=8, n_out=3), "h")
    from deeplearning4j_tpu.models.graph import ComputationGraph

    net = ComputationGraph(b.set_outputs("o1", "o2").build()).init()
    rs = np.random.RandomState(0)
    x = rs.rand(4, 4).astype(np.float32)
    y = {"o1": np.eye(2, dtype=np.float32)[rs.randint(0, 2, 4)],
         "o2": np.eye(3, dtype=np.float32)[rs.randint(0, 3, 4)]}
    net.fit(x, y)
    full = net.score_value
    # masking o2 out entirely must reduce the total to o1's share
    net2 = ComputationGraph(net.conf).init()
    net2.fit(x, y, lmask={"o2": np.zeros((4,), np.float32)})
    assert net2.score_value < full
    # and a full mask equals no mask
    net3 = ComputationGraph(net.conf).init()
    net3.fit(x, y, lmask={"o2": np.ones((4,), np.float32)})
    assert abs(net3.score_value - full) < 1e-6


def test_graph_tbptt_with_multidataset():
    """TBPTT over a MultiDataSet iterator (single recurrent input; the
    rank-2-inputs-pass-whole invariant is unit-tested separately below)."""
    from deeplearning4j_tpu.models.graph import ComputationGraph

    b = (NeuralNetConfiguration.builder().seed(53)
         .updater("sgd", learning_rate=0.05).graph()
         .add_inputs("seq")
         .add_layer("lstm", GravesLSTM(n_in=3, n_out=6), "seq")
         .add_layer("out", RnnOutputLayer(n_in=6, n_out=3), "lstm")
         .set_outputs("out")
         .backprop_type("truncated_bptt", fwd_length=4, back_length=4))
    net = ComputationGraph(b.build()).init()
    rs = np.random.RandomState(1)
    ids = rs.randint(0, 3, (8, 12))
    x = np.eye(3, dtype=np.float32)[ids]
    y = np.eye(3, dtype=np.float32)[np.roll(ids, -1, 1)]
    mds = MultiDataSet((x,), (y,))
    it = ListMultiDataSetIterator(mds, 4)
    net.fit(it)
    # 2 batches x 3 windows of 4 = 6 optimizer steps
    assert net.iteration == 6
    assert np.isfinite(net.score_value)


def test_graph_tbptt_slicing_semantics():
    """The TBPTT window slicers: rank-3 sequences are time-sliced, rank-2
    static features/one-hot labels pass whole, rank-2 masks ARE temporal."""
    net = _lstm_graph(tbptt=4)
    data = {"seq": np.zeros((2, 12, 3)), "static": np.zeros((2, 5))}
    sl = slice(0, 4)
    sliced = ComputationGraph._tbptt_slice_data(data, sl)
    assert sliced["seq"].shape == (2, 4, 3)
    assert sliced["static"].shape == (2, 5)  # untouched
    masks = {"seq": np.zeros((2, 12)), "out": np.zeros((2, 12))}
    msliced = ComputationGraph._tbptt_slice_mask(masks, sl)
    assert msliced["seq"].shape == (2, 4)
    assert msliced["out"].shape == (2, 4)
    assert ComputationGraph._tbptt_slice_data(None, sl) is None
    assert ComputationGraph._tbptt_slice_mask(None, sl) is None
    # end-to-end: a graph with no rank-3 input must refuse TBPTT loudly
    with pytest.raises(ValueError, match="rank-3"):
        net._fit_tbptt({"in": np.zeros((2, 5), np.float32)},
                       {"out": np.zeros((2, 3), np.float32)}, None, None)


def test_graph_attention_streaming_matches_full_forward():
    """CG rnn_time_step seeds attention KV caches like MLN: a causal
    attention DAG streamed one step at a time reproduces the full
    forward (reference ``ComputationGraph.rnnTimeStep`` :1674)."""
    from deeplearning4j_tpu.nn.layers import LayerNorm, RnnOutputLayer
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer

    b = (NeuralNetConfiguration.builder().seed(3)
         .updater("sgd", learning_rate=0.01).graph()
         .add_inputs("seq")
         .add_layer("attn", SelfAttentionLayer(n_in=6, n_out=6, n_heads=2,
                                               causal=True), "seq")
         .add_layer("ln", LayerNorm(n_in=6), "attn")
         .add_layer("out", RnnOutputLayer(n_in=6, n_out=3), "ln")
         .set_outputs("out"))
    net = ComputationGraph(b.build()).init()
    rs = np.random.RandomState(4)
    x = rs.randn(2, 5, 6).astype(np.float32)
    full = np.asarray(net.output({"seq": x}))
    net.rnn_clear_previous_state()
    for t in range(5):
        step = np.asarray(net.rnn_time_step({"seq": x[:, t]}))
        np.testing.assert_allclose(step, full[:, t], rtol=2e-4, atol=1e-5,
                                   err_msg=f"t={t}")
