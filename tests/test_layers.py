"""Layer unit tests: shapes, forward semantics, config round-trip.
Mirrors reference suites under deeplearning4j-core/src/test/.../nn/**."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn import activations, initializers, losses
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    AutoEncoder,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    GravesBidirectionalLSTM,
    GravesLSTM,
    LocalResponseNormalization,
    OutputLayer,
    RBM,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.base import layer_from_dict


KEY = jax.random.key(0)


def test_dense_forward_shape():
    layer = DenseLayer(n_in=4, n_out=3, activation="relu", name="d0")
    p = layer.init(KEY)
    assert p["W"].shape == (4, 3) and p["b"].shape == (3,)
    y, _ = layer.apply(p, {}, jnp.ones((2, 4)))
    assert y.shape == (2, 3)
    # relu of positive preactivation matches manual matmul
    expected = jax.nn.relu(jnp.ones((2, 4)) @ p["W"] + p["b"])
    np.testing.assert_allclose(y, expected, rtol=1e-6)


def test_dense_setup_infers_n_in():
    layer = DenseLayer(n_out=7).setup(InputType.feed_forward(13))
    assert layer.n_in == 13
    assert layer.output_type(InputType.feed_forward(13)).size == 7


def test_conv_shapes():
    layer = ConvolutionLayer(n_out=6, kernel_size=(5, 5), stride=(1, 1),
                             name="c").setup(InputType.convolutional(28, 28, 1))
    assert layer.n_in == 1
    out = layer.output_type(InputType.convolutional(28, 28, 1))
    assert (out.height, out.width, out.channels) == (24, 24, 6)
    p = layer.init(KEY)
    y, _ = layer.apply(p, {}, jnp.ones((2, 28, 28, 1)))
    assert y.shape == (2, 24, 24, 6)


def test_subsampling_max_pool():
    layer = SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2))
    x = jnp.arange(16.0).reshape(1, 4, 4, 1)
    y, _ = layer.apply({}, {}, x)
    assert y.shape == (1, 2, 2, 1)
    np.testing.assert_allclose(y[0, :, :, 0], [[5.0, 7.0], [13.0, 15.0]])


def test_subsampling_avg_pool():
    layer = SubsamplingLayer(pooling_type="avg", kernel_size=(2, 2), stride=(2, 2))
    x = jnp.arange(16.0).reshape(1, 4, 4, 1)
    y, _ = layer.apply({}, {}, x)
    np.testing.assert_allclose(y[0, :, :, 0], [[2.5, 4.5], [10.5, 12.5]])


def test_batchnorm_train_and_inference():
    layer = BatchNormalization(n_out=3, decay=0.5, name="bn")
    p = layer.init(KEY)
    st = layer.init_state()
    x = jnp.asarray(np.random.RandomState(0).randn(16, 3) * 3 + 1, jnp.float32)
    y, new_st = layer.apply(p, st, x, train=True)
    # normalized output: ~zero mean, ~unit var
    np.testing.assert_allclose(np.mean(np.asarray(y), 0), 0, atol=1e-5)
    np.testing.assert_allclose(np.var(np.asarray(y), 0), 1, atol=1e-2)
    # running stats moved toward batch stats
    assert not np.allclose(np.asarray(new_st["mean"]), 0)
    # inference path uses running stats, state unchanged
    y2, st2 = layer.apply(p, new_st, x, train=False)
    assert st2 is new_st


def test_batchnorm_conv_rank4():
    layer = BatchNormalization(n_out=2)
    p, st = layer.init(KEY), layer.init_state()
    x = jnp.asarray(np.random.RandomState(0).randn(4, 5, 5, 2), jnp.float32)
    y, _ = layer.apply(p, st, x, train=True)
    assert y.shape == x.shape
    np.testing.assert_allclose(np.asarray(y).mean((0, 1, 2)), 0, atol=1e-5)


def test_lrn_shape_and_identity_limit():
    layer = LocalResponseNormalization()
    x = jnp.ones((2, 4, 4, 8))
    y, _ = layer.apply({}, {}, x)
    assert y.shape == x.shape
    assert float(y[0, 0, 0, 4]) < 1.0  # denominator > 1


def test_embedding_lookup():
    layer = EmbeddingLayer(n_in=10, n_out=4, name="e")
    p = layer.init(KEY)
    idx = jnp.asarray([[1], [3]])
    y, _ = layer.apply(p, {}, idx)
    assert y.shape == (2, 4)
    np.testing.assert_allclose(y[0], p["W"][1] + p["b"], rtol=1e-6)


def test_dropout_train_vs_test():
    layer = DropoutLayer(dropout=0.5)
    x = jnp.ones((4, 10))
    y_test, _ = layer.apply({}, {}, x, train=False)
    np.testing.assert_allclose(y_test, x)
    y_train, _ = layer.apply({}, {}, x, train=True, rng=jax.random.key(1))
    vals = np.unique(np.asarray(y_train))
    assert set(np.round(vals, 4)).issubset({0.0, 2.0})


def test_lstm_shapes_and_streaming_consistency():
    layer = GravesLSTM(n_in=3, n_out=5, name="l")
    p = layer.init(KEY)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 7, 3), jnp.float32)
    y, _ = layer.apply(p, {}, x)
    assert y.shape == (2, 7, 5)
    # streaming step-by-step equals full-sequence scan
    carry = layer.initial_carry(2, x.dtype)
    outs = []
    for t in range(7):
        o, carry = layer.step(p, carry, x[:, t])
        outs.append(o)
    np.testing.assert_allclose(np.stack(outs, 1), np.asarray(y), rtol=2e-5, atol=1e-5)


def test_lstm_masking_freezes_state():
    layer = GravesLSTM(n_in=3, n_out=4)
    p = layer.init(KEY)
    x = jnp.asarray(np.random.RandomState(1).randn(1, 5, 3), jnp.float32)
    mask = jnp.asarray([[1.0, 1.0, 1.0, 0.0, 0.0]])
    y, _, (hT, cT) = layer.apply_with_carry(p, {}, x, None, mask=mask)
    # masked outputs are zero
    np.testing.assert_allclose(np.asarray(y[0, 3:]), 0, atol=1e-7)
    # final carry equals carry after step 3
    y3, _, (h3, c3) = layer.apply_with_carry(p, {}, x[:, :3], None)
    np.testing.assert_allclose(np.asarray(hT), np.asarray(h3), rtol=1e-5)


def test_bidirectional_lstm_sums_directions():
    layer = GravesBidirectionalLSTM(n_in=3, n_out=4, name="b")
    p = layer.init(KEY)
    x = jnp.asarray(np.random.RandomState(2).randn(2, 6, 3), jnp.float32)
    y, _ = layer.apply(p, {}, x)
    assert y.shape == (2, 6, 4)


def test_autoencoder_pretrain_loss_decreases():
    layer = AutoEncoder(n_in=8, n_out=4, corruption_level=0.0, name="ae",
                        activation="sigmoid")
    p = layer.init(KEY)
    x = jnp.asarray(np.random.RandomState(3).rand(32, 8), jnp.float32)
    loss_fn = jax.jit(jax.value_and_grad(lambda pp: layer.pretrain_loss(pp, x, jax.random.key(0))))
    l0, _ = loss_fn(p)
    for _ in range(50):
        l, g = loss_fn(p)
        p = jax.tree_util.tree_map(lambda a, b: a - 0.5 * b, p, g)
    assert float(l) < float(l0)


def test_rbm_cd_reduces_reconstruction_error():
    layer = RBM(n_in=6, n_out=4, k=1, name="rbm")
    p = layer.init(KEY)
    rs = np.random.RandomState(4)
    x = jnp.asarray((rs.rand(64, 6) > 0.5).astype(np.float32))
    loss_fn = jax.jit(jax.value_and_grad(layer.pretrain_loss))
    err0 = float(layer.reconstruction_error(p, x, jax.random.key(0)))
    key = jax.random.key(1)
    for i in range(100):
        key, sub = jax.random.split(key)
        _, g = loss_fn(p, x, sub)
        p = jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)
    err1 = float(layer.reconstruction_error(p, x, jax.random.key(0)))
    assert err1 < err0


def test_layer_json_roundtrip():
    for layer in [
        DenseLayer(n_in=3, n_out=4, activation="relu", l2=0.01, name="x"),
        ConvolutionLayer(n_in=1, n_out=6, kernel_size=(3, 3), name="c"),
        SubsamplingLayer(pooling_type="avg"),
        BatchNormalization(n_out=5),
        GravesLSTM(n_in=2, n_out=3),
        OutputLayer(n_in=4, n_out=2, loss="mcxent", activation="softmax"),
        RBM(n_in=3, n_out=2),
    ]:
        d = layer.to_dict()
        restored = layer_from_dict(d)
        assert restored == layer, f"round-trip failed for {type(layer).__name__}"
