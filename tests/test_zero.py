"""ZeRO-style cross-replica sharding of the weight update (arXiv
2004.13336): reduce-scatter grads -> sharded update -> all-gather params.

The acceptance oracle: a ``update_sharding="zero"`` run matches the same
master's replicated mode within rtol 1e-5 per step on params — including
under Adam, the stability guard's non-finite skip / poison masking, and
an elastic eviction mid-run — with ZERO steady-state recompiles.  The
measured side: the sharding ledger's updater-state replication factor
drops K -> ~1, the compiled window's collectives are reduce-scatter +
all-gather (wrapper: all-to-all + all-gather — same wire bytes) instead
of all-reduce, and the PR-14 projected-ZeRO ledger column matches the
ACTUAL ZeRO ledger (shared predicate: ``shardstats.zero_shardable``).
"""

import json
import os

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import (
    NeuralNetConfiguration, TrainingIntrospection, TrainingStability,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.observability import get_registry, shardstats
from deeplearning4j_tpu.parallel import (
    DistributedNetwork, ParallelWrapper, ParameterAveragingTrainingMaster,
    SyncTrainingMaster, restore_checkpoint, save_checkpoint,
)
from deeplearning4j_tpu.parallel import zero as zero_mod
from deeplearning4j_tpu.parallel.elastic import ElasticConfig
from deeplearning4j_tpu.resilience import FaultInjector, inject_faults

pytestmark = pytest.mark.zero

RTOL, ATOL = 1e-5, 1e-7


def make_net(seed=21, n_out=4, stab=None, intro=False, updater="adam"):
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(updater, learning_rate=0.05))
    if stab is not None:
        b = b.training_stability(stab)
    if intro:
        b = b.training_introspection(TrainingIntrospection())
    return MultiLayerNetwork(
        (b.list()
         .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
         .layer(OutputLayer(n_in=16, n_out=n_out)).build())).init()


def make_data(n=128, n_out=4, seed=1):
    rs = np.random.RandomState(seed)
    x = rs.rand(n, 8).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rs.randint(0, n_out, n)]
    return x, y


def mesh_of(k):
    return backend.default_mesh(data=k, devices=jax.devices()[:k])


def params_vec(net):
    return np.asarray(net.params_to_vector())


def compiles_total():
    return get_registry().family_total("dl4j_compiles_total")


# ---------------------------------------------------------------- oracles
def test_sync_master_zero_matches_replicated_adam():
    """The per-step oracle: ZeRO sync training == replicated sync
    training (same seed, same data) under Adam, with zero steady-state
    recompiles and the sharded collective signature in the compiled
    HLO."""
    x, y = make_data()
    mesh = mesh_of(4)
    vecs = {}
    for mode in (zero_mod.REPLICATED, zero_mod.ZERO):
        net = make_net()
        master = SyncTrainingMaster(mesh=mesh, update_sharding=mode)
        with shardstats.ShardStatsCollector() as coll:
            DistributedNetwork(net, master).fit(
                ListDataSetIterator(DataSet(x[:64], y[:64]), 16))
            c0 = compiles_total()
            DistributedNetwork(net, master).fit(
                ListDataSetIterator(DataSet(x[64:], y[64:]), 16))
            assert compiles_total() - c0 == 0, \
                f"{mode}: steady-state recompiles"
            vecs[mode] = params_vec(net)
            programs = coll.programs()
        if mode == zero_mod.ZERO:
            census = programs["SyncTrainingMaster.step_zero"]["collectives"]
            assert census.get("reduce-scatter", {}).get("count", 0) >= 1
            assert census.get("all-gather", {}).get("count", 0) >= 1
            # residual all-reduces carry only tiny scalars (loss,
            # normalizer, finiteness) — the gradient payload moved to
            # the reduce-scatter
            assert census.get("all-reduce", {}).get("bytes", 0) < 1024
    np.testing.assert_allclose(vecs[zero_mod.ZERO],
                               vecs[zero_mod.REPLICATED],
                               rtol=RTOL, atol=ATOL)


def test_sync_master_zero_wire_bytes_no_worse():
    """RS + AG wire bytes (ring recipe) must not exceed the replicated
    arm's all-reduce wire bytes by more than rounding — the paper's
    'strictly cheaper on the wire' claim, held via the HLO census."""
    x, y = make_data()
    mesh = mesh_of(4)
    wire = {}
    for mode in (zero_mod.REPLICATED, zero_mod.ZERO):
        net = make_net()
        with shardstats.ShardStatsCollector() as coll:
            DistributedNetwork(
                net, SyncTrainingMaster(mesh=mesh, update_sharding=mode)
            ).fit(ListDataSetIterator(DataSet(x, y), 32))
            name = ("SyncTrainingMaster.step_zero"
                    if mode == zero_mod.ZERO else "SyncTrainingMaster.step")
            wire[mode] = coll.programs()[name]["wire_bytes_per_device"]
    assert wire[zero_mod.ZERO] <= wire[zero_mod.REPLICATED] * 1.05, wire


def test_sync_master_zero_masked_loss_and_nondividing_leaves():
    """Masked-loss normalization (the per-shard weighting must reproduce
    the global sum/​sum(mask) exactly) and non-dividing leaves (n_out=5:
    the [5] bias stays replicated) both hold the oracle."""
    x, y = make_data(n=64, n_out=5)
    rs = np.random.RandomState(7)
    lm = (rs.rand(64) > 0.3).astype(np.float32)
    mesh = mesh_of(4)
    vecs = {}
    for mode in (zero_mod.REPLICATED, zero_mod.ZERO):
        net = make_net(n_out=5)
        DistributedNetwork(
            net, SyncTrainingMaster(mesh=mesh, update_sharding=mode)).fit(
            ListDataSetIterator(DataSet(x, y, labels_mask=lm), 32))
        vecs[mode] = params_vec(net)
    np.testing.assert_allclose(vecs[zero_mod.ZERO],
                               vecs[zero_mod.REPLICATED],
                               rtol=RTOL, atol=ATOL)


@pytest.mark.stability
def test_sync_master_zero_stability_poisoned_rows():
    """The stability engine under ZeRO: poisoned rows are zeroed and
    renormalized out exactly as in replicated mode."""
    x, y = make_data()
    mesh = mesh_of(4)
    vecs = {}
    for mode in (zero_mod.REPLICATED, zero_mod.ZERO):
        inj = FaultInjector(seed=3).poison_gradients(
            "d1", at_step=1, until_step=2, mode="nan")
        net = make_net(stab=TrainingStability(check_every=100))
        with inject_faults(inj):
            DistributedNetwork(
                net, SyncTrainingMaster(mesh=mesh, update_sharding=mode)
            ).fit(ListDataSetIterator(DataSet(x, y), 32))
        assert any(e["kind"] == "worker_poisoned" for e in inj.injected)
        vecs[mode] = params_vec(net)
    np.testing.assert_allclose(vecs[zero_mod.ZERO],
                               vecs[zero_mod.REPLICATED],
                               rtol=RTOL, atol=1e-6)


def test_wrapper_zero_oracle_with_stability_and_elastic_eviction():
    """The acceptance drill: a 4-replica ZeRO wrapper run — Adam, the
    stability guard live, a poisoned replica window, and an elastic
    eviction mid-run — matches replicated mode within rtol 1e-5 with
    zero steady-state recompiles."""
    x, y = make_data(n=192)
    mesh = mesh_of(4)
    vecs = {}
    for mode in (zero_mod.REPLICATED, zero_mod.ZERO):
        inj = FaultInjector(seed=3).poison_gradients(
            "1", at_step=1, until_step=2, mode="nan")
        net = make_net(stab=TrainingStability(check_every=100))
        pw = ParallelWrapper(net, workers=4, mesh=mesh,
                             averaging_frequency=1,
                             elastic=ElasticConfig(degraded_mode=True),
                             update_sharding=mode)
        with inject_faults(inj):
            pw.fit(ListDataSetIterator(DataSet(x[:64], y[:64]), 16))
            # elastic eviction mid-run: drop replica 2, keep training
            assert pw.elastic.evict("2", reason="manual", step=net.iteration)
            c0 = compiles_total()
            pw.fit(ListDataSetIterator(DataSet(x[64:128], y[64:128]), 16))
            # eviction flipped weight VALUES, not the pytree
            assert compiles_total() - c0 == 0, \
                f"{mode}: recompile on eviction"
            # re-admit and finish
            pw.elastic.readmit("2", step=net.iteration)
            pw.fit(ListDataSetIterator(DataSet(x[128:], y[128:]), 16))
        vecs[mode] = params_vec(net)
        assert np.isfinite(vecs[mode]).all()
    np.testing.assert_allclose(vecs[zero_mod.ZERO],
                               vecs[zero_mod.REPLICATED],
                               rtol=RTOL, atol=ATOL)


@pytest.mark.introspect
def test_wrapper_zero_introspection_parity_and_harvest():
    """Introspection flows through the ZeRO window: params match
    replicated mode, and the harvested per-replica gradient-norm view
    ([K, L]) survives the new layout."""
    from deeplearning4j_tpu.observability import introspection

    x, y = make_data()
    mesh = mesh_of(4)
    vecs = {}
    for mode in (zero_mod.REPLICATED, zero_mod.ZERO):
        net = make_net(intro=True)
        ParallelWrapper(net, workers=4, mesh=mesh, averaging_frequency=1,
                        update_sharding=mode).fit(
            ListDataSetIterator(DataSet(x, y), 16))
        vecs[mode] = params_vec(net)
        h = introspection.harvest(introspection.latest(net),
                                  introspection.plan_for(net))
        assert h is not None and h.get("replicas") == 4
        assert set(h["gradient_stats"]) == {"layer_0", "layer_1"}
        for stats in h["gradient_stats"].values():
            assert len(stats["per_replica"]) == 4
            assert all(np.isfinite(v) for v in stats["per_replica"])
    np.testing.assert_allclose(vecs[zero_mod.ZERO],
                               vecs[zero_mod.REPLICATED],
                               rtol=RTOL, atol=ATOL)


@pytest.mark.parametrize("updater", ["sgd", "rmsprop", "nesterovs"])
def test_wrapper_zero_other_updaters(updater):
    """The sharded elementwise update is exact for every updater rule,
    not just Adam."""
    x, y = make_data(n=64)
    mesh = mesh_of(4)
    vecs = {}
    for mode in (zero_mod.REPLICATED, zero_mod.ZERO):
        net = make_net(updater=updater)
        ParallelWrapper(net, workers=4, mesh=mesh, averaging_frequency=1,
                        update_sharding=mode).fit(
            ListDataSetIterator(DataSet(x, y), 16))
        vecs[mode] = params_vec(net)
    np.testing.assert_allclose(vecs[zero_mod.ZERO],
                               vecs[zero_mod.REPLICATED],
                               rtol=RTOL, atol=ATOL)


def test_wrapper_zero_ragged_tail_pad_weights():
    """A dataset whose final window pads replica slots: the pad weights
    compose with the ZeRO weighted-average exactly as in replicated
    mode (the tail-window bias fix carries over)."""
    x, y = make_data(n=88)        # 5 batches of 16 + ragged 8 -> pad
    mesh = mesh_of(4)
    vecs = {}
    for mode in (zero_mod.REPLICATED, zero_mod.ZERO):
        net = make_net()
        ParallelWrapper(net, workers=4, mesh=mesh, averaging_frequency=1,
                        update_sharding=mode).fit(
            ListDataSetIterator(DataSet(x, y), 16))
        vecs[mode] = params_vec(net)
    np.testing.assert_allclose(vecs[zero_mod.ZERO],
                               vecs[zero_mod.REPLICATED],
                               rtol=RTOL, atol=ATOL)


def test_param_averaging_master_forwards_zero():
    """ParameterAveragingTrainingMaster(update_sharding="zero") routes
    the mode into its per-fit wrappers."""
    x, y = make_data(n=64)
    mesh = mesh_of(4)
    vecs = {}
    for mode in (zero_mod.REPLICATED, zero_mod.ZERO):
        net = make_net()
        master = ParameterAveragingTrainingMaster(
            workers=4, mesh=mesh, averaging_frequency=1,
            update_sharding=mode)
        DistributedNetwork(net, master).fit(
            ListDataSetIterator(DataSet(x, y), 16))
        vecs[mode] = params_vec(net)
    np.testing.assert_allclose(vecs[zero_mod.ZERO],
                               vecs[zero_mod.REPLICATED],
                               rtol=RTOL, atol=ATOL)


# ------------------------------------------------- ledger & projection loop
def test_ledger_updater_replication_drops_to_one():
    """The measured criterion: under ZeRO the ledger's updater-state and
    params replication factors read ~1 (K in replicated mode), and the
    layout choice is recorded in the notes."""
    x, y = make_data(n=64)
    mesh = mesh_of(4)
    net = make_net()
    ParallelWrapper(net, workers=4, mesh=mesh, averaging_frequency=1,
                    update_sharding="zero").fit(
        ListDataSetIterator(DataSet(x, y), 16))
    led = shardstats.latest_ledgers()["parallel_wrapper"]
    assert led["trees"]["params"]["replication_factor"] <= 1.05
    assert led["trees"]["updater_state"]["replication_factor"] <= 1.1
    assert led["notes"]["update_sharding"] == "zero"
    assert led["notes"]["reserved_subtrees"]["__stability__"] == "replicated"

    rep = make_net(seed=22)
    ParallelWrapper(rep, workers=4, mesh=mesh, averaging_frequency=1).fit(
        ListDataSetIterator(DataSet(x, y), 16))
    led_rep = shardstats.latest_ledgers()["parallel_wrapper"]
    assert led_rep["trees"]["updater_state"]["replication_factor"] == 4.0
    assert "notes" not in led_rep


def test_projection_matches_actual_zero_ledger():
    """The PR-14 projection loop closed: the projected-ZeRO column of a
    REPLICATED run's ledger equals the per-device bytes the ACTUAL ZeRO
    run lands at, for params and updater state — including a net with
    non-dividing leaves and the reserved stability subtree."""
    x, y = make_data(n=64, n_out=5)
    mesh = mesh_of(4)
    stab = TrainingStability(check_every=100)
    ledgers = {}
    for mode in (zero_mod.REPLICATED, zero_mod.ZERO):
        net = make_net(n_out=5, stab=stab)
        ParallelWrapper(net, workers=4, mesh=mesh, averaging_frequency=1,
                        update_sharding=mode).fit(
            ListDataSetIterator(DataSet(x, y), 16))
        ledgers[mode] = shardstats.latest_ledgers()["parallel_wrapper"]
    for tree in ("params", "updater_state"):
        projected = ledgers[zero_mod.REPLICATED]["trees"][tree][
            "zero_projected_per_device_bytes"]
        actual = ledgers[zero_mod.ZERO]["trees"][tree]["per_device_bytes"]
        assert abs(projected - actual) <= 0.02 * max(actual, 1), (
            tree, projected, actual)


def test_reserved_subtrees_mirror_state_keys():
    """shardstats' literal reserved-subtree names must track the real
    owners (the ledger stays importable without jax, so it cannot import
    them)."""
    from deeplearning4j_tpu.observability import introspection, numerics
    from deeplearning4j_tpu.resilience import stability

    assert set(shardstats.RESERVED_REPLICATED_SUBTREES) == {
        stability.STATE_KEY, introspection.STATE_KEY, numerics.STATE_KEY}


def test_zero_shardable_predicate():
    assert shardstats.zero_shardable((8, 3), 4)
    assert not shardstats.zero_shardable((5,), 4)     # non-dividing
    assert not shardstats.zero_shardable((), 4)       # scalar
    assert not shardstats.zero_shardable((8,), 1)     # no data axis
    assert not shardstats.zero_shardable((0, 3), 4)


# ------------------------------------------------------- checkpoint interop
def test_checkpoint_interop_zero_and_replicated():
    """A ZeRO run's checkpoint (genuinely sharded moment files) resumes
    bit-identically onto (a) a replicated-mode wrapper, (b) a different
    K in ZeRO mode, and (c) a single-device net — via the resharded
    ``restore(mesh=)`` path — and a replicated checkpoint resumes into
    ZeRO mode."""
    x, y = make_data(n=192)
    mesh4, mesh2 = mesh_of(4), mesh_of(2)
    a = make_net()
    pw = ParallelWrapper(a, workers=4, mesh=mesh4, averaging_frequency=1,
                         update_sharding="zero")
    pw.fit(ListDataSetIterator(DataSet(x[:64], y[:64]), 16))
    ref_vec = params_vec(a)
    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        save_checkpoint(tmp, a)
        # the moments were written as genuine shards with their spec
        man = json.load(open(os.path.join(tmp, "manifest-0.json")))
        entry = man["leaves"]["updater_state/m/layer_0/W"]
        assert len(entry["shards"]) == 4
        assert entry["spec"] == [backend.AXIS_DATA]

        # (a) resume onto a replicated-mode wrapper; continue both
        b = make_net(seed=99)
        restore_checkpoint(tmp, b, mesh=mesh4)
        np.testing.assert_allclose(params_vec(b), ref_vec, atol=0)
        pw.fit(ListDataSetIterator(DataSet(x[64:128], y[64:128]), 16))
        ParallelWrapper(b, workers=4, mesh=mesh4,
                        averaging_frequency=1).fit(
            ListDataSetIterator(DataSet(x[64:128], y[64:128]), 16))
        np.testing.assert_allclose(params_vec(b), params_vec(a),
                                   rtol=RTOL, atol=ATOL)

        # (b) a different K, still ZeRO: restore on a 2-way mesh and
        # continue sharded
        c = make_net(seed=98)
        restore_checkpoint(tmp, c, mesh=mesh2)
        np.testing.assert_allclose(params_vec(c), ref_vec, atol=0)
        ParallelWrapper(c, workers=2, mesh=mesh2, averaging_frequency=1,
                        update_sharding="zero").fit(
            ListDataSetIterator(DataSet(x[64:128], y[64:128]), 16))
        assert np.isfinite(params_vec(c)).all()

        # (c) single-device net: host-gather restore, forward parity
        # against a mesh-restored copy of the SAME checkpoint
        d = make_net(seed=97)
        restore_checkpoint(tmp, d)
        np.testing.assert_allclose(params_vec(d), ref_vec, atol=0)
        e = make_net(seed=94)
        restore_checkpoint(tmp, e, mesh=mesh4)
        xq = x[:4]
        np.testing.assert_allclose(np.asarray(d.output(xq)),
                                   np.asarray(e.output(xq)),
                                   rtol=1e-5, atol=1e-6)

    # replicated checkpoint -> ZeRO resume, continuation equivalence
    r = make_net(seed=5)
    ParallelWrapper(r, workers=4, mesh=mesh4, averaging_frequency=1).fit(
        ListDataSetIterator(DataSet(x[:64], y[:64]), 16))
    with tempfile.TemporaryDirectory() as tmp:
        save_checkpoint(tmp, r)
        z = make_net(seed=96)
        restore_checkpoint(tmp, z, mesh=mesh4)
        ParallelWrapper(z, workers=4, mesh=mesh4, averaging_frequency=1,
                        update_sharding="zero").fit(
            ListDataSetIterator(DataSet(x[64:], y[64:]), 16))
        r2 = make_net(seed=95)
        restore_checkpoint(tmp, r2, mesh=mesh4)
        ParallelWrapper(r2, workers=4, mesh=mesh4,
                        averaging_frequency=1).fit(
            ListDataSetIterator(DataSet(x[64:], y[64:]), 16))
        np.testing.assert_allclose(params_vec(z), params_vec(r2),
                                   rtol=RTOL, atol=ATOL)


@pytest.mark.faults
def test_checkpoint_manager_resume_into_zero(tmp_path):
    """CheckpointManager end to end: a ZeRO wrapper saves through the
    manager mid-fit; a fresh ZeRO wrapper auto-resumes and finishes
    bit-identical to the uninterrupted run."""
    from deeplearning4j_tpu.resilience import CheckpointManager

    x, y = make_data(n=128)
    mesh = mesh_of(4)
    ref = make_net()
    ParallelWrapper(ref, workers=4, mesh=mesh, averaging_frequency=1,
                    update_sharding="zero").fit(
        ListDataSetIterator(DataSet(x, y), 16))

    a = make_net()
    cm = CheckpointManager(str(tmp_path), save_every_steps=1,
                           async_save=False)
    ParallelWrapper(a, workers=4, mesh=mesh, averaging_frequency=1,
                    update_sharding="zero", checkpoint_manager=cm).fit(
        ListDataSetIterator(DataSet(x[:64], y[:64]), 16))
    b = make_net(seed=1234)
    cm2 = CheckpointManager(str(tmp_path), save_every_steps=1,
                            async_save=False)
    ParallelWrapper(b, workers=4, mesh=mesh, averaging_frequency=1,
                    update_sharding="zero", checkpoint_manager=cm2).fit(
        ListDataSetIterator(DataSet(x, y), 16))
    np.testing.assert_allclose(params_vec(b), params_vec(ref),
                               rtol=RTOL, atol=ATOL)


# ------------------------------------------------------------- validation
def test_validation_errors():
    mesh = mesh_of(4)
    net = make_net()
    with pytest.raises(ValueError, match="update_sharding"):
        SyncTrainingMaster(mesh=mesh, update_sharding="bogus")
    with pytest.raises(ValueError, match="averaging_frequency"):
        ParallelWrapper(net, workers=4, mesh=mesh, averaging_frequency=3,
                        update_sharding="zero")
    with pytest.raises(ValueError, match="average_updaters"):
        ParallelWrapper(net, workers=4, mesh=mesh, averaging_frequency=1,
                        average_updaters=False, update_sharding="zero")
    with pytest.raises(ValueError, match="data axis"):
        SyncTrainingMaster(mesh=mesh_of(1), update_sharding="zero")
    with pytest.raises(ValueError, match="pure data-parallel"):
        zero_mod.validate_mode(
            "zero", backend.default_mesh(data=2, model=2,
                                         devices=jax.devices()[:4]))

    from deeplearning4j_tpu.parallel import TensorParallelTrainingMaster

    tp = TensorParallelTrainingMaster(
        mesh=backend.default_mesh(data=2, model=2,
                                  devices=jax.devices()[:4]))
    tp.update_sharding = "zero"     # force past the mesh validation
    tp._zero_layout = zero_mod.ZeroLayout(mesh, 4)
    with pytest.raises(ValueError, match="_param_layout"):
        tp._build_zero(net)
