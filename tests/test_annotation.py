"""Text annotation (UIMA add-on analog), stopwords, moving windows,
YAML config round-trip, profiler listener."""

from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    TextAnnotator, Window, get_stop_words, is_stop_word, pos_tag,
    remove_stop_words, sentiment_score, split_sentences, windows,
)


def test_sentence_splitting():
    text = "Dr. Smith went home. He was tired! Was it late? Yes."
    sents = split_sentences(text)
    assert sents == ["Dr. Smith went home.", "He was tired!", "Was it late?",
                     "Yes."]


def test_sentence_splitting_no_terminal():
    assert split_sentences("no punctuation here") == ["no punctuation here"]


def test_sentence_splitting_dotted_abbreviations():
    # regression: 'e.g.'/'i.e.' must not end a sentence
    assert split_sentences("See e.g. the docs.") == ["See e.g. the docs."]
    assert split_sentences("It works, i.e. it compiles.") == [
        "It works, i.e. it compiles."]


def test_single_stoplist():
    from deeplearning4j_tpu.nlp.stopwords import ENGLISH
    from deeplearning4j_tpu.nlp.tokenization import STOP_WORDS

    assert STOP_WORDS is ENGLISH


def test_pos_tagging():
    tags = dict(pos_tag(["the", "dog", "quickly", "jumped", "over", "3",
                         "wonderful", "fences", "!"]))
    assert tags["the"] == "DET"
    assert tags["dog"] == "NOUN"
    assert tags["quickly"] == "ADV"
    assert tags["jumped"] == "VERB"
    assert tags["over"] == "ADP"
    assert tags["3"] == "NUM"
    assert tags["wonderful"] == "ADJ"
    assert tags["!"] == "PUNCT"


def test_sentiment():
    assert sentiment_score("this movie was great".split()) > 0.5
    assert sentiment_score("this movie was terrible".split()) < -0.5
    # negation flips within the window
    assert sentiment_score("this was not good".split()) < 0
    assert sentiment_score("nothing emotive here".split()) == 0.0


def test_text_annotator_pipeline():
    ann = TextAnnotator()
    sents = ann.annotate("The food was great. The service was terrible.")
    assert len(sents) == 2
    assert sents[0].sentiment > 0 > sents[1].sentiment
    assert any(t.pos == "ADJ" for t in sents[1].tokens)  # "terrible"


def test_stop_words():
    assert is_stop_word("The") and not is_stop_word("tensor")
    assert "the" in get_stop_words()
    assert remove_stop_words(["the", "quick", "fox"]) == ["quick", "fox"]


def test_moving_windows():
    ws = windows(["a", "b", "c", "d"], window_size=3)
    assert len(ws) == 4
    assert ws[0].as_list() == ["<s>", "a", "b"] and ws[0].focus_word == "a"
    assert ws[3].as_list() == ["c", "d", "</s>"]
    assert all(len(w.words) == 3 for w in ws)
    with pytest.raises(ValueError):
        windows(["a"], 0)


def test_mcxent_sigmoid_warns():
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    with pytest.warns(UserWarning, match="mcxent.*sigmoid"):
        (NeuralNetConfiguration.builder().list()
         .layer(DenseLayer(n_in=4, n_out=8))
         .layer(OutputLayer(n_in=8, n_out=2, activation="sigmoid"))
         .build())
    # the defaults themselves are safe now (softmax + mcxent)
    assert OutputLayer(n_in=8, n_out=2).activation == "softmax"


def test_yaml_config_roundtrip():
    from deeplearning4j_tpu.nn.conf import (
        MultiLayerConfiguration, NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    conf = (NeuralNetConfiguration.builder().seed(9)
            .updater("adam", learning_rate=0.02).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu", l2=1e-4))
            .layer(OutputLayer(n_in=8, n_out=2, loss="mcxent",
                               activation="softmax"))
            .build())
    back = MultiLayerConfiguration.from_yaml(conf.to_yaml())
    assert back.to_json() == conf.to_json()


def test_yaml_graph_roundtrip():
    from deeplearning4j_tpu.models.graph import (
        ComputationGraph, GraphConfiguration,
    )
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    conf = (NeuralNetConfiguration.builder().seed(3).graph()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=4, n_out=8), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=2), "d")
            .set_outputs("out")
            .build())
    back = GraphConfiguration.from_yaml(conf.to_yaml())
    assert back.to_json() == conf.to_json()


def test_profiler_listener(tmp_path):
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.listeners import ProfilerListener

    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater("sgd", learning_rate=0.1).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=2)).build())
    net = MultiLayerNetwork(conf).init()
    prof = ProfilerListener(str(tmp_path), start_iteration=1, duration=2)
    net.set_listeners(prof)
    x = np.random.RandomState(0).rand(8, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[np.zeros(8, int)]
    for _ in range(5):
        net.fit(x, y)
    prof.stop()
    produced = list(Path(tmp_path).rglob("*"))
    assert any(p.is_file() for p in produced), "no trace files captured"
