"""Self-healing online learning (`-m online`): stream consumption with
quarantine, windowed incremental fit with crash replay, the promotion
state machine (gate / canary / retaining swap / watch / rollback), HTTP
transport resilience, and the full chaos acceptance drill from
docs/online.md."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.observability import (
    MetricsRegistry, get_flight_recorder,
)
from deeplearning4j_tpu.online import (
    OnlineLearningPipeline, PromotionManager, StreamConsumer,
    default_gate_rules,
)
from deeplearning4j_tpu.resilience import (
    CheckpointManager, FaultInjector, RetryPolicy, inject_faults,
)
from deeplearning4j_tpu.serving import ServingEngine
from deeplearning4j_tpu.streaming import MessageBroker, dataset_to_json

pytestmark = pytest.mark.online

N_IN, N_OUT = 2, 2


def small_net(seed=7, lr=0.3):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater("sgd", learning_rate=lr).list()
            .layer(DenseLayer(n_in=N_IN, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=N_OUT, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def task_batch(rng, n=16, poisoned=False):
    """Linearly separable 2-class task (fast for plain SGD, so healthy
    windows measurably improve and poisoned ones measurably regress)."""
    x = rng.rand(n, N_IN).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 1.0).astype(np.int64)
    if poisoned:
        y = 1 - y      # inverted labels: valid records, regressed model
    lab = np.zeros((n, N_OUT), np.float32)
    lab[np.arange(n), y] = 1.0
    return DataSet(x, lab)


def publish_window(broker, topic, rng, n_batches, batch=16, poisoned=False):
    for _ in range(n_batches):
        broker.publish(topic, dataset_to_json(
            task_batch(rng, batch, poisoned=poisoned),
            meta={"ts": time.time()}))


def make_engine(registry=None, **kw):
    kw.setdefault("max_batch", 16)
    kw.setdefault("max_queue", 4096)
    kw.setdefault("example", np.zeros((N_IN,), np.float32))
    engine = ServingEngine(small_net(), registry=registry, **kw)
    engine.start()
    return engine


def fast_pm(engine, holdout, registry=None, **kw):
    kw.setdefault("gate_rules", default_gate_rules(max_loss_regression=0.35))
    kw.setdefault("canary_fraction", 1.0)
    kw.setdefault("canary_min_requests", 2)
    kw.setdefault("canary_timeout_s", 10.0)
    kw.setdefault("watch_window_s", 0.2)
    kw.setdefault("watch_poll_s", 0.02)
    return PromotionManager(engine, eval_set=holdout, registry=registry,
                            **kw)


def events(kind):
    return [e for e in get_flight_recorder().events() if e.kind == kind]


# ------------------------------------------------------------ consumer
def test_consumer_quarantines_bad_records_and_counts():
    reg = MetricsRegistry()
    broker = MessageBroker(registry=reg)
    quarantine = broker.subscribe("t.quarantine")
    cons = StreamConsumer("t", broker=broker, registry=reg)
    rng = np.random.RandomState(0)

    good = task_batch(rng, 4)
    broker.publish("t", dataset_to_json(good))
    nan = task_batch(rng, 4)
    nan.features[0, 0] = np.nan
    broker.publish("t", dataset_to_json(nan))
    broker.publish("t", "this is not json")
    lies = json.loads(dataset_to_json(task_batch(rng, 4)))
    lies["features"]["shape"] = [400, 400]     # payload-length lie
    broker.publish("t", json.dumps(lies))
    good2 = task_batch(rng, 4)
    broker.publish("t", dataset_to_json(good2))

    got1 = cons.poll_dataset(timeout=2.0)
    got2 = cons.poll_dataset(timeout=2.0)
    assert got1 is not None and got2 is not None
    np.testing.assert_allclose(got1[0].features, good.features)
    np.testing.assert_allclose(got2[0].features, good2.features)
    assert cons.poll_dataset(timeout=0.1) is None
    assert cons.quarantined == 3 and cons.delivered == 2

    reasons = set()
    while quarantine.qsize():
        letter = json.loads(quarantine.get_nowait())
        reasons.add(letter["reason"])
        assert letter["topic"] == "t" and "payload" in letter
    assert reasons == {"non_finite", "bad_json", "shape_mismatch"}
    assert reg.get_value("dl4j_stream_quarantined_total", topic="t",
                         reason="non_finite") == 1
    assert len(events("stream_quarantined")) >= 3


def test_consumer_http_retries_through_broker_restart():
    """Satellite: dead/restarted broker endpoint — the consumer backs
    off through the outage and resumes the SAME subscription with no
    duplicated and no lost messages among those published after the
    broker came back."""
    rng = np.random.RandomState(1)
    broker = MessageBroker()
    port = broker.serve()
    url = f"http://127.0.0.1:{port}"

    def publish_http(ds):
        req = urllib.request.Request(
            f"{url}/publish/t", data=dataset_to_json(ds).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=5)

    retry = RetryPolicy(max_retries=40, base_delay_s=0.05, max_delay_s=0.15,
                        seed=3, component="test-consumer")
    cons = StreamConsumer("t", url=url, sub_id="s1", retry_policy=retry)
    # HTTP subscriptions are created server-side by the first poll
    assert cons.poll_dataset(timeout=0.2) is None
    first = task_batch(rng, 4)
    publish_http(first)
    got = cons.poll_dataset(timeout=5.0)
    np.testing.assert_allclose(got[0].features, first.features)

    broker.stop()   # the endpoint dies mid-stream

    def restart():
        time.sleep(0.4)
        broker2 = MessageBroker()
        broker2.serve(port=port)

    threading.Thread(target=restart, daemon=True).start()
    # this poll spans the outage: it must retry with backoff until the
    # restarted endpoint answers (an empty poll re-creates the sub)
    assert cons.poll_dataset(timeout=0.3) is None
    assert retry.retries > 0, "the outage never exercised the backoff path"

    after = [task_batch(rng, 4) for _ in range(3)]
    for ds in after:
        publish_http(ds)
    received = [cons.poll_dataset(timeout=5.0) for _ in range(3)]
    assert all(r is not None for r in received)
    for r, ds in zip(received, after):       # ordered, exactly once
        np.testing.assert_allclose(r[0].features, ds.features)
    assert cons.poll_dataset(timeout=0.2) is None   # no duplicates


# ----------------------------------------------------------- promotion
def test_gate_rejects_regressed_candidate_registry_untouched():
    reg = MetricsRegistry()
    engine = make_engine(registry=reg)
    try:
        rng = np.random.RandomState(2)
        holdout = task_batch(rng, 48)
        pm = fast_pm(engine, holdout, registry=reg,
                     canary_fraction=None)       # gate is under test
        v0 = engine.models.active("default").version

        poisoned = small_net(seed=9, lr=1.0)
        for _ in range(12):
            poisoned.fit(*_xy(task_batch(rng, 32, poisoned=True)))
        res = pm.consider(poisoned, "bad-candidate")

        assert res.outcome == "rejected"
        assert engine.models.active("default").version == v0
        assert reg.get_value("dl4j_promotions_total", model="default",
                             outcome="rejected") == 1
        ev = [e for e in events("promotion_rejected")
              if e.attrs.get("candidate") == "bad-candidate"]
        assert ev and "no_loss_regression_vs_active" in \
            ev[-1].attrs["failed_rules"]
    finally:
        engine.stop()


def _xy(ds):
    return ds.features, ds.labels


def test_canary_rejects_erroring_candidate():
    reg = MetricsRegistry()
    engine = make_engine(registry=reg)
    try:
        rng = np.random.RandomState(3)
        holdout = task_batch(rng, 32)

        class ExplodesOnRealTraffic:
            """Scores fine offline and warms up fine (zeros), but raises
            on live rows — exactly the failure class a canary exists to
            absorb before a full swap would."""

            def output(self, x):
                if np.asarray(x).max() > 0:
                    raise RuntimeError("boom on real traffic")
                return np.zeros((len(x), N_OUT), np.float32)

            def score(self, x, y, fmask=None, lmask=None):
                return 0.5

        pm = fast_pm(engine, holdout, registry=reg, gate_rules=[],
                     canary_max_error_rate=0.0)
        v0 = engine.models.active("default").version
        res = pm.consider(ExplodesOnRealTraffic(), "exploder")
        assert res.outcome == "canary_rejected"
        assert res.canary["bad"] > 0
        assert engine.models.active("default").version == v0
        assert "default:canary" not in engine.models.names()
        assert reg.get_value("dl4j_promotions_total", model="default",
                             outcome="canary_rejected") == 1
        # the primary kept serving fine throughout
        out = engine.predict(holdout.features[:4])
        assert np.isfinite(np.asarray(out)).all()
    finally:
        engine.stop()


def test_promote_commit_and_freshness_gauge():
    reg = MetricsRegistry()
    engine = make_engine(registry=reg)
    try:
        rng = np.random.RandomState(4)
        holdout = task_batch(rng, 48)
        pm = fast_pm(engine, holdout, registry=reg)
        cand = small_net(seed=11)
        ts = time.time() - 2.0
        res = pm.consider(cand, "good-candidate", event_ts=ts)
        assert res.outcome == "promoted"
        assert res.freshness_s is not None and res.freshness_s >= 2.0
        assert reg.get_value("dl4j_online_model_freshness_seconds",
                             model="default") >= 2.0
        # the rollback window is CLOSED after commit
        assert engine.models.retained("default") is None
        with pytest.raises(Exception):
            engine.rollback("default")
    finally:
        engine.stop()


def test_watch_regression_triggers_automatic_rollback():
    reg = MetricsRegistry()
    engine = make_engine(registry=reg)
    try:
        rng = np.random.RandomState(5)
        holdout = task_batch(rng, 32)
        baseline = np.asarray(
            engine.models.active("default").model.output(holdout.features))

        # the forced post-swap metric regression: every watch poll fires
        # requests with an impossible deadline -> real `deadline`
        # statuses on the serving counters
        def poisoned_sleep(dt):
            for _ in range(3):
                try:
                    engine.predict(holdout.features[:4], deadline_s=1e-6)
                except Exception:
                    pass
            time.sleep(min(dt, 0.02))

        pm = fast_pm(engine, holdout, registry=reg,
                     gate_rules=[], canary_fraction=None,
                     watch_window_s=0.5, watch_min_requests=3,
                     watch_max_error_rate=0.3, sleep=poisoned_sleep)
        v0 = engine.models.active("default").version
        res = pm.consider(small_net(seed=12), "watched-candidate")
        assert res.outcome == "rolled_back"
        active = engine.models.active("default")
        assert active.version == v0, "rollback must restore the previous"
        assert reg.get_value("dl4j_promotions_total", model="default",
                             outcome="rolled_back") == 1
        assert events("rollback"), "engine rollback flight event missing"
        # and the restored version actually serves the OLD weights
        out = np.asarray(engine.predict(holdout.features))
        np.testing.assert_allclose(out, baseline, atol=1e-5)
    finally:
        engine.stop()


# ------------------------------------------------------------- pipeline
def test_pipeline_trains_windows_and_promotes(tmp_path):
    reg = MetricsRegistry()
    engine = make_engine(registry=reg)
    try:
        rng = np.random.RandomState(6)
        broker = MessageBroker(registry=reg)
        holdout = task_batch(rng, 48)
        cm = CheckpointManager(str(tmp_path), keep=5, async_save=False,
                               registry=reg)
        pipe = OnlineLearningPipeline(
            small_net(seed=7), engine, topic="train", broker=broker,
            checkpoint_manager=cm,
            promotion=fast_pm(engine, holdout, registry=reg),
            window_size=2, poll_timeout_s=0.3, registry=reg)
        publish_window(broker, "train", rng, 4)
        summary = pipe.run(max_windows=2)
        assert summary["windows"] == 2
        assert summary["outcomes"].get("promoted") == 2
        assert summary["active_version"] == 3    # initial + 2 promotions
        assert len(summary["freshness_s"]) == 2
        assert reg.get_value("dl4j_online_windows_total",
                             status="trained") == 2
        # each window boundary committed a checkpoint (anchor + 2)
        assert len(cm.all_steps()) >= 3
    finally:
        engine.stop()


def test_pipeline_partial_window_still_trains(tmp_path):
    reg = MetricsRegistry()
    engine = make_engine(registry=reg)
    try:
        rng = np.random.RandomState(8)
        broker = MessageBroker(registry=reg)
        pipe = OnlineLearningPipeline(
            small_net(seed=7), engine, topic="train", broker=broker,
            checkpoint_manager=CheckpointManager(
                str(tmp_path), async_save=False, registry=reg),
            promotion=fast_pm(engine, task_batch(rng, 32), registry=reg),
            window_size=8, poll_timeout_s=0.3, registry=reg)
        publish_window(broker, "train", rng, 2)   # < window_size
        summary = pipe.run(max_windows=1)
        assert summary["windows"] == 1
        assert summary["records_delivered"] == 2
    finally:
        engine.stop()


def test_trainer_crash_replay_is_resume_equivalent(tmp_path):
    """A fatal mid-window crash restores the window boundary and replays
    the window from memory: the final weights are bit-identical to an
    uninterrupted run over the same stream, and nothing was re-consumed
    from the broker."""
    import jax

    def run(tmp, crash):
        reg = MetricsRegistry()
        engine = make_engine(registry=reg)
        try:
            rng = np.random.RandomState(9)
            broker = MessageBroker(registry=reg)
            net = small_net(seed=13)
            pipe = OnlineLearningPipeline(
                net, engine, topic="train", broker=broker,
                checkpoint_manager=CheckpointManager(
                    str(tmp), keep=5, async_save=False, registry=reg),
                promotion=fast_pm(engine, task_batch(rng, 32), registry=reg,
                                  canary_fraction=None, watch_window_s=0.0),
                window_size=3, poll_timeout_s=0.3, registry=reg,
                retry_policy=RetryPolicy(max_retries=1, base_delay_s=0.01,
                                         component="online", registry=reg))
            publish_window(broker, "train", rng, 6)
            inj = FaultInjector(seed=1)
            if crash:
                # step 4 = inside the SECOND window (steps 3,4,5)
                inj.fail_at_step(4, component="MultiLayerNetwork",
                                 transient=False)
            with inject_faults(inj):
                summary = pipe.run(max_windows=2)
            assert summary["windows"] == 2
            if crash:
                assert [e for e in inj.injected
                        if e["kind"] == "step_fault"], "fault never fired"
                assert reg.get_value("dl4j_online_windows_total",
                                     status="retried") == 1
                assert events("online_trainer_crash")
            assert pipe.consumer.delivered == 6   # stream never re-read
            return jax.tree_util.tree_leaves(net.params)
        finally:
            engine.stop()

    clean = run(tmp_path / "clean", crash=False)
    crashed = run(tmp_path / "crashed", crash=True)
    for a, b in zip(clean, crashed):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pipeline_works_with_computation_graph(tmp_path):
    """Both fit-loop facades drive the windowed mini-epochs."""
    from deeplearning4j_tpu.models.graph import ComputationGraph

    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater("sgd", learning_rate=0.3).graph()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=N_IN, n_out=8,
                                       activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=N_OUT,
                                          loss="mcxent",
                                          activation="softmax"), "d")
            .set_outputs("out").build())
    net = ComputationGraph(conf).init()
    serving_model = ComputationGraph(conf).init()

    reg = MetricsRegistry()
    engine = ServingEngine(serving_model, max_batch=16, registry=reg,
                           example=np.zeros((N_IN,), np.float32))
    engine.start()
    try:
        rng = np.random.RandomState(10)
        broker = MessageBroker(registry=reg)
        pipe = OnlineLearningPipeline(
            net, engine, topic="train", broker=broker,
            checkpoint_manager=CheckpointManager(
                str(tmp_path), async_save=False, registry=reg),
            promotion=fast_pm(engine, task_batch(rng, 32), registry=reg),
            window_size=2, poll_timeout_s=0.3, registry=reg)
        publish_window(broker, "train", rng, 2)
        summary = pipe.run(max_windows=1)
        assert summary["outcomes"].get("promoted") == 1
        assert engine.models.active("default").model_type \
            == "ComputationGraph"
    finally:
        engine.stop()


# ------------------------------------------------------- chaos acceptance
def test_chaos_full_loop(tmp_path):
    """The acceptance drill: injected bad records, one fatal trainer
    crash mid-window, one deliberately regressed candidate, and a forced
    post-swap metric regression — the pipeline quarantines, auto-resumes,
    refuses the regressed candidate by name, promotes the next healthy
    one, and rolls back automatically, while concurrent serving clients
    see correct answers with zero dropped requests."""
    reg = MetricsRegistry()
    engine = make_engine(registry=reg)
    rng = np.random.RandomState(42)
    broker = MessageBroker(registry=reg)
    quarantine = broker.subscribe("train.quarantine")
    holdout = task_batch(rng, 64)

    # -------- concurrent serving load, asserting correctness per reply
    stop = threading.Event()
    failures, served = [], [0]

    def client():
        # own RNG: the shared `rng` drives the published training stream
        # and must stay deterministic
        feats = np.random.RandomState(123).rand(4, N_IN).astype(np.float32)
        while not stop.is_set():
            try:
                out = np.asarray(engine.predict(feats, deadline_s=10.0))
                if out.shape != (4, N_OUT) or not np.isfinite(out).all() \
                        or abs(float(out[0].sum()) - 1.0) > 1e-3:
                    failures.append(f"bad output {out!r}")
                served[0] += 1
            except Exception as e:
                failures.append(repr(e))

    clients = [threading.Thread(target=client, daemon=True)
               for _ in range(3)]
    for t in clients:
        t.start()

    # -------- forced post-swap regression, armed for the LAST window
    armed = {"on": False}

    def chaos_sleep(dt):
        # fire only while a rollback window is OPEN (post-swap watch):
        # the canary phase must judge the candidate on clean traffic
        if armed["on"] and engine.models.retained("default") is not None:
            for _ in range(4):
                try:
                    engine.predict(holdout.features[:4], deadline_s=1e-6)
                except Exception:
                    pass
        time.sleep(min(dt, 0.02))

    cm = CheckpointManager(str(tmp_path), keep=8, async_save=False,
                           registry=reg)
    # watch rules: the stock error-rate/probe rules PLUS an absolute
    # post-swap deadline-burst cap — the concurrent clients' ok volume
    # must not be able to dilute the forced regression below a rate
    # threshold, so the chaos assertion stays deterministic under load
    from deeplearning4j_tpu.observability import HealthRule
    from deeplearning4j_tpu.online import default_watch_rules

    def _deadline_burst(e):
        n = (e or {}).get("statuses", {}).get("deadline", 0)
        return (n <= 2, n, "post-swap deadline failures vs burst cap 2")

    pm = fast_pm(engine, holdout, registry=reg,
                 gate_rules=default_gate_rules(max_loss_regression=0.15),
                 watch_rules=default_watch_rules(max_error_rate=0.3,
                                                 min_requests=3)
                 + [HealthRule("deadline_burst", "predicate",
                               fn=_deadline_burst)],
                 watch_window_s=0.4, watch_poll_s=0.05,
                 sleep=chaos_sleep)
    net = small_net(seed=5, lr=1.0)
    pipe = OnlineLearningPipeline(
        net, engine, topic="train", broker=broker, checkpoint_manager=cm,
        promotion=pm, window_size=3, poll_timeout_s=0.5, registry=reg)

    try:
        # ---- window 1: healthy, laced with bad records + a trainer crash
        nan = task_batch(rng, 16)
        nan.features[0, 1] = np.inf
        broker.publish("train", dataset_to_json(nan))
        broker.publish("train", "garbage{{{")
        publish_window(broker, "train", rng, 3, batch=32)
        inj = FaultInjector(seed=7).fail_at_step(
            1, component="MultiLayerNetwork", transient=False)
        with inject_faults(inj):
            r1 = pipe.run(max_windows=1)
        assert [e for e in inj.injected if e["kind"] == "step_fault"]
        assert r1["outcomes"].get("promoted") == 1
        assert pipe.consumer.quarantined == 2
        assert reg.get_value("dl4j_online_windows_total",
                             status="retried") == 1
        v_good = engine.models.active("default").version

        # ---- window 2: poisoned-but-valid labels -> regressed candidate
        publish_window(broker, "train", rng, 3, batch=32, poisoned=True)
        r2 = pipe.run(max_windows=1)
        assert r2["outcomes"].get("rejected") == 1
        assert engine.models.active("default").version == v_good
        named = [e for e in events("promotion_rejected")
                 if str(e.attrs.get("candidate", "")).startswith("window-2")]
        assert named, "the flight event must name the refused candidate"

        # ---- window 3: healthy again -> promotes through canary + swap
        publish_window(broker, "train", rng, 3, batch=32)
        r3 = pipe.run(max_windows=1)
        assert r3["outcomes"].get("promoted") == 2
        v_promoted = engine.models.active("default").version
        assert v_promoted > v_good

        # ---- window 4: healthy candidate, but serving regresses after
        # the swap (forced deadline failures) -> automatic rollback
        publish_window(broker, "train", rng, 3, batch=32)
        armed["on"] = True
        r4 = pipe.run(max_windows=1)
        armed["on"] = False
        assert r4["outcomes"].get("rolled_back") == 1
        assert engine.models.active("default").version == v_promoted, \
            "rollback must restore the last promoted version"
        assert reg.get_value("dl4j_promotions_total", model="default",
                             outcome="rolled_back") == 1
    finally:
        stop.set()
        for t in clients:
            t.join(timeout=10)
        engine.stop()
        cm.close()

    # ---- the whole drill dropped ZERO legitimate requests
    assert not failures, failures[:5]
    assert served[0] > 0
    # quarantine preserved both dead letters with their reasons
    letters = []
    while quarantine.qsize():
        letters.append(json.loads(quarantine.get_nowait()))
    assert {l["reason"] for l in letters} == {"non_finite", "bad_json"}


# --------------------------------------------------- review-hardening pins
def test_watch_error_rate_ignores_sheds_in_denominator():
    """95 queue_full deltas must not dilute 2 failures out of 5 judged
    requests below the SLO (same 'judged' convention as the canary)."""
    reg = MetricsRegistry()
    engine = make_engine(registry=reg)
    try:
        pm = fast_pm(engine, task_batch(np.random.RandomState(0), 16),
                     registry=reg)
        base = pm._status_counts()
        with engine._breakdown_lock:
            tally = engine._model_status.setdefault("default", {})
            for status, n in (("ok", 3), ("error", 2), ("queue_full", 95)):
                tally[status] = tally.get(status, 0) + n
        extra = pm._watch_extra(base, True, None)
        assert extra["requests"] == 5         # judged only
        assert extra["bad"] == 2
        assert abs(extra["error_rate"] - 0.4) < 1e-9
        assert extra["statuses"]["queue_full"] == 95   # still visible
    finally:
        engine.stop()


def test_canary_rejects_nan_outputs_via_probe():
    """A candidate that returns NaN without raising scores 'ok' on
    transport tallies — the canary probe verdict must catch it BEFORE
    the full swap."""
    reg = MetricsRegistry()
    engine = make_engine(registry=reg)
    try:
        rng = np.random.RandomState(17)
        holdout = task_batch(rng, 32)

        class NaNModel:
            def output(self, x):
                return np.full((len(np.asarray(x)), N_OUT), np.nan,
                               np.float32)

            def score(self, x, y, fmask=None, lmask=None):
                return 0.5

        pm = fast_pm(engine, holdout, registry=reg, gate_rules=[])
        v0 = engine.models.active("default").version
        res = pm.consider(NaNModel(), "nan-candidate")
        assert res.outcome == "canary_rejected"
        assert "NaN" in res.canary["probe_detail"]
        assert engine.models.active("default").version == v0
    finally:
        engine.stop()


def test_continuous_mode_survives_traffic_lull(tmp_path):
    """start() runs the loop in continuous mode: a quiet period longer
    than poll_timeout_s must NOT silently end it."""
    reg = MetricsRegistry()
    engine = make_engine(registry=reg)
    try:
        rng = np.random.RandomState(18)
        broker = MessageBroker(registry=reg)
        pipe = OnlineLearningPipeline(
            small_net(seed=7), engine, topic="train", broker=broker,
            checkpoint_manager=CheckpointManager(
                str(tmp_path), async_save=False, registry=reg),
            promotion=fast_pm(engine, task_batch(rng, 32), registry=reg),
            window_size=2, poll_timeout_s=0.2, registry=reg)
        pipe.start()
        time.sleep(0.8)          # several idle poll timeouts
        assert pipe._thread.is_alive(), "continuous mode exited on a lull"
        publish_window(broker, "train", rng, 2)
        deadline = time.monotonic() + 30
        while not pipe.results and time.monotonic() < deadline:
            time.sleep(0.05)
        assert pipe.results and pipe.results[0]["outcome"] == "promoted"
        pipe.stop()
        assert not pipe._thread or not pipe._thread.is_alive()
    finally:
        engine.stop()


def test_consumer_retains_dead_letters_locally():
    """The broker has no retention: dead letters published before anyone
    subscribed the quarantine topic must still be inspectable on the
    consumer itself."""
    reg = MetricsRegistry()
    broker = MessageBroker(registry=reg)   # note: NO quarantine subscriber
    cons = StreamConsumer("t", broker=broker, registry=reg)
    bad = task_batch(np.random.RandomState(0), 4)
    bad.features[0, 0] = np.nan
    broker.publish("t", dataset_to_json(bad))
    broker.publish("t", "junk{{")
    assert cons.poll_dataset(timeout=0.3) is None
    letters = list(cons.dead_letters)
    assert [l["reason"] for l in letters] == ["non_finite", "bad_json"]
    assert all("payload" in l for l in letters)
