"""Lazy score_value: training loops must not host-sync per step.

Reference contrast: the reference pushes a host double to listeners every
iteration (``BaseOptimizer.java`` score update); on TPU that per-step
``float(loss)`` serializes dispatch.  Here the device scalar is stored
as-is and fetched only on read (``models/common.py``).
"""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer


def _net(seed=0):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(seed)
         .updater("sgd", learning_rate=0.1).list()
         .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
         .layer(OutputLayer(n_in=16, n_out=3)).build())
    ).init()


def _data(rng, n=64):
    x = rng.rand(n, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return x, y


def test_fit_keeps_loss_on_device(rng):
    net = _net()
    x, y = _data(rng)
    net.fit(ListDataSetIterator(DataSet(x, y), 16))
    # the loop stored the raw device scalar — proof no float() ran per step
    assert isinstance(net._score, jax.Array)
    assert not isinstance(net._score, float)


def test_score_value_fetches_and_caches(rng):
    net = _net()
    x, y = _data(rng)
    net.fit(x, y)
    first = net.score_value
    assert np.isfinite(first)
    # after the read, the fetched float is cached
    assert isinstance(net._score, float)
    assert net.score_value == first


def test_score_value_nan_before_training():
    net = _net()
    assert np.isnan(net.score_value)


def test_listener_reads_still_work(rng):
    from deeplearning4j_tpu.optimize.listeners import CollectScoresIterationListener

    net = _net()
    lst = CollectScoresIterationListener(frequency=1)
    net.set_listeners(lst)
    x, y = _data(rng)
    net.fit(ListDataSetIterator(DataSet(x, y), 16))
    assert len(lst.scores) == 4
    assert all(np.isfinite(s) for _, s in lst.scores)


def test_graph_fit_keeps_loss_on_device(rng):
    from deeplearning4j_tpu.models.graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration

    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater("sgd", learning_rate=0.1).graph()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=8, n_out=16, activation="relu"), "in")
            .add_layer("out", OutputLayer(n_in=16, n_out=3), "d")
            .set_outputs("out").build())
    net = ComputationGraph(conf).init()
    x, y = _data(rng)
    net.fit(x, y)
    assert isinstance(net._score, jax.Array)
    assert np.isfinite(net.score_value)
