"""Generates the committed serialization-regression corpus.

Run once per format change:  python tests/make_regression_fixtures.py

Mirrors the reference's ``RegressionTest050`` strategy
(``deeplearning4j-core/.../regressiontest/RegressionTest050.java:33-124``):
checkpoints produced by an earlier build are committed and every later
build must keep loading them bit-for-bit — the backward-compat contract on
the zip format (config.json + params + updater state).
"""

import json
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

FIXTURES = Path(__file__).parent / "regression_fixtures"


def make_mlp():
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    conf = (NeuralNetConfiguration.builder().seed(42)
            .updater("adam", learning_rate=0.01).list()
            .layer(DenseLayer(n_in=6, n_out=10, activation="tanh",
                              weight_init="xavier", l2=1e-4))
            .layer(OutputLayer(n_in=10, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def make_cnn():
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers import (
        ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer,
    )

    conf = (NeuralNetConfiguration.builder().seed(42)
            .updater("nesterovs", learning_rate=0.02).list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=2, loss="mcxent", activation="softmax"))
            .set_input_type(InputType.convolutional_flat(8, 8, 1))
            .build())
    return MultiLayerNetwork(conf).init()


def make_lstm():
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import GravesLSTM, RnnOutputLayer

    conf = (NeuralNetConfiguration.builder().seed(42)
            .updater("rmsprop", learning_rate=0.01).list()
            .layer(GravesLSTM(n_in=5, n_out=8, activation="tanh"))
            .layer(RnnOutputLayer(n_in=8, n_out=4, loss="mcxent",
                                  activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def make_graph():
    """DAG fixture: merge of two inputs (the CG zip layout must stay
    restorable too)."""
    from deeplearning4j_tpu.models.graph import ComputationGraph
    from deeplearning4j_tpu.models.vertices import MergeVertex
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    b = (NeuralNetConfiguration.builder().seed(42)
         .updater("adam", learning_rate=0.01).graph()
         .add_inputs("a", "b"))
    b.add_layer("da", DenseLayer(n_in=3, n_out=6, activation="relu"), "a")
    b.add_layer("db", DenseLayer(n_in=2, n_out=6, activation="relu"), "b")
    b.add_vertex("m", MergeVertex(), "da", "db")
    b.add_layer("out", OutputLayer(n_in=12, n_out=2), "m")
    return ComputationGraph(b.set_outputs("out").build()).init()


def make_transformer():
    """Composite-layer fixture (ResidualBlock + attention + layernorm nest
    in the zip manifest)."""
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    return transformer_char_lm(vocab_size=7, d_model=8, n_heads=2, layers=1,
                               seed=42)


def make_transformer_v2():
    """Modern-attention fixture: RoPE + GQA + sliding window must survive
    the config round-trip forever once this zip is committed."""
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    return transformer_char_lm(vocab_size=7, d_model=8, n_heads=2, layers=1,
                               seed=43, rope=True, n_kv_heads=1, window=4)


def main():
    from deeplearning4j_tpu.models.serialization import write_model

    FIXTURES.mkdir(exist_ok=True)
    rs = np.random.RandomState(7)
    tid = rs.randint(0, 7, (2, 6))
    cases = {
        "mlp": (make_mlp(), rs.rand(4, 6).astype(np.float32),
                np.eye(3, dtype=np.float32)[rs.randint(0, 3, 4)]),
        "cnn": (make_cnn(), rs.rand(4, 64).astype(np.float32),
                np.eye(2, dtype=np.float32)[rs.randint(0, 2, 4)]),
        "lstm": (make_lstm(), rs.rand(2, 6, 5).astype(np.float32),
                 np.eye(4, dtype=np.float32)[rs.randint(0, 4, (2, 6))]),
        "transformer": (make_transformer(), tid.astype(np.float32),
                        np.eye(7, dtype=np.float32)[np.roll(tid, -1, 1)]),
        "transformer_v2": (make_transformer_v2(), tid.astype(np.float32),
                           np.eye(7, dtype=np.float32)[np.roll(tid, -1, 1)]),
    }
    # INCREMENTAL: a case whose zip is already committed is an old-build
    # artifact — regenerating it would destroy exactly the backward-compat
    # evidence the corpus exists to provide.  Delete a zip deliberately to
    # regenerate that case (format-version bumps only).
    meta_path = FIXTURES / "meta.json"
    meta = json.loads(meta_path.read_text()) if meta_path.exists() else {}

    def complete(name):
        """All artifacts present (zip + npys + meta entry)."""
        return ((FIXTURES / f"{name}.zip").exists()
                and (FIXTURES / f"{name}_input.npy").exists()
                and (FIXTURES / f"{name}_expected.npy").exists()
                and name in meta)

    for name, (net, x, y) in cases.items():
        if complete(name):
            print(f"  {name}: exists, kept")
            continue
        if (FIXTURES / f"{name}.zip").exists():
            # zip committed but sidecars/meta lost: NEVER regenerate the
            # old-build zip — rebuild the sidecars FROM it instead, so the
            # backward-compat evidence survives
            from deeplearning4j_tpu.models.serialization import load_model

            old = load_model(FIXTURES / f"{name}.zip")
            np.save(FIXTURES / f"{name}_input.npy", x)
            np.save(FIXTURES / f"{name}_expected.npy",
                    np.asarray(old.output(x)))
            meta[name] = {"score": float(old.score_value)
                          if old.score_value == old.score_value else None,
                          "iterations": old.iteration}
            print(f"  {name}: zip kept, sidecars rebuilt from it "
                  "(NOTE: output baseline re-derived by the CURRENT build — "
                  "old-build output parity is no longer what this case "
                  "checks; restore the committed sidecars if possible)")
            continue
        for _ in range(3):  # non-trivial updater state
            net.fit(x, y)
        write_model(net, FIXTURES / f"{name}.zip")
        out = np.asarray(net.output(x))
        np.save(FIXTURES / f"{name}_input.npy", x)
        np.save(FIXTURES / f"{name}_expected.npy", out)
        meta[name] = {"score": float(net.score_value),
                      "iterations": net.iteration}

    # CG fixture (two inputs — stored as separate arrays)
    graph_ok = ((FIXTURES / "graph.zip").exists()
                and all((FIXTURES / f"graph_{s}.npy").exists()
                        for s in ("input_a", "input_b", "expected"))
                and "graph" in meta)
    if graph_ok:
        print("  graph: exists, kept")
    elif (FIXTURES / "graph.zip").exists():
        # same zip-preservation rule as the MLN cases
        from deeplearning4j_tpu.models.serialization import load_model

        old = load_model(FIXTURES / "graph.zip")
        xa = rs.rand(4, 3).astype(np.float32)
        xb = rs.rand(4, 2).astype(np.float32)
        np.save(FIXTURES / "graph_input_a.npy", xa)
        np.save(FIXTURES / "graph_input_b.npy", xb)
        np.save(FIXTURES / "graph_expected.npy",
                np.asarray(old.output({"a": xa, "b": xb})))
        meta["graph"] = {"score": None, "iterations": old.iteration}
        print("  graph: zip kept, sidecars rebuilt from it "
              "(NOTE: output baseline re-derived by the CURRENT build)")
    else:
        cg = make_graph()
        xa = rs.rand(4, 3).astype(np.float32)
        xb = rs.rand(4, 2).astype(np.float32)
        yg = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 4)]
        for _ in range(3):
            cg.fit({"a": xa, "b": xb}, yg)
        write_model(cg, FIXTURES / "graph.zip")
        np.save(FIXTURES / "graph_input_a.npy", xa)
        np.save(FIXTURES / "graph_input_b.npy", xb)
        np.save(FIXTURES / "graph_expected.npy",
                np.asarray(cg.output({"a": xa, "b": xb})))
        meta["graph"] = {"score": float(cg.score_value),
                         "iterations": cg.iteration}
    meta_path.write_text(json.dumps(meta, indent=2))
    print("fixtures written to", FIXTURES)


if __name__ == "__main__":
    main()
