"""Out-of-core GloVe co-occurrence (spill runs + external merge) and the
embedding-quality metric (wordsNearest cluster purity — the text8-class
sanity check runnable without network egress).

Reference: ``models/glove/AbstractCoOccurrences.java`` (binary spill files,
shadow-copy round buffers) — capability parity: corpora whose co-occurrence
table exceeds the pair budget still train, with identical counts.
"""

import json

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import Glove
from deeplearning4j_tpu.nlp.glove import CoOccurrences, SpillingCoOccurrences
from deeplearning4j_tpu.nlp.vocab import (
    Sequence, VocabConstructor, VocabWord,
)


def synthetic_corpus(n=400, seed=7):
    rs = np.random.RandomState(seed)
    weather = ["rain", "snow", "storm", "cloud", "wind", "sun"]
    finance = ["bank", "money", "stock", "market", "trade", "price"]
    out = []
    for i in range(n):
        topic = weather if i % 2 == 0 else finance
        out.append(" ".join(rs.choice(topic, size=10)))
    return out


def _vocab(corpus):
    def seqs():
        for s in corpus:
            seq = Sequence()
            for t in s.split():
                seq.add_element(VocabWord(label=t))
            yield seq

    return VocabConstructor(min_element_frequency=1).build_vocab(seqs())


def _tokens(corpus):
    return [s.split() for s in corpus]


def test_spilling_counts_match_in_ram():
    corpus = synthetic_corpus(200)
    vocab = _vocab(corpus)
    ram = CoOccurrences(vocab, window=4).fit_sentences(_tokens(corpus))
    spill = SpillingCoOccurrences(vocab, window=4, memory_pairs=16)
    spill.fit_sentences(_tokens(corpus))
    assert spill.n_spills > 1, "budget of 16 pairs must force spills"

    r1, c1, v1 = ram.as_arrays()
    order = np.argsort(r1.astype(np.int64) * len(vocab) + c1)
    r2, c2, v2 = spill.as_arrays()  # merged output is key-sorted
    np.testing.assert_array_equal(r1[order], r2)
    np.testing.assert_array_equal(c1[order], c2)
    np.testing.assert_allclose(v1[order], v2, rtol=1e-5)
    spill.close()


def test_spilling_stream_chunks_bounded():
    corpus = synthetic_corpus(100)
    vocab = _vocab(corpus)
    spill = SpillingCoOccurrences(vocab, window=3, memory_pairs=8)
    spill.fit_sentences(_tokens(corpus))
    chunks = list(spill.stream_chunks(chunk_size=10))
    assert all(len(r) <= 10 for r, _, _ in chunks[:-1])
    # keys unique across the whole stream
    all_keys = np.concatenate(
        [r.astype(np.int64) * len(vocab) + c for r, c, _ in chunks])
    assert len(np.unique(all_keys)) == len(all_keys)
    spill.close()


def test_glove_trains_out_of_core():
    glove = (Glove.Builder()
             .iterate(synthetic_corpus(400))
             .layer_size(24)
             .window_size(4)
             .epochs(12)
             .learning_rate(0.1)
             .min_word_frequency(2)
             .seed(3)
             .max_memory_pairs(16)   # tiny budget: forces the spill path
             .build())
    glove.fit()
    weather = ["rain", "snow", "storm"]
    finance = ["bank", "money", "stock"]
    within = np.mean([glove.similarity(a, b)
                      for a in weather for b in weather if a != b])
    across = np.mean([glove.similarity(a, b)
                      for a in weather for b in finance])
    assert within > across + 0.1, f"within={within:.3f} across={across:.3f}"


def _cluster_purity(model, clusters, top_n=3):
    """wordsNearest quality: fraction of top-n neighbours that stay within
    the query word's topic cluster."""
    hits = total = 0
    for cluster in clusters:
        others = set(cluster)
        for w in cluster:
            for n in model.words_nearest([w], top_n=top_n):
                total += 1
                hits += n in others
    return hits / max(1, total)


def test_embedding_quality_metric(tmp_path):
    """The committed quality number: wordsNearest cluster purity for
    Word2Vec and GloVe on the hermetic two-topic corpus (text8-class
    protocol; the image has no network egress for the real text8)."""
    from deeplearning4j_tpu.nlp import Word2Vec

    corpus = synthetic_corpus(400)
    weather = ["rain", "snow", "storm", "cloud", "wind", "sun"]
    finance = ["bank", "money", "stock", "market", "trade", "price"]

    w2v = (Word2Vec.Builder().iterate(corpus).layer_size(24).window_size(4)
           .epochs(8).min_word_frequency(2).seed(5).build())
    w2v.fit()
    glove = (Glove.Builder().iterate(corpus).layer_size(24).window_size(4)
             .epochs(12).learning_rate(0.1).min_word_frequency(2).seed(3)
             .max_memory_pairs(64).build())
    glove.fit()

    report = {
        "protocol": "wordsNearest top-3 cluster purity, 2-topic corpus",
        "word2vec_purity": round(_cluster_purity(w2v, [weather, finance]), 3),
        "glove_purity": round(_cluster_purity(glove, [weather, finance]), 3),
    }
    (tmp_path / "quality.json").write_text(json.dumps(report))
    assert report["word2vec_purity"] > 0.8, report
    assert report["glove_purity"] > 0.8, report
