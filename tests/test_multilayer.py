"""MultiLayerNetwork facade tests (reference MultiLayerTest / conf serde suites)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration, NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    GravesLSTM,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import CollectScoresIterationListener


def simple_net(updater="sgd", lr=0.5, seed=42):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater, learning_rate=lr)
        .list()
        .layer(DenseLayer(n_in=2, n_out=8, activation="tanh", weight_init="xavier"))
        .layer(OutputLayer(n_in=8, n_out=2, loss="mcxent", activation="softmax"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def xor_data():
    x = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
    y = np.array([[1, 0], [0, 1], [0, 1], [1, 0]], np.float32)
    return x, y


def test_fit_learns_xor():
    net = simple_net(lr=1.0)
    x, y = xor_data()
    s0 = net.score(x, y)
    for _ in range(300):
        net.fit(x, y)
    assert net.score(x, y) < s0 * 0.2
    preds = np.asarray(net.output(x))
    assert (preds.argmax(-1) == y.argmax(-1)).all()


def test_listeners_receive_scores():
    net = simple_net()
    col = CollectScoresIterationListener()
    net.set_listeners(col)
    x, y = xor_data()
    for _ in range(5):
        net.fit(x, y)
    assert len(col.scores) == 5
    assert all(np.isfinite(s) for _, s in col.scores)


def test_config_json_roundtrip_full_network():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(7)
        .updater("adam", learning_rate=1e-3)
        .regularization(True)
        .l2(1e-4)
        .list()
        .layer(ConvolutionLayer(n_out=6, kernel_size=(5, 5), activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        .layer(BatchNormalization())
        .layer(DenseLayer(n_out=32, activation="relu"))
        .layer(OutputLayer(n_out=10, loss="mcxent", activation="softmax"))
        .set_input_type(InputType.convolutional(28, 28, 1))
        .build()
    )
    js = conf.to_json()
    restored = MultiLayerConfiguration.from_json(js)
    assert restored == conf
    # and it initializes identically
    n1 = MultiLayerNetwork(conf).init()
    n2 = MultiLayerNetwork(restored).init()
    for l1, l2 in zip(
        jax.tree_util.tree_leaves(n1.params), jax.tree_util.tree_leaves(n2.params)
    ):
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))


def test_input_type_inference_lenet_shapes():
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5), activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        .layer(DenseLayer(n_out=500, activation="relu"))
        .layer(OutputLayer(n_out=10, loss="mcxent", activation="softmax"))
        .set_input_type(InputType.convolutional_flat(28, 28, 1))
        .build()
    )
    # conv1 sees 1 channel; dense sees 4*4*50 = 800
    assert conf.layers[0].n_in == 1
    assert conf.layers[2].n_in == 20
    assert conf.layers[4].n_in == 4 * 4 * 50
    net = MultiLayerNetwork(conf).init()
    x = np.random.RandomState(0).rand(2, 784).astype(np.float32)
    out = np.asarray(net.output(x))
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)


def test_save_restore_roundtrip(tmp_path):
    net = simple_net(updater="adam", lr=0.01)
    x, y = xor_data()
    for _ in range(10):
        net.fit(x, y)
    path = tmp_path / "model.zip"
    net.save(path)
    restored = MultiLayerNetwork.load(path)
    np.testing.assert_allclose(
        np.asarray(net.output(x)), np.asarray(restored.output(x)), rtol=1e-6
    )
    assert restored.iteration == net.iteration
    # resume training continues identically (updater state restored)
    net.fit(x, y)
    restored.fit(x, y)
    np.testing.assert_allclose(
        net.params_to_vector(), restored.params_to_vector(), rtol=1e-5
    )


def test_params_vector_roundtrip():
    net = simple_net()
    vec = net.params_to_vector()
    assert vec.size == net.num_params()
    net2 = simple_net(seed=99)
    net2.set_params_vector(vec)
    np.testing.assert_array_equal(net2.params_to_vector(), vec)


def test_rnn_fit_and_time_step():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(1)
        .updater("adam", learning_rate=0.01)
        .list()
        .layer(GravesLSTM(n_in=4, n_out=8, activation="tanh"))
        .layer(RnnOutputLayer(n_in=8, n_out=4, loss="mcxent", activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(0)
    x = rs.rand(3, 6, 4).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, (3, 6))]
    s0 = net.score(x, y)
    for _ in range(30):
        net.fit(x, y)
    assert net.score(x, y) < s0
    # streaming: rnn_time_step over the sequence == full output
    full = np.asarray(net.output(x))
    net.rnn_clear_previous_state()
    outs = [np.asarray(net.rnn_time_step(x[:, t])) for t in range(6)]
    np.testing.assert_allclose(np.stack(outs, 1), full, rtol=2e-4, atol=1e-5)


def test_tbptt_training_runs():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(1)
        .updater("sgd", learning_rate=0.1)
        .list()
        .layer(GravesLSTM(n_in=3, n_out=6))
        .layer(RnnOutputLayer(n_in=6, n_out=3, loss="mcxent", activation="softmax"))
        .backprop_type("truncated_bptt", fwd_length=4, back_length=4)
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(0)
    x = rs.rand(2, 12, 3).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, (2, 12))]
    s0 = net.score(x, y)
    for _ in range(20):
        net.fit(x, y)
    assert np.isfinite(net.score_value)
    assert net.score(x, y) < s0
    # 12 timesteps / fwd 4 = 3 steps per fit call
    assert net.iteration == 20 * 3


def test_per_layer_lr_override():
    conf = (
        NeuralNetConfiguration.builder()
        .updater("sgd", learning_rate=0.0)  # global lr zero
        .list()
        .layer(DenseLayer(n_in=2, n_out=4, activation="tanh", learning_rate=0.5))
        .layer(OutputLayer(n_in=4, n_out=2, loss="mcxent", activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x, y = xor_data()
    w_out_before = np.asarray(net.params["layer_1"]["W"]).copy()
    w_hid_before = np.asarray(net.params["layer_0"]["W"]).copy()
    net.fit(x, y)
    # output layer frozen (lr 0), hidden layer moved (lr 0.5)
    np.testing.assert_array_equal(np.asarray(net.params["layer_1"]["W"]), w_out_before)
    assert not np.allclose(np.asarray(net.params["layer_0"]["W"]), w_hid_before)
