"""Elastic data parallelism: degraded-mode eviction/re-admission and the
renormalized average (docs/resilience.md "Elasticity").

Correctness oracles follow the repo's equivalence discipline
(TestCompareParameterAveragingSparkVsSingleMachine): a degraded collective
must equal the EXPLICIT math over the healthy set — manual replica
averaging for ParallelWrapper, single-device training on the healthy rows
for SyncTrainingMaster.  Every fault is driven deterministically by the
PR-5 FaultInjector (delay/hang/kill + until_step clearing)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.observability import (
    HealthEvaluator, HealthRule, get_flight_recorder, get_registry,
)
from deeplearning4j_tpu.observability.metrics import MetricsRegistry
from deeplearning4j_tpu.parallel import (
    DistributedNetwork, ElasticConfig, ElasticController,
    ParallelWrapper, ParameterAveragingTrainingMaster, SyncTrainingMaster,
)
from deeplearning4j_tpu.resilience import FaultInjector, inject_faults

pytestmark = pytest.mark.elastic


def make_net(seed=12345, updater="sgd", lr=0.1):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater, learning_rate=lr)
        .list()
        .layer(DenseLayer(n_in=6, n_out=10, activation="tanh"))
        .layer(OutputLayer(n_in=10, n_out=3, loss="mcxent",
                           activation="softmax"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def make_batches(n_batches, batch_size, seed=0):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n_batches):
        x = rs.randn(batch_size, 6).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, batch_size)]
        out.append(DataSet(x, y))
    return out


def counter_value(name, **labels):
    fam = get_registry().get(name)
    if fam is None:
        return 0.0
    total = 0.0
    for label_pairs, child in fam.samples():
        d = dict(label_pairs)
        if all(d.get(k) == v for k, v in labels.items()):
            total += child.value
    return total


def flight_events(kind, **attrs):
    out = []
    for ev in get_flight_recorder().events():
        if ev.kind != kind:
            continue
        if all(ev.attrs.get(k) == v for k, v in attrs.items()):
            out.append(ev)
    return out


# ------------------------------------------------------- injector chaos modes
@pytest.mark.faults
def test_fault_injector_worker_states():
    inj = FaultInjector(seed=0)
    inj.hang_worker("1", at_step=3, until_step=6)
    inj.kill_worker("2", at_step=5)
    assert inj.worker_state("1", 2) == "ok"
    assert inj.worker_state("1", 3) == "hung"
    assert inj.worker_state("1", 5) == "hung"
    assert inj.worker_state("1", 6) == "ok"       # until_step clears it
    assert inj.worker_state("2", 4) == "ok"
    assert inj.worker_state("2", 5) == "dead"
    assert inj.worker_state("2", 999) == "dead"   # no until: dead forever
    inj.clear_worker("2")
    assert inj.worker_state("2", 999) == "ok"
    # dead wins over hung when both are armed
    inj.hang_worker("3", at_step=0)
    inj.kill_worker("3", at_step=0)
    assert inj.worker_state("3", 1) == "dead"
    kinds = [e["kind"] for e in inj.injected]
    assert "worker_hung" in kinds and "worker_dead" in kinds
    inj.reset()
    assert inj.worker_state("1", 4) == "ok"


# -------------------------------------------------------- controller invariants
def test_controller_min_healthy_and_max_evicted():
    reg = MetricsRegistry()
    ctl = ElasticController(
        "t", ["0", "1", "2"],
        config=ElasticConfig(min_healthy=2), registry=reg)
    assert ctl.evict("1", "manual", step=0) is True
    assert ctl.active_workers == ["0", "2"]
    # a second eviction would drop below min_healthy=2: refused
    assert ctl.evict("2", "manual", step=1) is False
    assert ctl.active_workers == ["0", "2"]
    ctl.readmit("1", step=2)
    assert ctl.active_workers == ["0", "1", "2"]
    # max_evicted caps simultaneous evictions even when min_healthy allows
    ctl2 = ElasticController(
        "t2", ["0", "1", "2", "3"],
        config=ElasticConfig(min_healthy=1, max_evicted=1), registry=reg)
    assert ctl2.evict("0", "manual", step=0) is True
    assert ctl2.evict("1", "manual", step=0) is False


def test_health_rule_max_evicted_replicas():
    reg = MetricsRegistry()
    ctl = ElasticController("hr", ["0", "1", "2", "3"],
                            config=ElasticConfig(), registry=reg)
    rule = HealthRule("evicted_budget", "max_evicted_replicas", 1)
    ev = HealthEvaluator([rule], component="hr_test", registry=reg)
    assert ev.evaluate().healthy
    ctl.evict("1", "manual", step=0)
    assert ev.evaluate().healthy           # 1 evicted <= budget 1
    ctl.evict("2", "manual", step=1)
    verdict = ev.evaluate()
    assert not verdict.healthy
    assert verdict.failing[0]["observed"] == 2.0


# ------------------------------------------------------------ tail-window bias
def test_tail_window_padding_not_double_counted():
    """3 minibatches over K=2: the tail window pads replica 1 with a
    duplicate of b2.  The pad-filled replica must be weighted out, so the
    result equals the EXPLICIT math: average after (b0, b1), then train
    replica 0 alone on b2."""
    K = 2
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    batches = make_batches(3, 4, seed=3)

    net = make_net(updater="sgd", lr=0.2)
    pw = ParallelWrapper(net, workers=K, averaging_frequency=1, mesh=mesh)
    pw.fit(iter(batches))

    r0, r1 = make_net(updater="sgd", lr=0.2), make_net(updater="sgd", lr=0.2)
    r0.fit(batches[0].features, batches[0].labels)
    r1.fit(batches[1].features, batches[1].labels)
    avg = jax.tree_util.tree_map(lambda a, b: (a + b) / 2.0,
                                 r0.params, r1.params)
    ref = make_net(updater="sgd", lr=0.2)
    ref.params = avg
    ref.fit(batches[2].features, batches[2].labels)

    np.testing.assert_allclose(net.params_to_vector(),
                               ref.params_to_vector(), rtol=2e-5, atol=1e-6)


def test_tail_split_keeps_real_minibatches_with_avg_freq():
    """avg_freq=2, K=2, 7 batches: the tail (b4, b5, b6) must emit its
    full frame (b4, b5) as a real averaging window and only mask the
    padded slot of the final partial frame — weighting the whole tail
    per-replica would silently drop b5 (a REAL minibatch) from the
    average."""
    K = 2
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    batches = make_batches(7, 4, seed=31)
    net = make_net(updater="sgd", lr=0.2)
    ParallelWrapper(net, workers=K, averaging_frequency=2,
                    mesh=mesh).fit(iter(batches))

    def avg(trees):
        return jax.tree_util.tree_map(
            lambda *xs: sum(xs) / len(xs), *trees)

    # window 1 (full, F=2): r0 <- b0,b2; r1 <- b1,b3; average
    r0, r1 = make_net(updater="sgd", lr=0.2), make_net(updater="sgd", lr=0.2)
    for b in (batches[0], batches[2]):
        r0.fit(b.features, b.labels)
    for b in (batches[1], batches[3]):
        r1.fit(b.features, b.labels)
    avg1 = avg([r0.params, r1.params])
    # window 2 (tail full frame, F=1): r0 <- b4; r1 <- b5; average
    # (independent copies: the jitted facade step donates its buffers)
    copy = lambda t: jax.tree_util.tree_map(jnp.array, t)  # noqa: E731
    r0.params, r1.params = copy(avg1), copy(avg1)
    r0.fit(batches[4].features, batches[4].labels)
    r1.fit(batches[5].features, batches[5].labels)
    avg2 = avg([r0.params, r1.params])
    # window 3 (partial frame): r0 <- b6; r1 is pad-filled -> masked out
    ref = make_net(updater="sgd", lr=0.2)
    ref.params = avg2
    ref.fit(batches[6].features, batches[6].labels)

    np.testing.assert_allclose(net.params_to_vector(),
                               ref.params_to_vector(), rtol=2e-5, atol=1e-6)


def test_native_and_generic_tail_paths_agree():
    """The native C++ slab path and the generic window assembler must
    produce identical params on a ragged tail (7 batches over K=2, F=2) —
    zero-fill + mask + weight-out vs duplicate-fill + weight-out."""
    K = 2
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    batches = make_batches(7, 8, seed=37)
    merged = DataSet.merge(batches)

    generic = make_net(updater="adam", lr=0.05)
    ParallelWrapper(generic, workers=K, averaging_frequency=2,
                    mesh=mesh).fit(iter(batches))
    native = make_net(updater="adam", lr=0.05)
    ParallelWrapper(native, workers=K, averaging_frequency=2,
                    mesh=mesh).fit(ListDataSetIterator(merged, 8))

    assert native.iteration == generic.iteration
    np.testing.assert_allclose(native.params_to_vector(),
                               generic.params_to_vector(),
                               rtol=2e-5, atol=1e-6)


def test_all_ones_weights_reproduce_plain_mean():
    """With no faults and no padding, the weighted average must reproduce
    the legacy unweighted path (the healthy hot path is unchanged)."""
    K = 2
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    batches = make_batches(4, 4, seed=5)
    plain = make_net()
    ParallelWrapper(plain, workers=K, averaging_frequency=2,
                    mesh=mesh).fit(iter(batches))
    elastic = make_net()
    ParallelWrapper(elastic, workers=K, averaging_frequency=2, mesh=mesh,
                    elastic=ElasticConfig()).fit(iter(batches))
    np.testing.assert_allclose(plain.params_to_vector(),
                               elastic.params_to_vector(),
                               rtol=1e-6, atol=1e-7)


# ------------------------------------------------------------ manual eviction
def test_manual_eviction_renormalizes_average():
    """With replica 1 evicted for the whole run (K=2), every window's
    average is replica 0's params alone — the run must equal sequential
    training on replica 0's batch share (b0 then b2)."""
    K = 2
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    batches = make_batches(4, 4, seed=7)
    net = make_net(updater="sgd", lr=0.2)
    pw = ParallelWrapper(
        net, workers=K, averaging_frequency=1, mesh=mesh,
        elastic=ElasticConfig(readmit_after_windows=10 ** 9))
    pw.elastic.evict("1", "manual", step=0)
    pw.fit(iter(batches))

    ref = make_net(updater="sgd", lr=0.2)
    ref.fit(batches[0].features, batches[0].labels)
    ref.fit(batches[2].features, batches[2].labels)
    np.testing.assert_allclose(net.params_to_vector(),
                               ref.params_to_vector(), rtol=2e-5, atol=1e-6)


@pytest.mark.faults
def test_refused_eviction_of_dead_worker_is_visible():
    """When min_healthy blocks evicting a dead worker, the refusal must
    be loud — metric + flight event, once per episode — because the dead
    replica keeps weight 1 while the evicted-replicas gauge reads within
    budget."""
    reg = MetricsRegistry()
    ctl = ElasticController(
        "ref", ["0", "1", "2"],
        config=ElasticConfig(min_healthy=2), registry=reg)
    inj = FaultInjector(seed=0)
    inj.kill_worker("0", at_step=0)
    inj.kill_worker("1", at_step=0)
    with inject_faults(inj):
        for step in range(3):
            ctl.begin_window(step)
    # one eviction landed, the second was refused by min_healthy=2
    assert len(ctl.evicted_workers) == 1
    refused = [w for w in ("0", "1") if w not in ctl.evicted_workers]
    fam = reg.get("dl4j_elastic_eviction_refusals_total")
    counts = {dict(lp)["worker"]: c.value for lp, c in fam.samples()}
    assert counts == {refused[0]: 1.0}      # once per episode, not per window
    evs = flight_events("elastic_eviction_refused", component="ref")
    assert evs and evs[-1].attrs["worker"] == refused[0]
    assert evs[-1].attrs["reason"] == "dead"
    # fault clears -> refused worker is fine, episode re-arms; a new death
    # (now evictable: the other dead slot was readmitted) evicts cleanly
    inj.clear_worker(refused[0])
    ctl.begin_window(3)
    assert ctl._state[refused[0]]["refused"] is None


def test_manual_eviction_is_not_auto_readmitted():
    """Only straggler evictions take the readmit_after_windows probation
    path; a manual eviction stays in force until an explicit readmit()."""
    reg = MetricsRegistry()
    ctl = ElasticController(
        "man", ["0", "1"],
        config=ElasticConfig(readmit_after_windows=2), registry=reg)
    assert ctl.evict("1", "manual", step=0) is True
    for step in range(6):
        ctl.begin_window(step)
    assert ctl.evicted_workers == ["1"]
    ctl.readmit("1", step=6)
    assert ctl.evicted_workers == []


def test_lockstep_config_admits_no_evictions():
    """degraded_mode=False is the lockstep baseline arm: evict() is
    refused even when called manually, so nothing is ever weighted out
    of the average and the degraded-windows counter stays flat."""
    reg = MetricsRegistry()
    ctl = ElasticController(
        "lockstep", ["0", "1"],
        config=ElasticConfig(degraded_mode=False), registry=reg)
    assert ctl.evict("1", "manual", step=0) is False
    assert ctl.active_workers == ["0", "1"]
    assert (ctl.begin_window(0) == 1.0).all()


def test_param_averaging_master_elastic_state_survives_epochs():
    """ParameterAveragingTrainingMaster builds a fresh ParallelWrapper
    per epoch; its ElasticController must be persistent so an eviction
    in epoch 1 is still in force in epoch 2 and visible afterwards via
    master.elastic / training_stats()."""
    K = 2
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    master = ParameterAveragingTrainingMaster(
        workers=K, averaging_frequency=1, mesh=mesh,
        elastic=ElasticConfig(readmit_after_windows=10 ** 9))
    assert isinstance(master.elastic, ElasticController)
    master.elastic.evict("1", "manual", step=0)
    net = make_net(updater="sgd", lr=0.2)
    batches = make_batches(4, 4, seed=11)
    DistributedNetwork(net, master).fit(
        ListDataSetIterator(DataSet.merge(batches), 4), epochs=2)
    assert master.elastic.evicted_workers == ["1"]
    assert master.training_stats()["elastic"]["evicted"]["1"][
        "reason"] == "manual"
    # two epochs over replica 0's batch share: b0, b2, then b0, b2 again
    ref = make_net(updater="sgd", lr=0.2)
    for b in (batches[0], batches[2], batches[0], batches[2]):
        ref.fit(b.features, b.labels)
    np.testing.assert_allclose(net.params_to_vector(),
                               ref.params_to_vector(), rtol=2e-5, atol=1e-6)


# --------------------------------------------------- straggler-driven eviction
@pytest.mark.faults
def test_straggler_eviction_named_in_metrics_and_flight_events():
    K = 8
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    base_evictions = counter_value("dl4j_elastic_evictions_total",
                                   component="parallel_wrapper", worker="3")
    net = make_net()
    # straggler_window=8 ages the compile-inflated first windows out of
    # the rolling medians quickly; 16 windows leaves ample room for the
    # min_steps warm-up + 2 flags before the run ends
    pw = ParallelWrapper(
        net, workers=K, averaging_frequency=1, mesh=mesh,
        elastic=ElasticConfig(evict_after_flags=2, straggler_min_steps=2,
                              straggler_window=8,
                              readmit_after_windows=10 ** 9))
    inj = FaultInjector(seed=1).delay_worker("3", 0.1)
    with inject_faults(inj):
        pw.fit(iter(make_batches(K * 16, 4, seed=9)))
    assert "3" in pw.elastic.evicted_workers
    assert pw.elastic.summary()["evicted"]["3"]["reason"] == "straggler"
    assert counter_value("dl4j_elastic_evictions_total",
                         component="parallel_wrapper",
                         worker="3") > base_evictions
    evs = flight_events("elastic_eviction", component="parallel_wrapper",
                        worker="3")
    assert evs and evs[-1].attrs["reason"] == "straggler"
    # training continued on the healthy set
    assert np.isfinite(net.score_value)
    assert np.isfinite(net.params_to_vector()).all()


@pytest.mark.faults
def test_kill_worker_eviction_then_readmission_converges():
    """Worker 2 dies at step 2 and comes back at step 6: the run must
    evict it (reason dead), re-admit it when the fault clears, and land
    within tolerance of the uninterrupted elastic run (the degraded
    windows lose worker 2's minibatches from the average — DeepSpark
    relaxed synchrony, not bit-parity)."""
    K = 8
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    batches = make_batches(K * 12, 4, seed=11)

    ref = make_net(updater="sgd", lr=0.05)
    ParallelWrapper(ref, workers=K, averaging_frequency=1, mesh=mesh,
                    elastic=ElasticConfig()).fit(iter(batches))

    net = make_net(updater="sgd", lr=0.05)
    pw = ParallelWrapper(net, workers=K, averaging_frequency=1, mesh=mesh,
                         elastic=ElasticConfig(evict_after_flags=None))
    inj = FaultInjector(seed=2).kill_worker("2", at_step=2, until_step=6)
    with inject_faults(inj):
        pw.fit(iter(batches))

    assert pw.elastic.evicted_workers == []    # re-admitted
    evs = flight_events("elastic_eviction", component="parallel_wrapper",
                        worker="2")
    assert evs and evs[-1].attrs["reason"] == "dead"
    assert flight_events("elastic_readmission",
                         component="parallel_wrapper", worker="2")
    assert inj.injected and inj.injected[0]["kind"] == "worker_dead"
    np.testing.assert_allclose(net.params_to_vector(),
                               ref.params_to_vector(), atol=0.05)
    assert abs(float(net.score_value) - float(ref.score_value)) < 0.05


@pytest.mark.faults
def test_hang_worker_evicts_and_clear_readmits():
    K = 4
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    net = make_net()
    pw = ParallelWrapper(net, workers=K, averaging_frequency=1, mesh=mesh,
                         elastic=ElasticConfig(evict_after_flags=None,
                                               hang_stall_s=0.0))
    inj = FaultInjector(seed=3).hang_worker("1", at_step=1, until_step=4)
    with inject_faults(inj):
        pw.fit(iter(make_batches(K * 8, 4, seed=13)))
    evs = flight_events("elastic_eviction", component="parallel_wrapper",
                        worker="1")
    assert evs and evs[-1].attrs["reason"] == "hang"
    assert "1" in pw.elastic.active_workers    # hang cleared -> re-admitted


# ------------------------------------------------------------- sync master
def test_sync_master_eviction_equals_healthy_rows_math():
    """Sync DP with a dead data slot == single-device training on the
    batch WITHOUT that slot's rows: the masked loss renormalizes the
    gradient mean over the healthy rows (exact, not approximate)."""
    K = 4
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    rs = np.random.RandomState(17)
    x = rs.randn(32, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 32)]

    net = make_net()
    master = SyncTrainingMaster(mesh=mesh, elastic=ElasticConfig())
    victim = master.elastic.workers[2]         # data slot 2, rows 4:6 of 8
    inj = FaultInjector(seed=4).kill_worker(victim, at_step=0)
    with inject_faults(inj):
        DistributedNetwork(net, master).fit(
            ListDataSetIterator(DataSet(x, y), 8))
    assert master.elastic.summary()["evicted"][victim]["reason"] == "dead"
    assert master.training_stats()["elastic"]["active"] == K - 1

    ref = make_net()
    keep = np.r_[0:4, 6:8]
    for i in range(4):
        bx = x[i * 8:(i + 1) * 8][keep]
        by = y[i * 8:(i + 1) * 8][keep]
        ref.fit(bx, by)
    np.testing.assert_allclose(net.params_to_vector(),
                               ref.params_to_vector(), rtol=2e-5, atol=1e-6)


def test_sync_master_readmission_after_fault_clears():
    K = 4
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    rs = np.random.RandomState(19)
    x = rs.randn(64, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 64)]
    net = make_net()
    master = SyncTrainingMaster(mesh=mesh, elastic=ElasticConfig())
    victim = master.elastic.workers[1]
    inj = FaultInjector(seed=5).kill_worker(victim, at_step=1, until_step=4)
    recompiles0 = counter_value("dl4j_recompiles_total")
    with inject_faults(inj):
        DistributedNetwork(net, master).fit(
            ListDataSetIterator(DataSet(x, y), 8))
    assert master.elastic.evicted_workers == []
    assert flight_events("elastic_readmission", component="sync_master",
                         worker=victim)
    assert np.isfinite(net.score_value)
    # eviction/re-admission flip mask VALUES, not the pytree: the elastic
    # sync master always feeds a labels mask, so degrading the mesh never
    # triggers an XLA recompile
    assert counter_value("dl4j_recompiles_total") == recompiles0


# ----------------------------------------------------------- barrier semantics
@pytest.mark.faults
def test_degraded_mode_stops_paying_the_straggler_stall():
    """The synchrony-barrier simulation: lockstep (degraded off) pays the
    slow worker's injected delay every window; degraded mode stops paying
    the moment the worker is evicted.  Eviction is driven by a
    deterministic kill at step 2 (not detector timing), so the two arms
    differ by exactly (n_win - 2) barrier stalls.  This is the
    bench_elastic claim in miniature."""
    import time as _time

    K = 4
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    delay = 0.1
    n_win = 8

    def run(cfg):
        net = make_net()
        pw = ParallelWrapper(net, workers=K, averaging_frequency=1,
                             mesh=mesh, elastic=cfg)
        inj = (FaultInjector(seed=6).delay_worker("1", delay)
               .kill_worker("1", at_step=2))
        t0 = _time.perf_counter()
        with inject_faults(inj):
            pw.fit(iter(make_batches(K * n_win, 4, seed=23)))
        return _time.perf_counter() - t0

    lock_s = run(ElasticConfig(degraded_mode=False, hang_stall_s=0.0))
    deg_s = run(ElasticConfig(evict_after_flags=None, hang_stall_s=0.0))
    # lockstep pays ~n_win * delay; degraded pays only the 2 pre-kill
    # windows — assert a wide margin so compile jitter can't flip it
    assert lock_s >= n_win * delay
    assert deg_s < lock_s - 3 * delay


def test_degraded_windows_counter_increments():
    reg_before = counter_value("dl4j_elastic_degraded_windows_total",
                               component="parallel_wrapper")
    K = 2
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    net = make_net()
    pw = ParallelWrapper(net, workers=K, averaging_frequency=1, mesh=mesh,
                         elastic=ElasticConfig(readmit_after_windows=10 ** 9))
    pw.elastic.evict("1", "manual", step=0)
    pw.fit(iter(make_batches(4, 4, seed=29)))
    assert counter_value("dl4j_elastic_degraded_windows_total",
                         component="parallel_wrapper") >= reg_before + 2
