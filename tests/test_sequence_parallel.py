"""Sequence/context parallelism tests.

Correctness contract (reference test pattern, SURVEY.md §4): distributed
attention == exact local attention, and sequence-parallel TRAINING ==
single-device training, on the 8-virtual-device CPU mesh (conftest.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.nn.layers.attention import dot_product_attention
from deeplearning4j_tpu.parallel import (
    SequenceParallelTrainingMaster,
    ring_self_attention,
)


def _qkv(rng, b=2, t=32, h=4, d=8):
    q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    return q, k, v


def _seq_mesh(n_seq=4):
    devs = np.array(jax.devices()[:n_seq]).reshape(1, 1, n_seq)
    return Mesh(devs, (backend.AXIS_DATA, backend.AXIS_MODEL, backend.AXIS_SEQ))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_distributed_attention_matches_exact(causal, impl):
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng)
    mesh = _seq_mesh(4)
    expected = dot_product_attention(q, k, v, causal=causal)
    got = ring_self_attention(q, k, v, mesh, causal=causal, impl=impl)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_declines_flash_off_tpu(causal):
    """Ulysses routes its local attention through the flash helper seam on
    compiled TPU backends only — on CPU, even an interpret-permissive
    helper must be bypassed (the Pallas HLO interpreter cannot run under
    shard_map's varying-axes checks), and the exact path must still hold."""
    from deeplearning4j_tpu import helpers
    from deeplearning4j_tpu.helpers.flash_attention import FlashAttentionHelper

    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng, b=1, t=512, h=4, d=8)
    mesh = _seq_mesh(4)
    helpers.register_helper("attention", FlashAttentionHelper(
        allow_interpret=True))
    try:
        got = ring_self_attention(q, k, v, mesh, causal=causal, impl="ulysses")
    finally:
        helpers._registry.pop("attention", None)
    expected = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_gqa_bypasses_flash_helper():
    """GQA k/v (H_kv < H) must never reach the flash helper from the
    Ulysses path — the helper's to_bh reshape assumes k/v share q's head
    count, so on TPU an eligible-looking GQA call would crash instead of
    falling back to the grouped einsum (advisor finding, round 3)."""
    from deeplearning4j_tpu import helpers

    class EagerSpyHelper:
        """Claims support unconditionally (as the real helper does compiled
        on TPU) and records whether it was consulted with GQA shapes."""

        def __init__(self):
            self.attend_heads = []

        def supports(self, t, d, *, under_shard_map=False):
            return True

        def attend(self, q, k, v, *, causal=False, window=None):
            self.attend_heads.append((q.shape[2], k.shape[2]))
            return dot_product_attention(q, k, v, causal=causal,
                                         window=window)

    rng = np.random.default_rng(3)
    # 2 shards: Ulysses all_to_all needs H_kv % n_shards == 0
    b, t, h, hkv, d = 1, 64, 4, 2, 8
    q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, t, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, t, hkv, d)), jnp.float32)
    mesh = _seq_mesh(2)
    spy = EagerSpyHelper()
    helpers.register_helper("attention", spy)
    try:
        got = ring_self_attention(q, k, v, mesh, causal=True, impl="ulysses")
        # MHA control: same helper IS consulted when head counts agree
        qm = jnp.asarray(rng.standard_normal((b, t, hkv, d)), jnp.float32)
        ring_self_attention(qm, k, v, mesh, causal=True, impl="ulysses")
    finally:
        helpers._registry.pop("attention", None)
    assert all(hq == hk for hq, hk in spy.attend_heads), (
        f"flash helper consulted with GQA head mismatch: {spy.attend_heads}")
    assert spy.attend_heads, "MHA control never reached the helper"
    expected = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_gradients_match_exact():
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, b=1, t=16, h=2, d=4)
    mesh = _seq_mesh(4)

    def loss_exact(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_self_attention(q, k, v, mesh, causal=True) ** 2)

    ge = jax.grad(loss_exact, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(ge, gr):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=5e-5)


def test_attention_layer_gradcheck():
    """Numerical gradient check of the local attention layer — the
    reference's central-difference oracle (GradientCheckUtil pattern)."""
    from deeplearning4j_tpu.gradientcheck import check_gradients
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (
        LayerNorm, RnnOutputLayer, SelfAttentionLayer,
    )

    conf = (
        NeuralNetConfiguration.builder()
        .seed(42)
        .updater("sgd", learning_rate=0.1)
        .list()
        .layer(SelfAttentionLayer(n_in=6, n_out=6, n_heads=2, causal=True))
        .layer(LayerNorm(n_in=6))
        .layer(RnnOutputLayer(n_in=6, n_out=3, loss="mcxent", activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init(dtype=jnp.float64)
    rng = np.random.default_rng(3)
    x = rng.standard_normal((2, 5, 6))
    y = np.eye(3)[rng.integers(0, 3, (2, 5))]
    assert check_gradients(net, x, y, epsilon=1e-6, max_rel_error=1e-3)


def _char_batches(vocab, b, t, n, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.integers(0, vocab, (b, t)).astype(np.float32)
        y = np.eye(vocab, dtype=np.float32)[rng.integers(0, vocab, (b, t))]
        out.append(DataSet(x, y))
    return out


def test_remat_block_equivalence():
    """jax.checkpoint'd transformer blocks train identically to stored
    activations (the long-context memory trade changes nothing numerically)."""
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    rs = np.random.RandomState(0)
    ids = rs.randint(0, 11, (4, 8))
    x = ids.astype(np.float32)
    y = np.eye(11, dtype=np.float32)[np.roll(ids, -1, 1)]
    a = transformer_char_lm(vocab_size=11, d_model=16, n_heads=2, layers=1,
                            seed=7, remat=False)
    b = transformer_char_lm(vocab_size=11, d_model=16, n_heads=2, layers=1,
                            seed=7, remat=True)
    a.fit(x, y)
    b.fit(x, y)
    assert abs(a.score_value - b.score_value) < 1e-6
    assert np.allclose(a.params_to_vector(), b.params_to_vector(), atol=1e-6)
    # remat flag round-trips through config JSON
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

    back = MultiLayerConfiguration.from_json(b.conf.to_json())
    assert back.layers[1].remat is True


def test_sequence_parallel_training_matches_single_device():
    """Transformer LM trained with (data=2, seq=4) sharding == the same
    model trained on one device — the TestCompareParameterAveraging...
    equivalence, extended to SP."""
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    vocab, b, t = 11, 4, 16
    batches = _char_batches(vocab, b, t, n=3)

    # single-device reference
    # plain SGD: linear in the gradient, so fp-reordering noise stays tiny
    # (adam's 1/sqrt(v) amplifies near-zero-grad sign flips; the reference
    # equivalence tests also compare under plain SGD)
    ref = transformer_char_lm(vocab_size=vocab, d_model=16, n_heads=2,
                              layers=1, seed=7, updater="sgd", lr=0.1)
    for ds in batches:
        ref.fit(ds.features, ds.labels)

    # sequence-parallel: same seed -> identical init
    devs = np.array(jax.devices()[:8]).reshape(2, 1, 4)
    mesh = Mesh(devs, (backend.AXIS_DATA, backend.AXIS_MODEL, backend.AXIS_SEQ))
    sp_net = transformer_char_lm(vocab_size=vocab, d_model=16, n_heads=2,
                                 layers=1, seed=7, updater="sgd", lr=0.1,
                                 seq_axis=backend.AXIS_SEQ)
    master = SequenceParallelTrainingMaster(mesh=mesh)
    master.execute_training(sp_net, batches)

    ref_vec = ref.params_to_vector()
    sp_vec = sp_net.params_to_vector()
    np.testing.assert_allclose(sp_vec, ref_vec, rtol=1e-4, atol=1e-5)
    assert abs(sp_net.score_value - ref.score_value) < 1e-4


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_windowed_distributed_attention_matches_exact(impl):
    """Both SP implementations accept window and match exact banded
    attention (global positions line up across shards / reshards)."""
    rng = np.random.default_rng(5)
    q, k, v = _qkv(rng, b=1, t=16, h=4, d=4)
    mesh = _seq_mesh(4)
    got = ring_self_attention(q, k, v, mesh, causal=True, window=6, impl=impl)
    want = dot_product_attention(q, k, v, causal=True, window=6)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
