"""Serving-fleet control plane: placement, membership, failover, rollout.

Covers the PR-20 contracts end to end:
- placement policy (pure simulation: CI gate 6's selftest + seeded-tie
  determinism),
- in-process fleet: prefix-affinity stickiness, session pin + re-pin on
  a survivor, queued-request failover with zero client-visible errors,
  fleet_route spans, router metrics,
- fleet-wide rollout: canary → wave → commit, and forced watch
  regression → every replica rolled back,
- subprocess fleet (supervisor-spawned replicas): SIGKILL mid-flight →
  queued requests retried on survivors, in-stream kill → clean terminal
  SSE error event at the frontend, crash → restart → rejoin with a
  fresh publisher epoch,
- TelemetryPublisher publish-loop retry hygiene (PR-5 RetryPolicy).
"""

import json
import logging
import signal
import threading
import time
import urllib.request

import pytest

from deeplearning4j_tpu.fleet.placement import (
    AFFINITY, CANARY, LEAST_LOADED, PINNED, ReplicaView, ShadowIndex,
    choose, placement_selftest)
from deeplearning4j_tpu.generation.engine import GenerationEngine
from deeplearning4j_tpu.models.zoo import transformer_char_lm
from deeplearning4j_tpu.observability.metrics import MetricsRegistry
from deeplearning4j_tpu.observability.tracing import get_tracer

pytestmark = pytest.mark.fleet_router

VOCAB = 40
PROMPT = list(range(8))


def small_lm(seed=12345):
    return transformer_char_lm(vocab_size=VOCAB, d_model=32, n_heads=2,
                               layers=1, max_cache=32, seed=seed)


def make_engine(seed=12345, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_context", 32)
    kw.setdefault("max_queue", 16)
    kw.setdefault("prefill_buckets", (8,))
    kw.setdefault("prefix_cache", True)
    return GenerationEngine(small_lm(seed), **kw).start()


def make_router(**kw):
    from deeplearning4j_tpu.fleet import FleetRouter

    kw.setdefault("page_size", 4)
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("refresh_interval_s", 0.0)
    return FleetRouter(**kw)


# ---------------------------------------------------------------- placement
def test_placement_selftest_passes():
    # the same simulation CI gate 6 runs (determinism, affinity vs
    # random, version-tag invalidation, drain, canary split, pins)
    assert placement_selftest() == 0


def test_placement_deterministic_under_seeded_ties():
    def fresh_views():
        out = []
        for i in range(4):
            v = ReplicaView(f"r{i}", page_size=4, slots=4)
            v.healthy, v.free_pages = True, 64
            out.append(v)
        return out

    seq_a = [choose(fresh_views(), PROMPT, seed=11, n=n)[0]
             for n in range(32)]
    seq_b = [choose(fresh_views(), PROMPT, seed=11, n=n)[0]
             for n in range(32)]
    assert seq_a == seq_b           # same seed → identical tie-breaks
    seq_c = [choose(fresh_views(), PROMPT, seed=12, n=n)[0]
             for n in range(32)]
    assert seq_a != seq_c           # the seed is load-bearing


def test_shadow_index_pricing_matches_admission():
    # matched pages = whole page_size-token chunks, the PR-17 pricing
    sh = ShadowIndex(page_size=4)
    sh.insert(list(range(10)))      # 2 whole pages recorded (10 // 4)
    assert sh.matched_pages(list(range(10))) == 2
    assert sh.matched_pages(list(range(4))) == 1
    assert sh.matched_pages([9, 9, 9, 9]) == 0
    assert sh.observe_version("v2") is True     # version move resets
    assert sh.matched_pages(list(range(8))) == 0


# ----------------------------------------------------------- in-process fleet
@pytest.fixture(scope="module")
def duo():
    """Two live in-process replicas behind one router."""
    from deeplearning4j_tpu.fleet import FleetRouter, InProcessReplica

    e0, e1 = make_engine(), make_engine()
    router = make_router(seed=3)
    router.attach(InProcessReplica("r0", e0))
    router.attach(InProcessReplica("r1", e1))
    yield router, {"r0": e0, "r1": e1}
    for e in (e0, e1):
        e.stop(drain=False)


def test_affinity_keeps_session_on_one_replica(duo):
    router, _engines = duo
    prompt = [3] * 8
    first = router.submit(prompt, 3)
    first.result(timeout=30)
    assert first.finish_reason in ("length", "stop")
    again = router.submit(prompt, 3)
    again.result(timeout=30)
    assert again.replica_id == first.replica_id
    assert again.placements[0].reason == AFFINITY


def test_fleet_route_span_records_placement(duo):
    router, _engines = duo
    req = router.submit([5] * 8, 2)
    req.result(timeout=30)
    spans = [s for s in get_tracer().spans_for_trace(req.trace_id)
             if s.name == "fleet_route"]
    assert spans, "placement must record a fleet_route span"
    attrs = spans[-1].attrs
    assert attrs["replica"] == req.replica_id
    assert attrs["reason"] in (AFFINITY, LEAST_LOADED, PINNED, CANARY,
                               "repin", "random")
    assert set(attrs["candidates"]) == {"r0", "r1"}
    for s in attrs["candidates"].values():
        assert {"affinity_pages", "load", "free_pages"} <= set(s)


def test_router_metrics_and_replica_table(duo):
    router, _engines = duo
    router.submit([7] * 8, 2).result(timeout=30)
    rows = {r["replica"]: r for r in router.replicas()}
    assert set(rows) == {"r0", "r1"}
    assert all(r["live"] for r in rows.values())
    placed = sum(c.value for _l, c in router._m_requests.samples())
    assert placed >= 1


def test_admin_drain_excludes_replica(duo):
    router, _engines = duo
    router.drain("r0")
    try:
        for _ in range(4):
            req = router.submit([11] * 8, 2)
            req.result(timeout=30)
            assert req.replica_id == "r1"
    finally:
        router.drain("r0", False)


def test_queued_failover_zero_errors_and_session_repin():
    # a dead replica's queued (not-yet-streamed) requests land on the
    # survivor with no client-visible error, and the pinned session
    # re-pins there — the in-process version of the SIGKILL drill
    from deeplearning4j_tpu.fleet import FleetRouter, InProcessReplica

    e0, e1 = make_engine(), make_engine()
    # long refresh interval: the router must still BELIEVE the victim is
    # live when it submits, so the failure happens at the replica and
    # the failover path (not just placement avoidance) is exercised
    router = make_router(seed=5, refresh_interval_s=30.0)
    router.attach(InProcessReplica("a", e0))
    router.attach(InProcessReplica("b", e1))
    try:
        prompt = [2] * 8
        pinned_on = router.pin_session("conv", prompt)
        victim = {"a": e0, "b": e1}[pinned_on]
        survivor_id = "b" if pinned_on == "a" else "a"
        victim.stop(drain=False)    # in-queue requests die ShuttingDown

        req = router.submit(prompt, 3, session_id="conv")
        toks = req.result(timeout=30)       # zero client-visible errors
        assert len(toks) == 3
        assert req.replica_id == survivor_id
        assert req.failovers >= 1
        assert router.session_replica("conv") == survivor_id
        fo = sum(c.value for _l, c in router._m_failovers.samples())
        assert fo >= 1
        # dead replica is drained from subsequent placements entirely
        again = router.submit(prompt, 2, session_id="conv")
        again.result(timeout=30)
        assert again.replica_id == survivor_id and again.failovers == 0
    finally:
        e0.stop(drain=False) if e1 is victim else e1.stop(drain=False)


def test_no_live_replica_is_terminal():
    from deeplearning4j_tpu.fleet import (
        FleetRouter, InProcessReplica, NoLiveReplicaError)

    e = make_engine()
    router = make_router()
    router.attach(InProcessReplica("only", e))
    e.stop(drain=False)
    with pytest.raises(NoLiveReplicaError):
        router.submit(PROMPT, 2)


# ------------------------------------------------------------- fleet rollout
def test_fleet_rollout_promotes_and_forced_regression_rolls_back_all():
    from deeplearning4j_tpu.fleet import (
        FleetRollout, FleetRouter, InProcessReplica)

    engines = {f"r{i}": make_engine() for i in range(3)}
    router = make_router(seed=9)
    handles = {rid: InProcessReplica(rid, e) for rid, e in engines.items()}
    for h in handles.values():
        router.attach(h)
    stop_load = threading.Event()

    def load():
        while not stop_load.is_set():
            try:
                router.submit([1] * 8, 2).result(timeout=30)
            except Exception:
                time.sleep(0.05)

    t = threading.Thread(target=load, daemon=True)
    t.start()
    try:
        before = {rid: e.models.active("default").version
                  for rid, e in engines.items()}
        good = transformer_char_lm(vocab_size=VOCAB, d_model=32,
                                   n_heads=2, layers=1, max_cache=32,
                                   seed=777)
        ro = FleetRollout(router, handles, canary_fraction=0.5,
                          canary_min_requests=2, canary_timeout_s=60,
                          watch_window_s=0.3, watch_poll_s=0.05,
                          registry=router.registry)
        res = ro.consider(good, "good")
        assert res.outcome == "promoted"
        assert sorted(res.committed) == sorted(engines)
        after = {rid: e.models.active("default").version
                 for rid, e in engines.items()}
        assert all(after[r] > before[r] for r in engines)

        # forced regression mid-wave: EVERY deployed replica (canary
        # included) must return to the promoted version
        bad = transformer_char_lm(vocab_size=VOCAB, d_model=32,
                                  n_heads=2, layers=1, max_cache=32,
                                  seed=778)
        ro2 = FleetRollout(router, handles, canary_fraction=0.5,
                           canary_min_requests=2, canary_timeout_s=60,
                           watch_window_s=0.3, watch_poll_s=0.05,
                           registry=router.registry,
                           watch_extra_fn=lambda rid: {
                               "probe_ok": False,
                               "probe_detail": "forced regression"})
        res2 = ro2.consider(bad, "bad")
        assert res2.outcome == "rolled_back"
        restored = {rid: e.models.active("default").version
                    for rid, e in engines.items()}
        assert restored == after
        outcomes = {l[0][1]: c.value
                    for l, c in ro2._m_outcomes.samples()}
        assert outcomes.get("rolled_back", 0) >= 1
    finally:
        stop_load.set()
        t.join(timeout=5)
        for e in engines.values():
            e.stop(drain=False)


def test_fleet_rollout_rejects_http_replicas():
    from deeplearning4j_tpu.fleet import FleetRollout, HTTPReplica

    with pytest.raises(ValueError):
        FleetRollout(object(), {"w": HTTPReplica("w", "http://x")})


# ------------------------------------------------------- publisher retry loop
class _FlakyBroker:
    def __init__(self, fail_times, exc=ConnectionError("broker down")):
        self.fail_times = fail_times
        self.exc = exc
        self.calls = 0
        self.delivered = []

    def publish(self, topic, payload):
        self.calls += 1
        if self.calls <= self.fail_times:
            raise self.exc
        self.delivered.append(topic)
        return 1


def test_publisher_transient_outage_backs_off_and_resumes():
    from deeplearning4j_tpu.observability.fleet import TelemetryPublisher

    broker = _FlakyBroker(fail_times=2)
    pub = TelemetryPublisher("w", broker=broker, interval_s=0.05,
                             registry=MetricsRegistry())
    pub.retry_policy.base_delay_s = 0.01
    pub.start()
    try:
        deadline = time.monotonic() + 10
        while not broker.delivered and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        pub.stop()
    assert broker.delivered, "publish must resume after transient outage"
    assert broker.calls >= 3                      # 2 failures + success
    assert pub.retry_policy.retries >= 2          # rode the RetryPolicy


def test_publisher_fatal_error_surfaces(caplog):
    from deeplearning4j_tpu.observability.fleet import TelemetryPublisher

    broker = _FlakyBroker(fail_times=10**9, exc=ValueError("bad payload"))
    pub = TelemetryPublisher("w", broker=broker, interval_s=0.05,
                             registry=MetricsRegistry())
    with caplog.at_level(logging.WARNING,
                         logger="deeplearning4j_tpu.observability"):
        pub.start()
        deadline = time.monotonic() + 10
        while not any("telemetry publish failed after retries" in r.message
                      for r in caplog.records) \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        pub.stop()
    assert any("telemetry publish failed after retries" in r.message
               for r in caplog.records)
    assert broker.calls >= 1
    assert pub.retry_policy.retries == 0          # fatal: no backoff loop


def test_publisher_publish_once_keeps_swallow_semantics():
    from deeplearning4j_tpu.observability.fleet import TelemetryPublisher

    broker = _FlakyBroker(fail_times=10**9)
    pub = TelemetryPublisher("w", broker=broker,
                             registry=MetricsRegistry())
    assert pub.publish_once() == -1               # no raise, old contract


# ----------------------------------------------------------- subprocess fleet
@pytest.fixture(scope="module")
def subprocess_fleet():
    """Two supervisor-spawned replicas + broker + aggregator + router.

    Spawn cost ~10s for the module; every test leaves BOTH replicas
    serving (the SIGKILL drill restores the fleet via supervisor
    restart before yielding back).
    """
    from deeplearning4j_tpu.fleet import FleetRouter, ReplicaSupervisor
    from deeplearning4j_tpu.observability.fleet import FleetAggregator
    from deeplearning4j_tpu.streaming.pubsub import MessageBroker

    broker = MessageBroker()
    burl = f"http://127.0.0.1:{broker.serve(port=0)}"
    agg = FleetAggregator(url=burl, expire_after_s=3.0,
                          registry=MetricsRegistry()).start()
    sup = ReplicaSupervisor(
        broker_url=burl, warmup_timeout_s=180,
        registry=MetricsRegistry(),
        replica_args={"slots": 4, "page_size": 4, "max_context": 32,
                      "prefill_buckets": "8", "d_model": 32,
                      "n_heads": 2, "layers": 1, "vocab": VOCAB,
                      "interval_s": 0.25,
                      # paced decode: wide enough per-token window for
                      # the mid-stream kill drill to land mid-stream
                      "step_floor_ms": 25}).start()
    sup.start_replica("w0")
    sup.start_replica("w1")
    router = make_router(aggregator=agg, seed=7, refresh_interval_s=0.1)
    for h in sup.handles(timeout=60).values():
        router.attach(h)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if sum(r["live"] for r in router.replicas()) == 2:
            break
        time.sleep(0.1)
    assert sum(r["live"] for r in router.replicas()) == 2
    yield router, sup, agg
    sup.stop_all()
    agg.stop()
    broker.stop()


def _wait_live(router, wid, timeout=90):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        rows = {r["replica"]: r for r in router.replicas()}
        if rows.get(wid, {}).get("live"):
            return True
        time.sleep(0.2)
    return False


def test_http_replica_envelope_echoes_replica_id(subprocess_fleet):
    router, sup, _agg = subprocess_fleet
    rp = sup.processes()["w0"]
    body = json.dumps({"prompt": PROMPT, "max_tokens": 2}).encode()
    req = urllib.request.Request(
        f"{rp.url}/generate", data=body,
        headers={"Content-Type": "application/json",
                 "X-Request-Id": "cafe0123deadbeef"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        env = json.loads(resp.read().decode())
    assert env["replica"] == "w0"
    assert env["trace_id"] == "cafe0123deadbeef"   # propagated, not minted


def test_sigkill_failover_and_restart_rejoin(subprocess_fleet):
    """The headline drill: SIGKILL one replica mid-flight → queued
    requests retried on the survivor with zero client-visible errors,
    the pinned session re-pins there, and the supervisor's restart
    rejoins the routing table under a fresh publisher epoch."""
    router, sup, _agg = subprocess_fleet
    prompt = [9] * 8
    pinned_on = router.pin_session("talk", prompt)
    survivor = "w1" if pinned_on == "w0" else "w0"

    sup.kill(pinned_on, sig=signal.SIGKILL, restart=True)
    ok, errors = 0, []
    for _ in range(6):
        try:
            r = router.submit(prompt, 2, session_id="talk")
            r.result(timeout=60)
            ok += 1
        except Exception as e:      # noqa: BLE001 - recording, not hiding
            errors.append(e)
    assert not errors, f"queued requests must not error: {errors!r}"
    assert ok == 6
    assert router.session_replica("talk") == survivor
    fo = sum(c.value for _l, c in router._m_failovers.samples())
    assert fo >= 1

    # crash → restart → rejoin: fresh epoch clears the death mark
    assert _wait_live(router, pinned_on), "restarted replica must rejoin"
    assert sup.processes()[pinned_on].restarts >= 1
    restarts = sum(c.value for _l, c in sup._m_restarts.samples())
    assert restarts >= 1


def test_mid_stream_kill_clean_terminal_sse_event(subprocess_fleet):
    """A replica killed MID-STREAM cannot be failed over (tokens were
    already delivered): the frontend must end the stream with a clean
    terminal SSE error event, never a silent EOF."""
    from deeplearning4j_tpu.fleet import FleetFrontend

    router, sup, _agg = subprocess_fleet
    front = FleetFrontend(router, access_log=True)
    fport = front.start()
    try:
        # 20 paced tokens (25 ms step floor) = a ~500 ms stream: plenty
        # of window to kill after the first event
        body = json.dumps({"prompt": [4] * 8, "max_tokens": 20,
                           "stream": True}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{fport}/generate", data=body,
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=60)
        events, killed = [], None
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            ev = json.loads(line[len(b"data: "):].decode())
            events.append(ev)
            if killed is None and "token" in ev:
                # first token seen: find the serving replica (the
                # router-local inflight count is current, unlike the
                # snapshot-lagged active/queued) and kill it
                killed = next(
                    r["replica"] for r in router.replicas()
                    if r["inflight"] > 0)
                sup.kill(killed, sig=signal.SIGKILL, restart=True)
            if ev.get("done"):
                break
        assert killed is not None
        terminal = events[-1]
        assert terminal.get("done") is True
        assert "error" in terminal, f"want terminal error event: {terminal}"
        assert any("token" in e for e in events)   # stream really started
        assert _wait_live(router, killed)          # fleet heals for peers
    finally:
        front.stop()


def test_frontend_mints_and_propagates_request_id(subprocess_fleet):
    from deeplearning4j_tpu.fleet import FleetFrontend

    router, _sup, _agg = subprocess_fleet
    front = FleetFrontend(router)
    fport = front.start()
    try:
        body = json.dumps({"prompt": [6] * 8, "max_tokens": 2}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{fport}/generate", data=body,
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "feedface00000001"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            env = json.loads(resp.read().decode())
        assert env["trace_id"] == "feedface00000001"
        assert env["replica"] in ("w0", "w1")
        assert env["placement_reason"] in (AFFINITY, LEAST_LOADED,
                                           PINNED, "repin")
        # the SAME id names the router's placement span
        spans = [s for s in get_tracer().spans_for_trace(
            "feedface00000001") if s.name == "fleet_route"]
        assert spans and spans[-1].attrs["replica"] == env["replica"]
        # minted when absent
        req2 = urllib.request.Request(
            f"http://127.0.0.1:{fport}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req2, timeout=60) as resp:
            env2 = json.loads(resp.read().decode())
        assert len(env2["trace_id"]) == 16
    finally:
        front.stop()
