"""Production serving subsystem: shape-bucketed dynamic batching, AOT
warmup (zero steady-state recompiles), versioned hot-swap with zero
dropped requests, and admission control (shed -> 429, deadline -> 504,
drain on shutdown).  See docs/serving.md."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.observability import MetricsRegistry, set_registry
from deeplearning4j_tpu.serving import (
    BucketPolicy, DeadlineExceededError, ModelNotFoundError, QueueFullError,
    ServingEngine, ShuttingDownError,
)
from deeplearning4j_tpu.streaming import (
    InferenceServer, MessageBroker, ServingPipeline, base64_to_array,
)


@pytest.fixture(autouse=True)
def fresh_registry():
    from deeplearning4j_tpu.observability import get_registry

    old = get_registry()
    reg = set_registry(MetricsRegistry())
    yield reg
    set_registry(old)


def small_net(n_in=4, n_out=3, seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater("sgd", learning_rate=0.5).list()
            .layer(DenseLayer(n_in=n_in, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=n_out, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


class SlowModel:
    """Model stub with a tunable forward-pass duration (admission tests)."""

    def __init__(self, delay=0.2, width=4):
        self.delay = delay
        self.width = width
        self.calls = 0

    def output(self, x):
        self.calls += 1
        time.sleep(self.delay)
        return np.asarray(x)[:, : self.width]


# ------------------------------------------------------------ bucket policy

def test_bucket_policy_powers_of_two():
    p = BucketPolicy(max_batch=32)
    assert p.batch_buckets == (1, 2, 4, 8, 16, 32)
    assert p.bucket_rows(1) == 1
    assert p.bucket_rows(3) == 4
    assert p.bucket_rows(17) == 32
    assert p.bucket_rows(999) == 32  # oversized: batcher chunks first


def test_bucket_policy_non_pow2_cap_and_fixed_mode():
    p = BucketPolicy(max_batch=24)
    assert p.batch_buckets == (1, 2, 4, 8, 16, 24)
    fixed = BucketPolicy(max_batch=16, batch_buckets=(16,))
    assert fixed.bucket_rows(1) == 16  # legacy pad-to-max behaviour
    with pytest.raises(ValueError, match="must equal"):
        BucketPolicy(max_batch=16, batch_buckets=(8,))


def test_bucket_policy_seq_buckets_and_warmup_shapes():
    p = BucketPolicy(max_batch=4, seq_buckets=(8, 16))
    assert p.bucket_seq(5) == 8
    assert p.bucket_seq(16) == 16
    assert p.bucket_seq(100) == 100  # beyond largest: pass through
    shapes = p.warmup_shapes((8, 7))  # (time, feat) row
    assert set(shapes) == {(b, s, 7) for b in (1, 2, 4) for s in (8, 16)}
    assert BucketPolicy(max_batch=2).warmup_shapes((5,)) == [(1, 5), (2, 5)]
    # a rank-1 (dense) row has no time axis — predict never seq-buckets
    # rank-2 inputs, so warmup must not either
    assert p.warmup_shapes((64,)) == [(1, 64), (2, 64), (4, 64)]


# ------------------------------------------------- warmup / recompile proof

def test_warmup_precompiles_all_buckets_zero_steady_state_compiles(
        fresh_registry):
    net = small_net()
    warnings = []
    import logging

    handler = logging.Handler()
    handler.emit = lambda rec: warnings.append(rec.getMessage())
    logging.getLogger("deeplearning4j_tpu.observability").addHandler(handler)
    try:
        eng = ServingEngine(net, max_batch=8, max_wait_ms=1.0,
                            example=np.zeros((4,), np.float32))
        eng.start()
        compiles = fresh_registry.get_value("dl4j_compiles_total",
                                            fn="serving.default")
        assert compiles == 4  # buckets 1, 2, 4, 8
        # warmup compiles are PLANNED: no recompile warnings, no recompiles
        assert not any("recompile" in w for w in warnings)
        assert fresh_registry.get_value("dl4j_recompiles_total",
                                        fn="serving.default") in (None, 0)

        # mixed-size steady-state traffic (incl. oversized -> chunked)
        rs = np.random.RandomState(0)
        for rows in (1, 2, 3, 5, 8, 11, 19):
            out = eng.predict(rs.rand(rows, 4))
            assert out.shape == (rows, 3)
        after = fresh_registry.get_value("dl4j_compiles_total",
                                        fn="serving.default")
        assert after == compiles, "steady-state serving must not compile"
    finally:
        logging.getLogger(
            "deeplearning4j_tpu.observability").removeHandler(handler)
        eng.stop()
    util = fresh_registry.get("dl4j_serving_bucket_utilization").get()
    assert util is not None and util.count > 0


def test_bucketed_results_match_direct_forward(fresh_registry):
    net = small_net()
    eng = ServingEngine(net, max_batch=8, max_wait_ms=1.0,
                        example=np.zeros((4,), np.float32)).start()
    try:
        rs = np.random.RandomState(1)
        for rows in (1, 3, 8, 13):
            x = rs.rand(rows, 4).astype(np.float32)
            np.testing.assert_allclose(eng.predict(x),
                                       np.asarray(net.output(x)),
                                       rtol=1e-5, atol=1e-6)
    finally:
        eng.stop()


# --------------------------------------------------------- concurrent load

def test_concurrent_mixed_size_stress_deinterleaves_correctly(fresh_registry):
    net = small_net(n_in=6)
    eng = ServingEngine(net, max_batch=16, max_wait_ms=2.0, max_queue=512,
                        example=np.zeros((6,), np.float32)).start()
    compiles = fresh_registry.get_value("dl4j_compiles_total",
                                        fn="serving.default")
    n_threads, per_thread = 12, 8
    errors, checked = [], [0]
    lock = threading.Lock()

    def client(tid):
        rs = np.random.RandomState(tid)
        for i in range(per_thread):
            x = rs.rand(1 + rs.randint(9), 6).astype(np.float32)
            try:
                out = eng.predict(x)
                expect = np.asarray(net.output(x))
                np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)
                with lock:
                    checked[0] += 1
            except Exception as e:  # pragma: no cover - failure detail
                with lock:
                    errors.append(f"t{tid}r{i}: {e!r}")

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(n_threads)]
    [t.start() for t in threads]
    [t.join(timeout=60) for t in threads]
    eng.stop()
    assert not errors, errors[:3]
    assert checked[0] == n_threads * per_thread
    assert fresh_registry.get_value(
        "dl4j_compiles_total", fn="serving.default") == compiles
    assert fresh_registry.get_value("dl4j_serving_requests_total",
                                    status="ok") == n_threads * per_thread
    # micro-batching actually coalesced concurrent requests
    batches = fresh_registry.get("dl4j_serving_batch_rows").get()
    assert batches.count < n_threads * per_thread


def test_full_batch_dispatches_immediately_not_after_max_wait(fresh_registry):
    net = small_net()
    eng = ServingEngine(net, max_batch=4, max_wait_ms=2000.0,
                        example=np.zeros((4,), np.float32)).start()
    try:
        barrier = threading.Barrier(4)
        latencies = [None] * 4

        def hit(i):
            barrier.wait()
            t0 = time.perf_counter()
            eng.predict(np.random.rand(1, 4))
            latencies[i] = time.perf_counter() - t0

        threads = [threading.Thread(target=hit, args=(i,)) for i in range(4)]
        [t.start() for t in threads]
        [t.join(timeout=10) for t in threads]
        assert all(l is not None for l in latencies)
        # budget met -> immediate dispatch; the 2000 ms max_wait never taxes
        assert max(latencies) < 1.0, latencies
    finally:
        eng.stop()


# -------------------------------------------------------------- admission

def test_queue_budget_sheds_with_429_semantics(fresh_registry):
    eng = ServingEngine(SlowModel(delay=0.25), max_batch=1, max_queue=2,
                        max_wait_ms=0.0)
    eng.start(warmup=False)
    results = [None] * 8

    def hit(i):
        try:
            results[i] = ("ok", eng.predict(np.zeros((1, 4), np.float32)))
        except QueueFullError as e:
            results[i] = ("shed", e)
        except Exception as e:
            results[i] = ("err", e)

    threads = [threading.Thread(target=hit, args=(i,)) for i in range(8)]
    [t.start() for t in threads]
    [t.join(timeout=30) for t in threads]
    eng.stop()
    kinds = [r[0] for r in results]
    assert None not in kinds, "a shed request hung its waiter"
    assert "shed" in kinds and "ok" in kinds
    assert "err" not in kinds
    shed = [r for k, r in zip(kinds, results) if k == "shed"]
    assert all(r[1].http_status == 429 for r in shed)
    assert fresh_registry.get_value("dl4j_serving_shed_total",
                                    reason="queue_full") == kinds.count("shed")


def test_dead_dispatcher_times_out_instead_of_hanging(fresh_registry):
    eng = ServingEngine(SlowModel(delay=0.0), max_batch=2)
    # engine never started: no dispatcher thread exists
    t0 = time.perf_counter()
    with pytest.raises(DeadlineExceededError, match="dispatcher dead"):
        eng.predict(np.zeros((1, 4), np.float32), deadline_s=0.3)
    assert time.perf_counter() - t0 < 5.0
    assert fresh_registry.get_value("dl4j_serving_requests_total",
                                    status="deadline") == 1


def test_deadline_expires_in_queue_without_running_model(fresh_registry):
    model = SlowModel(delay=0.4)
    eng = ServingEngine(model, max_batch=1, max_queue=16, max_wait_ms=0.0)
    eng.start(warmup=False)
    try:
        blocker = threading.Thread(
            target=lambda: eng.predict(np.zeros((1, 4), np.float32)))
        blocker.start()
        time.sleep(0.05)  # let the blocker batch enter the model
        with pytest.raises(DeadlineExceededError):
            eng.predict(np.zeros((1, 4), np.float32), deadline_s=0.1)
        blocker.join(timeout=10)
        assert model.calls == 1  # the expired request never ran
    finally:
        eng.stop()


def test_unknown_model_is_a_404_error(fresh_registry):
    eng = ServingEngine(small_net(), max_batch=2,
                        example=np.zeros((4,), np.float32)).start()
    try:
        with pytest.raises(ModelNotFoundError):
            eng.predict(np.zeros((1, 4), np.float32), model="nope")
    finally:
        eng.stop()


def test_stop_drains_queued_requests_then_sheds_new_ones(fresh_registry):
    eng = ServingEngine(SlowModel(delay=0.05), max_batch=1, max_queue=32,
                        max_wait_ms=50.0)
    eng.start(warmup=False)
    results = [None] * 5

    def hit(i):
        try:
            results[i] = ("ok", eng.predict(np.zeros((1, 4), np.float32)))
        except Exception as e:
            results[i] = ("err", e)

    threads = [threading.Thread(target=hit, args=(i,)) for i in range(5)]
    [t.start() for t in threads]
    time.sleep(0.02)
    eng.stop(drain=True)   # graceful: everything queued still serves
    [t.join(timeout=30) for t in threads]
    assert all(r is not None and r[0] == "ok" for r in results), results
    with pytest.raises(ShuttingDownError):
        eng.predict(np.zeros((1, 4), np.float32))


def test_stop_without_drain_fails_waiters_instead_of_hanging(fresh_registry):
    eng = ServingEngine(SlowModel(delay=0.3), max_batch=1, max_queue=32,
                        max_wait_ms=0.0)
    eng.start(warmup=False)
    results = [None] * 4

    def hit(i):
        try:
            results[i] = ("ok", eng.predict(np.zeros((1, 4), np.float32)))
        except ShuttingDownError as e:
            results[i] = ("shutdown", e)
        except Exception as e:
            results[i] = ("err", e)

    threads = [threading.Thread(target=hit, args=(i,)) for i in range(4)]
    [t.start() for t in threads]
    time.sleep(0.05)
    eng.stop(drain=False, timeout=10.0)
    [t.join(timeout=30) for t in threads]
    assert None not in [r[0] for r in results], "a waiter hung on shutdown"
    assert any(r[0] == "shutdown" for r in results)
    assert not any(r[0] == "err" for r in results)


def test_saturated_key_does_not_starve_other_shapes(fresh_registry):
    # one shape floods the engine continuously; a request of ANOTHER shape
    # must still be served long before its deadline (oldest-head fairness)
    eng = ServingEngine(SlowModel(delay=0.02, width=2), max_batch=2,
                        max_queue=256, max_wait_ms=0.0)
    eng.start(warmup=False)
    stop_flag = threading.Event()

    def flood():
        while not stop_flag.is_set():
            try:
                eng.predict(np.zeros((2, 4), np.float32))
            except Exception:
                return

    floods = [threading.Thread(target=flood) for _ in range(4)]
    [t.start() for t in floods]
    time.sleep(0.1)  # let the width-4 key saturate
    try:
        t0 = time.perf_counter()
        out = eng.predict(np.zeros((1, 8), np.float32), deadline_s=10.0)
        assert out.shape == (1, 2)
        assert time.perf_counter() - t0 < 5.0
    finally:
        stop_flag.set()
        [t.join(timeout=10) for t in floods]
        eng.stop(drain=False)


def test_restarted_engine_rebinds_queue_depth_gauge(fresh_registry):
    eng = ServingEngine(SlowModel(delay=0.2), max_batch=1, max_wait_ms=0.0)
    eng.start(warmup=False)
    eng.stop()
    eng.start(warmup=False)   # stop() froze the gauge at 0; must re-arm
    t = threading.Thread(
        target=lambda: eng.predict(np.zeros((1, 4), np.float32)))
    t.start()
    time.sleep(0.05)
    t2 = threading.Thread(
        target=lambda: eng.predict(np.zeros((1, 4), np.float32)))
    t2.start()
    time.sleep(0.05)
    depth = fresh_registry.get_value("dl4j_serving_queue_depth",
                                     server=eng.metrics.server_id)
    [x.join(timeout=10) for x in (t, t2)]
    eng.stop()
    assert depth >= 1, "restarted engine exports a dead queue-depth gauge"


def test_pinned_version_never_rewinds_the_counter(fresh_registry):
    from deeplearning4j_tpu.serving import ModelRegistry

    reg = ModelRegistry()
    assert reg.register("m", object()).version == 1
    assert reg.register("m", object()).version == 2
    assert reg.register("m", object(), version=1).version == 1  # pinned
    assert reg.register("m", object()).version == 3  # no duplicate v2


def test_retired_versions_release_weights_and_history_is_capped(
        fresh_registry):
    from deeplearning4j_tpu.serving import ModelRegistry

    reg = ModelRegistry()
    displaced = []
    for _ in range(ModelRegistry.HISTORY_LIMIT + 5):
        old = reg.activate(reg.new_version("m", object()))
        if old is not None:
            assert reg.retire(old, timeout=1.0)
            displaced.append(old)
    # weights are the memory cost of a swap — retire must drop them
    assert all(mv.model is None for mv in displaced)
    assert all(mv.model_type == "object" for mv in displaced)  # metadata kept
    assert len(reg.as_dict()["retired"]) == ModelRegistry.HISTORY_LIMIT


def test_serving_pipeline_requires_broker():
    with pytest.raises(ValueError, match="broker"):
        ServingPipeline(small_net())


def test_inference_server_rejects_model_plus_engine(fresh_registry):
    eng = ServingEngine(small_net(), max_batch=2)
    with pytest.raises(ValueError, match="not both"):
        InferenceServer(small_net(seed=9), engine=eng)


def test_serving_pipeline_survives_transient_shed_on_shared_engine(
        fresh_registry):
    eng = ServingEngine(SlowModel(delay=0.15, width=2), max_batch=1,
                        max_queue=1, max_wait_ms=0.0)
    eng.start(warmup=False)
    broker = MessageBroker()
    out_q = broker.subscribe("p")
    pipe = ServingPipeline(broker=broker, in_topic="f", out_topic="p",
                           engine=eng)
    # saturate the engine so the pipeline's first predicts get shed
    stop_flag = threading.Event()

    def flood():
        while not stop_flag.is_set():
            try:
                eng.predict(np.zeros((1, 4), np.float32))
            except Exception:
                pass

    flooder = threading.Thread(target=flood)
    flooder.start()
    for i in range(4):
        broker.publish("f", json.dumps([0.1 * i, 0.2, 0.3, 0.4]))
    t = threading.Thread(target=lambda: pipe.run(timeout=0.3))
    t.start()
    time.sleep(1.0)
    stop_flag.set()
    flooder.join(timeout=10)
    pipe.stop()
    t.join(timeout=30)
    assert not t.is_alive(), "a shed killed the consumer loop"
    # with the flood gone the loop kept consuming: at least one message
    # made it through end-to-end (shed ones were dropped, not fatal)
    eng.stop()


def test_healthz_fails_when_dispatcher_dead(fresh_registry):
    eng = ServingEngine(small_net(), max_batch=4,
                        example=np.zeros((4,), np.float32))
    # never started: dispatcher thread does not exist
    server = InferenceServer(engine=eng)
    port = server.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["dispatcher_alive"] is False
    finally:
        server.stop()


def test_serving_pipeline_owned_engine_scoped_to_run():
    broker = MessageBroker()
    out_q = broker.subscribe("p")
    pipe = ServingPipeline(small_net(n_in=2, n_out=2), broker=broker,
                           in_topic="f", out_topic="p", max_batch=4)
    broker.publish("f", json.dumps([0.1, 0.2]))
    pipe.run(max_messages=1, timeout=0.5)
    # an owned engine lives only while run() executes — a dropped
    # pipeline must not leak the dispatch thread or pin the model
    assert not pipe.engine.batcher.is_alive()
    assert out_q.get(timeout=2) is not None
    # a later run() restarts it transparently
    broker.publish("f", json.dumps([0.3, 0.4]))
    pipe.run(max_messages=1, timeout=0.5)
    assert out_q.get(timeout=2) is not None
    assert not pipe.engine.batcher.is_alive()


# --------------------------------------------------------------- hot swap

def test_hot_swap_serves_continuously_with_zero_drops(fresh_registry):
    net_a = small_net(seed=7)
    net_b = small_net(seed=99)
    probe = np.linspace(0.0, 1.0, 8, dtype=np.float32).reshape(2, 4)
    # distinguishable versions, else the swap assertion proves nothing
    assert not np.allclose(np.asarray(net_a.output(probe)),
                           np.asarray(net_b.output(probe)))
    eng = ServingEngine(net_a, max_batch=8, max_wait_ms=1.0,
                        example=np.zeros((4,), np.float32)).start()
    stop_flag = threading.Event()
    failures, served = [], [0]
    lock = threading.Lock()

    def client():
        rs = np.random.RandomState()
        while not stop_flag.is_set():
            try:
                out = eng.predict(rs.rand(1 + rs.randint(6), 4))
                assert np.isfinite(out).all()
                with lock:
                    served[0] += 1
            except Exception as e:
                with lock:
                    failures.append(repr(e))

    threads = [threading.Thread(target=client) for _ in range(6)]
    [t.start() for t in threads]
    time.sleep(0.2)
    mv = eng.deploy("default", net_b,
                    example=np.zeros((4,), np.float32))
    time.sleep(0.2)
    stop_flag.set()
    [t.join(timeout=30) for t in threads]
    try:
        assert not failures, failures[:3]
        assert served[0] > 20
        assert mv.version == 2
        # the new version is what serves now
        np.testing.assert_allclose(eng.predict(probe),
                                   np.asarray(net_b.output(probe)),
                                   rtol=1e-5, atol=1e-6)
        assert fresh_registry.get_value("dl4j_serving_model_swaps_total",
                                        model="default") == 1
        state = eng.stats()["models"]
        assert state["active"]["default"]["version"] == 2
        assert state["retired"][0]["state"] == "retired"
        assert state["retired"][0]["inflight"] == 0
    finally:
        eng.stop()


def test_hot_swap_from_checkpoint_pins_manifest_version(
        fresh_registry, tmp_path):
    from deeplearning4j_tpu.models.serialization import write_model

    net_a, net_b = small_net(seed=7), small_net(seed=31)
    path = tmp_path / "v7.zip"
    write_model(net_b, path, extra_manifest={"serving_version": 7})
    eng = ServingEngine(net_a, max_batch=4,
                        example=np.zeros((4,), np.float32)).start()
    try:
        mv = eng.deploy("default", str(path),
                        example=np.zeros((4,), np.float32))
        assert mv.version == 7
        probe = np.linspace(0.0, 1.0, 8, dtype=np.float32).reshape(2, 4)
        np.testing.assert_allclose(eng.predict(probe),
                                   np.asarray(net_b.output(probe)),
                                   rtol=1e-5, atol=1e-6)
    finally:
        eng.stop()


def test_extra_manifest_rejects_reserved_keys(tmp_path):
    from deeplearning4j_tpu.models.serialization import write_model

    with pytest.raises(ValueError, match="may not override"):
        write_model(small_net(), tmp_path / "x.zip",
                    extra_manifest={"model_type": "evil"})


def test_deploy_of_broken_model_aborts_swap_keeps_old_serving(fresh_registry):
    net_a = small_net(seed=7)
    net_wrong_width = small_net(n_in=9, seed=8)
    eng = ServingEngine(net_a, max_batch=4,
                        example=np.zeros((4,), np.float32)).start()
    try:
        with pytest.raises(Exception):  # warmup forward fails -> no flip
            eng.deploy("default", net_wrong_width,
                       example=np.zeros((4,), np.float32))
        assert eng.stats()["models"]["active"]["default"]["version"] == 1
        assert eng.predict(np.zeros((1, 4), np.float32)).shape == (1, 3)
        assert fresh_registry.get_value("dl4j_serving_model_swaps_total",
                                        model="default") in (None, 0)
    finally:
        eng.stop()


def test_batcher_stop_timeout_leaves_live_dispatcher_intact(fresh_registry):
    eng = ServingEngine(SlowModel(delay=0.6), max_batch=1, max_wait_ms=0.0)
    eng.start(warmup=False)
    result = []
    t = threading.Thread(target=lambda: result.append(
        eng.predict(np.zeros((1, 4), np.float32))))
    t.start()
    time.sleep(0.1)  # request is inside the model forward
    eng.stop(drain=True, timeout=0.05)  # join times out mid-execute
    assert eng.batcher.is_alive()  # must not lie about a live thread
    t.join(timeout=10)
    assert result and result[0].shape == (1, 4)  # drain promise kept


# ----------------------------------------------------------- HTTP front-end

def _post(url, body, timeout=15):
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def test_http_malformed_json_gets_structured_400(fresh_registry):
    server = InferenceServer(small_net(), max_batch=4,
                             example=np.zeros((4,), np.float32))
    port = server.start()
    url = f"http://127.0.0.1:{port}"
    try:
        for body in (b"{not json", b"\xff\xfe garbage",
                     json.dumps([[1.0], [1.0, 2.0]]).encode()):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"{url}/predict", body)
            assert ei.value.code == 400
            err = json.loads(ei.value.read())
            assert "error" in err
        # server still healthy afterwards
        with urllib.request.urlopen(f"{url}/healthz", timeout=5) as r:
            assert json.loads(r.read())["dispatcher_alive"]
    finally:
        server.stop()


def test_http_shed_returns_429_not_hang(fresh_registry):
    eng = ServingEngine(SlowModel(delay=0.25), max_batch=1, max_queue=1,
                        max_wait_ms=0.0)
    eng.start(warmup=False)
    server = InferenceServer(engine=eng)
    port = server.start()
    url = f"http://127.0.0.1:{port}/predict"
    body = json.dumps([[0.0, 0.0, 0.0, 0.0]]).encode()
    codes = [None] * 6

    def hit(i):
        try:
            with _post(url, body, timeout=30) as r:
                codes[i] = r.status
        except urllib.error.HTTPError as e:
            codes[i] = e.code

    threads = [threading.Thread(target=hit, args=(i,)) for i in range(6)]
    [t.start() for t in threads]
    [t.join(timeout=60) for t in threads]
    server.stop()
    eng.stop()
    assert None not in codes, "an HTTP request hung"
    assert 429 in codes and 200 in codes, codes


def test_http_models_endpoint_and_hot_swap(fresh_registry, tmp_path):
    from deeplearning4j_tpu.models.serialization import write_model

    net_a, net_b = small_net(seed=7), small_net(seed=31)
    path = tmp_path / "next.zip"
    write_model(net_b, path)
    server = InferenceServer(net_a, max_batch=4,
                             example=np.zeros((4,), np.float32))
    port = server.start()
    url = f"http://127.0.0.1:{port}"
    try:
        with urllib.request.urlopen(f"{url}/models", timeout=5) as r:
            state = json.loads(r.read())
        assert state["models"]["active"]["default"]["version"] == 1
        assert state["batch_buckets"] == [1, 2, 4]
        with _post(f"{url}/models/default",
                   json.dumps({"path": str(path)}).encode()) as r:
            swap = json.loads(r.read())
        assert swap == {"model": "default", "version": 2, "state": "active"}
        probe = np.linspace(0.0, 1.0, 8, dtype=np.float32).reshape(2, 4)
        with _post(f"{url}/predict",
                   json.dumps(probe.tolist()).encode()) as r:
            out = base64_to_array(json.loads(r.read()))
        np.testing.assert_allclose(out, np.asarray(net_b.output(probe)),
                                   rtol=1e-5, atol=1e-6)
        # swap body validation
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{url}/models/default", json.dumps({"nope": 1}).encode())
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{url}/models/default",
                  json.dumps({"path": str(tmp_path / "missing.zip")}).encode())
        assert ei.value.code == 400
        # an existing file that is not a zip must be a 400, not a 500
        notzip = tmp_path / "notzip.zip"
        notzip.write_bytes(b"definitely not a zip archive")
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{url}/models/default",
                  json.dumps({"path": str(notzip)}).encode())
        assert ei.value.code == 400
    finally:
        server.stop()


# ------------------------------------------------------------ seq buckets

def test_seq_bucketing_pads_time_axis_and_slices_output(fresh_registry):
    from deeplearning4j_tpu.models.zoo import graves_lstm_char_lm

    vocab = 6
    net = graves_lstm_char_lm(vocab_size=vocab, hidden=8, layers=1, tbptt=16)
    policy = BucketPolicy(max_batch=2, seq_buckets=(8, 16))
    eng = ServingEngine(net, policy=policy, max_wait_ms=1.0,
                        example=np.zeros((8, vocab), np.float32))
    eng.start()
    try:
        compiles = fresh_registry.get_value("dl4j_compiles_total",
                                            fn="serving.default")
        assert compiles == 2 * 2  # batch {1,2} x seq {8,16}
        rs = np.random.RandomState(0)
        x = rs.rand(1, 5, vocab).astype(np.float32)  # ragged seq: 5 -> 8
        out = eng.predict(x)
        assert out.shape == (1, 5, vocab)
        # causal model: padded future steps cannot alter the real prefix
        np.testing.assert_allclose(out, np.asarray(net.output(x)),
                                   rtol=1e-4, atol=1e-5)
        assert fresh_registry.get_value(
            "dl4j_compiles_total", fn="serving.default") == compiles
    finally:
        eng.stop()


# --------------------------------------------------------- pipeline routing

def test_serving_pipeline_routes_through_shared_engine(fresh_registry):
    net = small_net(n_in=2, n_out=2)
    eng = ServingEngine(net, max_batch=8, max_wait_ms=1.0,
                        example=np.zeros((2,), np.float32)).start()
    broker = MessageBroker()
    out_q = broker.subscribe("preds")
    pipe = ServingPipeline(broker=broker, in_topic="features",
                           out_topic="preds", engine=eng)
    for i in range(3):
        broker.publish("features", json.dumps([0.1 * i, 0.7]))
    pipe.run(max_messages=3, timeout=1.0)
    preds = [base64_to_array(json.loads(out_q.get(timeout=2)))
             for _ in range(3)]
    eng.stop()
    assert all(p.shape == (1, 2) for p in preds)
    np.testing.assert_allclose(preds[1],
                               np.asarray(net.output(
                                   np.array([[0.1, 0.7]], np.float32))),
                               rtol=1e-5, atol=1e-6)
    # predictions went through the engine's batcher, not model.output
    assert fresh_registry.get_value("dl4j_serving_requests_total",
                                    status="ok") == 3
    assert fresh_registry.get("dl4j_serving_batch_rows").get().count >= 1


# ------------------------------------------------- retain / rollback / canary

def test_retaining_swap_rollback_under_load_zero_drops(fresh_registry):
    """Satellite: hot-swap with retain_old keeps the previous version
    loaded; rollback under concurrent load atomically flips back and
    drops zero requests — every reply matches one of the two versions."""
    net_a, net_b = small_net(seed=7), small_net(seed=99)
    probe = np.linspace(0.0, 1.0, 8, dtype=np.float32).reshape(2, 4)
    out_a = np.asarray(net_a.output(probe))
    out_b = np.asarray(net_b.output(probe))
    assert not np.allclose(out_a, out_b)
    eng = ServingEngine(net_a, max_batch=8, max_wait_ms=1.0,
                        example=np.zeros((4,), np.float32)).start()
    stop_flag = threading.Event()
    failures, served = [], [0]
    lock = threading.Lock()

    def client():
        while not stop_flag.is_set():
            try:
                out = np.asarray(eng.predict(probe))
                # a reply must be EXACTLY one version's output — a swap
                # or rollback mid-flight may pick either, never a blend
                if not (np.allclose(out, out_a, atol=1e-5)
                        or np.allclose(out, out_b, atol=1e-5)):
                    with lock:
                        failures.append("blended output")
                with lock:
                    served[0] += 1
            except Exception as e:
                with lock:
                    failures.append(repr(e))

    threads = [threading.Thread(target=client) for _ in range(6)]
    [t.start() for t in threads]
    try:
        time.sleep(0.15)
        mv_b = eng.deploy("default", net_b, retain_old=True,
                          example=np.zeros((4,), np.float32))
        retained = eng.models.retained("default")
        assert retained is not None and retained.version == 1
        assert retained.state == "retained"
        assert retained.model is not None, "rollback target must stay loaded"
        time.sleep(0.15)

        restored = eng.rollback("default")
        assert restored.version == 1
        time.sleep(0.15)
        stop_flag.set()
        [t.join(timeout=30) for t in threads]

        assert not failures, failures[:3]
        assert served[0] > 20
        # v1 serves again; the displaced bad version drained + retired
        np.testing.assert_allclose(eng.predict(probe), out_a,
                                   rtol=1e-5, atol=1e-6)
        assert eng.models.active("default").version == 1
        assert eng.models.retained("default") is None
        retired = eng.stats()["models"]["retired"]
        assert any(r["version"] == mv_b.version
                   and r["state"] == "retired" for r in retired)
        with pytest.raises(ModelNotFoundError):
            eng.rollback("default")    # window closed
    finally:
        stop_flag.set()
        eng.stop()


def test_commit_swap_closes_rollback_window(fresh_registry):
    eng = ServingEngine(small_net(seed=7), max_batch=8,
                        example=np.zeros((4,), np.float32)).start()
    try:
        eng.deploy("default", small_net(seed=99), retain_old=True,
                   example=np.zeros((4,), np.float32))
        assert eng.models.retained("default") is not None
        released = eng.commit_swap("default")
        assert released.version == 1 and released.state == "retired"
        assert released.model is None        # weights freed
        assert eng.models.retained("default") is None
        assert eng.commit_swap("default") is None   # idempotent
        with pytest.raises(ModelNotFoundError):
            eng.rollback("default")
        # a second retaining swap opens a fresh window on the new pair
        eng.deploy("default", small_net(seed=3), retain_old=True,
                   example=np.zeros((4,), np.float32))
        assert eng.models.retained("default").version == 2
    finally:
        eng.stop()


def test_canary_routes_fraction_and_tears_down(fresh_registry):
    net_a, net_b = small_net(seed=7), small_net(seed=99)
    probe = np.linspace(0.0, 1.0, 8, dtype=np.float32).reshape(2, 4)
    out_a = np.asarray(net_a.output(probe))
    out_b = np.asarray(net_b.output(probe))
    eng = ServingEngine(net_a, max_batch=8, max_wait_ms=1.0,
                        example=np.zeros((4,), np.float32)).start()
    try:
        eng.start_canary("default", net_b, fraction=0.5, seed=11)
        assert "default:canary" in eng.models.names()
        hits = {"a": 0, "b": 0}
        for _ in range(40):
            out = np.asarray(eng.predict(probe))
            hits["a" if np.allclose(out, out_a, atol=1e-5) else "b"] += 1
        assert hits["a"] > 0 and hits["b"] > 0, hits
        stats = eng.canary_stats("default")
        assert stats["requests"] == hits["b"]
        assert stats["ok"] == hits["b"] and stats["bad"] == 0

        final = eng.stop_canary("default")
        assert final["requests"] == hits["b"]
        assert "default:canary" not in eng.models.names()
        assert eng.canary_stats("default") is None
        # all traffic back on the primary
        for _ in range(10):
            np.testing.assert_allclose(eng.predict(probe), out_a,
                                       rtol=1e-5, atol=1e-6)
        # primary was never displaced
        assert eng.models.active("default").version == 1
    finally:
        eng.stop()


def test_http_rollback_endpoint(fresh_registry):
    from deeplearning4j_tpu.streaming import InferenceServer

    eng = ServingEngine(small_net(seed=7), max_batch=8,
                        example=np.zeros((4,), np.float32))
    server = InferenceServer(engine=eng)
    port = server.start()
    try:
        eng.deploy("default", small_net(seed=99), retain_old=True,
                   example=np.zeros((4,), np.float32))
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/models/default/rollback", data=b"{}")
        with urllib.request.urlopen(req, timeout=10) as resp:
            body = json.loads(resp.read())
        assert body == {"model": "default", "version": 1, "state": "active"}
        # nothing retained anymore: a second rollback is a structured 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
    finally:
        server.stop()


def test_canary_teardown_race_falls_back_to_primary(fresh_registry):
    """A request that took the canary route just as the canary registry
    entry disappeared must fall back to the primary, not error — the
    zero-drop contract outranks the traffic split."""
    net_a, net_b = small_net(seed=7), small_net(seed=99)
    probe = np.linspace(0.0, 1.0, 8, dtype=np.float32).reshape(2, 4)
    out_a = np.asarray(net_a.output(probe))
    eng = ServingEngine(net_a, max_batch=8, max_wait_ms=1.0,
                        example=np.zeros((4,), np.float32)).start()
    try:
        eng.start_canary("default", net_b, fraction=1.0)
        # simulate the unlucky interleaving: the route still exists (the
        # request will take it) but the registry entry is already gone
        mv = eng.models.remove("default:canary")
        assert mv is not None
        out = np.asarray(eng.predict(probe))     # must NOT raise
        np.testing.assert_allclose(out, out_a, rtol=1e-5, atol=1e-6)
        eng.stop_canary("default")
    finally:
        eng.stop()
