"""Provisioning (deeplearning4j-aws analog) + interop (MLLibUtil analog)."""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.provision import (
    ClusterSetup, ClusterSpec, HostProvisioner, bootstrap_distributed,
)
from deeplearning4j_tpu.utils import (
    dataset_from_torch, dataset_to_labeled_points, dataset_to_torch,
    from_torch, labeled_points_to_dataset, to_torch,
)


def test_cluster_spec_commands():
    spec = ClusterSpec(name="c1", accelerator_type="v4-32", zone="z",
                       project="p")
    create = spec.create_command()
    assert create[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "create",
                          "c1"]
    assert "--accelerator-type=v4-32" in create and "--project=p" in create
    assert spec.num_workers == 4  # 32 chips / 8 per host
    ssh = spec.ssh_command(2, "echo hi")
    assert "--worker=2" in ssh and ssh[-1] == "echo hi"


def test_cluster_setup_bootstrap(tmp_path):
    spec = ClusterSpec(num_slices=2)
    setup = ClusterSetup(spec, train_module="myproj.train")
    p = setup.write_bootstrap(tmp_path)
    text = p.read_text()
    assert "jax.distributed" in text and "myproj.train" in text
    cmds = setup.launch_commands()
    assert cmds[0][4] == "create"
    assert any("bootstrap.sh" in " ".join(c) for c in cmds)
    prov = HostProvisioner(spec)
    up = prov.upload_command("model.zip", worker=1)
    assert "scp" in up and "--worker=1" in up


def test_bootstrap_distributed_single_process_noop():
    out = bootstrap_distributed()
    assert out == {"distributed": False, "processes": 1, "process_id": 0}


def test_torch_interop_roundtrip():
    rs = np.random.RandomState(0)
    ds = DataSet(rs.rand(6, 3).astype(np.float32),
                 np.eye(2, dtype=np.float32)[rs.randint(0, 2, 6)])
    t = to_torch(ds.features)
    np.testing.assert_allclose(from_torch(t), ds.features)
    back = dataset_from_torch(dataset_to_torch(ds))
    np.testing.assert_allclose(back.features, ds.features)
    np.testing.assert_allclose(back.labels, ds.labels)


def test_labeled_points_roundtrip():
    pts = [([0.1, 0.2], 1), ([0.3, 0.4], 0)]
    ds = labeled_points_to_dataset(pts, num_classes=2)
    assert ds.labels[0, 1] == 1.0 and ds.labels[1, 0] == 1.0
    back = dataset_to_labeled_points(ds)
    assert back[0][1] == 1 and back[1][1] == 0
    np.testing.assert_allclose(back[0][0], [0.1, 0.2], atol=1e-6)
