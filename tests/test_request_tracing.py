"""End-to-end request tracing: trace ids minted at the HTTP edge (or
accepted from X-Request-Id), propagated request -> admission -> batcher
queue -> dispatch -> execute -> response; per-stage spans retrievable from
SpanTracer by trace id; shed/deadline errors naming the id; latency
exemplars; and the access log."""

import json
import logging
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers.dense import DenseLayer, OutputLayer
from deeplearning4j_tpu.observability import (
    FlightRecorder, MetricsRegistry, SpanTracer, get_registry, get_tracer,
    new_trace_id, set_flight_recorder, set_registry, set_tracer,
)
from deeplearning4j_tpu.observability.flightrecorder import (
    get_flight_recorder,
)
from deeplearning4j_tpu.serving import ServingEngine
from deeplearning4j_tpu.serving.admission import (
    DeadlineExceededError, QueueFullError, ServingError, ShuttingDownError,
)
from deeplearning4j_tpu.streaming.serving import InferenceServer

pytestmark = pytest.mark.profiling

N_IN, N_OUT = 8, 4


@pytest.fixture(autouse=True)
def fresh_telemetry():
    old_reg = get_registry()
    old_tr = get_tracer()
    reg = set_registry(MetricsRegistry())
    set_tracer(SpanTracer(max_spans=65536))
    set_flight_recorder(FlightRecorder())
    yield reg
    set_registry(old_reg)
    set_tracer(old_tr)
    set_flight_recorder(FlightRecorder())


def make_net(seed=7):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(seed)
         .updater("sgd", learning_rate=0.1).list()
         .layer(DenseLayer(n_in=N_IN, n_out=16))
         .layer(OutputLayer(n_in=16, n_out=N_OUT)).build())).init()


def test_new_trace_id_shape_and_uniqueness():
    ids = {new_trace_id() for _ in range(64)}
    assert len(ids) == 64
    assert all(len(t) == 16 and int(t, 16) >= 0 for t in ids)


def test_concurrent_mixed_bucket_trace_propagation():
    """Acceptance: concurrent mixed-bucket load — every response is the
    model's output for ITS OWN request (no cross-batch swaps), and the
    queue/execute span breakdown is retrievable from SpanTracer by each
    request's trace id."""
    net = make_net()
    engine = ServingEngine(net, max_batch=8, max_wait_ms=1.0,
                           max_queue=4096,
                           example=np.zeros((N_IN,), np.float32))
    engine.start()
    results = {}
    errors = []
    lock = threading.Lock()

    def client(tid_idx):
        rs = np.random.RandomState(100 + tid_idx)
        try:
            for j in range(6):
                rows = 1 + int(rs.randint(6))      # mixed bucket sizes
                x = rs.rand(rows, N_IN).astype(np.float32)
                trace_id = f"client{tid_idx:02d}-req{j:02d}----"
                out = engine.predict(x, trace_id=trace_id)
                with lock:
                    results[trace_id] = (x, np.asarray(out))
        except Exception as e:
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(6)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    engine.stop()
    assert not errors, errors
    assert len(results) == 36
    tracer = get_tracer()
    for trace_id, (x, out) in results.items():
        # the response really belongs to this request's input
        expected = np.asarray(net.output(x))
        np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)
        # per-stage breakdown by this id
        names = {s.name for s in tracer.spans_for_trace(trace_id)}
        assert {"serving_request", "serving_queue_wait",
                "serving_execute"} <= names
        br = engine.request_breakdown(trace_id)
        assert br["status"] == "ok"
        assert br["queue_wait_ms"] >= 0.0
        assert br["execute_ms"] > 0.0
        assert br["bucket"] >= br["batch_rows"] or br["batch_rows"] > 8


def test_shed_and_deadline_errors_name_the_trace_id():
    """Acceptance: a shed request's error names the same trace id the
    caller submitted (attribute, message, and flight event)."""
    net = make_net()
    engine = ServingEngine(net, max_batch=4, max_queue=2, deadline_s=0.3,
                           example=np.zeros((N_IN,), np.float32))
    # dispatcher NOT started: the queue can only fill or expire
    x = np.random.rand(1, N_IN).astype(np.float32)
    caught = {}

    def call(tid):
        try:
            engine.predict(x, trace_id=tid)
        except ServingError as e:
            caught[tid] = e

    threads = [threading.Thread(target=call, args=(f"trace-{i:04d}-ab",))
               for i in range(4)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert len(caught) == 4
    kinds = {type(e) for e in caught.values()}
    assert QueueFullError in kinds          # queue budget is 2
    assert DeadlineExceededError in kinds   # nobody drained the queue
    for tid, e in caught.items():
        assert e.trace_id == tid
        assert tid in str(e)
    sheds = [e.to_dict() for e in get_flight_recorder().events()
             if e.kind == "shed"]
    shed_ids = {s.get("trace_id") for s in sheds}
    assert set(caught) <= shed_ids


def test_latency_exemplar_carries_trace_id():
    net = make_net()
    engine = ServingEngine(net, max_batch=8,
                           example=np.zeros((N_IN,), np.float32))
    engine.start()
    tid = new_trace_id()
    engine.predict(np.random.rand(2, N_IN).astype(np.float32), trace_id=tid)
    engine.stop()
    exemplars = engine.metrics.latency.get().exemplars()
    assert any(e["trace_id"] == tid for e in exemplars.values())


def test_http_trace_id_echo_and_access_log(caplog):
    """HTTP edge: X-Request-Id is echoed in the response body, a minted id
    appears when the client sends none, and access_log=True emits one
    structured line per completed request."""
    srv = InferenceServer(make_net(), max_batch=8,
                          example=np.zeros((N_IN,), np.float32),
                          access_log=True)
    port = srv.start()
    try:
        with caplog.at_level(logging.INFO,
                             logger="deeplearning4j_tpu.serving.access"):
            body = json.dumps(np.random.rand(2, N_IN).tolist()).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/predict", data=body,
                headers={"X-Request-Id": "edge-trace-000001"})
            resp = json.load(urllib.request.urlopen(req))
            assert resp["trace_id"] == "edge-trace-000001"
            # no header -> server mints one
            req2 = urllib.request.Request(
                f"http://127.0.0.1:{port}/predict", data=body)
            resp2 = json.load(urllib.request.urlopen(req2))
            assert len(resp2["trace_id"]) == 16
        lines = [json.loads(r.message) for r in caplog.records
                 if r.name == "deeplearning4j_tpu.serving.access"]
        assert len(lines) == 2
        by_id = {l["trace_id"]: l for l in lines}
        line = by_id["edge-trace-000001"]
        assert line["status"] == "ok" and line["http_status"] == 200
        assert line["queue_wait_ms"] >= 0.0
        assert line["execute_ms"] > 0.0
        assert line["bucket"] in (2, 4, 8)
    finally:
        srv.stop()


def test_http_error_payload_names_trace_id(caplog):
    """429/503/504-class errors carry the trace id in the JSON payload
    and still produce an access-log line."""
    srv = InferenceServer(make_net(), max_batch=8,
                          example=np.zeros((N_IN,), np.float32),
                          access_log=True)
    port = srv.start()
    try:
        srv.engine.stop(drain=False)   # -> ShuttingDownError (503)
        with caplog.at_level(logging.INFO,
                             logger="deeplearning4j_tpu.serving.access"):
            body = json.dumps(np.random.rand(1, N_IN).tolist()).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/predict", data=body,
                headers={"X-Request-Id": "edge-trace-err-01"})
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(req)
            err = exc_info.value
            assert err.code == 503
            payload = json.load(err)
            assert payload["trace_id"] == "edge-trace-err-01"
            assert payload["type"] == "ShuttingDownError"
        lines = [json.loads(r.message) for r in caplog.records
                 if r.name == "deeplearning4j_tpu.serving.access"]
        assert any(l["trace_id"] == "edge-trace-err-01"
                   and l["http_status"] == 503 for l in lines)
    finally:
        srv.stop()


def test_access_log_off_by_default(caplog):
    srv = InferenceServer(make_net(), max_batch=8,
                          example=np.zeros((N_IN,), np.float32))
    port = srv.start()
    try:
        with caplog.at_level(logging.INFO,
                             logger="deeplearning4j_tpu.serving.access"):
            body = json.dumps(np.random.rand(1, N_IN).tolist()).encode()
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/predict", data=body))
        assert not [r for r in caplog.records
                    if r.name == "deeplearning4j_tpu.serving.access"]
    finally:
        srv.stop()
