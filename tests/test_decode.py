"""On-device generation (models.decode): parity with the host-side sampling
loop and cache-mode coverage (GQA, rolling window).

The contract: ``generate`` is ``utils.sampling.sample_sequence`` compiled
into one XLA program — greedy decoding must produce IDENTICAL token ids
through both paths (same forward math through the same KV caches / LSTM
carries), for both input encodings (embedding-ids transformers, one-hot
LSTMs).
"""

import numpy as np
import jax
import pytest

from deeplearning4j_tpu.models.decode import generate
from deeplearning4j_tpu.utils.sampling import sample_sequence


def _greedy_both(net, prompt, steps, **kw):
    ref = sample_sequence(net, prompt, steps, temperature=0.0, **kw)
    got = generate(net, prompt, steps, temperature=0.0, **kw)
    return ref, got


def test_transformer_greedy_matches_host_loop():
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    net = transformer_char_lm(vocab_size=17, d_model=16, n_heads=2, layers=2,
                              max_cache=64)
    rs = np.random.RandomState(0)
    prompt = rs.randint(0, 17, (3, 5))
    ref, got = _greedy_both(net, prompt, 12)
    np.testing.assert_array_equal(got, ref)


def test_transformer_gqa_rolling_greedy_matches_host_loop():
    """The decode-bandwidth features (GQA cache, rolling window cache) run
    through the same scanned program and still match the host loop."""
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    net = transformer_char_lm(vocab_size=13, d_model=16, n_heads=4, layers=2,
                              n_kv_heads=2, window=8)
    rs = np.random.RandomState(1)
    prompt = rs.randint(0, 13, (2, 6))
    # decode well past the window: the rolling cache wraps several times
    ref, got = _greedy_both(net, prompt, 20)
    np.testing.assert_array_equal(got, ref)


def test_lstm_one_hot_greedy_matches_host_loop():
    from deeplearning4j_tpu.models.zoo import graves_lstm_char_lm

    net = graves_lstm_char_lm(vocab_size=11, hidden=12, tbptt=8)
    rs = np.random.RandomState(2)
    prompt = rs.randint(0, 11, (2, 4))
    ref, got = _greedy_both(net, prompt, 10)
    np.testing.assert_array_equal(got, ref)


def test_sampled_decode_shape_determinism_and_filtering():
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    net = transformer_char_lm(vocab_size=17, d_model=16, n_heads=2, layers=1,
                              max_cache=64)
    rs = np.random.RandomState(3)
    prompt = rs.randint(0, 17, (4, 3))
    key = jax.random.PRNGKey(7)
    a = generate(net, prompt, 9, temperature=0.8, top_k=5, rng=key)
    b = generate(net, prompt, 9, temperature=0.8, top_k=5, rng=key)
    assert a.shape == (4, 9)
    np.testing.assert_array_equal(a, b)      # same key -> same draw
    c = generate(net, prompt, 9, temperature=0.8, top_k=5,
                 rng=jax.random.PRNGKey(8))
    assert not np.array_equal(a, c)          # different key -> different draw


def test_generate_overflow_checked_upfront():
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    net = transformer_char_lm(vocab_size=8, d_model=8, n_heads=2, layers=1,
                              max_cache=6)
    prompt = np.zeros((1, 4), np.int64)
    with pytest.raises(ValueError, match="max_cache"):
        generate(net, prompt, 5)             # 4 + 5 > 6
    assert generate(net, prompt, 2).shape == (1, 2)
