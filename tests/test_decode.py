"""On-device generation (models.decode): parity with the host-side sampling
loop and cache-mode coverage (GQA, rolling window).

The contract: ``generate`` is ``utils.sampling.sample_sequence`` compiled
into one XLA program — greedy decoding must produce IDENTICAL token ids
through both paths (same forward math through the same KV caches / LSTM
carries), for both input encodings (embedding-ids transformers, one-hot
LSTMs).
"""

import numpy as np
import jax
import pytest

from deeplearning4j_tpu.models.decode import generate
from deeplearning4j_tpu.utils.sampling import sample_sequence


def _greedy_both(net, prompt, steps, **kw):
    ref = sample_sequence(net, prompt, steps, temperature=0.0, **kw)
    got = generate(net, prompt, steps, temperature=0.0, **kw)
    return ref, got


def test_transformer_greedy_matches_host_loop():
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    net = transformer_char_lm(vocab_size=17, d_model=16, n_heads=2, layers=2,
                              max_cache=64)
    rs = np.random.RandomState(0)
    prompt = rs.randint(0, 17, (3, 5))
    ref, got = _greedy_both(net, prompt, 12)
    np.testing.assert_array_equal(got, ref)


def test_transformer_gqa_rolling_greedy_matches_host_loop():
    """The decode-bandwidth features (GQA cache, rolling window cache) run
    through the same scanned program and still match the host loop."""
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    net = transformer_char_lm(vocab_size=13, d_model=16, n_heads=4, layers=2,
                              n_kv_heads=2, window=8)
    rs = np.random.RandomState(1)
    prompt = rs.randint(0, 13, (2, 6))
    # decode well past the window: the rolling cache wraps several times
    ref, got = _greedy_both(net, prompt, 20)
    np.testing.assert_array_equal(got, ref)


def test_lstm_one_hot_greedy_matches_host_loop():
    from deeplearning4j_tpu.models.zoo import graves_lstm_char_lm

    net = graves_lstm_char_lm(vocab_size=11, hidden=12, tbptt=8)
    rs = np.random.RandomState(2)
    prompt = rs.randint(0, 11, (2, 4))
    ref, got = _greedy_both(net, prompt, 10)
    np.testing.assert_array_equal(got, ref)


def test_sampled_decode_shape_determinism_and_filtering():
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    net = transformer_char_lm(vocab_size=17, d_model=16, n_heads=2, layers=1,
                              max_cache=64)
    rs = np.random.RandomState(3)
    prompt = rs.randint(0, 17, (4, 3))
    key = jax.random.PRNGKey(7)
    a = generate(net, prompt, 9, temperature=0.8, top_k=5, rng=key)
    b = generate(net, prompt, 9, temperature=0.8, top_k=5, rng=key)
    assert a.shape == (4, 9)
    np.testing.assert_array_equal(a, b)      # same key -> same draw
    c = generate(net, prompt, 9, temperature=0.8, top_k=5,
                 rng=jax.random.PRNGKey(8))
    assert not np.array_equal(a, c)          # different key -> different draw


def test_generate_overflow_checked_upfront():
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    net = transformer_char_lm(vocab_size=8, d_model=8, n_heads=2, layers=1,
                              max_cache=6)
    prompt = np.zeros((1, 4), np.int64)
    with pytest.raises(ValueError, match="max_cache"):
        generate(net, prompt, 5)             # 4 + 5 > 6
    assert generate(net, prompt, 2).shape == (1, 2)


def _cg_lstm_char_lm(vocab=11, hidden=12):
    from deeplearning4j_tpu.models.graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import GravesLSTM, RnnOutputLayer

    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater("sgd", learning_rate=0.1).graph()
            .add_inputs("in")
            .add_layer("lstm", GravesLSTM(n_in=vocab, n_out=hidden), "in")
            .add_layer("out", RnnOutputLayer(n_in=hidden, n_out=vocab,
                                             loss="mcxent",
                                             activation="softmax"), "lstm")
            .set_outputs("out").build())
    return ComputationGraph(conf).init()


def _cg_attention_char_lm(vocab=13, d=16, heads=2, cache=64):
    from deeplearning4j_tpu.models.graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (
        EmbeddingLayer, LayerNorm, RnnOutputLayer, SelfAttentionLayer,
    )

    conf = (NeuralNetConfiguration.builder().seed(6)
            .updater("sgd", learning_rate=0.1).graph()
            .add_inputs("ids")
            .add_layer("emb", EmbeddingLayer(n_in=vocab, n_out=d,
                                             collapse_column=False), "ids")
            .add_layer("attn", SelfAttentionLayer(n_in=d, n_out=d,
                                                  n_heads=heads, causal=True,
                                                  max_cache=cache), "emb")
            .add_layer("ln", LayerNorm(n_in=d), "attn")
            .add_layer("out", RnnOutputLayer(n_in=d, n_out=vocab,
                                             loss="mcxent",
                                             activation="softmax"), "ln")
            .set_outputs("out").build())
    return ComputationGraph(conf).init()


def test_cg_lstm_greedy_matches_host_loop():
    """VERDICT r4 task 10: the compiled decode scan now covers
    ComputationGraph (reference ComputationGraph.rnnTimeStep:1674)."""
    net = _cg_lstm_char_lm()
    rs = np.random.RandomState(3)
    prompt = rs.randint(0, 11, (2, 4))
    ref = sample_sequence(net, prompt, 10, temperature=0.0, one_hot=True,
                          vocab_size=11)
    net.rnn_clear_previous_state()
    got = generate(net, prompt, 10, temperature=0.0)  # encoding auto-detected
    np.testing.assert_array_equal(got, ref)


def test_cg_attention_greedy_matches_host_loop():
    net = _cg_attention_char_lm()
    rs = np.random.RandomState(4)
    prompt = rs.randint(0, 13, (3, 5))
    ref = sample_sequence(net, prompt, 12, temperature=0.0, one_hot=False)
    net.rnn_clear_previous_state()
    got = generate(net, prompt, 12, temperature=0.0)
    np.testing.assert_array_equal(got, ref)


def test_cg_multi_input_graph_rejected_with_guidance():
    from deeplearning4j_tpu.models.graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.models.vertices import MergeVertex

    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater("sgd", learning_rate=0.1).graph()
            .add_inputs("a", "b")
            .add_layer("da", DenseLayer(n_in=4, n_out=4), "a")
            .add_layer("db", DenseLayer(n_in=4, n_out=4), "b")
            .add_vertex("m", MergeVertex(), "da", "db")
            .add_layer("out", OutputLayer(n_in=8, n_out=2, loss="mcxent",
                                          activation="softmax"), "m")
            .set_outputs("out").build())
    net = ComputationGraph(conf).init()
    with pytest.raises(ValueError, match="single-input"):
        generate(net, np.zeros((1, 3), np.int64), 2)


def test_cg_collapse_column_embedding_greedy_matches_host_loop():
    """Regression: a default (collapse_column=True) EmbeddingLayer feeds
    per-token [B,1] ids that would collapse away the time axis; decode
    must expand them like rnn_time_step does."""
    from deeplearning4j_tpu.models.graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (
        EmbeddingLayer, GravesLSTM, RnnOutputLayer,
    )

    conf = (NeuralNetConfiguration.builder().seed(8)
            .updater("sgd", learning_rate=0.1).graph()
            .add_inputs("ids")
            .add_layer("emb", EmbeddingLayer(n_in=11, n_out=8), "ids")
            .add_layer("lstm", GravesLSTM(n_in=8, n_out=10), "emb")
            .add_layer("out", RnnOutputLayer(n_in=10, n_out=11,
                                             loss="mcxent",
                                             activation="softmax"), "lstm")
            .set_outputs("out").build())
    net = ComputationGraph(conf).init()
    rs = np.random.RandomState(9)
    prompt = rs.randint(0, 11, (2, 4))
    ref = sample_sequence(net, prompt, 6, temperature=0.0)
    net.rnn_clear_previous_state()
    got = generate(net, prompt, 6, temperature=0.0)
    np.testing.assert_array_equal(got, ref)


def test_cg_one_hot_vocab_inferred_from_input_consumer():
    """Asymmetric vocab: one-hot width must come from the INPUT consumer's
    n_in (30), not the output head's n_out (11)."""
    from deeplearning4j_tpu.models.graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import GravesLSTM, RnnOutputLayer

    conf = (NeuralNetConfiguration.builder().seed(10)
            .updater("sgd", learning_rate=0.1).graph()
            .add_inputs("in")
            .add_layer("lstm", GravesLSTM(n_in=30, n_out=10), "in")
            .add_layer("out", RnnOutputLayer(n_in=10, n_out=11,
                                             loss="mcxent",
                                             activation="softmax"), "lstm")
            .set_outputs("out").build())
    net = ComputationGraph(conf).init()
    rs = np.random.RandomState(10)
    prompt = rs.randint(0, 30, (2, 3))
    out = generate(net, prompt, 4, temperature=0.0)  # would crash at 11
    assert out.shape == (2, 4) and out.max() < 11


def test_mln_one_hot_vocab_inferred_from_first_layer():
    """Asymmetric vocab, sequential net: one-hot width = first layer's
    n_in (30), not the head's n_out (11) — same input-side rule as CG."""
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import GravesLSTM, RnnOutputLayer

    b = (NeuralNetConfiguration.builder().seed(12)
         .updater("sgd", learning_rate=0.1).list()
         .layer(GravesLSTM(n_in=30, n_out=10))
         .layer(RnnOutputLayer(n_in=10, n_out=11, loss="mcxent",
                               activation="softmax")))
    net = MultiLayerNetwork(b.build()).init()
    rs = np.random.RandomState(12)
    prompt = rs.randint(0, 30, (2, 3))
    out = generate(net, prompt, 4, temperature=0.0)
    assert out.shape == (2, 4) and out.max() < 11


def test_generate_identical_after_zip_round_trip(tmp_path):
    """Serialization composes with the compiled decode: save -> load ->
    generate must reproduce the original tokens exactly (config carries
    GQA/window/max_cache; params + updater state ride the zip)."""
    from deeplearning4j_tpu.models.serialization import load_model
    from deeplearning4j_tpu.models.zoo import transformer_char_lm

    net = transformer_char_lm(vocab_size=19, d_model=16, n_heads=4,
                              layers=2, n_kv_heads=2, window=16,
                              max_cache=32)
    rs = np.random.RandomState(5)
    prompt = rs.randint(0, 19, (2, 4))
    before = generate(net, prompt, 10, temperature=0.0)
    path = tmp_path / "lm.zip"
    net.save(str(path))
    loaded = load_model(str(path))
    after = generate(loaded, prompt, 10, temperature=0.0)
    np.testing.assert_array_equal(before, after)
