"""Mixed precision (bf16 compute / fp32 params) + Viterbi decoding."""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import (
    MultiLayerConfiguration, NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.utils import Viterbi, viterbi_decode


def build(compute_dtype=None, seed=5):
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater("adam", learning_rate=0.05).list()
         .layer(DenseLayer(n_in=4, n_out=32, activation="relu"))
         .layer(OutputLayer(n_in=32, n_out=2, loss="mcxent",
                            activation="softmax")))
    if compute_dtype:
        b = b.compute_dtype(compute_dtype)
    return MultiLayerNetwork(b.build()).init()


def task_data(n=64):
    rs = np.random.RandomState(0)
    x = rs.rand(n, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 2).astype(int)]
    return x, y


def test_bf16_trains_params_stay_fp32():
    net = build("bfloat16")
    x, y = task_data()
    for _ in range(60):
        net.fit(x, y)
    # master params remain fp32
    assert net.params["layer_0"]["W"].dtype == jnp.float32
    acc = (np.asarray(net.output(x)).argmax(-1) == y.argmax(-1)).mean()
    assert acc > 0.9, acc
    assert np.isfinite(net.score_value)


def test_bf16_close_to_fp32():
    x, y = task_data()
    a, b = build("bfloat16"), build(None)
    for _ in range(20):
        a.fit(x, y)
        b.fit(x, y)
    # same seed, same data: scores track within bf16 noise
    assert abs(a.score_value - b.score_value) < 0.05


def test_compute_dtype_serializes():
    conf = build("bfloat16").conf
    back = MultiLayerConfiguration.from_json(conf.to_json())
    assert back.compute_dtype == "bfloat16"


def test_compute_dtype_validation():
    with pytest.raises(ValueError, match="unsupported"):
        NeuralNetConfiguration.builder().list().compute_dtype("int8")


def build_graph(compute_dtype=None, seed=5):
    from deeplearning4j_tpu.models.graph import ComputationGraph

    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater("adam", learning_rate=0.05).graph()
         .add_inputs("in")
         .add_layer("d", DenseLayer(n_in=4, n_out=32, activation="relu"), "in")
         .add_layer("out", OutputLayer(n_in=32, n_out=2, loss="mcxent",
                                       activation="softmax"), "d")
         .set_outputs("out"))
    if compute_dtype:
        b = b.compute_dtype(compute_dtype)
    return ComputationGraph(b.build()).init()


def test_graph_bf16_trains_params_stay_fp32():
    net = build_graph("bfloat16")
    x, y = task_data()
    for _ in range(60):
        net.fit(x, y)
    assert net.params["d"]["W"].dtype == jnp.float32
    out = np.asarray(net.output(x))
    assert out.dtype == np.float32  # fp32 API boundary
    acc = (out.argmax(-1) == y.argmax(-1)).mean()
    assert acc > 0.9, acc
    assert np.isfinite(net.score_value)


def test_graph_bf16_close_to_fp32():
    x, y = task_data()
    a, b = build_graph("bfloat16"), build_graph(None)
    for _ in range(20):
        a.fit(x, y)
        b.fit(x, y)
    assert abs(a.score_value - b.score_value) < 0.05


def test_graph_compute_dtype_serializes():
    from deeplearning4j_tpu.models.graph import GraphConfiguration

    conf = build_graph("bfloat16").conf
    back = GraphConfiguration.from_json(conf.to_json())
    assert back.compute_dtype == "bfloat16"


def test_viterbi_decode_prefers_transitions():
    # emissions say state 1 at t=1 only weakly; strong self-transitions
    # keep the path in state 0
    em = np.log(np.array([[0.9, 0.1], [0.45, 0.55], [0.9, 0.1]], np.float32))
    tr = np.log(np.array([[0.95, 0.05], [0.05, 0.95]], np.float32))
    path, score = viterbi_decode(em, tr)
    assert path.tolist() == [0, 0, 0]
    assert np.isfinite(score)


def test_viterbi_facade_smooths_flicker():
    v = Viterbi([0, 1], meta_stability=0.95, p_correct=0.9)
    smoothed, _ = v.decode([0, 0, 1, 0, 0, 0])
    assert smoothed.tolist() == [0, 0, 0, 0, 0, 0]
    # a sustained switch survives smoothing
    smoothed2, _ = v.decode([0, 0, 1, 1, 1, 1])
    assert smoothed2.tolist()[-3:] == [1, 1, 1]
