"""Native C++ runtime core + DataVec bridge + dataset fetchers.

Mirrors the reference's test pattern for its native seam: the same
operation run through the accelerated path and the plain path must agree
exactly (CuDNNGradientChecks-style parity, here for host-side ETL)."""

import struct

import numpy as np
import pytest

from deeplearning4j_tpu import native
from deeplearning4j_tpu.datasets import (
    CifarDataSetIterator, CurvesDataSetIterator, DataSet, FileDataSetIterator,
    LFWDataSetIterator, ListDataSetIterator, NativeBatchDataSetIterator,
    export_datasets,
)
from deeplearning4j_tpu.datasets.datavec import (
    ALIGN_END, CollectionRecordReader, CollectionSequenceRecordReader,
    CSVRecordReader, CSVSequenceRecordReader, RecordReaderDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)


def test_native_available():
    # g++ is part of the supported toolchain; the native path must build here
    assert native.available()


def test_csv_native_python_parity():
    data = b"a,b,c\n1.5,2,3\n-4,5e-1,6\n7,8,9.25\n"
    m = native.csv_to_matrix(data, skip_lines=1)
    mp = native.csv_to_matrix(data, skip_lines=1, force_python=True)
    np.testing.assert_allclose(m, mp)
    assert m.shape == (3, 3) and m[1, 1] == pytest.approx(0.5)


def test_csv_nonnumeric_falls_back():
    data = b"1,2\n3,4\n"
    ok = native.csv_to_matrix(data)
    np.testing.assert_allclose(ok, [[1, 2], [3, 4]])
    with pytest.raises(ValueError):
        native.csv_to_matrix(b"1,x\n")


def test_idx_parsers_parity():
    imgs = struct.pack(">IIII", 0x803, 4, 3, 3) + bytes(range(36))
    labs = struct.pack(">II", 0x801, 4) + bytes([1, 0, 9, 5])
    np.testing.assert_allclose(native.parse_idx_images(imgs),
                               native.parse_idx_images(imgs, force_python=True))
    np.testing.assert_allclose(native.parse_idx_labels(labs),
                               native.parse_idx_labels(labs, force_python=True))
    assert native.parse_idx_labels(labs)[2, 9] == 1.0


def test_gather_rows():
    src = np.arange(50, dtype=np.float32).reshape(10, 5)
    idx = np.array([9, 0, 3, 3])
    np.testing.assert_allclose(native.gather_rows(src, idx), src[idx])
    with pytest.raises(IndexError):
        native.gather_rows(src, np.array([10]))


def test_csv_ragged_rows_rejected():
    # extra trailing field must not be silently dropped by the native path
    with pytest.raises(ValueError):
        native.csv_to_matrix(b"1,2\n3,4,5\n")


def test_native_batch_iterator_reshuffles_per_epoch():
    f = np.arange(32, dtype=np.float32).reshape(32, 1)
    l = np.zeros((32, 1), np.float32)
    it = NativeBatchDataSetIterator(DataSet(f, l), 32, seed=4)
    first = it.next().features[:, 0].copy()
    it.reset()
    second = it.next().features[:, 0].copy()
    assert sorted(first) == sorted(second)
    assert not np.array_equal(first, second)
    it.close()


def test_export_mask_roundtrip(tmp_path):
    # 5 examples batched by 4 -> final batch zero-padded with a labels mask
    rs = np.random.RandomState(3)
    ds = DataSet(rs.rand(5, 4).astype(np.float32),
                 np.eye(2, dtype=np.float32)[rs.randint(0, 2, 5)])
    export_datasets(ListDataSetIterator(ds, 4), tmp_path)
    batches = list(FileDataSetIterator(tmp_path))
    assert batches[1].labels_mask is not None
    np.testing.assert_allclose(batches[1].labels_mask, [1, 0, 0, 0])


def test_batcher_covers_every_row_once():
    f = np.arange(37, dtype=np.float32).reshape(37, 1)
    b = native.Batcher(f, None, 8, shuffle=True, seed=5)
    seen = []
    while True:
        out = b.next()
        if out is None:
            break
        feat, lab, nv = out
        assert lab is None
        seen.extend(feat[:nv, 0].tolist())
    b.close()
    assert sorted(seen) == list(range(37))


def test_batcher_native_python_identical_order():
    f = np.random.RandomState(0).rand(41, 3).astype(np.float32)
    l = np.eye(4, dtype=np.float32)[np.random.RandomState(1).randint(0, 4, 41)]
    bn = native.Batcher(f, l, 8, seed=9)
    bp = native.Batcher(f, l, 8, seed=9, force_python=True)
    while True:
        a, b = bn.next(), bp.next()
        if a is None:
            assert b is None
            break
        np.testing.assert_allclose(a[0], b[0])
        np.testing.assert_allclose(a[1], b[1])
        assert a[2] == b[2]
    bn.close(), bp.close()


def test_native_batch_iterator_trains():
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    rs = np.random.RandomState(0)
    x = rs.rand(64, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 64)]
    it = NativeBatchDataSetIterator(DataSet(x, y), 16, seed=3)
    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater("sgd", learning_rate=0.1).list()
            .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(it, epochs=2)
    assert np.isfinite(net.score_value)
    it.close()


def test_dataset_export_roundtrip(tmp_path):
    rs = np.random.RandomState(2)
    ds = DataSet(rs.rand(20, 6).astype(np.float32),
                 rs.rand(20, 2).astype(np.float32))
    src = ListDataSetIterator(ds, 8)
    paths = export_datasets(src, tmp_path)
    assert len(paths) == 3
    back = FileDataSetIterator(tmp_path)
    merged = DataSet.merge(list(back))
    # final batch was zero-padded to 8 on export
    np.testing.assert_allclose(merged.features[:20], ds.features, atol=1e-6)
    assert back.batch() == 8


def test_csv_record_reader_iterator(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("f1,f2,label\n0.1,0.2,0\n0.3,0.4,2\n0.5,0.6,1\n")
    reader = CSVRecordReader(skip_lines=1).initialize(p)
    it = RecordReaderDataSetIterator(reader, batch_size=2, label_index=2,
                                     num_classes=3)
    b1 = it.next()
    assert b1.features.shape == (2, 2) and b1.labels.shape == (2, 3)
    assert b1.labels[1, 2] == 1.0
    b2 = it.next()
    assert len(b2) == 1 and not it.has_next()
    it.reset()
    assert it.has_next()


def test_record_reader_regression():
    reader = CollectionRecordReader([[1, 2, 10, 20], [3, 4, 30, 40]])
    it = RecordReaderDataSetIterator(reader, 2, label_index=2, regression=True,
                                     label_index_to=3)
    ds = it.next()
    np.testing.assert_allclose(ds.features, [[1, 2], [3, 4]])
    np.testing.assert_allclose(ds.labels, [[10, 20], [30, 40]])


def test_sequence_reader_masks_and_alignment(tmp_path):
    feats = CollectionSequenceRecordReader(
        [[[1, 2], [3, 4], [5, 6]], [[7, 8]]])
    labels = CollectionSequenceRecordReader([[[0], [1], [0]], [[1]]])
    it = SequenceRecordReaderDataSetIterator(
        feats, labels, batch_size=2, num_classes=2, alignment=ALIGN_END)
    ds = it.next()
    assert ds.features.shape == (2, 3, 2) and ds.labels.shape == (2, 3, 2)
    # second sequence (length 1) is aligned to the END of the time axis
    np.testing.assert_allclose(ds.features_mask, [[1, 1, 1], [0, 0, 1]])
    np.testing.assert_allclose(ds.features[1, 2], [7, 8])
    assert ds.labels[1, 2, 1] == 1.0


def test_csv_sequence_reader(tmp_path):
    for i, rows in enumerate(["1,2\n3,4\n", "5,6\n"]):
        (tmp_path / f"seq_{i}.csv").write_text(rows)
    reader = CSVSequenceRecordReader().initialize(
        sorted(tmp_path.glob("seq_*.csv")))
    it = SequenceRecordReaderDataSetIterator(reader, batch_size=2,
                                             label_index=1, num_classes=7)
    ds = it.next()
    assert ds.features.shape == (2, 2, 1)
    assert ds.labels[0, 1, 4] == 1.0  # label value 4 one-hot


def test_cifar_curves_lfw_iterators():
    c = CifarDataSetIterator(batch_size=16, num_examples=32)
    ds = c.next()
    assert ds.features.shape == (16, 3072) and ds.labels.shape == (16, 10)
    assert c.is_synthetic
    cv = CurvesDataSetIterator(batch_size=8, num_examples=16)
    d2 = cv.next()
    np.testing.assert_allclose(d2.features, d2.labels)
    lfw = LFWDataSetIterator(batch_size=8, num_examples=16, num_classes=5)
    d3 = lfw.next()
    assert d3.features.shape == (8, 1600) and d3.labels.shape == (8, 5)


def test_cifar_real_binary_format(tmp_path):
    # write two records in the authentic data_batch format and parse them
    rec = bytes([3]) + bytes(range(256)) * 12  # label 3 + 3072 bytes
    rec2 = bytes([7]) + bytes([255] * 3072)
    (tmp_path / "data_batch_1.bin").write_bytes(rec + rec2)
    it = CifarDataSetIterator(batch_size=2, data_dir=str(tmp_path))
    assert not it.is_synthetic
    ds = it.next()
    assert ds.labels[0, 3] == 1.0 and ds.labels[1, 7] == 1.0
    assert ds.features[1].max() == pytest.approx(1.0)
