"""Distributed early stopping, sharded evaluation, conv-activation rendering."""

import numpy as np

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.datasets import DataSet, ListDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator, DistributedEarlyStoppingTrainer,
    EarlyStoppingConfiguration, InMemoryModelSaver,
    MaxEpochsTerminationCondition, TerminationReason,
)
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer, DenseLayer, OutputLayer,
)
from deeplearning4j_tpu.parallel import DistributedNetwork, SyncTrainingMaster
from deeplearning4j_tpu.ui import (
    ConvolutionalIterationListener, activation_grid, write_png,
)


def small_net():
    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater("adam", learning_rate=0.05).list()
            .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=2)).build())
    return MultiLayerNetwork(conf).init()


def task(n=64):
    rs = np.random.RandomState(0)
    x = rs.rand(n, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 2).astype(int)]
    return DataSet(x, y)


def test_distributed_early_stopping():
    train = ListDataSetIterator(task(64), 16)
    val = ListDataSetIterator(task(32), 16)
    dist = DistributedNetwork(small_net(),
                              SyncTrainingMaster(mesh=backend.default_mesh()))
    cfg = (EarlyStoppingConfiguration.Builder()
           .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
           .score_calculator(DataSetLossCalculator(val))
           .model_saver(InMemoryModelSaver())
           .build())
    result = DistributedEarlyStoppingTrainer(cfg, dist, train).fit()
    assert result.termination_reason == TerminationReason.EPOCH_TERMINATION_CONDITION
    assert result.total_epochs == 3
    assert result.best_model is not None


def test_sharded_evaluation_matches_serial():
    ds = task(50)  # deliberately not divisible by 8
    net = small_net()
    net.fit(ds.features, ds.labels)
    dist = DistributedNetwork(net, SyncTrainingMaster(mesh=backend.default_mesh()))
    ev_sharded = dist.evaluate(ListDataSetIterator(ds, 25, drop_last=True))
    # serial oracle
    from deeplearning4j_tpu.evaluation import Evaluation

    ev = Evaluation()
    for b in ListDataSetIterator(ds, 25, drop_last=True):
        ev.eval(b.labels, np.asarray(net.output(b.features)))
    assert ev_sharded.accuracy() == ev.accuracy()


def test_graph_net_evaluate_falls_back_to_serial():
    # ComputationGraph has no _output_fn; evaluate must not crash
    from deeplearning4j_tpu.models.graph import ComputationGraph

    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater("sgd", learning_rate=0.1).graph()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=4, n_out=8), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=2), "d")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    dist = DistributedNetwork(net, SyncTrainingMaster(mesh=backend.default_mesh()))
    ds = task(16)
    ev = dist.evaluate(ListDataSetIterator(ds, 8))
    assert 0.0 <= ev.accuracy() <= 1.0


def test_compute_dtype_rejected_from_json():
    import pytest

    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

    conf = (NeuralNetConfiguration.builder().list()
            .layer(DenseLayer(n_in=2, n_out=2))
            .layer(OutputLayer(n_in=2, n_out=2)).build())
    d = conf.to_dict()
    d["compute_dtype"] = "int8"
    with pytest.raises(ValueError, match="compute_dtype"):
        MultiLayerConfiguration.from_dict(d)


def test_conv_listener_skips_non_conv_layer_index(tmp_path):
    net = small_net()
    listener = ConvolutionalIterationListener(
        np.zeros((1, 4), np.float32), tmp_path, frequency=1, layer_index=0)
    net.set_listeners(listener)
    ds = task(8)
    net.fit(ds.features, ds.labels)  # dense activation: skipped, no crash
    assert listener.rendered == []


def test_activation_grid_channels_first():
    a = np.random.RandomState(0).rand(5, 6, 6).astype(np.float32)  # [C,H,W]
    grid = activation_grid(a, channels_last=False)
    assert grid.shape == (2 * 7 - 1, 3 * 7 - 1)


def test_activation_grid_and_png(tmp_path):
    a = np.random.RandomState(0).rand(6, 6, 5).astype(np.float32)
    grid = activation_grid(a)
    assert grid.shape == (2 * 7 - 1, 3 * 7 - 1)  # 5 channels -> 2x3 grid
    p = tmp_path / "g.png"
    write_png(p, grid)
    data = p.read_bytes()
    assert data[:8] == b"\x89PNG\r\n\x1a\n" and b"IEND" in data


def test_convolutional_listener_renders(tmp_path):
    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater("sgd", learning_rate=0.05).list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    activation="relu"))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.convolutional_flat(8, 8, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(0)
    x = rs.rand(8, 64).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 8)]
    listener = ConvolutionalIterationListener(x, tmp_path, frequency=1)
    net.set_listeners(listener)
    net.fit(x, y)
    assert listener.rendered and listener.rendered[0].exists()
