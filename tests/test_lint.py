"""Tier-1 gate for the dl4jlint static-analysis suite.

Covers, per the PR-9 acceptance criteria:

- every rule has a true-positive fixture (the violation is found) and a
  clean-negative fixture (no findings) under ``tests/lint_fixtures/``;
- suppression comments silence findings (line + next-line + file);
- the ratcheting baseline: new findings fail, ``--update-baseline``
  bootstraps, refuses to grow, and shrinks when debt is paid;
- the full-repo run exits 0 against the committed baseline, without
  importing jax, inside the time budget;
- a synthetic violation introduced in a fixture COPY of a real repo
  file turns the exit code to 1.

The linter is stdlib-only and loaded as a package from the repo root
(``scripts`` is importable); everything here runs in-process except the
no-jax check, which needs a subprocess with a poisoned ``jax`` module.
"""

import json
import os
import shutil
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from scripts.dl4jlint import baseline as baseline_mod  # noqa: E402
from scripts.dl4jlint import cli  # noqa: E402
from scripts.dl4jlint.rules import ALL_RULES, get_rules  # noqa: E402


def lint(files, rules=()):
    """Findings (post-suppression) for explicit fixture files."""
    paths = [os.path.join(FIXTURES, f) for f in files]
    return cli.run(paths, rules).findings


def fixture_pair(rule, bad, ok):
    bad_findings = lint([bad], (rule,))
    ok_findings = lint([ok], (rule,))
    assert bad_findings, f"{rule}: no findings in {bad}"
    assert all(f.rule == rule for f in bad_findings)
    assert ok_findings == [], (
        f"{rule}: false positives in {ok}: "
        + "; ".join(f.format() for f in ok_findings))
    return bad_findings


# ------------------------------------------------------------------- rules
def test_host_sync_rule():
    found = fixture_pair("host-sync-in-hot-path",
                         "host_sync_bad.py", "host_sync_ok.py")
    lines = {f.line for f in found}
    # .item() in the decorated jit, float() in the wrapped jit, the
    # per-step np.asarray + block_until_ready in the hot loop, and the
    # per-tensor readback in a loop driving a DECORATED jit helper (the
    # StatsListener sync-storm shape — decorated names are jitted
    # symbols too)
    assert len(lines) >= 5
    assert any("item" in f.message for f in found)
    assert any("block_until_ready" in f.message for f in found)
    assert any(f.symbol == "fit_loop" for f in found)
    assert any(f.symbol == "per_tensor_stats" for f in found)


def test_recompile_rule():
    found = fixture_pair("recompile-hazard",
                         "recompile_bad.py", "recompile_ok.py")
    msgs = " | ".join(f.message for f in found)
    assert "invoked immediately" in msgs
    assert "inside a loop" in msgs
    assert "static_argnums" in msgs


def test_lock_discipline_rule():
    found = fixture_pair("lock-discipline", "lock_bad.py", "lock_ok.py")
    symbols = {f.symbol for f in found}
    assert "Registry.lookup._active" in symbols     # unlocked dict read
    assert "Registry.evict._active" in symbols      # unlocked .pop()
    assert "Registry.size._count" in symbols        # unlocked scalar read


def test_rng_reuse_rule():
    found = fixture_pair("rng-key-reuse", "rng_bad.py", "rng_ok.py")
    symbols = {f.symbol for f in found}
    assert "double_draw" in symbols
    assert "loop_carried" in symbols                # caught on 2nd pass


def test_dtype_widening_rule():
    found = fixture_pair("implicit-dtype-widening",
                         "dtype_widening_bad.py", "dtype_widening_ok.py")
    msgs = " | ".join(f.message for f in found)
    assert "inside a jit-traced function" in msgs
    assert "host-numpy np.mean()" in msgs
    assert "dtype=float64" in msgs
    symbols = {f.symbol for f in found}
    assert "decorated_step" in symbols      # astype/dtype kw/np.mean
    assert "wrapped" in symbols             # np.float64() via jax.jit(wrapped)
    assert "build_reference" in symbols     # corpus-wide jnp dtype check


def test_thread_hygiene_rule():
    found = fixture_pair("thread-hygiene", "thread_bad.py", "thread_ok.py")
    msgs = " | ".join(f.message for f in found)
    assert "non-daemon thread is never joined" in msgs
    assert "daemon thread bound to self._thread" in msgs


def test_metrics_docs_rule():
    found = fixture_pair("metrics-docs",
                         "metrics_docs_bad.py", "metrics_docs_ok.py")
    assert any("help text" in f.message for f in found)
    assert all(f.symbol == "dl4j_fixture_only_total" for f in found)


def test_metrics_docs_help_drift_rule():
    """One dl4j_* family registered in two modules with diverging help
    text is flagged (federated HELP lines need one agreed string);
    whitespace-only rewraps inside one module are not drift."""
    bad = lint(["metrics_docs_drift_bad.py", "metrics_docs_drift_bad2.py"],
               ("metrics-docs",))
    drift = [f for f in bad if "diverges" in f.message]
    assert drift, "no drift finding for diverging help across modules"
    assert all(f.symbol == "dl4j_fixture_drift_total" for f in drift)
    # each drift-bad file alone has ONE help string -> no drift finding
    solo = lint(["metrics_docs_drift_bad.py"], ("metrics-docs",))
    assert not any("diverges" in f.message for f in solo)
    ok = lint(["metrics_docs_drift_ok.py"], ("metrics-docs",))
    assert not any("diverges" in f.message for f in ok), (
        "whitespace rewrap flagged as drift: "
        + "; ".join(f.format() for f in ok))


def test_rule_registry_complete():
    names = {r.name for r in ALL_RULES}
    assert names == {"host-sync-in-hot-path", "recompile-hazard",
                     "lock-discipline", "rng-key-reuse", "thread-hygiene",
                     "implicit-dtype-widening", "metrics-docs"}
    with pytest.raises(KeyError):
        get_rules(["no-such-rule"])


# ------------------------------------------------------------ suppressions
def test_suppressions(tmp_path):
    src = (tmp_path / "s.py")
    src.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    a = x.item()  # dl4jlint: disable=host-sync-in-hot-path -- why\n"
        "    # dl4jlint: disable-next-line=host-sync-in-hot-path -- why\n"
        "    b = x.item()\n"
        "    return a + b\n")
    res = cli.run([str(src)], ("host-sync-in-hot-path",))
    assert res.findings == [] and res.suppressed == 2
    src.write_text(
        "# dl4jlint: disable-file=all -- fixture\n"
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.item()\n")
    res = cli.run([str(src)], ("host-sync-in-hot-path",))
    assert res.findings == [] and res.suppressed == 1


# ---------------------------------------------------------------- baseline
def _violation(n=1):
    body = "import jax\n"
    for i in range(n):
        body += f"def use{i}(x):\n    return jax.jit(lambda a: a)(x)\n"
    return body


def test_baseline_ratchet(tmp_path):
    corpus = tmp_path / "corpus"
    corpus.mkdir()
    mod = corpus / "m.py"
    base = tmp_path / "baseline.json"
    args = [str(corpus), "--baseline", str(base),
            "--rules", "recompile-hazard"]

    mod.write_text(_violation(1))
    assert cli.main(args) == 1                      # no baseline yet: new
    assert cli.main(args + ["--update-baseline"]) == 0   # bootstrap
    assert cli.main(args) == 0                      # debt accepted

    mod.write_text(_violation(2))
    assert cli.main(args) == 1                      # NEW finding fails
    # the ratchet refuses to absorb growth
    assert cli.main(args + ["--update-baseline"]) == 1
    doc = json.loads(base.read_text())
    assert sum(e["count"] for e in doc["entries"]) == 1

    mod.write_text("X = 1\n")                       # debt paid off
    assert cli.main(args) == 0                      # stale entries pass...
    assert cli.main(args + ["--update-baseline"]) == 0
    doc = json.loads(base.read_text())
    assert doc["entries"] == []                     # ...and ratchet DOWN


def test_baseline_keys_survive_line_drift(tmp_path):
    corpus = tmp_path / "corpus"
    corpus.mkdir()
    mod = corpus / "m.py"
    base = tmp_path / "baseline.json"
    args = [str(corpus), "--baseline", str(base),
            "--rules", "recompile-hazard"]
    mod.write_text(_violation(1))
    assert cli.main(args + ["--update-baseline"]) == 0
    # unrelated edits above the finding shift its line, not its key
    mod.write_text("# comment\n# comment\n\n" + _violation(1))
    assert cli.main(args) == 0


def test_baseline_why_preserved(tmp_path):
    corpus = tmp_path / "corpus"
    corpus.mkdir()
    (corpus / "m.py").write_text(_violation(1))
    res = cli.run([str(corpus)], ("recompile-hazard",))
    doc = baseline_mod.update(res.findings, None)
    doc["entries"][0]["why"] = "cold path: fixture"
    doc2 = baseline_mod.update(res.findings, doc)
    assert doc2["entries"][0]["why"] == "cold path: fixture"


def test_committed_baseline_has_justifications():
    """Every accepted finding in the committed baseline carries a why —
    the satellite-task contract: no silent debt."""
    path = os.path.join(REPO, "scripts", "dl4jlint", "baseline.json")
    doc = baseline_mod.load(path)
    missing = [e for e in doc["entries"] if not e.get("why")]
    assert missing == [], f"baseline entries without why: {missing}"


# ------------------------------------------------------------ repo contract
def test_full_repo_clean_fast_and_jaxless(tmp_path):
    """`python -m scripts.dl4jlint` exits 0 against the committed
    baseline, never imports jax (a poisoned jax module would crash it),
    and stays inside the time budget (<5s unloaded; asserted with
    headroom for a loaded CI box)."""
    poison = tmp_path / "poison"
    poison.mkdir()
    (poison / "jax.py").write_text(
        "raise ImportError('dl4jlint must not import jax')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = f"{poison}{os.pathsep}{REPO}"
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "scripts.dl4jlint"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=120)
    dt = time.perf_counter() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "must not import jax" not in proc.stdout + proc.stderr
    assert dt < 20.0, f"lint run took {dt:.1f}s"


def test_synthetic_violation_in_fixture_copy_fails(tmp_path):
    """Copy a REAL repo file, introduce one violation, and the driver
    (same rules, same committed baseline) exits 1 on the copy."""
    victim = os.path.join(REPO, "deeplearning4j_tpu", "serving",
                          "batcher.py")
    copy = tmp_path / "batcher_copy.py"
    shutil.copy(victim, copy)
    assert cli.main([str(copy)]) == 0       # the copy starts clean
    with open(copy, "a") as f:
        f.write("\nimport jax\n"
                "def _synthetic(x):\n"
                "    return jax.jit(lambda a: a)(x)\n")
    assert cli.main([str(copy)]) == 1


def test_ci_checks_lists_all_gates():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "ci_checks.py"),
         "--list"], capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0
    assert "dl4jlint" in proc.stdout
    assert "check_bench_regression" in proc.stdout
    assert "check_metrics_docs" in proc.stdout
