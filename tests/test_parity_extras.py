"""Small parity holes closed in round 3: DropConnect, phase-timed
distributed stats, explicit-distributed-init validation.

Reference: ``util/Dropout.java:24-36`` (applyDropConnect),
``spark/.../stats/CommonSparkTrainingStats.java`` (phase-timed fit).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.layers.base import layer_from_dict
from deeplearning4j_tpu.parallel import DistributedNetwork, SyncTrainingMaster


# ------------------------------------------------------------- DropConnect

def test_drop_connect_masks_weights_at_train():
    import jax

    layer = DenseLayer(n_in=8, n_out=8, dropout=0.5, drop_connect=True,
                       activation="identity", name="d")
    params = {"W": jnp.ones((8, 8)), "b": jnp.zeros((8,))}
    x = jnp.ones((4, 8))
    y_train, _ = layer.apply(params, {}, x, train=True,
                             rng=jax.random.key(0))
    y_test, _ = layer.apply(params, {}, x, train=False, rng=None)
    # inference untouched
    np.testing.assert_allclose(np.asarray(y_test), 8.0)
    # training output differs (weights masked) but is unbiased in
    # expectation thanks to inverted scaling
    assert not np.allclose(np.asarray(y_train), 8.0)
    assert abs(float(jnp.mean(y_train)) - 8.0) < 2.0


def test_drop_connect_disables_input_dropout():
    import jax

    layer = DenseLayer(n_in=4, n_out=4, dropout=0.5, drop_connect=True,
                       activation="identity", name="d")
    x = jnp.ones((2, 4))
    # maybe_dropout must be a no-op when drop_connect repurposes dropOut
    out = layer.maybe_dropout(x, train=True, rng=jax.random.key(1))
    np.testing.assert_allclose(np.asarray(out), 1.0)


def test_drop_connect_trains_and_serializes():
    rs = np.random.RandomState(0)
    conf = (NeuralNetConfiguration.builder().seed(2)
            .updater("sgd", learning_rate=0.1).list()
            .layer(DenseLayer(n_in=8, n_out=16, dropout=0.3,
                              drop_connect=True, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=3)).build())
    net = MultiLayerNetwork(conf).init()
    x = rs.rand(32, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 32)]
    for _ in range(5):
        net.fit(x, y)
    assert np.isfinite(net.score_value)
    back = layer_from_dict(conf.layers[0].to_dict())
    assert back.drop_connect is True


# ---------------------------------------------------- phase-timed stats

def test_sync_master_phase_stats():
    rs = np.random.RandomState(1)
    x = rs.rand(64, 8).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 64)]
    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(3)
         .updater("sgd", learning_rate=0.1).list()
         .layer(DenseLayer(n_in=8, n_out=16))
         .layer(OutputLayer(n_in=16, n_out=4)).build())).init()
    master = SyncTrainingMaster(mesh=backend.default_mesh(),
                                collect_stats=True)
    DistributedNetwork(net, master).fit(
        ListDataSetIterator(DataSet(x, y), 16))
    stats = master.training_stats()
    assert stats["steps"] == 4
    for phase in ("fetch", "place", "dispatch", "device_sync"):
        assert phase in stats["phases"], stats["phases"].keys()
        p = stats["phases"][phase]
        assert p["count"] >= 4
        assert p["total_ms"] >= p["mean_ms"] >= 0.0
        assert p["max_ms"] >= p["min_ms"]


# ------------------------------------- native DP window path semantics

def _dp_net(seed=11):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(seed)
         .updater("sgd", learning_rate=0.1).list()
         .layer(DenseLayer(n_in=6, n_out=12))
         .layer(OutputLayer(n_in=12, n_out=3)).build())).init()


def _dp_data(rs, n):
    x = rs.rand(n, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, n)]
    return DataSet(x, y)


def test_native_dp_iteration_count_matches_generic():
    """Ragged tail: the native slab path must advance net.iteration exactly
    like the generic window path (truncated tail window, not F*K)."""
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper

    rs = np.random.RandomState(3)
    data = _dp_data(rs, 80)  # B=8 -> 10 batches; K=2,F=2 -> 2 full + tail
    mesh = backend.default_mesh(n_devices=2, data=2, model=1)

    net_a = _dp_net()
    ParallelWrapper(net_a, workers=2, averaging_frequency=2,
                    mesh=mesh).fit(ListDataSetIterator(data, 8))
    # generic path forced by masks: pad_batch would mask, so use a masked
    # clone of the same data to route around the native fast path
    masked = DataSet(data.features, data.labels,
                     None, np.ones((80,), np.float32))
    net_b = _dp_net()
    ParallelWrapper(net_b, workers=2, averaging_frequency=2,
                    mesh=mesh).fit(ListDataSetIterator(masked, 8))
    assert net_a.iteration == net_b.iteration == 5


def test_native_dp_honors_drop_last():
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper

    rs = np.random.RandomState(4)
    data = _dp_data(rs, 70)  # B=8 -> 8 full batches + ragged 6 dropped
    mesh = backend.default_mesh(n_devices=2, data=2, model=1)
    net = _dp_net()
    ParallelWrapper(net, workers=2, averaging_frequency=2, mesh=mesh).fit(
        ListDataSetIterator(data, 8, drop_last=True))
    # 8 batches -> 2 windows of K*F=4 -> it += 2 each
    assert net.iteration == 4
    assert np.isfinite(net.score_value)


# ------------------------------------------- explicit distributed init

def test_bootstrap_incomplete_triple_raises(monkeypatch):
    from deeplearning4j_tpu.provision.cluster import bootstrap_distributed

    for var in ("DL4J_TPU_COORDINATOR", "DL4J_TPU_NUM_PROCS",
                "DL4J_TPU_PROC_ID"):
        monkeypatch.delenv(var, raising=False)
    with pytest.raises(ValueError, match="missing.*num_processes"):
        bootstrap_distributed(coordinator="10.0.0.1:1234")
    monkeypatch.setenv("DL4J_TPU_PROC_ID", "0")
    with pytest.raises(ValueError, match="coordinator"):
        bootstrap_distributed()


def test_bootstrap_single_process_noop(monkeypatch):
    from deeplearning4j_tpu.provision.cluster import bootstrap_distributed

    for var in ("DL4J_TPU_COORDINATOR", "DL4J_TPU_NUM_PROCS",
                "DL4J_TPU_PROC_ID", "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(var, raising=False)
    out = bootstrap_distributed()
    assert out == {"distributed": False, "processes": 1, "process_id": 0}
