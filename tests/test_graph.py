"""ComputationGraph tests (reference TestComputationGraphNetwork /
TestCompGraphCNN / GradientCheckTestsComputationGraph)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, GravesLSTM, OutputLayer, RnnOutputLayer
from deeplearning4j_tpu.models.graph import ComputationGraph, GraphConfiguration
from deeplearning4j_tpu.models.vertices import (
    ElementWiseVertex,
    LastTimeStepVertex,
    MergeVertex,
    SubsetVertex,
)


def simple_graph(seed=1):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater("sgd", learning_rate=0.5)
        .graph()
        .add_inputs("in")
        .add_layer("d0", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
        .add_layer("d1", DenseLayer(n_in=4, n_out=8, activation="relu"), "in")
        .add_vertex("merge", MergeVertex(), "d0", "d1")
        .add_layer("out", OutputLayer(n_in=16, n_out=3, loss="mcxent",
                                      activation="softmax"), "merge")
        .set_outputs("out")
        .build()
    )
    return ComputationGraph(conf).init()


def test_topological_order_and_forward():
    net = simple_graph()
    x = np.random.RandomState(0).randn(5, 4).astype(np.float32)
    out = np.asarray(net.output(x))
    assert out.shape == (5, 3)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)


def test_graph_fit_reduces_score():
    net = simple_graph()
    rs = np.random.RandomState(0)
    x = rs.randn(16, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 16)]
    s0 = net.score(x, y)
    for _ in range(60):
        net.fit(x, y)
    assert net.score(x, y) < s0 * 0.7


def test_graph_json_roundtrip():
    net = simple_graph()
    js = net.conf.to_json()
    conf2 = GraphConfiguration.from_json(js)
    assert conf2 == net.conf
    net2 = ComputationGraph(conf2).init()
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)), np.asarray(net2.output(x)))


def test_graph_save_restore(tmp_path):
    net = simple_graph()
    rs = np.random.RandomState(0)
    x = rs.randn(8, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 8)]
    net.fit(x, y)
    p = tmp_path / "graph.zip"
    net.save(p)
    restored = ComputationGraph.load(p)
    np.testing.assert_allclose(
        np.asarray(net.output(x)), np.asarray(restored.output(x)), rtol=1e-6
    )


def test_elementwise_residual_gradients():
    """Residual-style add vertex gradient-checks through both branches
    (reference GradientCheckTestsComputationGraph elementwise tests)."""
    conf = (
        NeuralNetConfiguration.builder()
        .graph()
        .add_inputs("in")
        .add_layer("d0", DenseLayer(n_in=4, n_out=4, activation="tanh"), "in")
        .add_layer("d1", DenseLayer(n_in=4, n_out=4, activation="tanh"), "d0")
        .add_vertex("add", ElementWiseVertex(op="add"), "d0", "d1")
        .add_layer("out", OutputLayer(n_in=4, n_out=2, loss="mcxent",
                                      activation="softmax"), "add")
        .set_outputs("out")
        .build()
    )
    net = ComputationGraph(conf).init(dtype=jnp.float64)
    rs = np.random.RandomState(3)
    x = rs.randn(6, 4)
    y = np.eye(2)[rs.randint(0, 2, 6)]
    assert check_gradients(net, x, y)


def test_multi_output_graph():
    conf = (
        NeuralNetConfiguration.builder()
        .updater("sgd", learning_rate=0.1)
        .graph()
        .add_inputs("in")
        .add_layer("trunk", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
        .add_layer("out_a", OutputLayer(n_in=8, n_out=2, loss="mcxent",
                                        activation="softmax"), "trunk")
        .add_layer("out_b", OutputLayer(n_in=8, n_out=1, loss="mse",
                                        activation="identity"), "trunk")
        .set_outputs("out_a", "out_b")
        .build()
    )
    net = ComputationGraph(conf).init()
    rs = np.random.RandomState(0)
    x = rs.randn(10, 4).astype(np.float32)
    ya = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 10)]
    yb = rs.randn(10, 1).astype(np.float32)
    s0 = None
    for _ in range(30):
        net.fit(x, {"out_a": ya, "out_b": yb})
        if s0 is None:
            s0 = net.score_value
    assert net.score_value < s0
    outs = net.output(x)
    assert len(outs) == 2 and outs[0].shape == (10, 2) and outs[1].shape == (10, 1)


def test_last_time_step_vertex():
    conf = (
        NeuralNetConfiguration.builder()
        .graph()
        .add_inputs("in")
        .add_layer("lstm", GravesLSTM(n_in=3, n_out=5), "in")
        .add_vertex("last", LastTimeStepVertex(), "lstm")
        .add_layer("out", OutputLayer(n_in=5, n_out=2, loss="mcxent",
                                      activation="softmax"), "last")
        .set_outputs("out")
        .build()
    )
    net = ComputationGraph(conf).init()
    x = np.random.RandomState(0).randn(4, 7, 3).astype(np.float32)
    out = np.asarray(net.output(x))
    assert out.shape == (4, 2)


def test_subset_vertex():
    conf = (
        NeuralNetConfiguration.builder()
        .graph()
        .add_inputs("in")
        .add_layer("d", DenseLayer(n_in=4, n_out=10, activation="tanh"), "in")
        .add_vertex("sub", SubsetVertex(index_from=2, index_to=5), "d")
        .add_layer("out", OutputLayer(n_in=4, n_out=2, loss="mcxent",
                                      activation="softmax"), "sub")
        .set_outputs("out")
        .build()
    )
    net = ComputationGraph(conf).init()
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    assert np.asarray(net.output(x)).shape == (3, 2)


def test_cycle_detection():
    from deeplearning4j_tpu.models.graph import GraphNode
    from deeplearning4j_tpu.nn.conf import UpdaterConfig

    conf = GraphConfiguration(
        inputs=("in",),
        outputs=("a",),
        nodes=(
            GraphNode("a", ("b",), layer=DenseLayer(n_in=2, n_out=2, name="a")),
            GraphNode("b", ("a",), layer=DenseLayer(n_in=2, n_out=2, name="b")),
        ),
        updater=UpdaterConfig(),
    )
    with pytest.raises(ValueError, match="cycle"):
        conf.topological_order()


def test_resnet_tiny_builds_and_trains():
    """A 2-stage tiny ResNet via the zoo builder compiles and trains."""
    from deeplearning4j_tpu.models.zoo import resnet50

    net = resnet50(height=16, width=16, channels=3, n_classes=4,
                   blocks=(1, 1), stem_stride=1, init_channels=8,
                   updater="sgd", lr=0.01)
    rs = np.random.RandomState(0)
    x = rs.randn(2, 16, 16, 3).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 2)]
    net.fit(x, y)
    assert np.isfinite(net.score_value)
    out = np.asarray(net.output(x))
    assert out.shape == (2, 4)
