"""End-to-end slice (SURVEY.md §7 stage 3): MNIST iterator -> LeNet via the
DSL -> jitted training -> Evaluation accuracy -> checkpoint/restore ->
PerformanceListener timings.  Exercises L0-L3 + eval + serde in one path."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import MnistDataSetIterator
from deeplearning4j_tpu.evaluation import Evaluation
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.models.zoo import lenet
from deeplearning4j_tpu.optimize.listeners import PerformanceListener, ScoreIterationListener


def test_lenet_mnist_end_to_end(tmp_path):
    train_iter = MnistDataSetIterator(batch_size=64, num_examples=1024, train=True)
    test_iter = MnistDataSetIterator(batch_size=64, num_examples=256, train=False)

    net = lenet(updater="adam", lr=1e-3)
    perf = PerformanceListener(frequency=10)
    perf.set_batch_size(64)
    net.set_listeners(ScoreIterationListener(10), perf)

    net.fit(train_iter, epochs=3)
    assert np.isfinite(net.score_value)
    assert perf.last_iteration_ms is not None

    ev = Evaluation(10)
    for ds in test_iter:
        out = np.asarray(net.output(ds.features))
        ev.eval(ds.labels, out)
    acc = ev.accuracy()
    # synthetic digits are near-separable; anything < 0.85 means training broke
    assert acc > 0.85, f"accuracy {acc}\n{ev.stats()}"

    # checkpoint -> restore -> same predictions
    p = tmp_path / "lenet.zip"
    net.save(p)
    restored = MultiLayerNetwork.load(p)
    ds = next(iter(MnistDataSetIterator(batch_size=32, num_examples=32)))
    np.testing.assert_allclose(
        np.asarray(net.output(ds.features)),
        np.asarray(restored.output(ds.features)),
        rtol=1e-5,
    )


def test_lenet_mnist_distributed_parity():
    """Sync-DP LeNet over the 8-device mesh must match local training on
    the same model/data/optimizer (the CuDNNGradientChecks pattern applied
    to the mesh path: accelerated-vs-plain, equivalent results).

    Parity — not an absolute accuracy bar — is the contract: 2 epochs over
    512 synthetic digits lands wherever it lands (~0.695 today), and the
    old fixed 0.7 floor merely tracked that noise while the distributed
    path was in fact bit-identical to local."""
    from deeplearning4j_tpu.backend import device as backend
    from deeplearning4j_tpu.parallel import DistributedNetwork, SyncTrainingMaster

    net = lenet(updater="adam", lr=1e-3)
    dist = DistributedNetwork(net, SyncTrainingMaster(mesh=backend.default_mesh()))
    for _ in range(2):
        dist.fit(MnistDataSetIterator(batch_size=64, num_examples=512, train=True))
    ev = dist.evaluate(MnistDataSetIterator(batch_size=64, num_examples=256, train=False))

    local = lenet(updater="adam", lr=1e-3)
    local.fit(MnistDataSetIterator(batch_size=64, num_examples=512, train=True),
              epochs=2)
    ev_local = Evaluation(10)
    for ds in MnistDataSetIterator(batch_size=64, num_examples=256, train=False):
        ev_local.eval(ds.labels, np.asarray(local.output(ds.features)))

    # the mesh path may not change what is learned
    assert abs(ev.accuracy() - ev_local.accuracy()) < 0.02, (
        f"distributed {ev.accuracy()} vs local {ev_local.accuracy()}\n"
        f"{ev.stats()}")
    assert ev.accuracy() > 0.5, ev.stats()  # sanity: training happened at all
