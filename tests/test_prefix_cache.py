"""Persistent radix-tree prefix cache (`generation/prefix_cache.py`).

Acceptance oracles from the PR issue:

- a cached-hit decode is BIT-IDENTICAL to a cold prefill of the same
  prompt (and to the legacy free-on-release engine — the oracle path);
- a host-tier offload -> restore round-trip is bit-identical, at the
  numpy-transport unit level and through the live engine;
- refcount/pin/evict invariants hold under churn: no page freed while
  referenced, pinned nodes never evicted, double-unpin raises;
- hot-swap invalidation: no hit ever serves KV prefilled under
  displaced weights (and a forced stale match raises);
- page exhaustion still sheds 429 — admission never evicts a pinned or
  in-flight node to make room;
- a seeded randomized fuzzer drives admit/release/pin/unpin/offload/
  evict sequences against a model-checker dict.
"""

import json
import threading
import time

import http.client

import numpy as np
import pytest

from deeplearning4j_tpu.generation import (
    GenerationEngine, PagedKVCache, PageExhaustedError, PrefixCache,
    PrefixCacheConfig, StalePrefixError,
)
from deeplearning4j_tpu.models.zoo import transformer_char_lm
from deeplearning4j_tpu.serving.admission import QueueFullError

pytestmark = pytest.mark.prefix_cache

VOCAB = 29


@pytest.fixture(scope="module")
def lm():
    return transformer_char_lm(vocab_size=VOCAB, d_model=32, n_heads=4,
                               layers=2, max_cache=128, seed=12345)


@pytest.fixture(scope="module")
def lm2():
    return transformer_char_lm(vocab_size=VOCAB, d_model=32, n_heads=4,
                               layers=2, max_cache=128, seed=777)


@pytest.fixture(scope="module")
def engine(lm):
    eng = GenerationEngine(lm, slots=4, page_size=4, max_context=32,
                           max_queue=64, deadline_s=30.0,
                           prefix_cache=True)
    eng.start()
    yield eng
    eng.stop()


# ------------------------------------------------------- numpy-level plumbing
class NumpyTransport:
    """Unit-test pool transport: fake numpy pools, byte-exact slices."""

    def __init__(self, num_pages, page_size, feat=4):
        self.pools = {"att": {
            "pk": np.zeros((num_pages, page_size, 1, feat), np.float32),
            "pv": np.zeros((num_pages, page_size, 1, feat), np.float32)}}

    def page_bytes(self):
        c = self.pools["att"]
        return (c["pk"].nbytes + c["pv"].nbytes) // c["pk"].shape[0]

    def cache_read_page(self, page):
        c = self.pools["att"]
        return {"att": {"pk": c["pk"][page].copy(),
                        "pv": c["pv"][page].copy()}}

    def cache_write_page(self, page, payload):
        self.pools["att"]["pk"][page] = payload["att"]["pk"]
        self.pools["att"]["pv"][page] = payload["att"]["pv"]

    def stamp(self, page, value):
        self.pools["att"]["pk"][page] = value
        self.pools["att"]["pv"][page] = -value

    def read_stamp(self, page):
        return float(self.pools["att"]["pk"][page].flat[0])


def _mk(num_pages=17, page_size=4, pages_per_slot=8, budget=1 << 20):
    cache = PagedKVCache(num_pages, page_size, pages_per_slot)
    tp = NumpyTransport(num_pages, page_size)
    pc = PrefixCache(cache, host_budget_bytes=budget, transport=tp,
                     page_bytes=tp.page_bytes())
    pc.set_version("v1")
    cache.retention = pc
    return cache, pc, tp


def _stamp_fresh(pc, tp, prompt, res):
    """What the engine's prefill does to full prompt pages: write
    content that is a function of the WHOLE chain up to each page."""
    ps = pc.page_size
    for i in range(len(prompt) // ps):
        page = res.pages[i]
        if i >= res.shared_len // ps:
            tp.stamp(page, _chain_stamp(prompt, i))


def _chain_stamp(prompt, i):
    return float(hash(tuple(prompt[:(i + 1) * 4])) % 100003) + 1.0


# ------------------------------------------------------------ unit: admission
def test_admission_pricing_hit_cheaper_than_miss():
    """A hit is priced at ⌈suffix/page⌉: a pool too small for a cold
    admission still admits the same prompt when its prefix is cached."""
    cache, pc, tp = _mk(num_pages=9, page_size=4, pages_per_slot=8)
    prompt = list(range(12))
    res = pc.admit(prompt, 5)          # 12+5-1=16 -> 4 pages, 3 cached
    _stamp_fresh(pc, tp, prompt, res)
    assert res.shared_len == 0 and len(res.pages) == 4
    cache.free(res.pages)              # request leaves; tree keeps 3
    assert pc.resident_pages() == 3
    pin = pc.pin(prompt)               # cached prefix is un-evictable
    # an in-flight blocker takes 2 more: 3 of 8 pages left free
    blocker = pc.admit([100, 101, 102, 103, 104], 4)   # 2 pages
    _stamp_fresh(pc, tp, [100, 101, 102, 103, 104], blocker)
    assert cache.free_pages == 3
    # a cold 4-page admission finds no victim (blocker in flight,
    # prompt pinned, blocker's own node shares the in-flight page)
    with pytest.raises(PageExhaustedError):
        pc.admit([200 + i for i in range(12)], 5)
    # but the CACHED prompt matches 2 pages (the match cap leaves >= 1
    # prompt token to prefill) and only needs 2 fresh -> admits
    res2 = pc.admit(prompt, 5)
    assert res2.shared_len == 8 and len(res2.pages) == 4
    assert res2.pages[:2] == res.pages[:2]
    cache.free(res2.pages)
    cache.free(blocker.pages)
    pc.unpin(pin)


def test_mid_admission_hit_refs_before_eviction():
    """The matched nodes are ref'd before room-making runs, so the
    eviction pass can never free the very pages the hit points at —
    even when they are the coldest in the tree."""
    cache, pc, tp = _mk(num_pages=7, page_size=4, pages_per_slot=8,
                        budget=0)      # no host tier: evictions drop
    old = list(range(9))
    res = pc.admit(old, 8)             # 9+8-1=16 -> 4 pages, 2 cached
    _stamp_fresh(pc, tp, old, res)
    cache.free(res.pages)
    assert pc.resident_pages() == 2 and cache.free_pages == 4
    # a second prompt leaves `old`'s nodes the COLDEST in the tree
    filler = [50 + i for i in range(9)]
    res_f = pc.admit(filler, 8)
    _stamp_fresh(pc, tp, filler, res_f)
    cache.free(res_f.pages)
    assert cache.free_pages == 2 and pc.resident_pages() == 4
    # hit on `old` needing 4 fresh (2 free): matched pages are ref'd
    # FIRST, so room-making must victimize the WARMER filler nodes —
    # plain LRU without the ref step would evict the hit's own pages
    res2 = pc.admit(old, 16)           # 9+16-1=24 -> 6 pages
    assert res2.shared_len == 8
    for i in range(2):
        assert tp.read_stamp(res2.pages[i]) == _chain_stamp(old, i)
    assert pc.evictions.get("capacity", 0) == 2   # both filler nodes
    assert pc.resident_pages() == 2               # only old's remain
    cache.free(res2.pages)


def test_stale_version_match_raises():
    cache, pc, tp = _mk()
    prompt = list(range(8))
    res = pc.admit(prompt, 4)
    _stamp_fresh(pc, tp, prompt, res)
    cache.free(res.pages)
    pc.set_version("v2")   # swap WITHOUT the engine's invalidate
    with pytest.raises(StalePrefixError):
        pc.admit(prompt, 4)


def test_failed_prefill_forgets_created_nodes():
    """fail_admitted unwinds nodes the admission created — a later
    identical prompt must MISS (the pages were never prefilled)."""
    cache, pc, tp = _mk()
    prompt = list(range(8))
    res = pc.admit(prompt, 4)
    # prefill "fails": scheduler calls forget() then frees the pages
    pc.forget(res)
    cache.free(res.pages)
    assert pc.resident_pages() == 0
    assert cache.free_pages == cache.num_pages - 1
    res2 = pc.admit(prompt, 4)
    assert res2.shared_len == 0
    cache.free(res2.pages)


# -------------------------------------------------- unit: host tier + pinning
def test_offload_restore_round_trip_unit():
    """Evicted-to-host pages restore bit-identically, via the same
    chain-stamp content the model checker uses."""
    cache, pc, tp = _mk(num_pages=6, page_size=4, pages_per_slot=8)
    a = list(range(9))
    res = pc.admit(a, 8)               # 4 pages (2 become tree nodes)
    _stamp_fresh(pc, tp, a, res)
    cache.free(res.pages)
    assert cache.free_pages == 3
    # a second prompt needs 4: one of a's cold pages spills to host
    b = [20 + i for i in range(9)]
    res_b = pc.admit(b, 8)
    _stamp_fresh(pc, tp, b, res_b)
    cache.free(res_b.pages)
    assert pc.offload_total > 0 and pc.host_pages() > 0
    assert pc.host_bytes == pc.host_pages() * tp.page_bytes()
    # hitting `a` again restores from host — bit-identical stamps
    res_a = pc.admit(a, 8)
    assert res_a.shared_len == 8 and res_a.restored_pages > 0
    for i in range(2):
        assert tp.read_stamp(res_a.pages[i]) == _chain_stamp(a, i)
    assert pc.restore_total > 0
    cache.free(res_a.pages)


def test_matched_host_node_protected_during_room_making():
    """A host-tier hit has no device page to ref when admission starts,
    so the admission pins must keep room-making off it: without them
    `_host_has_room`'s drop pass picks the very node the restore loop
    is about to write back (it is childless, unpinned, and the coldest
    host leaf), detaching it from the tree mid-admission and nulling
    its payload."""
    # host budget of exactly ONE page: any further offload must first
    # drop a host leaf — and the only host leaf is the matched node
    cache, pc, tp = _mk(num_pages=6, page_size=4, pages_per_slot=8,
                        budget=128)
    assert tp.page_bytes() == 128
    a = list(range(9))
    res = pc.admit(a, 8)               # 4 pages, 2 become tree nodes
    _stamp_fresh(pc, tp, a, res)
    cache.free(res.pages)
    # b's admission victimizes a's coldest node -> offloaded to host
    b = [20 + i for i in range(9)]
    res_b = pc.admit(b, 8)
    _stamp_fresh(pc, tp, b, res_b)
    cache.free(res_b.pages)
    assert pc.host_pages() == 1 and res_b.offloaded_pages == 1
    # hitting `a` matches one resident + one HOST node and still needs
    # room; the full host tier must find its victims elsewhere
    res_a = pc.admit(a, 8)
    assert res_a.shared_len == 8 and res_a.restored_pages == 1
    for i in range(2):
        assert tp.read_stamp(res_a.pages[i]) == _chain_stamp(a, i)
    # the matched host node was never dropped — a cold resident b-node
    # was dropped outright instead (host tier full, budget 1 page)
    assert pc.evictions.get("host_capacity", 0) == 0
    assert pc.evictions.get("capacity", 0) == 1
    # restore emptied the tier; a mid-admission drop of the matched
    # node would have decremented host_bytes twice (negative bytes)
    assert pc.host_pages() == 0 and pc.host_bytes == 0
    # the admission pins were temporary: nothing stays pinned
    assert pc.pinned_pages() == 0
    cache.free(res_a.pages)


def test_host_budget_bounds_tier_then_drops():
    """Past the host budget the coldest host leaf is dropped for room;
    with budget 0 the tier never holds anything."""
    cache, pc, tp = _mk(num_pages=7, page_size=4, pages_per_slot=8,
                        budget=0)
    for base in (0, 20, 40):
        p = [base + i for i in range(8)]
        r = pc.admit(p, 9)
        _stamp_fresh(pc, tp, p, r)
        cache.free(r.pages)
    assert pc.host_pages() == 0 and pc.offload_total == 0
    assert pc.evictions.get("capacity", 0) > 0


def test_pinned_nodes_survive_pressure_and_unpin_releases():
    cache, pc, tp = _mk(num_pages=6, page_size=4, pages_per_slot=8,
                        budget=0)
    a = list(range(9))
    res = pc.admit(a, 8)
    _stamp_fresh(pc, tp, a, res)
    cache.free(res.pages)
    pin = pc.pin(a)
    assert pc.pinned_pages() == 2 and cache.free_pages == 3
    # pressure: another request would need a's pages evicted — pinned,
    # so admission fails instead of evicting them
    b = [20 + i for i in range(12)]
    with pytest.raises(PageExhaustedError):
        pc.admit(b, 5)                 # 4 pages, only 3 free
    assert pc.resident_pages() == 2    # a's nodes untouched
    res_a = pc.admit(a, 8)             # pinned prefix still hits
    assert res_a.shared_len == 8
    cache.free(res_a.pages)
    pc.unpin(pin)
    res_b = pc.admit(b, 5)             # now a's cold nodes may go
    cache.free(res_b.pages)
    with pytest.raises(KeyError):
        pc.unpin(pin)                  # double unpin raises


def test_double_unpin_raises_after_invalidate():
    """Invalidation empties pins' node lists but keeps the ids: the one
    legal unpin works, the second still raises."""
    cache, pc, tp = _mk()
    a = list(range(8))
    r = pc.admit(a, 4)
    _stamp_fresh(pc, tp, a, r)
    cache.free(r.pages)
    pin = pc.pin(a)
    pc.invalidate("swap")
    pc.unpin(pin)                      # legal (no-op on nodes)
    with pytest.raises(KeyError):
        pc.unpin(pin)


# ------------------------------------------------------------- seeded fuzzer
def test_stats_consistent_under_concurrent_eviction():
    """``GET /generation/cache`` and the fleet snapshot read
    prefix-cache stats through ``stats()``, which owns the tree lock —
    a stats walk racing admit/offload/invalidate churn must never
    report torn numbers (e.g. a node's host slice set but
    ``host_tier_bytes`` not yet bumped)."""
    rng = np.random.RandomState(20260807)
    cache, pc, tp = _mk(num_pages=13, page_size=4, pages_per_slot=8,
                        budget=3 * 512)   # tiny host tier: evicts + drops
    families = [list(rng.randint(0, 50, 16)) for _ in range(4)]
    stop = threading.Event()
    torn = []

    def reader():
        while not stop.is_set():
            st = pc.stats()
            # every pair below is updated together under the lock, so
            # any mismatch inside ONE returned dict is a torn read
            if st["host_tier_bytes"] != st["host_pages"] * tp.page_bytes():
                torn.append(("host_tier", st))
            if (st["resident_pages"] > st["nodes"]
                    or st["pinned_pages"] > st["nodes"]):
                torn.append(("pages_vs_nodes", st))
            total = st["hits"] + st["misses"]
            expect = round(st["hits"] / total, 4) if total else 0.0
            if st["hit_rate"] != expect:
                torn.append(("hit_rate", st))

    readers = [threading.Thread(target=reader, daemon=True)
               for _ in range(3)]
    for t in readers:
        t.start()
    inflight = []
    try:
        for step in range(300):
            op = rng.randint(0, 8)
            if op <= 4:
                fam = families[rng.randint(len(families))]
                prompt = fam[:int(rng.randint(5, len(fam) + 1))]
                try:
                    res = pc.admit(prompt, int(rng.randint(1, 6)))
                except PageExhaustedError:
                    continue
                _stamp_fresh(pc, tp, prompt, res)
                inflight.append(res)
            elif op <= 6 and inflight:
                cache.free(inflight.pop(
                    rng.randint(len(inflight))).pages)
            elif rng.random_sample() < 0.2:
                pc.invalidate("pool_reset")
    finally:
        stop.set()
        for t in readers:
            t.join(timeout=10.0)
    assert torn == [], f"torn stats snapshots: {torn[:3]}"
    # the churn must actually have exercised eviction/offload paths
    assert pc.offload_total > 0 or pc.evictions


def test_cache_invariant_fuzz():
    """Randomized admit/release/pin/unpin/invalidate churn, checked
    step-by-step against a model-checker dict: chain-stamped content on
    every hit, allocator/free-list consistency, pinned nodes never
    evicted, no page freed while referenced."""
    rng = np.random.RandomState(20260806)
    cache, pc, tp = _mk(num_pages=13, page_size=4, pages_per_slot=8,
                        budget=3 * 512)   # tiny tier: exercises drops
    inflight = []          # (prompt, AdmitResult)
    pins = {}              # pin_id -> prompt
    # prompts drawn from few families => real shared-prefix structure
    families = [list(rng.randint(0, 50, 16)) for _ in range(4)]

    def check_invariants():
        free = set(cache._free)
        assert len(free) == len(cache._free), "free list duplicates"
        for p in free:
            assert cache.refcount(p) == 0, f"page {p} free but ref'd"
        for p in range(1, cache.num_pages):
            assert cache.refcount(p) >= 0
            if cache.refcount(p) == 0:
                assert p in free, f"page {p} leaked (ref 0, not free)"
        resident = [n.page for n in pc._all if n.page is not None]
        assert len(resident) == len(set(resident)), "node page dup"
        for pg in resident:
            assert cache.refcount(pg) >= 1 and pg not in free
        assert pc.host_bytes == pc.host_pages() * tp.page_bytes()
        for pid, nodes in pc._pins.items():
            for n in nodes:
                assert n.pins >= 1

    for step in range(400):
        op = rng.randint(0, 10)
        if op <= 3:          # admit
            fam = families[rng.randint(len(families))]
            cut = int(rng.randint(5, len(fam) + 1))
            prompt = fam[:cut]
            try:
                res = pc.admit(prompt, int(rng.randint(1, 6)))
            except PageExhaustedError:
                pass
            else:
                # model check: every matched page's content must be the
                # chain stamp its prefix dictates
                for i in range(res.shared_len // 4):
                    got = tp.read_stamp(res.pages[i])
                    assert got == _chain_stamp(prompt, i), (
                        f"step {step}: hit page {res.pages[i]} holds "
                        f"{got}, expected chain stamp of "
                        f"{tuple(prompt[:(i + 1) * 4])}")
                _stamp_fresh(pc, tp, prompt, res)
                inflight.append((prompt, res))
        elif op <= 5 and inflight:   # release a random request
            _, res = inflight.pop(rng.randint(len(inflight)))
            cache.free(res.pages)
        elif op == 6:        # pin a family prefix
            fam = families[rng.randint(len(families))]
            pins[pc.pin(fam[:int(rng.randint(4, 13))])] = True
        elif op == 7 and pins:       # unpin
            pid = list(pins)[rng.randint(len(pins))]
            del pins[pid]
            pc.unpin(pid)
        elif op == 8 and rng.random_sample() < 0.1:
            pc.invalidate("pool_reset")
            tp.pools["att"]["pk"][:] = 0
            tp.pools["att"]["pv"][:] = 0
        check_invariants()
    for _, res in inflight:
        cache.free(res.pages)
    check_invariants()
    # the run must actually have exercised the interesting paths
    assert pc.hits > 0 and pc.misses > 0
    assert pc.offload_total > 0 or pc.evictions


# ----------------------------------------------------------- engine: parity
def test_persistent_hits_bit_identical_and_legacy_oracle(engine, lm, rng):
    """Cold pass == warm (cached-hit) pass == legacy free-on-release
    engine, token for token; warm passes must actually hit."""
    legacy = GenerationEngine(lm, slots=4, page_size=4, max_context=32)
    legacy.start()
    prompts = [rng.randint(0, VOCAB, 9).tolist() for _ in range(4)]
    ref = [legacy.generate(p, 8).tolist() for p in prompts]
    legacy.stop()

    h0 = engine.prefix_cache.hits
    cold = [engine.generate(p, 8).tolist() for p in prompts]
    assert cold == ref
    warm = [engine.generate(p, 8).tolist() for p in prompts]
    assert warm == ref
    assert engine.prefix_cache.hits >= h0 + len(prompts)
    # sampled decoding hits the cache identically
    kw = dict(temperature=0.9, top_k=7, seed=42)
    s1 = engine.generate(prompts[0], 8, **kw).tolist()
    s2 = engine.generate(prompts[0], 8, **kw).tolist()
    assert s1 == s2


def test_engine_offload_restore_round_trip(lm, rng):
    """Tight pool: cold pages spill to host mid-run and restore on
    revisit; every completion stays bit-identical to the legacy
    engine."""
    eng = GenerationEngine(lm, slots=2, page_size=4, max_context=32,
                           num_pages=13, prefix_cache=True)
    eng.start()
    prompts = [rng.randint(0, VOCAB, 9).tolist() for _ in range(6)]
    ref = [eng.generate(p, 8).tolist() for p in prompts]
    st = eng.prefix_cache.stats()
    assert st["offload_total"] > 0, st
    again = [eng.generate(p, 8).tolist() for p in prompts]
    assert again == ref
    st = eng.prefix_cache.stats()
    assert st["restore_total"] > 0 and st["hits"] >= len(prompts), st
    eng.stop()

    legacy = GenerationEngine(lm, slots=2, page_size=4, max_context=32,
                              num_pages=13)
    legacy.start()
    assert [legacy.generate(p, 8).tolist() for p in prompts] == ref
    legacy.stop()


def test_chat_session_pinning(engine, rng):
    """Multi-turn conversation: pin the history after each turn; later
    turns only prefill the new tokens (shared_len grows monotonically)
    and the transcript matches an unpinned cold engine."""
    history = rng.randint(0, VOCAB, 6).tolist()
    pin = None
    shared_seen = []
    for turn in range(3):
        req = engine.submit(history, 4)
        toks = req.result(timeout=60)
        shared_seen.append(req.shared_len)
        history = history + toks + rng.randint(0, VOCAB, 2).tolist()
        if pin is not None:
            engine.unpin_prefix(pin)
        pin = engine.pin_prefix(history)
    engine.unpin_prefix(pin)
    assert shared_seen[1] > 0 and shared_seen[2] > shared_seen[1]
    with pytest.raises(KeyError):
        engine.unpin_prefix(pin)


# ---------------------------------------------- engine: invalidation + 429s
def test_hot_swap_invalidation_drill(lm, lm2, rng):
    """After a deploy, the very next identical prompt must NOT hit the
    old tree (stale weights) — its tokens must equal a fresh engine
    running the new weights; rollback invalidates again."""
    eng = GenerationEngine(lm, slots=2, page_size=4, max_context=32,
                           prefix_cache=True)
    eng.start()
    prompt = rng.randint(0, VOCAB, 9).tolist()
    eng.generate(prompt, 8)
    assert eng.generate(prompt, 8) is not None
    assert eng.prefix_cache.hits >= 1

    eng.deploy("default", lm2, retain_old=True)
    got = eng.generate(prompt, 8).tolist()
    st = eng.prefix_cache.stats()
    assert st["evictions_total"].get("swap", 0) > 0, st
    fresh = GenerationEngine(lm2, slots=2, page_size=4, max_context=32)
    fresh.start()
    assert got == fresh.generate(prompt, 8).tolist()
    fresh.stop()

    eng.rollback()
    back = eng.generate(prompt, 8).tolist()
    fresh_old = GenerationEngine(lm, slots=2, page_size=4,
                                 max_context=32)
    fresh_old.start()
    assert back == fresh_old.generate(prompt, 8).tolist()
    fresh_old.stop()
    eng.stop()


def test_restart_invalidates_pool_reset(lm, rng):
    """stop() + start() reseeds the pools; the tree must not survive
    into the new pools (their pages hold zeros, not the cached KV)."""
    eng = GenerationEngine(lm, slots=2, page_size=4, max_context=32,
                           prefix_cache=True)
    eng.start()
    prompt = rng.randint(0, VOCAB, 9).tolist()
    ref = eng.generate(prompt, 8).tolist()
    assert eng.prefix_cache.resident_pages() > 0
    eng.stop()
    eng.start()
    assert eng.prefix_cache.resident_pages() == 0
    assert eng.prefix_cache.stats()["evictions_total"].get(
        "pool_reset", 0) > 0
    assert eng.generate(prompt, 8).tolist() == ref
    eng.stop()


def test_page_exhaustion_sheds_never_evicts_pinned(lm, rng):
    """Every page pinned or in flight: admission must shed (429 once
    the queue fills) rather than evict a pinned/in-use node; unpinning
    unblocks the queued request."""
    # pool of 8 usable pages: one 16-occupancy request takes 4
    eng = GenerationEngine(lm, slots=2, page_size=4, max_context=32,
                           num_pages=9, max_queue=2, deadline_s=30.0,
                           prefix_cache=True)
    eng.start()
    a = rng.randint(0, VOCAB, 9).tolist()
    b = rng.randint(0, VOCAB, 9).tolist()
    for p in (a, b):
        eng.generate(p, 8)
    pin_a, pin_b = eng.pin_prefix(a), eng.pin_prefix(b)
    assert eng.prefix_cache.pinned_pages() == 4
    # a long-running request occupies the remaining 4 pages
    blocker = eng.submit(rng.randint(0, VOCAB, 9).tolist(), 8,
                         temperature=0.5, seed=3)
    blocker.result(timeout=60)
    # now every allocatable page is pinned tree state; new cold
    # requests queue (cannot admit), then overflow sheds 429
    q1 = eng.submit(rng.randint(0, VOCAB, 12).tolist(), 8)
    q2 = eng.submit(rng.randint(0, VOCAB, 12).tolist(), 8)
    time.sleep(0.3)
    assert not q1.done.is_set() and not q2.done.is_set()
    assert eng.prefix_cache.pinned_pages() == 4   # nothing evicted
    with pytest.raises(QueueFullError):
        eng.submit(rng.randint(0, VOCAB, 12).tolist(), 8)
    # release the pins: the queued requests admit and complete
    eng.unpin_prefix(pin_a)
    eng.unpin_prefix(pin_b)
    assert len(q1.result(timeout=60)) == 8
    assert len(q2.result(timeout=60)) == 8
    eng.stop()


# ------------------------------------------------------------ engine: churn
def test_concurrent_join_leave_pin_churn(engine, rng):
    """Client threads submitting/pinning/unpinning concurrently while
    the decode loop evicts and restores: every request completes with
    deterministic greedy tokens; allocator invariants hold after."""
    prompts = [rng.randint(0, VOCAB, 9).tolist() for _ in range(6)]
    ref = {i: engine.generate(p, 6).tolist()
           for i, p in enumerate(prompts)}
    pinned_before = engine.prefix_cache.pinned_pages()
    errors = []

    def worker(wid):
        try:
            r = np.random.RandomState(wid)
            for _ in range(5):
                i = int(r.randint(len(prompts)))
                pin = engine.pin_prefix(prompts[i])
                got = engine.generate(prompts[i], 6).tolist()
                assert got == ref[i], (i, got, ref[i])
                engine.unpin_prefix(pin)
        except Exception as e:      # surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    assert not errors, errors
    # steady state: everything in flight drained, refcounts consistent
    time.sleep(0.2)
    cache = engine.cache
    for p in range(1, cache.num_pages):
        assert cache.refcount(p) >= 0
    free = set(cache._free)
    for n in engine.prefix_cache._all:
        if n.page is not None:
            assert n.page not in free
    assert engine.prefix_cache.pinned_pages() == pinned_before


# ------------------------------------------------------------- HTTP surface
def test_generation_cache_endpoint(engine, rng):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.streaming.serving import InferenceServer

    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater("sgd", learning_rate=0.1).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss="mcxent"))
            .build())
    pred = MultiLayerNetwork(conf).init()
    srv = InferenceServer(pred, generation=engine)
    port = srv.start()
    try:
        engine.generate(rng.randint(0, VOCAB, 9).tolist(), 4)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/generation/cache")
        resp = conn.getresponse()
        assert resp.status == 200
        body = json.loads(resp.read())
        assert body["cache"]["num_pages"] == engine.cache.num_pages
        pc = body["prefix_cache"]
        assert pc is not None and pc["nodes"] >= 1
        assert set(pc) >= {"hits", "misses", "resident_pages",
                           "host_tier_bytes", "pinned_pages",
                           "offload_total", "restore_total",
                           "evictions_total"}
        conn.close()
    finally:
        srv.stop()


def test_ui_generation_cache_route(engine):
    from deeplearning4j_tpu.ui.server import UIServer

    ui = UIServer()
    port = ui.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/generation/cache")
        assert conn.getresponse().status == 404   # nothing attached
        ui.attach_generation(engine)
        conn.request("GET", "/generation/cache")
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read())["prefix_cache"] is not None
        conn.close()
    finally:
        ui.stop()
