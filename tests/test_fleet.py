"""Fleet telemetry plane (`deeplearning4j_tpu/observability/fleet.py`).

Acceptance oracles from the PR issue:

- schema-versioned snapshots: bounded, JSON-safe, deterministic wire
  form; NaN gauges map to null instead of tripping the strict encoder;
- epoch/seq delta merge: counter/histogram totals accumulate across
  snapshots without double-counting replays, a restarted publisher
  (new epoch) RESUMES merging — no double-count, no reset-to-zero;
- staleness: a worker that stops publishing flips stale within
  ``expire_after_s``, its gauges drop from the fleet view while its
  monotonic counters survive, and fleet health NAMES it;
- forward compatibility: unparseable/foreign-schema/malformed input is
  counted and skipped, never raised;
- decode SLO attribution: TTFT/ITL attainment + goodput math, the
  engine's per-phase breakdown reconciling with its busy wall, the ITL
  histogram populating under real decode, and the /generate access log
  carrying the per-request SLO verdict;
- the router-facing cache stats surface: prefix-cache stats ride the
  federated snapshot with the tree version tag, and a hot-swap
  invalidation is visible THROUGH the aggregator within one publish.
"""

import json
import logging
import time
import urllib.request

import numpy as np
import pytest

import http.client

from deeplearning4j_tpu.generation import GenerationEngine
from deeplearning4j_tpu.models.zoo import transformer_char_lm
from deeplearning4j_tpu.observability.fleet import (
    SCHEMA_VERSION, FleetAggregator, SLOTracker, TelemetryPublisher,
    schema_roundtrip_selftest,
)
from deeplearning4j_tpu.observability.metrics import MetricsRegistry
from deeplearning4j_tpu.streaming import MessageBroker

pytestmark = pytest.mark.fleet

VOCAB = 29


def small_lm(seed=12345):
    return transformer_char_lm(vocab_size=VOCAB, d_model=32, n_heads=4,
                               layers=2, max_cache=128, seed=seed)


@pytest.fixture(scope="module")
def engine():
    eng = GenerationEngine(small_lm(), slots=4, page_size=4,
                           max_context=32, max_queue=64, deadline_s=30.0,
                           prefix_cache=True)
    eng.start()
    yield eng
    eng.stop()


def wait_for(cond, timeout=10.0, poll=0.02):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return True
        time.sleep(poll)
    return False


def worker_registry():
    """A publisher-side registry with one family of each kind."""
    reg = MetricsRegistry()
    c = reg.counter("dl4j_test_work_total", "Work items processed",
                    labels=("kind",))
    g = reg.gauge("dl4j_test_depth", "Queue depth right now")
    h = reg.histogram("dl4j_test_lat_seconds", "Observed latencies",
                      buckets=(0.01, 0.1, 1.0))
    return reg, c, g, h


def fleet_value(agg, name, worker):
    """Merged value for one worker's unlabeled-or-first sample of a
    family in the rebuilt fleet registry (None = absent)."""
    for fam in agg.registry().families():
        if fam.name != name:
            continue
        total, seen = 0.0, False
        for label_pairs, child in fam.samples():
            labels = dict(label_pairs)
            if labels.get("worker", labels.get("origin")) == worker:
                seen = True
                total += (child.snapshot()["count"]
                          if fam.kind == "histogram" else child.value)
        return total if seen else None
    return None


def wire(worker="w1", epoch="e1", seq=1, families=None, **extra):
    snap = {"schema": SCHEMA_VERSION, "worker": worker, "epoch": epoch,
            "seq": seq, "ts": time.time(), "families": families or {}}
    snap.update(extra)
    return json.dumps(snap)


def counter_fam(value):
    return {"kind": "counter", "help": "h", "label_names": [],
            "samples": [{"labels": {}, "value": value}]}


# ------------------------------------------------------------ SLO tracker
def test_slo_tracker_attainment_math():
    reg = MetricsRegistry()
    t = SLOTracker(ttft_target_s=0.1, itl_target_s=0.05,
                   goodput_window_s=10.0, registry=reg, engine_id="e0")
    # good: fast TTFT, fast ITL
    assert t.observe_request(ttft_s=0.05, itl_s=[0.01] * 20, now=100.0)
    # TTFT miss
    assert not t.observe_request(ttft_s=0.2, itl_s=[0.01], now=100.2)
    # ITL p95 miss (every gap slow)
    assert not t.observe_request(ttft_s=0.05, itl_s=[0.2] * 10, now=100.4)
    # failed request is never good, even with fast latencies
    assert not t.observe_request(ttft_s=0.05, itl_s=[0.01],
                                 completed=False, now=100.6)
    # no inter-token gaps: the ITL leg passes vacuously
    assert t.observe_request(ttft_s=0.05, itl_s=[], now=100.8)
    d = t.as_dict()
    assert d["finished"] == 5
    assert d["ttft_attainment"] == pytest.approx(4 / 5)
    assert d["itl_attainment"] == pytest.approx(4 / 5)
    assert d["good_attainment"] == pytest.approx(2 / 5)
    assert d["targets"] == {"ttft_s": 0.1, "itl_p95_s": 0.05,
                            "goodput_window_s": 10.0}
    # the registry mirrors the attainment as lazy gauges
    text = reg.to_prometheus()
    assert "dl4j_decode_slo_attainment" in text
    assert "dl4j_decode_goodput_rps" in text


def test_slo_tracker_goodput_window_slides():
    t = SLOTracker(ttft_target_s=1.0, itl_target_s=1.0,
                   goodput_window_s=10.0, registry=MetricsRegistry())
    for i in range(4):
        t.observe_request(ttft_s=0.1, now=100.0 + i)
    assert t.goodput_rps(now=104.0) == pytest.approx(4 / 10.0)
    # two of the four age out of the window
    assert t.goodput_rps(now=111.5) == pytest.approx(2 / 10.0)
    # all gone
    assert t.goodput_rps(now=1000.0) == 0.0


# ------------------------------------------------- snapshot schema + wire
def test_snapshot_schema_and_bounds():
    reg, c, g, h = worker_registry()
    c.inc(3, kind="a")
    g.set(7.5)
    h.observe(0.05)
    pub = TelemetryPublisher(
        "w1", registry=reg,
        state_fn=lambda: {"scheduler": {"queued": 2}},
        prefix_cache=lambda: {"version": "default@v1", "hits": 4})
    snap = pub.snapshot()
    assert snap["schema"] == SCHEMA_VERSION
    assert snap["worker"] == "w1" and snap["seq"] == 1
    assert snap["epoch"] and snap["ts"] > 0
    fams = snap["families"]
    assert fams["dl4j_test_work_total"]["kind"] == "counter"
    assert fams["dl4j_test_work_total"]["samples"][0] == {
        "labels": {"kind": "a"}, "value": 3.0}
    assert fams["dl4j_test_depth"]["samples"][0]["value"] == 7.5
    hist = fams["dl4j_test_lat_seconds"]
    assert hist["buckets"] == [0.01, 0.1, 1.0]
    assert hist["samples"][0]["count"] == 1
    assert snap["state"] == {"scheduler": {"queued": 2}}
    assert snap["prefix_cache"]["version"] == "default@v1"
    # seq advances per snapshot within one epoch
    assert pub.snapshot()["seq"] == 2


def test_snapshot_nan_gauge_serializes_to_null():
    reg = MetricsRegistry()
    reg.gauge("dl4j_test_depth", "Queue depth right now").set(float("nan"))
    pub = TelemetryPublisher("w1", registry=reg)
    payload = pub.serialize()   # allow_nan=False: must not raise
    fams = json.loads(payload)["families"]
    assert fams["dl4j_test_depth"]["samples"][0]["value"] is None


def test_snapshot_bounds_sample_explosion():
    reg = MetricsRegistry()
    c = reg.counter("dl4j_test_work_total", "Work items processed",
                    labels=("kind",))
    for i in range(40):
        c.inc(kind=f"k{i:03d}")
    pub = TelemetryPublisher("w1", registry=reg,
                             max_samples_per_family=16)
    snap = pub.snapshot()
    assert len(snap["families"]["dl4j_test_work_total"]["samples"]) == 16
    assert snap["truncated_samples"] == 24


def test_schema_roundtrip_selftest_green():
    assert schema_roundtrip_selftest() == 0


# ------------------------------------------------------ delta/epoch merge
def test_counter_delta_merge_ignores_replays():
    agg = FleetAggregator(registry=MetricsRegistry())
    assert agg.ingest(wire(seq=1, families={
        "dl4j_test_work_total": counter_fam(10)}))
    assert agg.ingest(wire(seq=2, families={
        "dl4j_test_work_total": counter_fam(25)}))
    assert fleet_value(agg, "dl4j_test_work_total", "w1") == 25.0
    # replay of seq 2 and an out-of-order seq 1 both drop
    assert not agg.ingest(wire(seq=2, families={
        "dl4j_test_work_total": counter_fam(25)}))
    assert not agg.ingest(wire(seq=1, families={
        "dl4j_test_work_total": counter_fam(10)}))
    assert fleet_value(agg, "dl4j_test_work_total", "w1") == 25.0
    assert agg.fleet_table()["merge_skips"].get("replay") == 2


def test_epoch_restart_resumes_without_double_count():
    agg = FleetAggregator(registry=MetricsRegistry())
    agg.ingest(wire(epoch="e1", seq=1, families={
        "dl4j_test_work_total": counter_fam(10)}))
    agg.ingest(wire(epoch="e1", seq=2, families={
        "dl4j_test_work_total": counter_fam(25)}))
    # restart: new epoch re-counts from a fresh base (5), history stays
    agg.ingest(wire(epoch="e2", seq=1, families={
        "dl4j_test_work_total": counter_fam(5)}))
    assert fleet_value(agg, "dl4j_test_work_total", "w1") == 30.0
    # and the new epoch keeps delta-merging
    agg.ingest(wire(epoch="e2", seq=2, families={
        "dl4j_test_work_total": counter_fam(9)}))
    assert fleet_value(agg, "dl4j_test_work_total", "w1") == 34.0


def test_histogram_delta_merge_across_snapshots():
    agg = FleetAggregator(registry=MetricsRegistry())

    def hist_fam(count, total, counts):
        return {"kind": "histogram", "help": "h", "label_names": [],
                "buckets": [0.1, 1.0],
                "samples": [{"labels": {}, "count": count, "sum": total,
                             "min": 0.01, "max": 0.5,
                             "bucket_counts": counts}]}

    agg.ingest(wire(seq=1, families={
        "dl4j_test_lat_seconds": hist_fam(5, 0.5, [2, 3])}))
    agg.ingest(wire(seq=2, families={
        "dl4j_test_lat_seconds": hist_fam(8, 0.9, [3, 5])}))
    assert fleet_value(agg, "dl4j_test_lat_seconds", "w1") == 8


# ----------------------------------------------------------- staleness
def test_stale_worker_drops_gauges_keeps_counters_and_is_named():
    agg = FleetAggregator(expire_after_s=0.2, registry=MetricsRegistry())
    agg.ingest(wire(families={
        "dl4j_test_work_total": counter_fam(12),
        "dl4j_test_depth": {"kind": "gauge", "help": "h",
                            "label_names": [],
                            "samples": [{"labels": {}, "value": 4.0}]},
    }))
    assert fleet_value(agg, "dl4j_test_depth", "w1") == 4.0
    assert agg.workers()[0]["stale"] is False
    assert agg.evaluate_health().healthy
    time.sleep(0.35)
    # flipped stale: gauges vanish from the fleet view, counters survive
    assert agg.workers()[0]["stale"] is True
    assert fleet_value(agg, "dl4j_test_depth", "w1") is None
    assert fleet_value(agg, "dl4j_test_work_total", "w1") == 12.0
    verdict = agg.evaluate_health()
    assert not verdict.healthy
    assert any("w1" in str(r) for r in verdict.results if not r["ok"])
    # the fleet meta gauges agree
    text = agg.registry().to_prometheus()
    assert "dl4j_fleet_stale_workers 1" in text
    assert "dl4j_fleet_workers 0" in text


# ---------------------------------------------------- federation transport
def test_two_publishers_one_aggregator_over_broker():
    broker = MessageBroker()
    agg = FleetAggregator(broker=broker, topic="t.fleet",
                          registry=MetricsRegistry()).start()
    try:
        regs = []
        for i, wid in enumerate(("w1", "w2")):
            reg, c, g, _h = worker_registry()
            c.inc(10 * (i + 1), kind="x")
            g.set(float(i))
            regs.append(TelemetryPublisher(wid, broker=broker,
                                           topic="t.fleet", registry=reg))
        for pub in regs:
            assert pub.publish_once() == 1   # one subscriber: the agg
        assert wait_for(lambda: len(agg.workers()) == 2)
        table = agg.fleet_table()
        assert [w["worker"] for w in table["workers"]] == ["w1", "w2"]
        assert fleet_value(agg, "dl4j_test_work_total", "w1") == 10.0
        assert fleet_value(agg, "dl4j_test_work_total", "w2") == 20.0
        text = agg.registry().to_prometheus()
        assert 'worker="w1"' in text and 'worker="w2"' in text
        assert "dl4j_fleet_workers 2" in text
    finally:
        agg.stop()


def test_http_federation_and_fleet_endpoints():
    broker = MessageBroker()
    bport = broker.serve(port=0)
    url = f"http://127.0.0.1:{bport}"
    agg = FleetAggregator(url=url, topic="t.http",
                          registry=MetricsRegistry()).start()
    try:
        time.sleep(0.3)   # first long-poll registers the subscription
        reg, c, _g, _h = worker_registry()
        c.inc(6, kind="x")
        pub = TelemetryPublisher("w1", url=url, topic="t.http",
                                 registry=reg)
        assert wait_for(lambda: pub.publish_once() >= 1 and
                        len(agg.workers()) == 1)
        fport = agg.serve(port=0)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fport}/metrics") as resp:
            text = resp.read().decode()
        assert 'dl4j_test_work_total{kind="x",worker="w1"} 6' in text
        assert "dl4j_fleet_workers 1" in text
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fport}/fleet") as resp:
            table = json.loads(resp.read())
        assert table["workers"][0]["worker"] == "w1"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fport}/health") as resp:
            assert json.loads(resp.read())["healthy"] is True
    finally:
        agg.stop()
        broker.stop()


# ------------------------------------------------- forward compatibility
def test_ingest_never_raises_on_garbage():
    agg = FleetAggregator(registry=MetricsRegistry())
    assert not agg.ingest("{not json")
    assert not agg.ingest(json.dumps([1, 2, 3]))
    assert not agg.ingest(wire(schema=99))          # foreign schema
    assert not agg.ingest(json.dumps({"schema": SCHEMA_VERSION}))  # no id
    # a missing/non-numeric seq is dropped, NOT defaulted to 0 (which
    # would pin the worker and replay-drop all its later snapshots)
    snap = json.loads(wire())
    del snap["seq"]
    assert not agg.ingest(json.dumps(snap))
    assert not agg.ingest(wire(seq="soon"))
    # malformed family fragments are skipped, the snapshot still lands —
    # including fragments that RAISE mid-merge (non-iterable
    # label_names), which must not wedge the aggregator's lock
    assert agg.ingest(wire(seq=1, families={
        "dl4j_bad": "not-a-dict",
        "dl4j_weird": {"kind": "thermometer", "samples": []},
        "dl4j_explodes": {"kind": "gauge", "label_names": 5,
                          "samples": [{"labels": {}, "value": 1.0}]},
        "dl4j_test_work_total": counter_fam(3),
    }, some_future_field={"ok": True}))
    assert fleet_value(agg, "dl4j_test_work_total", "w1") == 3.0
    # the lock was released cleanly: later snapshots keep merging
    assert agg.ingest(wire(seq=2, families={
        "dl4j_test_work_total": counter_fam(7)}))
    assert fleet_value(agg, "dl4j_test_work_total", "w1") == 7.0
    skips = agg.fleet_table()["merge_skips"]
    assert skips.get("parse") == 2
    assert skips.get("schema") == 1
    assert skips.get("fields") == 3
    assert skips.get("family") == 1


def test_vanished_gauge_labelset_drops_from_fleet_view():
    """A gauge label-set absent from the next snapshot (truncated away,
    or simply gone) must leave the fleet view, not stay frozen."""
    agg = FleetAggregator(registry=MetricsRegistry())

    def depth_fam(samples):
        return {"kind": "gauge", "help": "h", "label_names": ["q"],
                "samples": samples}

    agg.ingest(wire(seq=1, families={"dl4j_test_depth": depth_fam(
        [{"labels": {"q": "a"}, "value": 4.0},
         {"labels": {"q": "b"}, "value": 9.0}])}))
    assert fleet_value(agg, "dl4j_test_depth", "w1") == 13.0
    agg.ingest(wire(seq=2, families={"dl4j_test_depth": depth_fam(
        [{"labels": {"q": "a"}, "value": 5.0}])}))
    assert fleet_value(agg, "dl4j_test_depth", "w1") == 5.0
    text = agg.registry().to_prometheus()
    assert 'q="b"' not in text
    # an empty sample list clears the family outright
    agg.ingest(wire(seq=3, families={"dl4j_test_depth": depth_fam([])}))
    assert fleet_value(agg, "dl4j_test_depth", "w1") is None


# ------------------------------------------- decode SLO attribution (e2e)
def test_engine_decode_slo_attribution(engine):
    rs = np.random.RandomState(7)
    for _ in range(4):
        h = engine.submit(rs.randint(0, VOCAB, 6).tolist(), 8)
        assert len(h.result(timeout=60)) == 8
        assert h.slo_ok is not None        # settled by the SLO tracker
    st = engine.stats()
    slo = st["slo"]
    assert slo["finished"] >= 4
    assert slo["good_attainment"] is not None
    assert slo["goodput_rps"] >= 0.0
    # every decode-loop phase fired, and the breakdown reconciles with
    # the loop's busy wall (phases nest inside it, so sum <= busy + eps)
    phases = st["phases"]["phases"]
    for name in ("schedule", "page_gather", "jitted_step",
                 "sample_harvest", "stream_write"):
        assert phases[name]["count"] > 0, name
    phase_ms = sum(p["total_ms"] for p in phases.values())
    assert phase_ms <= st["busy_wall_s"] * 1e3 * 1.1 + 5.0
    assert phase_ms > 0
    # the ITL histogram populated under real decode
    itl = sum(child.snapshot()["count"] for _l, child
              in engine.metrics.inter_token.samples())
    assert itl > 0


def test_generate_access_log_carries_slo_fields(caplog):
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.streaming.serving import InferenceServer

    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater("sgd", learning_rate=0.1).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax")).build())
    pred = MultiLayerNetwork(conf).init()
    gen = GenerationEngine(small_lm(), slots=2, page_size=4,
                           max_context=16, max_queue=8,
                           prefill_buckets=(8,)).start()
    srv = InferenceServer(pred, generation=gen, access_log=True)
    port = srv.start()
    try:
        with caplog.at_level(logging.INFO,
                             logger="deeplearning4j_tpu.serving.access"):
            c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            c.request("POST", "/generate", json.dumps(
                {"prompt": [1, 2, 3], "max_tokens": 5}),
                {"X-Request-Id": "slo-trace-1"})
            r = c.getresponse()
            assert r.status == 200
            r.read()
        lines = [json.loads(rec.message) for rec in caplog.records
                 if rec.name == "deeplearning4j_tpu.serving.access"]
        line = next(l for l in lines if l["trace_id"] == "slo-trace-1")
        assert line["tokens"] == 5
        assert line["slo_ok"] in (True, False)
        # 5 tokens -> 4 inter-token gaps -> a real p50
        assert line["itl_p50_ms"] is not None and line["itl_p50_ms"] >= 0
    finally:
        srv.stop()
        gen.stop()


# ------------------------------- router-facing cache stats + hot swap
def test_prefix_stats_federate_and_hotswap_is_visible(engine):
    broker = MessageBroker()
    agg = FleetAggregator(broker=broker, topic="t.swap",
                          registry=MetricsRegistry()).start()
    pub = engine.fleet_publisher("w-eng", broker=broker, topic="t.swap")
    try:
        engine.submit([1, 2, 3, 4, 5, 6], 4).result(timeout=60)
        assert pub.publish_once() == 1
        assert wait_for(lambda: len(agg.workers()) == 1)
        row = agg.workers()[0]
        # the router-facing surface, exactly as the worker published it
        pc = row["prefix_cache"]
        for key in ("version", "resident_pages", "pinned_pages",
                    "host_tier_bytes", "hit_rate"):
            assert key in pc, key
        v1 = pc["version"]
        assert row["slo"]["finished"] >= 1
        assert row["state"]["scheduler"]
        # hot swap: the tree version tag must change THROUGH the
        # aggregator within one publish interval (the decode loop stamps
        # the tree on its next idle tick, then the publish carries it)
        engine.deploy("default", small_lm(seed=777))
        assert wait_for(lambda: engine.prefix_cache.version != v1)
        assert pub.publish_once() == 1
        assert wait_for(lambda: len(agg.workers()) == 1 and
                        agg.workers()[0]["prefix_cache"]["version"] != v1)
        assert agg.workers()[0]["prefix_cache"]["version"] != v1
    finally:
        agg.stop()
