"""The bench's driver contract: the LAST stdout line must be compact,
parseable JSON under the driver's ~2 KB tail-capture window (round 4
shipped a line that outgrew it — BENCH_r04.json ``"parsed": null`` — so
the contract is now pinned by test).

Two tiers: a cheap unit test of ``emit_result`` (always runs, with a
deliberately bloated payload), and a full-bench subprocess integration
test gated behind ``DL4J_BENCH_TEST=1`` (minutes of CPU)."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(REPO, "bench.py"))
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _fake_full(n_metrics=8):
    # every metric padded with the spread/variant bulk that overflowed the
    # round-4 line
    metrics = []
    for i in range(n_metrics):
        metrics.append({
            "metric": f"Metric number {i} (d1024 L8 T2048, flash attention)",
            "value": 123456.7 + i,
            "unit": "tokens/sec",
            "vs_baseline": None,
            "spread": {"reps": 3, "rep_ms": [1.0, 2.0, 3.0] * 10},
            "variants": {f"v{j}": {"tokens_per_sec": j, "per_token_ms": j,
                                   "spread": {"rep_ms": [0.1] * 12}}
                         for j in range(4)},
        })
    return {
        "metric": metrics[0]["metric"], "value": metrics[0]["value"],
        "unit": metrics[0]["unit"], "vs_baseline": 1.23, "mfu": 0.68,
        "platform": "tpu", "device_kind": "TPU v5 lite",
        "peak_flops": 197e12, "baseline_source": "baseline_cpu.json",
        "all": metrics,
        "errors": ["x" * 400, "y" * 400, "z" * 400],
    }


def test_emit_line_is_compact_and_parseable(tmp_path):
    line = bench.emit_result(_fake_full(), out_dir=str(tmp_path))
    assert len(line) < 1500
    head = json.loads(line)
    for field in ("metric", "value", "unit", "vs_baseline", "mfu",
                  "platform", "device_kind"):
        assert field in head, f"missing driver field {field}"
    assert head["platform"] == "tpu"
    # the full payload round-trips from the file
    with open(tmp_path / "bench_full.json") as f:
        full = json.load(f)
    assert len(full["all"]) == 8


def test_emit_line_never_exceeds_window_even_when_huge(tmp_path):
    full = _fake_full(n_metrics=40)  # summary alone would blow the window
    for m in full["all"]:
        m["metric"] = "Very long metric name " * 8 + m["metric"]
    full["metric"] = full["all"][0]["metric"]
    line = bench.emit_result(full, out_dir=str(tmp_path))
    assert len(line) <= 1500
    json.loads(line)  # shrunk by dropping FIELDS — still valid JSON


def test_emit_survives_unwritable_out_dir(tmp_path):
    line = bench.emit_result(_fake_full(),
                             out_dir=str(tmp_path / "no" / "such" / "dir"))
    head = json.loads(line)
    assert "full_write_error" in head
    assert head["value"] == _fake_full()["value"]


@pytest.mark.skipif(os.environ.get("DL4J_BENCH_TEST") != "1",
                    reason="full CPU bench takes minutes; set DL4J_BENCH_TEST=1")
def test_full_bench_subprocess_contract():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["DL4J_BENCH_NO_FALLBACK"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=1800, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    last = lines[-1]
    assert len(last) < 2000, f"headline line is {len(last)} chars"
    head = json.loads(last)
    assert head["platform"] in ("cpu", "tpu")
    with open(os.path.join(REPO, "bench_full.json")) as f:
        full = json.load(f)
    assert not full.get("errors"), full.get("errors")
    by_name = {m["metric"]: m for m in full["all"]}
    lenet = next(m for n, m in by_name.items() if n.startswith("LeNet"))
    # VERDICT r4 task 4: the dispatch-floor fix is measured, not just built
    assert lenet["scanned_k"] >= 16 and lenet["scanned_step_ms"] > 0
    decode = next(m for n, m in by_name.items() if n.startswith("Decode"))
    # VERDICT r4 task 3: the KV cache is big enough to mean something
    assert decode["variants"]["mha"]["kv_cache_mb"] >= 10
