"""Clustering / trees / t-SNE tests (≙ KMeans, KDTree, VPTree, SpTree and
Tsne/BarnesHutTsne suites in deeplearning4j-core)."""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (
    BarnesHutTsne,
    KDTree,
    KMeansClustering,
    QuadTree,
    SpTree,
    Tsne,
    VPTree,
)


def blobs(n_per=40, centers=((0, 0), (10, 10), (-10, 10)), seed=0, scale=0.5):
    rs = np.random.RandomState(seed)
    pts, labels = [], []
    for k, c in enumerate(centers):
        pts.append(rs.randn(n_per, len(c)) * scale + np.asarray(c))
        labels += [k] * n_per
    return np.concatenate(pts).astype(np.float32), np.array(labels)


# ---------------------------------------------------------------- k-means

def test_kmeans_recovers_blobs():
    x, labels = blobs()
    cs = KMeansClustering(k=3, seed=1).apply_to(x)
    # each true cluster maps to exactly one k-means cluster
    mapping = {}
    for k in range(3):
        assigned = cs.assignments[labels == k]
        vals, counts = np.unique(assigned, return_counts=True)
        assert counts.max() / counts.sum() > 0.95
        mapping[k] = vals[counts.argmax()]
    assert len(set(mapping.values())) == 3
    assert cs.inertia < 200.0


def test_kmeans_nearest_cluster_and_members():
    x, _ = blobs()
    cs = KMeansClustering(k=3, seed=1).apply_to(x)
    c = cs.nearest_cluster([10, 10])
    center = cs.centers[c]
    assert np.linalg.norm(center - [10, 10]) < 1.0
    assert sum(len(cl.point_indices) for cl in cs.clusters) == len(x)


def test_kmeans_k_exceeds_points():
    with pytest.raises(ValueError):
        KMeansClustering(k=10).apply_to(np.zeros((3, 2), np.float32))


# ------------------------------------------------------------------ trees

def brute_knn(points, q, k):
    d = np.linalg.norm(points - q, axis=1)
    order = np.argsort(d)[:k]
    return list(order), d[order]


@pytest.mark.parametrize("tree_cls", [KDTree, VPTree])
def test_tree_knn_matches_bruteforce(tree_cls):
    rs = np.random.RandomState(3)
    pts = rs.rand(200, 4)
    tree = tree_cls(pts)
    for _ in range(10):
        q = rs.rand(4)
        got = tree.knn(q, 5)
        want_idx, want_d = brute_knn(pts, q, 5)
        assert [i for i, _ in got] == want_idx
        np.testing.assert_allclose([d for _, d in got], want_d, rtol=1e-9)


def test_kdtree_nn():
    pts = np.array([[0, 0], [1, 1], [5, 5]], float)
    idx, d = KDTree(pts).nn([0.9, 0.9])
    assert idx == 1 and d == pytest.approx(np.hypot(0.1, 0.1))


def test_quadtree_counts_and_com():
    rs = np.random.RandomState(0)
    pts = rs.rand(50, 2)
    qt = QuadTree.build(pts)
    assert qt.n_points == 50
    np.testing.assert_allclose(qt.com, pts.mean(0), atol=1e-9)


def test_quadtree_duplicate_points_no_infinite_recursion():
    pts = np.array([[0.5, 0.5]] * 5 + [[0.1, 0.1]])
    qt = QuadTree.build(pts)
    assert qt.n_points == 6


def test_sptree_3d_and_forces():
    rs = np.random.RandomState(1)
    pts = rs.randn(60, 3)
    sp = SpTree.build(pts)
    assert sp.n_points == 60
    np.testing.assert_allclose(sp.com, pts.mean(0), atol=1e-9)
    # theta=0 (always recurse) must equal exact repulsion
    target = pts[0]
    f = np.zeros(3)
    z = sp.compute_non_edge_forces(target, 0.0, f)
    diff = target[None, :] - pts[1:]
    q = 1.0 / (1.0 + (diff ** 2).sum(1))
    z_exact = q.sum()
    f_exact = ((q ** 2)[:, None] * diff).sum(0)
    assert z == pytest.approx(z_exact, rel=1e-9)
    np.testing.assert_allclose(f, f_exact, rtol=1e-9)


# ------------------------------------------------------------------ t-SNE

def separation_score(emb, labels):
    """mean inter-class dist / mean intra-class dist."""
    intra, inter = [], []
    for i in range(0, len(emb), 7):
        for j in range(i + 1, len(emb), 7):
            d = np.linalg.norm(emb[i] - emb[j])
            (intra if labels[i] == labels[j] else inter).append(d)
    return np.mean(inter) / np.mean(intra)


def test_exact_tsne_separates_blobs():
    x, labels = blobs(n_per=30, scale=0.3)
    ts = Tsne(perplexity=10, n_iter=300, learning_rate=100, seed=2)
    emb = ts.fit_transform(x)
    assert emb.shape == (90, 2)
    assert np.isfinite(ts.kl_divergence_)
    assert separation_score(emb, labels) > 2.0


def test_barnes_hut_tsne_separates_blobs():
    x, labels = blobs(n_per=25, scale=0.3)
    ts = BarnesHutTsne(theta=0.5, perplexity=8, n_iter=150,
                       learning_rate=100, seed=2)
    emb = ts.fit_transform(x)
    assert emb.shape == (75, 2)
    assert separation_score(emb, labels) > 2.0


def test_barnes_hut_theta0_close_to_exact_gradient():
    x, _ = blobs(n_per=10, scale=0.3, seed=5)
    P = np.full((30, 30), 1.0 / (30 * 29))
    np.fill_diagonal(P, 0)
    rs = np.random.RandomState(0)
    y = rs.randn(30, 2) * 0.1
    bh = BarnesHutTsne(theta=0.0, n_iter=1)
    g_bh = bh._gradient(P, y)
    # exact gradient
    d2 = ((y[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    num = 1.0 / (1.0 + d2)
    np.fill_diagonal(num, 0)
    Q = np.maximum(num / num.sum(), 1e-12)
    PQ = (P - Q) * num
    g_exact = 4.0 * (np.diag(PQ.sum(1)) - PQ) @ y
    np.testing.assert_allclose(g_bh, g_exact, atol=1e-6)
