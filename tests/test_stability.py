"""Training stability engine (docs/resilience.md "Stability"): device-side
non-finite step guard, dynamic loss scaling, divergence sentinel with
auto-rewind, and per-replica poison masking in the data-parallel masters.

Correctness oracles follow the repo's equivalence discipline: the guarded
healthy path must be BIT-IDENTICAL to the unguarded one, a guarded
poisoned step must be a bit-exact no-op, the wrapper's poison masking
must equal an explicit manual eviction of the same replica, and the sync
master's row masking must equal single-device training on the healthy
rows.  Every fault is driven deterministically by
``FaultInjector.poison_gradients`` (nan | inf | spike, at/until_step).
"""

import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import (
    NeuralNetConfiguration, TrainingStability,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.observability import (
    HealthEvaluator, HealthRule, get_flight_recorder, get_registry,
)
from deeplearning4j_tpu.observability.metrics import MetricsRegistry
from deeplearning4j_tpu.parallel import (
    DistributedNetwork, ElasticConfig, ElasticController,
    ParallelWrapper, SyncTrainingMaster,
)
from deeplearning4j_tpu.resilience import (
    CheckpointManager, FaultInjector, inject_faults, stability,
)

pytestmark = pytest.mark.stability


def make_net(seed=12345, updater="adam", lr=0.01, stab=None):
    b = (NeuralNetConfiguration.builder()
         .seed(seed)
         .updater(updater, learning_rate=lr))
    if stab is not None:
        b.training_stability(stab)
    conf = (b.list()
            .layer(DenseLayer(n_in=6, n_out=10, activation="tanh"))
            .layer(OutputLayer(n_in=10, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def make_batches(n_batches, batch_size, seed=0):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n_batches):
        x = rs.randn(batch_size, 6).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, batch_size)]
        out.append((x, y))
    return out


def params_equal(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree_util.tree_leaves(a),
                               jax.tree_util.tree_leaves(b)))


def all_finite_tree(tree):
    return all(bool(jnp.all(jnp.isfinite(l)))
               for l in jax.tree_util.tree_leaves(tree))


def counter_value(name, **labels):
    fam = get_registry().get(name)
    if fam is None:
        return 0.0
    total = 0.0
    for label_pairs, child in fam.samples():
        d = dict(label_pairs)
        if all(d.get(k) == v for k, v in labels.items()):
            total += child.value
    return total


def flight_events(kind, **attrs):
    out = []
    for ev in get_flight_recorder().events():
        if ev.kind != kind:
            continue
        if all(ev.attrs.get(k) == v for k, v in attrs.items()):
            out.append(ev)
    return out


# ------------------------------------------------------------ the step guard
def test_guarded_healthy_run_bit_identical_to_unguarded():
    """The guard must be free when nothing is poisoned: identical params
    after identical batches (the skip mask multiplies updates by 1.0 and
    the loss scale is 1 — both exact)."""
    batches = make_batches(8, 6, seed=1)
    plain = make_net().fit(batches)
    guarded = make_net(stab=TrainingStability(check_every=100)).fit(batches)
    assert params_equal(plain.params, guarded.params)


def test_poisoned_step_is_bitexact_noop():
    """One poisoned step: params, updater moments, and net state keep
    their exact pre-step values; the device counter records the skip; the
    unguarded contrast run is NaN from the same poison."""
    batches = make_batches(6, 6, seed=2)
    net = make_net(stab=TrainingStability(check_every=100))
    net.fit(batches[:3])
    before_p = jax.tree_util.tree_map(np.asarray, net.params)
    before_u = jax.tree_util.tree_map(
        np.asarray, {k: v for k, v in net.updater_state.items()
                     if k != stability.STATE_KEY})
    inj = FaultInjector(seed=3).poison_gradients("0", at_step=3,
                                                 until_step=4)
    with inject_faults(inj):
        net.fit([batches[3]])
    assert inj.injected[0]["kind"] == "worker_poisoned"
    assert params_equal(before_p, net.params)
    assert params_equal(before_u,
                        {k: v for k, v in net.updater_state.items()
                         if k != stability.STATE_KEY})
    stab = net.updater_state[stability.STATE_KEY]
    assert float(np.asarray(stab["nonfinite_total"])) == 1.0

    unguarded = make_net()
    inj2 = FaultInjector(seed=3).poison_gradients("0", at_step=3,
                                                  until_step=4)
    with inject_faults(inj2):
        unguarded.fit(batches[:4])
    assert not all_finite_tree(unguarded.params)


@pytest.mark.parametrize("mode", ["nan", "inf", "spike"])
def test_poison_modes(mode):
    """nan/inf poison non-finite steps (skipped); spike stays finite (the
    sentinel's domain) but every mode leaves guarded params finite."""
    net = make_net(stab=TrainingStability(check_every=100))
    inj = FaultInjector().poison_gradients("0", at_step=1, until_step=2,
                                           mode=mode)
    with inject_faults(inj):
        net.fit(make_batches(4, 6, seed=3))
    assert all_finite_tree(net.params)
    nf = float(np.asarray(
        net.updater_state[stability.STATE_KEY]["nonfinite_total"]))
    assert nf == (0.0 if mode == "spike" else 1.0)


def test_guarded_run_converges_to_no_fault_trajectory():
    """Acceptance (a): a guarded single-device run with a poisoned step
    skips it and converges back to the no-fault trajectory — and the skip
    flips VALUES, not the trace (zero recompiles at the poison step).
    Both runs train the same small problem to (near) convergence; one
    skipped update early on must wash out."""
    batches = make_batches(10, 8, seed=4) * 15       # 150 steps
    clean = make_net(stab=TrainingStability(check_every=100)).fit(batches)
    poisoned = make_net(stab=TrainingStability(check_every=100))
    poisoned.fit(batches[:3])
    compiles0 = counter_value("dl4j_compiles_total")
    recompiles0 = counter_value("dl4j_recompiles_total")
    inj = FaultInjector().poison_gradients("0", at_step=3, until_step=4)
    with inject_faults(inj):
        poisoned.fit(batches[3:])
    assert counter_value("dl4j_compiles_total") == compiles0
    assert counter_value("dl4j_recompiles_total") == recompiles0
    # same minimum: compare the trained functions on held-out data and
    # the converged parameter vectors
    probe = make_batches(1, 16, seed=99)[0][0]
    np.testing.assert_allclose(np.asarray(poisoned.output(probe)),
                               np.asarray(clean.output(probe)), atol=0.02)
    np.testing.assert_allclose(poisoned.params_to_vector(),
                               clean.params_to_vector(), atol=0.05)


# ------------------------------------------------------------- loss scaling
def test_static_loss_scaling_is_exact():
    """Power-of-two scales multiply/divide exactly: a statically scaled
    run is bit-identical to the unscaled one on healthy data."""
    batches = make_batches(6, 6, seed=5)
    plain = make_net().fit(batches)
    scaled = make_net(stab=TrainingStability(
        loss_scaling="static", loss_scale=2.0 ** 10,
        check_every=100)).fit(batches)
    assert params_equal(plain.params, scaled.params)
    st = scaled.updater_state[stability.STATE_KEY]
    assert float(np.asarray(st["loss_scale"])) == 2.0 ** 10


def test_dynamic_loss_scale_grows_and_halves():
    stab = TrainingStability(loss_scaling="dynamic", loss_scale=2.0 ** 8,
                             loss_scale_growth_interval=3, check_every=100)
    net = make_net(stab=stab)
    net.fit(make_batches(7, 6, seed=6))      # 7 finite steps: 2 growths
    scale = float(np.asarray(
        net.updater_state[stability.STATE_KEY]["loss_scale"]))
    assert scale == 2.0 ** 10
    inj = FaultInjector().poison_gradients("0", at_step=7, until_step=8,
                                           mode="inf")
    with inject_faults(inj):
        net.fit(make_batches(1, 6, seed=7))
    scale = float(np.asarray(
        net.updater_state[stability.STATE_KEY]["loss_scale"]))
    assert scale == 2.0 ** 9                 # halved on overflow


def test_scale_state_checkpoints_and_resumes():
    """The scale state rides in the updater-state pytree, so a resumed
    run continues with the exact scale it crashed with."""
    stab = TrainingStability(loss_scaling="dynamic", loss_scale=2.0 ** 8,
                             loss_scale_growth_interval=2, check_every=100)
    with tempfile.TemporaryDirectory() as tmp:
        cm = CheckpointManager(tmp, save_every_steps=2, async_save=False)
        net = make_net(stab=stab)
        net.fit(make_batches(6, 6, seed=8), checkpoint_manager=cm)
        want = jax.tree_util.tree_map(
            np.asarray, net.updater_state[stability.STATE_KEY])
        # the save landed at step 6 (boundary save); restore into a fresh
        # net and compare the whole stability subtree
        fresh = make_net(stab=stab)
        cm2 = CheckpointManager(tmp, async_save=False)
        cm2.restore(fresh)
        got = jax.tree_util.tree_map(
            np.asarray, fresh.updater_state[stability.STATE_KEY])
        assert params_equal(want, got)
        assert fresh.iteration == net.iteration
        cm.close()
        cm2.close()


# ------------------------------------------------------- divergence sentinel
def test_sentinel_escalates_backoff_then_rewind_and_resumes_past_failure():
    """Acceptance (c): sustained poison drives skip -> LR backoff ->
    auto-rewind to the last good checkpoint; once the poison clears the
    run resumes and trains PAST the original failure step with finite
    params — with zero recompiles throughout."""
    # poison spans iterations 8..19: long enough that the escalation
    # ladder (backoff at the 1st hot check, rewind at the next
    # non-cooldown check) fires while the fault is live; the post-rewind
    # cooldown (6 checks = 12 steps) lets the rewound run march through
    # the poisoned region on guard-skips alone and come out healthy
    stab = TrainingStability(check_every=2, nonfinite_streak=2,
                             rewind_cooldown_checks=6)
    batches = make_batches(40, 8, seed=9)
    net = make_net(stab=stab)
    with tempfile.TemporaryDirectory() as tmp:
        cm = CheckpointManager(tmp, keep=4, save_every_steps=4,
                               async_save=False)
        net.fit(batches[:8], checkpoint_manager=cm)     # healthy prefix
        compiles0 = counter_value("dl4j_compiles_total")
        inj = FaultInjector().poison_gradients("0", at_step=8,
                                               until_step=20)
        with inject_faults(inj):
            net.fit(batches[8:], checkpoint_manager=cm)
        cm.close()
    assert counter_value("dl4j_compiles_total") == compiles0
    assert flight_events("divergence_backoff", component="MultiLayerNetwork")
    rewinds = flight_events("divergence_rewind",
                            component="MultiLayerNetwork")
    assert rewinds
    assert rewinds[0].attrs["to_step"] <= 8
    assert net.iteration > 20          # resumed past the failure region
    assert all_finite_tree(net.params)
    assert all_finite_tree(
        {k: v for k, v in net.updater_state.items()
         if k != stability.STATE_KEY})


def test_rewind_without_checkpoint_manager_downgrades_to_backoff():
    stab = TrainingStability(check_every=1, nonfinite_streak=1,
                             rewind_cooldown_checks=1, lr_backoff=0.5)
    net = make_net(stab=stab)
    inj = FaultInjector().poison_gradients("0", at_step=0)
    with inject_faults(inj):
        net.fit(make_batches(6, 6, seed=10))
    lr_scale = float(np.asarray(
        net.updater_state[stability.STATE_KEY]["lr_scale"]))
    assert lr_scale < 1.0              # backoffs landed in the state
    assert all_finite_tree(net.params)


def test_resumed_run_does_not_recount_checkpointed_nonfinite():
    """A checkpointed nonfinite_total restored by auto-resume is history:
    the fresh runtime must baseline on it, not re-publish it as a new
    delta (which would double-count the metric and could trip a spurious
    backoff on a healthy resumed run)."""
    stab = TrainingStability(check_every=1, nonfinite_streak=1)
    with tempfile.TemporaryDirectory() as tmp:
        cm = CheckpointManager(tmp, save_every_steps=2, async_save=False)
        net = make_net(stab=stab)
        inj = FaultInjector().poison_gradients("0", at_step=1, until_step=2)
        with inject_faults(inj):
            net.fit(make_batches(4, 6, seed=20), checkpoint_manager=cm)
        total0 = counter_value("dl4j_nonfinite_steps_total",
                               component="MultiLayerNetwork")
        backoffs0 = counter_value("dl4j_divergence_backoffs_total",
                                  component="MultiLayerNetwork")
        # "new process": fresh facade + fresh runtime, same checkpoint
        # dir, same stream — resume skips the consumed prefix
        net2 = make_net(stab=stab)
        net2.fit(make_batches(8, 6, seed=21),
                 checkpoint_manager=CheckpointManager(tmp, async_save=False))
        assert net2.iteration > 4          # resumed ahead, trained on
        assert counter_value("dl4j_nonfinite_steps_total",
                             component="MultiLayerNetwork") == total0
        assert counter_value("dl4j_divergence_backoffs_total",
                             component="MultiLayerNetwork") == backoffs0
        cm.close()


def test_wrapper_without_cm_keeps_backing_off_instead_of_stalling():
    """A master with no CheckpointManager downgrades every rewind verdict
    to a further LR backoff (mirrors poll_net) — sustained divergence
    must keep being mitigated, not silently dropped after level 1."""
    K = 4
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    stab = TrainingStability(check_every=1, nonfinite_streak=1,
                             rewind_cooldown_checks=1)
    net = make_net(stab=stab)
    pw = ParallelWrapper(net, workers=K, averaging_frequency=1, mesh=mesh)
    backoffs0 = counter_value("dl4j_divergence_backoffs_total",
                              component="parallel_wrapper")
    inj = FaultInjector()
    for k in range(K):
        inj.poison_gradients(str(k), at_step=0)   # every replica: nf loss
    with inject_faults(inj):
        pw.fit(iter(DataSet(x, y) for x, y in make_batches(K * 8, 4,
                                                           seed=22)))
    assert counter_value("dl4j_divergence_backoffs_total",
                         component="parallel_wrapper") >= backoffs0 + 2
    assert all_finite_tree(net.params)


def test_spike_mode_trips_the_sentinel():
    """A finite loss spike (poison mode 'spike') must escalate through
    the spike-strike path, not the non-finite path."""
    stab = TrainingStability(check_every=1, spike_factor=5.0,
                             spike_patience=2)
    net = make_net(stab=stab)
    net.fit(make_batches(6, 6, seed=11))   # establish the loss baseline
    inj = FaultInjector().poison_gradients("0", at_step=6, mode="spike")
    with inject_faults(inj):
        net.fit(make_batches(6, 6, seed=12))
    assert flight_events("divergence_backoff",
                         component="MultiLayerNetwork")


# ------------------------------------------- per-replica poisoning (wrapper)
def test_wrapper_poison_masking_equals_manual_eviction():
    """Acceptance (b, wrapper): the healthy replicas' window average with
    replica 1 poisoned is bit-exact the average with replica 1 manually
    evicted — the poison mask IS the elastic [K] weight mask."""
    K = 4
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    stab = TrainingStability(check_every=100)
    data = make_batches(K * 6, 4, seed=13)
    ds = [DataSet(x, y) for x, y in data]

    evicted = make_net(stab=stab)
    ctrl = ElasticController("parallel_wrapper", [str(k) for k in range(K)],
                             config=ElasticConfig())
    assert ctrl.evict("1", "manual", step=0)
    ParallelWrapper(evicted, workers=K, averaging_frequency=1,
                    mesh=mesh, elastic=ctrl).fit(iter(ds))

    poisoned = make_net(stab=stab)
    inj = FaultInjector().poison_gradients("1", at_step=0)
    with inject_faults(inj):
        ParallelWrapper(poisoned, workers=K, averaging_frequency=1,
                        mesh=mesh).fit(iter(ds))
    assert params_equal(evicted.params, poisoned.params)
    assert all_finite_tree(poisoned.params)


def test_wrapper_repeat_offender_evicted_as_poisoned():
    """Acceptance (b): a repeat offender is handed to the elastic layer
    as eviction reason "poisoned", named in metrics + flight events."""
    K = 4
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    stab = TrainingStability(check_every=1, poison_evict_after=2)
    net = make_net(stab=stab)
    pw = ParallelWrapper(net, workers=K, averaging_frequency=1, mesh=mesh,
                         elastic=ElasticConfig())
    ev0 = counter_value("dl4j_elastic_evictions_total",
                        component="parallel_wrapper", worker="1",
                        reason="poisoned")
    recompiles0 = counter_value("dl4j_recompiles_total")
    inj = FaultInjector().poison_gradients("1", at_step=0)
    with inject_faults(inj):
        pw.fit(iter(DataSet(x, y) for x, y in make_batches(K * 8, 4,
                                                           seed=14)))
    # poison masking + eviction flip VALUES, not the pytree: zero
    # steady-state recompiles while the mesh degrades
    assert counter_value("dl4j_recompiles_total") == recompiles0
    assert "1" in pw.elastic.evicted_workers
    assert pw.elastic.summary()["evicted"]["1"]["reason"] == "poisoned"
    assert counter_value("dl4j_elastic_evictions_total",
                         component="parallel_wrapper", worker="1",
                         reason="poisoned") == ev0 + 1
    assert flight_events("elastic_eviction", component="parallel_wrapper",
                         worker="1", reason="poisoned")
    assert flight_events("replica_poisoned", component="parallel_wrapper",
                         worker="1")
    assert counter_value("dl4j_poisoned_replica_windows_total",
                         component="parallel_wrapper", worker="1") > 0


def test_wrapper_poison_clears_and_readmits():
    """Poison with until_step: the replica is evicted while poisoned and
    probationally re-admitted once the injector state clears."""
    K = 4
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    stab = TrainingStability(check_every=1, poison_evict_after=1)
    net = make_net(stab=stab)
    pw = ParallelWrapper(
        net, workers=K, averaging_frequency=1, mesh=mesh,
        elastic=ElasticConfig(readmit_after_windows=2))
    inj = FaultInjector().poison_gradients("1", at_step=0, until_step=3)
    with inject_faults(inj):
        pw.fit(iter(DataSet(x, y) for x, y in make_batches(K * 10, 4,
                                                           seed=15)))
    assert pw.elastic.evicted_workers == []
    assert flight_events("elastic_readmission",
                         component="parallel_wrapper", worker="1")
    assert all_finite_tree(net.params)


# --------------------------------------------- per-replica poisoning (sync)
def test_sync_master_poison_equals_healthy_rows_math():
    """Acceptance (b, sync master): with one data slot poisoned, the
    global gradient equals single-device training on the healthy rows
    (the poisoned rows are zeroed pre-forward and renormalized out of the
    masked loss mean)."""
    K = 4
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    stab = TrainingStability(check_every=100)
    rs = np.random.RandomState(17)
    x = rs.randn(32, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 32)]

    net = make_net(stab=stab)
    master = SyncTrainingMaster(mesh=mesh)
    inj = FaultInjector(seed=4).poison_gradients("d2", at_step=0)
    with inject_faults(inj):
        DistributedNetwork(net, master).fit(
            ListDataSetIterator(DataSet(x, y), 8))
    assert all_finite_tree(net.params)

    ref = make_net(stab=stab)
    keep = np.r_[0:4, 6:8]                  # slot 2 owns rows 4:6 of 8
    for i in range(4):
        ref.fit(x[i * 8:(i + 1) * 8][keep], y[i * 8:(i + 1) * 8][keep])
    np.testing.assert_allclose(net.params_to_vector(),
                               ref.params_to_vector(), rtol=2e-5,
                               atol=1e-6)


def test_sync_master_repeat_offender_evicted_as_poisoned():
    K = 4
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    stab = TrainingStability(check_every=1, poison_evict_after=2)
    net = make_net(stab=stab)
    master = SyncTrainingMaster(mesh=mesh, elastic=ElasticConfig())
    victim = master.elastic.workers[1]
    recompiles0 = counter_value("dl4j_recompiles_total")
    inj = FaultInjector().poison_gradients(victim, at_step=0)
    with inject_faults(inj):
        DistributedNetwork(net, master).fit(
            ListDataSetIterator(
                DataSet(*map(np.concatenate,
                             zip(*[(x, y) for x, y in
                                   make_batches(10, 8, seed=18)]))), 8))
    assert master.elastic.summary()["evicted"][victim]["reason"] == \
        "poisoned"
    assert counter_value("dl4j_recompiles_total") == recompiles0
    assert all_finite_tree(net.params)


# ------------------------------------------------------- health + earlystop
def test_stability_health_rules():
    reg = MetricsRegistry()
    rt = stability.StabilityRuntime(
        "hr", TrainingStability(check_every=1), registry=reg)
    rules = [HealthRule("nf_budget", "max_nonfinite_steps", 2),
             HealthRule("rw_budget", "max_divergence_rewinds", 0)]
    ev = HealthEvaluator(rules, component="hr_test", registry=reg)
    assert ev.evaluate().healthy
    rt._publish(3.0, 1.0)                  # 3 non-finite steps harvested
    verdict = ev.evaluate()
    assert not verdict.healthy
    assert verdict.failing[0]["observed"] == 3.0


def test_invalid_score_condition_watches_nonfinite_counter():
    """Satellite: early stopping catches NaN through the device-side
    counter even though the guard keeps the score finite."""
    from deeplearning4j_tpu.earlystopping import (
        InvalidScoreIterationTerminationCondition,
    )

    cond = InvalidScoreIterationTerminationCondition()
    cond.initialize()
    assert not cond.terminate(0.5)
    net = make_net(stab=TrainingStability(check_every=1))
    inj = FaultInjector().poison_gradients("0", at_step=1, until_step=2)
    with inject_faults(inj):
        net.fit(make_batches(3, 6, seed=19))
    # the guarded score is finite, but the counter advanced
    assert np.isfinite(net.score_value)
    assert cond.terminate(net.score_value)
    # classic path still works
    cond2 = InvalidScoreIterationTerminationCondition()
    cond2.initialize()
    assert cond2.terminate(float("nan"))
    # component filter: another component's skipped step must not
    # terminate a run watching only its own counter children
    cond3 = InvalidScoreIterationTerminationCondition(
        component="ComputationGraph")
    cond3.initialize()
    net2 = make_net(stab=TrainingStability(check_every=1))
    inj2 = FaultInjector().poison_gradients("0", at_step=1, until_step=2)
    with inject_faults(inj2):
        net2.fit(make_batches(3, 6, seed=23))   # MultiLayerNetwork bump
    assert not cond3.terminate(0.5)


# ----------------------------------------------------------- pipeline + conf
def test_pipeline_gradient_normalization_downgrade_is_loud():
    """Satellite: the sharded-fast-path downgrade emits a one-shot
    RuntimeWarning + a flight event naming gradient_normalization."""
    from deeplearning4j_tpu.parallel.pipeline import (
        PipelineParallelTrainingMaster,
    )

    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater("sgd", learning_rate=0.1)
            .gradient_normalization("clip_l2_per_layer", 1.0)
            .list()
            .layer(DenseLayer(n_in=6, n_out=8, activation="relu"))
            .layer(DenseLayer(n_in=8, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    master = PipelineParallelTrainingMaster(
        n_stages=2, n_microbatches=2, mode="compiled",
        devices=jax.devices()[:2])
    with pytest.warns(RuntimeWarning, match="fast path DISABLED"):
        master._build(net)
    evs = flight_events("pipeline_fast_path_downgrade",
                        component="pipeline_master")
    assert evs and "gradient_normalization='clip_l2_per_layer'" in \
        evs[-1].attrs["reasons"][0]


def test_training_stability_conf_validation_and_serde():
    with pytest.raises(ValueError, match="loss_scaling"):
        TrainingStability(loss_scaling="bogus")
    with pytest.raises(ValueError, match="lr_backoff"):
        TrainingStability(lr_backoff=1.5)
    with pytest.raises(ValueError, match="takes no kwargs"):
        NeuralNetConfiguration.builder().training_stability(
            False, check_every=3)
    stab = TrainingStability(loss_scaling="dynamic", check_every=7)
    conf = (NeuralNetConfiguration.builder().training_stability(stab)
            .list()
            .layer(DenseLayer(n_in=4, n_out=4, activation="relu"))
            .layer(OutputLayer(n_in=4, n_out=2, loss="mcxent",
                               activation="softmax"))
            .build())
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

    back = MultiLayerConfiguration.from_json(conf.to_json())
    assert back.stability == stab
