"""Sharded checkpoint/resume: per-host shard files, any-mesh restore,
resume-equivalence (train A→B straight == train A, checkpoint, restore,
train B).  Reference analog: ModelSerializer.java:32-95 scaled to a mesh.
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel import (
    DistributedNetwork, SyncTrainingMaster, restore_checkpoint,
    save_checkpoint,
)


def _net(seed=21):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(seed)
         .updater("adam", learning_rate=0.05).list()
         .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
         .layer(OutputLayer(n_in=16, n_out=4)).build())
    ).init()


def _batches(rs, n=64):
    x = rs.rand(n, 8).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, n)]
    return x, y


def test_roundtrip_plain(tmp_path):
    net = _net()
    rs = np.random.RandomState(0)
    x, y = _batches(rs)
    net.fit(x, y)
    save_checkpoint(str(tmp_path), net)
    net2 = _net(seed=99)
    restore_checkpoint(str(tmp_path), net2)
    assert net2.iteration == net.iteration
    assert np.allclose(net.params_to_vector(), net2.params_to_vector())
    xq = rs.rand(4, 8).astype(np.float32)
    assert np.allclose(np.asarray(net.output(xq)), np.asarray(net2.output(xq)))


def test_dp_train_checkpoint_resume_equivalence(tmp_path):
    """The verdict's oracle: DP-train -> checkpoint -> restore -> continue
    must equal uninterrupted DP training (params AND updater state AND the
    RNG stream survive)."""
    rs = np.random.RandomState(1)
    x, y = _batches(rs, 64)
    mesh = backend.default_mesh()

    # uninterrupted: 4 batches
    ref = _net()
    DistributedNetwork(ref, SyncTrainingMaster(mesh=mesh)).fit(
        ListDataSetIterator(DataSet(x, y), 16))

    # interrupted after 2 batches
    a = _net()
    DistributedNetwork(a, SyncTrainingMaster(mesh=mesh)).fit(
        ListDataSetIterator(DataSet(x[:32], y[:32]), 16))
    save_checkpoint(str(tmp_path), a)

    b = _net(seed=1234)  # fresh facade, wrong seed — restore must fix it
    restore_checkpoint(str(tmp_path), b, mesh=mesh)
    assert b.iteration == 2
    DistributedNetwork(b, SyncTrainingMaster(mesh=mesh)).fit(
        ListDataSetIterator(DataSet(x[32:], y[32:]), 16))

    np.testing.assert_allclose(ref.params_to_vector(), b.params_to_vector(),
                               atol=1e-6)


def test_sharded_leaves_saved_per_shard(tmp_path):
    """Mesh-sharded leaves are written as genuine shards (no host gather of
    the global array) and restore onto a mesh with the saved spec."""
    mesh = backend.default_mesh()
    axis = backend.AXIS_DATA
    n_dev = mesh.shape[axis]
    arr = jax.device_put(
        np.arange(n_dev * 4 * 3, dtype=np.float32).reshape(n_dev * 4, 3),
        NamedSharding(mesh, P(axis)))

    class Fake:
        params = {"layer_0": {"W": arr}}
        updater_state = {}
        net_state = {}
        iteration = 7
        _keys = None

    save_checkpoint(str(tmp_path), Fake())
    import json, os
    man = json.load(open(os.path.join(tmp_path, "manifest-0.json")))
    entry = man["leaves"]["params/layer_0/W"]
    assert len(entry["shards"]) == n_dev          # one piece per device
    assert entry["spec"] == [axis]
    params, _, _, it = restore_checkpoint(str(tmp_path), mesh=mesh)
    got = params["layer_0"]["W"]
    assert got.sharding.spec == P(axis)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(arr))
    assert it == 7


def test_replicated_leaves_stored_once(tmp_path):
    mesh = backend.default_mesh()
    arr = jax.device_put(np.ones((4, 4), np.float32),
                         NamedSharding(mesh, P()))

    class Fake:
        params = {"l": {"W": arr}}
        updater_state = {}
        net_state = {}
        iteration = 0
        _keys = None

    save_checkpoint(str(tmp_path), Fake())
    import json, os
    man = json.load(open(os.path.join(tmp_path, "manifest-0.json")))
    assert len(man["leaves"]["params/l/W"]["shards"]) == 1


def test_restore_missing_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(str(tmp_path / "nope"))


# ------------------------------------------------- topology-portable restore
def _forbid_full_gather(monkeypatch):
    """The resharded-restore acceptance: with mesh= given, the full-leaf
    host assembly must never run (docs/resilience.md resharding
    semantics)."""
    from deeplearning4j_tpu.parallel import checkpoint as cp

    def boom(*a, **k):
        raise AssertionError("full gather-to-host on the resharded path")

    monkeypatch.setattr(cp, "_assemble", boom)


def _save_2x2_layout(tmp_path):
    """A (K=4, 2x2 data x model) checkpoint with every sharding flavor:
    data-sharded, model-sharded, and replicated leaves."""
    devs = jax.devices()
    mesh = backend.default_mesh(data=2, model=2, devices=devs[:4])
    trees = {
        "W": jax.device_put(
            np.arange(16 * 6, dtype=np.float32).reshape(16, 6),
            NamedSharding(mesh, P("data"))),
        "V": jax.device_put(
            np.arange(8 * 8, dtype=np.float32).reshape(8, 8),
            NamedSharding(mesh, P(None, "model"))),
        "b": jax.device_put(np.arange(8, dtype=np.float32),
                            NamedSharding(mesh, P())),
    }

    class Fake:
        params = {"l": trees}
        updater_state = {}
        net_state = {}
        iteration = 5
        _keys = None

    save_checkpoint(str(tmp_path), Fake())
    return {k: np.asarray(v) for k, v in trees.items()}


@pytest.mark.elastic
def test_resharded_restore_matrix(tmp_path, monkeypatch):
    """Save on (K=4, 2x2 layout); resume on K=2, K=8, 1x8, and a single
    device — bit-identical to the gather-to-host reference path, with the
    full-leaf gather forbidden."""
    ref = _save_2x2_layout(tmp_path)
    # reference path: explicit gather-to-host (no mesh)
    host_params, _, _, _ = restore_checkpoint(str(tmp_path))
    for k, v in ref.items():
        np.testing.assert_array_equal(np.asarray(host_params["l"][k]), v)

    devs = jax.devices()
    _forbid_full_gather(monkeypatch)
    for target in (
            backend.default_mesh(data=2, devices=devs[:2]),        # K=4->2
            backend.default_mesh(data=8, devices=devs),            # K=4->8
            backend.default_mesh(data=1, model=8, devices=devs),   # 1x8
            backend.default_mesh(data=1, devices=devs[:1])):       # single
        params, _, _, it = restore_checkpoint(str(tmp_path), mesh=target)
        assert it == 5
        for k, v in ref.items():
            got = params["l"][k]
            assert isinstance(got.sharding, NamedSharding)
            np.testing.assert_array_equal(np.asarray(got), v)


@pytest.mark.elastic
def test_resharded_restore_reads_each_member_once(tmp_path, monkeypatch):
    """A target mesh finer than the saver must not re-read saved npz
    members once per intersecting target shard (NpzFile decompresses the
    whole member on every access): restoring a 2x2-saved checkpoint on
    K=8 reads each shard member exactly once."""
    _save_2x2_layout(tmp_path)
    reads = []
    orig = np.lib.npyio.NpzFile.__getitem__

    def counting(self, key):
        reads.append(key)
        return orig(self, key)

    monkeypatch.setattr(np.lib.npyio.NpzFile, "__getitem__", counting)
    restore_checkpoint(str(tmp_path),
                       mesh=backend.default_mesh(data=8))
    data_members = [k for k in reads if "@" in k]
    assert data_members, "no shard members read"
    assert len(data_members) == len(set(data_members)), (
        f"members re-read: {sorted(set(k for k in data_members if data_members.count(k) > 1))}")


@pytest.mark.elastic
def test_resharded_2x4_to_1x8_is_device_side(tmp_path, monkeypatch):
    """Same device count (2x4 -> 1x8): the saved shards load in the SAVED
    layout and ONE device-side resharding (collective permutes) lands the
    target layout — counted via the reshard seam, full gather forbidden."""
    from deeplearning4j_tpu.parallel import checkpoint as cp

    devs = jax.devices()
    mesh_save = backend.default_mesh(data=2, model=4, devices=devs)
    W = jax.device_put(
        np.arange(16 * 8, dtype=np.float32).reshape(16, 8),
        NamedSharding(mesh_save, P("data", "model")))

    class Fake:
        params = {"l": {"W": W}}
        updater_state = {}
        net_state = {}
        iteration = 1
        _keys = None

    save_checkpoint(str(tmp_path), Fake())

    calls = []
    orig = cp._reshard_on_device
    monkeypatch.setattr(cp, "_reshard_on_device",
                        lambda a, t: calls.append(1) or orig(a, t))
    _forbid_full_gather(monkeypatch)
    target = backend.default_mesh(data=1, model=8, devices=devs)
    params, _, _, _ = restore_checkpoint(str(tmp_path), mesh=target)
    got = params["l"]["W"]
    assert len(calls) == 1
    assert got.sharding.spec == P("data", "model")
    np.testing.assert_array_equal(
        np.asarray(got), np.arange(16 * 8, dtype=np.float32).reshape(16, 8))
    # same-topology restore takes direct placement, not the permute
    calls.clear()
    restore_checkpoint(str(tmp_path), mesh=mesh_save)
    assert not calls


@pytest.mark.elastic
def test_manager_resume_onto_different_topology(tmp_path):
    """CheckpointManager end to end: train + save under one mesh, resume a
    fresh facade on a smaller mesh — params/updater/iteration/RNG all
    bit-identical to a same-mesh restore."""
    from deeplearning4j_tpu.resilience import CheckpointManager

    mesh_a = backend.default_mesh()                       # 8-way data
    net = _net()
    rs = np.random.RandomState(3)
    x, y = _batches(rs, 32)
    DistributedNetwork(net, SyncTrainingMaster(mesh=mesh_a)).fit(
        ListDataSetIterator(DataSet(x, y), 16))
    cm = CheckpointManager(str(tmp_path), async_save=False)
    cm.save(net)

    mesh_b = backend.default_mesh(data=2, devices=jax.devices()[:2])
    fresh = _net(seed=777)
    assert cm.resume(fresh, mesh=mesh_b) == net.iteration
    np.testing.assert_array_equal(np.asarray(fresh.params_to_vector()),
                                  np.asarray(net.params_to_vector()))
    # and training continues on the new topology
    DistributedNetwork(fresh, SyncTrainingMaster(mesh=mesh_b)).fit(
        ListDataSetIterator(DataSet(x, y), 16))
    assert fresh.iteration == net.iteration + 2


def test_multi_host_manifests_merge(tmp_path):
    """A cross-host-sharded leaf: each process's manifest lists only its own
    shards (process-qualified keys); restore must union them."""
    import json, os

    full = np.arange(8 * 2, dtype=np.float32).reshape(8, 2)
    for proc, rows in ((0, (0, 4)), (1, (4, 8))):
        man = {"leaves": {"params/l/W": {
            "shape": [8, 2], "dtype": "float32", "spec": ["data"],
            "shards": [{"key": f"p{proc}/params/l/W@0",
                        "index": [[rows[0], rows[1]], [0, 2]]}],
        }}}
        with open(tmp_path / f"manifest-{proc}.json", "w") as f:
            json.dump(man, f)
        np.savez(tmp_path / f"shards-{proc}.npz",
                 **{f"p{proc}/params/l/W@0": full[rows[0]:rows[1]]})
    with open(tmp_path / "checkpoint.json", "w") as f:
        json.dump({"format_version": 1, "iteration": 3, "processes": 2}, f)
    params, _, _, it = restore_checkpoint(str(tmp_path))
    assert it == 3
    np.testing.assert_array_equal(np.asarray(params["l"]["W"]), full)
