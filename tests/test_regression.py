"""Serialization backward-compat regression (reference RegressionTest050:
checkpoints committed by an earlier build must keep restoring exactly).

Fixtures live in tests/regression_fixtures/ (see make_regression_fixtures.py);
regenerate ONLY on a deliberate format-version bump.
"""

import json
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu.models.serialization import (
    restore_computation_graph, restore_multi_layer_network,
)

FIXTURES = Path(__file__).parent / "regression_fixtures"
CASES = ["mlp", "cnn", "lstm", "transformer", "transformer_v2"]


@pytest.mark.parametrize("name", CASES)
def test_restore_committed_checkpoint(name):
    net = restore_multi_layer_network(FIXTURES / f"{name}.zip")
    x = np.load(FIXTURES / f"{name}_input.npy")
    expected = np.load(FIXTURES / f"{name}_expected.npy")
    out = np.asarray(net.output(x))
    # tolerance covers TPU-vs-CPU float differences, not format drift
    np.testing.assert_allclose(out, expected, rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("name", CASES)
def test_restored_checkpoint_resumes_training(name):
    net = restore_multi_layer_network(FIXTURES / f"{name}.zip")
    x = np.load(FIXTURES / f"{name}_input.npy")
    meta = json.loads((FIXTURES / "meta.json").read_text())
    if name == "mlp":
        y = np.eye(3, dtype=np.float32)[np.zeros(len(x), int)]
    elif name == "cnn":
        y = np.eye(2, dtype=np.float32)[np.zeros(len(x), int)]
    elif name.startswith("transformer"):
        y = np.eye(7, dtype=np.float32)[np.zeros((x.shape[0], x.shape[1]), int)]
    else:
        y = np.eye(4, dtype=np.float32)[np.zeros((x.shape[0], x.shape[1]), int)]
    net.fit(x, y)  # updater state restored -> continues without error
    assert np.isfinite(net.score_value)
    assert meta[name]["iterations"] == 3


def test_restore_committed_graph_checkpoint():
    """CG zip layout (DAG config + per-vertex params) stays restorable."""
    cg = restore_computation_graph(FIXTURES / "graph.zip")
    xa = np.load(FIXTURES / "graph_input_a.npy")
    xb = np.load(FIXTURES / "graph_input_b.npy")
    expected = np.load(FIXTURES / "graph_expected.npy")
    out = np.asarray(cg.output({"a": xa, "b": xb}))
    np.testing.assert_allclose(out, expected, rtol=1e-3, atol=1e-4)
    # resumes with restored updater state
    y = np.eye(2, dtype=np.float32)[np.zeros(len(xa), int)]
    cg.fit({"a": xa, "b": xb}, y)
    assert np.isfinite(cg.score_value)


def test_updater_state_round_trips(tmp_path):
    # a freshly saved model reloads with identical updater state leaves
    from deeplearning4j_tpu.models.serialization import write_model
    from tests.make_regression_fixtures import make_mlp

    net = make_mlp()
    x = np.random.RandomState(0).rand(4, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.zeros(4, int)]
    net.fit(x, y)
    write_model(net, tmp_path / "m.zip")
    back = restore_multi_layer_network(tmp_path / "m.zip")
    for slot, tree in net.updater_state.items():
        for ln, lp in tree.items():
            for pn, arr in lp.items():
                np.testing.assert_allclose(
                    np.asarray(arr), np.asarray(back.updater_state[slot][ln][pn]),
                    atol=1e-6)
