"""Compiled pipeline schedule: one XLA program (shard_map + scan + ppermute)
for homogeneous-block nets; equivalence to serial training is the oracle
(the reference's distributed-vs-local pattern, SURVEY.md §4).
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel import (
    DistributedNetwork, PipelineParallelTrainingMaster,
)
from deeplearning4j_tpu.parallel.pipeline import find_periodic_run, _layer_sig


def block_mlp(n_blocks=4, width=16, seed=7, updater="sgd", lr=0.2, l2=0.0):
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(updater, learning_rate=lr).list()
         .layer(DenseLayer(n_in=8, n_out=width, activation="tanh", l2=l2)))
    for _ in range(n_blocks):
        b.layer(DenseLayer(n_in=width, n_out=width, activation="tanh", l2=l2))
    b.layer(OutputLayer(n_in=width, n_out=4, l2=l2))
    return MultiLayerNetwork(b.build()).init()


def data(n=32, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.rand(n, 8).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, n)]
    return x, y


def test_find_periodic_run():
    net = block_mlp(n_blocks=4)
    sigs = [_layer_sig(l) for l in net.layers]
    run = find_periodic_run(sigs, 4)
    assert run == (1, 1, 4)
    # with 2 stages, the 4-block run still qualifies
    assert find_periodic_run(sigs, 2) == (1, 1, 4)
    # no run long enough for 8 stages
    assert find_periodic_run(sigs, 8) is None


def _fit_pp(net, x, y, n_stages, n_micro, epochs=2):
    master = PipelineParallelTrainingMaster(
        n_stages=n_stages, n_microbatches=n_micro,
        devices=jax.devices()[:n_stages])
    DistributedNetwork(net, master).fit(
        ListDataSetIterator(DataSet(x, y), len(x)), epochs=epochs)
    return master


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 2), (4, 4)])
def test_compiled_pipeline_matches_serial(n_stages, n_micro):
    x, y = data(32)
    serial = block_mlp()
    serial.fit(x, y)
    serial.fit(x, y)

    pp_net = block_mlp()
    master = _fit_pp(pp_net, x, y, n_stages, n_micro)
    assert master._mode == "compiled"
    for ln in serial.params:
        for pn in serial.params[ln]:
            np.testing.assert_allclose(
                np.asarray(serial.params[ln][pn]),
                np.asarray(pp_net.params[ln][pn]), atol=2e-5,
                err_msg=f"{ln}/{pn}")
    assert abs(serial.score_value - pp_net.score_value) < 1e-4


def test_compiled_pipeline_momentum_state_roundtrips():
    x, y = data(32)
    serial = block_mlp(updater="nesterovs", lr=0.1)
    serial.fit(x, y)
    serial.fit(x, y)
    pp_net = block_mlp(updater="nesterovs", lr=0.1)
    master = _fit_pp(pp_net, x, y, 4, 2)
    assert master._mode == "compiled"
    for ln in serial.params:
        for pn in serial.params[ln]:
            np.testing.assert_allclose(
                np.asarray(serial.params[ln][pn]),
                np.asarray(pp_net.params[ln][pn]), atol=2e-5,
                err_msg=f"{ln}/{pn}")
    # updater momentum state mirrored back per layer
    assert set(serial.updater_state["v"]) == set(pp_net.updater_state["v"])


def test_compiled_pipeline_regularization():
    x, y = data(16)
    serial = block_mlp(l2=0.01, seed=9)
    serial.fit(x, y)
    pp_net = block_mlp(l2=0.01, seed=9)
    master = _fit_pp(pp_net, x, y, 2, 2, epochs=1)
    assert master._mode == "compiled"
    assert abs(serial.score_value - pp_net.score_value) < 1e-5


def test_compiled_pipeline_single_compile():
    x, y = data(32)
    pp_net = block_mlp(seed=11)
    master = PipelineParallelTrainingMaster(
        n_stages=4, n_microbatches=4, devices=jax.devices()[:4])
    dn = DistributedNetwork(pp_net, master)
    dn.fit(ListDataSetIterator(DataSet(x, y), len(x)), epochs=3)
    assert master._mode == "compiled"
    # one program for the whole config: 3 epochs reuse one compiled step
    assert len(master._compiled_steps) == 1
    assert next(iter(master._compiled_steps.values()))._cache_size() == 1


def test_compiled_pipeline_handles_batch_size_change():
    # regression: second fit with a different batch size must rebuild the
    # schedule for the new microbatch shape, not crash on the stale probe
    x, y = data(32)
    pp_net = block_mlp(seed=13)
    master = PipelineParallelTrainingMaster(
        n_stages=2, n_microbatches=2, devices=jax.devices()[:2])
    dn = DistributedNetwork(pp_net, master)
    dn.fit(ListDataSetIterator(DataSet(x, y), 32))
    dn.fit(ListDataSetIterator(DataSet(x[:16], y[:16]), 16))
    assert master._mode == "compiled"
    assert len(master._compiled_steps) == 2
    assert np.isfinite(pp_net.score_value)


def hetero_mlp(seed=3, lr=0.1):
    """Non-periodic stack: every boundary has a different width, so no
    periodic run exists — exercises the switch-based compiled path."""
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater("sgd", learning_rate=lr).list()
         .layer(DenseLayer(n_in=8, n_out=16, activation="tanh"))
         .layer(DenseLayer(n_in=16, n_out=12, activation="relu"))
         .layer(DenseLayer(n_in=12, n_out=8, activation="tanh"))
         .layer(OutputLayer(n_in=8, n_out=4)))
    return MultiLayerNetwork(b.build()).init()


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 2)])
def test_heterogeneous_compiles_and_matches_serial(n_stages, n_micro):
    """Round 4: non-periodic stacks COMPILE (lax.switch stages, padded
    activation buffer) — serial equivalence is the oracle."""
    x, y = data(32)
    serial = hetero_mlp()
    serial.fit(x, y)
    serial.fit(x, y)
    net = hetero_mlp()
    master = _fit_pp(net, x, y, n_stages, n_micro)
    assert master._mode == "compiled"
    assert master._compiled_kind == "hetero"
    for ln in serial.params:
        for pn in serial.params[ln]:
            np.testing.assert_allclose(
                np.asarray(serial.params[ln][pn]),
                np.asarray(net.params[ln][pn]), atol=2e-5,
                err_msg=f"{ln}/{pn}")
    assert abs(serial.score_value - net.score_value) < 1e-4


def conv_then_dense(seed=5, lr=0.05):
    """The conv-then-dense shape the compiled-heterogeneity work targets:
    CNN input, conv + pooling stages, preprocessor-flattened dense head."""
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers import (
        ConvolutionLayer, SubsamplingLayer,
    )

    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater("sgd", learning_rate=lr).list()
         .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                 activation="relu"))
         .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
         .layer(DenseLayer(n_out=16, activation="tanh"))
         .layer(OutputLayer(n_out=4)))
    b.set_input_type(InputType.convolutional(8, 8, 1))
    return MultiLayerNetwork(b.build()).init()


def test_conv_then_dense_pipeline_compiles():
    rs = np.random.RandomState(0)
    x = rs.rand(16, 8, 8, 1).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 16)]
    serial = conv_then_dense()
    serial.fit(x, y)
    net = conv_then_dense()
    master = _fit_pp(net, x, y, 2, 2, epochs=1)
    assert master._mode == "compiled"
    assert master._compiled_kind == "hetero"
    for ln in serial.params:
        for pn in serial.params[ln]:
            np.testing.assert_allclose(
                np.asarray(serial.params[ln][pn]),
                np.asarray(net.params[ln][pn]), atol=2e-5,
                err_msg=f"{ln}/{pn}")


def test_orchestrated_opt_in_and_1f1b_schedules_match_serial():
    """mode='orchestrated' still exists (real per-device param placement),
    and both schedules produce serial-identical math — 1F1B only reorders
    the same vjp calls (memory, not numerics)."""
    x, y = data(16)
    for schedule in ("gpipe", "1f1b"):
        serial = hetero_mlp(seed=21)
        serial.fit(x, y)
        net = hetero_mlp(seed=21)
        master = PipelineParallelTrainingMaster(
            n_stages=2, n_microbatches=4, devices=jax.devices()[:2],
            mode="orchestrated", schedule=schedule)
        DistributedNetwork(net, master).fit(
            ListDataSetIterator(DataSet(x, y), 16))
        assert master._mode == "orchestrated"
        for ln in serial.params:
            for pn in serial.params[ln]:
                np.testing.assert_allclose(
                    np.asarray(serial.params[ln][pn]),
                    np.asarray(net.params[ln][pn]), atol=2e-5,
                    err_msg=f"{schedule}: {ln}/{pn}")


def test_bubble_fraction_analytic_and_measured():
    from deeplearning4j_tpu.parallel.pipeline import measure_bubble_fraction

    m = PipelineParallelTrainingMaster(n_stages=4, n_microbatches=4,
                                       devices=jax.devices()[:4])
    assert abs(m.bubble_fraction() - 3 / 7) < 1e-9

    def make_batch(n):
        x, y = data(n)
        return DataSet(x, y)

    stats = measure_bubble_fraction(
        lambda: block_mlp(n_blocks=4, seed=17), make_batch,
        n_stages=2, mb_size=8, m_small=2, m_large=4, iters=2,
        devices=jax.devices()[:2])
    assert stats["mode"] == "compiled"
    assert 0.0 <= stats["bubble_analytic"] < 1.0
    assert np.isfinite(stats["bubble_measured"])


def test_hetero_sharded_params_with_adam_matches_serial():
    """Round 5: the flat-row SHARDED param layout must be exact through a
    STATEFUL elementwise updater (adam m/v ride the same flat rows)."""
    x, y = data(32)

    def make():
        b = (NeuralNetConfiguration.builder().seed(17)
             .updater("adam", learning_rate=0.01).list()
             .layer(DenseLayer(n_in=8, n_out=16, activation="tanh"))
             .layer(DenseLayer(n_in=16, n_out=12, activation="relu"))
             .layer(DenseLayer(n_in=12, n_out=8, activation="tanh"))
             .layer(OutputLayer(n_in=8, n_out=4)))
        return MultiLayerNetwork(b.build()).init()

    serial = make()
    serial.fit(x, y)
    serial.fit(x, y)
    net = make()
    master = _fit_pp(net, x, y, 2, 4)
    assert master._compiled_kind == "hetero"
    assert master._hetero_sharded
    for ln in serial.params:
        for pn in serial.params[ln]:
            np.testing.assert_allclose(
                np.asarray(serial.params[ln][pn]),
                np.asarray(net.params[ln][pn]), atol=2e-5,
                err_msg=f"{ln}/{pn}")
    # adam state rode the flat rows and unflattened back per layer
    for slot in ("m", "v"):
        for ln in serial.updater_state[slot]:
            for pn in serial.updater_state[slot][ln]:
                np.testing.assert_allclose(
                    np.asarray(serial.updater_state[slot][ln][pn]),
                    np.asarray(net.updater_state[slot][ln][pn]), atol=2e-5,
                    err_msg=f"{slot}/{ln}/{pn}")


def test_hetero_params_actually_partitioned_per_device():
    """The memory point of pipeline parallelism (VERDICT r4 weak #4): with
    the sharded layout, each device holds ~1/S of the param bytes, not a
    full replica."""
    x, y = data(32)
    net = hetero_mlp()
    total = sum(int(np.prod(p.shape)) * 4
                for lp in net.params.values() for p in lp.values())
    master = PipelineParallelTrainingMaster(
        n_stages=2, n_microbatches=4, devices=jax.devices()[:2])
    master._build(net)
    assert master._hetero_sharded
    rows = jax.device_put(master._hetero_flatten(net.params),
                          master._row_sharding)
    shard_bytes = {s.device: s.data.nbytes for s in rows.addressable_shards}
    assert len(shard_bytes) == 2
    for dev, nb in shard_bytes.items():
        # Pmax row per device: strictly less than the whole model, and no
        # more than the padded largest stage
        assert nb < total, f"{dev} holds a full replica ({nb} >= {total})"
        assert nb == master._flat_pmax * 4


def test_hetero_falls_back_to_replicated_with_lr_overrides(capsys):
    """Per-layer lr overrides break the one-pseudo-layer updater trick; the
    build must keep params replicated (with a note) and stay serially
    exact."""
    x, y = data(16)

    def make():
        b = (NeuralNetConfiguration.builder().seed(19)
             .updater("sgd", learning_rate=0.1).list()
             .layer(DenseLayer(n_in=8, n_out=16, activation="tanh"))
             .layer(DenseLayer(n_in=16, n_out=12, activation="relu",
                               learning_rate=0.05))
             .layer(OutputLayer(n_in=12, n_out=4)))
        return MultiLayerNetwork(b.build()).init()

    serial = make()
    serial.fit(x, y)
    net = make()
    master = _fit_pp(net, x, y, 2, 2, epochs=1)
    assert master._compiled_kind == "hetero"
    assert not master._hetero_sharded
    assert "REPLICATED" in capsys.readouterr().err  # the one-time note fired
    for ln in serial.params:
        for pn in serial.params[ln]:
            np.testing.assert_allclose(
                np.asarray(serial.params[ln][pn]),
                np.asarray(net.params[ln][pn]), atol=2e-5,
                err_msg=f"{ln}/{pn}")


def test_pipeline_rejects_net_without_output_tail_early():
    b = (NeuralNetConfiguration.builder().seed(23)
         .updater("sgd", learning_rate=0.1).list()
         .layer(DenseLayer(n_in=8, n_out=16, activation="tanh"))
         .layer(DenseLayer(n_in=16, n_out=4, activation="identity")))
    net = MultiLayerNetwork(b.build()).init()
    master = PipelineParallelTrainingMaster(
        n_stages=2, n_microbatches=2, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="score"):
        master._build(net)


@pytest.mark.parametrize("maker", ["periodic", "hetero"])
def test_remat_pipeline_matches_serial(maker):
    """remat=True (jax.checkpoint per schedule tick — the compiled-path
    counterpart of 1F1B's activation-memory win) must not change numerics."""
    x, y = data(32)
    make = (lambda: block_mlp(seed=31)) if maker == "periodic" \
        else (lambda: hetero_mlp(seed=31))
    serial = make()
    serial.fit(x, y)
    net = make()
    master = PipelineParallelTrainingMaster(
        n_stages=2, n_microbatches=4, devices=jax.devices()[:2], remat=True)
    DistributedNetwork(net, master).fit(
        ListDataSetIterator(DataSet(x, y), 32))
    assert master._mode == "compiled"
    assert master._compiled_kind == ("periodic" if maker == "periodic"
                                     else "hetero")
    for ln in serial.params:
        for pn in serial.params[ln]:
            np.testing.assert_allclose(
                np.asarray(serial.params[ln][pn]),
                np.asarray(net.params[ln][pn]), atol=2e-5,
                err_msg=f"{maker}: {ln}/{pn}")


def test_remat_rejected_on_orchestrated_mode():
    with pytest.raises(ValueError, match="remat"):
        PipelineParallelTrainingMaster(n_stages=2, mode="orchestrated",
                                       remat=True,
                                       devices=jax.devices()[:2])


def test_hetero_sharded_randomized_config_sweep():
    """Seeded property sweep over the sharded-hetero config space: random
    widths/depths/updaters/stage counts must all match serial training
    (the flat-row layout has per-config offsets — exercise many)."""
    rs = np.random.RandomState(77)
    for trial in range(4):
        depth = int(rs.randint(3, 7))
        widths = [int(rs.choice([6, 10, 14, 18, 22])) for _ in range(depth)]
        updater = ["sgd", "nesterovs", "adam", "rmsprop"][trial % 4]
        n_stages = int(rs.choice([2, 3, 4]))
        n_micro = int(rs.choice([2, 4]))
        acts = ["tanh", "relu", "sigmoid"]

        def make():
            b = (NeuralNetConfiguration.builder().seed(100 + trial)
                 .updater(updater, learning_rate=0.05).list())
            prev = 8
            for i, w in enumerate(widths):
                b.layer(DenseLayer(n_in=prev, n_out=w,
                                   activation=acts[i % 3]))
                prev = w
            b.layer(OutputLayer(n_in=prev, n_out=4))
            return MultiLayerNetwork(b.build()).init()

        x, y = data(n_micro * 8, seed=trial)
        serial = make()
        serial.fit(x, y)
        net = make()
        master = _fit_pp(net, x, y, n_stages, n_micro, epochs=1)
        cfg = (f"trial {trial}: widths={widths} updater={updater} "
               f"S={n_stages} M={n_micro}")
        assert master._compiled_kind == "hetero", cfg
        assert master._hetero_sharded, cfg
        for ln in serial.params:
            for pn in serial.params[ln]:
                np.testing.assert_allclose(
                    np.asarray(serial.params[ln][pn]),
                    np.asarray(net.params[ln][pn]), atol=3e-5,
                    err_msg=f"{cfg}: {ln}/{pn}")
