"""Multi-slice (DCN-spanning) mesh helper: device order is the whole
mechanism — each slice's chips contiguous along the data axis so XLA's
hierarchical all-reduce rides ICI within a slice and crosses DCN once.
Every existing TrainingMaster accepts the mesh unchanged."""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu import backend
from deeplearning4j_tpu.backend import slice_mesh


def test_virtual_slices_group_contiguously():
    mesh = slice_mesh(2)
    devs = list(mesh.devices.flatten())
    assert len(devs) == 8
    # first four ids then last four: slice blocks stay contiguous
    ids = [d.id for d in devs]
    assert ids == sorted(ids)
    assert set(ids[:4]) == set(range(4))


def test_model_axis_stays_inside_a_slice():
    mesh = slice_mesh(2, model=2)
    assert mesh.shape[backend.AXIS_DATA] == 4
    assert mesh.shape[backend.AXIS_MODEL] == 2
    # the model-pair for each data row must come from ONE slice group
    arr = mesh.devices  # [data, model, seq]
    for d in range(arr.shape[0]):
        pair = {dev.id // 4 for dev in arr[d].flatten()}
        assert len(pair) == 1, f"model group straddles slices: {pair}"


def test_rejects_model_group_straddling_dcn():
    with pytest.raises(ValueError, match="ICI"):
        slice_mesh(8, model=2)  # 1 device/slice cannot hold a model pair


def test_rejects_wrong_slice_count():
    with pytest.raises(ValueError, match="n_slices"):
        slice_mesh(3)  # 8 devices cannot form 3 equal virtual slices


def test_dp_training_over_two_virtual_slices_matches_serial():
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.parallel import (
        DistributedNetwork, SyncTrainingMaster,
    )

    def make():
        b = (NeuralNetConfiguration.builder().seed(9)
             .updater("sgd", learning_rate=0.1).list()
             .layer(DenseLayer(n_in=6, n_out=12, activation="tanh"))
             .layer(OutputLayer(n_in=12, n_out=3)))
        return MultiLayerNetwork(b.build()).init()

    rs = np.random.RandomState(0)
    x = rs.rand(32, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 32)]
    serial = make()
    serial.fit(x, y)

    net = make()
    master = SyncTrainingMaster(mesh=slice_mesh(2))
    DistributedNetwork(net, master).fit(
        ListDataSetIterator(DataSet(x, y), 32))
    for ln in serial.params:
        for pn in serial.params[ln]:
            np.testing.assert_allclose(
                np.asarray(serial.params[ln][pn]),
                np.asarray(net.params[ln][pn]), atol=2e-5,
                err_msg=f"{ln}/{pn}")


class _StubDev:
    def __init__(self, id, slice_index):
        self.id = id
        self.slice_index = slice_index

    def __repr__(self):
        return f"dev{self.id}@s{self.slice_index}"


def test_slice_index_regrouping_reorders_interleaved_devices():
    """The real multi-slice mechanism: jax.devices() may interleave
    slices; grouping must reorder so each slice is contiguous."""
    from deeplearning4j_tpu.backend.device import _group_by_slice

    interleaved = [_StubDev(i, i % 2) for i in range(8)]  # s0,s1,s0,s1...
    ordered, per = _group_by_slice(interleaved, 2)
    assert per == 4
    assert [d.slice_index for d in ordered] == [0] * 4 + [1] * 4
    # original order preserved WITHIN a slice
    assert [d.id for d in ordered] == [0, 2, 4, 6, 1, 3, 5, 7]


def test_slice_index_unequal_groups_rejected():
    from deeplearning4j_tpu.backend.device import _group_by_slice

    lopsided = [_StubDev(i, 0 if i < 3 else 1) for i in range(8)]
    with pytest.raises(ValueError, match="unequal"):
        _group_by_slice(lopsided, 2)


def test_virtual_split_error_names_the_real_cause():
    from deeplearning4j_tpu.backend.device import _group_by_slice

    with pytest.raises(ValueError, match="virtual slicing"):
        _group_by_slice([object()] * 8, 3)
