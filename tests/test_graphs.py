"""Graph module tests (≙ TestGraphLoading / TestGraph / TestDeepWalk)."""

import numpy as np
import pytest

from deeplearning4j_tpu.graphs import (
    DeepWalk,
    Graph,
    RandomWalkIterator,
    WeightedRandomWalkIterator,
    generate_walks,
    load_delimited_edges,
    load_delimited_vertices,
    load_weighted_edges,
)


def ring_graph(n=10):
    g = Graph(n)
    for i in range(n):
        g.add_edge(i, (i + 1) % n)
    return g


def two_cliques(k=5):
    """Two k-cliques joined by a single bridge edge."""
    g = Graph(2 * k)
    for base in (0, k):
        for i in range(k):
            for j in range(i + 1, k):
                g.add_edge(base + i, base + j)
    g.add_edge(0, k)  # bridge
    return g


# ------------------------------------------------------------------- api

def test_graph_basics():
    g = ring_graph(5)
    assert g.num_vertices == 5
    assert g.degree(0) == 2            # undirected: 0-1 and 4-0
    assert set(g.neighbors(0)) == {1, 4}
    assert g.num_edges() == 10         # 5 undirected edges, both directions


def test_directed_edges():
    g = Graph(3)
    g.add_edge(0, 1, directed=True)
    assert g.neighbors(0) == [1]
    assert g.neighbors(1) == []


def test_neighbor_table():
    g = ring_graph(4)
    table, weights, deg = g.neighbor_table()
    assert table.shape[0] == 4
    assert (deg == 2).all()
    assert set(table[0][:2]) == {1, 3}


# ---------------------------------------------------------------- loaders

def test_edge_list_loading(tmp_path):
    p = tmp_path / "edges.txt"
    p.write_text("# comment\n0,1\n1,2\n2,0\n")
    g = load_delimited_edges(str(p), 3)
    assert g.num_edges() == 6
    assert set(g.neighbors(0)) == {1, 2}


def test_weighted_edge_loading(tmp_path):
    p = tmp_path / "edges.txt"
    p.write_text("0,1,0.5\n1,2,2.0\n")
    g = load_weighted_edges(str(p), 3)
    assert g.edges_out(0)[0].weight == 0.5


def test_vertex_loading(tmp_path):
    p = tmp_path / "verts.txt"
    p.write_text("0,zero\n1,one\n")
    vs = load_delimited_vertices(str(p))
    assert vs[0].value == "zero" and vs[1].idx == 1


# ------------------------------------------------------------------ walks

def test_random_walk_iterator_structure():
    g = ring_graph(6)
    it = RandomWalkIterator(g, walk_length=8, seed=1)
    walks = list(it)
    assert len(walks) == 6
    for i, w in enumerate(walks):
        assert w[0] == i and len(w) == 9
        for a, b in zip(w, w[1:]):   # every hop follows an edge
            assert b in g.neighbors(a)


def test_weighted_walk_prefers_heavy_edges():
    g = Graph(3)
    g.add_edge(0, 1, weight=100.0)
    g.add_edge(0, 2, weight=0.01)
    it = WeightedRandomWalkIterator(g, walk_length=1, seed=0)
    hops = [it._walk_from(0)[1] for _ in range(50)]
    assert hops.count(1) > 40


def test_dead_end_self_loops():
    g = Graph(2)
    g.add_edge(0, 1, directed=True)
    it = RandomWalkIterator(g, walk_length=3, seed=0)
    w = it._walk_from(0)
    assert w == [0, 1, 1, 1]


def test_generate_walks_batch():
    g = ring_graph(6)
    walks = generate_walks(g, walk_length=5, walks_per_vertex=3, seed=2)
    assert walks.shape == (18, 6)
    for w in walks:
        for a, b in zip(w, w[1:]):
            assert b in g.neighbors(int(a))


def test_generate_walks_weighted():
    g = Graph(3)
    g.add_edge(0, 1, weight=100.0)
    g.add_edge(0, 2, weight=0.01)
    walks = generate_walks(g, walk_length=1, walks_per_vertex=200, seed=0,
                           weighted=True)
    first_hops = walks[walks[:, 0] == 0][:, 1]
    assert (first_hops == 1).mean() > 0.9


# --------------------------------------------------------------- deepwalk

def test_deepwalk_learns_community_structure():
    g = two_cliques(5)
    dw = DeepWalk(vector_size=16, window_size=3, walk_length=20,
                  walks_per_vertex=8, epochs=5, learning_rate=0.2,
                  batch_size=64, seed=4)
    dw.fit(g)
    # same-clique vertices more similar than cross-clique
    within = np.mean([dw.similarity(a, b)
                      for a in range(1, 5) for b in range(1, 5) if a != b])
    across = np.mean([dw.similarity(a, b)
                      for a in range(1, 5) for b in range(6, 10)])
    assert within > across, f"within={within:.3f} across={across:.3f}"
    near = dw.vertices_nearest(1, top_n=3)
    assert len(set(near) & {0, 2, 3, 4}) >= 2


def test_deepwalk_vertex_vector_shape():
    g = ring_graph(8)
    dw = DeepWalk(vector_size=12, walk_length=10, walks_per_vertex=2,
                  seed=1).fit(g)
    assert dw.vertex_vector(0).shape == (12,)
    assert dw.num_vertices() == 8
