"""Constituency tree parser + vectorizer (≙ the reference's UIMA
treeparser package: TreeParser.java:60, TreeVectorizer.java,
HeadWordFinder.java, BinarizeTreeTransformer.java, CollapseUnaries.java)."""

import numpy as np

from deeplearning4j_tpu.nlp.treeparser import (
    BinarizeTreeTransformer, CollapseUnaries, HeadWordFinder, Tree,
    TreeParser, TreeVectorizer,
)


def test_parser_sentence_trees_cover_all_tokens():
    parser = TreeParser()
    trees = parser.get_trees("The quick dog chased a red ball. It was fun.")
    assert len(trees) == 2
    assert trees[0].label == "S"
    assert [t.lower() for t in trees[0].tokens()] == [
        "the", "quick", "dog", "chased", "a", "red", "ball."]
    # every token sits under a preterminal POS node
    for leaf in trees[0].leaves():
        assert leaf.is_leaf() and leaf.token


def test_parser_phrase_structure():
    parser = TreeParser()
    (tree,) = parser.get_trees("The quick dog ran in the park")
    labels = [c.label for c in tree.children]
    assert labels[0] == "NP"        # the quick dog
    assert "VP" in labels           # ran
    assert "PP" in labels           # in the park
    pp = tree.children[labels.index("PP")]
    assert pp.children[0].label == "ADP"
    assert pp.children[1].label == "NP"


def test_empty_text_gives_no_trees():
    assert TreeParser().get_trees("") == []


def test_head_word_finder():
    parser = TreeParser()
    (tree,) = parser.get_trees("The quick dog chased the ball")
    finder = HeadWordFinder()
    # S's head comes from the VP (Collins S -> VP rule)
    assert finder.find_head_word(tree) == "chased"
    np_tree = tree.children[0]
    assert np_tree.label == "NP"
    # NP head: rightmost noun
    assert finder.find_head_word(np_tree) == "dog"


def test_binarize_caps_fanout_at_two():
    parser = TreeParser()
    (tree,) = parser.get_trees(
        "The dog ran in the park with a ball and a stick")

    def max_fanout(t):
        if t.is_leaf():
            return 0
        return max([len(t.children)] + [max_fanout(c) for c in t.children])

    assert max_fanout(tree) > 2  # the raw S is flat
    binarized = BinarizeTreeTransformer().transform(tree)
    assert max_fanout(binarized) <= 2
    # binarization preserves the yield exactly
    assert binarized.tokens() == tree.tokens()
    # intermediate nodes carry the @-marked parent label
    assert any(c.label == "@S" for c in binarized.children)


def test_collapse_unaries():
    # X -> NP -> (...) unary chain collapses to one node keeping top label
    inner = Tree("NP", [Tree("NOUN", [Tree("dog", token="dog")])])
    outer = Tree("X", [inner])
    collapsed = CollapseUnaries().transform(outer)
    assert collapsed.label == "X"
    assert collapsed.children[0].label == "NOUN"  # preterminal survives
    assert collapsed.tokens() == ["dog"]


def test_vectorizer_pipeline_binarized_and_labeled():
    vec = TreeVectorizer()
    trees = vec.get_trees_with_labels(
        "The movie was great. The food was terrible.")
    assert len(trees) == 2
    assert trees[0].gold_label == "positive"
    assert trees[1].gold_label == "negative"
    # labels propagate to every node (RNTN per-node target)
    for node in trees[0].children:
        assert node.gold_label == "positive"


def test_vectorizer_explicit_labels():
    vec = TreeVectorizer()
    trees = vec.get_trees_with_labels("A dog ran. A cat sat.", ["x", "y"])
    assert [t.gold_label for t in trees] == ["x", "y"]


class _ToyVectors:
    def __init__(self, words, dim=4):
        self.v = {w: np.full(dim, i + 1.0, np.float32)
                  for i, w in enumerate(words)}

    def get_word_vector(self, word):
        return self.v.get(word)


def test_vectorize_attaches_leaf_vectors_with_oov_zeros():
    vec = TreeVectorizer()
    wv = _ToyVectors(["the", "dog", "ran"])
    (tree,) = vec.vectorize("The dog ran quickly", wv)
    leaves = tree.leaves()
    by_tok = {l.token.lower().strip("."): l.vector for l in leaves}
    assert by_tok["dog"].tolist() == [2.0] * 4
    assert by_tok["quickly"].tolist() == [0.0] * 4  # OOV -> zeros, same dim
    assert all(l.vector is not None and l.vector.shape == (4,)
               for l in leaves)


def test_tree_repr_is_penn_style():
    (tree,) = TreeParser().get_trees("The dog ran")
    s = repr(tree)
    assert s.startswith("(S (NP (DET ")
    assert "(VP (VERB " in s
