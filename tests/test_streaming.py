"""Streaming/serving layer (reference dl4j-streaming: Kafka pub/sub,
serve routes, streaming train pipeline — Dl4jServingRouteTest pattern)."""

import json
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.streaming import (
    InferenceServer, MessageBroker, NDArrayConsumer, NDArrayPublisher,
    ServingPipeline, StreamingPipeline, array_to_base64, base64_to_array,
    dataset_from_json, dataset_to_json,
)


def small_net(n_in=2, n_out=2, seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater("sgd", learning_rate=0.5).list()
            .layer(DenseLayer(n_in=n_in, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=n_out, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def test_array_serde_roundtrip():
    a = np.random.RandomState(0).rand(3, 4, 5).astype(np.float32)
    env = array_to_base64(a)
    np.testing.assert_allclose(base64_to_array(env), a)


def test_dataset_serde_roundtrip():
    ds = DataSet(np.ones((2, 3), np.float32), np.zeros((2, 1), np.float32),
                 labels_mask=np.array([1.0, 0.0], np.float32))
    back = dataset_from_json(dataset_to_json(ds))
    np.testing.assert_allclose(back.features, ds.features)
    np.testing.assert_allclose(back.labels_mask, ds.labels_mask)
    assert back.features_mask is None


def test_pubsub_local():
    broker = MessageBroker()
    consumer = NDArrayConsumer("t1", broker=broker)
    publisher = NDArrayPublisher("t1", broker=broker)
    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    publisher.publish(arr)
    got = consumer.poll(timeout=2)
    np.testing.assert_allclose(got, arr)
    assert consumer.poll(timeout=0.05) is None


def test_pubsub_http():
    broker = MessageBroker()
    port = broker.serve()
    url = f"http://127.0.0.1:{port}"
    pub = NDArrayPublisher("t2", url=url)
    arr = np.array([[1.5, -2.0]], np.float32)

    results = []
    consumer = NDArrayConsumer("t2", url=url, sub_id="a")

    def consume():
        # first poll registers the HTTP subscription, may race the publish
        results.append(consumer.poll(timeout=3))

    # register the subscription before publishing
    assert consumer.poll(timeout=0.2) is None
    t = threading.Thread(target=consume)
    t.start()
    pub.publish(arr)
    t.join(timeout=5)
    broker.stop()
    assert results and results[0] is not None
    np.testing.assert_allclose(results[0], arr)


def test_inference_server_batches_and_serves():
    net = small_net()
    server = InferenceServer(net, max_batch=8, port=0)
    port = server.start()
    url = f"http://127.0.0.1:{port}"
    with urllib.request.urlopen(f"{url}/healthz", timeout=5) as r:
        assert json.loads(r.read())["status"] == "ok"

    # plain JSON list body
    req = urllib.request.Request(
        f"{url}/predict", data=json.dumps([[0.1, 0.9], [0.8, 0.2]]).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        out = base64_to_array(json.loads(r.read()))
    assert out.shape == (2, 2)
    np.testing.assert_allclose(out.sum(-1), 1.0, atol=1e-5)

    # concurrent requests micro-batch through one forward pass
    outs = [None] * 6

    def hit(i):
        outs[i] = server.predict(np.array([0.1 * i, 0.5], np.float32))

    threads = [threading.Thread(target=hit, args=(i,)) for i in range(6)]
    [t.start() for t in threads]
    [t.join(timeout=10) for t in threads]
    assert all(o is not None and o.shape == (1, 2) for o in outs)
    server.stop()


def test_inference_server_survives_bad_request():
    net = small_net()
    server = InferenceServer(net, max_batch=4, port=0)
    server.start()
    import pytest

    with pytest.raises(Exception):
        server.predict(np.zeros((1, 7), np.float32))  # wrong width
    # dispatcher must still be alive for good requests
    out = server.predict(np.zeros((1, 2), np.float32))
    assert out.shape == (1, 2)
    # oversized request chunks through the fixed batch shape
    big = server.predict(np.zeros((11, 2), np.float32))
    assert big.shape == (11, 2)
    server.stop()


def test_publish_never_blocks_on_slow_consumer():
    broker = MessageBroker(queue_size=4)
    q = broker.subscribe("slow")
    for i in range(20):  # would deadlock with a blocking put
        broker.publish("slow", str(i))
    # oldest messages dropped, newest retained
    got = [q.get_nowait() for _ in range(q.qsize())]
    assert got[-1] == "19" and len(got) == 4


def test_record_to_dataset_validation():
    import pytest

    from deeplearning4j_tpu.streaming.serde import record_to_dataset

    with pytest.raises(ValueError, match="num_classes"):
        record_to_dataset([1.0, 2.0, 0.0], label_index=2)
    with pytest.raises(ValueError, match="outside"):
        record_to_dataset([1.0, 2.0, 9.0], label_index=2, num_classes=3)


def test_streaming_pipeline_trains():
    net = small_net()
    broker = MessageBroker()
    pipe = StreamingPipeline(net, broker, "records", label_index=2,
                             num_classes=2, batch_size=4)
    rs = np.random.RandomState(0)
    for _ in range(8):
        a, b = rs.rand(2)
        broker.publish("records", json.dumps([a, b, int(a + b > 1)]))
    pipe.run(max_batches=2, timeout=0.2)
    assert pipe.batches_trained == 2
    assert np.isfinite(net.score_value)


def test_serving_pipeline_round_trip():
    net = small_net()
    broker = MessageBroker()
    out_q = broker.subscribe("preds")
    pipe = ServingPipeline(net, broker, "features", "preds")
    broker.publish("features", json.dumps([0.2, 0.7]))
    pipe.run(max_messages=1, timeout=1.0)
    msg = out_q.get(timeout=2)
    pred = base64_to_array(json.loads(msg))
    assert pred.shape == (1, 2)


def test_serde_consume_validation_rejects_bad_records():
    """Satellite: consume-side validation — NaN/Inf payloads, dtype and
    shape lies, and a bit-flipped base64 payload all raise a typed
    BadRecordError with a bounded reason instead of reaching fit."""
    from deeplearning4j_tpu.streaming import (
        BadRecordError, consume_dataset_json,
    )

    ds = DataSet(np.ones((2, 3), np.float32), np.zeros((2, 2), np.float32))
    msg = dataset_to_json(ds)
    # the happy path round-trips (and returns the meta dict)
    back, meta = consume_dataset_json(dataset_to_json(ds, meta={"ts": 1.0}))
    np.testing.assert_allclose(back.features, ds.features)
    assert meta == {"ts": 1.0}

    def reason(text):
        with pytest.raises(BadRecordError) as ei:
            consume_dataset_json(text)
        return ei.value.reason

    # regression: a bit-flipped base64 character (payload corrupted in
    # transit) must fail the STRICT decode, not be silently skipped
    obj = json.loads(msg)
    data = obj["features"]["data"]
    i = next(idx for idx, c in enumerate(data) if c.islower())
    obj["features"]["data"] = (data[:i] + chr(ord(data[i]) ^ 0x60)
                               + data[i + 1:])
    assert reason(json.dumps(obj)) == "bad_base64"

    nan = DataSet(np.full((1, 3), np.nan, np.float32),
                  np.zeros((1, 2), np.float32))
    assert reason(dataset_to_json(nan)) == "non_finite"

    obj = json.loads(msg)
    obj["features"]["shape"] = [5, 7]          # payload-length lie
    assert reason(json.dumps(obj)) == "shape_mismatch"

    # 0-d arrays have no batch dimension: must quarantine, not TypeError
    import base64 as b64

    obj = json.loads(msg)
    obj["features"] = {"shape": [], "dtype": "float32",
                       "data": b64.b64encode(
                           np.float32(1.0).tobytes()).decode()}
    assert reason(json.dumps(obj)) == "shape_mismatch"

    obj = json.loads(msg)
    obj["features"]["dtype"] = "float64"
    assert reason(json.dumps(obj)) == "bad_dtype"

    obj = json.loads(msg)
    del obj["labels"]
    assert reason(json.dumps(obj)) == "bad_envelope"

    assert reason("{{{not json") == "bad_json"

    # rows mismatch between features and labels
    obj = json.loads(dataset_to_json(
        DataSet(np.ones((3, 2), np.float32), np.zeros((3, 2), np.float32))))
    obj["labels"] = json.loads(msg)["labels"]  # 2 rows vs 3
    assert reason(json.dumps(obj)) == "shape_mismatch"

    # a shape-lying MASK must quarantine too, not crash fit mid-window
    masked = DataSet(np.ones((2, 3), np.float32),
                     np.zeros((2, 2), np.float32),
                     labels_mask=np.ones((2,), np.float32))
    obj = json.loads(dataset_to_json(masked))
    obj["labels_mask"] = json.loads(dataset_to_json(DataSet(
        np.ones((5, 1), np.float32),
        np.zeros((5, 1), np.float32))))["features"]   # 5 rows vs 2
    assert reason(json.dumps(obj)) == "shape_mismatch"

    # the lenient legacy decode still accepts what it used to
    assert dataset_from_json(msg).features.shape == (2, 3)


def test_publish_counts_dropped_messages_per_topic():
    """Satellite: a full subscriber queue drops the OLDEST message —
    every drop lands in dl4j_stream_dropped_total{topic}."""
    from deeplearning4j_tpu.observability import MetricsRegistry

    reg = MetricsRegistry()
    broker = MessageBroker(queue_size=2, registry=reg)
    q = broker.subscribe("hot")
    other = broker.subscribe("cold")
    for i in range(5):
        broker.publish("hot", str(i))
    broker.publish("cold", "x")
    assert [q.get_nowait() for _ in range(2)] == ["3", "4"]  # oldest gone
    assert reg.get_value("dl4j_stream_dropped_total", topic="hot") == 3
    assert reg.get_value("dl4j_stream_dropped_total", topic="cold") is None
    assert other.get_nowait() == "x"
