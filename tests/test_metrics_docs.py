"""Tier-1 lint: every registered dl4j_* metric family has non-empty help
text and a row in the docs/observability.md metric table
(scripts/check_metrics_docs.py — pure source analysis, no jax)."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_linter():
    path = os.path.join(REPO, "scripts", "check_metrics_docs.py")
    spec = importlib.util.spec_from_file_location("check_metrics_docs", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_every_metric_family_has_help_and_docs_row():
    mod = _load_linter()
    problems = mod.run_lint()
    assert problems == [], "\n".join(problems)


def test_scanner_sees_known_families():
    """Guard against the scanner silently matching nothing (which would
    make the lint above vacuously green)."""
    mod = _load_linter()
    regs = mod.find_registrations()
    for expected in ("dl4j_fit_step_seconds", "dl4j_worker_step_seconds",
                     "dl4j_stragglers_total", "dl4j_serving_requests_total",
                     "dl4j_health_status", "dl4j_watchdog_dumps_total",
                     "dl4j_phase_seconds"):
        assert expected in regs, f"scanner missed {expected}"
    docs = mod.documented_families()
    assert "dl4j_fit_step_seconds" in docs
