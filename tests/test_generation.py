"""Continuous-batching generation engine (`deeplearning4j_tpu/generation/`).

Acceptance oracles from the PR issue:

- mixed join/leave decode traffic produces BIT-IDENTICAL tokens to
  isolated sequential decode of each request (scheduler/paging oracle);
- prefix-sharing refcount/free correctness;
- page exhaustion sheds with 429 instead of hanging;
- model hot-swap under continuous decode load: zero dropped/corrupted
  streams;
- deterministic seeded sampling regardless of slot placement / batch
  composition;
- zero steady-state compiles under mixed traffic (per-program jit cache
  sizes AND the version's RecompileDetector);
- one shared sampling-policy implementation across the three decode
  paths (host loop / compiled scan / engine), parity-tested.
"""

import json
import threading
import time

import http.client

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.generation import GenerationEngine, PagedKVCache
from deeplearning4j_tpu.models.zoo import transformer_char_lm
from deeplearning4j_tpu.serving.admission import (
    DeadlineExceededError, QueueFullError,
)

pytestmark = pytest.mark.generation

VOCAB = 29


def small_lm(seed=12345, d_model=32, layers=2, **kw):
    return transformer_char_lm(vocab_size=VOCAB, d_model=d_model,
                               n_heads=4, layers=layers, max_cache=128,
                               seed=seed, **kw)


@pytest.fixture(scope="module")
def lm():
    return small_lm()


@pytest.fixture(scope="module")
def engine(lm):
    eng = GenerationEngine(lm, slots=4, page_size=4, max_context=32,
                           max_queue=64, deadline_s=30.0)
    eng.start()
    yield eng
    eng.stop()


def _prompts(rng, n, lo=1, hi=12):
    return [rng.randint(0, VOCAB, rng.randint(lo, hi)).tolist()
            for _ in range(n)]


# --------------------------------------------------------------- the oracle
def test_join_leave_parity_vs_sequential(engine, rng):
    """Mixed concurrent traffic (requests joining and leaving the
    RUNNING batch at different steps) must produce bit-identical greedy
    tokens to the same requests decoded one at a time."""
    prompts = _prompts(rng, 10)
    lens = [int(rng.randint(2, 10)) for _ in prompts]

    # isolated sequential reference (one request in flight at a time)
    seq = [engine.generate(p, n).tolist() for p, n in zip(prompts, lens)]

    # concurrent, staggered: different max tokens => leaves mid-batch,
    # staggered submits => joins mid-batch
    handles = []
    for i, (p, n) in enumerate(zip(prompts, lens)):
        handles.append(engine.submit(p, n))
        if i % 3 == 0:
            time.sleep(0.002)
    mixed = [h.result(timeout=60) for h in handles]
    assert mixed == seq
    assert all(h.finish_reason == "length" for h in handles)


def test_matches_compiled_scan_decode(engine, lm, rng):
    """The paged engine's greedy continuation equals the single-stream
    compiled ``lax.scan`` decode (``models/decode.generate``) — the
    model-correctness cross-check between independent decode paths."""
    from deeplearning4j_tpu.models.decode import generate

    prompt = rng.randint(0, VOCAB, (1, 7))
    ref = generate(lm, prompt, 10, temperature=0.0)[0].tolist()
    got = engine.generate(prompt[0], 10).tolist()
    assert got == ref


def test_seeded_sampling_slot_invariant(engine, rng):
    """A seeded sampled request must produce identical tokens whatever
    slot it lands in and whoever shares the batch (keys fold per
    request seed + token index, never per slot)."""
    prompt = rng.randint(0, VOCAB, 6).tolist()
    kw = dict(temperature=0.9, top_k=7, top_p=0.95, seed=123)
    alone = engine.generate(prompt, 8, **kw).tolist()

    # same request next to unrelated noise traffic
    noise = [engine.submit(p, 6, temperature=1.1, seed=50 + i)
             for i, p in enumerate(_prompts(rng, 3))]
    busy = engine.generate(prompt, 8, **kw).tolist()
    for h in noise:
        h.result(timeout=60)
    assert busy == alone


def test_sampler_shared_across_paths(rng):
    """One policy implementation: the static ``_sampler`` (host loop +
    compiled scan) and the runtime-array ``sample_tokens`` (engine)
    agree draw-for-draw, and ``models.decode`` imports the shared
    symbol rather than owning a copy."""
    from deeplearning4j_tpu.models import decode
    from deeplearning4j_tpu.utils import sampling
    from deeplearning4j_tpu.utils.sampling import _sampler, sample_tokens

    assert decode._sampler is sampling._sampler
    logits = jnp.asarray(rng.randn(1, 40).astype(np.float32) * 2)
    base = jax.random.PRNGKey(9)
    raw = np.asarray(jax.device_get(base), np.uint32)[None]
    for t, k, p in [(1.0, None, None), (0.8, 5, None), (1.2, None, 0.9),
                    (0.7, 6, 0.85), (0.0, 3, 0.5)]:
        stat = _sampler(t, k, p)
        for idx in range(3):
            a = int(np.asarray(stat(logits, jax.random.fold_in(base, idx)))[0])
            b = int(np.asarray(sample_tokens(
                logits, raw, jnp.asarray([idx], jnp.int32),
                jnp.asarray([t], jnp.float32),
                jnp.asarray([k or 0], jnp.int32),
                jnp.asarray([p or 1.0], jnp.float32)))[0])
            assert a == b, (t, k, p, idx)


def test_filter_logits_static_vs_runtime(rng):
    from deeplearning4j_tpu.utils.sampling import _filter_logits

    logits = jnp.asarray(rng.randn(3, 17).astype(np.float32))
    for k, p in [(4, None), (None, 0.7), (5, 0.8)]:
        stat = _filter_logits(logits, k, p)
        run = _filter_logits(
            logits,
            None if k is None else jnp.full((3,), k, jnp.int32),
            None if p is None else jnp.full((3,), p, jnp.float32))
        np.testing.assert_array_equal(np.asarray(stat), np.asarray(run))
    # runtime disabled sentinels == no filtering
    off = _filter_logits(logits, jnp.zeros((3,), jnp.int32),
                         jnp.ones((3,), jnp.float32))
    np.testing.assert_array_equal(np.asarray(off), np.asarray(logits))


# ---------------------------------------------------------- prefix sharing
def test_prefix_cache_refcounts_unit():
    cache = PagedKVCache(num_pages=12, page_size=4, pages_per_slot=4)
    prompt = list(range(9))                       # 2 full pages + 1
    pages_a, shared_a = cache.admit(prompt, 4)    # occupancy 12 -> 3 pages
    assert shared_a == 0 and len(pages_a) == 3
    pages_b, shared_b = cache.admit(prompt, 4)    # identical prefix
    assert shared_b == 8                          # both full pages shared
    assert pages_b[:2] == pages_a[:2]
    assert cache.refcount(pages_a[0]) == 2
    # a diverging prompt shares only the first page
    pages_c, shared_c = cache.admit(prompt[:4] + [27, 27, 27, 27, 1], 4)
    assert shared_c == 4 and pages_c[0] == pages_a[0]
    assert cache.refcount(pages_a[0]) == 3
    cache.free(pages_b)
    cache.free(pages_c)
    assert cache.refcount(pages_a[0]) == 1
    cache.free(pages_a)
    assert cache.free_pages == 11                 # everything returned
    assert cache.as_dict()["prefix_index_size"] == 0
    with pytest.raises(AssertionError):
        cache.free(pages_a[:1])                   # double free is a bug


def test_prefix_share_cap_leaves_one_token():
    """A prompt whose every page is cached must still prefill >= 1 token
    (the last position's logits seed the first sample and are not part
    of the shared pages)."""
    cache = PagedKVCache(num_pages=12, page_size=4, pages_per_slot=4)
    prompt = list(range(8))                       # exactly 2 pages
    a, _ = cache.admit(prompt, 4)
    b, shared = cache.admit(prompt, 4)
    assert shared == 4                            # NOT 8: one page re-run
    cache.free(a)
    cache.free(b)


def test_prefix_sharing_under_load(engine, rng):
    """Two in-flight requests with the same long prompt share pages
    (visible in allocator counters) and still produce identical greedy
    tokens."""
    before = engine.cache.shared_pages
    prompt = rng.randint(0, VOCAB, 11).tolist()   # 2 full pages @ ps=4
    a = engine.submit(prompt, 12)
    # make sure A is RUNNING (holding its pages) when B admits
    first = next(iter(a.stream()))
    b = engine.submit(prompt, 12)
    ta = [first] + [t for t in a.stream()]
    tb = b.result(timeout=60)
    assert ta == tb
    assert engine.cache.shared_pages > before
    assert b.ttft_s is not None


# ------------------------------------------------- admission / backpressure
def test_page_exhaustion_sheds_429_not_hang(lm):
    """Slots full + pages pinned by long-running requests: a bounded
    pending queue sheds new arrivals with QueueFullError (HTTP 429)
    promptly instead of queueing unbounded or hanging."""
    eng = GenerationEngine(lm, slots=2, page_size=4, max_context=32,
                           max_queue=2, deadline_s=30.0)
    eng.start()
    try:
        long = [eng.submit([1, 2, 3], 24) for _ in range(2)]   # fill slots
        for h in long:                    # both RUNNING (pages pinned)
            next(iter(h.stream()))
        queued = [eng.submit([4, 5], 24) for _ in range(2)]    # fill queue
        t0 = time.perf_counter()
        with pytest.raises(QueueFullError) as ei:
            eng.submit([6], 24)
        assert time.perf_counter() - t0 < 1.0      # shed, not hung
        assert ei.value.http_status == 429
        for h in long + queued:
            assert h.result(timeout=60)
    finally:
        eng.stop()


def test_request_that_can_never_fit_rejected(engine):
    with pytest.raises(ValueError):
        engine.submit(list(range(20)), 1000)       # > max_context


def test_over_bucket_prompt_rejected_at_submit(lm):
    """A prompt longer than the largest prefill bucket must fail the
    SUBMITTER with a clean ValueError — not detonate on the decode
    thread and take the whole running batch down with it."""
    eng = GenerationEngine(lm, slots=2, page_size=4, max_context=32,
                           max_queue=8, prefill_buckets=(8,))
    eng.start()
    try:
        running = eng.submit([1, 2, 3], 20)
        with pytest.raises(ValueError):
            eng.submit(list(range(12)), 4)         # > bucket 8
        # the running batch was untouched by the rejection
        assert len(running.result(timeout=60)) == 20
    finally:
        eng.stop()


def test_prefill_failure_terminates_request_not_zombie(lm):
    """A prefill that raises must FAIL the admitted request (waiters
    released, pages freed) instead of leaving it permanently pending
    while its pages leak."""
    eng = GenerationEngine(lm, slots=2, page_size=4, max_context=32,
                           max_queue=8, prefill_buckets=(8,))
    eng.start()
    try:
        free0 = eng.cache.free_pages
        mv = eng.models.active("default")
        progs = eng._programs[mv.key]
        orig = progs.prefill
        boom = {"n": 0}

        def exploding(*a, **kw):
            if boom["n"] == 0:
                boom["n"] += 1
                raise RuntimeError("injected prefill failure")
            return orig(*a, **kw)

        progs.prefill = exploding
        doomed = eng.submit([1, 2, 3, 4], 6)
        with pytest.raises(RuntimeError, match="injected"):
            doomed.result(timeout=30)              # released, not hung
        assert doomed.finish_reason == "error"
        # pages freed, engine recovered: next request serves normally
        deadline = time.monotonic() + 10
        while eng.cache.free_pages < free0 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert eng.cache.free_pages == free0
        assert len(eng.generate([5, 6], 4)) == 4
    finally:
        eng.stop()


def test_stop_drain_timeout_sheds_503_not_504(lm):
    """Requests still queued when the drain window closes failed because
    the ENGINE stopped, not because their deadline passed: 503."""
    eng = GenerationEngine(lm, slots=1, page_size=4, max_context=32,
                           max_queue=8, deadline_s=600.0,
                           prefill_buckets=(8,))
    eng.start()
    blocker = eng.submit([1, 2], 30)
    next(iter(blocker.stream()))
    queued = eng.submit([3, 4], 30)                # waits behind blocker
    eng.stop(drain=True, timeout=0.01)             # drain window too short
    with pytest.raises(Exception) as ei:
        queued.result(timeout=10)
    from deeplearning4j_tpu.serving.admission import ShuttingDownError

    assert isinstance(ei.value, ShuttingDownError)
    assert ei.value.http_status == 503


def test_queued_deadline_purged_504(lm):
    eng = GenerationEngine(lm, slots=1, page_size=4, max_context=32,
                           max_queue=8, deadline_s=30.0,
                           prefill_buckets=(8,))
    eng.start()
    try:
        blocker = eng.submit([1, 2], 30)
        next(iter(blocker.stream()))   # RUNNING: the only slot + all pages
        doomed = eng.submit([3, 4], 30, deadline_s=0.02)
        with pytest.raises(DeadlineExceededError) as ei:
            doomed.result(timeout=30)
        assert ei.value.http_status == 504
        assert doomed.trace_id in str(ei.value)
        assert blocker.result(timeout=60)
    finally:
        eng.stop()


def test_cancel_frees_pages_mid_flight(engine, rng):
    used0 = engine.cache.used_pages
    h = engine.submit(rng.randint(0, VOCAB, 5).tolist(), 28)
    next(iter(h.stream()))        # running
    h.cancel()
    h.done.wait(timeout=30)
    assert h.finish_reason == "cancelled"
    deadline = time.monotonic() + 10
    while engine.cache.used_pages > used0 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert engine.cache.used_pages == used0


def test_stop_token_ends_stream(engine, rng):
    prompt = rng.randint(0, VOCAB, 4).tolist()
    free = engine.generate(prompt, 12).tolist()
    stop = free[3]
    first = free.index(stop)      # stop may occur earlier in the stream
    h = engine.submit(prompt, 12, stop_token=stop)
    toks = h.result(timeout=60)
    assert h.finish_reason == "stop"
    assert toks == free[:first + 1]   # identical up to and incl. the stop


# ----------------------------------------------------------- zero recompile
def test_zero_steady_state_compiles(engine, rng):
    mv = engine.models.active("default")
    warm = mv.detector.compile_count
    handles = [engine.submit(p, int(rng.randint(1, 8)),
                             temperature=float(rng.rand() * 1.4),
                             top_k=int(rng.randint(0, 6)) or None,
                             seed=i)
               for i, p in enumerate(_prompts(rng, 16))]
    for h in handles:
        h.result(timeout=60)
    assert mv.detector.compile_count == warm
    assert mv.detector.recompile_count == 0
    progs = engine._programs[mv.key]
    sizes = [f._cache_size() for f in progs._prefill.values()]
    sizes.append(progs._decode._cache_size())
    assert sizes == [1] * len(sizes)   # one REAL XLA program each


# ----------------------------------------------------------------- hot-swap
def test_hot_swap_zero_drops(lm, rng):
    """Deploy a new version (different weights, same architecture) while
    a continuous stream of requests decodes: every stream completes,
    none error, and the registry serves the new version afterwards."""
    eng = GenerationEngine(lm, slots=4, page_size=4, max_context=32,
                           max_queue=64, deadline_s=60.0)
    eng.start()
    try:
        stop = threading.Event()
        results, errors = [], []
        lock = threading.Lock()

        def client(cid):
            r = np.random.RandomState(cid)
            while not stop.is_set():
                try:
                    toks = eng.generate(
                        r.randint(0, VOCAB, r.randint(1, 8)).tolist(),
                        int(r.randint(2, 6)))
                except Exception as e:    # pragma: no cover - must not happen
                    with lock:
                        errors.append(e)
                    return
                with lock:
                    results.append(len(toks))

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(3)]
        [t.start() for t in threads]
        time.sleep(0.3)
        mv2 = eng.deploy("default", small_lm(seed=777))
        time.sleep(0.3)
        stop.set()
        [t.join(30) for t in threads]
        assert not errors
        assert len(results) > 10
        assert eng.models.active("default").version == mv2.version == 2
        # swapped weights actually serve: greedy output differs from v1
        prompt = rng.randint(0, VOCAB, 6)
        from deeplearning4j_tpu.models.decode import generate as scan_gen

        assert (eng.generate(prompt, 8).tolist()
                == scan_gen(small_lm(seed=777), prompt[None], 8,
                            temperature=0.0)[0].tolist())
    finally:
        eng.stop()


def test_incompatible_deploy_rejected(lm):
    eng = GenerationEngine(lm, slots=2, page_size=4, max_context=16,
                           prefill_buckets=(8,))
    eng.start()
    try:
        bad = small_lm(layers=1)          # different cache geometry
        with pytest.raises(ValueError):
            eng.deploy("default", bad)
        # old version still serves
        assert eng.generate([1, 2, 3], 3).shape == (3,)
        assert eng.models.active("default").version == 1
    finally:
        eng.stop()


def test_rollback_between_steps(lm, rng):
    eng = GenerationEngine(lm, slots=2, page_size=4, max_context=16,
                           prefill_buckets=(8,))
    eng.start()
    try:
        prompt = rng.randint(0, VOCAB, 4)
        v1 = eng.generate(prompt, 6).tolist()
        eng.deploy("default", small_lm(seed=31337), retain_old=True)
        v2 = eng.generate(prompt, 6).tolist()
        eng.rollback()
        back = eng.generate(prompt, 6).tolist()
        assert back == v1
        assert v2 != v1                    # different weights really served
    finally:
        eng.stop()


# --------------------------------------------------------------- drain/stop
def test_stop_drain_serves_queued(lm):
    eng = GenerationEngine(lm, slots=1, page_size=4, max_context=16,
                           max_queue=8, prefill_buckets=(8,))
    eng.start()
    hs = [eng.submit([1, 2], 4) for _ in range(3)]
    eng.stop(drain=True)
    for h in hs:
        assert len(h.result(timeout=5)) == 4


def test_stop_no_drain_fails_fast(lm):
    eng = GenerationEngine(lm, slots=1, page_size=4, max_context=16,
                           max_queue=8, prefill_buckets=(8,))
    eng.start()
    hs = [eng.submit([1, 2], 14) for _ in range(4)]
    eng.stop(drain=False)
    outcomes = []
    for h in hs:
        try:
            h.result(timeout=5)
            outcomes.append("ok")
        except Exception as e:
            outcomes.append(type(e).__name__)
    # nobody hangs; later arrivals are shed with the 503 error
    assert "ShuttingDownError" in outcomes


# -------------------------------------------------------------------- HTTP
def test_http_generate_full_sse_and_errors(lm):
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.streaming.serving import InferenceServer

    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater("sgd", learning_rate=0.1).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax")).build())
    pred = MultiLayerNetwork(conf).init()
    gen = GenerationEngine(lm, slots=2, page_size=4, max_context=16,
                           max_queue=8, prefill_buckets=(8,)).start()
    srv = InferenceServer(pred, generation=gen, access_log=True)
    port = srv.start()
    try:
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        c.request("POST", "/generate", json.dumps(
            {"prompt": [1, 2, 3], "max_tokens": 5, "seed": 3,
             "temperature": 0.7}),
            {"X-Request-Id": "gen-trace-1"})
        r = c.getresponse()
        body = json.loads(r.read())
        assert r.status == 200
        assert len(body["tokens"]) == 5
        assert body["trace_id"] == "gen-trace-1"
        assert body["finish_reason"] == "length"
        assert body["ttft_ms"] is not None

        # SSE: one event per token + terminal done event
        c.request("POST", "/generate", json.dumps(
            {"prompt": [4, 5], "max_tokens": 4, "stream": True}))
        r = c.getresponse()
        assert r.status == 200
        assert r.getheader("Content-Type") == "text/event-stream"
        events = [json.loads(line[len("data: "):])
                  for line in r.read().decode().splitlines()
                  if line.startswith("data: ")]
        assert len(events) == 5 and events[-1]["done"] is True
        assert [e["index"] for e in events[:-1]] == [0, 1, 2, 3]
        assert all(isinstance(e["token"], int) for e in events[:-1])
        assert events[-1]["tokens"] == 4

        # malformed body -> structured 400
        c.request("POST", "/generate", json.dumps({"max_tokens": 3}))
        r = c.getresponse()
        assert r.status == 400 and "prompt" in json.loads(r.read())["error"]

        # oversized request -> 400, not a hang
        c.request("POST", "/generate", json.dumps(
            {"prompt": list(range(10)), "max_tokens": 10_000}))
        r = c.getresponse()
        assert r.status == 400
    finally:
        srv.stop()
        gen.stop()


# ------------------------------------------------------------ observability
def test_metrics_and_spans(lm, rng):
    from deeplearning4j_tpu.observability import get_registry
    from deeplearning4j_tpu.observability.tracing import get_tracer

    eng = GenerationEngine(lm, slots=2, page_size=4, max_context=16,
                           max_queue=8, prefill_buckets=(8,))
    eng.start()
    try:
        h = eng.submit(rng.randint(0, VOCAB, 5).tolist(), 6,
                       trace_id="gen-span-1")
        h.result(timeout=60)
        reg = get_registry()
        assert reg.get_value("dl4j_decode_requests_total",
                             status="length") >= 1
        assert reg.get_value("dl4j_decode_tokens_total",
                             model="default") >= 6
        spans = get_tracer().spans_for_trace("gen-span-1")
        assert any(s.name == "generation_request" for s in spans)
        # decode steps are step_guard steps: flight events exist
        from deeplearning4j_tpu.observability import get_flight_recorder
        kinds = [e.kind for e in get_flight_recorder().events()]
        assert "step_begin" in kinds
    finally:
        eng.stop()
