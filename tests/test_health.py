"""Cluster health & diagnostics: straggler detection (an injected slow
worker in a real 4-replica ParallelWrapper run is NAMED — metric +
warning), step watchdog + flight recorder (a deliberately hung fit step
dumps a JSONL report containing the step events and the live span stack),
SLO-driven HealthEvaluator verdicts, /health on both servers, and the
concurrent-snapshot hammer for the registry."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.layers.dense import DenseLayer, OutputLayer
from deeplearning4j_tpu.observability import (
    ClusterStatsAggregator, FlightRecorder, HealthEvaluator, HealthRule,
    MetricsRegistry, SpanTracer, StepWatchdog, StragglerDetector,
    WorkerTelemetry, get_registry, get_tracer, histogram_quantile,
    read_flight_report, set_flight_recorder, set_registry, set_tracer,
    step_guard,
)
from deeplearning4j_tpu.observability import flightrecorder as fr_mod


@pytest.fixture(autouse=True)
def fresh_telemetry():
    """Isolate registry, tracer, flight recorder, and watchdog per test."""
    old_reg = get_registry()
    old_tr = get_tracer()
    reg = set_registry(MetricsRegistry())
    set_tracer(SpanTracer())
    set_flight_recorder(FlightRecorder())
    yield reg
    wd = fr_mod.get_watchdog()
    if wd is not None:
        wd.uninstall()
    set_registry(old_reg)
    set_tracer(old_tr)
    set_flight_recorder(FlightRecorder())


def make_net(seed=7, n_in=8):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(seed)
         .updater("sgd", learning_rate=0.1).list()
         .layer(DenseLayer(n_in=n_in, n_out=16))
         .layer(OutputLayer(n_in=16, n_out=4)).build())).init()


def make_data(n=32, n_in=8, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.rand(n, n_in).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, n)]
    return x, y


# ------------------------------------------------------- straggler detection

def test_straggler_detector_names_slow_worker(fresh_telemetry):
    warns = []
    det = StragglerDetector("unit", threshold=2.0, min_steps=3,
                            warn=warns.append)
    flagged = False
    for _ in range(8):
        for w in range(4):
            hit = det.observe(w, 0.010 if w != 2 else 0.050,
                              phases={"dispatch": 0.050})
            flagged = flagged or (hit and w == 2)
    assert flagged
    assert det.stragglers().keys() == {"2"}
    assert fresh_telemetry.get_value(
        "dl4j_stragglers_total", component="unit", worker="2") > 0
    # exactly one rate-limited warning, naming the worker + phase breakdown
    assert len(warns) == 1
    assert "worker 2" in warns[0] and "dispatch" in warns[0]
    assert "cluster median" in warns[0]


def test_straggler_detector_works_with_two_workers(fresh_telemetry):
    """The cluster reference excludes the candidate worker, so even a
    2-worker (or 2-stage pipeline) cluster can name its slow half."""
    det = StragglerDetector("pair", threshold=2.0, min_steps=3,
                            warn=lambda m: None)
    for _ in range(6):
        det.observe("0", 0.010)
        det.observe("1", 0.060)
    assert det.stragglers().keys() == {"1"}


def test_straggler_detector_jitter_floor(fresh_telemetry):
    """Sub-millisecond 'stragglers' are scheduling noise, not actionable:
    the min_excess_s floor keeps a 3x-but-40-microsecond excess quiet."""
    det = StragglerDetector("tiny", threshold=2.0, min_steps=3,
                            warn=lambda m: None)
    for _ in range(8):
        for w in range(4):
            det.observe(w, 0.00002 if w != 2 else 0.00006)
    assert det.stragglers() == {}


def test_straggler_detector_quiet_on_healthy_cluster(fresh_telemetry):
    warns = []
    det = StragglerDetector("unit2", threshold=2.0, min_steps=3,
                            warn=warns.append)
    rs = np.random.RandomState(0)
    for _ in range(20):
        for w in range(4):
            # +-20% jitter never crosses a 2x-median threshold
            assert not det.observe(w, 0.010 * (0.8 + 0.4 * rs.rand()))
    assert det.stragglers() == {}
    assert warns == []
    assert fresh_telemetry.get_value(
        "dl4j_stragglers_total", component="unit2", worker="0") is None


def test_worker_telemetry_families_and_cluster_view(fresh_telemetry):
    wt = WorkerTelemetry("comp", min_steps=3)
    for _ in range(6):
        for w in range(3):
            wt.observe(w, 0.01 * (w + 1), batch=32)
    fam = fresh_telemetry.get("dl4j_worker_step_seconds")
    assert fam.get(component="comp", worker="0").count == 6
    tput = fresh_telemetry.get_value(
        "dl4j_worker_samples_per_second", component="comp", worker="2")
    assert tput == pytest.approx(32 / 0.03)
    view = wt.cluster_view()
    assert view["workers"] == 3
    assert view["slowest_worker"] == "2"
    assert view["step_seconds"]["max"] == pytest.approx(0.03)
    assert view["step_seconds"]["p50"] == pytest.approx(0.02)
    assert view["samples_per_second_total"] > 0


def test_cluster_aggregator_merges_plain_dicts():
    snaps = [
        {"worker": "a", "count": 4, "mean": 0.01, "samples": [0.01] * 4,
         "samples_per_second": 100.0},
        {"worker": "b", "count": 4, "mean": 0.04, "samples": [0.04] * 4,
         "samples_per_second": 25.0},
    ]
    view = ClusterStatsAggregator.merge(snaps)
    assert view["slowest_worker"] == "b"
    assert view["steps"] == 8
    assert view["samples_per_second_total"] == pytest.approx(125.0)
    assert view["step_seconds"]["mean"] == pytest.approx(0.025)
    # empty / no-data snapshots are ignored, not crashed on
    assert ClusterStatsAggregator.merge([])["workers"] == 0


def test_cluster_aggregator_merge_forward_compat():
    """Mixed-version fleets: snapshots with missing keys, unknown extra
    keys, or a different schema version are log-and-skip (or tolerated),
    NEVER raised — a v2 worker must not take down a v1 aggregator."""
    ok = {"worker": "good", "count": 3, "mean": 0.02,
          "samples": [0.02] * 3, "samples_per_second": 50.0}
    # unknown extra keys from a newer publisher are simply ignored
    newer = dict(ok, worker="newer", mean=0.04,
                 some_v2_field={"nested": True}, zstd_dict=b"\x00")
    view = ClusterStatsAggregator.merge([ok, newer])
    assert view["workers"] == 2
    assert view["slowest_worker"] == "newer"
    # a mismatched schema version is skipped, not merged, not raised
    alien = dict(ok, worker="alien", schema=99)
    view = ClusterStatsAggregator.merge([ok, alien])
    assert view["workers"] == 1
    # a matching explicit schema tag still merges
    tagged = dict(ok, worker="tagged",
                  schema=ClusterStatsAggregator.SNAPSHOT_SCHEMA)
    assert ClusterStatsAggregator.merge([ok, tagged])["workers"] == 2


def test_cluster_aggregator_merge_never_raises_on_garbage():
    """Every malformed shape the wire could produce: wrong types, junk
    counts, non-numeric samples — merged output stays well-formed."""
    ok = {"worker": "good", "count": 2, "mean": 0.01,
          "samples": [0.01, 0.01], "samples_per_second": 10.0}
    garbage = [
        None, {}, "not-a-dict", 42, [],
        {"worker": "no-count"},
        {"worker": "zero", "count": 0},
        {"worker": "bool-count", "count": True},
        {"worker": "str-count", "count": "three"},
        {"worker": "bad-mean", "count": 2, "mean": "fast"},
        {"worker": "bad-samples", "count": 2, "mean": 0.01,
         "samples": "oops"},
        {"worker": "mixed-samples", "count": 2, "mean": 0.01,
         "samples": [0.01, "nan-ish", None, True]},
        {"worker": "bad-sps", "count": 2, "mean": 0.01,
         "samples_per_second": {"rate": 1}},
    ]
    view = ClusterStatsAggregator.merge([ok] + garbage)
    # the one usable snapshot plus the count-bearing degraded ones merge;
    # nothing raises and the summary stays numeric
    assert view["slowest_worker"] == "good"
    assert view["steps"] >= 2
    assert isinstance(view["samples_per_second_total"], float)
    for k in ("mean", "p50", "max"):
        assert isinstance(view["step_seconds"][k], float)
    # all-garbage input degrades to the empty view
    assert ClusterStatsAggregator.merge(garbage[:5])["workers"] == 0


def test_cluster_aggregator_from_registry(fresh_telemetry):
    wt = WorkerTelemetry("regview", min_steps=2)
    for _ in range(5):
        wt.observe("0", 0.002)
        wt.observe("1", 0.2)
    view = ClusterStatsAggregator.from_registry(component="regview")
    assert view["workers"] == 2
    assert view["slowest_worker"] == "1"
    assert view["step_seconds"]["max"] == pytest.approx(0.2)


# --------------------------------------- acceptance: ParallelWrapper run

def test_parallel_wrapper_straggler_acceptance(fresh_telemetry, monkeypatch):
    """A deliberately slowed worker in a 4-replica ParallelWrapper run is
    NAMED by the straggler detector — metric + warning (acceptance
    criterion).  Virtual CPU devices execute one lockstep XLA program, so
    the slowdown is injected at the per-replica timing seam the real
    measurement (`_worker_step_times`) feeds."""
    import jax

    from deeplearning4j_tpu.backend import device as backend
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper

    K = 4
    real = ParallelWrapper._worker_step_times

    def slowed(self, losses, dispatch_s):
        times = real(self, losses, dispatch_s)
        times["2"] = times["2"] + 0.05   # worker 2 is 'slow'
        return times

    monkeypatch.setattr(ParallelWrapper, "_worker_step_times", slowed)
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    net = make_net(n_in=6)

    rs = np.random.RandomState(1)
    batches = []
    for _ in range(K * 6):   # 6 windows -> 6 observations per worker
        x = rs.rand(4, 6).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 4)]
        batches.append(DataSet(x, y))

    pw = ParallelWrapper(net, workers=K, averaging_frequency=1, mesh=mesh,
                         collect_worker_stats=True)
    warns = []
    # detector is created lazily at fit(); pre-create by fitting one window
    pw.fit(iter(batches[:K]))
    pw.straggler_detector.warn = warns.append
    pw.fit(iter(batches[K:]))

    assert pw.straggler_detector.stragglers().keys() == {"2"}
    assert fresh_telemetry.get_value(
        "dl4j_stragglers_total", component="parallel_wrapper",
        worker="2") > 0
    assert any("worker 2" in w for w in warns)
    view = pw.cluster_stats()
    assert view["slowest_worker"] == "2"
    assert view["workers"] == K
    # healthy workers were NOT flagged
    for w in ("0", "1", "3"):
        assert fresh_telemetry.get_value(
            "dl4j_stragglers_total", component="parallel_wrapper",
            worker=w) is None


def test_sync_master_publishes_worker_stats(fresh_telemetry):
    import jax

    from deeplearning4j_tpu.backend import device as backend
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
    from deeplearning4j_tpu.parallel.training_master import (
        DistributedNetwork, SyncTrainingMaster,
    )

    net = make_net(n_in=8)
    x, y = make_data(64)
    master = SyncTrainingMaster(
        mesh=backend.default_mesh(data=4, devices=jax.devices()[:4]),
        collect_stats=True)
    DistributedNetwork(net, master).fit(ListDataSetIterator(DataSet(x, y), 16))
    stats = master.training_stats()
    assert "cluster" in stats and stats["cluster"]["workers"] >= 1
    fam = fresh_telemetry.get("dl4j_worker_step_seconds")
    assert fam is not None
    workers = {dict(lp).get("worker") for lp, _c in fam.samples()
               if dict(lp).get("component") == "sync_master"}
    assert len(workers) >= 1       # one per addressable device


def test_pipeline_master_publishes_stage_times(fresh_telemetry):
    import jax

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
    from deeplearning4j_tpu.parallel.pipeline import (
        PipelineParallelTrainingMaster,
    )
    from deeplearning4j_tpu.parallel.training_master import DistributedNetwork

    net = make_net(n_in=6)
    x, y = make_data(16, n_in=6)
    master = PipelineParallelTrainingMaster(
        n_stages=2, n_microbatches=4, devices=jax.devices()[:2],
        mode="orchestrated")
    DistributedNetwork(net, master).fit(
        ListDataSetIterator(DataSet(x, y), 16))
    fam = fresh_telemetry.get("dl4j_worker_step_seconds")
    stages = {dict(lp).get("worker") for lp, _c in fam.samples()
              if dict(lp).get("component") == "pipeline_master"}
    assert stages == {"stage0", "stage1"}
    assert master.training_stats()["cluster"]["workers"] == 2


# ------------------------------------------------- flight recorder/watchdog

def test_flight_recorder_ring_buffer_bounded():
    rec = FlightRecorder(capacity=4)
    for i in range(10):
        rec.record("e", i=i)
    evs = rec.to_list()
    assert len(evs) == 4
    assert rec.dropped == 6
    assert [e["i"] for e in evs] == [6, 7, 8, 9]
    assert all(e["kind"] == "e" for e in evs)


def test_watchdog_hang_dump_acceptance(tmp_path, fresh_telemetry):
    """A deliberately hung step produces a flight-recorder dump containing
    the step events and live span stack (acceptance criterion) — here via
    a real MultiLayerNetwork fit whose train step is wrapped to stall past
    the watchdog deadline."""
    net = make_net()
    x, y = make_data(16)
    net.fit(x, y)   # populate the jit cache
    real_step = net._jit_cache[("train_step", False)]

    def stalled(*a, **kw):
        time.sleep(0.6)
        return real_step(*a, **kw)

    net._jit_cache[("train_step", False)] = stalled
    wd = StepWatchdog(deadline_s=0.15, report_dir=str(tmp_path),
                      poll_interval_s=0.05).install()
    try:
        net.fit(x, y)        # hangs 0.6s inside the armed fit_step
    finally:
        wd.uninstall()
    assert wd.dumps, "watchdog produced no report"
    recs = read_flight_report(wd.dumps[0])
    meta = recs[0]
    assert meta["record"] == "meta" and meta["reason"] == "hang"
    assert meta["context"]["step"] == "fit_step"
    events = [r for r in recs if r["record"] == "event"]
    assert any(e["kind"] == "step_begin" and e["name"] == "fit_step"
               for e in events)
    # the hung step had begun but not ended at dump time
    begun = sum(1 for e in events
                if e["kind"] == "step_begin" and e["name"] == "fit_step")
    ended = sum(1 for e in events
                if e["kind"] == "step_end" and e["name"] == "fit_step")
    assert begun == ended + 1
    live = [r for r in recs if r["record"] == "live_span"]
    assert any(s["name"] == "fit_step" for s in live), \
        "live span stack missing the hung step"
    assert any(r["record"] == "registry" for r in recs)
    assert any(r["record"] == "device_memory" for r in recs)
    assert fresh_telemetry.get_value(
        "dl4j_watchdog_dumps_total", reason="hang") == 1


def test_fit_exception_produces_crash_dump(tmp_path, fresh_telemetry):
    net = make_net()
    x, y = make_data(16)
    net.fit(x, y)
    wd = StepWatchdog(deadline_s=30.0, report_dir=str(tmp_path)).install()
    try:
        with pytest.raises(Exception):
            net.fit(np.full((16, 8), np.nan, np.float32), "not labels")
    finally:
        wd.uninstall()
    assert wd.dumps
    recs = read_flight_report(wd.dumps[0])
    assert recs[0]["reason"] == "fit_exception"
    assert recs[0]["context"]["model"] == "MultiLayerNetwork"
    assert "error" in recs[0]["context"]
    assert fresh_telemetry.get_value(
        "dl4j_watchdog_dumps_total", reason="fit_exception") == 1


def test_step_guard_records_serving_dispatch(fresh_telemetry):
    from deeplearning4j_tpu.observability import get_flight_recorder
    from deeplearning4j_tpu.serving import ServingEngine

    eng = ServingEngine(make_net(n_in=8), max_batch=4,
                        example=np.zeros((8,), np.float32))
    eng.start(warmup=False)
    try:
        eng.predict(np.random.rand(2, 8).astype(np.float32))
    finally:
        eng.stop()
    kinds = [(e["kind"], e.get("name")) for e in
             get_flight_recorder().to_list()]
    assert ("step_begin", "serving_dispatch") in kinds
    assert ("step_end", "serving_dispatch") in kinds


# ----------------------------------------------------------------- health

def test_histogram_quantile(fresh_telemetry):
    h = fresh_telemetry.histogram("q_seconds", "q",
                                  buckets=(0.01, 0.1, 1.0)).labels()
    for _ in range(90):
        h.observe(0.005)
    for _ in range(10):
        h.observe(0.5)
    assert histogram_quantile(h, 0.5) <= 0.01
    assert 0.1 < histogram_quantile(h, 0.99) <= 1.0
    empty = fresh_telemetry.histogram("q2_seconds", "q").labels()
    assert np.isnan(histogram_quantile(empty, 0.99))


def test_health_rules_verdicts(fresh_telemetry):
    reg = fresh_telemetry
    h = reg.histogram("dl4j_fit_step_seconds", "t",
                      labels=("model",)).labels(model="M")
    for _ in range(100):
        h.observe(0.3)
    reg.gauge("dl4j_fit_samples_per_second", "s",
              labels=("model",)).set(50.0, model="M")
    reg.counter("dl4j_recompiles_total", "r", labels=("fn",)).inc(
        5, fn="step")

    ev = HealthEvaluator([
        HealthRule("step_p99", "max_step_p99", 0.1),
        HealthRule("tput", "min_throughput", 100.0),
        HealthRule("recompiles", "max_recompiles", 3),
    ], component="t1")
    verdict = ev.evaluate()
    assert not verdict.healthy
    assert {r["name"] for r in verdict.failing} == {
        "step_p99", "tput", "recompiles"}
    by_name = {r["name"]: r for r in verdict.results}
    assert by_name["step_p99"]["observed"] > 0.1
    assert by_name["recompiles"]["observed"] == 5.0
    assert reg.get_value("dl4j_health_status", component="t1") == 0.0

    ok = HealthEvaluator([
        HealthRule("step_p99", "max_step_p99", 1.0),
        HealthRule("tput", "min_throughput", 10.0),
        HealthRule("recompiles", "max_recompiles", 10),
    ], component="t2").evaluate()
    assert ok.healthy and ok.failing == []
    assert reg.get_value("dl4j_health_status", component="t2") == 1.0


def test_min_throughput_ignores_stale_low_child(fresh_telemetry):
    """The throughput floor reads the BEST child: a finished side model's
    stale low gauge must not fail /health forever."""
    fam = fresh_telemetry.gauge("dl4j_fit_samples_per_second", "s",
                                labels=("model",))
    fam.set(5.0, model="tiny_warmup")       # trained once, long done
    fam.set(10000.0, model="production")
    res = HealthRule("tput", "min_throughput", 100.0).evaluate(
        fresh_telemetry)
    assert res["ok"] and res["observed"] == 10000.0


def test_live_spans_prunes_dead_empty_threads(fresh_telemetry):
    tr = SpanTracer()

    def worker():
        with tr.span("work"):
            pass

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert tr.live_spans() == []          # prunes the dead thread's slot
    assert tr._live == {}


def test_health_rule_no_data_and_require_data(fresh_telemetry):
    lax_rule = HealthRule("p99", "max_step_p99", 0.1).evaluate(
        fresh_telemetry)
    assert lax_rule["ok"] and lax_rule["observed"] is None
    strict = HealthRule("p99", "max_step_p99", 0.1,
                        require_data=True).evaluate(fresh_telemetry)
    assert not strict["ok"]


def test_health_predicate_rule(fresh_telemetry):
    rule = HealthRule("alive", "predicate",
                      fn=lambda extra: (extra, extra, "thread check"))
    assert rule.evaluate(fresh_telemetry, extra=True)["ok"]
    assert not rule.evaluate(fresh_telemetry, extra=False)["ok"]
    boom = HealthRule("alive", "predicate",
                      fn=lambda extra: 1 / 0).evaluate(fresh_telemetry)
    assert not boom["ok"] and "raised" in boom["detail"]


# -------------------------------------------------------------- endpoints

def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_inference_server_health_endpoint(fresh_telemetry):
    from deeplearning4j_tpu.streaming.serving import InferenceServer

    server = InferenceServer(make_net(), max_batch=8, port=0)
    port = server.start()
    url = f"http://127.0.0.1:{port}"
    try:
        status, body = _get(f"{url}/health")
        assert status == 200 and body["healthy"] is True
        names = {r["name"] for r in body["rules"]}
        assert {"dispatcher_alive", "queue_depth",
                "recompile_budget"} <= names
        # violate an SLO: a custom rule that can never pass
        server.health.rules.append(
            HealthRule("always_red", "max_queue_depth", -1.0,
                       metric="dl4j_serving_queue_depth"))
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{url}/health", timeout=10)
        assert ei.value.code == 503
        body = json.loads(ei.value.read())
        assert body["healthy"] is False
        assert "always_red" in body["failing"]
        red = [r for r in body["rules"] if r["name"] == "always_red"][0]
        assert red["observed"] is not None and red["limit"] == -1.0
        # /healthz is LIVENESS only: a failing SLO rule does NOT 503 a
        # live dispatcher (restarting busy-but-working instances under
        # load cascades), and no rules are evaluated on that path
        status, hz = _get(f"{url}/healthz")
        assert status == 200 and hz["status"] == "ok"
        assert hz["dispatcher_alive"] is True
    finally:
        server.stop()


def test_ui_server_metrics_and_health(fresh_telemetry):
    from deeplearning4j_tpu.ui.server import UIServer

    net = make_net()
    x, y = make_data(16)
    net.fit(x, y)
    ui = UIServer(port=0)
    port = ui.start()
    url = f"http://127.0.0.1:{port}"
    try:
        with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
            assert r.status == 200
            assert r.headers.get("Content-Type", "").startswith("text/plain")
            text = r.read().decode()
        assert "dl4j_fit_step_seconds_bucket" in text
        assert "dl4j_fit_iterations_total" in text
        status, body = _get(f"{url}/health")
        assert status == 200 and body["healthy"] is True
        assert body["component"] == "training"
    finally:
        ui.stop()


def test_ui_server_health_failure(fresh_telemetry):
    from deeplearning4j_tpu.ui.server import UIServer

    ui = UIServer(port=0, health=HealthEvaluator(
        [HealthRule("tput", "min_throughput", 1e9, require_data=True)],
        component="training"))
    port = ui.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/health",
                                   timeout=10)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["failing"] == ["tput"]
    finally:
        ui.stop()


# ------------------------------------------------ registry snapshot hammer

def test_registry_snapshot_hammer(fresh_telemetry):
    """Concurrent mutation (new families, new children, observes) vs
    continuous to_prometheus()/to_json(): no exceptions, and every
    histogram snapshot is internally CONSISTENT (cumulative buckets end at
    count; sum consistent with count within the value range)."""
    reg = fresh_telemetry
    stop = threading.Event()
    errors = []

    def writer(i):
        try:
            c = reg.counter("ham_total", "h", labels=("t",))
            h = reg.histogram("ham_seconds", "h", labels=("t",))
            g = reg.gauge("ham_gauge", "h", labels=("t",))
            n = 0
            while not stop.is_set():
                c.inc(t=str(i))
                h.observe(0.01 * ((n % 10) + 1), t=str(i))
                g.set(n, t=str(i))
                reg.gauge(f"ham_dyn_{i}_{n % 7}", "h").set(n)
                n += 1
        except Exception as e:   # pragma: no cover - the failure mode
            errors.append(e)

    def reader():
        try:
            while not stop.is_set():
                text = reg.to_prometheus()
                snap = reg.to_json()
                for fam in snap.values():
                    if fam["type"] != "histogram":
                        continue
                    for v in fam["values"]:
                        # bucket counts never exceed total count, and the
                        # mean lies within the observed value range
                        assert sum(v["buckets"].values()) <= v["count"]
                        if v["count"]:
                            mean = v["sum"] / v["count"]
                            assert 0.0 < mean <= 0.11
                assert "ham_total" in text or not snap
        except Exception as e:
            errors.append(e)

    threads = ([threading.Thread(target=writer, args=(i,))
                for i in range(4)]
               + [threading.Thread(target=reader) for _ in range(2)])
    [t.start() for t in threads]
    time.sleep(0.8)
    stop.set()
    [t.join(timeout=5) for t in threads]
    assert not errors, errors


def test_gauge_callback_failure_degrades_to_nan(fresh_telemetry):
    g = fresh_telemetry.gauge("bad_gauge", "h")
    g.set_function(lambda: 1 / 0)
    assert np.isnan(fresh_telemetry.get_value("bad_gauge"))
    # and the scrape survives it
    assert "bad_gauge NaN" in fresh_telemetry.to_prometheus()


# ------------------------------------------------------ performance listener

def test_performance_listener_eta_and_rolling(fresh_telemetry):
    from deeplearning4j_tpu.optimize.listeners import PerformanceListener

    logs = []
    pl = PerformanceListener(frequency=1, report=logs.append,
                             total_iterations=100)
    net = make_net()
    net.set_listeners(pl)
    x, y = make_data(16)
    for _ in range(4):
        net.fit(x, y)
    assert pl.rolling_samples_per_sec and pl.rolling_samples_per_sec > 0
    assert pl.eta_seconds is not None and pl.eta_seconds >= 0
    assert any("rolling samples/sec" in m for m in logs)
    assert any("ETA:" in m for m in logs)


def test_performance_listener_eta_on_resumed_model(fresh_telemetry):
    """ETA counts iterations the LISTENER observed — a model resumed at a
    high global iteration (checkpoint restore, second fit) must not
    report ETA 0 from the start of a fresh run."""
    from deeplearning4j_tpu.optimize.listeners import PerformanceListener

    pl = PerformanceListener(frequency=1, report=lambda m: None,
                             total_iterations=100)

    class M:
        last_batch_size = 8

    for i in range(5000, 5005):   # resumed: global iteration >> total
        pl.iteration_done(M(), i)
    assert pl.eta_seconds is not None and pl.eta_seconds > 0


def test_performance_listener_unknown_epoch_length(fresh_telemetry):
    from deeplearning4j_tpu.optimize.listeners import PerformanceListener

    logs = []
    pl = PerformanceListener(frequency=1, report=logs.append)

    class M:
        last_batch_size = 8

    for i in range(5):
        pl.iteration_done(M(), i)
    assert pl.eta_seconds is None            # unknown length tolerated
    assert pl.rolling_samples_per_sec > 0
    assert logs and all("ETA" not in m for m in logs)
