"""Numerical gradient checks — the central correctness evidence.

Mirrors reference suites: GradientCheckTests.java, CNNGradientCheckTest.java,
BNGradientCheckTest.java, GradientCheckTestsMasking.java,
LossFunctionGradientCheck.java.  float64 end-to-end for the comparison.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    AutoEncoder,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    EmbeddingLayer,
    GravesBidirectionalLSTM,
    GravesLSTM,
    LocalResponseNormalization,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork

F64 = jnp.float64


def _data(rs, n, shape, n_classes):
    x = rs.randn(n, *shape)
    y = np.eye(n_classes)[rs.randint(0, n_classes, n)]
    return x, y


@pytest.mark.parametrize("activation", ["sigmoid", "tanh", "relu", "elu", "softplus"])
def test_mlp_gradients_activations(activation):
    rs = np.random.RandomState(12345)
    conf = (
        NeuralNetConfiguration.builder()
        .seed(0)
        .list()
        .layer(DenseLayer(n_in=4, n_out=6, activation=activation))
        .layer(OutputLayer(n_in=6, n_out=3, loss="mcxent", activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init(dtype=F64)
    x, y = _data(rs, 8, (4,), 3)
    assert check_gradients(net, x, y)


@pytest.mark.parametrize(
    "loss,out_act",
    [
        ("mse", "identity"),
        ("mse", "tanh"),
        ("l1", "identity"),
        ("xent", "sigmoid"),
        ("mcxent", "softmax"),
        ("negativeloglikelihood", "softmax"),
        ("hinge", "identity"),
        ("squared_hinge", "identity"),
        ("poisson", "softplus"),
        ("cosine_proximity", "identity"),
        ("kl_divergence", "softmax"),
    ],
)
def test_loss_function_gradients(loss, out_act):
    rs = np.random.RandomState(999)
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(DenseLayer(n_in=3, n_out=5, activation="tanh"))
        .layer(OutputLayer(n_in=5, n_out=2, loss=loss, activation=out_act))
        .build()
    )
    net = MultiLayerNetwork(conf).init(dtype=F64)
    if loss in ("xent",):
        y = rs.randint(0, 2, (6, 2)).astype(np.float64)
    elif loss in ("mcxent", "negativeloglikelihood", "kl_divergence"):
        y = np.eye(2)[rs.randint(0, 2, 6)]
    elif loss in ("hinge", "squared_hinge"):
        y = rs.choice([-1.0, 1.0], (6, 2))
    elif loss == "poisson":
        y = rs.poisson(2.0, (6, 2)).astype(np.float64)
    else:
        y = rs.randn(6, 2)
    x = rs.randn(6, 3)
    assert check_gradients(net, x, y)


def test_cnn_gradients():
    rs = np.random.RandomState(42)
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(ConvolutionLayer(n_out=3, kernel_size=(3, 3), activation="tanh"))
        .layer(SubsamplingLayer(pooling_type="avg", kernel_size=(2, 2), stride=(2, 2)))
        .layer(DenseLayer(n_out=8, activation="tanh"))
        .layer(OutputLayer(n_out=2, loss="mcxent", activation="softmax"))
        .set_input_type(InputType.convolutional(8, 8, 2))
        .build()
    )
    net = MultiLayerNetwork(conf).init(dtype=F64)
    x, y = _data(rs, 4, (8, 8, 2), 2)
    assert check_gradients(net, x, y, max_params_per_array=32)


def test_cnn_maxpool_gradients():
    rs = np.random.RandomState(43)
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(ConvolutionLayer(n_out=2, kernel_size=(3, 3), activation="relu"))
        .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
        .layer(OutputLayer(n_out=2, loss="mcxent", activation="softmax"))
        .set_input_type(InputType.convolutional(7, 7, 1))
        .build()
    )
    net = MultiLayerNetwork(conf).init(dtype=F64)
    x, y = _data(rs, 4, (7, 7, 1), 2)
    assert check_gradients(net, x, y, max_params_per_array=32)


def test_batchnorm_gradients():
    rs = np.random.RandomState(44)
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(DenseLayer(n_in=4, n_out=6, activation="identity"))
        .layer(BatchNormalization(n_out=6))
        .layer(OutputLayer(n_in=6, n_out=3, loss="mcxent", activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init(dtype=F64)
    x, y = _data(rs, 8, (4,), 3)
    assert check_gradients(net, x, y)


def test_lrn_gradients():
    rs = np.random.RandomState(45)
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3), activation="tanh"))
        .layer(LocalResponseNormalization())
        .layer(OutputLayer(n_out=2, loss="mcxent", activation="softmax"))
        .set_input_type(InputType.convolutional(6, 6, 1))
        .build()
    )
    net = MultiLayerNetwork(conf).init(dtype=F64)
    x, y = _data(rs, 3, (6, 6, 1), 2)
    assert check_gradients(net, x, y, max_params_per_array=32)


def test_graves_lstm_gradients():
    rs = np.random.RandomState(46)
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(GravesLSTM(n_in=3, n_out=4, activation="tanh"))
        .layer(RnnOutputLayer(n_in=4, n_out=2, loss="mcxent", activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init(dtype=F64)
    x = rs.randn(2, 5, 3)
    y = np.eye(2)[rs.randint(0, 2, (2, 5))]
    assert check_gradients(net, x, y, max_params_per_array=32)


def test_bidirectional_lstm_gradients():
    rs = np.random.RandomState(47)
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(GravesBidirectionalLSTM(n_in=3, n_out=3, activation="tanh"))
        .layer(RnnOutputLayer(n_in=3, n_out=2, loss="mcxent", activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init(dtype=F64)
    x = rs.randn(2, 4, 3)
    y = np.eye(2)[rs.randint(0, 2, (2, 4))]
    assert check_gradients(net, x, y, max_params_per_array=24)


def test_masked_sequence_gradients():
    """Reference GradientCheckTestsMasking: gradients with variable-length mask."""
    rs = np.random.RandomState(48)
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(GravesLSTM(n_in=3, n_out=4))
        .layer(RnnOutputLayer(n_in=4, n_out=2, loss="mcxent", activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init(dtype=F64)
    x = rs.randn(2, 6, 3)
    y = np.eye(2)[rs.randint(0, 2, (2, 6))]
    mask = np.array([[1, 1, 1, 1, 0, 0], [1, 1, 1, 1, 1, 1]], np.float64)
    assert check_gradients(net, x, y, fmask=mask, lmask=mask, max_params_per_array=32)


def test_embedding_gradients():
    rs = np.random.RandomState(49)
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(EmbeddingLayer(n_in=8, n_out=5))
        .layer(DenseLayer(n_in=5, n_out=4, activation="tanh"))
        .layer(OutputLayer(n_in=4, n_out=3, loss="mcxent", activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init(dtype=F64)
    x = rs.randint(0, 8, (6, 1)).astype(np.float64)
    y = np.eye(3)[rs.randint(0, 3, 6)]
    assert check_gradients(net, x, y)


def test_l1_l2_regularization_gradients():
    rs = np.random.RandomState(50)
    conf = (
        NeuralNetConfiguration.builder()
        .regularization(True)
        .l1(0.01)
        .l2(0.02)
        .list()
        .layer(DenseLayer(n_in=4, n_out=5, activation="tanh"))
        .layer(OutputLayer(n_in=5, n_out=2, loss="mcxent", activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init(dtype=F64)
    x, y = _data(rs, 6, (4,), 2)
    assert check_gradients(net, x, y)
