"""Model zoo configs build, shape-infer, and train at toy scale.

Reference analog: the DL4J model-zoo configs (AlexNet/VGG16/LeNet) built on
the same builder DSL users write by hand.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import alexnet, vgg16


def _train_tiny(net, hw, n_classes, batch=2):
    rs = np.random.RandomState(0)
    x = rs.rand(batch, hw, hw, 3).astype(np.float32)
    y = np.eye(n_classes, dtype=np.float32)[rs.randint(0, n_classes, batch)]
    net.fit(x, y)
    assert np.isfinite(net.score_value)
    out = np.asarray(net.output(x))
    assert out.shape == (batch, n_classes)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-4)


def test_alexnet_builds_and_trains_tiny():
    # 67px keeps the conv stack valid (11/4 stem) while staying CPU-cheap
    net = alexnet(height=67, width=67, n_classes=5, lr=0.001)
    assert net.num_params() > 1_000_000  # fc stack dominates
    _train_tiny(net, 67, 5)


def test_vgg16_builds_and_trains_tiny():
    net = vgg16(height=32, width=32, n_classes=4, lr=0.001)
    # 13 conv + 2 dense + output
    assert len(net.layers) == 21
    _train_tiny(net, 32, 4)


def test_googlenet_builds_and_trains_tiny():
    """Inception modules (4-branch MergeVertex concat) compile and train."""
    from deeplearning4j_tpu.models.zoo import googlenet

    net = googlenet(height=64, width=64, n_classes=5, lr=0.001)
    # 9 inception modules x 4 branches concatenated
    assert any(n.name == "i5b_cat" for n in net.conf.nodes)
    rs = np.random.RandomState(0)
    x = {"input": rs.rand(2, 64, 64, 3).astype(np.float32)}
    y = {"fc": np.eye(5, dtype=np.float32)[rs.randint(0, 5, 2)]}
    net.fit(x, y)
    assert np.isfinite(net.score_value)
    out = np.asarray(net.output(x))
    assert out.shape == (2, 5)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-4)


def test_dbn_pretrain_then_finetune():
    """Stacked-RBM DBN: layerwise CD-k pretrain changes RBM weights, then
    supervised fit converges on a separable toy problem."""
    import jax

    from deeplearning4j_tpu.models.zoo import dbn

    net = dbn(n_in=12, hidden=(8, 6), n_classes=2, lr=0.05)
    rs = np.random.RandomState(0)
    x = rs.rand(32, 12).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0.5).astype(int)]
    w_before = np.asarray(jax.device_get(net.params["layer_0"]["W"]))
    net.pretrain([x], epochs=2)
    w_after = np.asarray(jax.device_get(net.params["layer_0"]["W"]))
    assert not np.allclose(w_before, w_after), "pretrain did not touch RBM 0"
    net.fit(x, y)
    first_score = float(net.score_value)
    for _ in range(30):
        net.fit(x, y)
    assert np.isfinite(net.score_value)
    assert float(net.score_value) < first_score, "supervised fit did not learn"
    assert np.asarray(net.output(x)).shape == (32, 2)


def test_zoo_configs_serialize():
    net = alexnet(height=67, width=67, n_classes=5)
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

    back = MultiLayerConfiguration.from_json(net.conf.to_json())
    assert len(back.layers) == len(net.conf.layers)
