"""Model zoo configs build, shape-infer, and train at toy scale.

Reference analog: the DL4J model-zoo configs (AlexNet/VGG16/LeNet) built on
the same builder DSL users write by hand.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import alexnet, vgg16


def _train_tiny(net, hw, n_classes, batch=2):
    rs = np.random.RandomState(0)
    x = rs.rand(batch, hw, hw, 3).astype(np.float32)
    y = np.eye(n_classes, dtype=np.float32)[rs.randint(0, n_classes, batch)]
    net.fit(x, y)
    assert np.isfinite(net.score_value)
    out = np.asarray(net.output(x))
    assert out.shape == (batch, n_classes)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-4)


def test_alexnet_builds_and_trains_tiny():
    # 67px keeps the conv stack valid (11/4 stem) while staying CPU-cheap
    net = alexnet(height=67, width=67, n_classes=5, lr=0.001)
    assert net.num_params() > 1_000_000  # fc stack dominates
    _train_tiny(net, 67, 5)


def test_vgg16_builds_and_trains_tiny():
    net = vgg16(height=32, width=32, n_classes=4, lr=0.001)
    # 13 conv + 2 dense + output
    assert len(net.layers) == 21
    _train_tiny(net, 32, 4)


def test_zoo_configs_serialize():
    net = alexnet(height=67, width=67, n_classes=5)
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

    back = MultiLayerConfiguration.from_json(net.conf.to_json())
    assert len(back.layers) == len(net.conf.layers)
