"""Distributed-vs-single-machine equivalence tests.

Reference: ``TestCompareParameterAveragingSparkVsSingleMachine.java`` —
correctness of distribution is proven by numeric equivalence to local
sequential math, on a simulated cluster (here: 8 virtual CPU devices,
conftest.py; reference: Spark local[N])."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (
    DistributedNetwork,
    ParallelWrapper,
    ParameterAveragingTrainingMaster,
    SyncTrainingMaster,
)


def make_net(seed=12345, updater="sgd", lr=0.1):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater, learning_rate=lr)
        .list()
        .layer(DenseLayer(n_in=6, n_out=10, activation="tanh"))
        .layer(OutputLayer(n_in=10, n_out=3, loss="mcxent", activation="softmax"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def make_batches(n_batches, batch_size, seed=0):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n_batches):
        x = rs.randn(batch_size, 6).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, batch_size)]
        out.append(DataSet(x, y))
    return out


def test_mesh_has_8_devices():
    assert jax.device_count() == 8
    mesh = backend.default_mesh()
    assert mesh.shape[backend.AXIS_DATA] == 8


def test_sync_dp_equals_single_device_math():
    """Sync DP over K devices on a global batch == single-device training on
    the same batch: the sharded-mean gradient is the global-batch mean."""
    K = 4
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    batches = make_batches(6, 8)  # batch 8 shards over 4 devices

    net_dist = make_net()
    master = SyncTrainingMaster(mesh=mesh)
    dist = DistributedNetwork(net_dist, master)
    dist.fit(ListDataSetIterator(DataSet.merge(batches), 8))

    net_local = make_net()  # same seed -> same init
    for b in DataSet.merge(batches).batch_by(8):
        net_local.fit(b.features, b.labels)

    np.testing.assert_allclose(
        net_dist.params_to_vector(), net_local.params_to_vector(), rtol=2e-5, atol=1e-6
    )


def test_parameter_averaging_equals_manual_replica_math():
    """ParallelWrapper(K, avgFreq) == manually training K independent
    replicas F batches each then averaging params (the reference's
    Spark-vs-single-machine oracle)."""
    K, F = 2, 2
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    batches = make_batches(K * F, 4)

    net_dist = make_net(updater="sgd", lr=0.2)
    pw = ParallelWrapper(net_dist, workers=K, averaging_frequency=F, mesh=mesh)
    pw.fit(iter(batches))

    # manual: replica r sees batches in window order [f*K + r for f in 0..F)
    replicas = [make_net(updater="sgd", lr=0.2) for _ in range(K)]
    for r, rep in enumerate(replicas):
        for f in range(F):
            b = batches[f * K + r]
            rep.fit(b.features, b.labels)
    avg = np.mean([r.params_to_vector() for r in replicas], axis=0)

    np.testing.assert_allclose(net_dist.params_to_vector(), avg, rtol=2e-5, atol=1e-6)


def test_parameter_averaging_with_updater_state():
    """Averaging with a stateful updater (nesterov momentum), updater-state
    averaging on — runs and stays finite; equivalence of the state treatment
    mirrors reference averageUpdaters=true."""
    K, F = 2, 3
    mesh = backend.default_mesh(data=K, devices=jax.devices()[:K])
    batches = make_batches(K * F * 2, 4)
    net = make_net(updater="nesterovs", lr=0.05)
    pw = ParallelWrapper(net, workers=K, averaging_frequency=F,
                         average_updaters=True, mesh=mesh)
    pw.fit(iter(batches))
    assert np.isfinite(net.score_value)
    assert np.isfinite(net.params_to_vector()).all()
    assert pw.iteration == 2 * F


def test_sync_dp_training_reduces_loss():
    mesh = backend.default_mesh()
    net = make_net(updater="adam", lr=0.01)
    master = SyncTrainingMaster(mesh=mesh, collect_stats=True)
    dist = DistributedNetwork(net, master)
    data = DataSet.merge(make_batches(16, 16))
    s0 = net.score(data.features, data.labels)
    for _ in range(5):
        dist.fit(ListDataSetIterator(data, 16))
    assert net.score(data.features, data.labels) < s0
    stats = dist.training_stats()
    assert stats["steps"] == 5 * 16


def test_distributed_evaluation():
    mesh = backend.default_mesh()
    net = make_net()
    dist = DistributedNetwork(net, SyncTrainingMaster(mesh=mesh))
    data = DataSet.merge(make_batches(4, 16))
    ev = dist.evaluate(ListDataSetIterator(data, 16))
    assert 0.0 <= ev.accuracy() <= 1.0
    assert ev.confusion.matrix.sum() == 64


def test_sync_dp_trains_computation_graph_resnet():
    """The headline distributed config: ResNet (ComputationGraph) under
    SyncTrainingMaster — batch sharded over 'data', grads all-reduced
    in-graph (the BASELINE 'distributed ResNet-50 grad sync' path at toy
    scale)."""
    from deeplearning4j_tpu.models.zoo import resnet50
    from deeplearning4j_tpu.parallel import DistributedNetwork, SyncTrainingMaster

    net = resnet50(height=16, width=16, n_classes=4, blocks=(1,),
                   stem_stride=1, init_channels=8, lr=0.01)
    rs = np.random.RandomState(5)
    x = rs.rand(16, 16, 16, 3).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 16)]
    mesh = backend.default_mesh()
    DistributedNetwork(net, SyncTrainingMaster(mesh=mesh)).fit(
        ListDataSetIterator(DataSet(x, y), 16), epochs=3)
    assert net.iteration == 3
    assert np.isfinite(net.score_value)
    out = np.asarray(net.output(x))
    assert out.shape == (16, 4)


def test_sync_dp_cg_equals_single_device_math():
    """CG under sync DP == CG trained serially on the same batches."""
    from deeplearning4j_tpu.models.graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.parallel import DistributedNetwork, SyncTrainingMaster

    def build():
        b = (NeuralNetConfiguration.builder().seed(31)
             .updater("sgd", learning_rate=0.2).graph()
             .add_inputs("in")
             .add_layer("d", DenseLayer(n_in=6, n_out=12, activation="tanh"), "in")
             .add_layer("out", OutputLayer(n_in=12, n_out=3), "d")
             .set_outputs("out"))
        return ComputationGraph(b.build()).init()

    batches = make_batches(4, 16, seed=9)
    serial = build()
    for ds in batches:
        serial.fit(ds.features, ds.labels)
    dist = build()
    DistributedNetwork(dist, SyncTrainingMaster(mesh=backend.default_mesh())).fit(
        ListDataSetIterator(DataSet.merge(batches), 16))
    np.testing.assert_allclose(serial.params_to_vector(),
                               dist.params_to_vector(), atol=2e-5)
