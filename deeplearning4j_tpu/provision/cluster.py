"""Cluster provisioning — the deeplearning4j-aws analog for TPU pods.

Reference: ``deeplearning4j-aws/.../ec2/Ec2BoxCreator.java`` (create boxes),
``ec2/provision/HostProvisioner.java`` (ssh: upload artifact, run remote
commands), ``ec2/provision/ClusterSetup.java`` (wire the hosts into a
training cluster and launch the distributed trainer).

TPU redesign: "boxes" are TPU pod-slice workers.  Provisioning emits the
exact gcloud/ssh command lines and per-worker bootstrap scripts (this
environment has no cloud egress, so commands are generated, not executed —
the operator or a CI layer runs them).  The runtime half,
``bootstrap_distributed``, is what each worker executes at startup: it reads
the standard TPU pod env (or explicit args) and brings up
``jax.distributed`` so the whole pod becomes one mesh — XLA then routes
collectives over ICI within a slice and DCN across slices, replacing the
reference's ssh-launched parameter-averaging master/worker topology.
"""

from __future__ import annotations

import dataclasses
import os
from pathlib import Path
from typing import List, Optional


@dataclasses.dataclass
class ClusterSpec:
    """≙ the Ec2BoxCreator knobs, restated for TPU pods."""

    name: str = "dl4j-tpu-cluster"
    accelerator_type: str = "v4-32"        # pod slice (#chips = suffix)
    runtime_version: str = "tpu-ubuntu2204-base"
    zone: str = "us-central2-b"
    project: Optional[str] = None
    num_slices: int = 1                    # >1 = multislice (DCN between)

    @property
    def num_workers(self) -> int:
        chips = int(self.accelerator_type.split("-")[-1])
        return max(chips // 8, 1) * self.num_slices  # 8 chips per VM host

    def create_command(self) -> List[str]:
        """gcloud line that creates the queued resource (box creation)."""
        cmd = [
            "gcloud", "compute", "tpus", "tpu-vm", "create", self.name,
            f"--accelerator-type={self.accelerator_type}",
            f"--version={self.runtime_version}",
            f"--zone={self.zone}",
        ]
        if self.project:
            cmd.append(f"--project={self.project}")
        return cmd

    def ssh_command(self, worker: int, remote_cmd: str) -> List[str]:
        return [
            "gcloud", "compute", "tpus", "tpu-vm", "ssh", self.name,
            f"--zone={self.zone}", f"--worker={worker}",
            "--command", remote_cmd,
        ]


class HostProvisioner:
    """Generates the per-host provisioning steps (upload + run).
    ≙ ``HostProvisioner.java`` (JSch scp/exec), expressed as command lines."""

    def __init__(self, spec: ClusterSpec):
        self.spec = spec

    def upload_command(self, local_path: str, worker="all",
                       remote_path: str = "~/") -> List[str]:
        return [
            "gcloud", "compute", "tpus", "tpu-vm", "scp", str(local_path),
            f"{self.spec.name}:{remote_path}",
            f"--zone={self.spec.zone}", f"--worker={worker}",
        ]

    def run_on_all(self, remote_cmd: str) -> List[List[str]]:
        return [self.spec.ssh_command("all", remote_cmd)]


class ClusterSetup:
    """Wires a pod into a training cluster: writes the bootstrap script every
    worker runs, plus the launch commands.  ≙ ``ClusterSetup.java`` +
    ``DistributedDeepLearningTrainer.java`` bootstrap."""

    def __init__(self, spec: ClusterSpec, train_module: str = "train"):
        self.spec = spec
        self.train_module = train_module

    def bootstrap_script(self) -> str:
        return (
            "#!/usr/bin/env bash\n"
            "# dl4j-tpu worker bootstrap — runs on every pod worker.\n"
            "# jax.distributed discovers coordinator + process index from\n"
            "# the TPU pod metadata; nothing to pass explicitly.\n"
            "set -euo pipefail\n"
            "python -m deeplearning4j_tpu.provision.cluster --bootstrap "
            f"-- python -m {self.train_module}\n"
        )

    def write_bootstrap(self, directory) -> Path:
        p = Path(directory) / "bootstrap.sh"
        p.write_text(self.bootstrap_script())
        p.chmod(0o755)
        return p

    def launch_commands(self) -> List[List[str]]:
        """Everything needed to go from nothing to a training pod."""
        prov = HostProvisioner(self.spec)
        return (
            [self.spec.create_command()]
            + [prov.upload_command("bootstrap.sh", worker="all")]
            + prov.run_on_all("bash ~/bootstrap.sh")
        )


def _on_tpu_pod() -> bool:
    """Multi-worker TPU pod detection: the TPU runtime exports the worker
    host list on every pod VM (absent on single-host and CPU)."""
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return "," in hosts  # >1 worker


def bootstrap_distributed(coordinator: Optional[str] = None,
                          num_processes: Optional[int] = None,
                          process_id: Optional[int] = None) -> dict:
    """Initialise jax.distributed from explicit args, environment
    (DL4J_TPU_COORDINATOR / DL4J_TPU_NUM_PROCS / DL4J_TPU_PROC_ID), or — on
    a real TPU pod — automatically from pod metadata.  Returns a summary
    dict; no-op for a genuinely single-process launch."""
    import jax

    from deeplearning4j_tpu.parallel.training_master import (
        initialize_distributed,
    )

    coordinator = coordinator or os.environ.get("DL4J_TPU_COORDINATOR")
    num_processes = num_processes if num_processes is not None else (
        int(os.environ["DL4J_TPU_NUM_PROCS"])
        if "DL4J_TPU_NUM_PROCS" in os.environ else None)
    process_id = process_id if process_id is not None else (
        int(os.environ["DL4J_TPU_PROC_ID"])
        if "DL4J_TPU_PROC_ID" in os.environ else None)
    explicit = [coordinator, num_processes, process_id]
    if any(v is not None for v in explicit):
        # any of the triple signals explicit-init intent; an incomplete
        # triple is a config error, not a silent single-process no-op
        if any(v is None for v in explicit):
            missing = [n for n, v in zip(
                ("coordinator", "num_processes", "process_id"), explicit)
                if v is None]
            raise ValueError(
                "explicit distributed init needs coordinator, num_processes "
                f"AND process_id; missing: {missing} (set the "
                "DL4J_TPU_COORDINATOR/DL4J_TPU_NUM_PROCS/DL4J_TPU_PROC_ID "
                "env vars or pass them)")
        initialize_distributed(coordinator, num_processes, process_id)
    else:
        if not _on_tpu_pod():
            return {"distributed": False, "processes": 1, "process_id": 0}
        # pod metadata carries coordinator/count/index; jax discovers them
        initialize_distributed()
    return {"distributed": True,
            "processes": jax.process_count(),
            "process_id": jax.process_index()}


if __name__ == "__main__":  # pragma: no cover - pod-side entry
    import subprocess
    import sys

    args = sys.argv[1:]
    if args and args[0] == "--bootstrap":
        bootstrap_distributed()
        rest = args[1:]
        if rest and rest[0] == "--":
            rest = rest[1:]
        if rest:
            sys.exit(subprocess.call(rest))
