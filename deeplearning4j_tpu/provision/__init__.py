from deeplearning4j_tpu.provision.cluster import (
    ClusterSpec, ClusterSetup, bootstrap_distributed, HostProvisioner,
)
