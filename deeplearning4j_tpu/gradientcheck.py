"""Numerical gradient checking — the framework's correctness oracle.

Reference: ``gradientcheck/GradientCheckUtil.java`` — central-difference
numerical gradient vs analytic backprop per parameter, with
maxRelError/minAbsoluteError thresholds; used by every layer test suite
(``GradientCheckTests.java``, ``CNNGradientCheckTest.java``, ...).

Here the analytic side is ``jax.grad`` of the model's loss; the numerical
side perturbs parameters by ±epsilon in float64.  TPU-native twist on the
reference's per-parameter Java loop: all perturbed losses are evaluated by
ONE vmapped/jitted XLA call over a batch of perturbed flat param vectors —
hundreds of central differences per device launch instead of two.

Passing this check proves the whole forward graph (layers, preprocessors,
masking, losses) differentiates correctly — the same evidence triangle the
reference's test suite rests on.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def check_gradients(
    net,
    x,
    y,
    *,
    epsilon: float = 1e-6,
    max_rel_error: float = 1e-3,
    min_abs_error: float = 1e-8,
    fmask=None,
    lmask=None,
    max_params_per_array: Optional[int] = 64,
    seed: int = 0,
    print_results: bool = False,
    chunk: int = 512,
) -> bool:
    """Central-difference check of d(loss)/d(params) for a MultiLayerNetwork
    or ComputationGraph facade (anything exposing _loss_fn/params/net_state).

    Checks up to ``max_params_per_array`` randomly-chosen entries per param
    tensor (None = all) — sampling keeps suites fast while covering every
    tensor; the batched evaluation makes even full checks tractable.
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y, x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else None)

    flat_params, treedef = jax.tree_util.tree_flatten(net.params)
    sizes = [int(np.prod(p.shape)) if p.shape else 1 for p in flat_params]
    offsets = np.cumsum([0] + sizes)
    total = int(offsets[-1])
    vec0 = np.concatenate(
        [np.asarray(p, np.float64).reshape(-1) for p in flat_params]
    )

    def loss_of_vec(vec):
        leaves = [
            vec[offsets[i] : offsets[i + 1]].reshape(flat_params[i].shape).astype(flat_params[i].dtype)
            for i in range(len(flat_params))
        ]
        params = jax.tree_util.tree_unflatten(treedef, leaves)
        l, _ = net._loss_fn(params, net.net_state, x, y, None, fmask, lmask, train=False)
        return l

    analytic = np.asarray(
        jax.jit(jax.grad(loss_of_vec))(jnp.asarray(vec0)), np.float64
    )

    # choose indices to check
    rng = np.random.RandomState(seed)
    check_idx = []
    for i, size in enumerate(sizes):
        if size == 0:
            continue
        idxs = np.arange(size)
        if max_params_per_array is not None and size > max_params_per_array:
            idxs = rng.choice(size, max_params_per_array, replace=False)
        check_idx.extend(offsets[i] + idxs)
    check_idx = np.asarray(sorted(check_idx))

    # batched central differences: rows = [+eps at i, -eps at i, ...]
    batched_loss = jax.jit(jax.vmap(loss_of_vec))
    numeric = np.empty(len(check_idx), np.float64)
    for c0 in range(0, len(check_idx), chunk):
        ids = check_idx[c0 : c0 + chunk]
        pert = np.repeat(vec0[None, :], 2 * len(ids), axis=0)
        rows = np.arange(len(ids))
        pert[2 * rows, ids] += epsilon
        pert[2 * rows + 1, ids] -= epsilon
        vals = np.asarray(batched_loss(jnp.asarray(pert)), np.float64)
        numeric[c0 : c0 + len(ids)] = (vals[0::2] - vals[1::2]) / (2 * epsilon)

    ana = analytic[check_idx]
    denom = np.maximum(np.abs(numeric), np.abs(ana))
    rel = np.where(denom > 0, np.abs(numeric - ana) / np.maximum(denom, 1e-300), 0.0)
    ok = (rel < max_rel_error) | (np.abs(numeric - ana) < min_abs_error)
    n_fail = int((~ok).sum())

    if print_results or n_fail:
        print(f"GradientCheck: {len(ok) - n_fail} passed, {n_fail} failed")
        for j in np.nonzero(~ok)[0][:20]:
            print(
                f"  flat idx {check_idx[j]}: analytic={ana[j]:.8g} "
                f"numeric={numeric[j]:.8g} rel={rel[j]:.4g}"
            )
    return n_fail == 0
