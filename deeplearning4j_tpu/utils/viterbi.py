"""Viterbi decoding — most-likely state sequence under a transition model.

Reference: ``deeplearning4j-nn/.../util/Viterbi.java`` (decodes binarized
label sequences given emission probabilities and a transition weight).
TPU-native: the forward max-product recursion is a ``lax.scan`` over time
(static shapes, no Python loop), backtrace on host.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def viterbi_decode(emission_logprobs, transition_logprobs
                   ) -> Tuple[np.ndarray, float]:
    """Most likely state path.

    emission_logprobs  [T, S] — per-timestep state log-scores
    transition_logprobs [S, S] — log P(next=j | prev=i)

    Returns (path [T] int array, path log-score).
    """
    em = jnp.asarray(emission_logprobs, jnp.float32)
    tr = jnp.asarray(transition_logprobs, jnp.float32)
    T, S = em.shape
    if tr.shape != (S, S):
        raise ValueError(f"transition matrix {tr.shape} != ({S},{S})")

    def step(delta, em_t):
        # delta [S]: best score ending in each state at t-1
        scores = delta[:, None] + tr           # [S_prev, S_next]
        best_prev = jnp.argmax(scores, axis=0)  # [S]
        new_delta = jnp.max(scores, axis=0) + em_t
        return new_delta, best_prev

    delta0 = em[0]
    final_delta, backptrs = jax.lax.scan(step, delta0, em[1:])
    backptrs = np.asarray(backptrs)            # [T-1, S]
    path = np.empty(T, np.int64)
    path[-1] = int(jnp.argmax(final_delta))
    for t in range(T - 2, -1, -1):
        path[t] = backptrs[t, path[t + 1]]
    return path, float(jnp.max(final_delta))


class Viterbi:
    """Reference-shaped facade (``util/Viterbi.java``): binary label
    smoothing with a possibility-of-transition prior."""

    def __init__(self, possible_labels, meta_stability: float = 0.9,
                 p_correct: float = 0.99):
        self.labels = np.asarray(possible_labels)
        if len(self.labels) < 2:
            raise ValueError("need >= 2 possible labels")
        self.meta_stability = meta_stability
        self.p_correct = p_correct

    def decode(self, observed_labels) -> Tuple[np.ndarray, float]:
        """Smooth an observed label sequence: each observation emits its
        label with p_correct; transitions prefer staying (meta_stability)."""
        obs = np.asarray(observed_labels)
        S = len(self.labels)
        label_to_idx = {l: i for i, l in enumerate(self.labels.tolist())}
        idx = np.array([label_to_idx[l] for l in obs.tolist()])
        T = len(idx)
        eps = 1e-6
        em = np.full((T, S), np.log((1 - self.p_correct) / max(S - 1, 1) + eps),
                     np.float32)
        em[np.arange(T), idx] = np.log(self.p_correct)
        tr = np.full((S, S), np.log((1 - self.meta_stability) / max(S - 1, 1)
                                    + eps), np.float32)
        np.fill_diagonal(tr, np.log(self.meta_stability))
        path, score = viterbi_decode(em, tr)
        return self.labels[path], score
