"""Autoregressive sampling on top of the streaming inference API.

≙ the reference's char-modelling example loop (sampleCharactersFromNetwork
in the DL4J GravesLSTM example family: prime the RNN with a prompt via
``rnnTimeStep``, then repeatedly sample from the output distribution and
feed the sample back).  Works unchanged for both model families because
both stream through ``rnn_time_step``: LSTMs carry hidden state,
transformers carry KV caches.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def sample_sequence(net, prompt_ids, steps: int, *,
                    temperature: float = 1.0,
                    rng: Optional[jax.Array] = None,
                    one_hot: Optional[bool] = None,
                    vocab_size: Optional[int] = None) -> np.ndarray:
    """Generate ``steps`` tokens after priming with ``prompt_ids``.

    prompt_ids: [B, T_prompt] integer array.  ``one_hot`` controls the
    input encoding per step: True feeds one-hot vectors (LSTM char-LM
    configs whose first layer consumes features), False feeds raw ids
    (embedding-first transformers).  Auto-detected from the first layer
    when None.  ``temperature`` <= 0 means greedy argmax.  Returns the
    sampled ids [B, steps].
    """
    from deeplearning4j_tpu.nn.layers.dense import EmbeddingLayer

    prompt_ids = np.asarray(prompt_ids)
    if prompt_ids.ndim != 2:
        raise ValueError(f"prompt_ids must be [B, T], got {prompt_ids.shape}")
    layers = getattr(net, "layers", None)   # MLN only; CG has named nodes
    if one_hot is None:
        if layers is None:
            raise ValueError(
                "one_hot auto-detection needs a sequential net with "
                ".layers (MultiLayerNetwork); pass one_hot= explicitly "
                "for a ComputationGraph")
        one_hot = not (layers and isinstance(layers[0], EmbeddingLayer))
    if one_hot and vocab_size is None:
        if layers is None:
            raise ValueError("pass vocab_size= explicitly for a "
                             "ComputationGraph with one_hot inputs")
        vocab_size = layers[-1].n_out
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def encode(ids):
        ids = np.asarray(ids)
        if one_hot:
            return jnp.asarray(np.eye(vocab_size, dtype=np.float32)[ids])
        return jnp.asarray(ids)

    net.rnn_clear_previous_state()
    # prime on the full prompt in one chunk; the last step's distribution
    # seeds the first sample
    probs = net.rnn_time_step(encode(prompt_ids))
    probs = probs[:, -1] if probs.ndim == 3 else probs

    out = []
    tok = None
    for _ in range(steps):
        if temperature and temperature > 0:
            rng, key = jax.random.split(rng)
            logits = jnp.log(jnp.maximum(probs, 1e-30)) / temperature
            tok = jax.random.categorical(key, logits, axis=-1)
        else:
            tok = jnp.argmax(probs, axis=-1)
        out.append(np.asarray(tok))
        probs = net.rnn_time_step(encode(np.asarray(tok)[:, None]))
        probs = probs[:, -1] if probs.ndim == 3 else probs
    return np.stack(out, axis=1)
