"""Autoregressive sampling on top of the streaming inference API.

≙ the reference's char-modelling example loop (sampleCharactersFromNetwork
in the DL4J GravesLSTM example family: prime the RNN with a prompt via
``rnnTimeStep``, then repeatedly sample from the output distribution and
feed the sample back).  Works unchanged for both model families because
both stream through ``rnn_time_step``: LSTMs carry hidden state,
transformers carry KV caches.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _filter_logits(logits: jax.Array, top_k: Optional[int],
                   top_p: Optional[float]) -> jax.Array:
    """Standard nucleus/top-k logit filtering: everything outside the kept
    set drops to -inf before the categorical draw."""
    neg = jnp.asarray(-1e30, logits.dtype)
    if top_k is not None:
        if top_k < 1:
            raise ValueError(f"top_k={top_k} must be >= 1")
        k = min(top_k, logits.shape[-1])   # clamp to vocab
        kth = jnp.sort(logits, axis=-1)[..., -k][..., None]
        logits = jnp.where(logits >= kth, logits, neg)
    if top_p is not None:
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p={top_p} must be in (0, 1]; for greedy "
                             "use temperature=0")
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative mass >= top_p (always
        # keep the argmax)
        keep_sorted = cum - probs < top_p
        # threshold = the SMALLEST kept logit
        cutoff = jnp.min(jnp.where(keep_sorted, sorted_logits,
                                   jnp.asarray(jnp.inf, logits.dtype)),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits >= cutoff, logits, neg)
    return logits


def _resolve_encoding(net, prompt_ids, one_hot: Optional[bool],
                      vocab_size: Optional[int]):
    """Shared preamble for the host sampling loop and on-device generate:
    validate the prompt and resolve the input encoding.  Auto-detection
    covers sequential nets (first layer embedding or not) and
    SINGLE-INPUT ComputationGraphs (the one input either feeds an
    EmbeddingLayer or it doesn't — ``net._id_consumer``); multi-input
    graphs are ambiguous, so those callers must pass ``one_hot=``
    explicitly.  For one-hot CG inputs the vocab width comes from the
    INPUT-side consumer's ``n_in`` (the layer the vector actually feeds),
    never the output head's ``n_out`` — the two differ in
    asymmetric-vocab graphs."""
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.layers.dense import EmbeddingLayer

    prompt_ids = np.asarray(prompt_ids)
    if prompt_ids.ndim != 2:
        raise ValueError(f"prompt_ids must be [B, T], got {prompt_ids.shape}")
    sequential = isinstance(net, MultiLayerNetwork)
    single_in = sequential or len(net.conf.inputs) == 1
    if one_hot is None:
        if sequential:
            one_hot = not (net.layers
                           and isinstance(net.layers[0], EmbeddingLayer))
        elif single_in:
            one_hot = net._id_consumer(net.conf.inputs[0]) is None
        else:
            raise ValueError(
                "one_hot auto-detection needs a single-input net; pass "
                "one_hot= explicitly for a multi-input ComputationGraph")
    if one_hot and vocab_size is None:
        if sequential:
            # input-side rule: the first layer consumes the one-hot vector,
            # so ITS n_in is the width (asymmetric-vocab nets diverge from
            # the head's n_out); head n_out only as a last resort
            vocab_size = (getattr(net.layers[0], "n_in", None)
                          if net.layers else None) or net.layers[-1].n_out
        elif single_in:
            in_name = net.conf.inputs[0]
            consumer = next((net.nodes[n] for n in net.topo
                             if in_name in net.nodes[n].inputs), None)
            layer = getattr(consumer, "layer", None)
            if layer is None or getattr(layer, "n_in", None) is None:
                raise ValueError(
                    "cannot infer the one-hot width: the graph input "
                    f"'{in_name}' feeds a vertex; pass vocab_size=")
            vocab_size = layer.n_in
        else:
            raise ValueError("pass vocab_size= explicitly for a "
                             "multi-input ComputationGraph")
    return prompt_ids, one_hot, vocab_size


def sample_sequence(net, prompt_ids, steps: int, *,
                    temperature: float = 1.0,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    rng: Optional[jax.Array] = None,
                    one_hot: Optional[bool] = None,
                    vocab_size: Optional[int] = None) -> np.ndarray:
    """Generate ``steps`` tokens after priming with ``prompt_ids``.

    prompt_ids: [B, T_prompt] integer array.  ``one_hot`` controls the
    input encoding per step: True feeds one-hot vectors (LSTM char-LM
    configs whose first layer consumes features), False feeds raw ids
    (embedding-first transformers).  Auto-detected from the first layer
    when None.  ``temperature`` <= 0 means greedy argmax; ``top_k`` /
    ``top_p`` (nucleus) filter the distribution before sampling.
    Returns the sampled ids [B, steps].
    """
    prompt_ids, one_hot, vocab_size = _resolve_encoding(
        net, prompt_ids, one_hot, vocab_size)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def encode(ids):
        ids = np.asarray(ids)
        if one_hot:
            return jnp.asarray(np.eye(vocab_size, dtype=np.float32)[ids])
        return jnp.asarray(ids)

    net.rnn_clear_previous_state()
    # prime on the full prompt in one chunk; the last step's distribution
    # seeds the first sample
    probs = net.rnn_time_step(encode(prompt_ids))
    probs = probs[:, -1] if probs.ndim == 3 else probs

    out = []
    tok = None
    for _ in range(steps):
        if temperature and temperature > 0:
            rng, key = jax.random.split(rng)
            logits = jnp.log(jnp.maximum(probs, 1e-30)) / temperature
            logits = _filter_logits(logits, top_k, top_p)
            tok = jax.random.categorical(key, logits, axis=-1)
        else:
            tok = jnp.argmax(probs, axis=-1)
        out.append(np.asarray(tok))
        probs = net.rnn_time_step(encode(np.asarray(tok)[:, None]))
        probs = probs[:, -1] if probs.ndim == 3 else probs
    return np.stack(out, axis=1)
