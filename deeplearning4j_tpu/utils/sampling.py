"""Autoregressive sampling on top of the streaming inference API.

≙ the reference's char-modelling example loop (sampleCharactersFromNetwork
in the DL4J GravesLSTM example family: prime the RNN with a prompt via
``rnnTimeStep``, then repeatedly sample from the output distribution and
feed the sample back).  Works unchanged for both model families because
both stream through ``rnn_time_step``: LSTMs carry hidden state,
transformers carry KV caches.

This module is also the ONE owner of the sampling policy (temperature /
top-k / top-p logit filtering + the categorical draw) for every decode
path in the repo: the host loop here, the compiled ``lax.scan`` decode in
``models/decode.py`` (static per-program policy via ``_sampler``), and the
continuous-batching generation engine (per-slot RUNTIME policy arrays via
``sample_tokens`` — one compiled decode step serves requests with mixed
sampling configs).  All three route through ``_filter_logits`` so the
kept-set semantics can never diverge between paths.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _filter_logits(logits: jax.Array, top_k=None, top_p=None) -> jax.Array:
    """Standard nucleus/top-k logit filtering: everything outside the kept
    set drops to -inf before the categorical draw.

    ``top_k`` / ``top_p`` are either static Python numbers (validated
    eagerly — the host loop and the compiled-scan decode bake the policy
    into the program) or traced ``[B]`` arrays (the generation engine's
    per-slot policy, one value per running request).  Array semantics:
    ``top_k < 1`` and ``top_p >= 1`` mean "disabled" for that row — the
    runtime analog of passing None, so one compiled program covers every
    per-request mix."""
    neg = jnp.asarray(-1e30, logits.dtype)
    v = logits.shape[-1]
    if top_k is not None:
        if isinstance(top_k, (int, np.integer)):
            if top_k < 1:
                raise ValueError(f"top_k={top_k} must be >= 1")
            k = min(int(top_k), v)   # clamp to vocab
            kth = jnp.sort(logits, axis=-1)[..., -k][..., None]
        else:
            # per-row runtime k: <1 disables (clamps to the full vocab)
            karr = jnp.asarray(top_k, jnp.int32)
            k = jnp.where(karr >= 1, jnp.minimum(karr, v), v)
            sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
            kth = jnp.take_along_axis(sorted_desc, (k - 1)[..., None],
                                      axis=-1)
        logits = jnp.where(logits >= kth, logits, neg)
    if top_p is not None:
        if isinstance(top_p, (float, int, np.floating, np.integer)):
            if not 0.0 < top_p <= 1.0:
                raise ValueError(f"top_p={top_p} must be in (0, 1]; for "
                                 "greedy use temperature=0")
            p = jnp.asarray(top_p, logits.dtype)
        else:
            # per-row runtime p: values >= 1 keep everything (disabled)
            p = jnp.clip(jnp.asarray(top_p, logits.dtype),
                         jnp.finfo(logits.dtype).tiny, 1.0)[..., None]
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative mass >= top_p (always
        # keep the argmax)
        keep_sorted = cum - probs < p
        # threshold = the SMALLEST kept logit
        cutoff = jnp.min(jnp.where(keep_sorted, sorted_logits,
                                   jnp.asarray(jnp.inf, logits.dtype)),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits >= cutoff, logits, neg)
    return logits


def _sampler(temperature: float, top_k: Optional[int],
             top_p: Optional[float]):
    """Static sampling policy -> pure ``(logits [B, V], key) -> ids [B]``.
    ``temperature <= 0`` means greedy argmax (top-k/top-p ignored — the
    kept set never changes the argmax)."""
    if temperature and temperature > 0:

        def sample(logits, key):
            logits = logits / jnp.asarray(temperature, logits.dtype)
            return jax.random.categorical(
                key, _filter_logits(logits, top_k, top_p), axis=-1)
    else:

        def sample(logits, key):
            return jnp.argmax(logits, axis=-1)

    return sample


def sample_tokens(logits: jax.Array, keys: jax.Array, token_idx: jax.Array,
                  temperature: jax.Array, top_k: jax.Array,
                  top_p: jax.Array) -> jax.Array:
    """Per-row runtime sampling for a mixed decode batch.

    ``logits`` [B, V]; ``keys`` [B, 2] uint32 per-REQUEST base keys;
    ``token_idx`` [B] int32 index of the token being drawn (the draw key
    is ``fold_in(base_key, token_idx)``, so a request's stream depends
    only on its seed and position — never on which slot it occupies or
    who else is in the batch); ``temperature`` [B] (<= 0 -> greedy);
    ``top_k`` [B] int32 (< 1 disables); ``top_p`` [B] (>= 1 disables).
    Same policy math as ``_sampler`` row-for-row (shared
    ``_filter_logits``)."""
    step_keys = jax.vmap(jax.random.fold_in)(keys, token_idx)
    temp = jnp.asarray(temperature, logits.dtype)
    safe_t = jnp.where(temp > 0, temp, jnp.ones_like(temp))
    filtered = _filter_logits(logits / safe_t[:, None], top_k, top_p)
    drawn = jax.vmap(lambda k, l: jax.random.categorical(k, l, axis=-1))(
        step_keys, filtered)
    return jnp.where(temp > 0, drawn, jnp.argmax(logits, axis=-1))


def _resolve_encoding(net, prompt_ids, one_hot: Optional[bool],
                      vocab_size: Optional[int]):
    """Shared preamble for the host sampling loop and on-device generate:
    validate the prompt and resolve the input encoding.  Auto-detection
    covers sequential nets (first layer embedding or not) and
    SINGLE-INPUT ComputationGraphs (the one input either feeds an
    EmbeddingLayer or it doesn't — ``net._id_consumer``); multi-input
    graphs are ambiguous, so those callers must pass ``one_hot=``
    explicitly.  For one-hot CG inputs the vocab width comes from the
    INPUT-side consumer's ``n_in`` (the layer the vector actually feeds),
    never the output head's ``n_out`` — the two differ in
    asymmetric-vocab graphs."""
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.layers.dense import EmbeddingLayer

    prompt_ids = np.asarray(prompt_ids)
    if prompt_ids.ndim != 2:
        raise ValueError(f"prompt_ids must be [B, T], got {prompt_ids.shape}")
    sequential = isinstance(net, MultiLayerNetwork)
    single_in = sequential or len(net.conf.inputs) == 1
    if one_hot is None:
        if sequential:
            one_hot = not (net.layers
                           and isinstance(net.layers[0], EmbeddingLayer))
        elif single_in:
            one_hot = net._id_consumer(net.conf.inputs[0]) is None
        else:
            raise ValueError(
                "one_hot auto-detection needs a single-input net; pass "
                "one_hot= explicitly for a multi-input ComputationGraph")
    if one_hot and vocab_size is None:
        if sequential:
            # input-side rule: the first layer consumes the one-hot vector,
            # so ITS n_in is the width (asymmetric-vocab nets diverge from
            # the head's n_out); head n_out only as a last resort
            vocab_size = (getattr(net.layers[0], "n_in", None)
                          if net.layers else None) or net.layers[-1].n_out
        elif single_in:
            in_name = net.conf.inputs[0]
            consumer = next((net.nodes[n] for n in net.topo
                             if in_name in net.nodes[n].inputs), None)
            layer = getattr(consumer, "layer", None)
            if layer is None or getattr(layer, "n_in", None) is None:
                raise ValueError(
                    "cannot infer the one-hot width: the graph input "
                    f"'{in_name}' feeds a vertex; pass vocab_size=")
            vocab_size = layer.n_in
        else:
            raise ValueError("pass vocab_size= explicitly for a "
                             "multi-input ComputationGraph")
    return prompt_ids, one_hot, vocab_size


def sample_sequence(net, prompt_ids, steps: int, *,
                    temperature: float = 1.0,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    rng: Optional[jax.Array] = None,
                    one_hot: Optional[bool] = None,
                    vocab_size: Optional[int] = None) -> np.ndarray:
    """Generate ``steps`` tokens after priming with ``prompt_ids``.

    prompt_ids: [B, T_prompt] integer array.  ``one_hot`` controls the
    input encoding per step: True feeds one-hot vectors (LSTM char-LM
    configs whose first layer consumes features), False feeds raw ids
    (embedding-first transformers).  Auto-detected from the first layer
    when None.  ``temperature`` <= 0 means greedy argmax; ``top_k`` /
    ``top_p`` (nucleus) filter the distribution before sampling.
    Returns the sampled ids [B, steps].
    """
    prompt_ids, one_hot, vocab_size = _resolve_encoding(
        net, prompt_ids, one_hot, vocab_size)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def encode(ids):
        ids = np.asarray(ids)
        if one_hot:
            return jnp.asarray(np.eye(vocab_size, dtype=np.float32)[ids])
        return jnp.asarray(ids)

    net.rnn_clear_previous_state()
    # prime on the full prompt in one chunk; the last step's distribution
    # seeds the first sample
    probs = net.rnn_time_step(encode(prompt_ids))
    probs = probs[:, -1] if probs.ndim == 3 else probs

    # the one shared policy implementation (also used by the compiled-scan
    # and continuous-batching decode paths); this loop feeds it log-probs,
    # which only differ from the head's logits by a per-row constant the
    # softmax/argmax inside are invariant to
    sample = _sampler(temperature, top_k, top_p)
    out = []
    tok = None
    for _ in range(steps):
        rng, key = jax.random.split(rng)
        tok = sample(jnp.log(jnp.maximum(probs, 1e-30)), key)
        out.append(np.asarray(tok))
        probs = net.rnn_time_step(encode(np.asarray(tok)[:, None]))
        probs = probs[:, -1] if probs.ndim == 3 else probs
    return np.stack(out, axis=1)
