from deeplearning4j_tpu.utils.interop import (
    to_torch, from_torch, dataset_to_torch, dataset_from_torch,
    labeled_points_to_dataset, dataset_to_labeled_points,
)
from deeplearning4j_tpu.utils.viterbi import Viterbi, viterbi_decode
from deeplearning4j_tpu.utils.sampling import sample_sequence
