"""Framework interop — the MLLib bridge analog.

Reference: ``spark/dl4j-spark/.../util/MLLibUtil.java`` (MLLib Vector/Matrix
<-> INDArray, LabeledPoint <-> DataSet).  The ecosystem neighbour here is
torch (CPU) rather than Spark MLLib: tensors and TensorDatasets convert
both ways, plus the labeled-point style (features, label-class) pairs the
reference converts for classifier training.
"""

from __future__ import annotations

from typing import Iterable, List, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


def to_torch(arr):
    """numpy/jax array -> torch tensor (CPU, shares memory when possible)."""
    import torch

    return torch.from_numpy(np.ascontiguousarray(np.asarray(arr)))


def from_torch(tensor) -> np.ndarray:
    """torch tensor -> numpy array."""
    return tensor.detach().cpu().numpy()


def dataset_to_torch(ds: DataSet):
    """DataSet -> torch.utils.data.TensorDataset(features, labels)."""
    import torch.utils.data as tud

    return tud.TensorDataset(to_torch(ds.features), to_torch(ds.labels))


def dataset_from_torch(tensor_dataset) -> DataSet:
    """torch TensorDataset (features, labels) -> DataSet."""
    feats, labels = tensor_dataset.tensors[:2]
    return DataSet(from_torch(feats).astype(np.float32),
                   from_torch(labels).astype(np.float32))


def labeled_points_to_dataset(points: Iterable[Tuple[Sequence[float], int]],
                              num_classes: int) -> DataSet:
    """[(features, class_index)] -> DataSet with one-hot labels.
    ≙ ``MLLibUtil.fromLabeledPoint``."""
    feats: List[np.ndarray] = []
    labels: List[int] = []
    for f, c in points:
        feats.append(np.asarray(f, np.float32))
        labels.append(int(c))
    return DataSet(np.stack(feats),
                   np.eye(num_classes, dtype=np.float32)[labels])


def dataset_to_labeled_points(ds: DataSet) -> List[Tuple[np.ndarray, int]]:
    """DataSet -> [(features, argmax class)].  ≙ ``MLLibUtil.toLabeledPoint``."""
    return [(ds.features[i], int(ds.labels[i].argmax()))
            for i in range(len(ds))]
