"""Training-event listeners — the observability spine.

Reference: ``optimize/api/IterationListener.java`` invoked from the SGD hot
loop (``StochasticGradientDescent.java:65-66``); built-ins
``ScoreIterationListener``, ``PerformanceListener`` (samples/sec :71-86),
``CollectScoresIterationListener``, ``ComposableIterationListener``.
Listeners run host-side between jitted steps, so they never break the XLA
program; anything they read (score) is already on host.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")


class IterationListener:
    def iteration_done(self, model, iteration: int) -> None:
        raise NotImplementedError


class ScoreIterationListener(IterationListener):
    def __init__(self, print_iterations: int = 10, log=None):
        self.freq = max(1, print_iterations)
        self.log = log or logger.info

    def iteration_done(self, model, iteration):
        if iteration % self.freq == 0:
            score = getattr(model, "score_value", None)
            if score is None:  # models without a score surface (e.g. raw
                score = float("nan")  # pretrain wrappers) must not crash
            self.log(f"Score at iteration {iteration} is {score}")


class PerformanceListener(IterationListener):
    """Throughput: samples/sec, batches/sec, iteration wall time.

    Beyond the per-iteration instant numbers it keeps a rolling window
    (last ``window`` iterations) whose smoothed samples/sec rides along in
    every report, and — when the caller knows the run length
    (``total_iterations``) — an ETA extrapolated from the rolling mean
    iteration time.  An unknown epoch/run length is fine: the ETA simply
    stays out of the report (most streaming iterators cannot predict
    their length)."""

    def __init__(self, frequency: int = 1, report: Optional[Callable] = None,
                 total_iterations: Optional[int] = None, window: int = 50):
        from collections import deque

        self.freq = max(1, frequency)
        self.report = report or logger.info
        self.total_iterations = total_iterations
        self._last_time: Optional[float] = None
        self.last_samples_per_sec: Optional[float] = None
        self.last_iteration_ms: Optional[float] = None
        self.rolling_samples_per_sec: Optional[float] = None
        self.eta_seconds: Optional[float] = None
        self._batch_size: Optional[int] = None
        self._dts = deque(maxlen=max(2, window))
        self._samples = deque(maxlen=max(2, window))
        self._seen = 0   # iterations THIS listener observed (the model's
        # global counter survives resumes/earlier fits and would zero the
        # ETA of any run that isn't the model's first)

    def set_batch_size(self, n: int):
        """Called automatically by the fit loops with the actual minibatch
        size (``models.common.notify_listeners``); manual calls still work
        for custom training loops."""
        self._batch_size = n

    def iteration_done(self, model, iteration):
        now = time.perf_counter()
        if self._last_time is not None:
            dt = now - self._last_time
            self.last_iteration_ms = dt * 1e3
            # prefer the explicitly wired batch size; fall back to the fit
            # loop's last_batch_size mirror so samples/sec always reports
            bs = self._batch_size or getattr(model, "last_batch_size", None)
            if bs:
                self.last_samples_per_sec = bs / dt
            self._dts.append(dt)
            self._samples.append(bs or 0)
            wall = sum(self._dts)
            if wall > 0 and sum(self._samples):
                self.rolling_samples_per_sec = sum(self._samples) / wall
            if self.total_iterations:
                remaining = max(0, self.total_iterations - (self._seen + 1))
                self.eta_seconds = remaining * (wall / len(self._dts))
            if iteration % self.freq == 0:
                msg = f"iteration {iteration}; iteration time: {self.last_iteration_ms:.2f} ms"
                if self.last_samples_per_sec:
                    msg += f"; samples/sec: {self.last_samples_per_sec:.2f}"
                if self.rolling_samples_per_sec:
                    msg += (f"; rolling samples/sec: "
                            f"{self.rolling_samples_per_sec:.2f}")
                if self.eta_seconds is not None:
                    msg += f"; ETA: {self.eta_seconds:.1f}s"
                self.report(msg)
        self._seen += 1
        self._last_time = now


class CollectScoresIterationListener(IterationListener):
    def __init__(self, frequency: int = 1):
        self.freq = max(1, frequency)
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration):
        if iteration % self.freq == 0:
            self.scores.append((iteration, model.score_value))


class ComposableIterationListener(IterationListener):
    def __init__(self, *listeners):
        self.listeners = list(listeners)

    def set_batch_size(self, n: int):
        for l in self.listeners:
            setter = getattr(l, "set_batch_size", None)
            if setter is not None:
                setter(n)

    def iteration_done(self, model, iteration):
        for l in self.listeners:
            l.iteration_done(model, iteration)


class ProfilerListener(IterationListener):
    """Captures a JAX/XLA profiler trace (XPlane + TensorBoard format) of
    the ``duration`` training steps AFTER iteration ``start_iteration`` —
    the trace opens in step ``start``'s iteration_done callback and closes
    in step ``start + duration``'s (see ``iteration_done``).  The tracing
    analog of SURVEY.md §5: the reference has only wall-clock listeners; on
    TPU the XLA profile shows per-op device time, HBM traffic and fusion
    decisions.

    View with: ``tensorboard --logdir <log_dir>`` (Profile tab), or any
    XPlane consumer."""

    def __init__(self, log_dir: str, start_iteration: int = 5,
                 duration: int = 5):
        self.log_dir = str(log_dir)
        self.start = start_iteration
        self.end = start_iteration + duration
        self._active = False

    def iteration_done(self, model, iteration):
        """Callback-driven capture: the trace opens at the iteration_done of
        step ``start`` and closes at the iteration_done of step
        ``start + duration``, recording the dispatch+execution of the
        ``duration`` steps AFTER ``start`` (a callback listener cannot open
        a trace before the very first step; use ``jax.profiler.trace``
        directly to capture compile/warm-up).  If training ends inside the
        window, the trace stays open until ``stop()`` — call it from the
        training script — or, failing that, the atexit flush at process
        exit."""
        import jax

        if not self._active and self.start <= iteration < self.end:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            self._model = model  # for the device sync in stop()
            import atexit

            atexit.register(self.stop)
        elif self._active and iteration >= self.end:
            # block so the captured window contains finished device work
            jax.block_until_ready(model.params)
            jax.profiler.stop_trace()
            self._active = False
            self._model = None

    def stop(self):
        if self._active:
            import jax

            model = getattr(self, "_model", None)
            if model is not None:
                jax.block_until_ready(model.params)
                self._model = None
            jax.profiler.stop_trace()
            self._active = False
