"""Updater zoo — per-param-type learning rules + schedules + grad clipping.

Reference: ``nn/updater/BaseUpdater.java:72-168`` (preApply gradient
normalization, lr/momentum decay policies), ``UpdaterCreator.java:31-38``
(SGD/Adam/AdaGrad/AdaDelta/Nesterovs/RMSProp/NoOp), ``MultiLayerUpdater``
fan-out per layer.  Re-derived as pure functions over parameter pytrees:
``init_state(cfg, params)`` and ``update(cfg, grads, state, iteration,
lr_overrides)`` -> (updates-to-subtract, new state).  Everything is jit-safe
(schedules compile to ``jnp.select`` over static breakpoints), so the whole
optimizer lives inside the one XLA program and shards with the params.

This module is self-contained rather than wrapping optax so that reference
semantics (per-layer lr overrides, per-layer gradient normalization, momentum
schedules) are exact; optax interop is provided via ``as_optax``.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import UpdaterConfig


# ---------------------------------------------------------------------------
# learning-rate / momentum schedules (reference LearningRatePolicy + decay maps)
# ---------------------------------------------------------------------------

def schedule_value(base: float, policy: str, cfg: UpdaterConfig, iteration,
                   schedule: Optional[Dict[int, float]] = None):
    it = jnp.asarray(iteration, jnp.float32)
    if policy == "none":
        return jnp.asarray(base, jnp.float32)
    if policy == "exponential":
        return base * jnp.power(cfg.lr_policy_decay_rate, it)
    if policy == "inverse":
        return base / jnp.power(1.0 + cfg.lr_policy_decay_rate * it, cfg.lr_policy_power)
    if policy == "step":
        return base * jnp.power(cfg.lr_policy_decay_rate, jnp.floor(it / cfg.lr_policy_steps))
    if policy == "poly":
        frac = jnp.clip(it / jnp.maximum(cfg.lr_policy_steps, 1.0), 0.0, 1.0)
        return base * jnp.power(1.0 - frac, cfg.lr_policy_power)
    if policy == "sigmoid":
        return base / (1.0 + jnp.exp(-cfg.lr_policy_decay_rate * (it - cfg.lr_policy_steps)))
    if policy == "warmup_cosine":
        # linear warmup to base over lr_warmup_steps, then cosine decay to
        # base * lr_min_fraction at lr_policy_steps (total steps) — the
        # standard transformer-training schedule (no reference analog:
        # LearningRatePolicy predates it)
        warm = jnp.maximum(cfg.lr_policy_warmup_steps, 1.0)
        total = jnp.maximum(cfg.lr_policy_steps, warm + 1.0)
        warm_frac = jnp.minimum(it / warm, 1.0)
        prog = jnp.clip((it - warm) / (total - warm), 0.0, 1.0)
        floor = cfg.lr_policy_min_fraction
        cos = floor + (1.0 - floor) * 0.5 * (1.0 + jnp.cos(jnp.pi * prog))
        return base * warm_frac * cos
    if policy == "schedule":
        # piecewise-constant: value switches at each breakpoint iteration
        if not schedule:
            return jnp.asarray(base, jnp.float32)
        val = jnp.asarray(base, jnp.float32)
        for step_i in sorted(schedule):
            val = jnp.where(it >= step_i, schedule[step_i], val)
        return val
    raise ValueError(f"Unknown lr policy '{policy}'")


def current_lr(cfg: UpdaterConfig, iteration, override: Optional[float] = None):
    base = override if override is not None else cfg.learning_rate
    return schedule_value(base, cfg.lr_policy, cfg, iteration, cfg.lr_schedule)


def current_momentum(cfg: UpdaterConfig, iteration):
    if cfg.momentum_schedule:
        return schedule_value(cfg.momentum, "schedule", cfg, iteration, cfg.momentum_schedule)
    return jnp.asarray(cfg.momentum, jnp.float32)


# ---------------------------------------------------------------------------
# gradient normalization (reference BaseUpdater.preApply / GradientNormalization)
# ---------------------------------------------------------------------------

def _global_norm(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(l * l) for l in leaves))


def normalize_gradients(cfg: UpdaterConfig, layer_grads: Dict[str, jax.Array]):
    """Apply the configured normalization to ONE layer's gradient dict."""
    kind = cfg.gradient_normalization
    t = cfg.gradient_normalization_threshold
    if kind == "none":
        return layer_grads
    if kind == "renormalize_l2_per_layer":
        norm = _global_norm(layer_grads)
        return jax.tree_util.tree_map(lambda g: g / (norm + 1e-12), layer_grads)
    if kind == "renormalize_l2_per_param_type":
        return {k: g / (jnp.linalg.norm(g.reshape(-1)) + 1e-12) for k, g in layer_grads.items()}
    if kind == "clip_element_wise_absolute_value":
        return jax.tree_util.tree_map(lambda g: jnp.clip(g, -t, t), layer_grads)
    if kind == "clip_l2_per_layer":
        norm = _global_norm(layer_grads)
        scale = jnp.where(norm > t, t / (norm + 1e-12), 1.0)
        return jax.tree_util.tree_map(lambda g: g * scale, layer_grads)
    if kind == "clip_l2_per_param_type":
        out = {}
        for k, g in layer_grads.items():
            norm = jnp.linalg.norm(g.reshape(-1))
            out[k] = g * jnp.where(norm > t, t / (norm + 1e-12), 1.0)
        return out
    raise ValueError(f"Unknown gradient normalization '{kind}'")


# ---------------------------------------------------------------------------
# per-updater state + step rules
# ---------------------------------------------------------------------------

def init_state(cfg: UpdaterConfig, params):
    """Per-leaf optimizer state pytree (reference updater stateViewArray)."""
    name = cfg.name
    zeros = lambda: jax.tree_util.tree_map(jnp.zeros_like, params)
    if name in ("sgd", "none", "noop"):
        return {}
    if name == "nesterovs":
        return {"v": zeros()}
    if name == "adagrad":
        return {"h": zeros()}
    if name == "rmsprop":
        return {"ms": zeros()}
    if name == "adadelta":
        return {"msg": zeros(), "msdx": zeros()}
    if name in ("adam", "adamw"):
        return {"m": zeros(), "v": zeros()}
    raise ValueError(f"Unknown updater '{cfg.name}'")


def _flat(d, prefix=()):
    """Flatten a layer's (possibly nested — composite layers) param dict
    to {tuple-path: leaf}."""
    out = {}
    for k, v in d.items():
        if isinstance(v, dict):
            out.update(_flat(v, prefix + (k,)))
        else:
            out[prefix + (k,)] = v
    return out


def _unflat(flat):
    out = {}
    for path, v in flat.items():
        cur = out
        for k in path[:-1]:
            cur = cur.setdefault(k, {})
        cur[path[-1]] = v
    return out


def normalize_tree(cfg: UpdaterConfig, grads):
    """Apply the configured per-layer gradient normalization to a whole
    gradient tree — the same flatten/normalize walk ``update`` performs
    internally, exposed for callers that must normalize on the FULL
    per-layer gradients BEFORE scattering them into shards
    (``parallel/zero.py``: shard-local norms would be wrong) and then
    run ``update`` with normalization disabled."""
    if cfg.gradient_normalization == "none":
        return grads
    return {lname: _unflat(normalize_gradients(cfg, _flat(lgrads)))
            for lname, lgrads in grads.items()}


def update(
    cfg: UpdaterConfig,
    grads,
    state,
    iteration,
    lr_overrides: Optional[Dict[str, float]] = None,
    params=None,
):
    """Compute updates (to SUBTRACT from params) and new updater state.

    ``grads``/``params`` pytrees are {layer_name: {param_name: arr}} — the
    inner dict may nest further (composite layers, e.g. ResidualBlock), so
    each layer's subtree is walked by tuple path; gradient normalization is
    per-layer (the reference normalizes within each layer's gradient view);
    lr_overrides maps layer_name -> lr.
    """
    lr_overrides = lr_overrides or {}
    name = cfg.name
    if name == "adamw" and params is None:
        raise ValueError(
            "adamw applies decoupled weight decay to the parameters; pass "
            "params= to updaters.update() (all facade train steps do)")
    mu = current_momentum(cfg, iteration)
    it = jnp.asarray(iteration, jnp.float32)

    new_state = {k: {} for k in state}
    updates = {}
    for lname, lgrads in grads.items():
        lgrads = _flat(lgrads)
        lparams_flat = _flat(params[lname]) if params is not None else {}
        lstate_flat = {k: _flat(state[k].get(lname, {})) for k in state}
        lgrads = normalize_gradients(cfg, lgrads)
        lr = current_lr(cfg, it, lr_overrides.get(lname))
        lup = {}
        lns = {k: {} for k in state}
        for pname, g in lgrads.items():
            if name in ("sgd",):
                u = lr * g
            elif name in ("none", "noop"):
                u = g
            elif name == "nesterovs":
                v_prev = lstate_flat["v"][pname]
                v = mu * v_prev - lr * g
                # reference Nesterov: update = -(mu * v - lr*g) applied as
                # params += mu*v_new - lr*g  =>  subtract -(mu*v - lr*g)
                u = -(mu * v - lr * g)
                lns["v"][pname] = v
            elif name == "adagrad":
                h = lstate_flat["h"][pname] + g * g
                u = lr * g / (jnp.sqrt(h) + cfg.epsilon)
                lns["h"][pname] = h
            elif name == "rmsprop":
                ms = cfg.rmsprop_decay * lstate_flat["ms"][pname] + (1 - cfg.rmsprop_decay) * g * g
                u = lr * g / jnp.sqrt(ms + cfg.epsilon)
                lns["ms"][pname] = ms
            elif name == "adadelta":
                msg = cfg.rho * lstate_flat["msg"][pname] + (1 - cfg.rho) * g * g
                msdx_prev = lstate_flat["msdx"][pname]
                dx = jnp.sqrt((msdx_prev + cfg.epsilon) / (msg + cfg.epsilon)) * g
                msdx = cfg.rho * msdx_prev + (1 - cfg.rho) * dx * dx
                u = dx  # adadelta has no lr
                lns["msg"][pname] = msg
                lns["msdx"][pname] = msdx
            elif name in ("adam", "adamw"):
                m = cfg.adam_beta1 * lstate_flat["m"][pname] + (1 - cfg.adam_beta1) * g
                v = cfg.adam_beta2 * lstate_flat["v"][pname] + (1 - cfg.adam_beta2) * g * g
                t = it + 1.0
                mhat = m / (1 - jnp.power(cfg.adam_beta1, t))
                vhat = v / (1 - jnp.power(cfg.adam_beta2, t))
                u = lr * mhat / (jnp.sqrt(vhat) + cfg.epsilon)
                if name == "adamw" and cfg.weight_decay:
                    # DECOUPLED decay (AdamW): acts on the param directly,
                    # not through the adaptive denominator
                    u = u + lr * cfg.weight_decay * lparams_flat[pname]
                lns["m"][pname] = m
                lns["v"][pname] = v
            else:
                raise ValueError(f"Unknown updater '{name}'")
            lup[pname] = u
        updates[lname] = _unflat(lup)
        for k, flat in lns.items():
            if flat:
                new_state[k][lname] = _unflat(flat)
    return updates, new_state


def apply_updates(params, updates):
    return jax.tree_util.tree_map(lambda p, u: p - u, params, updates)


def as_optax(cfg: UpdaterConfig):
    """Optional optax interop for users who want the wider optax ecosystem."""
    import optax

    name = cfg.name
    lr = cfg.learning_rate
    if name == "sgd":
        return optax.sgd(lr)
    if name == "nesterovs":
        return optax.sgd(lr, momentum=cfg.momentum, nesterov=True)
    if name == "adam":
        return optax.adam(lr, b1=cfg.adam_beta1, b2=cfg.adam_beta2, eps=cfg.epsilon)
    if name == "adamw":
        return optax.adamw(lr, b1=cfg.adam_beta1, b2=cfg.adam_beta2,
                           eps=cfg.epsilon, weight_decay=cfg.weight_decay)
    if name == "adagrad":
        return optax.adagrad(lr, eps=cfg.epsilon)
    if name == "adadelta":
        return optax.adadelta(rho=cfg.rho, eps=cfg.epsilon)
    if name == "rmsprop":
        return optax.rmsprop(lr, decay=cfg.rmsprop_decay, eps=cfg.epsilon)
    raise ValueError(f"No optax equivalent for '{name}'")
