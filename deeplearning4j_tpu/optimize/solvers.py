"""Full-batch second-order-ish solvers: line search GD, conjugate gradient,
L-BFGS — plus the backtracking line search they share.

Reference: ``optimize/Solver.java:41-74`` (dispatch on OptimizationAlgorithm),
``optimize/solvers/BaseOptimizer.java:165`` (iterative optimize loop),
``BackTrackLineSearch.java``, ``ConjugateGradient.java``, ``LBFGS.java``,
``LineGradientDescent.java``, step functions ``optimize/stepfunctions/*``.

TPU redesign: the objective is a jitted scalar function of the ONE flattened
parameter vector (the reference's flattened-params invariant makes this the
natural interface — ``MultiLayerNetwork.java:97-98``); the search direction
math (two-loop recursion, Polak-Ribière β, Armijo backtracking) runs as tiny
host-side numpy over device-computed value/grad pairs, so each line-search
probe is one XLA call.  SGD itself does NOT live here — it is the jitted
train step in the model facades.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Tuple

import numpy as np


class BackTrackLineSearch:
    """Armijo backtracking. ≙ ``optimize/solvers/BackTrackLineSearch.java``.

    Returns the accepted step size along ``direction`` (0.0 if no step
    improves sufficiently).
    """

    def __init__(self, max_iterations: int = 20, c1: float = 1e-4,
                 shrink: float = 0.5, initial_step: float = 1.0,
                 max_step: float = 100.0):
        self.max_iterations = max_iterations
        self.c1 = c1
        self.shrink = shrink
        self.initial_step = initial_step
        self.max_step = max_step

    def optimize(self, f: Callable[[np.ndarray], float], x: np.ndarray,
                 fx: float, grad: np.ndarray, direction: np.ndarray) -> float:
        dg = float(np.dot(grad, direction))
        if dg >= 0:  # not a descent direction (reference ZeroDirection guard)
            return 0.0
        # clip overly long steps (reference stpmax logic)
        dnorm = float(np.linalg.norm(direction))
        step = min(self.initial_step, self.max_step / max(dnorm, 1e-12))
        for _ in range(self.max_iterations):
            trial = f(x + step * direction)
            if np.isfinite(trial) and trial <= fx + self.c1 * step * dg:
                return step
            step *= self.shrink
        return 0.0


ValueGrad = Callable[[np.ndarray], Tuple[float, np.ndarray]]


def line_gradient_descent(value_grad: ValueGrad, x0: np.ndarray,
                          iterations: int,
                          line_search: BackTrackLineSearch = None) -> Tuple[np.ndarray, float]:
    """Steepest descent with line search. ≙ ``LineGradientDescent.java``."""
    ls = line_search or BackTrackLineSearch()
    f = lambda v: value_grad(v)[0]
    x = np.asarray(x0, np.float64).copy()
    fx, g = value_grad(x)
    for _ in range(iterations):
        d = -g
        step = ls.optimize(f, x, fx, g, d)
        if step == 0.0:
            break
        x = x + step * d
        fx, g = value_grad(x)
    return x, fx


def conjugate_gradient(value_grad: ValueGrad, x0: np.ndarray,
                       iterations: int,
                       line_search: BackTrackLineSearch = None) -> Tuple[np.ndarray, float]:
    """Nonlinear CG, Polak-Ribière+ with automatic restart.
    ≙ ``ConjugateGradient.java``."""
    ls = line_search or BackTrackLineSearch()
    f = lambda v: value_grad(v)[0]
    x = np.asarray(x0, np.float64).copy()
    fx, g = value_grad(x)
    d = -g
    for _ in range(iterations):
        step = ls.optimize(f, x, fx, g, d)
        if step == 0.0:
            # restart along steepest descent once before giving up
            d = -g
            step = ls.optimize(f, x, fx, g, d)
            if step == 0.0:
                break
        x = x + step * d
        fx, g_new = value_grad(x)
        beta = float(np.dot(g_new, g_new - g) / max(np.dot(g, g), 1e-300))
        beta = max(beta, 0.0)  # PR+
        d = -g_new + beta * d
        g = g_new
    return x, fx


def lbfgs(value_grad: ValueGrad, x0: np.ndarray, iterations: int,
          memory: int = 10,
          line_search: BackTrackLineSearch = None) -> Tuple[np.ndarray, float]:
    """Limited-memory BFGS (two-loop recursion). ≙ ``LBFGS.java``."""
    ls = line_search or BackTrackLineSearch()
    f = lambda v: value_grad(v)[0]
    x = np.asarray(x0, np.float64).copy()
    fx, g = value_grad(x)
    s_hist: deque = deque(maxlen=memory)
    y_hist: deque = deque(maxlen=memory)
    for _ in range(iterations):
        # two-loop recursion for H·g
        q = g.copy()
        alphas = []
        for s, y in reversed(list(zip(s_hist, y_hist))):
            rho = 1.0 / max(float(np.dot(y, s)), 1e-300)
            a = rho * float(np.dot(s, q))
            alphas.append((a, rho, s, y))
            q -= a * y
        if y_hist:
            s, y = s_hist[-1], y_hist[-1]
            q *= float(np.dot(s, y)) / max(float(np.dot(y, y)), 1e-300)
        for a, rho, s, y in reversed(alphas):
            b = rho * float(np.dot(y, q))
            q += (a - b) * s
        d = -q
        step = ls.optimize(f, x, fx, g, d)
        if step == 0.0:
            d = -g
            step = ls.optimize(f, x, fx, g, d)
            if step == 0.0:
                break
        x_new = x + step * d
        fx, g_new = value_grad(x_new)
        s_vec, y_vec = x_new - x, g_new - g
        if float(np.dot(s_vec, y_vec)) > 1e-10:  # curvature condition
            s_hist.append(s_vec)
            y_hist.append(y_vec)
        x, g = x_new, g_new
    return x, fx


SOLVERS = {
    "line_gradient_descent": line_gradient_descent,
    "conjugate_gradient": conjugate_gradient,
    "lbfgs": lbfgs,
}


def solve(algo: str, value_grad: ValueGrad, x0: np.ndarray,
          iterations: int) -> Tuple[np.ndarray, float]:
    """Dispatch ≙ ``Solver.java:47-74``."""
    if algo not in SOLVERS:
        raise ValueError(f"Unknown optimization algorithm '{algo}' "
                         f"(known: {sorted(SOLVERS)} + stochastic_gradient_descent)")
    return SOLVERS[algo](value_grad, x0, iterations)


def fit_model_with_solver(model, loss_fn, args, algo: str, iterations: int) -> None:
    """One full-batch solver 'fit' on a model facade: run ``iterations`` of
    the chosen solver over the flat param vector, then write back params,
    refreshed net_state (BatchNorm running stats etc.), score, iteration
    count, and fire listeners.  Shared by MultiLayerNetwork and
    ComputationGraph (≙ the single ``Solver``/``BaseOptimizer`` the
    reference shares across Model impls).

    ``loss_fn(params, *args) -> (loss, (new_net_state, _))`` must be pure;
    the jitted value/grad closure is cached on ``model._jit_cache`` keyed by
    the arg structure+shapes, so repeated batches don't recompile.
    """
    import jax
    import jax.flatten_util
    import jax.numpy as jnp

    flat0, unravel = jax.flatten_util.ravel_pytree(model.params)
    leaves = jax.tree_util.tree_leaves(args)
    key = ("solver_vg", algo, jax.tree_util.tree_structure(args),
           tuple((l.shape, str(l.dtype)) for l in leaves))
    if key not in model._jit_cache:

        @jax.jit
        def vg(vec, args):
            p = unravel(vec)
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, *args)
            gflat, _ = jax.flatten_util.ravel_pytree(grads)
            return loss, gflat, aux

        model._jit_cache[key] = vg
    vg = model._jit_cache[key]

    def value_grad(v):
        loss, g, _ = vg(jnp.asarray(v, flat0.dtype), args)
        return float(loss), np.asarray(g, np.float64)

    xf, fx = solve(algo, value_grad, np.asarray(flat0, np.float64), iterations)
    xf_dev = jnp.asarray(xf, flat0.dtype)
    loss, _, aux = vg(xf_dev, args)  # state refresh at the accepted point
    model.params = unravel(xf_dev)
    new_state = aux[0]
    if new_state:
        model.net_state = new_state
    model.score_value = float(loss)
    model.iteration += 1
    for lst in model.listeners:
        lst.iteration_done(model, model.iteration)
